"""Text datasets (reference: python/paddle/text/datasets/ — uci_housing.py,
imdb.py, imikolov.py, movielens.py, wmt14.py, wmt16.py, conll05.py).

Zero-egress build: the reference's auto-download path is gated — every
dataset requires a local ``data_file`` (the same archive the reference
downloads) and parses it with the reference's format logic."""

from __future__ import annotations

import collections
import re
import string
import tarfile
import zipfile

import numpy as np

from ..io import Dataset

__all__ = ["UCIHousing", "Imdb", "Imikolov", "Movielens", "Conll05st",
           "WMT14", "WMT16"]

_DOWNLOAD_MSG = ("{name}: this build has no network egress — pass "
                 "data_file= pointing at the locally available archive "
                 "(the file the reference would download)")


def _require_file(data_file, name):
    if data_file is None:
        raise RuntimeError(_DOWNLOAD_MSG.format(name=name))
    return data_file


class UCIHousing(Dataset):
    """uci_housing.py — 13 features + price, whitespace floats; features
    mean-normalized by (max-min), 80/20 train/test split."""

    def __init__(self, data_file=None, mode="train", download=True):
        assert mode.lower() in ("train", "test")
        self.mode = mode.lower()
        self.data_file = _require_file(data_file, "UCIHousing")
        self.dtype = "float32"
        self._load_data()

    def _load_data(self, feature_num=14, ratio=0.8):
        data = np.fromfile(self.data_file, sep=" ")
        data = data.reshape(data.shape[0] // feature_num, feature_num)
        maximums = data.max(axis=0)
        minimums = data.min(axis=0)
        avgs = data.sum(axis=0) / data.shape[0]
        for i in range(feature_num - 1):
            data[:, i] = (data[:, i] - avgs[i]) / (maximums[i] - minimums[i])
        offset = int(data.shape[0] * ratio)
        self.data = data[:offset] if self.mode == "train" else data[offset:]

    def __getitem__(self, idx):
        row = self.data[idx]
        return (np.array(row[:-1]).astype(self.dtype),
                np.array(row[-1:]).astype(self.dtype))

    def __len__(self):
        return len(self.data)


class Imdb(Dataset):
    """imdb.py — aclImdb tarball; ad-hoc tokenization (punctuation strip +
    lower), vocab by frequency (> cutoff), pos label 0 / neg label 1."""

    def __init__(self, data_file=None, mode="train", cutoff=150,
                 download=True):
        assert mode.lower() in ("train", "test")
        self.mode = mode.lower()
        self.data_file = _require_file(data_file, "Imdb")
        self.word_idx = self._build_work_dict(cutoff)
        self._load_anno()

    def _tokenize(self, pattern):
        data = []
        with tarfile.open(self.data_file) as tarf:
            tf = tarf.next()
            while tf is not None:
                if bool(pattern.match(tf.name)):
                    data.append(
                        tarf.extractfile(tf).read().rstrip(b"\n\r")
                        .translate(None, string.punctuation.encode("latin-1"))
                        .lower().split())
                tf = tarf.next()
        return data

    def _build_work_dict(self, cutoff):
        word_freq = collections.defaultdict(int)
        pattern = re.compile(r"aclImdb/((train)|(test))/((pos)|(neg))/.*\.txt$")
        for doc in self._tokenize(pattern):
            for word in doc:
                word_freq[word] += 1
        word_freq = [x for x in word_freq.items() if x[1] > cutoff]
        dictionary = sorted(word_freq, key=lambda x: (-x[1], x[0]))
        if not dictionary:
            return {b"<unk>": 0}
        words, _ = list(zip(*dictionary))
        word_idx = dict(zip(words, range(len(words))))
        word_idx[b"<unk>"] = len(words)
        return word_idx

    def _load_anno(self):
        pos = re.compile(rf"aclImdb/{self.mode}/pos/.*\.txt$")
        neg = re.compile(rf"aclImdb/{self.mode}/neg/.*\.txt$")
        unk = self.word_idx[b"<unk>"]
        self.docs, self.labels = [], []
        for doc in self._tokenize(pos):
            self.docs.append([self.word_idx.get(w, unk) for w in doc])
            self.labels.append(0)
        for doc in self._tokenize(neg):
            self.docs.append([self.word_idx.get(w, unk) for w in doc])
            self.labels.append(1)

    def __getitem__(self, idx):
        return np.array(self.docs[idx]), np.array([self.labels[idx]])

    def __len__(self):
        return len(self.docs)


class Imikolov(Dataset):
    """imikolov.py — PTB language modeling from the simple-examples tar;
    NGRAM windows or SEQ (src, trg) pairs."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=-1,
                 mode="train", min_word_freq=50, download=True):
        assert data_type.upper() in ("NGRAM", "SEQ")
        assert mode.lower() in ("train", "test", "valid")
        self.data_type = data_type.upper()
        self.window_size = window_size
        self.mode = "valid" if mode.lower() == "test" else mode.lower()
        self.min_word_freq = min_word_freq
        self.data_file = _require_file(data_file, "Imikolov")
        self.word_idx = self._build_work_dict(min_word_freq)
        self._load_anno()

    @staticmethod
    def word_count(f, word_freq=None):
        if word_freq is None:
            word_freq = collections.defaultdict(int)
        for line in f:
            for w in line.strip().split():
                word_freq[w] += 1
            word_freq[b"<s>"] += 1
            word_freq[b"<e>"] += 1
        return word_freq

    def _member(self, tf, suffix):
        for m in tf.getmembers():
            if m.name.endswith(suffix):
                return m.name
        raise KeyError(f"{suffix} not in archive")

    def _build_work_dict(self, cutoff):
        with tarfile.open(self.data_file) as tf:
            trainf = tf.extractfile(self._member(tf, "ptb.train.txt"))
            testf = tf.extractfile(self._member(tf, "ptb.valid.txt"))
            word_freq = self.word_count(testf, self.word_count(trainf))
            word_freq.pop(b"<unk>", None)
            word_freq = [x for x in word_freq.items() if x[1] > cutoff]
            word_freq_sorted = sorted(word_freq, key=lambda x: (-x[1], x[0]))
            if not word_freq_sorted:
                return {b"<unk>": 0, b"<s>": 1, b"<e>": 2}
            words, _ = list(zip(*word_freq_sorted))
            word_idx = dict(zip(words, range(len(words))))
            word_idx[b"<unk>"] = len(words)
            for tok in (b"<s>", b"<e>"):
                word_idx.setdefault(tok, len(word_idx))
        return word_idx

    def _load_anno(self):
        self.data = []
        with tarfile.open(self.data_file) as tf:
            f = tf.extractfile(self._member(tf, f"ptb.{self.mode}.txt"))
            unk = self.word_idx[b"<unk>"]
            for line in f:
                if self.data_type == "NGRAM":
                    assert self.window_size > -1, "Invalid gram length"
                    toks = [b"<s>", *line.strip().split(), b"<e>"]
                    if len(toks) >= self.window_size:
                        ids = [self.word_idx.get(w, unk) for w in toks]
                        for i in range(self.window_size, len(ids) + 1):
                            self.data.append(tuple(ids[i - self.window_size:i]))
                else:
                    toks = line.strip().split()
                    ids = [self.word_idx.get(w, unk) for w in toks]
                    src = [self.word_idx[b"<s>"], *ids]
                    trg = [*ids, self.word_idx[b"<e>"]]
                    if self.window_size > 0 and len(src) > self.window_size:
                        continue
                    self.data.append((src, trg))

    def __getitem__(self, idx):
        return tuple(np.array(d) for d in self.data[idx])

    def __len__(self):
        return len(self.data)


class Movielens(Dataset):
    """movielens.py — ml-1m zip: users/movies metadata joined onto ratings;
    each sample is (uid, gender, age, job, mov_id, categories, title_ids,
    rating in [-5, 5])."""

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0, download=True):
        self.mode = mode.lower()
        self.test_ratio = test_ratio
        self.data_file = _require_file(data_file, "Movielens")
        np.random.seed(rand_seed)
        self._load_meta_info()
        self._load_data()

    def _load_meta_info(self):
        self.movie_info, self.user_info = {}, {}
        self.categories_dict, self.movie_title_dict = {}, {}
        with zipfile.ZipFile(self.data_file) as package:
            with package.open("ml-1m/movies.dat") as f:
                for line in f:
                    line = line.decode(encoding="latin")
                    movie_id, title, categories = line.strip().split("::")
                    categories = categories.split("|")
                    m = re.match(r"^(.*)\((\d+)\)$", title)
                    title = m.group(1) if m else title  # strip '(year)'
                    for c in categories:
                        self.categories_dict.setdefault(
                            c, len(self.categories_dict))
                    for w in title.split():
                        self.movie_title_dict.setdefault(
                            w.lower(), len(self.movie_title_dict))
                    self.movie_info[int(movie_id)] = (int(movie_id), title,
                                                      categories)
            with package.open("ml-1m/users.dat") as f:
                for line in f:
                    line = line.decode(encoding="latin")
                    uid, gender, age, job, _ = line.strip().split("::")
                    self.user_info[int(uid)] = (
                        int(uid), 0 if gender == "M" else 1, int(age),
                        int(job))

    def _load_data(self):
        self.data = []
        is_test = self.mode == "test"
        with zipfile.ZipFile(self.data_file) as package:
            with package.open("ml-1m/ratings.dat") as f:
                for line in f:
                    line = line.decode(encoding="latin")
                    if (np.random.random() < self.test_ratio) != is_test:
                        continue
                    uid, mov_id, rating, _ = line.strip().split("::")
                    mov_id = int(mov_id)
                    if mov_id not in self.movie_info:
                        continue
                    rating = float(rating) * 2 - 5.0
                    _, title, cats = self.movie_info[mov_id]
                    uid_, gender, age, job = self.user_info[int(uid)]
                    self.data.append((
                        [uid_], [gender], [age], [job], [mov_id],
                        [self.categories_dict[c] for c in cats],
                        [self.movie_title_dict[w.lower()]
                         for w in title.split()],
                        [rating]))

    def __getitem__(self, idx):
        return tuple(np.array(d) for d in self.data[idx])

    def __len__(self):
        return len(self.data)


_UNK_IDX = 2


class WMT14(Dataset):
    """wmt14.py — preprocessed tarball with {train,test,gen}/ tsv pairs and
    src.dict/trg.dict vocabularies; yields (src_ids, trg_ids, trg_ids_next)
    with <s>/<e>/<unk> at indices 0/1/2."""

    START, END, UNK = "<s>", "<e>", "<unk>"

    def __init__(self, data_file=None, mode="train", dict_size=-1,
                 download=True):
        assert mode.lower() in ("train", "test", "gen")
        self.mode = mode.lower()
        self.data_file = _require_file(data_file, "WMT14")
        assert dict_size > 0, "dict_size should be set as positive number"
        self.dict_size = dict_size
        self._load_data()

    def _load_data(self):
        def to_dict(fd, size):
            out = {}
            for i, line in enumerate(fd):
                if i >= size:
                    break
                out[line.strip().decode()] = i
            return out

        self.src_ids, self.trg_ids, self.trg_ids_next = [], [], []
        with tarfile.open(self.data_file, mode="r") as f:
            src_names = [m.name for m in f if m.name.endswith("src.dict")]
            trg_names = [m.name for m in f if m.name.endswith("trg.dict")]
            assert len(src_names) == 1 and len(trg_names) == 1
            self.src_dict = to_dict(f.extractfile(src_names[0]), self.dict_size)
            self.trg_dict = to_dict(f.extractfile(trg_names[0]), self.dict_size)
            data_names = [m.name for m in f
                          if m.name.endswith(f"{self.mode}/{self.mode}")]
            for name in data_names:
                for line in f.extractfile(name):
                    parts = line.decode().strip().split("\t")
                    if len(parts) != 2:
                        continue
                    src_words = parts[0].split()
                    src_ids = [self.src_dict.get(w, _UNK_IDX)
                               for w in [self.START, *src_words, self.END]]
                    trg_words = parts[1].split()
                    trg_ids = [self.trg_dict.get(w, _UNK_IDX)
                               for w in trg_words]
                    if len(src_ids) > 80 or len(trg_ids) > 80:
                        continue
                    self.src_ids.append(src_ids)
                    self.trg_ids.append([self.trg_dict[self.START], *trg_ids])
                    self.trg_ids_next.append(
                        [*trg_ids, self.trg_dict[self.END]])

    def get_dict(self, reverse=False):
        if reverse:
            return ({v: k for k, v in self.src_dict.items()},
                    {v: k for k, v in self.trg_dict.items()})
        return self.src_dict, self.trg_dict

    def __getitem__(self, idx):
        return (np.array(self.src_ids[idx]), np.array(self.trg_ids[idx]),
                np.array(self.trg_ids_next[idx]))

    def __len__(self):
        return len(self.src_ids)


class WMT16(Dataset):
    """wmt16.py — tarball with wmt16/{train,val,test} tab-separated pairs;
    vocab built from the corpus with frequency cutoff (the reference writes
    en/de vocab files next to the archive)."""

    START, END, UNK = "<s>", "<e>", "<unk>"

    def __init__(self, data_file=None, mode="train", src_dict_size=-1,
                 trg_dict_size=-1, lang="en", download=True):
        assert mode.lower() in ("train", "test", "val")
        self.mode = mode.lower()
        self.data_file = _require_file(data_file, "WMT16")
        self.lang = lang
        assert src_dict_size > 0 and trg_dict_size > 0
        # <s>/<e>/<unk> always present → effective floor of 3
        self.src_dict_size = max(src_dict_size, 3)
        self.trg_dict_size = max(trg_dict_size, 3)
        self._load_data()

    def _build_dict(self, lines, size):
        freq = collections.defaultdict(int)
        for line in lines:
            for w in line.split():
                freq[w] += 1
        vocab = {self.START: 0, self.END: 1, self.UNK: 2}
        for w, _ in sorted(freq.items(), key=lambda kv: (-kv[1], kv[0])):
            if len(vocab) >= size:
                break
            vocab.setdefault(w, len(vocab))
        return vocab

    def _load_data(self):
        src_col = 0 if self.lang == "en" else 1
        trg_col = 1 - src_col
        with tarfile.open(self.data_file) as f:
            names = {m.name.rsplit("/", 1)[-1]: m.name for m in f
                     if m.name.rsplit("/", 1)[-1] in ("train", "val", "test")}
            train_lines = [line.decode().strip() for line in
                           f.extractfile(names["train"])]
            mode_lines = (train_lines if self.mode == "train" else
                          [line.decode().strip() for line in
                           f.extractfile(names[self.mode])])
        self.src_dict = self._build_dict(
            [line.split("\t")[src_col] for line in train_lines
             if len(line.split("\t")) == 2], self.src_dict_size)
        self.trg_dict = self._build_dict(
            [line.split("\t")[trg_col] for line in train_lines
             if len(line.split("\t")) == 2], self.trg_dict_size)
        self.src_ids, self.trg_ids, self.trg_ids_next = [], [], []
        unk = 2
        for line in mode_lines:
            parts = line.split("\t")
            if len(parts) != 2:
                continue
            src_ids = [self.src_dict.get(w, unk)
                       for w in [self.START, *parts[src_col].split(),
                                 self.END]]
            trg = [self.trg_dict.get(w, unk) for w in parts[trg_col].split()]
            self.src_ids.append(src_ids)
            self.trg_ids.append([0, *trg])
            self.trg_ids_next.append([*trg, 1])

    def get_dict(self, lang="en", reverse=False):
        d = self.src_dict if lang == self.lang else self.trg_dict
        return {v: k for k, v in d.items()} if reverse else d

    def __getitem__(self, idx):
        return (np.array(self.src_ids[idx]), np.array(self.trg_ids[idx]),
                np.array(self.trg_ids_next[idx]))

    def __len__(self):
        return len(self.src_ids)


class Conll05st(Dataset):
    """conll05.py — semantic-role labeling: word/verb/target dictionaries
    plus the test.wsj words/props column files.  Yields
    (word_ids, predicate_id, label_ids) per proposition; the reference's
    context-window feature columns are model-side in this port (they are
    pure index arithmetic over word_ids)."""

    def __init__(self, data_file=None, word_dict_file=None,
                 verb_dict_file=None, target_dict_file=None, mode="test",
                 download=True):
        self.data_file = _require_file(data_file, "Conll05st")
        for f, n in ((word_dict_file, "word_dict_file"),
                     (verb_dict_file, "verb_dict_file"),
                     (target_dict_file, "target_dict_file")):
            if f is None:
                raise RuntimeError(_DOWNLOAD_MSG.format(name=f"Conll05st {n}"))
        self.word_dict = self._load_dict(word_dict_file)
        self.verb_dict = self._load_dict(verb_dict_file)
        self.label_dict = self._load_dict(target_dict_file)
        self._load_anno()

    @staticmethod
    def _load_dict(path):
        d = {}
        with open(path, "rb") as f:
            for i, line in enumerate(f):
                d[line.strip().decode()] = i
        return d

    def _load_anno(self):
        import gzip

        self.sentences = []
        with tarfile.open(self.data_file) as tf:
            words_name = [m.name for m in tf
                          if m.name.endswith("words.gz")]
            props_name = [m.name for m in tf
                          if m.name.endswith("props.gz")]
            if not words_name or not props_name:
                raise ValueError("archive must contain words.gz and props.gz")
            wordsf = gzip.GzipFile(
                fileobj=tf.extractfile(words_name[0]))
            propsf = gzip.GzipFile(
                fileobj=tf.extractfile(props_name[0]))
            sentence, props = [], []
            for wline, pline in zip(wordsf, propsf):
                w = wline.strip().decode()
                p = pline.strip().decode().split()
                if w:
                    sentence.append(w)
                    props.append(p)
                    continue
                self._emit(sentence, props)
                sentence, props = [], []
            if sentence:
                self._emit(sentence, props)

    def _emit(self, sentence, props):
        if not props:
            return
        unk_w = self.word_dict.get("<unk>", 0)
        n_props = len(props[0]) - 1  # col 0 is the predicate lemma column
        lemmas = [row[0] for row in props if row[0] != "-"]
        for k in range(n_props):
            # proposition k belongs to the k-th predicate of the sentence
            verb = lemmas[k] if k < len(lemmas) else None
            labels = []
            cur = "O"
            for row in props:
                tag = row[1 + k]
                # bracket format: '(X*' opens span X, '*)' closes the open
                # span, '(X*)' is a single-token span (opens AND closes)
                m = re.match(r"\(([^*]*)\*", tag)
                if m:
                    cur = m.group(1)
                    labels.append("B-" + cur if cur else "O")
                    if tag.endswith(")"):
                        cur = "O"  # single-token span closed in place
                elif tag.endswith(")"):
                    labels.append("I-" + cur if cur != "O" else "O")
                    cur = "O"
                elif cur != "O":
                    labels.append("I-" + cur)
                else:
                    labels.append("O")
            word_ids = [self.word_dict.get(w.lower(), unk_w)
                        for w in sentence]
            verb_id = self.verb_dict.get(verb, 0)
            label_ids = [self.label_dict.get(lb, 0) for lb in labels]
            self.sentences.append((word_ids, [verb_id], label_ids))

    def get_dict(self):
        return self.word_dict, self.verb_dict, self.label_dict

    def __getitem__(self, idx):
        return tuple(np.array(d) for d in self.sentences[idx])

    def __len__(self):
        return len(self.sentences)
