"""Optimizers (reference: python/paddle/optimizer/ — SGD/Momentum/Adam/AdamW/Lamb
+ fused multi-tensor paths in phi/kernels/fusion).

Design: each optimizer's math is a *pure function* over arrays
(``p, g, state -> p', state'``).  Eager ``opt.step()`` applies it per parameter;
the jit/pjit training path reuses exactly the same function over the whole
parameter pytree (the fused multi-tensor kernel of the reference is subsumed by
XLA fusing the pytree-wide update into one kernel)."""

from __future__ import annotations

import math
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Parameter, Tensor, _unwrap, no_grad
from ..nn.clip import ClipGradBase
from . import lr as lr_mod
from .lr import LRScheduler

__all__ = [
    "Optimizer",
    "SGD",
    "Momentum",
    "Adagrad",
    "Adadelta",
    "RMSProp",
    "Adam",
    "AdamW",
    "Adamax",
    "NAdam",
    "RAdam",
    "Lamb",
    "ASGD",
    "Rprop",
    "LBFGS",
    "lr",
]
lr = lr_mod


class Optimizer:
    """Base optimizer (reference: python/paddle/optimizer/optimizer.py)."""

    def __init__(
        self,
        learning_rate=0.001,
        parameters=None,
        weight_decay=None,
        grad_clip=None,
        multi_precision=False,
        name=None,
    ):
        self._lr = learning_rate
        if parameters is not None:
            parameters = list(parameters)
        self._parameter_list = parameters
        self._weight_decay = self._parse_wd(weight_decay)
        self._grad_clip = grad_clip
        self._multi_precision = multi_precision
        self._accumulators: dict[int, dict] = {}
        self._master_weights: dict[int, jnp.ndarray] = {}
        self._step_count = 0

    @staticmethod
    def _parse_wd(weight_decay):
        if weight_decay is None:
            return 0.0
        if isinstance(weight_decay, (int, float)):
            return float(weight_decay)
        # regularizer object with _coeff (L2Decay)
        return float(getattr(weight_decay, "_coeff", getattr(weight_decay, "coeff", 0.0)))

    # ---- lr ----
    def get_lr(self) -> float:
        if isinstance(self._lr, LRScheduler):
            return float(self._lr())
        return float(self._lr)

    def set_lr(self, value):
        self._lr = float(value)

    # ---- state ----
    def _state_names(self) -> list[str]:
        return []

    def _init_param_state(self, p) -> dict:
        return {name: jnp.zeros(p.shape, jnp.float32) for name in self._state_names()}

    def _get_state(self, p) -> dict:
        key = id(p)
        if key not in self._accumulators:
            self._accumulators[key] = self._init_param_state(p)
            if self._multi_precision and p.dtype != np.float32:
                self._master_weights[key] = _unwrap(p).astype(jnp.float32)
        return self._accumulators[key]

    # ---- the pure update rule: override in subclasses ----
    def _update(self, p, g, state: dict, lr: float, step: int):
        """p, g are float32 arrays; returns (new_p, new_state)."""
        raise NotImplementedError

    # functional entry for the jit path: same math over a pytree
    def init_state_pytree(self, params):
        # delegates to _init_param_state so non-zero-init optimizers (Rprop's
        # elem_lr, ASGD's ring of grads) have ONE init definition
        return {
            "step": jnp.zeros((), jnp.int32),
            "acc": jax.tree_util.tree_map(
                lambda p: self._init_param_state(p), params),
        }

    def apply_gradients_pytree(self, params, grads, opt_state, lr=None):
        lr_val = self.get_lr() if lr is None else lr
        step = opt_state["step"] + 1

        def upd(p, g, st):
            if g is None:
                return p, st
            p32 = p.astype(jnp.float32)
            g32 = g.astype(jnp.float32)
            new_p, new_st = self._update(p32, g32, st, lr_val, step)
            return new_p.astype(p.dtype), new_st

        flat_p, treedef = jax.tree_util.tree_flatten(params)
        # keep None gradients as leaves so flat_g stays aligned with flat_p
        flat_g = jax.tree_util.tree_flatten(grads, is_leaf=lambda x: x is None)[0]
        flat_s = treedef.flatten_up_to(opt_state["acc"])
        new_p, new_s = [], []
        for p, g, st in zip(flat_p, flat_g, flat_s):
            np_, ns_ = upd(p, g, st)
            new_p.append(np_)
            new_s.append(ns_)
        return (
            jax.tree_util.tree_unflatten(treedef, new_p),
            {"step": step, "acc": jax.tree_util.tree_unflatten(treedef, new_s)},
        )

    # ---- eager step ----
    @no_grad()
    def step(self):
        params = self._parameter_list
        if params is None:
            raise ValueError("optimizer constructed without parameters")
        params_grads = [
            (p, Tensor(p._grad)) for p in params if p._grad is not None and p.trainable
        ]
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        self._step_count += 1
        lr_val = self.get_lr()
        for p, g in params_grads:
            if g is None:
                continue
            st = self._get_state(p)
            key = id(p)
            if key in self._master_weights:
                p32 = self._master_weights[key]
            else:
                p32 = _unwrap(p).astype(jnp.float32)
            g32 = _unwrap(g).astype(jnp.float32)
            self._current_param_name = p.name
            self._current_param = p
            new_p, new_st = self._update(p32, g32, st, lr_val, self._step_count)
            self._accumulators[key] = new_st
            if key in self._master_weights:
                self._master_weights[key] = new_p
            p._value = new_p.astype(p.dtype)

    _current_param_name = None

    def clear_grad(self, set_to_zero=True):
        if self._parameter_list is not None:
            for p in self._parameter_list:
                p.clear_grad()

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        loss.backward()
        self.step()
        self.clear_grad()
        return None, None

    # ---- checkpointing ----
    def state_dict(self) -> dict:
        out = {"step": self._step_count, "accumulators": {}, "master_weights": {}}
        if self._parameter_list is not None:
            for i, p in enumerate(self._parameter_list):
                key = id(p)
                name = p.name or f"param_{i}"
                if key in self._accumulators:
                    out["accumulators"][name] = {
                        k: np.asarray(v) for k, v in self._accumulators[key].items()
                    }
                if key in self._master_weights:
                    out["master_weights"][name] = np.asarray(self._master_weights[key])
        if isinstance(self._lr, LRScheduler):
            out["LR_Scheduler"] = self._lr.state_dict()
        return out

    def set_state_dict(self, state: dict):
        self._step_count = int(state.get("step", 0))
        accs = state.get("accumulators", {})
        masters = state.get("master_weights", {})
        if self._parameter_list is not None:
            for i, p in enumerate(self._parameter_list):
                name = p.name or f"param_{i}"
                if name in accs:
                    self._accumulators[id(p)] = {
                        k: jnp.asarray(v) for k, v in accs[name].items()
                    }
                if name in masters:
                    self._master_weights[id(p)] = jnp.asarray(masters[name])
        if "LR_Scheduler" in state and isinstance(self._lr, LRScheduler):
            self._lr.set_state_dict(state["LR_Scheduler"])


class SGD(Optimizer):
    def _update(self, p, g, state, lr, step):
        if self._weight_decay:
            g = g + self._weight_decay * p
        return p - lr * g, state


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None, use_nesterov=False, weight_decay=None, grad_clip=None, multi_precision=False, rescale_grad=1.0, use_multi_tensor=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, multi_precision, name)
        self._momentum = momentum
        self._nesterov = use_nesterov
        # rescale_grad pre-scales incoming grads (the reference's dist-
        # training hook); use_multi_tensor is a CUDA fused-kernel knob —
        # XLA fuses the update chain regardless
        self._rescale_grad = float(rescale_grad)

    def _state_names(self):
        return ["velocity"]

    def _update(self, p, g, state, lr, step):
        if self._rescale_grad != 1.0:
            g = g * self._rescale_grad
        if self._weight_decay:
            g = g + self._weight_decay * p
        v = self._momentum * state["velocity"] + g
        if self._nesterov:
            p = p - lr * (g + self._momentum * v)
        else:
            p = p - lr * v
        return p, {"velocity": v}


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None, weight_decay=None, grad_clip=None, initial_accumulator_value=0.0, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, multi_precision, name)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _state_names(self):
        return ["moment"]

    def _init_param_state(self, p):
        return {"moment": jnp.full(p.shape, self._init_acc, jnp.float32)}

    def _update(self, p, g, state, lr, step):
        if self._weight_decay:
            g = g + self._weight_decay * p
        m = state["moment"] + g * g
        p = p - lr * g / (jnp.sqrt(m) + self._epsilon)
        return p, {"moment": m}


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95, parameters=None, weight_decay=None, grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, multi_precision, name)
        self._epsilon = epsilon
        self._rho = rho

    def _state_names(self):
        return ["avg_squared_grad", "avg_squared_update"]

    def _update(self, p, g, state, lr, step):
        if self._weight_decay:
            g = g + self._weight_decay * p
        eg = self._rho * state["avg_squared_grad"] + (1 - self._rho) * g * g
        dx = jnp.sqrt(state["avg_squared_update"] + self._epsilon) / jnp.sqrt(eg + self._epsilon) * g
        eu = self._rho * state["avg_squared_update"] + (1 - self._rho) * dx * dx
        return p - lr * dx, {"avg_squared_grad": eg, "avg_squared_update": eu}


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0, centered=False, parameters=None, weight_decay=None, grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, multi_precision, name)
        self._rho = rho
        self._epsilon = epsilon
        self._momentum = momentum
        self._centered = centered

    def _state_names(self):
        return ["mean_square", "mean_grad", "momentum"]

    def _update(self, p, g, state, lr, step):
        if self._weight_decay:
            g = g + self._weight_decay * p
        ms = self._rho * state["mean_square"] + (1 - self._rho) * g * g
        if self._centered:
            mg = self._rho * state["mean_grad"] + (1 - self._rho) * g
            denom = jnp.sqrt(ms - mg * mg + self._epsilon)
        else:
            mg = state["mean_grad"]
            denom = jnp.sqrt(ms + self._epsilon)
        mom = self._momentum * state["momentum"] + lr * g / denom
        return p - mom, {"mean_square": ms, "mean_grad": mg, "momentum": mom}


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, parameters=None, weight_decay=None, grad_clip=None, lazy_mode=False, multi_precision=False, use_multi_tensor=False, amsgrad=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, multi_precision, name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._amsgrad = amsgrad

    def _state_names(self):
        return ["moment1", "moment2"] + (["moment2_max"] if self._amsgrad else [])

    def _update(self, p, g, state, lr, step):
        if self._weight_decay:  # coupled L2 (paddle Adam semantics)
            g = g + self._weight_decay * p
        b1, b2 = self._beta1, self._beta2
        m = b1 * state["moment1"] + (1 - b1) * g
        v = b2 * state["moment2"] + (1 - b2) * g * g
        mhat = m / (1 - b1**step)
        if self._amsgrad:
            vmax = jnp.maximum(state["moment2_max"], v)
            vhat = vmax / (1 - b2**step)
            new_state = {"moment1": m, "moment2": v, "moment2_max": vmax}
        else:
            vhat = v / (1 - b2**step)
            new_state = {"moment1": m, "moment2": v}
        p = p - lr * mhat / (jnp.sqrt(vhat) + self._epsilon)
        return p, new_state


class AdamW(Adam):
    """Decoupled weight decay (reference: python/paddle/optimizer/adamw.py;
    fused kernel phi/kernels/fusion/fused_adam)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, parameters=None, weight_decay=0.01, lr_ratio=None, apply_decay_param_fun=None, grad_clip=None, lazy_mode=False, multi_precision=False, amsgrad=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters, None, grad_clip, lazy_mode, multi_precision, amsgrad=amsgrad, name=name)
        self._wd = float(weight_decay) if not hasattr(weight_decay, "_coeff") else float(weight_decay._coeff)
        self._apply_decay_param_fun = apply_decay_param_fun
        self._lr_ratio = lr_ratio
        self._current_param_name = None

    def _update(self, p, g, state, lr, step):
        decay = self._wd
        if self._apply_decay_param_fun is not None and self._current_param_name is not None:
            if not self._apply_decay_param_fun(self._current_param_name):
                decay = 0.0
        b1, b2 = self._beta1, self._beta2
        p = p * (1 - lr * decay)
        m = b1 * state["moment1"] + (1 - b1) * g
        v = b2 * state["moment2"] + (1 - b2) * g * g
        mhat = m / (1 - b1**step)
        if self._amsgrad:
            vmax = jnp.maximum(state["moment2_max"], v)
            vhat = vmax / (1 - b2**step)
            new_state = {"moment1": m, "moment2": v, "moment2_max": vmax}
        else:
            vhat = v / (1 - b2**step)
            new_state = {"moment1": m, "moment2": v}
        p = p - lr * mhat / (jnp.sqrt(vhat) + self._epsilon)
        return p, new_state


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, parameters=None, weight_decay=None, grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, multi_precision, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _state_names(self):
        return ["moment", "inf_norm"]

    def _update(self, p, g, state, lr, step):
        if self._weight_decay:
            g = g + self._weight_decay * p
        m = self._beta1 * state["moment"] + (1 - self._beta1) * g
        u = jnp.maximum(self._beta2 * state["inf_norm"], jnp.abs(g))
        p = p - lr / (1 - self._beta1**step) * m / (u + self._epsilon)
        return p, {"moment": m, "inf_norm": u}


class NAdam(Optimizer):
    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999, epsilon=1e-8, momentum_decay=0.004, parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, False, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._momentum_decay = momentum_decay

    def _state_names(self):
        return ["moment1", "moment2"]

    def _update(self, p, g, state, lr, step):
        if self._weight_decay:
            g = g + self._weight_decay * p
        b1, b2 = self._beta1, self._beta2
        mu_t = b1 * (1 - 0.5 * 0.96 ** (step * self._momentum_decay))
        mu_t1 = b1 * (1 - 0.5 * 0.96 ** ((step + 1) * self._momentum_decay))
        m = b1 * state["moment1"] + (1 - b1) * g
        v = b2 * state["moment2"] + (1 - b2) * g * g
        mhat = mu_t1 * m / (1 - b1 ** (step + 1)) + (1 - mu_t) * g / (1 - b1**step)
        vhat = v / (1 - b2**step)
        p = p - lr * mhat / (jnp.sqrt(vhat) + self._epsilon)
        return p, {"moment1": m, "moment2": v}


class RAdam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, False, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _state_names(self):
        return ["moment1", "moment2"]

    def _update(self, p, g, state, lr, step):
        if self._weight_decay:
            g = g + self._weight_decay * p
        b1, b2 = self._beta1, self._beta2
        m = b1 * state["moment1"] + (1 - b1) * g
        v = b2 * state["moment2"] + (1 - b2) * g * g
        mhat = m / (1 - b1**step)
        rho_inf = 2 / (1 - b2) - 1
        step_f = jnp.asarray(step, jnp.float32)
        rho_t = rho_inf - 2 * step_f * (b2**step_f) / (1 - b2**step_f)
        # traced-safe branch (step is a tracer on the jit path)
        l_t = jnp.sqrt(1 - b2**step_f) / (jnp.sqrt(v) + self._epsilon)
        safe_rho = jnp.maximum(rho_t, 4.0 + 1e-3)
        r_t = jnp.sqrt(
            ((safe_rho - 4) * (safe_rho - 2) * rho_inf)
            / ((rho_inf - 4) * (rho_inf - 2) * safe_rho)
        )
        rect = p - lr * r_t * mhat * l_t
        plain = p - lr * mhat
        p = jnp.where(rho_t > 5.0, rect, plain)
        return p, {"moment1": m, "moment2": v}


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None, exclude_from_weight_decay_fn=None, multi_precision=False, always_adapt=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, multi_precision, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._lamb_wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn
        self._always_adapt = always_adapt

    def _state_names(self):
        return ["moment1", "moment2"]

    def _update(self, p, g, state, lr, step):
        b1, b2 = self._beta1, self._beta2
        m = b1 * state["moment1"] + (1 - b1) * g
        v = b2 * state["moment2"] + (1 - b2) * g * g
        mhat = m / (1 - b1**step)
        vhat = v / (1 - b2**step)
        excluded = (self._exclude_fn is not None
                    and self._exclude_fn(getattr(self, "_current_param", None)))
        wd = 0.0 if excluded else self._lamb_wd
        r = mhat / (jnp.sqrt(vhat) + self._epsilon) + wd * p
        if excluded and not self._always_adapt:
            # reference: excluded params skip the layer-wise adaptation
            # unless always_adapt forces it
            return p - lr * r, {"moment1": m, "moment2": v}
        w_norm = jnp.linalg.norm(p)
        r_norm = jnp.linalg.norm(r)
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        return p - lr * trust * r, {"moment1": m, "moment2": v}


class ASGD(Optimizer):
    """Averaged/aggregated SGD (reference: optimizer/asgd.py:41 — the
    finite-sum SAG-style rule: d accumulates the freshest gradient of each
    of the last ``batch_num`` batches, y_i remembers batch i's gradient):

        d = d - y_i + g;  y_i = g;  x -= lr * (d / min(m+1, n) + wd * x)
    """

    def __init__(self, learning_rate=0.001, batch_num=1, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._n = int(batch_num)

    def _init_param_state(self, p):
        return {
            "d": jnp.zeros(tuple(jnp.shape(p)), jnp.float32),
            "ys": jnp.zeros((self._n,) + tuple(jnp.shape(p)), jnp.float32),
        }

    def _update(self, p, g, state, lr, step):
        m = step - 1  # 0-based batch counter
        i = m % self._n
        y_i = state["ys"][i]
        d = state["d"] - y_i + g
        ys = state["ys"].at[i].set(g)
        denom = jnp.minimum(jnp.asarray(m + 1, jnp.float32), float(self._n))
        new_p = p - lr * (d / denom + self._weight_decay * p)
        return new_p, {"d": d, "ys": ys}


class Rprop(Optimizer):
    """Resilient backpropagation (reference: optimizer/rprop.py:40):
    per-element step sizes grown by eta+ on agreeing gradient signs, shrunk
    by eta- on sign flips (with the flip's update suppressed)."""

    def __init__(self, learning_rate=0.001, learning_rate_range=(1e-5, 50.0),
                 parameters=None, etas=(0.5, 1.2), grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip,
                         multi_precision, name)
        self._lr0 = float(learning_rate)
        self._lr_min, self._lr_max = (float(v) for v in learning_rate_range)
        self._eta_minus, self._eta_plus = (float(v) for v in etas)

    def _init_param_state(self, p):
        return {
            "prev_grad": jnp.zeros(tuple(jnp.shape(p)), jnp.float32),
            "elem_lr": jnp.full(tuple(jnp.shape(p)), self._lr0, jnp.float32),
        }

    def _update(self, p, g, state, lr, step):
        prod = state["prev_grad"] * g
        elr = jnp.where(
            prod > 0, jnp.minimum(state["elem_lr"] * self._eta_plus, self._lr_max),
            jnp.where(prod < 0,
                      jnp.maximum(state["elem_lr"] * self._eta_minus, self._lr_min),
                      state["elem_lr"]))
        g_eff = jnp.where(prod < 0, 0.0, g)
        new_p = p - jnp.sign(g_eff) * elr
        return new_p, {"prev_grad": g_eff, "elem_lr": elr}


class LBFGS(Optimizer):
    """Limited-memory BFGS with closure-driven line search (reference:
    optimizer/lbfgs.py — step(closure) re-evaluates the loss; two-loop
    recursion over the last ``history_size`` (s, y) pairs; 'strong_wolfe'
    is approximated by backtracking Armijo, which the reference also falls
    back to between wolfe probes)."""

    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None,
                 tolerance_grad=1e-7, tolerance_change=1e-9, history_size=100,
                 line_search_fn=None, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         False, name)
        if grad_clip is not None:
            raise NotImplementedError(
                "LBFGS: grad_clip inside the line search is not supported")
        self._max_iter = int(max_iter)
        # reference default: max_iter * 5 / 4 closure evaluations
        self._max_eval = (int(max_eval) if max_eval is not None
                          else self._max_iter * 5 // 4)
        self._tol_grad = float(tolerance_grad)
        self._tol_change = float(tolerance_change)
        self._hist = int(history_size)
        self._line_search = line_search_fn
        self._s: list = []
        self._y: list = []
        self._prev_flat = None
        self._prev_grad = None

    def _flat_params(self):
        return jnp.concatenate([
            _unwrap(p).astype(jnp.float32).reshape(-1)
            for p in self._parameter_list])

    def _flat_grads(self):
        return jnp.concatenate([
            (_unwrap(p.grad).astype(jnp.float32).reshape(-1)
             if p.grad is not None else jnp.zeros(int(np.prod(p.shape)),
                                                  jnp.float32))
            for p in self._parameter_list])

    def _write_flat(self, flat):
        off = 0
        for p in self._parameter_list:
            n = int(np.prod(p.shape))
            p._value = flat[off:off + n].reshape(p.shape).astype(p.dtype)
            off += n

    def _direction(self, g):
        q = g
        alphas = []
        for s_i, y_i in zip(reversed(self._s), reversed(self._y)):
            rho = 1.0 / jnp.maximum(jnp.vdot(y_i, s_i), 1e-10)
            a = rho * jnp.vdot(s_i, q)
            q = q - a * y_i
            alphas.append((a, rho, s_i, y_i))
        if self._y:
            gamma = (jnp.vdot(self._s[-1], self._y[-1])
                     / jnp.maximum(jnp.vdot(self._y[-1], self._y[-1]), 1e-10))
            q = q * gamma
        for a, rho, s_i, y_i in reversed(alphas):
            b = rho * jnp.vdot(y_i, q)
            q = q + (a - b) * s_i
        return -q

    def step(self, closure):
        """closure: re-evaluates the model and returns the loss (it must
        call loss.backward() itself, reference lbfgs.py contract)."""
        wd = self._weight_decay

        def F_of(loss_val, flat):
            # the line search must probe the REGULARIZED objective the
            # gradient describes, or wd-steps get accepted/rejected against
            # the wrong directional derivative
            f = float(loss_val)
            if wd:
                f += 0.5 * wd * float(jnp.vdot(flat, flat))
            return f

        for p in self._parameter_list:
            p.clear_grad()  # a prior step()'s last probe leaves grads behind
        loss = closure()
        n_evals = 1
        for _ in range(self._max_iter):
            if n_evals >= self._max_eval:
                break
            flat = self._flat_params()
            g = self._flat_grads()
            if wd:
                g = g + wd * flat
            if float(jnp.max(jnp.abs(g))) <= self._tol_grad:
                break
            if self._prev_flat is not None:
                s_k = flat - self._prev_flat
                y_k = g - self._prev_grad
                if float(jnp.vdot(s_k, y_k)) > 1e-10:
                    self._s.append(s_k)
                    self._y.append(y_k)
                    if len(self._s) > self._hist:
                        self._s.pop(0)
                        self._y.pop(0)
            d = self._direction(g)
            self._prev_flat, self._prev_grad = flat, g
            t = self.get_lr()
            f0 = F_of(loss, flat)
            gtd = float(jnp.vdot(g, d))
            # backtracking Armijo (the reference's wolfe search reduces to
            # this when the curvature probe succeeds immediately)
            for _bt in range(20):
                self._write_flat(flat + t * d)
                for p in self._parameter_list:
                    p.clear_grad()
                loss = closure()
                n_evals += 1
                if (F_of(loss, flat + t * d) <= f0 + 1e-4 * t * gtd
                        or self._line_search is None
                        or n_evals >= self._max_eval):
                    break
                t *= 0.5
            if abs(float(jnp.max(jnp.abs(t * d)))) < self._tol_change:
                break
        return loss
