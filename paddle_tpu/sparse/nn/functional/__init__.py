"""Sparse neural-net functional ops (reference: python/paddle/sparse/nn/
functional/ — conv.py conv3d/subm_conv3d, transformer.py:28 attention,
activation.py relu).

TPU-first design: the reference lowers these to cuSPARSE/custom CUDA
"rulebook" kernels.  On TPU the honest mapping is gather/scatter over the
BCOO coordinate list feeding dense MXU matmuls:

- ``conv3d`` iterates the (static, small) kernel offsets; each offset is one
  dense [nnz, Cin] @ [Cin, M] matmul whose rows scatter-add into the output
  grid.  The output pattern is exactly the set of positions receiving any
  contribution (the reference's output layout), extracted host-side.
- ``subm_conv3d`` is pattern-preserving: neighbors are located by binary
  search (searchsorted) over linearized coordinates — a pure gather, no
  scatter, and the output keeps the input's indices (submanifold semantics,
  reference conv.py:578).
- ``attention`` computes the masked dense softmax(QK^T)V restricted to the
  sparse layout; on TPU a masked dense contraction IS the fast path (the MXU
  wants dense tiles), while the semantics match the reference's
  sparse_fused_attention (transformer.py:28).

All ops are composed of jnp primitives, so jax.grad provides the backward
passes (the reference registers hand-written CUDA grads).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

__all__ = ["conv3d", "subm_conv3d", "conv2d", "subm_conv2d",
           "subm_conv2d_igemm", "subm_conv3d_igemm", "attention",
           "relu", "relu6", "leaky_relu", "softmax", "max_pool3d"]


def _triple(v):
    return tuple(v) if isinstance(v, (tuple, list)) else (v,) * 3


def _coords_vals(x):
    """NDHWC normalization: (coords [nnz, 4] over (n, d, h, w),
    vals [nnz, C])."""
    return _coords_vals_nd(x, 4)


def _out_dim(size, k, stride, pad, dil):
    return (size + 2 * pad - (dil * (k - 1) + 1)) // stride + 1


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NDHWC", name=None):
    """Sparse 3-D convolution (reference sparse/nn/functional/conv.py:conv3d;
    layer at conv.py:308).  x: SparseCooTensor [N, D, H, W, C]; weight
    [kD, kH, kW, C, M] (DHWCM).  Returns a SparseCooTensor whose pattern is
    the set of output positions covered by any input non-zero."""
    from .... import sparse as sp

    assert groups == 1, "sparse conv3d currently supports groups=1 only"
    assert data_format == "NDHWC", data_format
    w = jnp.asarray(getattr(weight, "_value", weight))
    kD, kH, kW, Cin, M = w.shape
    st, pd, dl = _triple(stride), _triple(padding), _triple(dilation)
    coords, vals = _coords_vals(x)
    N, D, H, W, C = x.shape
    assert C == Cin, (C, Cin)
    Do = _out_dim(D, kD, st[0], pd[0], dl[0])
    Ho = _out_dim(H, kH, st[1], pd[1], dl[1])
    Wo = _out_dim(W, kW, st[2], pd[2], dl[2])

    def dense_out(coords, vals, w):
        out = jnp.zeros((N, Do, Ho, Wo, M), vals.dtype)
        occ = jnp.zeros((N, Do, Ho, Wo), jnp.int32)
        for kd in range(kD):
            for kh in range(kH):
                for kw in range(kW):
                    od = coords[:, 1] + pd[0] - kd * dl[0]
                    oh = coords[:, 2] + pd[1] - kh * dl[1]
                    ow = coords[:, 3] + pd[2] - kw * dl[2]
                    valid = ((od % st[0] == 0) & (oh % st[1] == 0)
                             & (ow % st[2] == 0))
                    od, oh, ow = od // st[0], oh // st[1], ow // st[2]
                    valid &= ((od >= 0) & (od < Do) & (oh >= 0) & (oh < Ho)
                              & (ow >= 0) & (ow < Wo))
                    contrib = vals @ w[kd, kh, kw]        # [nnz, M] on MXU
                    contrib = jnp.where(valid[:, None], contrib, 0)
                    n_ = coords[:, 0]
                    od = jnp.where(valid, od, 0)
                    oh = jnp.where(valid, oh, 0)
                    ow = jnp.where(valid, ow, 0)
                    out = out.at[n_, od, oh, ow].add(contrib)
                    occ = occ.at[n_, od, oh, ow].add(valid.astype(jnp.int32))
        return out, occ

    out, occ = dense_out(coords, vals, w)
    if bias is not None:
        b = jnp.asarray(getattr(bias, "_value", bias))
        out = out + jnp.where(occ[..., None] > 0, b, 0)
    # output pattern = positions receiving any contribution (exact even when
    # values cancel to 0); host-side extraction (dynamic nnz)
    pattern = np.asarray(occ) > 0
    idx = np.argwhere(pattern).astype(np.int32)           # [nnz_out, 4]
    out_vals = out[idx[:, 0], idx[:, 1], idx[:, 2], idx[:, 3]]
    bcoo = jsparse.BCOO((out_vals, jnp.asarray(idx)),
                        shape=(N, Do, Ho, Wo, M))
    return sp.SparseCooTensor(bcoo)


def subm_conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NDHWC", key=None, name=None):
    """Submanifold sparse conv (reference conv.py:578 SubmConv3D): the output
    keeps the INPUT's sparsity pattern — only positions that already hold a
    non-zero produce output, so deep stacks don't densify.  Neighbor lookup
    is a searchsorted gather over linearized coordinates."""
    from .... import sparse as sp

    assert groups == 1, "sparse subm_conv3d currently supports groups=1 only"
    assert data_format == "NDHWC", data_format
    if _triple(stride) != (1, 1, 1):
        raise NotImplementedError(
            "subm_conv3d is pattern-preserving; stride != 1 is not supported")
    w = jnp.asarray(getattr(weight, "_value", weight))
    kD, kH, kW, Cin, M = w.shape
    dl = _triple(dilation)
    coords, vals = _coords_vals(x)
    N, D, H, W, C = x.shape
    assert C == Cin, (C, Cin)

    def lin(c):  # linearize (n, d, h, w); grids here fit int32
        return ((c[:, 0] * D + c[:, 1]) * H + c[:, 2]) * W + c[:, 3]

    base = lin(coords)
    order = jnp.argsort(base)
    sorted_lin = base[order]

    def gather_out(vals, w):
        acc = jnp.zeros((coords.shape[0], M), vals.dtype)
        for kd in range(kD):
            for kh in range(kH):
                for kw in range(kW):
                    # neighbor whose center-aligned offset contributes here
                    dd = (kd - kD // 2) * dl[0]
                    dh = (kh - kH // 2) * dl[1]
                    dw = (kw - kW // 2) * dl[2]
                    nd = coords[:, 1] + dd
                    nh = coords[:, 2] + dh
                    nw = coords[:, 3] + dw
                    inb = ((nd >= 0) & (nd < D) & (nh >= 0) & (nh < H)
                           & (nw >= 0) & (nw < W))
                    nb = ((coords[:, 0] * D + nd) * H + nh) * W + nw
                    pos = jnp.searchsorted(sorted_lin, nb)
                    pos_c = jnp.clip(pos, 0, sorted_lin.shape[0] - 1)
                    found = inb & (sorted_lin[pos_c] == nb)
                    j = order[pos_c]
                    nb_vals = jnp.where(found[:, None], vals[j], 0)
                    # correlation semantics (matches the dense conv3d):
                    # out[c] += x[c + (k - center)] * w[k]
                    acc = acc + nb_vals @ w[kd, kh, kw]
        return acc

    out_vals = gather_out(vals, w)
    if bias is not None:
        out_vals = out_vals + jnp.asarray(getattr(bias, "_value", bias))
    bcoo = jsparse.BCOO((out_vals, coords), shape=(N, D, H, W, M))
    return sp.SparseCooTensor(bcoo)


def subm_conv2d_igemm(x, weight, bias=None, stride=1, padding=0, dilation=1,
                      groups=1, data_format="NHWC", key=None, name=None):
    """Reference's implicit-GEMM kernel variant of subm_conv2d (a CUDA
    kernel-choice distinction); on TPU the searchsorted-gather + dense GEMM
    engine IS the implicit-GEMM formulation, so both names share it."""
    return subm_conv2d(x, weight, bias, stride, padding, dilation, groups,
                       data_format, key=key)


def subm_conv3d_igemm(x, weight, bias=None, stride=1, padding=0, dilation=1,
                      groups=1, data_format="NDHWC", key=None, name=None):
    """Implicit-GEMM variant of subm_conv3d (see subm_conv2d_igemm)."""
    return subm_conv3d(x, weight, bias, stride, padding, dilation, groups,
                       data_format, key=key)


def attention(query, key, value, sparse_mask, key_padding_mask=None,
              attn_mask=None, name=None):
    """Sparse-layout attention (reference sparse/nn/functional/transformer
    .py:28 sparse_fused_attention): softmax(QK^T/sqrt(d)) V evaluated only at
    the positions present in ``sparse_mask`` (a SparseCsrTensor of dense
    shape [batch*num_heads, seq, seq]); zeros of ``key_padding_mask``
    [batch, seq] and ``attn_mask`` [seq, seq] also exclude positions.  On TPU
    the layout-restricted scores are computed as a masked dense contraction
    (the MXU-honest lowering of the reference's cuSPARSE SDD kernel)."""
    from ....core.tensor import Tensor

    q = jnp.asarray(getattr(query, "_value", query))
    k = jnp.asarray(getattr(key, "_value", key))
    v = jnp.asarray(getattr(value, "_value", value))
    B, Hh, S, hd = q.shape
    mask_dense = sparse_mask.to_dense()
    md = jnp.asarray(getattr(mask_dense, "_value", mask_dense))
    keep = (md != 0).reshape(B, Hh, S, S)
    if key_padding_mask is not None:
        kp = jnp.asarray(getattr(key_padding_mask, "_value", key_padding_mask))
        keep = keep & (kp[:, None, None, :] != 0)
    if attn_mask is not None:
        am = jnp.asarray(getattr(attn_mask, "_value", attn_mask))
        keep = keep & (am[None, None] != 0)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(hd)
    scores = jnp.where(keep, scores, -jnp.inf)
    # fully-masked rows softmax to zeros, not NaN
    mx = jnp.max(scores, axis=-1, keepdims=True)
    mx = jnp.where(jnp.isfinite(mx), mx, 0.0)
    e = jnp.where(keep, jnp.exp(scores - mx), 0.0)
    denom = jnp.sum(e, axis=-1, keepdims=True)
    p = e / jnp.where(denom == 0, 1.0, denom)
    return Tensor(jnp.einsum("bhqk,bhkd->bhqd", p, v))


def relu(x, name=None):
    from .... import sparse as sp

    return sp.relu(x)


def relu6(x, name=None):
    from .... import sparse as sp

    return sp._as_coo(x)._map(lambda v: jnp.clip(v, 0, 6))


def leaky_relu(x, negative_slope=0.01, name=None):
    from .... import sparse as sp

    return sp._as_coo(x)._map(
        lambda v: jnp.where(v >= 0, v, negative_slope * v))


def softmax(x, axis=-1, name=None):
    """Sparse softmax over the stored values of each last-dim row
    (reference sparse/nn/functional/activation.py softmax: only the
    non-zero entries participate; zeros are treated as -inf, NOT 0)."""
    from .... import sparse as sp

    coo = x if isinstance(x, sp.SparseCooTensor) else x.to_sparse_coo()
    b = coo._bcoo
    nd = b.indices.shape[1]
    if axis not in (-1, nd - 1):
        raise NotImplementedError("sparse softmax supports the last axis")
    # group rows: linearize all dims but the last
    key = jnp.zeros(b.indices.shape[0], jnp.int32)
    mul = 1
    nrows = 1
    for d in range(nd - 2, -1, -1):
        key = key + b.indices[:, d].astype(jnp.int32) * mul
        mul *= coo.shape[d]
        nrows *= coo.shape[d]
    v = b.data.astype(jnp.float32)
    mx = jax.ops.segment_max(v, key, num_segments=nrows)
    e = jnp.exp(v - mx[key])
    den = jax.ops.segment_sum(e, key, num_segments=nrows)
    out = (e / den[key]).astype(b.data.dtype)
    res = sp.SparseCooTensor(jsparse.BCOO((out, b.indices), shape=b.shape))
    return res if isinstance(x, sp.SparseCooTensor) else res.to_sparse_csr()


def _as_3d(x):
    """Lift an NHWC sparse tensor to NDHWC with a singleton depth, so the
    2-D convs reuse the 3-D gather/scatter engines."""
    from .... import sparse as sp

    coords, vals = _coords_vals_nd(x, 3)
    N, H, W, C = x.shape
    c4 = jnp.concatenate([coords[:, :1],
                          jnp.zeros((coords.shape[0], 1), coords.dtype),
                          coords[:, 1:]], axis=1)
    return sp.SparseCooTensor(jsparse.BCOO((vals, c4),
                                           shape=(N, 1, H, W, C)))


def _coords_vals_nd(x, n_spatial_plus_batch):
    """_coords_vals generalized to [N, spatial..., C] tensors."""
    b = x._bcoo
    nd = n_spatial_plus_batch
    if b.indices.shape[1] == nd and b.data.ndim == 2:
        return jnp.asarray(b.indices), jnp.asarray(b.data)
    if b.indices.shape[1] == nd + 1:
        idx = np.asarray(b.indices)
        dat = np.asarray(b.data)
        C = x.shape[-1]
        spatial, inv = np.unique(idx[:, :nd], axis=0, return_inverse=True)
        vals = np.zeros((len(spatial), C), dat.dtype)
        np.add.at(vals, (inv, idx[:, nd]), dat)
        return jnp.asarray(spatial), jnp.asarray(vals)
    raise ValueError((b.indices.shape, b.data.shape))


def _pair(v):
    return tuple(v) if isinstance(v, (tuple, list)) else (v,) * 2


def _strip_depth(out3):
    """Drop the singleton depth axis the 2-D convs added for the 3-D
    engine: NDHWC output (D=1) -> NHWC."""
    from .... import sparse as sp

    b3 = out3._bcoo
    idx = jnp.concatenate([b3.indices[:, :1], b3.indices[:, 2:]], axis=1)
    N, _, Ho, Wo, M_ = out3.shape
    return sp.SparseCooTensor(jsparse.BCOO((b3.data, idx),
                                           shape=(N, Ho, Wo, M_)))


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NHWC", name=None):
    """Sparse 2-D conv (reference sparse Conv2D, conv.py): NHWC input,
    HWCM kernel — runs through the 3-D engine with a singleton depth."""
    assert data_format == "NHWC", data_format
    w = jnp.asarray(getattr(weight, "_value", weight))
    st, pd, dl = _pair(stride), _pair(padding), _pair(dilation)
    return _strip_depth(conv3d(_as_3d(x), w[None], bias, (1,) + st,
                               (0,) + pd, (1,) + dl, groups, "NDHWC"))


def subm_conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NHWC", key=None, name=None):
    assert data_format == "NHWC", data_format
    w = jnp.asarray(getattr(weight, "_value", weight))
    st, pd, dl = _pair(stride), _pair(padding), _pair(dilation)
    return _strip_depth(subm_conv3d(_as_3d(x), w[None], bias, (1,) + st,
                                    (0,) + pd, (1,) + dl, groups, "NDHWC",
                                    key=key))


def max_pool3d(x, kernel_size, stride=None, padding=0, data_format="NDHWC",
               name=None):
    """Sparse max-pool (reference sparse/nn/functional/pooling.py): the max
    is over the PRESENT entries of each window — windows with no non-zeros
    produce no output entry (sparse semantics, not zero-padding)."""
    from .... import sparse as sp

    assert data_format == "NDHWC", data_format
    kD, kH, kW = _triple(kernel_size)
    st = _triple(stride if stride is not None else kernel_size)
    pd = _triple(padding)
    coords, vals = _coords_vals(x)
    N, D, H, W, C = x.shape
    Do = _out_dim(D, kD, st[0], pd[0], 1)
    Ho = _out_dim(H, kH, st[1], pd[1], 1)
    Wo = _out_dim(W, kW, st[2], pd[2], 1)
    out = jnp.full((N, Do, Ho, Wo, C), -jnp.inf, jnp.float32)
    occ = jnp.zeros((N, Do, Ho, Wo), jnp.int32)
    for kd in range(kD):
        for kh in range(kH):
            for kw in range(kW):
                od = coords[:, 1] + pd[0] - kd
                oh = coords[:, 2] + pd[1] - kh
                ow = coords[:, 3] + pd[2] - kw
                valid = ((od % st[0] == 0) & (oh % st[1] == 0)
                         & (ow % st[2] == 0))
                od, oh, ow = od // st[0], oh // st[1], ow // st[2]
                valid &= ((od >= 0) & (od < Do) & (oh >= 0) & (oh < Ho)
                          & (ow >= 0) & (ow < Wo))
                contrib = jnp.where(valid[:, None],
                                    vals.astype(jnp.float32), -jnp.inf)
                n_ = coords[:, 0]
                od = jnp.where(valid, od, 0)
                oh = jnp.where(valid, oh, 0)
                ow = jnp.where(valid, ow, 0)
                out = out.at[n_, od, oh, ow].max(contrib)
                occ = occ.at[n_, od, oh, ow].add(valid.astype(jnp.int32))
    pattern = np.asarray(occ) > 0
    idx = np.argwhere(pattern).astype(np.int32)
    out_vals = out[idx[:, 0], idx[:, 1], idx[:, 2], idx[:, 3]]
    return sp.SparseCooTensor(jsparse.BCOO(
        (out_vals.astype(x.dtype), jnp.asarray(idx)),
        shape=(N, Do, Ho, Wo, C)))
