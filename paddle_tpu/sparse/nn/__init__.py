"""Sparse nn layers (reference: python/paddle/sparse/nn/layer/ — conv.py:308
Conv3D, :578 SubmConv3D, norm.py BatchNorm, activation.py ReLU).

Layer classes hold parameters through the framework Layer base (so
state_dict/apply/to work) and delegate math to sparse.nn.functional.
"""

from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from ...nn.layer_base import Layer
from . import functional  # noqa: F401
from .functional import (attention, conv2d, conv3d, leaky_relu, max_pool3d,
                         relu as _frelu, relu6 as _frelu6, softmax as _fsoftmax,
                         subm_conv2d, subm_conv3d)

__all__ = ["ReLU", "ReLU6", "LeakyReLU", "Softmax", "Conv2D", "Conv3D",
           "SubmConv2D", "SubmConv3D", "BatchNorm", "SyncBatchNorm",
           "MaxPool3D", "functional"]


class ReLU(Layer):
    def forward(self, x):
        return _frelu(x)

    __call__ = forward


class ReLU6(Layer):
    def forward(self, x):
        return _frelu6(x)

    __call__ = forward


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self.negative_slope = negative_slope

    def forward(self, x):
        return leaky_relu(x, self.negative_slope)

    __call__ = forward


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return _fsoftmax(x, self.axis)

    __call__ = forward


class MaxPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NDHWC", name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.data_format = data_format

    def forward(self, x):
        return max_pool3d(x, self.kernel_size, self.stride, self.padding,
                          self.data_format)

    __call__ = forward


class _ConvBase(Layer):
    _ndim = 3

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format=None):
        super().__init__()
        assert padding_mode == "zeros", padding_mode
        if groups != 1:
            raise NotImplementedError(
                f"{type(self).__name__}: sparse convs support groups=1 only "
                f"(got groups={groups})")
        nd = self._ndim
        tup = (lambda v: tuple(v) if isinstance(v, (tuple, list))
               else (v,) * nd)
        self.in_channels = in_channels
        self.out_channels = out_channels
        self.kernel_size = tup(kernel_size)
        self.stride = tup(stride)
        self.padding = padding
        self.dilation = tup(dilation)
        self.groups = groups
        self.data_format = data_format or ("NDHWC" if nd == 3 else "NHWC")
        # reference default init: Normal(0, sqrt(2 / fan_in))
        fan_in = in_channels
        for k in self.kernel_size:
            fan_in *= k
        std = math.sqrt(2.0 / fan_in)
        from ...nn import initializer as I

        self.weight = self.create_parameter(
            self.kernel_size + (in_channels, out_channels), attr=weight_attr,
            default_initializer=I.Normal(0.0, std))
        self.bias = (self.create_parameter(
            (out_channels,), attr=bias_attr, is_bias=True)
            if bias_attr is not False else None)


class Conv3D(_ConvBase):
    """Sparse 3-D convolution layer (reference sparse/nn/layer/conv.py:308).
    Input/output are SparseCooTensors in NDHWC; weight is DHWCM."""

    def forward(self, x):
        return conv3d(x, self.weight, self.bias, self.stride, self.padding,
                      self.dilation, self.groups, self.data_format)

    __call__ = forward


class SubmConv3D(_ConvBase):
    """Submanifold sparse conv layer (reference conv.py:578): output keeps
    the input's sparsity pattern."""

    def __init__(self, *args, key=None, **kwargs):
        super().__init__(*args, **kwargs)
        self.key = key

    def forward(self, x):
        return subm_conv3d(x, self.weight, self.bias, self.stride,
                           self.padding, self.dilation, self.groups,
                           self.data_format, key=self.key)

    __call__ = forward


class Conv2D(_ConvBase):
    """Sparse 2-D conv layer (reference sparse/nn/layer/conv.py Conv2D);
    NHWC input, HWCM kernel."""

    _ndim = 2

    def forward(self, x):
        return conv2d(x, self.weight, self.bias, self.stride, self.padding,
                      self.dilation, self.groups, self.data_format)

    __call__ = forward


class SubmConv2D(_ConvBase):
    _ndim = 2

    def __init__(self, *args, key=None, **kwargs):
        super().__init__(*args, **kwargs)
        self.key = key

    def forward(self, x):
        return subm_conv2d(x, self.weight, self.bias, self.stride,
                           self.padding, self.dilation, self.groups,
                           self.data_format, key=self.key)

    __call__ = forward


class BatchNorm(Layer):
    """Sparse BatchNorm (reference sparse/nn/layer/norm.py BatchNorm):
    normalizes the COO values [nnz, C] per channel over the non-zero
    elements — zeros never enter the statistics."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NDHWC",
                 use_global_stats=None, name=None):
        super().__init__()
        assert data_format == "NDHWC", data_format
        self.num_features = num_features
        self.momentum = momentum
        self.epsilon = epsilon
        self.use_global_stats = use_global_stats
        from ...nn import initializer as I

        self.weight = self.create_parameter(
            (num_features,), attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter(
            (num_features,), attr=bias_attr, is_bias=True)
        self.register_buffer("_mean", jnp.zeros((num_features,), jnp.float32))
        self.register_buffer("_variance",
                             jnp.ones((num_features,), jnp.float32))

    def forward(self, x):
        from ... import sparse as sp
        from jax.experimental import sparse as jsparse

        b = x._bcoo
        vals = b.data
        assert vals.ndim >= 1 and vals.shape[-1] == self.num_features, (
            vals.shape, self.num_features)
        v32 = vals.astype(jnp.float32)
        use_global = (self.use_global_stats
                      if self.use_global_stats is not None
                      else not self.training)
        if use_global:
            mean = jnp.asarray(self._mean.numpy()
                               if hasattr(self._mean, "numpy")
                               else self._mean)
            var = jnp.asarray(self._variance.numpy()
                              if hasattr(self._variance, "numpy")
                              else self._variance)
        else:
            axes = tuple(range(v32.ndim - 1))
            mean = v32.mean(axis=axes)
            var = v32.var(axis=axes)
            m = self.momentum
            old_m = np.asarray(self._mean.numpy()
                               if hasattr(self._mean, "numpy")
                               else self._mean)
            old_v = np.asarray(self._variance.numpy()
                               if hasattr(self._variance, "numpy")
                               else self._variance)
            self._buffers["_mean"] = jnp.asarray(
                m * old_m + (1 - m) * np.asarray(mean))
            self._buffers["_variance"] = jnp.asarray(
                m * old_v + (1 - m) * np.asarray(var))
        g = jnp.asarray(getattr(self.weight, "_value", self.weight))
        be = jnp.asarray(getattr(self.bias, "_value", self.bias))
        out = (v32 - mean) / jnp.sqrt(var + self.epsilon) * g + be
        return sp.SparseCooTensor(
            jsparse.BCOO((out.astype(vals.dtype), b.indices), shape=b.shape))

    __call__ = forward


class SyncBatchNorm(BatchNorm):
    """Sparse SyncBatchNorm (reference sparse/nn/layer/norm.py
    SyncBatchNorm): under pjit/GSPMD the batch statistics reduce across the
    data-parallel mesh automatically (the mean/var jnp reductions are global
    under sharding), so the eager single-process behavior is BatchNorm —
    the same absorption as the dense SyncBatchNorm."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        if isinstance(layer, BatchNorm) and not isinstance(layer, cls):
            out = cls(layer.num_features, layer.momentum, layer.epsilon)
            out.weight = layer.weight
            out.bias = layer.bias
            out._buffers.update(layer._buffers)
            return out
        for name, sub in getattr(layer, "_sub_layers", {}).items():
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return layer
