"""Sparse tensors (reference: python/paddle/sparse/ — COO/CSR creation in
sparse/creation.py, unary/binary ops sparse/unary.py, binary.py, matmul in
sparse/matmul.py; C++ SparseCooTensor/SparseCsrTensor in
paddle/phi/core/sparse_coo_tensor.h, kernels paddle/phi/kernels/sparse/).

TPU-native: backed by jax.experimental.sparse.BCOO — XLA lowers sparse
contractions to gather/scatter + dense MXU matmuls, which on TPU is the
honest cost model (the reference's cuSPARSE path has no TPU analog).  CSR is
carried as a thin view that converts through BCOO; dense bridges
(to_dense/values/indices) dispatch through the eager tape so gradients flow
into dense consumers."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor, apply_op, _unwrap

__all__ = [
    "sparse_coo_tensor", "sparse_csr_tensor", "SparseCooTensor",
    "SparseCsrTensor", "is_same_shape", "matmul", "masked_matmul", "add",
    "multiply", "subtract", "relu", "sin", "tanh", "abs", "sqrt", "square",
    "pow", "neg", "cast", "transpose", "sum",
]


class SparseCooTensor:
    """COO sparse tensor (reference sparse_coo_tensor.h:30)."""

    def __init__(self, bcoo: jsparse.BCOO):
        self._bcoo = bcoo

    # -- creation-side accessors -----------------------------------------
    @property
    def shape(self):
        return tuple(self._bcoo.shape)

    @property
    def dtype(self):
        return self._bcoo.dtype

    def nnz(self):
        return int(self._bcoo.nse)

    def indices(self):
        return Tensor(jnp.asarray(self._bcoo.indices).T)  # paddle: [ndim, nnz]

    def values(self):
        return Tensor(self._bcoo.data)

    def to_dense(self):
        if self._bcoo.data.dtype == jnp.bool_:
            # BCOO densify scatter-adds, which rejects bool — round-trip int8
            cast = jsparse.BCOO(
                (self._bcoo.data.astype(jnp.int8), self._bcoo.indices),
                shape=self._bcoo.shape)
            return Tensor(cast.todense().astype(jnp.bool_))
        return Tensor(self._bcoo.todense())

    def to_sparse_csr(self):
        return SparseCsrTensor.from_coo(self)

    def coalesce(self):
        return SparseCooTensor(self._bcoo.sum_duplicates())

    def is_sparse_coo(self):
        return True

    def is_sparse_csr(self):
        return False

    def _map(self, fn):
        return SparseCooTensor(jsparse.BCOO((fn(self._bcoo.data),
                                             self._bcoo.indices),
                                            shape=self._bcoo.shape))

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")


class SparseCsrTensor:
    """CSR view (reference sparse_csr_tensor.h); stores crows/cols/values and
    converts through BCOO for compute."""

    def __init__(self, crows, cols, values, shape):
        self._crows = jnp.asarray(_unwrap(crows), jnp.int32)
        self._cols = jnp.asarray(_unwrap(cols), jnp.int32)
        self._values = jnp.asarray(_unwrap(values))
        self._shape = tuple(int(s) for s in shape)

    @staticmethod
    def from_coo(coo: SparseCooTensor) -> "SparseCsrTensor":
        if len(coo.shape) != 2:
            raise ValueError("CSR requires 2-D")
        idx = np.asarray(coo._bcoo.indices)
        data = coo._bcoo.data
        order = np.lexsort((idx[:, 1], idx[:, 0]))
        rows, cols = idx[order, 0], idx[order, 1]
        crows = np.zeros(coo.shape[0] + 1, np.int32)
        np.add.at(crows, rows + 1, 1)
        crows = np.cumsum(crows).astype(np.int32)
        return SparseCsrTensor(crows, cols, jnp.take(data, jnp.asarray(order)),
                               coo.shape)

    @property
    def shape(self):
        return self._shape

    @property
    def dtype(self):
        return self._values.dtype

    def nnz(self):
        return int(self._cols.shape[0])

    def crows(self):
        return Tensor(self._crows)

    def cols(self):
        return Tensor(self._cols)

    def values(self):
        return Tensor(self._values)

    def to_sparse_coo(self, sparse_dim=2):
        counts = np.diff(np.asarray(self._crows))
        rows = np.repeat(np.arange(self._shape[0]), counts)
        idx = jnp.stack([jnp.asarray(rows, jnp.int32), self._cols], axis=1)
        return SparseCooTensor(jsparse.BCOO((self._values, idx), shape=self._shape))

    def to_dense(self):
        return self.to_sparse_coo().to_dense()

    def is_sparse_coo(self):
        return False

    def is_sparse_csr(self):
        return True

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True):
    """Reference: python/paddle/sparse/creation.py:sparse_coo_tensor.
    indices: [ndim, nnz]."""
    idx = jnp.asarray(_unwrap(indices), jnp.int32).T  # BCOO: [nnz, ndim]
    vals = jnp.asarray(_unwrap(values))
    if dtype is not None:
        vals = vals.astype(dtype)
    if shape is None:
        shape = tuple(int(m) + 1 for m in np.asarray(idx).max(axis=0))
    return SparseCooTensor(jsparse.BCOO((vals, idx), shape=tuple(shape)))


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    vals = _unwrap(values)
    if dtype is not None:
        vals = jnp.asarray(vals).astype(dtype)
    return SparseCsrTensor(crows, cols, vals, shape)


def is_same_shape(x, y) -> bool:
    return tuple(x.shape) == tuple(y.shape)


def _as_coo(x) -> SparseCooTensor:
    if isinstance(x, SparseCooTensor):
        return x
    if isinstance(x, SparseCsrTensor):
        return x.to_sparse_coo()
    raise TypeError(f"expected sparse tensor, got {type(x)}")


def matmul(x, y, name=None):
    """Sparse @ dense (reference sparse/matmul.py)."""
    coo = _as_coo(x)
    yv = _unwrap(y)
    out = coo._bcoo @ jnp.asarray(yv)
    return Tensor(out)


def masked_matmul(x, y, mask, name=None):
    """Dense @ dense, keeping only mask's sparsity pattern (reference
    sparse/matmul.py:masked_matmul; SDDMM)."""
    m = _as_coo(mask)
    xv, yv = jnp.asarray(_unwrap(x)), jnp.asarray(_unwrap(y))
    idx = m._bcoo.indices  # [nnz, 2]
    rows, cols = idx[:, 0], idx[:, 1]
    vals = jnp.einsum("nk,nk->n", xv[rows], yv[:, cols].T)
    return SparseCooTensor(jsparse.BCOO((vals.astype(m.dtype), idx), shape=m.shape))


def _pattern_union(a: jsparse.BCOO, b: jsparse.BCOO, bsign=1.0) -> jsparse.BCOO:
    """O(nnz) union: concatenate (data, indices) and merge duplicates."""
    data = jnp.concatenate([a.data, (b.data * bsign).astype(a.data.dtype)])
    idx = jnp.concatenate([a.indices, b.indices])
    return jsparse.BCOO((data, idx), shape=a.shape).sum_duplicates()


def add(x, y, name=None):
    a, b = _as_coo(x), _as_coo(y)
    return SparseCooTensor(_pattern_union(a._bcoo, b._bcoo))


def subtract(x, y, name=None):
    a, b = _as_coo(x), _as_coo(y)
    return SparseCooTensor(_pattern_union(a._bcoo, b._bcoo, bsign=-1.0))


def multiply(x, y, name=None):
    """O(nnz_a * lookup) intersection: for each of a's entries, find the
    matching entry in b (hash the coordinates into a scalar key)."""
    a, b = _as_coo(x)._bcoo.sum_duplicates(), _as_coo(y)._bcoo.sum_duplicates()
    # row-major strides: strides[i] = prod(shape[i+1:]), last stride 1
    strides = jnp.asarray(
        np.append(np.cumprod(np.asarray(a.shape[1:])[::-1])[::-1], 1)
        if len(a.shape) > 1 else [1], jnp.int64)
    ka = (a.indices.astype(jnp.int64) * strides).sum(-1)
    kb = (b.indices.astype(jnp.int64) * strides).sum(-1)
    order = jnp.argsort(kb)
    kb_sorted = kb[order]
    pos = jnp.searchsorted(kb_sorted, ka)
    pos = jnp.clip(pos, 0, kb_sorted.shape[0] - 1)
    match = kb_sorted[pos] == ka
    bvals = b.data[order][pos]
    data = jnp.where(match, a.data * bvals, 0)
    return SparseCooTensor(jsparse.BCOO((data, a.indices), shape=a.shape))


def _unary(name, jfn):
    def op(x, name=None):
        return _as_coo(x)._map(jfn)

    op.__name__ = name
    return op


# value-wise ops preserve the sparsity pattern (f(0)=0 family, reference
# sparse/unary.py)
relu = _unary("relu", jax.nn.relu)
sin = _unary("sin", jnp.sin)
tanh = _unary("tanh", jnp.tanh)
abs = _unary("abs", jnp.abs)
sqrt = _unary("sqrt", jnp.sqrt)
square = _unary("square", jnp.square)
neg = _unary("neg", jnp.negative)


def pow(x, factor, name=None):
    return _as_coo(x)._map(lambda v: jnp.power(v, factor))


def cast(x, index_dtype=None, value_dtype=None, name=None):
    coo = _as_coo(x)
    data = coo._bcoo.data.astype(value_dtype) if value_dtype else coo._bcoo.data
    idx = coo._bcoo.indices.astype(index_dtype) if index_dtype else coo._bcoo.indices
    return SparseCooTensor(jsparse.BCOO((data, idx), shape=coo.shape))


def transpose(x, perm, name=None):
    coo = _as_coo(x)
    idx = coo._bcoo.indices[:, jnp.asarray(perm)]
    shape = tuple(coo.shape[p] for p in perm)
    return SparseCooTensor(jsparse.BCOO((coo._bcoo.data, idx), shape=shape))


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    coo = _as_coo(x)
    out = coo._bcoo.todense().sum(axis=axis, keepdims=keepdim)
    if dtype:
        out = out.astype(dtype)
    return Tensor(out)


# nn sub-namespace: full layer package (Conv3D/SubmConv3D/BatchNorm/ReLU +
# functional.attention) — imported at the END of this module, after every
# name it needs here exists (see bottom)


# ---- unary tail (f(0)=0 family, reference sparse/unary.py) ----
tan = _unary("tan", jnp.tan)
asin = _unary("asin", jnp.arcsin)
atan = _unary("atan", jnp.arctan)
sinh = _unary("sinh", jnp.sinh)
asinh = _unary("asinh", jnp.arcsinh)
atanh = _unary("atanh", jnp.arctanh)
log1p = _unary("log1p", jnp.log1p)
expm1 = _unary("expm1", jnp.expm1)
deg2rad = _unary("deg2rad", jnp.deg2rad)
rad2deg = _unary("rad2deg", jnp.rad2deg)


def isnan(x, name=None):
    """reference sparse/unary.py isnan: same pattern, bool values."""
    coo = _as_coo(x)
    return SparseCooTensor(jsparse.BCOO(
        (jnp.isnan(coo._bcoo.data), coo._bcoo.indices), shape=coo.shape))


def coalesce(x, name=None):
    """reference sparse COO coalesce: merge duplicate coordinates."""
    coo = _as_coo(x)
    return SparseCooTensor(coo._bcoo.sum_duplicates())


def reshape(x, shape, name=None):
    """reference sparse/unary.py reshape: remap flat coordinates."""
    coo = _as_coo(x)._bcoo.sum_duplicates()
    old_shape = np.asarray(coo.shape, np.int64)
    new_shape = list(int(s) for s in shape)
    neg = [i for i, s in enumerate(new_shape) if s == -1]
    total = int(old_shape.prod())
    if neg:
        known = int(np.prod([s for s in new_shape if s != -1]))
        new_shape[neg[0]] = total // known
    strides_old = jnp.asarray(
        np.append(np.cumprod(old_shape[1:][::-1])[::-1], 1), jnp.int64)
    flat = (coo.indices.astype(jnp.int64) * strides_old).sum(-1)
    strides_new = np.append(
        np.cumprod(np.asarray(new_shape[1:], np.int64)[::-1])[::-1], 1)
    new_idx = jnp.stack(
        [(flat // int(s)) % int(d) for s, d in zip(strides_new, new_shape)],
        axis=-1)
    return SparseCooTensor(jsparse.BCOO(
        (coo.data, new_idx.astype(coo.indices.dtype)),
        shape=tuple(new_shape)))


def slice(x, axes, starts, ends, name=None):
    """reference sparse slice: keep entries inside the window and shift
    their coordinates."""
    coo = _as_coo(x)._bcoo.sum_duplicates()
    shape = list(coo.shape)
    idx = coo.indices
    keep = jnp.ones(idx.shape[0], bool)
    shift = np.zeros(len(shape), np.int64)
    for ax, st, en in zip(axes, starts, ends):
        ax = int(ax) % len(shape)
        st = int(st) if st >= 0 else int(st) + shape[ax]
        st = min(max(st, 0), shape[ax])  # clamp into [0, dim] like dense slice
        en = min(int(en) if en >= 0 else int(en) + shape[ax], shape[ax])
        en = max(en, st)
        keep &= (idx[:, ax] >= st) & (idx[:, ax] < en)
        shift[ax] = st
        shape[ax] = en - st
    kept = np.asarray(keep)
    new_idx = np.asarray(idx)[kept] - shift[None, :]
    return SparseCooTensor(jsparse.BCOO(
        (np.asarray(coo.data)[kept], new_idx.astype(np.int32)),
        shape=tuple(shape)))


def mv(x, vec, name=None):
    """reference sparse/matmul.py mv: sparse matrix @ dense vector."""
    coo = _as_coo(x)
    return Tensor(coo._bcoo @ jnp.asarray(_unwrap(vec)))


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    """reference sparse/matmul.py addmm: beta*input + alpha*(x @ y)."""
    xv = x._bcoo if isinstance(x, SparseCooTensor) else jnp.asarray(_unwrap(x))
    yv = jnp.asarray(_unwrap(y))
    prod = xv @ yv
    base = _unwrap(input)
    return Tensor(beta * jnp.asarray(base) + alpha * prod)


def divide(x, y, name=None):
    """Elementwise divide on the intersection pattern (reference
    sparse/binary.py divide; a-entry with no b-match divides by zero, as the
    dense kernel would)."""
    a, b = _as_coo(x)._bcoo.sum_duplicates(), _as_coo(y)._bcoo.sum_duplicates()
    strides = jnp.asarray(
        np.append(np.cumprod(np.asarray(a.shape[1:])[::-1])[::-1], 1)
        if len(a.shape) > 1 else [1], jnp.int64)
    ka = (a.indices.astype(jnp.int64) * strides).sum(-1)
    kb = (b.indices.astype(jnp.int64) * strides).sum(-1)
    order = jnp.argsort(kb)
    kb_sorted = kb[order]
    pos = jnp.clip(jnp.searchsorted(kb_sorted, ka), 0, kb_sorted.shape[0] - 1)
    match = kb_sorted[pos] == ka
    bvals = b.data[order][pos]
    data = a.data / jnp.where(match, bvals, 0)
    return SparseCooTensor(jsparse.BCOO((data, a.indices), shape=a.shape))


def mask_as(x, mask, name=None):
    """reference sparse mask_as: take dense ``x``'s values at ``mask``'s
    sparsity pattern."""
    m = _as_coo(mask)._bcoo.sum_duplicates()
    xv = jnp.asarray(_unwrap(x))
    # values (and dtype) come from x; only the PATTERN comes from mask
    vals = xv[tuple(m.indices[:, i] for i in range(m.indices.shape[1]))]
    return SparseCooTensor(jsparse.BCOO((vals, m.indices), shape=m.shape))


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """reference sparse pca_lowrank: densify and run the randomized PCA
    (sparse input is a storage format here, not a compute path)."""
    from ..ops.linalg import pca_lowrank as _dense_pca

    dense = Tensor(_as_coo(x)._bcoo.todense())
    return _dense_pca(dense, q=q, center=center, niter=niter)


__all__ += ["tan", "asin", "atan", "sinh", "asinh", "atanh", "log1p",
            "expm1", "deg2rad", "rad2deg", "isnan", "coalesce", "reshape",
            "slice", "mv", "addmm", "divide", "mask_as", "pca_lowrank"]

from . import nn  # noqa: E402,F401  (after the names nn's functional needs)

__all__ += ["nn"]
