"""Host contracts: static effect/race analysis of the async host runtime
plus exhaustive protocol verification of the fleet & request state machines.

The device-side passes (lint rules, program cards, kernel contracts) verify
the COMPILED program; since the async host runtime (docs/async_runtime.md)
the remaining correctness risk is host-side Python: ``_host_overlap()``
mutates engine state while the device step is in flight, and the fleet's
health machine / request lifecycle grow transitions with every
fault-tolerance PR.  This pass verifies both statically, on the module AST
— no engine build, no trace, deterministic across runs:

1. **Effect/race analysis of the overlap window.**  For every class that
   defines ``_host_overlap()``, each call site's enclosing step method is
   split at the call line: the *lexical prefix* (the code that built the
   in-flight launch's operands) and the *overlap closure* (everything
   reachable from ``_host_overlap`` through the self-call graph, bounded
   by ``PADDLE_TPU_HOST_VERIFY_DEPTH``).  Any ``self.*`` field read in the
   prefix and written in the overlap closure is a host/device pipeline
   race (``host_race``): the overlap bookkeeping mutates state the launch
   was built from.  Deliberate overlaps (the incremental journal's own
   fields) are carried as reasoned ``allowlist.toml`` entries with a raw
   ``host_contract_violations`` ceiling in ``budgets.toml`` — exactly the
   kernel-contracts shape, so a NEW race moves the budgeted figure even if
   an allowlist entry over-matches.  A blocking device fetch
   (``np.asarray`` / ``.block_until_ready`` / ``device_get``) reachable
   from the window is ``host_blocking``: it would serialize the pipeline
   the window exists to overlap.

2. **Exhaustive protocol verification.**  The replica health machine
   (``fleet.HEALTH_EDGES`` over ``REPLICA_STATES``) and the request
   lifecycle (``serving.REQUEST_EDGES`` over PENDING/RUNNING +
   ``TERMINAL_STATUSES``) are declared transition tables beside the code.
   Every assignment site of the state field — direct literal stores,
   choke-point calls (``_health_to``, ``_terminal`` and any function that
   forwards a status parameter into one), each under its dominating guard
   constraints — must map to a declared edge (``host_transition``
   otherwise), and every declared edge must have at least one site
   (``host_dead_edge`` otherwise).  Mirror stores (``f.status =
   c.status``) are safe by induction and exempt-but-reported.  The
   declared tables themselves are model-checked by enumeration
   (``host_protocol``): terminal states absorbing, every state reachable
   from the initial state, every non-terminal state able to reach a
   terminal, and — for ladder machines — strictly monotone degradation
   with an explicit heal-edge whitelist (HEALTHY->DEGRADED->DRAINING->DEAD
   with only DEGRADED->HEALTHY climbing back).

Findings flow through the ordinary severity/allowlist machinery
(``analyze(host=True)``, run by every serving gate target), land as a
``host_contracts`` section on program cards and in bench rung detail, and
``python -m paddle_tpu.analysis --host`` gates them standalone in CI.
"""

from __future__ import annotations

import ast
import copy as _copy
import dataclasses

from .report import Finding, Severity
from ..utils.envflags import env_int

__all__ = ["check_host_contracts", "host_contracts_summary",
           "host_verify_depth", "MachineSpec", "DEFAULT_HOST_DEPTH"]

#: default call-graph resolution depth (edges followed from the overlap
#: window / choke chain); PADDLE_TPU_HOST_VERIFY_DEPTH overrides, min 1
DEFAULT_HOST_DEPTH = 8


def host_verify_depth() -> int:
    """Validated PADDLE_TPU_HOST_VERIFY_DEPTH (utils/envflags.py): a typo
    or sub-minimum value warns once and keeps the default — a
    misconfigured depth must not silently shrink the effect closure to
    nothing (races hidden) or explode it."""
    return env_int("PADDLE_TPU_HOST_VERIFY_DEPTH", DEFAULT_HOST_DEPTH,
                   minimum=1)


#: container-mutating method names: ``self.x.<name>(...)`` WRITES x (and
#: reads it — the mutation starts from the current value)
_MUTATORS = frozenset({
    "append", "appendleft", "add", "extend", "update", "pop", "popitem",
    "popleft", "clear", "discard", "remove", "insert", "setdefault", "sort",
    "fill"})


def _blocking_label(call: ast.Call) -> str | None:
    """Name a blocking device fetch: np.asarray / numpy.asarray,
    jax.device_get / bare device_get, and any ``.block_until_ready()``.
    (``jnp.asarray`` is a device put — async — and deliberately NOT
    matched.)"""
    fn = call.func
    if isinstance(fn, ast.Attribute):
        if fn.attr == "block_until_ready":
            return ".block_until_ready"
        if isinstance(fn.value, ast.Name):
            base = fn.value.id
            if fn.attr == "asarray" and base in ("np", "numpy"):
                return f"{base}.asarray"
            if fn.attr == "device_get" and base == "jax":
                return "jax.device_get"
    elif isinstance(fn, ast.Name) and fn.id == "device_get":
        return "device_get"
    return None


class _Effects(ast.NodeVisitor):
    """Per-function ``self.*`` read/write sets, self-call + module-call
    names, and blocking-fetch sites."""

    def __init__(self):
        self.reads: set[str] = set()
        self.writes: set[str] = set()
        self.calls: set[str] = set()
        self.blocking: list[tuple[str, int]] = []   # (label, lineno)

    def _self_attr(self, node) -> str | None:
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"):
            return node.attr
        return None

    def visit_Attribute(self, node):
        attr = self._self_attr(node)
        if attr is not None:
            if isinstance(node.ctx, (ast.Store, ast.Del)):
                self.writes.add(attr)
            else:
                self.reads.add(attr)
        self.generic_visit(node)

    def visit_Subscript(self, node):
        # self.x[i] = v / del self.x[i]: a write THROUGH x (x itself read)
        attr = self._self_attr(node.value)
        if attr is not None and isinstance(node.ctx, (ast.Store, ast.Del)):
            self.writes.add(attr)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        t = node.target
        attr = self._self_attr(t)
        if attr is None and isinstance(t, ast.Subscript):
            attr = self._self_attr(t.value)
        if attr is not None:
            self.reads.add(attr)
            self.writes.add(attr)
        self.generic_visit(node)

    def visit_Call(self, node):
        label = _blocking_label(node)
        if label is not None:
            self.blocking.append((label, node.lineno))
        fn = node.func
        if isinstance(fn, ast.Attribute):
            attr = self._self_attr(fn.value)
            if attr is not None:
                # self.x.append(...): mutator call writes x
                if fn.attr in _MUTATORS:
                    self.writes.add(attr)
            elif self._self_attr(fn) is not None:
                self.calls.add(fn.attr)     # self.method(...)
        elif isinstance(fn, ast.Name):
            self.calls.add(fn.id)           # module-level function
        self.generic_visit(node)


def _effects_of(nodes) -> _Effects:
    eff = _Effects()
    for n in nodes:
        eff.visit(n)
    return eff


def _collect_prefix(body, before_line: int, out: list) -> None:
    """The lexical prefix of a method at ``before_line``: every statement
    (recursively, through compound statements) that STARTS before the
    overlap call — the over-approximation of "code that ran before the
    launch returned", operand reads included."""
    for stmt in body:
        if getattr(stmt, "lineno", before_line) >= before_line:
            continue
        if isinstance(stmt, ast.If):
            out.append(stmt.test)
            _collect_prefix(stmt.body, before_line, out)
            _collect_prefix(stmt.orelse, before_line, out)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            out.append(stmt.iter)
            _collect_prefix(stmt.body, before_line, out)
            _collect_prefix(stmt.orelse, before_line, out)
        elif isinstance(stmt, ast.While):
            out.append(stmt.test)
            _collect_prefix(stmt.body, before_line, out)
            _collect_prefix(stmt.orelse, before_line, out)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                out.append(item.context_expr)
            _collect_prefix(stmt.body, before_line, out)
        elif isinstance(stmt, ast.Try):
            _collect_prefix(stmt.body, before_line, out)
            for h in stmt.handlers:
                _collect_prefix(h.body, before_line, out)
            _collect_prefix(stmt.orelse, before_line, out)
            _collect_prefix(stmt.finalbody, before_line, out)
        else:
            out.append(stmt)


@dataclasses.dataclass
class _Module:
    name: str                       # short module name ("serving", "fleet")
    filename: str                   # for finding provenance
    tree: ast.Module = None
    classes: dict = None            # cls name -> {method name -> FunctionDef}
    functions: dict = None          # module-level name -> FunctionDef


def _parse_module(name: str, source: str, filename: str) -> _Module:
    tree = ast.parse(source, filename=filename)
    classes, functions = {}, {}
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            classes[node.name] = {
                n.name: n for n in node.body
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            functions[node.name] = node
    return _Module(name=name, filename=filename, tree=tree,
                   classes=classes, functions=functions)


def _where(mod: _Module, lineno: int, fn: str = "") -> str:
    base = mod.filename.rsplit("/", 1)[-1]
    return f"{base}:{lineno}" + (f" ({fn})" if fn else "")


# ---------------------------------------------------------------------------
# effect/race analysis of the _host_overlap() window
# ---------------------------------------------------------------------------

def _closure(seeds, methods: dict, functions: dict, depth: int):
    """Breadth-first self-call/module-call closure from ``seeds`` (method
    names), following at most ``depth`` call edges.  Returns
    {name: _Effects} for every resolved function in the closure."""
    resolved: dict[str, _Effects] = {}
    frontier = [s for s in seeds]
    for _ in range(depth + 1):
        if not frontier:
            break
        nxt = []
        for name in frontier:
            if name in resolved:
                continue
            node = methods.get(name) or functions.get(name)
            if node is None:
                continue        # stdlib/np/jax call — out of scope
            eff = _effects_of(node.body)
            resolved[name] = eff
            nxt.extend(sorted(eff.calls))
        frontier = nxt
    return resolved


def _check_overlap(mod: _Module, overlap: str, depth: int, raw: list,
                   sections: list) -> None:
    for cls_name in sorted(mod.classes):
        methods = mod.classes[cls_name]
        if overlap not in methods:
            continue
        ov_closure = _closure([overlap], methods, mod.functions, depth)
        ov_writes: set[str] = set()
        writers: dict[str, list] = {}
        ov_blocking: list[tuple[str, str, int]] = []   # (fn, label, lineno)
        for fname in sorted(ov_closure):
            eff = ov_closure[fname]
            for w in eff.writes:
                ov_writes.add(w)
                writers.setdefault(w, []).append(fname)
            for label, lineno in eff.blocking:
                ov_blocking.append((fname, label, lineno))
        ov_blocking.sort(key=lambda b: (b[2], b[0]))

        # one analysis unit per (method containing >= 1 window); both
        # graceful/serial window sites of a step method share one prefix
        # approximation, so findings dedupe on (method, field)
        sites: dict[str, list[int]] = {}
        for mname in sorted(methods):
            if mname == overlap:
                continue
            for node in ast.walk(methods[mname]):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == overlap
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id == "self"):
                    sites.setdefault(mname, []).append(node.lineno)

        blocked_reported: set[tuple[str, int]] = set()
        for mname in sorted(sites):
            lines = sorted(sites[mname])
            prefix_nodes: list = []
            _collect_prefix(methods[mname].body, lines[0], prefix_nodes)
            pre = _effects_of(prefix_nodes)
            pre_reads = set(pre.reads)
            pre_closure = _closure(sorted(pre.calls), methods,
                                   mod.functions, depth)
            for eff in pre_closure.values():
                pre_reads |= eff.reads
            races = sorted(pre_reads & ov_writes)
            n_findings = 0
            for field in races:
                wby = ", ".join(sorted(set(writers[field])))
                raw.append((
                    "host_race", Severity.ERROR,
                    f"host/device pipeline race: self.{field} is read "
                    f"while building {cls_name}.{mname}'s launch and "
                    f"written inside the {overlap}() window (by {wby}) "
                    f"while the device step is in flight — overlap "
                    f"bookkeeping must not touch launch-read state "
                    f"(a deliberate journal overlap needs a reasoned "
                    f"allowlist.toml entry)",
                    _where(mod, lines[0], f"{cls_name}.{mname}")))
                n_findings += 1
            sec_blocking = []
            for fname, label, lineno in ov_blocking:
                sec_blocking.append(f"{label} in {fname} "
                                    f"[{_where(mod, lineno)}]")
                if (fname, lineno) in blocked_reported:
                    continue
                blocked_reported.add((fname, lineno))
                raw.append((
                    "host_blocking", Severity.ERROR,
                    f"blocking device fetch reachable from the "
                    f"{overlap}() window: {label} in {fname} — the window "
                    f"runs while the device step is in flight, so a "
                    f"blocking fetch serializes the host/device pipeline "
                    f"it exists to overlap",
                    _where(mod, lineno, fname)))
                n_findings += 1
            sections.append({
                "kind": "overlap",
                "method": f"{cls_name}.{mname}",
                "where": _where(mod, lines[0]),
                "windows": lines,
                "launch_reads": len(pre_reads),
                "overlap_writes": sorted(ov_writes),
                "races": [{"field": f,
                           "writers": sorted(set(writers[f]))}
                          for f in races],
                "blocking": sec_blocking,
                "findings": n_findings,
            })


# ---------------------------------------------------------------------------
# protocol verification: declared transition tables vs assignment sites
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class MachineSpec:
    """One declared state machine: the states, the transition table
    (declared beside the code it governs), and how its assignment sites
    look in the AST.

    ``kind``: ``"attr"`` — the state lives in ``<obj>.<field>`` (the
    request lifecycle's ``req.status``); ``"self_index"`` — in
    ``self.<field>[<subject>]`` (the fleet's ``self.health[r]``).
    ``default_sources`` are the source states assumed at a site with no
    dominating guard on the state expression (with ``default_reason``
    naming why that assumption is sound).  ``named_sets`` resolves
    ``in <NAME>`` guards (e.g. ``in TERMINAL_STATUSES``).  ``ladder``,
    when set, model-checks strictly monotone degradation with
    ``heal_edges`` the only edges allowed to climb back."""

    name: str
    field: str
    kind: str
    states: tuple
    edges: frozenset
    terminal: frozenset
    initial: str
    default_sources: frozenset
    default_reason: str = ""
    named_sets: dict = dataclasses.field(default_factory=dict)
    ladder: tuple | None = None
    heal_edges: frozenset = frozenset()


def _state_key(node, m: MachineSpec) -> str | None:
    """The guard-matching key of a state READ expression: for attr
    machines the owning object (``req`` in ``req.status``), for
    self_index machines the subject index (``r`` in ``self.health[r]``)."""
    if m.kind == "attr":
        if isinstance(node, ast.Attribute) and node.attr == m.field:
            return ast.dump(node.value)
    else:
        if (isinstance(node, ast.Subscript)
                and isinstance(node.value, ast.Attribute)
                and node.value.attr == m.field
                and isinstance(node.value.value, ast.Name)
                and node.value.value.id == "self"):
            return ast.dump(node.slice)
    return None


def _resolve_states(node, m: MachineSpec) -> frozenset | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return frozenset({node.value})
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        vals = set()
        for e in node.elts:
            if not (isinstance(e, ast.Constant) and isinstance(e.value, str)):
                return None
            vals.add(e.value)
        return frozenset(vals)
    if isinstance(node, ast.Name) and node.id in m.named_sets:
        return frozenset(m.named_sets[node.id])
    return None


def _constraints(test, m: MachineSpec, positive: bool) -> list:
    """Extract (key, allowed-state-set) facts from a guard expression.
    Sound under negation: ``and`` decomposes positively, ``or``
    negatively; anything unrecognized contributes nothing."""
    out = []
    if isinstance(test, ast.BoolOp):
        decomposes = (isinstance(test.op, ast.And) if positive
                      else isinstance(test.op, ast.Or))
        if decomposes:
            for v in test.values:
                out += _constraints(v, m, positive)
        return out
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        return _constraints(test.operand, m, not positive)
    if isinstance(test, ast.Compare) and len(test.ops) == 1:
        key = _state_key(test.left, m)
        if key is None:
            return out
        lits = _resolve_states(test.comparators[0], m)
        if lits is None:
            return out
        op = test.ops[0]
        if isinstance(op, (ast.Eq, ast.In)):
            allowed = set(lits)
        elif isinstance(op, (ast.NotEq, ast.NotIn)):
            allowed = set(m.states) - set(lits)
        else:
            return out
        if not positive:
            allowed = set(m.states) - allowed
        out.append((key, frozenset(allowed)))
    return out


def _always_exits(body) -> bool:
    return bool(body) and isinstance(
        body[-1], (ast.Raise, ast.Return, ast.Continue, ast.Break))


@dataclasses.dataclass
class _Site:
    """One state-transition site: an assignment (or choke call) with its
    resolved destination and guard-narrowed source-state set."""

    mod: str
    where: str
    fn: str
    dest: str | None        # None = mirror
    sources: frozenset
    guarded: bool           # False -> default_sources applied
    mirror: bool = False


def _fn_params(node) -> list[str]:
    a = node.args
    return ([p.arg for p in a.posonlyargs] if hasattr(a, "posonlyargs")
            else []) + [p.arg for p in a.args]


def _match_store(target, m: MachineSpec):
    """Classify an assignment TARGET against the machine's state pattern.
    Returns (kind, key): kind ``"site"`` (per-subject store, key = guard
    key), ``"init"`` (whole-attr store of a self_index machine — initial
    population), or None."""
    if m.kind == "attr":
        if isinstance(target, ast.Attribute) and target.attr == m.field:
            return "site", ast.dump(target.value)
        return None
    if (isinstance(target, ast.Subscript)
            and isinstance(target.value, ast.Attribute)
            and target.value.attr == m.field
            and isinstance(target.value.value, ast.Name)
            and target.value.value.id == "self"):
        return "site", ast.dump(target.slice)
    if (isinstance(target, ast.Attribute) and target.attr == m.field
            and isinstance(target.value, ast.Name)
            and target.value.id == "self"):
        return "init", None
    return None


def _find_chokes(mod: _Module, m: MachineSpec, depth: int) -> dict:
    """Choke-point discovery: functions that store a PARAMETER into the
    machine's state field (``_terminal``'s ``req.status = status``,
    ``_health_to``'s ``self.health[r] = state``), then — to fixpoint,
    depth-bounded — functions that forward one of their own parameters
    into a known choke's state position (``_fail_slot``).  Returns
    {(cls, fn): (state_param, subject_param | None)}."""
    chokes: dict = {}

    def scan_direct(cls, fname, node):
        params = _fn_params(node)
        for n in ast.walk(node):
            targets = []
            if isinstance(n, ast.Assign):
                targets, value = n.targets, n.value
            elif isinstance(n, ast.AnnAssign) and n.value is not None:
                targets, value = [n.target], n.value
            else:
                continue
            for t in targets:
                mt = _match_store(t, m)
                if mt is None or mt[0] != "site":
                    continue
                if isinstance(value, ast.Name) and value.id in params:
                    subject = None
                    if m.kind == "attr":
                        if (isinstance(t.value, ast.Name)
                                and t.value.id in params):
                            subject = t.value.id
                    else:
                        sl = t.slice
                        if isinstance(sl, ast.Name) and sl.id in params:
                            subject = sl.id
                    chokes[(cls, fname)] = (value.id, subject)

    for cls in sorted(mod.classes):
        for fname in sorted(mod.classes[cls]):
            scan_direct(cls, fname, mod.classes[cls][fname])
    for fname in sorted(mod.functions):
        scan_direct(None, fname, mod.functions[fname])

    # forwarding chains: f(..., status, ...) -> choke(status) makes f a
    # choke too; bounded by depth iterations
    for _ in range(depth):
        grew = False
        for cls in sorted(mod.classes):
            for fname in sorted(mod.classes[cls]):
                if (cls, fname) in chokes:
                    continue
                node = mod.classes[cls][fname]
                params = _fn_params(node)
                for call in ast.walk(node):
                    if not isinstance(call, ast.Call):
                        continue
                    ck = _choke_of_call(call, cls, chokes)
                    if ck is None:
                        continue
                    state_arg, subj_arg = _choke_args(call, ck, chokes,
                                                      mod)
                    if (isinstance(state_arg, ast.Name)
                            and state_arg.id in params):
                        subject = (subj_arg.id
                                   if isinstance(subj_arg, ast.Name)
                                   and subj_arg.id in params else None)
                        chokes[(cls, fname)] = (state_arg.id, subject)
                        grew = True
                        break
        if not grew:
            break
    return chokes


def _choke_of_call(call: ast.Call, cls, chokes: dict):
    fn = call.func
    if (isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name)
            and fn.value.id == "self"):
        key = (cls, fn.attr)
        return key if key in chokes else None
    if isinstance(fn, ast.Name):
        key = (None, fn.id)
        return key if key in chokes else None
    return None


def _choke_args(call: ast.Call, choke_key, chokes: dict, mod: _Module):
    """The (state, subject) argument expressions of a call to a choke,
    resolved by the choke's own parameter names/positions."""
    cls, fname = choke_key
    node = (mod.classes[cls][fname] if cls is not None
            else mod.functions[fname])
    params = _fn_params(node)
    state_param, subject_param = chokes[choke_key]
    # methods are called through self: drop the leading 'self' param when
    # mapping positional call args
    offset = 1 if params and params[0] == "self" else 0

    def arg_for(pname):
        if pname is None:
            return None
        idx = params.index(pname) - offset
        if 0 <= idx < len(call.args):
            return call.args[idx]
        for kw in call.keywords:
            if kw.arg == pname:
                return kw.value
        return None

    return arg_for(state_param), arg_for(subject_param)


def _machine_sites(mod: _Module, m: MachineSpec, depth: int, raw: list):
    """Every transition site of machine ``m`` in ``mod``, guard-narrowed.
    Dynamic (unresolvable) stores raise ``host_transition`` findings
    directly into ``raw``."""
    chokes = _find_chokes(mod, m, depth)
    sites: list[_Site] = []
    inits: list[str] = []

    def classify_value(value, params, t):
        """-> ('literal', dest) | ('mirror', None) | ('choke-param', None)
        | ('dynamic', None)"""
        if isinstance(value, ast.Constant) and isinstance(value.value, str):
            return "literal", value.value
        if m.kind == "attr" and isinstance(value, ast.Attribute) \
                and value.attr == m.field:
            return "mirror", None
        if _state_key(value, m) is not None:
            return "mirror", None
        if isinstance(value, ast.Name) and value.id in params:
            return "choke-param", None
        return "dynamic", None

    def scan_fn(cls, fname, node):
        params = _fn_params(node)
        is_choke = (cls, fname) in chokes

        def handle_stmt(stmt, facts):
            for n in ast.walk(stmt):
                targets = []
                if isinstance(n, ast.Assign):
                    targets, value = n.targets, n.value
                elif isinstance(n, ast.AnnAssign) and n.value is not None:
                    targets, value = [n.target], n.value
                elif isinstance(n, ast.Call):
                    ck = _choke_of_call(n, cls, chokes)
                    if ck is None or (cls, fname) == ck:
                        continue
                    state_arg, subj_arg = _choke_args(n, ck, chokes, mod)
                    if state_arg is None:
                        continue
                    if (isinstance(state_arg, ast.Name)
                            and state_arg.id in params and is_choke):
                        continue      # forwarding edge; caller sites gate
                    if not (isinstance(state_arg, ast.Constant)
                            and isinstance(state_arg.value, str)):
                        raw.append((
                            "host_transition", Severity.ERROR,
                            f"[{m.name}] non-literal {m.field} transition "
                            f"passed into choke point "
                            f"{ck[1]}() — every transition site must name "
                            f"its destination state so the declared table "
                            f"can be verified",
                            _where(mod, n.lineno, fname)))
                        continue
                    subj_key = (ast.dump(subj_arg)
                                if subj_arg is not None else None)
                    _emit(n.lineno, state_arg.value, subj_key, facts)
                    continue
                else:
                    continue
                for t in targets:
                    mt = _match_store(t, m)
                    if mt is None:
                        continue
                    if mt[0] == "init":
                        lits = {c.value for c in ast.walk(value)
                                if isinstance(c, ast.Constant)
                                and isinstance(c.value, str)}
                        bad = sorted(lits - {m.initial})
                        if bad:
                            raw.append((
                                "host_protocol", Severity.ERROR,
                                f"[{m.name}] initial population of "
                                f"self.{m.field} uses state(s) {bad} — "
                                f"the machine starts at {m.initial!r}",
                                _where(mod, n.lineno, fname)))
                        inits.append(_where(mod, n.lineno, fname))
                        continue
                    kind, dest = classify_value(value, params, t)
                    if kind == "choke-param" and is_choke:
                        continue      # the choke body itself
                    if kind == "mirror":
                        sites.append(_Site(
                            mod=mod.name,
                            where=_where(mod, n.lineno, fname),
                            fn=fname, dest=None, sources=frozenset(),
                            guarded=False, mirror=True))
                        continue
                    if kind != "literal":
                        raw.append((
                            "host_transition", Severity.ERROR,
                            f"[{m.name}] dynamic {m.field} store (value "
                            f"not a state literal, a mirror of another "
                            f"{m.field}, or a verified choke parameter) — "
                            f"unverifiable against the declared "
                            f"transition table",
                            _where(mod, n.lineno, fname)))
                        continue
                    _emit(n.lineno, dest, mt[1], facts)

        def _emit(lineno, dest, subj_key, facts):
            srcs = set(m.states)
            guarded = False
            if subj_key is not None:
                for key, allowed in facts:
                    if key == subj_key:
                        srcs &= allowed
                        guarded = True
            if not guarded:
                srcs = set(m.default_sources)
            sites.append(_Site(
                mod=mod.name, where=_where(mod, lineno, fname), fn=fname,
                dest=dest, sources=frozenset(srcs), guarded=guarded))

        def walk_body(body, facts):
            facts = list(facts)
            for stmt in body:
                if isinstance(stmt, ast.If):
                    walk_body(stmt.body,
                              facts + _constraints(stmt.test, m, True))
                    walk_body(stmt.orelse,
                              facts + _constraints(stmt.test, m, False))
                    if _always_exits(stmt.body) and not stmt.orelse:
                        facts += _constraints(stmt.test, m, False)
                    continue
                if isinstance(stmt, ast.While):
                    walk_body(stmt.body,
                              facts + _constraints(stmt.test, m, True))
                    walk_body(stmt.orelse, facts)
                    continue
                if isinstance(stmt, (ast.For, ast.AsyncFor)):
                    walk_body(stmt.body, facts)
                    walk_body(stmt.orelse, facts)
                    continue
                if isinstance(stmt, (ast.With, ast.AsyncWith)):
                    walk_body(stmt.body, facts)
                    continue
                if isinstance(stmt, ast.Try):
                    walk_body(stmt.body, facts)
                    for h in stmt.handlers:
                        walk_body(h.body, facts)
                    walk_body(stmt.orelse, facts)
                    walk_body(stmt.finalbody, facts)
                    continue
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                    continue
                handle_stmt(stmt, facts)

        walk_body(node.body, [])

    for cls in sorted(mod.classes):
        for fname in sorted(mod.classes[cls]):
            scan_fn(cls, fname, mod.classes[cls][fname])
    for fname in sorted(mod.functions):
        scan_fn(None, fname, mod.functions[fname])

    # class-body field declarations (dataclass defaults) pin the initial
    # state: Request.status = "PENDING"
    for node in mod.tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        for stmt in node.body:
            if (isinstance(stmt, ast.AnnAssign)
                    and isinstance(stmt.target, ast.Name)
                    and stmt.target.id == m.field
                    and isinstance(stmt.value, ast.Constant)
                    and isinstance(stmt.value.value, str)):
                if stmt.value.value != m.initial:
                    raw.append((
                        "host_protocol", Severity.ERROR,
                        f"[{m.name}] {node.name}.{m.field} defaults to "
                        f"{stmt.value.value!r} — the machine starts at "
                        f"{m.initial!r}",
                        _where(mod, stmt.lineno, node.name)))
                inits.append(_where(mod, stmt.lineno, node.name))
    return sites, inits


def _model_check(m: MachineSpec) -> list[str]:
    """Enumerate the DECLARED table's invariants (no code involved)."""
    errs = []
    states = set(m.states)
    for s, d in sorted(m.edges):
        if s not in states or d not in states:
            errs.append(f"edge {s}->{d} names an unknown state "
                        f"(states: {sorted(states)})")
        if s in m.terminal:
            errs.append(f"terminal state {s} has outgoing edge {s}->{d} "
                        f"— terminal states are absorbing")
        if s == d:
            errs.append(f"self-loop {s}->{d} declared — self-transitions "
                        f"are implicit no-ops, not edges")
    # reachability from the initial state
    reach, frontier = {m.initial}, [m.initial]
    while frontier:
        s = frontier.pop()
        for a, b in m.edges:
            if a == s and b not in reach:
                reach.add(b)
                frontier.append(b)
    for s in sorted(states - reach):
        errs.append(f"state {s} is unreachable from {m.initial}")
    # every non-terminal state must be able to reach a terminal state
    if m.terminal:
        ok = set(m.terminal)
        grew = True
        while grew:
            grew = False
            for a, b in m.edges:
                if b in ok and a not in ok:
                    ok.add(a)
                    grew = True
        for s in sorted(states - ok):
            errs.append(f"state {s} cannot reach any terminal state "
                        f"({sorted(m.terminal)})")
    # degradation ladder: strictly monotone down, heals whitelisted
    if m.ladder is not None:
        rank = {s: i for i, s in enumerate(m.ladder)}
        for s, d in sorted(m.edges):
            if s in rank and d in rank and rank[d] <= rank[s] \
                    and (s, d) not in m.heal_edges:
                errs.append(
                    f"edge {s}->{d} climbs the degradation ladder "
                    f"{'->'.join(m.ladder)} without being a declared "
                    f"heal edge ({sorted(m.heal_edges) or 'none'})")
    return errs


def _check_machines(mods: list, machines, depth: int, raw: list,
                    sections: list) -> None:
    for m in machines:
        all_sites: list[_Site] = []
        inits: list[str] = []
        for mod in mods:
            s, i = _machine_sites(mod, m, depth, raw)
            all_sites += s
            inits += i
        covered: set = set()
        undeclared: list[str] = []
        for site in all_sites:
            if site.mirror:
                continue
            if site.dest not in m.states:
                raw.append((
                    "host_transition", Severity.ERROR,
                    f"[{m.name}] transition to unknown state "
                    f"{site.dest!r} (states: {sorted(m.states)})",
                    site.where))
                continue
            for src in sorted(site.sources):
                if src == site.dest:
                    continue    # self-transition: choke no-op, not an edge
                if (src, site.dest) in m.edges:
                    covered.add((src, site.dest))
                else:
                    undeclared.append(f"{src}->{site.dest} @ {site.where}")
                    raw.append((
                        "host_transition", Severity.ERROR,
                        f"[{m.name}] undeclared transition "
                        f"{src}->{site.dest}: the site "
                        f"{'is guarded to' if site.guarded else 'defaults to'} "
                        f"source state(s) {sorted(site.sources)} but the "
                        f"declared table has no {src}->{site.dest} edge — "
                        f"declare it (and re-model-check) or guard the "
                        f"site",
                        site.where))
        dead = sorted(m.edges - covered)
        for s, d in dead:
            raw.append((
                "host_dead_edge", Severity.ERROR,
                f"[{m.name}] declared edge {s}->{d} has no assignment "
                f"site in the code — a transition the table promises but "
                f"nothing performs; delete the edge or restore the site",
                f"{m.name}"))
        protocol = _model_check(m)
        for msg in protocol:
            raw.append(("host_protocol", Severity.ERROR,
                        f"[{m.name}] {msg}", m.name))
        n_sites = sum(1 for s in all_sites if not s.mirror)
        n_mirror = sum(1 for s in all_sites if s.mirror)
        sections.append({
            "kind": "machine",
            "machine": m.name,
            "states": list(m.states),
            "declared_edges": sorted(f"{s}->{d}" for s, d in m.edges),
            "sites": n_sites,
            "mirror_sites": n_mirror,
            "init_sites": sorted(inits),
            "covered_edges": sorted(f"{s}->{d}" for s, d in covered),
            "dead_edges": [f"{s}->{d}" for s, d in dead],
            "undeclared": sorted(undeclared),
            "protocol": protocol,
            "default_sources": sorted(m.default_sources),
            "findings": len(undeclared) + len(dead) + len(protocol),
        })


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------

def _default_modules() -> list:
    from ..inference import fleet, serving

    out = []
    for mod in (serving, fleet):
        with open(mod.__file__) as f:
            src = f.read()
        out.append((mod.__name__.rsplit(".", 1)[-1], src, mod.__file__))
    return out


def _default_machines() -> tuple:
    from ..inference.fleet import HEALTH_EDGES, REPLICA_STATES
    from ..inference.serving import REQUEST_EDGES, TERMINAL_STATUSES

    request = MachineSpec(
        name="request_lifecycle", field="status", kind="attr",
        states=("PENDING", "RUNNING") + tuple(sorted(TERMINAL_STATUSES)),
        edges=frozenset(REQUEST_EDGES),
        terminal=frozenset(TERMINAL_STATUSES), initial="PENDING",
        default_sources=frozenset({"PENDING", "RUNNING"}),
        default_reason="engine/fleet registries hold only live requests — "
                       "_terminal/_finish pop the rid at the terminal "
                       "transition, so an unguarded site can only see "
                       "PENDING or RUNNING",
        named_sets={"TERMINAL_STATUSES": frozenset(TERMINAL_STATUSES)})
    health = MachineSpec(
        name="replica_health", field="health", kind="self_index",
        states=tuple(REPLICA_STATES), edges=frozenset(HEALTH_EDGES),
        terminal=frozenset({"DEAD"}), initial="HEALTHY",
        default_sources=frozenset(REPLICA_STATES),
        default_reason="every health write funnels through the _health_to "
                       "choke, which no-ops self-transitions; unguarded "
                       "callers (_kill) legitimately fire from any state",
        named_sets={"REPLICA_STATES": frozenset(REPLICA_STATES)},
        ladder=tuple(REPLICA_STATES),
        heal_edges=frozenset({("DEGRADED", "HEALTHY")}))
    return (request, health)


#: memoized default-module verification, keyed by depth — the pass is pure
#: AST over fixed sources, so every serving gate target shares one run
_CACHE: dict = {}


def _verify(modules, machines, overlap: str, depth: int):
    mods = [_parse_module(n, s, f) for (n, s, f) in modules]
    raw: list = []
    sections: list = []
    for mod in mods:
        _check_overlap(mod, overlap, depth, raw, sections)
    _check_machines(mods, machines, depth, raw, sections)
    return raw, sections


def check_host_contracts(target: str = "", *, modules=None, machines=None,
                         overlap: str = "_host_overlap",
                         depth: int | None = None):
    """Run the host-contract pass.  Returns ``(findings, sections)`` —
    the same shape as :func:`check_kernel_contracts`: typed findings for
    the severity/allowlist machinery plus per-unit section dicts for
    program cards / bench detail / ``--json``.

    ``modules`` (``[(name, source, filename), ...]``) and ``machines``
    (:class:`MachineSpec` s) default to the shipped engine + fleet and
    their declared tables; tests inject fixtures through them.  ``depth``
    bounds call-graph resolution (default:
    :func:`host_verify_depth`).  Pure AST — deterministic across runs and
    cheap enough to run per gate target (the default configuration is
    memoized)."""
    if depth is None:
        depth = host_verify_depth()
    if modules is None and machines is None:
        hit = _CACHE.get(depth)
        if hit is None:
            hit = _verify(_default_modules(), _default_machines(),
                          overlap, depth)
            _CACHE[depth] = hit
        raw, sections = hit
    else:
        raw, sections = _verify(
            modules if modules is not None else _default_modules(),
            machines if machines is not None else _default_machines(),
            overlap, depth)
    findings = [Finding(rule=r, severity=sev, message=msg, where=where,
                        target=target)
                for (r, sev, msg, where) in raw]
    return findings, _copy.deepcopy(sections)


def host_contracts_summary(sections) -> dict:
    """Aggregate host-contract verdicts for card summaries / bench
    detail.  ``violations`` counts RAW findings (pre-allowlist) — the
    figure ``budgets.toml`` ceilings as ``host_contract_violations``."""
    out = {"windows": 0, "methods": 0, "machines": 0, "sites": 0,
           "races": 0, "blocking": 0, "undeclared_transitions": 0,
           "dead_edges": 0, "protocol": 0, "violations": 0}
    for s in sections or ():
        if s.get("kind") == "overlap":
            out["methods"] += 1
            out["windows"] += len(s.get("windows", ()))
            out["races"] += len(s.get("races", ()))
            out["blocking"] += len(set(s.get("blocking", ())))
        elif s.get("kind") == "machine":
            out["machines"] += 1
            out["sites"] += s.get("sites", 0)
            out["undeclared_transitions"] += len(s.get("undeclared", ()))
            out["dead_edges"] += len(s.get("dead_edges", ()))
            out["protocol"] += len(s.get("protocol", ()))
        out["violations"] += s.get("findings", 0)
    return out
