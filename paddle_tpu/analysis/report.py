"""Findings, reports, and the allowlist for the jaxpr-level TPU lint.

A :class:`Finding` is one typed diagnostic (rule, severity, message, eqn
provenance).  A :class:`Report` is the result of one ``analyze()`` run:
findings partitioned into active vs allowlisted, renderable for the CLI and
queryable from tests/CI (``tools/lint_gate.py`` exits nonzero on any active
finding at or above ``warning``).

The allowlist (``analysis/allowlist.toml``) records *accepted* findings with a
one-line justification — the linter's equivalent of a lint-ignore pragma, but
centralized so every suppression is visible and reviewed in one file.  Python
3.10 has no ``tomllib``, so a minimal TOML-subset reader lives here (array of
``[[allow]]`` tables with string values — exactly what the allowlist uses).
"""

from __future__ import annotations

import dataclasses
import os
import re

__all__ = ["Severity", "Finding", "Report", "AllowRule", "load_allowlist",
           "DEFAULT_ALLOWLIST"]

# severity order for gating: info findings are advisory and never fail the
# lint gate; warning/error do unless allowlisted
_SEV_ORDER = {"info": 0, "warning": 1, "error": 2}

DEFAULT_ALLOWLIST = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 "allowlist.toml")


class Severity:
    INFO = "info"
    WARNING = "warning"
    ERROR = "error"


@dataclasses.dataclass
class Finding:
    """One typed lint finding.

    ``rule``: dtype_upcast | donation | recompile | host_sync | resharding |
    engine_audit | program_card | budget | kernel_bounds | kernel_race |
    kernel_lost_write | kernel_alias | kernel_registry (the last five:
    kernel_contracts.py).  ``where`` is eqn provenance
    (``file.py:line (fn)``) when the jaxpr carries source info, else a
    structural path (``params/layers/wq``).
    """

    rule: str
    severity: str
    message: str
    where: str = ""
    target: str = ""

    def key(self) -> str:
        return f"{self.rule}:{self.target}:{self.where}:{self.message}"

    def render(self) -> str:
        loc = f" [{self.where}]" if self.where else ""
        return f"{self.severity.upper():7s} {self.rule}: {self.message}{loc}"


@dataclasses.dataclass
class AllowRule:
    """One ``[[allow]]`` entry: rule + optional target + substring match."""

    rule: str = "*"
    target: str = "*"
    match: str = ""
    reason: str = ""

    def covers(self, f: Finding) -> bool:
        if self.rule not in ("*", f.rule):
            return False
        if self.target not in ("*", "", f.target):
            return False
        return (not self.match or self.match in f.where
                or self.match in f.message)


def _parse_mini_toml(text: str, header: str = "allow") -> list[dict]:
    """Parse the allowlist/budgets TOML subset: ``[[<header>]]``
    array-of-tables with ``key = "string"`` or ``key = <int>`` pairs and
    ``#`` comments.  Anything else is a loud error — a silently ignored
    allowlist line would un-suppress findings (and a silently ignored
    budget line would un-gate a ceiling)."""
    entries: list[dict] = []
    current: dict | None = None
    for ln, raw in enumerate(text.splitlines(), 1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if line == f"[[{header}]]":
            current = {}
            entries.append(current)
            continue
        m = re.match(r'^([A-Za-z_][A-Za-z0-9_]*)\s*=\s*'
                     r'(?:"((?:[^"\\]|\\.)*)"|(-?\d+))'
                     r'\s*(?:#.*)?$', line)
        if m is None or current is None:
            raise ValueError(
                f"{header} table parse error at line {ln}: {raw!r} "
                f'(expected [[{header}]], key = "value", or key = <int>)')
        current[m.group(1)] = (int(m.group(3)) if m.group(3) is not None
                               else re.sub(r'\\(["\\])', r"\1", m.group(2)))
    return entries


def load_allowlist(path: str | None = None) -> list[AllowRule]:
    """Load allow rules; a missing default file is an empty allowlist, a
    missing *explicit* path is an error (a typoed --allowlist must not
    silently allow nothing)."""
    explicit = path is not None
    path = path or DEFAULT_ALLOWLIST
    if not os.path.exists(path):
        if explicit:
            raise FileNotFoundError(f"allowlist file not found: {path}")
        return []
    with open(path) as f:
        entries = _parse_mini_toml(f.read())
    rules = []
    for i, e in enumerate(entries):
        unknown = set(e) - {"rule", "target", "match", "reason"}
        if unknown:
            raise ValueError(f"allowlist entry {i}: unknown keys {unknown}")
        bad = {k for k, v in e.items() if not isinstance(v, str)}
        if bad:
            raise ValueError(f"allowlist entry {i}: non-string value(s) for "
                             f"{sorted(bad)} (budgets live in budgets.toml)")
        if not e.get("reason"):
            raise ValueError(
                f"allowlist entry {i} ({e}): every suppression needs a "
                f"one-line reason")
        rules.append(AllowRule(**e))
    return rules


class Report:
    """Result of one ``analyze()`` run over one target."""

    def __init__(self, target: str, findings: list[Finding],
                 allowlist: list[AllowRule] | None = None,
                 n_traces: int | None = None):
        self.target = target
        self.n_traces = n_traces  # distinct trace signatures seen (churn rule)
        self.card = None          # ProgramCard when analyze(card=True)
        #: wall seconds of the analyze() pass; the number of rule/card
        #: consumers that REUSED its one baseline trace; and the number
        #: of jaxpr traces ACTUALLY performed (a live counter on the
        #: trace closure — expected 2: the baseline plus the recompile
        #: rule's deliberate determinism re-trace; any growth means a
        #: rule started re-tracing).  Surfaced by
        #: ``python -m paddle_tpu.analysis --json`` so CI logs show the
        #: gate stayed single-trace/single-compile per target.
        self.seconds: float | None = None
        self.trace_reuse: int | None = None
        self.traces_performed: int | None = None
        self.findings: list[Finding] = []       # active (not allowlisted)
        self.allowlisted: list[tuple[Finding, AllowRule]] = []
        for f in findings:
            rule = next((a for a in (allowlist or []) if a.covers(f)), None)
            if rule is None:
                self.findings.append(f)
            else:
                self.allowlisted.append((f, rule))

    @property
    def ok(self) -> bool:
        """True when no active finding gates (info is advisory)."""
        return not self.gating()

    def gating(self) -> list[Finding]:
        return [f for f in self.findings
                if _SEV_ORDER[f.severity] >= _SEV_ORDER["warning"]]

    def by_rule(self, rule: str) -> list[Finding]:
        return [f for f in self.findings if f.rule == rule]

    def render(self, verbose: bool = False) -> str:
        lines = [f"== {self.target}: {len(self.findings)} finding(s), "
                 f"{len(self.allowlisted)} allowlisted =="]
        for f in self.findings:
            lines.append("  " + f.render())
        if verbose:
            for f, a in self.allowlisted:
                lines.append(f"  ALLOWED {f.render().strip()}  "
                             f"(reason: {a.reason})")
        return "\n".join(lines)
