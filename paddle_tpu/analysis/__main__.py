"""CLI: ``JAX_PLATFORMS=cpu python -m paddle_tpu.analysis --target <name>``.

Traces the named target (or ``--all``) and prints the findings; exit status
0 = clean or fully allowlisted, 1 = gating findings, making the module
directly usable as a pre-submit check.  ``tools/lint_gate.py`` is the CI
wrapper over the same registry.

``--cards`` switches to the program-card mode (cost_model.py): derive each
selected target's static ProgramCard and gate it against the checked-in
``analysis/budgets.toml`` ceilings (exit 1 on any over-budget field,
missing entry, stale entry, or over-VMEM-cap launch);
``--cards --update-budgets`` instead rewrites the budget file at the
measured values (preserving existing reasons) and exits 0 — the documented
workflow for a PR that legitimately moves a figure.  ``--json`` emits
machine-readable findings/cards on stdout in either mode (lint mode
additionally carries per-target ``seconds`` and the ``trace_reuse`` count
— the number of rule/card consumers sharing each target's ONE trace, the
CI evidence the gate is single-compile per target); exit codes are
unchanged.

``--host`` switches to the host-contracts mode (host_contracts.py): no
target builds, no tracing — just the AST effect/race analysis of the
serving engine's ``_host_overlap()`` windows and the exhaustive protocol
verification of the fleet health machine and request lifecycle, gated
through the same allowlist (exit 1 on any non-allowlisted finding).
This is the CI entry point ISSUE 18 names: ``python -m
paddle_tpu.analysis --host`` must stay green over engine + fleet.
"""

from __future__ import annotations

import argparse
import os
import sys
import time


def main(argv=None) -> int:
    # analysis is pure tracing: never let the CLI grab a TPU (or fail when
    # the relay is down).  Effective only when the backend is not yet
    # initialized — the canonical invocation sets JAX_PLATFORMS=cpu anyway.
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # multi-device targets (serving_tp_step) need a host mesh: force the
    # virtual CPU device count like tests/conftest.py.  XLA_FLAGS is read
    # at BACKEND init, not jax import (running as ``-m`` already imported
    # the package, hence jax), so setting it here still works; it is
    # harmless if the backend is somehow already up — the target then
    # reports a build failure instead of tracing the wrong mesh.
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8")
    import jax

    try:
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    except Exception:
        pass  # backend already up; proceed on whatever it is

    from . import load_allowlist
    from .targets import GATE_TARGETS, TARGETS
    from .targets import run as run_target
    from .targets import run_card

    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.analysis",
        description="jaxpr-level TPU lint over registered paddle_tpu targets")
    p.add_argument("--target", action="append", default=[],
                   help=f"target(s) to lint; registered: {sorted(TARGETS)}")
    p.add_argument("--all", action="store_true",
                   help="lint every gate target")
    p.add_argument("--list", action="store_true",
                   help="list registered targets and exit")
    p.add_argument("--allowlist", default=None,
                   help="allowlist TOML (default: packaged allowlist.toml)")
    p.add_argument("--no-allowlist", action="store_true",
                   help="show findings the allowlist would suppress")
    p.add_argument("--cards", action="store_true",
                   help="program-card mode: derive static cost/memory cards "
                        "and gate them against budgets.toml")
    p.add_argument("--host", action="store_true",
                   help="host-contracts mode: AST effect/race analysis of "
                        "the async host runtime + state-machine protocol "
                        "verification (no tracing)")
    p.add_argument("--update-budgets", action="store_true",
                   help="with --cards: rewrite budgets.toml at the measured "
                        "values (reasons preserved) instead of gating")
    p.add_argument("--budgets", default=None,
                   help="budgets TOML (default: packaged budgets.toml)")
    p.add_argument("--json", action="store_true",
                   help="emit machine-readable findings/cards on stdout "
                        "(exit codes unchanged)")
    p.add_argument("-v", "--verbose", action="store_true",
                   help="also print allowlisted findings with reasons")
    args = p.parse_args(argv)

    if args.list:
        for name in sorted(TARGETS):
            gate = " [gate]" if name in GATE_TARGETS else ""
            print(f"{name}{gate}")
        return 0
    if args.update_budgets and not args.cards:
        p.error("--update-budgets requires --cards")
    if args.host:
        if args.cards or args.target or args.all:
            p.error("--host is a standalone mode (module-scoped, not "
                    "per-target); drop --cards/--target/--all")
        return _host_main(args)
    names = list(args.target) or (
        list(GATE_TARGETS) if (args.all or args.cards) else [])
    if not names:
        p.error("pass --target <name> (repeatable), --all, --host, "
                "or --list")

    if args.cards:
        return _cards_main(args, names, run_card, TARGETS)

    allowlist = [] if args.no_allowlist else load_allowlist(args.allowlist)
    rc = 0
    reports = []
    seconds = []
    for name in names:
        # per-target wall time INCLUDING the target build (the analyze
        # pass alone is report.seconds) — with trace_reuse in the JSON so
        # CI logs show each target stayed single-trace: N rule/card
        # consumers sharing the one ClosedJaxpr, not N traces
        t0 = time.perf_counter()
        report = run_target(name, allowlist=allowlist)
        seconds.append(time.perf_counter() - t0)
        reports.append(report)
        if not args.json:
            print(report.render(verbose=args.verbose))
        if not report.ok:
            rc = 1
    if args.json:
        import dataclasses
        import json

        print(json.dumps({"reports": [
            {"target": r.target, "ok": r.ok, "n_traces": r.n_traces,
             "seconds": round(secs, 3),
             "analyze_seconds": (round(r.seconds, 3)
                                 if r.seconds is not None else None),
             "trace_reuse": r.trace_reuse,
             "traces_performed": r.traces_performed,
             "findings": [dataclasses.asdict(f) for f in r.findings],
             "allowlisted": [{**dataclasses.asdict(f), "reason": a.reason}
                             for f, a in r.allowlisted]}
            for r, secs in zip(reports, seconds)]}, indent=2))
    if rc and not args.json:
        print("\nlint FAILED: fix the findings above or allowlist them in "
              "paddle_tpu/analysis/allowlist.toml with a reason",
              file=sys.stderr)
    return rc


def _host_main(args) -> int:
    """--host: the standalone host-contracts gate (host_contracts.py) —
    pure AST over the shipped engine + fleet sources and their declared
    transition tables, gated through the same allowlist as every lint
    rule.  Prints the per-window / per-machine sections (or --json with
    the raw section dicts) and exits 1 on any non-allowlisted finding."""
    from . import Report, load_allowlist
    from .host_contracts import check_host_contracts, host_contracts_summary

    allowlist = [] if args.no_allowlist else load_allowlist(args.allowlist)
    t0 = time.perf_counter()
    findings, sections = check_host_contracts(target="host")
    secs = time.perf_counter() - t0
    report = Report("host", findings, allowlist=allowlist)
    summary = host_contracts_summary(sections)
    if args.json:
        import dataclasses
        import json

        print(json.dumps(
            {"host_contracts": summary, "sections": sections,
             "seconds": round(secs, 3), "ok": report.ok,
             "findings": [dataclasses.asdict(f) for f in report.findings],
             "allowlisted": [{**dataclasses.asdict(f), "reason": a.reason}
                             for f, a in report.allowlisted]}, indent=2))
    else:
        print(f"-- host contracts: {summary['methods']} overlap method(s) "
              f"/ {summary['windows']} window(s), {summary['machines']} "
              f"state machine(s) / {summary['sites']} transition site(s); "
              f"{summary['races']} race(s), {summary['blocking']} blocking "
              f"fetch(es), {summary['undeclared_transitions']} undeclared "
              f"transition(s), {summary['dead_edges']} dead edge(s), "
              f"{summary['protocol']} protocol finding(s) --")
        for s in sections:
            if s.get("kind") == "overlap":
                print(f"   overlap {s['method']} "
                      f"windows={s['windows']} "
                      f"races={[r['field'] for r in s['races']]} "
                      f"blocking={len(s['blocking'])} [{s['where']}]")
            else:
                print(f"   machine {s['machine']} sites={s['sites']} "
                      f"edges {len(s['covered_edges'])}/"
                      f"{len(s['declared_edges'])} covered "
                      f"dead={s['dead_edges']} "
                      f"undeclared={len(s['undeclared'])} "
                      f"protocol={len(s['protocol'])}")
        print(report.render(verbose=args.verbose))
        if not report.ok:
            print("\nhost-contract gate FAILED: fix the race/transition "
                  "or allowlist it in paddle_tpu/analysis/allowlist.toml "
                  "with a reason", file=sys.stderr)
    return 0 if report.ok else 1


def _cards_main(args, names, run_card, TARGETS) -> int:
    """--cards: derive the selected targets' ProgramCards, then either
    rewrite budgets.toml (--update-budgets) or gate against it.  The stale
    check (budget entries naming no registered target) needs only the
    registry, so it runs regardless of which targets were selected.
    Gating policy lives in ONE place — ``cost_model.gate_cards`` — shared
    with ``tools/lint_gate.py --cards-only``; ``-v`` additionally prints
    the card findings the allowlist suppressed, with their reasons, like
    the lint mode."""
    from . import Report, load_allowlist
    from .cost_model import (card_findings, gate_cards, load_budgets,
                             update_budgets_file)

    card_seconds = {}
    cards = {}
    for name in names:
        t0 = time.perf_counter()
        cards[name] = run_card(name)
        card_seconds[name] = round(time.perf_counter() - t0, 3)
    if args.update_budgets:
        # registered=TARGETS: entries for targets NOT selected this run are
        # kept verbatim (a partial --target update must not delete the
        # rest); only unregistered (stale) entries retire
        path = update_budgets_file(cards, args.budgets, registered=TARGETS)
        print(f"wrote {len(cards)} budget entr"
              f"{'y' if len(cards) == 1 else 'ies'} to {path}")
        return 0
    allowlist = [] if args.no_allowlist else load_allowlist(args.allowlist)
    findings = gate_cards(cards, load_budgets(args.budgets),
                          allowlist=allowlist, registered=TARGETS)
    gating = [f for f in findings if f.severity != "info"]
    if args.json:
        import dataclasses
        import json

        print(json.dumps(
            {"cards": {n: c.summary() for n, c in cards.items()},
             "seconds": card_seconds,
             "findings": [dataclasses.asdict(f) for f in findings],
             "ok": not gating}, indent=2))
    else:
        for name in sorted(cards):
            print(cards[name].render())
            if args.verbose:
                rep = Report(name, card_findings(cards[name]),
                             allowlist=allowlist)
                for f, a in rep.allowlisted:
                    print(f"   ALLOWED {f.render().strip()}  "
                          f"(reason: {a.reason})")
        for f in findings:
            print(f.render() + (f"  <{f.target}>" if f.target else ""))
        if gating:
            print("\ncard gate FAILED: fix the regression or re-run "
                  "--cards --update-budgets and justify the new ceilings "
                  "in paddle_tpu/analysis/budgets.toml", file=sys.stderr)
    return 1 if gating else 0


if __name__ == "__main__":
    sys.exit(main())
