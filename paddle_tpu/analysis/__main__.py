"""CLI: ``JAX_PLATFORMS=cpu python -m paddle_tpu.analysis --target <name>``.

Traces the named target (or ``--all``) and prints the findings; exit status
0 = clean or fully allowlisted, 1 = gating findings, making the module
directly usable as a pre-submit check.  ``tools/lint_gate.py`` is the CI
wrapper over the same registry.
"""

from __future__ import annotations

import argparse
import os
import sys


def main(argv=None) -> int:
    # analysis is pure tracing: never let the CLI grab a TPU (or fail when
    # the relay is down).  Effective only when the backend is not yet
    # initialized — the canonical invocation sets JAX_PLATFORMS=cpu anyway.
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # multi-device targets (serving_tp_step) need a host mesh: force the
    # virtual CPU device count like tests/conftest.py.  XLA_FLAGS is read
    # at BACKEND init, not jax import (running as ``-m`` already imported
    # the package, hence jax), so setting it here still works; it is
    # harmless if the backend is somehow already up — the target then
    # reports a build failure instead of tracing the wrong mesh.
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8")
    import jax

    try:
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    except Exception:
        pass  # backend already up; proceed on whatever it is

    from . import load_allowlist
    from .targets import GATE_TARGETS, TARGETS
    from .targets import run as run_target

    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.analysis",
        description="jaxpr-level TPU lint over registered paddle_tpu targets")
    p.add_argument("--target", action="append", default=[],
                   help=f"target(s) to lint; registered: {sorted(TARGETS)}")
    p.add_argument("--all", action="store_true",
                   help="lint every gate target")
    p.add_argument("--list", action="store_true",
                   help="list registered targets and exit")
    p.add_argument("--allowlist", default=None,
                   help="allowlist TOML (default: packaged allowlist.toml)")
    p.add_argument("--no-allowlist", action="store_true",
                   help="show findings the allowlist would suppress")
    p.add_argument("-v", "--verbose", action="store_true",
                   help="also print allowlisted findings with reasons")
    args = p.parse_args(argv)

    if args.list:
        for name in sorted(TARGETS):
            gate = " [gate]" if name in GATE_TARGETS else ""
            print(f"{name}{gate}")
        return 0
    names = list(args.target) or (list(GATE_TARGETS) if args.all else [])
    if not names:
        p.error("pass --target <name> (repeatable), --all, or --list")

    allowlist = [] if args.no_allowlist else load_allowlist(args.allowlist)
    rc = 0
    for name in names:
        report = run_target(name, allowlist=allowlist)
        print(report.render(verbose=args.verbose))
        if not report.ok:
            rc = 1
    if rc:
        print("\nlint FAILED: fix the findings above or allowlist them in "
              "paddle_tpu/analysis/allowlist.toml with a reason",
              file=sys.stderr)
    return rc


if __name__ == "__main__":
    sys.exit(main())
