"""Serving-engine invariant auditor (``PADDLE_TPU_ENGINE_AUDIT=1``).

The paged continuous-batching engine + prefix cache maintain a handful of
host-side invariants the whole memory model rests on.  A single bookkeeping
bug (double-freed page, leaked refcount, COW miss) silently corrupts KV bytes
for *other* requests — the worst failure class in a multi-tenant server,
detectable only by cross-checking the allocator, the block tables, and the
cache against each other.  With the env var set, the engine calls
:func:`audit_engine` after admission and after every decode chunk; a
violation raises :class:`EngineAuditError` naming the invariant.

Invariants (paged mode):

I1  page partition — every pool page is in exactly one of {free list, a
    slot's private blocks, the prefix cache}; no duplicates, total == pool.
    Under tensor parallelism (docs/tp_serving.md) the device pools must
    shard ONLY the kv_heads axis — the page axis stays whole per shard, so
    this host-side partition is exact on every shard (one allocator,
    tp-many replicas of its accounting).  Under the fused decode step
    (docs/paged_attention.md) the device pool carries exactly ONE spill
    page past the allocator's range — dropped writes' trash can, never
    handed out, never accounted — and none otherwise.
I2  block-table rows — row[i] mirrors [shared pages..., private pages...] in
    order; every remaining entry is the unallocated sentinel.
I3  refcounts — each cached block's refcount equals the number of slot
    mappings over it; the cache's O(1) zero-ref counter matches a scan.
I4  COW — no cache-resident page is simultaneously a slot's *private*
    (writable) block: the engine never writes a shared page.
I5  chain shape — a slot's shared list is a parent-linked hash chain rooted
    at None; each cached block's ``children`` count matches a scan.
I6  position bounds — active slots have 0 <= pos <= max_seq, the KV-write
    high-water mark ``_written`` satisfies pos <= written <= max_seq (a
    speculative verify step appends up to K+1 tokens, then rolls pos back
    past rejected drafts — pos may trail written, never lead it), and the
    mapped blocks cover every written position including rejected drafts'
    (multi-token append must have allocated pages before the device wrote).
I7  chunked-prefill progress (engines with ``enable_chunked_prefill``) — a
    prefilling slot holds a seated request, its ``prefilled`` cursor stays
    within [0, prompt_len], the slot's mapped pages cover every prefilled
    position (a chunk must never have scattered K/V into unallocated
    pages), and no slot was packed as BOTH a decode lane and a prefill lane
    in the same mixed step (the unified launch's two roles are disjoint by
    construction — an overlap means the scheduler double-advanced a slot).
I8  terminal ownership (docs/fault_tolerance.md) — a request in a terminal
    status (FINISHED/FAILED/REJECTED/CANCELLED/EXPIRED) owns zero pages and
    zero cache refs: it is neither seated on a slot nor waiting in the
    queue (pages and refs are slot-keyed, so "not seated" + I1's exact pool
    partition IS the zero-ownership proof); conversely every seated request
    is RUNNING and every queued request is PENDING.  The fault paths
    (_fail_slot, expiry, cancel) release before they mark terminal — a
    violation means a failed request's pages leaked or a zombie is still
    being scheduled.

I10 hierarchical-KV tier (docs/kv_tier.md; engines with a host tier
    attached) — every cached block is in exactly one of {HBM pool, host
    tier, dead}: demotion MOVES a block D2H (the victim leaves the prefix
    cache as its page ships) and re-admission moves it back, so a
    **private** tier never holds a hash that is simultaneously resident
    in the engine's prefix cache (a **shared** fleet tier deliberately
    relaxes this to per-replica accounting: replica A's demoted copy may
    coexist with replica B's HBM-resident one — byte-identical by the
    content-address contract — so the exclusivity clause is skipped and
    the remaining clauses carry the invariant).  Tier accounting must
    close exactly: every entry is keyed by its own hash, byte usage sums
    to ``used_bytes`` within the budget, pins are non-negative, and every
    hash in a slot's pending match-to-restore plan is still tier-resident
    (pins protect the match-to-restore window; only a ``tier_drop``
    injection may break it, and that seam drops the plan atomically).
    "Dead" is the explicit third state: a block in neither structure —
    the tier refused it (budget) or LRU-dropped it — which is exactly the
    pre-tier eviction, never an accounting hole.

I9  fleet ownership (docs/fleet_serving.md; :func:`audit_fleet`, run by the
    FleetRouter after every fleet step) — every LIVE fleet rid is owned by
    exactly one replica: the owner is alive (not DEAD) and holds a
    replica-local copy; a hedge-pending rid counts as the primary's until
    first-writer-wins resolves, and its only extra copy lives on the
    recorded hedge target; no replica engine serves a rid the router does
    not route to it (a copy on a third replica is double ownership — the
    fleet would bank one stream twice); terminal fleet requests appear in
    no routing registry.

Dense (non-paged) engines only get I6's bounds check and I8 — there is no
allocator to corrupt.  The audit is O(pool + slots·blocks) pure-host work per step:
cheap next to a device step, but nonzero, hence opt-in (a debug validator,
not a production default).
"""

from __future__ import annotations

from ..utils.envflags import env_bool

__all__ = ["EngineAuditError", "audit_engine", "audit_fleet",
           "audit_tier", "audit_enabled"]


class EngineAuditError(AssertionError):
    """A serving-engine invariant does not hold (engine state is corrupt)."""


def audit_enabled() -> bool:
    """Parse ``PADDLE_TPU_ENGINE_AUDIT`` (validated: '', '0', '1'; anything
    else warns and falls back to off — see utils/envflags.py)."""
    return env_bool("PADDLE_TPU_ENGINE_AUDIT", False)


def _fail(invariant: str, detail: str):
    raise EngineAuditError(f"engine audit {invariant} violated: {detail}")


def audit_engine(eng) -> None:
    """Cross-check a ContinuousBatchingEngine's host state; raises
    :class:`EngineAuditError` on the first violated invariant."""
    B = eng.max_batch
    # I6 first — it applies to dense and paged alike
    for s in range(B):
        if eng._slot_req[s] is None:
            continue
        pos = int(eng._pos[s])
        if not 0 <= pos <= eng.max_seq:
            _fail("I6", f"slot {s} pos {pos} outside [0, {eng.max_seq}]")
        w = int(eng._written[s])
        if w > eng.max_seq:
            _fail("I6", f"slot {s} written high-water {w} beyond "
                        f"max_seq {eng.max_seq}")
        if pos > w:
            _fail("I6", f"slot {s} pos {pos} ahead of written high-water "
                        f"{w}: speculative rollback may trail the device's "
                        f"writes but pos must never pass them")

    # I8: terminal ownership — dense and paged alike (the journal and the
    # queue are host structures both engine shapes share)
    from ..inference.serving import TERMINAL_STATUSES

    seated = {id(r) for r in eng._slot_req if r is not None}
    queued = {id(r) for r in eng._queue}
    for req in getattr(eng, "_reqs", {}).values():
        if req.status in TERMINAL_STATUSES:
            if id(req) in seated:
                _fail("I8", f"rid {req.rid} is {req.status} (terminal) but "
                            f"still seated on a slot: its pages were never "
                            f"released")
            if id(req) in queued:
                _fail("I8", f"rid {req.rid} is {req.status} (terminal) but "
                            f"still waiting in the queue (zombie: it would "
                            f"be re-admitted)")
    for s in range(B):
        req = eng._slot_req[s]
        if req is not None and req.status != "RUNNING":
            _fail("I8", f"slot {s} seats rid {req.rid} with status "
                        f"{req.status} (seated requests must be RUNNING)")
    for req in eng._queue:
        if req.status != "PENDING":
            _fail("I8", f"queued rid {req.rid} has status {req.status} "
                        f"(queued requests must be PENDING)")
    if not getattr(eng, "paged", False):
        return

    nb = eng.num_blocks
    free = list(eng._free)
    cache = eng._pcache
    cached_pages = cache.resident_pages() if cache is not None else []
    private = [p for s in range(B) for p in eng._slot_blocks[s]]

    # I1: exact partition of the pool
    if len(free) != len(set(free)):
        _fail("I1", f"duplicate pages in the free list: {sorted(free)}")
    if len(private) != len(set(private)):
        _fail("I1", f"page owned by two slots: {sorted(private)}")
    if len(cached_pages) != len(set(cached_pages)):
        _fail("I1", f"page cached twice: {sorted(cached_pages)}")
    everything = sorted(free + private + cached_pages)
    if everything != sorted(set(everything)):
        seen, dup = set(), set()
        for p in free + private + cached_pages:
            (dup if p in seen else seen).add(p)
        _fail("I1", f"pages in two owners at once: {sorted(dup)} "
                    f"(free/slot/cache overlap)")
    if everything != list(range(nb)):
        missing = sorted(set(range(nb)) - set(everything))
        extra = sorted(set(everything) - set(range(nb)))
        _fail("I1", f"pool accounting does not close: missing={missing} "
                    f"out-of-range={extra}")
    # I1 under the fused decode step (docs/paged_attention.md "Fused decode
    # step"): the device pool carries exactly one SPILL page past the
    # allocator's range iff fused mode is on.  The spill page is dropped
    # writes' trash can — it must exist when the fused kernel targets it
    # (a missing page means dropped writes corrupt page num_blocks - 1) and
    # must NOT exist otherwise (a stray page means the pool layout drifted
    # from the compiled programs').  The partition above already proves the
    # allocator never hands it out (everything == range(num_blocks)).
    # quantized pools (kv_quant engines) are {"q": codes, "scale": ...}
    # pytrees: geometry and sharding checks read the code leaf (the scale
    # leaf shares the page axis and shards the same kv_heads axis 2)
    def _pool_leaves(pool):
        if isinstance(pool, dict):
            return [("q", pool["q"]), ("scale", pool["scale"])]
        return [("", pool)]

    pool_k = eng.cache_k["q"] if isinstance(eng.cache_k, dict) \
        else eng.cache_k
    phys = int(pool_k.shape[1])
    want = nb + (1 if getattr(eng, "_fused", False) else 0)
    if phys != want:
        _fail("I1", f"device pool has {phys} physical pages, expected "
                    f"{want} (num_blocks={nb}, fused decode "
                    f"{'on' if getattr(eng, '_fused', False) else 'off'})")
    if getattr(eng, "tp", 1) > 1:
        # I1 under tensor parallelism (docs/tp_serving.md): the host
        # partition above is only exact PER SHARD if the device pool
        # shards kv_heads alone — a spec that touched the page axis would
        # give shards different page capacities and the single host
        # allocator would silently misaccount every one of them.
        for nm, pool in (("cache_k", eng.cache_k), ("cache_v", eng.cache_v)):
            for leaf_nm, leaf in _pool_leaves(pool):
                spec = tuple(getattr(leaf.sharding, "spec", ()) or ())
                axes = spec + (None,) * (leaf.ndim - len(spec))
                kv_ax = axes[2]
                if kv_ax not in ("tp", ("tp",)):
                    _fail("I1", f"TP pool {nm}{'.' + leaf_nm if leaf_nm else ''} "
                                f"does not shard kv_heads: spec={spec}")
                if any(a is not None for i, a in enumerate(axes) if i != 2):
                    _fail("I1", f"TP pool {nm}{'.' + leaf_nm if leaf_nm else ''} "
                                f"shards a non-kv_heads axis (per-shard "
                                f"page accounting breaks): spec={spec}")

    # I4: cached pages are read-only — never simultaneously private
    leaked = set(cached_pages) & set(private)
    if leaked:
        _fail("I4", f"cache-resident pages mapped writable: {sorted(leaked)}")

    by_hash = cache._by_hash if cache is not None else {}

    # I2: table rows mirror shared+private, sentinel elsewhere
    for s in range(B):
        shared = eng._slot_shared[s]
        owned = eng._slot_blocks[s]
        row = eng._table[s]
        expect = [by_hash[h].page if h in by_hash else None for h in shared] \
            + list(owned)
        if len(expect) > eng.max_blocks:
            # must precede the row[i] loop: an over-appended allocator list
            # would otherwise surface as a bare IndexError, not the named
            # invariant
            _fail("I2", f"slot {s} maps {len(expect)} blocks but the table "
                        f"row holds max_blocks={eng.max_blocks}")
        for i, want in enumerate(expect):
            if want is None:
                _fail("I2", f"slot {s} maps evicted cached block "
                            f"{shared[i][:8]}")
            if int(row[i]) != want:
                _fail("I2", f"slot {s} table[{i}]={int(row[i])} but "
                            f"allocator says page {want}")
        for i in range(len(expect), eng.max_blocks):
            if int(row[i]) != nb:
                _fail("I2", f"slot {s} table[{i}]={int(row[i])} past the "
                            f"mapped blocks (sentinel {nb} expected)")
        # I6 continued: mapped blocks must cover every written position —
        # including a speculative verify step's rejected drafts (the device
        # wrote their K/V before the rollback), hence the _written
        # high-water mark rather than pos
        if eng._slot_req[s] is not None and expect:
            covered = len(expect) * eng.block_size
            pos = min(int(eng._pos[s]), eng.max_seq)
            hw = min(int(eng._written[s]), eng.max_seq)
            if pos > covered:
                _fail("I6", f"slot {s} pos {pos} beyond mapped pages "
                            f"({covered} positions)")
            if hw > covered:
                _fail("I6", f"slot {s} written high-water {hw} beyond "
                            f"mapped pages ({covered} positions): "
                            f"multi-token append outran its allocation")

    # I7: chunked-prefill progress (only when the feature is live)
    if getattr(eng, "_chunked", False):
        for s in range(B):
            ids = eng._prefill_ids[s]
            if ids is None:
                continue
            if eng._slot_req[s] is None:
                _fail("I7", f"slot {s} is mid-prefill with no request "
                            f"seated")
            cur = int(eng._prefilled[s])
            if not 0 <= cur <= ids.size:
                _fail("I7", f"slot {s} prefill cursor {cur} outside "
                            f"[0, {ids.size}] (prompt length)")
            covered = (len(eng._slot_shared[s])
                       + len(eng._slot_blocks[s])) * eng.block_size
            if cur > covered:
                _fail("I7", f"slot {s} prefilled {cur} positions but its "
                            f"mapped pages cover only {covered}: a chunk "
                            f"scattered K/V into unallocated pages")
        dec, pre = getattr(eng, "_last_pack", ((), ()))
        overlap = set(dec) & set(pre)
        if overlap:
            _fail("I7", f"slot(s) {sorted(overlap)} packed as BOTH decode "
                        f"and prefill in one mixed step")

    if cache is None:
        return

    # I3: refcount == slot mappings; O(1) zero-ref counter == scan
    mapped: dict[str, int] = {}
    for s in range(B):
        for h in eng._slot_shared[s]:
            mapped[h] = mapped.get(h, 0) + 1
    for h, e in by_hash.items():
        if e.refcount != mapped.get(h, 0):
            _fail("I3", f"block {h[:8]} refcount={e.refcount} but "
                        f"{mapped.get(h, 0)} slot(s) map it")
    for h in mapped:
        if h not in by_hash:
            _fail("I3", f"slot maps block {h[:8]} that is not resident")
    n_zero = sum(1 for e in by_hash.values() if e.refcount == 0)
    if cache._n_zero_ref != n_zero:
        _fail("I3", f"zero-ref counter {cache._n_zero_ref} != scan {n_zero}")

    # I5: chain shape — parent links + children counts
    kids: dict[str, int] = {}
    for e in by_hash.values():
        if e.parent is not None:
            kids[e.parent] = kids.get(e.parent, 0) + 1
    for h, e in by_hash.items():
        if e.children != kids.get(h, 0):
            _fail("I5", f"block {h[:8]} children={e.children} but scan "
                        f"finds {kids.get(h, 0)}")
    for s in range(B):
        parent = None
        for h in eng._slot_shared[s]:
            e = by_hash.get(h)
            if e is None:
                _fail("I5", f"slot {s} chain references evicted {h[:8]}")
            if e.parent != parent:
                _fail("I5", f"slot {s} shared chain broken at {h[:8]}: "
                            f"parent {str(e.parent)[:8]} != previous "
                            f"{str(parent)[:8]}")
            parent = h

    # I10: hierarchical-KV tier (docs/kv_tier.md) — block in exactly one
    # of {HBM pool, host tier, dead}
    tier = getattr(eng, "_tier", None)
    if tier is not None:
        audit_tier(tier)
        if not tier.shared:
            # private tier: strict move semantics — demotion removes the
            # hash from the prefix cache as its page ships D2H, and
            # re-admission removes the tier entry as the page comes back.
            # (A fleet-shared tier relaxes this: another replica's
            # demotion may coexist with this replica's HBM residency.)
            both = set(by_hash) & set(tier._by_hash)
            if both:
                _fail("I10", f"block(s) {sorted(h[:8] for h in both)} "
                             f"resident in BOTH the HBM prefix cache and "
                             f"the private host tier — demote/re-admit "
                             f"must MOVE a block, never fork it")
        for s in range(B):
            plan = getattr(eng, "_tier_plan", None)
            if plan is None:
                break
            for b, h, _p in plan[s]:
                if eng._slot_req[s] is None:
                    _fail("I10", f"slot {s} holds a tier-restore plan "
                                 f"with no request seated (plan leak: "
                                 f"its pins would starve the tier LRU)")
                if h not in tier._by_hash and h not in by_hash:
                    _fail("I10", f"slot {s} plans to restore block "
                                 f"{h[:8]} which is resident in neither "
                                 f"the tier nor the HBM cache (the pin "
                                 f"window broke: only a tier_drop "
                                 f"injection may discard a pinned entry, "
                                 f"and that seam drops the plan "
                                 f"atomically)")


def audit_tier(tier) -> None:
    """I10's tier-internal half (docs/kv_tier.md): cross-check a
    :class:`~paddle_tpu.inference.kv_tier.HostKVTier`'s byte accounting
    and entry bookkeeping.  Every entry must be keyed by its own hash,
    entry bytes must sum exactly to ``used_bytes`` within the budget, and
    pins must be non-negative — a mismatch means demote/re-admit/evict
    bookkeeping corrupted the store (the failure class that silently
    serves one prompt's KV bytes to another).  Raises
    :class:`EngineAuditError` on the first violation."""
    total = 0
    for h, e in tier._by_hash.items():
        if e.hash != h:
            _fail("I10", f"tier entry keyed {h[:8]} carries hash "
                         f"{e.hash[:8]} (content address forged: ship_in "
                         f"would restore the wrong bytes)")
        if e.pins < 0:
            _fail("I10", f"tier entry {h[:8]} has negative pin count "
                         f"{e.pins} (unbalanced pin/unpin)")
        if e.nbytes <= 0:
            _fail("I10", f"tier entry {h[:8]} accounts {e.nbytes} bytes "
                         f"(empty payload)")
        total += e.nbytes
    if total != tier.used_bytes:
        _fail("I10", f"tier byte accounting does not close: entries sum "
                     f"to {total} but used_bytes={tier.used_bytes}")
    if tier.used_bytes > tier.budget_bytes:
        _fail("I10", f"tier over budget: used_bytes={tier.used_bytes} > "
                     f"budget_bytes={tier.budget_bytes} (eviction must "
                     f"run BEFORE insert, never after)")


def audit_fleet(router) -> None:
    """I9 — fleet single-ownership (docs/fleet_serving.md): cross-check a
    FleetRouter's routing registries against its replicas' live request
    journals.  Every live fleet rid is owned by EXACTLY one replica (a
    hedge-pending rid counts as the primary's until first-writer-wins
    resolves — the hedge target is the one sanctioned extra copy), owners
    are alive and actually hold the work, and no replica serves a rid the
    router does not route to it.  Raises :class:`EngineAuditError` on the
    first violation.  Note: this checks the ROUTER's invariants only —
    each replica engine audits its own I1–I8 via :func:`audit_engine`."""
    from ..inference.serving import TERMINAL_STATUSES

    for rid, req in router._reqs.items():
        if req.status in TERMINAL_STATUSES:
            _fail("I9", f"rid {rid} is {req.status} (terminal) but still "
                        f"in the fleet's live registry (zombie: it would "
                        f"keep an owner and copies)")
        owner = router._owner.get(rid)
        if owner is None:
            _fail("I9", f"live rid {rid} has no owning replica (orphaned: "
                        f"no one will ever step it)")
        if router.replicas[owner] is None or router.health[owner] == "DEAD":
            _fail("I9", f"live rid {rid} is owned by DEAD replica {owner}")
        copies = router._copies.get(rid, {})
        if owner not in copies:
            _fail("I9", f"live rid {rid}'s owner (replica {owner}) holds "
                        f"no copy of it")
        hedge = router._hedge.get(rid)
        if hedge == owner:
            _fail("I9", f"rid {rid} hedged onto its own owner (replica "
                        f"{owner}): first-writer-wins could never resolve")
        sanctioned = {owner} | ({hedge} if hedge is not None else set())
        extra = set(copies) - sanctioned
        if extra:
            _fail("I9", f"rid {rid} has copies on replica(s) "
                        f"{sorted(extra)} beyond owner {owner}"
                        + (f" and hedge {hedge}" if hedge is not None
                           else "")
                        + " — double ownership banks one stream twice")
    for rid in router._owner:
        if rid not in router._reqs:
            _fail("I9", f"owner-map entry for rid {rid} which is not a "
                        f"live fleet request")
    for rid in router._hedge:
        if rid not in router._reqs:
            _fail("I9", f"hedge-map entry for rid {rid} which is not a "
                        f"live fleet request")
    for rid, copies in router._copies.items():
        if rid not in router._reqs:
            # each leaked copy pins a Request (full prompt+output token
            # lists) for the router's lifetime — the retention class the
            # engine's rid-journal pruning fixed
            _fail("I9", f"replica-local copies (on replica(s) "
                        f"{sorted(copies)}) registered for rid {rid} "
                        f"which is not a live fleet request")
    for r, eng in enumerate(router.replicas):
        if eng is None:
            continue
        for rid in eng._reqs:
            if rid < 0:
                continue        # warmup rids (bench convention) are unrouted
            if rid not in router._reqs:
                _fail("I9", f"replica {r} serves rid {rid} unknown to the "
                            f"router (a cancelled/failed-over copy was "
                            f"never released)")
            if r not in router._copies.get(rid, {}):
                _fail("I9", f"replica {r} serves rid {rid} but the router "
                            f"records no copy there (untracked ownership)")
