"""Program cards: static cost & memory analysis over traced programs.

The serving stack's performance contract — launches per decode step, peak
live HBM, per-step collective bytes, VMEM fit of every Pallas launch,
compiled trace-family count — was until now enforced only dynamically
(``decode_step_launches()`` counts at runtime, bench rungs notice drift
rounds later).  This module derives all of it from the ClosedJaxpr the lint
rules already trace (zero device time, ``JAX_PLATFORMS=cpu``) and gates it
against checked-in per-target ceilings (``analysis/budgets.toml``), the
same contract the allowlist gives lint findings: every ceiling carries a
REQUIRED one-line reason, and a PR that reintroduces a scatter on the
fused decode path, doubles a step's trace families, or silently grows
peak HBM fails ``tools/lint_gate.py`` with a card diff instead of a bench
regression three rounds later (PAPERS.md: MPK makes launch count, and the
Gemma-on-TPU serving paper makes HBM residency, the quantities that decide
decode latency and cache capacity).

Card fields
-----------
``peak_hbm_bytes``          liveness pass over eqn def/use ranges: inputs
                            are caller-held for the whole step, donated
                            inputs credit their matching output (the
                            aliased buffer is not double-counted — same
                            for pallas ``input_output_aliases``), and
                            sub-jaxpr bodies (scan/pjit/remat/shard_map)
                            contribute their own internal peak at the eqn
                            that runs them.
``eqns / pallas_calls / scatters``
                            the launch census (:func:`eqn_census`): a
                            ``pallas_call`` is ONE launch however large
                            its body — the same walk
                            ``serving.decode_step_launches()`` reports at
                            runtime (a parity test pins the two together).
``collective_bytes``        per-step bytes crossing the mesh, summed from
                            the post-SPMD HLO with the resharding rule's
                            attribution (all-gather/all-to-all/all-reduce);
                            0 on single-device programs, None when the
                            compile is unavailable.
``vmem_bytes_per_launch``   max per-``pallas_call`` VMEM estimate (block
                            shapes x dtype + scratch operands) vs a
                            per-generation cap (:data:`VMEM_CAPS`,
                            ``PADDLE_TPU_VMEM_CAP_MIB`` override) —
                            over-cap is a gating finding.
``trace_families``          distinct jit cache signatures under the
                            recompile rule's equivalence perturbations
                            (``rules.signature_families``).
``kernel_contracts``        per-``pallas_call`` contract verdicts from the
                            kernel-contract verifier (kernel_contracts.py:
                            index-map bounds, output write races, alias
                            safety) on the same trace; the aggregate
                            ``kernel_contract_violations`` count is a
                            budgeted field — the reviewed set of
                            deliberate violations is a ceiling, so an
                            unsound new kernel fails the card gate too.
"""

from __future__ import annotations

import dataclasses
import os

import numpy as np

from .report import Finding, Severity, _parse_mini_toml

__all__ = ["ProgramCard", "BudgetEntry", "VMEM_CAPS", "BUDGET_FIELDS",
           "DEFAULT_BUDGETS", "eqn_census", "peak_live_hbm",
           "vmem_estimates", "vmem_cap_bytes", "collective_bytes_from_hlo",
           "build_card", "card_findings", "load_budgets", "check_budgets",
           "gate_cards", "render_budgets", "update_budgets_file"]

DEFAULT_BUDGETS = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "budgets.toml")

#: per-generation VMEM capacity a single Pallas launch must fit in
#: (bytes/core; the pallas guide's figure — v6e doubles it).  The fit
#: estimate is block residency only; the pipeline's double buffering and
#: compiler temporaries eat into the same budget, so a launch NEAR the cap
#: deserves scrutiny even when it passes.
VMEM_CAPS = {"v4": 16 << 20, "v5e": 16 << 20, "v5p": 16 << 20,
             "v6e": 32 << 20}

#: card fields a budgets.toml entry may (and --update-budgets does) ceiling.
#: ``eqns`` is deliberately NOT budgeted by default — it drifts with any
#: innocuous refactor; the census still reports it on the card.
#: ``kernel_contract_violations`` counts the RAW kernel-contract findings
#: (kernel_contracts.py) before the allowlist: the ceiling pins the
#: reviewed set of deliberate violations (0 for most targets; the fused
#: decode step's allowlisted in-place append overlap for the flash
#: target), so a NEW unsound kernel moves the figure even if someone
#: over-broadens an allowlist entry.
#: ``host_contract_violations`` is the host-side analog
#: (host_contracts.py): raw pre-allowlist count of _host_overlap() races,
#: blocking fetches, and state-machine protocol findings — nonzero only
#: for serving targets, where it pins the reviewed journal-overlap set.
BUDGET_FIELDS = ("peak_hbm_bytes", "pallas_calls", "scatters",
                 "collective_bytes", "vmem_bytes_per_launch",
                 "trace_families", "kernel_contract_violations",
                 "host_contract_violations")
_CEILING_KEYS = BUDGET_FIELDS + ("eqns",)


def _as_jaxpr(closed):
    return closed.jaxpr if hasattr(closed, "jaxpr") else closed


# ---------------------------------------------------------------------------
# launch census (shared with serving.decode_step_launches)
# ---------------------------------------------------------------------------

def eqn_census(closed) -> dict:
    """Count equations and launch-shaped primitives: every ``pallas_call``
    (ONE launch however large its body — in-kernel eqns are not dispatches,
    so the walk does not descend into it) and every scatter (the KV-append
    pattern).  Descends scan/pjit/remat/cond/shard_map bodies.  This is THE
    census — ``serving.decode_step_launches()`` calls it on the decode
    program, the static ProgramCard calls it on every registered target,
    and a parity test asserts the two agree."""
    from .rules import _sub_jaxprs

    counts = {"eqns": 0, "pallas_calls": 0, "scatters": 0}

    def walk(jx):
        counts["eqns"] += len(jx.eqns)
        for e in jx.eqns:
            nm = e.primitive.name
            if nm == "pallas_call":
                counts["pallas_calls"] += 1
                continue
            if nm.startswith("scatter"):
                counts["scatters"] += 1
            for sub in _sub_jaxprs(e):
                walk(sub)

    walk(_as_jaxpr(closed))
    return counts


# ---------------------------------------------------------------------------
# peak live HBM (liveness over eqn def/use ranges)
# ---------------------------------------------------------------------------

def _var_bytes(v) -> int:
    a = getattr(v, "aval", None)
    shape = getattr(a, "shape", None)
    dtype = getattr(a, "dtype", None)
    if shape is None or dtype is None:
        return 0
    try:
        return int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
    except Exception:
        return 0


def _shape_sig(v):
    a = getattr(v, "aval", None)
    if a is None or not hasattr(a, "shape"):
        return None
    return (tuple(a.shape), str(a.dtype))


def _pallas_aliased_outvars(eqn) -> set:
    """Outvars a ``pallas_call`` writes in place over an input buffer
    (``input_output_aliases``) — the fused decode step's pool output lives
    here; its bytes are the input's, not a second allocation."""
    out = set()
    for pair in eqn.params.get("input_output_aliases") or ():
        try:
            _, o_idx = pair
            if 0 <= o_idx < len(eqn.outvars):
                out.add(eqn.outvars[o_idx])
        except Exception:
            continue
    return out


def _liveness_peak(jaxpr, boundary_counted: bool,
                   donated=(), _depth: int = 0) -> int:
    """Peak live bytes across the jaxpr's eqn timeline.

    ``boundary_counted=True`` (the top level): invars/constvars are
    caller-held HBM for the whole step; donated invars credit one matching
    (shape, dtype) output as aliased (size 0) — XLA reuses the donated
    buffer, so input and output never both cost.  ``False`` (sub-jaxpr
    bodies): boundary values are the caller's operands, already counted at
    the eqn that runs the body; only the body's OWN intermediates add, and
    the result rides on top of the caller's live set at that eqn
    (scan/pjit/remat/shard_map working sets).  ``pallas_call`` bodies never
    count — their refs are VMEM, not HBM."""
    from jax._src.core import Literal

    from .rules import _sub_jaxprs

    if _depth > 32:  # defensive: pathological nesting
        return 0
    n = len(jaxpr.eqns)
    size: dict = {}
    defat: dict = {}
    last: dict = {}

    aliased: set = set()
    real_outs = [v for v in jaxpr.outvars if not isinstance(v, Literal)]
    if boundary_counted and donated:
        claimed: set = set()
        for i, v in enumerate(jaxpr.invars):
            if i < len(donated) and donated[i]:
                sig = _shape_sig(v)
                for ov in real_outs:
                    if ov not in claimed and _shape_sig(ov) == sig:
                        claimed.add(ov)
                        break
        aliased |= claimed

    for v in list(jaxpr.invars) + list(jaxpr.constvars):
        size[v] = _var_bytes(v) if boundary_counted else 0
        defat[v] = 0
        last[v] = n if boundary_counted else last.get(v, 0)

    inner_extra = [0] * (n + 1)
    for k, eqn in enumerate(jaxpr.eqns):
        for v in eqn.invars:
            if not isinstance(v, Literal) and v in defat:
                last[v] = max(last[v], k)
        pal_alias = (_pallas_aliased_outvars(eqn)
                     if eqn.primitive.name == "pallas_call" else set())
        for ov in eqn.outvars:
            defat[ov] = k
            last[ov] = k
            size[ov] = 0 if (ov in pal_alias or ov in aliased) \
                else _var_bytes(ov)
        if eqn.primitive.name != "pallas_call":
            subs = _sub_jaxprs(eqn)
            if subs:
                inner_extra[k] = max(
                    _liveness_peak(s, False, _depth=_depth + 1)
                    for s in subs)
    for ov in real_outs:
        if ov in last:
            last[ov] = n  # outputs survive the step

    delta = [0] * (n + 2)
    for v, sz in size.items():
        if not sz:
            continue
        d, u = defat[v], max(last[v], defat[v])
        delta[d] += sz
        delta[u + 1] -= sz
    peak = cur = 0
    for k in range(n + 1):
        cur += delta[k]
        peak = max(peak, cur + (inner_extra[k] if k < n else 0))
    return peak


def peak_live_hbm(closed, donated=None) -> int:
    """Peak live HBM estimate (bytes) of one execution of the traced
    program.  ``donated`` overrides the donation flags read off the pjit
    eqn (a plain traced callable has none)."""
    from .rules import _unwrap_pjit

    inner, don = _unwrap_pjit(closed)
    if donated is None:
        donated = don or ()
    return _liveness_peak(_as_jaxpr(inner), True, donated=tuple(donated))


# ---------------------------------------------------------------------------
# per-pallas-call VMEM fit
# ---------------------------------------------------------------------------

def vmem_cap_bytes(generation: str = "v4") -> int:
    """The VMEM ceiling a single launch is gated against: the
    per-generation figure (:data:`VMEM_CAPS`; default the v4 16 MiB floor,
    the conservative bound every current generation satisfies), overridden
    by ``PADDLE_TPU_VMEM_CAP_MIB`` (validated integer, utils/envflags.py)."""
    from ..utils.envflags import env_int

    cap_mib = VMEM_CAPS.get(generation, VMEM_CAPS["v4"]) >> 20
    return env_int("PADDLE_TPU_VMEM_CAP_MIB", cap_mib, minimum=1) << 20


def _pallas_vmem(eqn) -> dict:
    """Block shapes x dtype + scratch operands of one ``pallas_call`` —
    the VMEM residency its grid steps pin (double buffering and compiler
    temporaries ride on top; the cap leaves that headroom)."""
    from .rules import _where

    gm = eqn.params.get("grid_mapping")
    name = ""
    nsi = eqn.params.get("name_and_src_info")
    if nsi is not None:
        name = getattr(nsi, "name", "") or str(nsi)
    block_bytes = 0
    for bm in getattr(gm, "block_mappings", ()) or ():
        shape = tuple(int(d) if isinstance(d, int) else 1
                      for d in (bm.block_shape or ()))
        try:
            itemsize = bm.array_shape_dtype.dtype.itemsize
        except Exception:
            itemsize = 4
        block_bytes += int(np.prod(shape, dtype=np.int64)) * itemsize
    scratch_bytes = 0
    n_scratch = int(getattr(gm, "num_scratch_operands", 0) or 0)
    if n_scratch:
        kjx = _as_jaxpr(eqn.params.get("jaxpr"))
        if kjx is not None and len(kjx.invars) >= n_scratch:
            scratch_bytes = sum(_var_bytes(v)
                                for v in kjx.invars[-n_scratch:])
    return {"kernel": name, "where": _where(eqn),
            "grid": tuple(getattr(gm, "grid", ()) or ()),
            "block_bytes": block_bytes, "scratch_bytes": scratch_bytes,
            "vmem_bytes": block_bytes + scratch_bytes}


def vmem_estimates(closed) -> list[dict]:
    """One VMEM-fit estimate per ``pallas_call`` anywhere in the program
    (descending scan/pjit/remat/shard_map bodies, in program order — the
    shared :func:`rules.iter_pallas_eqns` walk)."""
    from .rules import iter_pallas_eqns

    return [_pallas_vmem(e) for e in iter_pallas_eqns(closed)]


# ---------------------------------------------------------------------------
# collective bytes (resharding rule's HLO attribution, summed)
# ---------------------------------------------------------------------------

def collective_bytes_from_hlo(hlo: str) -> int:
    """Total bytes per step crossing the mesh: every all-gather /
    all-to-all / all-reduce in the post-SPMD HLO, matched exactly like the
    resharding rule (incl. the combiner's tuple-result form), with NO size
    floor — a budget sums the design's deliberate boundaries (the TP
    engine's two psums per layer) so any NEW collective, however small,
    moves the figure."""
    from .rules import (_HLO_OP_RE, _HLO_TUPLE_OP_RE, _SHAPE_RE,
                        _shape_bytes)

    total = 0
    for line in hlo.splitlines():
        m = _HLO_OP_RE.search(line)
        if m is not None:
            total += _shape_bytes(m.group(1), m.group(2))
            continue
        mt = _HLO_TUPLE_OP_RE.search(line)
        if mt is not None:
            total += sum(_shape_bytes(d, s)
                         for d, s in _SHAPE_RE.findall(mt.group(1)))
    return total


# ---------------------------------------------------------------------------
# the card
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class ProgramCard:
    """Static cost/memory card of one compiled program (one gate target)."""

    target: str
    peak_hbm_bytes: int
    eqns: int
    pallas_calls: int
    scatters: int
    collective_bytes: int | None      # None = multi-device, compile failed
    vmem_bytes_per_launch: int        # max across pallas_calls (0 = none)
    vmem_cap_bytes: int
    trace_families: int | None        # None = no example args to perturb
    vmem: list = dataclasses.field(default_factory=list)  # per-call detail
    #: per-pallas_call kernel-contract sections (kernel_contracts.py):
    #: bounds / race / alias verdicts, grid points checked, finding count
    kernel_contracts: list = dataclasses.field(default_factory=list)
    #: host-contract sections (host_contracts.py) when the host pass ran
    #: for this target: per-overlap-window race/blocking verdicts and
    #: per-state-machine coverage; None = host pass not applicable
    host_contracts: list | None = None

    def summary(self) -> dict:
        """Compact dict for bench rung detail / --json."""
        from .host_contracts import host_contracts_summary
        from .kernel_contracts import contracts_summary

        kc = contracts_summary(self.kernel_contracts)
        hc = (host_contracts_summary(self.host_contracts)
              if self.host_contracts is not None else None)
        return {"target": self.target,
                "peak_hbm_bytes": self.peak_hbm_bytes,
                "peak_hbm_mib": round(self.peak_hbm_bytes / 2**20, 3),
                "eqns": self.eqns,
                "pallas_calls": self.pallas_calls,
                "scatters": self.scatters,
                "collective_bytes": self.collective_bytes,
                "vmem_bytes_per_launch": self.vmem_bytes_per_launch,
                "vmem_cap_bytes": self.vmem_cap_bytes,
                "vmem_launch_sites": len(self.vmem),
                "trace_families": self.trace_families,
                "kernel_contracts": kc,
                "kernel_contract_violations": kc["violations"],
                "host_contracts": hc,
                "host_contract_violations":
                    hc["violations"] if hc is not None else 0}

    def render(self) -> str:
        s = self.summary()
        lines = [f"-- card {self.target}: "
                 f"peak_hbm {s['peak_hbm_mib']} MiB, "
                 f"{self.pallas_calls} pallas launch(es), "
                 f"{self.scatters} scatter(s), "
                 f"collective_bytes {self.collective_bytes}, "
                 f"vmem/launch {self.vmem_bytes_per_launch} "
                 f"(cap {self.vmem_cap_bytes}), "
                 f"trace_families {self.trace_families}, "
                 f"{self.eqns} eqns --"]
        for v in self.vmem:
            lines.append(f"   pallas {v['kernel'] or '<unnamed>'} "
                         f"grid={v['grid']} vmem={v['vmem_bytes']}B "
                         f"(blocks {v['block_bytes']} + scratch "
                         f"{v['scratch_bytes']}) [{v['where']}]")
        for c in self.kernel_contracts:
            lines.append(f"   contracts {c['kernel']} grid={c['grid']} "
                         f"bounds={c['bounds']} race={c['race']} "
                         f"alias={c['alias']} "
                         f"({c['points_checked']}/{c['grid_points']} grid "
                         f"point(s){', sampled' if c['sampled'] else ''})")
        for h in self.host_contracts or ():
            if h.get("kind") == "overlap":
                lines.append(
                    f"   host-overlap {h['method']} "
                    f"windows={len(h['windows'])} "
                    f"races={[r['field'] for r in h['races']]} "
                    f"blocking={len(h['blocking'])} [{h['where']}]")
            elif h.get("kind") == "machine":
                lines.append(
                    f"   host-machine {h['machine']} "
                    f"sites={h['sites']} "
                    f"edges {len(h['covered_edges'])}/"
                    f"{len(h['declared_edges'])} covered, "
                    f"dead={h['dead_edges']} "
                    f"undeclared={len(h['undeclared'])} "
                    f"protocol={len(h['protocol'])}")
        return "\n".join(lines)


def build_card(fn, args=(), *, target: str = "", closed=None, hlo=None,
               donated=None, trace_families=None, compile_collectives=True,
               vmem_cap: int | None = None,
               kernel_contracts=None,
               host_contracts=None) -> ProgramCard:
    """Derive a :class:`ProgramCard` from a traced program.

    ``closed`` reuses an existing trace (else ``fn(*args)`` is traced);
    ``hlo`` reuses a compiled-HLO text for the collective attribution
    (else, on multi-device programs, one compile is attempted when
    ``compile_collectives`` and ``fn`` allow).  ``trace_families`` reuses
    the recompile rule's signature count when the caller already ran it;
    ``kernel_contracts`` likewise reuses the verifier's per-kernel
    sections when ``analyze()`` already ran the kernel_contracts rule on
    this trace — else they are derived here (the cards-only gate and
    ``engine.decode_step_card()`` paths), still on the same trace.
    ``host_contracts`` attaches the host-contract pass's sections
    (host_contracts.py); unlike kernel contracts it is NOT derived here —
    the pass is module-scoped, not trace-scoped, so only callers that
    know the target serves from the async host runtime opt in
    (targets.HOST_TARGETS / ``analyze(host=True)``)."""
    import jax

    from .rules import _mesh_devices_of, compiled_hlo, signature_families

    if closed is None:
        closed = jax.make_jaxpr(fn)(*args)
    census = eqn_census(closed)
    vm = vmem_estimates(closed)
    if kernel_contracts is None:
        from .kernel_contracts import check_kernel_contracts

        _, kernel_contracts = check_kernel_contracts(closed, target=target)
    if trace_families is None and args:
        trace_families = signature_families(args)
    devices = _mesh_devices_of(closed, args)
    if devices <= 1:
        coll: int | None = 0
    elif hlo is not None:
        coll = collective_bytes_from_hlo(hlo)
    elif compile_collectives and fn is not None:
        text, _err = compiled_hlo(fn, args)
        coll = collective_bytes_from_hlo(text) if text is not None else None
    else:
        coll = None
    return ProgramCard(
        target=target or getattr(fn, "__name__", "anonymous"),
        peak_hbm_bytes=peak_live_hbm(closed, donated=donated),
        eqns=census["eqns"], pallas_calls=census["pallas_calls"],
        scatters=census["scatters"], collective_bytes=coll,
        vmem_bytes_per_launch=max((v["vmem_bytes"] for v in vm), default=0),
        vmem_cap_bytes=vmem_cap if vmem_cap is not None else vmem_cap_bytes(),
        trace_families=trace_families, vmem=vm,
        kernel_contracts=kernel_contracts,
        host_contracts=host_contracts)


def card_findings(card: ProgramCard) -> list[Finding]:
    """Gating findings derivable from the card alone: any single Pallas
    launch whose estimated VMEM residency exceeds the per-generation cap
    (a launch that can't fit won't compile on hardware — or will, with the
    compiler spilling blocks back to HBM and the kernel's win gone)."""
    findings = []
    for v in card.vmem:
        if v["vmem_bytes"] > card.vmem_cap_bytes:
            findings.append(Finding(
                rule="program_card", severity=Severity.WARNING,
                message=(f"pallas launch {v['kernel'] or '<unnamed>'} "
                         f"estimated VMEM {v['vmem_bytes']} B (blocks "
                         f"{v['block_bytes']} + scratch "
                         f"{v['scratch_bytes']}) exceeds the "
                         f"{card.vmem_cap_bytes} B cap "
                         f"(PADDLE_TPU_VMEM_CAP_MIB overrides) — shrink "
                         f"the block shapes or shard the grid"),
                where=v["where"], target=card.target))
    return findings


# ---------------------------------------------------------------------------
# budgets.toml (per-target ceilings, reasoned like the allowlist)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class BudgetEntry:
    """One ``[[budget]]`` table: a target's ceilings + REQUIRED reason."""

    target: str
    ceilings: dict
    reason: str


def load_budgets(path: str | None = None) -> list[BudgetEntry]:
    """Load the budget file; a missing default file is an empty budget set
    (the gate then flags every card as un-budgeted), a missing EXPLICIT
    path is an error — same contract as the allowlist loader."""
    explicit = path is not None
    path = path or DEFAULT_BUDGETS
    if not os.path.exists(path):
        if explicit:
            raise FileNotFoundError(f"budgets file not found: {path}")
        return []
    with open(path) as f:
        entries = _parse_mini_toml(f.read(), header="budget")
    out: list[BudgetEntry] = []
    seen: set[str] = set()
    for i, e in enumerate(entries):
        target = e.pop("target", None)
        reason = e.pop("reason", "")
        if not isinstance(target, str) or not target:
            raise ValueError(f"budget entry {i}: missing target")
        if target in seen:
            raise ValueError(f"budget entry {i}: duplicate target "
                             f"{target!r} — one ceiling set per target")
        seen.add(target)
        if not reason or not isinstance(reason, str):
            raise ValueError(
                f"budget entry {i} ({target}): every budget needs a "
                f"one-line reason justifying its ceilings")
        unknown = set(e) - set(_CEILING_KEYS)
        if unknown:
            raise ValueError(f"budget entry {i} ({target}): unknown "
                             f"ceiling keys {sorted(unknown)}; known: "
                             f"{sorted(_CEILING_KEYS)}")
        bad = {k for k, v in e.items() if not isinstance(v, int)}
        if bad:
            raise ValueError(f"budget entry {i} ({target}): non-integer "
                             f"ceiling(s) {sorted(bad)}")
        out.append(BudgetEntry(target=target, ceilings=dict(e),
                               reason=reason))
    return out


def check_budgets(cards: dict, budgets: list[BudgetEntry],
                  registered=None) -> list[Finding]:
    """Gate cards against their ceilings.  Findings (all gating):

    * a card field EXCEEDING its ceiling (the regression the subsystem
      exists to catch — named field, measured vs budgeted value);
    * a card with NO budget entry (every registered target must carry a
      reasoned ceiling set — run ``--cards --update-budgets`` and justify);
    * a STALE budget entry naming no registered target (``registered``:
      the target registry; a renamed target must not leave its old
      ceilings lingering as if still enforced).

    A card field of None (collective bytes when the compile was
    unavailable) is skipped with an advisory info finding, never silently.
    """
    findings: list[Finding] = []
    by_target = {b.target: b for b in budgets}
    for name, card in cards.items():
        entry = by_target.get(name)
        if entry is None:
            findings.append(Finding(
                rule="budget", severity=Severity.WARNING,
                message=(f"no budgets.toml entry for target {name!r} — "
                         f"every gate target needs reasoned ceilings "
                         f"(python -m paddle_tpu.analysis --cards "
                         f"--update-budgets, then justify the entry)"),
                target=name))
            continue
        s = card.summary()
        for field, ceiling in sorted(entry.ceilings.items()):
            value = s.get(field)
            if value is None:
                findings.append(Finding(
                    rule="budget", severity=Severity.INFO,
                    message=(f"{field} unknown on this run (compile "
                             f"unavailable) — ceiling {ceiling} not "
                             f"checked"),
                    where=field, target=name))
                continue
            if value > ceiling:
                findings.append(Finding(
                    rule="budget", severity=Severity.ERROR,
                    message=(f"{field} = {value} exceeds the budgeted "
                             f"ceiling {ceiling} — a static cost "
                             f"regression; fix it, or re-run "
                             f"--update-budgets and re-justify the entry "
                             f"(reason on file: {entry.reason[:80]})"),
                    where=field, target=name))
    if registered is not None:
        names = set(registered)
        for b in budgets:
            if b.target not in names:
                findings.append(Finding(
                    rule="budget", severity=Severity.WARNING,
                    message=(f"stale budgets.toml entry: target "
                             f"{b.target!r} is not registered — a renamed/"
                             f"removed target must not keep phantom "
                             f"ceilings on file (registered: "
                             f"{sorted(names)})"),
                    target=b.target))
    return findings


def gate_cards(cards: dict, budgets: list[BudgetEntry], allowlist=None,
               registered=None) -> list[Finding]:
    """THE cards-gate policy, shared by ``tools/lint_gate.py --cards-only``
    and the ``--cards`` CLI so the two documented entry points can never
    desynchronize: card-level findings (VMEM over cap) pass through the
    allowlist exactly as ``analyze(card=True)`` folds them into a report
    on the full-gate path, then the budget ceilings are checked.  Returns
    the combined finding list (callers gate on severity != info)."""
    from .report import Report

    findings: list[Finding] = []
    for name, card in cards.items():
        findings += Report(name, card_findings(card),
                           allowlist=allowlist or []).findings
    findings += check_budgets(cards, budgets, registered=registered)
    return findings


_BUDGETS_HEADER = """\
# paddle_tpu.analysis budgets — per-target static-cost ceilings gated by
# tools/lint_gate.py (and `python -m paddle_tpu.analysis --cards`).  One
# [[budget]] table per registered target; every entry carries a REQUIRED
# one-line reason (enforced by the loader), same contract as
# allowlist.toml.  Ceilings are the card values at the last reviewed
# state: a PR that legitimately grows a figure re-runs
#   python -m paddle_tpu.analysis --cards --update-budgets
# (which preserves reasons) and re-justifies the entry in review; a PR
# that grows one silently fails the gate with the offending field named.
# Fields: peak_hbm_bytes, pallas_calls, scatters, collective_bytes,
# vmem_bytes_per_launch, trace_families, kernel_contract_violations,
# host_contract_violations (docs/analysis.md).
"""


def render_budgets(cards: dict, reasons: dict | None = None,
                   keep: list | None = None,
                   extra_fields: dict | None = None,
                   fallback: dict | None = None) -> str:
    """Serialize cards as a budgets.toml (ceilings = measured values).
    ``reasons`` maps target -> reason to preserve; new targets get a
    placeholder the reviewer must replace with a real justification.
    ``keep``: existing :class:`BudgetEntry` s to re-emit verbatim (targets
    NOT re-measured this run).  ``extra_fields`` maps target -> ceiling
    keys beyond :data:`BUDGET_FIELDS` (e.g. a hand-added ``eqns``) to
    re-emit at the measured value — a deliberate extra ceiling must not
    silently vanish on update.  ``fallback`` maps target -> the existing
    entry's ceilings, used when a card field is None this run (e.g.
    collective_bytes on a host whose multi-device compile failed): the
    previous ceiling is preserved rather than silently un-gated."""
    reasons = reasons or {}
    extra_fields = extra_fields or {}
    fallback = fallback or {}

    def quote(s: str) -> str:  # exact inverse of the parser's unescape
        return (s.replace("\n", " ").replace("\\", "\\\\")
                .replace('"', '\\"'))

    chunks = [_BUDGETS_HEADER]
    entries: dict[str, list[str]] = {}
    for b in keep or []:
        lines = ["[[budget]]", f'target = "{quote(b.target)}"']
        lines += [f"{k} = {int(v)}" for k, v in sorted(b.ceilings.items())]
        lines.append(f'reason = "{quote(b.reason)}"')
        entries[b.target] = lines
    for name in sorted(cards):
        s = cards[name].summary()
        lines = ["[[budget]]", f'target = "{quote(name)}"']
        fields = BUDGET_FIELDS + tuple(
            k for k in extra_fields.get(name, ())
            if k in _CEILING_KEYS and k not in BUDGET_FIELDS)
        for field in fields:
            value = s.get(field)
            if value is None:  # unknowable on this run — keep the
                value = (fallback.get(name) or {}).get(field)  # old ceiling
            if value is None:
                continue
            lines.append(f"{field} = {int(value)}")
        reason = reasons.get(name) or (
            "auto-added by --update-budgets at the measured card values; "
            "review and justify before merging")
        lines.append(f'reason = "{quote(reason)}"')
        entries[name] = lines
    chunks += ["\n".join(entries[n]) for n in sorted(entries)]
    return "\n\n".join(chunks) + "\n"


def update_budgets_file(cards: dict, path: str | None = None,
                        registered=None) -> str:
    """Rewrite budgets.toml: ``cards`` get their measured ceilings (reasons
    preserved from the existing file), existing entries for targets NOT
    re-measured this run are kept verbatim — a partial
    ``--update-budgets --target X`` run must never delete the other
    targets' reviewed ceilings.  Entries are dropped only when
    ``registered`` is given and the target is not in it (that is how a
    stale entry retires).  Returns the path written."""
    path = path or DEFAULT_BUDGETS
    existing: list[BudgetEntry] = []
    if os.path.exists(path):
        # a malformed existing file is a hard error, NOT a rewrite-from-
        # scratch: silently discarding it would replace every reviewed
        # reason with the auto placeholder (fail-loud contract, same as
        # the parser's own)
        existing = load_budgets(path)
    reasons = {b.target: b.reason for b in existing}
    keep = [b for b in existing if b.target not in cards
            and (registered is None or b.target in registered)]
    extra = {b.target: [k for k in b.ceilings if k not in BUDGET_FIELDS]
             for b in existing if b.target in cards}
    fallback = {b.target: b.ceilings for b in existing if b.target in cards}
    with open(path, "w") as f:
        f.write(render_budgets(cards, reasons, keep=keep,
                               extra_fields=extra, fallback=fallback))
    return path
