"""paddle_tpu.analysis — jaxpr-level TPU lint + serving-engine auditor.

Static analysis over any jittable callable: trace to a ClosedJaxpr (no device
execution — runs under ``JAX_PLATFORMS=cpu``) and walk it for the properties
that keep a program on the TPU fast path:

* ``dtype_upcast`` — f32 MXU ops reachable from bf16/int-quant inputs, and
  weak-type (python-scalar) promotions;
* ``donation``     — bitwise-dead input buffers not donated (HBM doubled);
* ``recompile``    — jit cache-key instability under equivalent inputs;
* ``host_sync``    — callback-class primitives / host round-trips in hot
  loops;
* ``resharding``   — implicit all-gathers the SPMD partitioner inserted;
* ``kernel_contracts`` — static Pallas verification (kernel_contracts.py):
  every ``pallas_call``'s index maps proven in-bounds (``kernel_bounds``),
  output maps race-free (``kernel_race`` / ``kernel_lost_write``), and
  ``input_output_aliases`` pairs sound (``kernel_alias``), by concrete
  grid enumeration on the same trace;
* host contracts (``analyze(..., host=True)``; host_contracts.py) — AST
  effect/race analysis of the async host runtime's ``_host_overlap()``
  windows (``host_race`` / ``host_blocking``) plus exhaustive protocol
  verification of the fleet health machine and request lifecycle against
  their declared transition tables (``host_transition`` /
  ``host_dead_edge`` / ``host_protocol``).

Three surfaces (docs/analysis.md):

* library — ``analyze(fn, *args) -> Report``;
* CLI     — ``python -m paddle_tpu.analysis --target llama_train_step``;
* runtime — ``PADDLE_TPU_ENGINE_AUDIT=1`` cross-checks the serving engine's
  block-pool/prefix-cache invariants every step (engine_audit.py).

``tools/lint_gate.py`` runs the registered targets (targets.py) and exits
nonzero on non-allowlisted findings; accepted findings live in
``allowlist.toml`` with one-line justifications.
"""

from __future__ import annotations

import jax

from .report import (AllowRule, Finding, Report, Severity, load_allowlist,
                     DEFAULT_ALLOWLIST)
from . import rules as _rules
from .cost_model import (ProgramCard, BudgetEntry, build_card, card_findings,
                         check_budgets, load_budgets, eqn_census,
                         DEFAULT_BUDGETS)
from .engine_audit import EngineAuditError, audit_engine, audit_enabled
from .kernel_contracts import (check_kernel_contracts, contracts_summary,
                               registry_drift_findings)
from .host_contracts import (check_host_contracts, host_contracts_summary,
                             host_verify_depth)

__all__ = ["analyze", "Report", "Finding", "Severity", "AllowRule",
           "load_allowlist", "audit_engine", "audit_enabled",
           "EngineAuditError", "n_traces", "ALL_RULES", "ProgramCard",
           "BudgetEntry", "build_card", "card_findings", "check_budgets",
           "load_budgets", "eqn_census", "DEFAULT_BUDGETS",
           "check_kernel_contracts", "contracts_summary",
           "registry_drift_findings", "check_host_contracts",
           "host_contracts_summary", "host_verify_depth"]

ALL_RULES = ("dtype_upcast", "donation", "recompile", "host_sync",
             "resharding", "kernel_contracts")


def analyze(fn, *args, target: str = "", rules=None, allowlist=None,
            allowlist_path: str | None = None,
            min_donation_bytes: int = 1 << 20,
            min_gather_bytes: int = 1 << 20,
            card: bool = False, vmem_cap: int | None = None,
            host: bool = False) -> Report:
    """Trace ``fn(*args)`` and lint the program.  ``fn`` may be jit-wrapped
    (donation/sharding metadata is read off the pjit eqn) or a plain
    callable.  ``rules`` restricts to a subset of :data:`ALL_RULES`;
    ``allowlist`` takes parsed :class:`AllowRule` s (or ``allowlist_path`` a
    TOML file; default: the packaged ``allowlist.toml``).

    ``card=True`` additionally derives the static :class:`ProgramCard`
    (cost_model.py) in the same pass — reusing this trace, the recompile
    rule's signature count, and (on multi-device programs) the ONE compiled
    HLO the resharding rule reads — and attaches it as ``report.card``;
    card-level gating findings (a Pallas launch over the ``vmem_cap``)
    join the report's findings and go through the allowlist like any rule's.
    Budget ceilings are checked by the callers that hold the full card set
    (``tools/lint_gate.py``, the ``--cards`` CLI) via
    :func:`check_budgets`.

    ``host=True`` additionally runs the host-contract pass
    (host_contracts.py) — AST effect/race analysis of the serving
    engine's ``_host_overlap()`` windows and exhaustive protocol
    verification of the fleet/request state machines.  It is keyed off
    the MODULE sources, not the traced program, so serving gate targets
    enable it (targets.HOST_TARGETS) and train targets skip it; its
    findings gate through the same allowlist and its sections land on the
    card as ``host_contracts``."""
    active = set(rules if rules is not None else ALL_RULES)
    unknown = active - set(ALL_RULES)
    if unknown:
        raise ValueError(f"unknown rules {sorted(unknown)}; "
                         f"expected subset of {ALL_RULES}")
    if allowlist is None:
        allowlist = load_allowlist(allowlist_path)

    n_traced = 0   # ACTUAL jaxpr traces of the target this pass performed
    #                — a real counter, not a tally of enabled rules, so a
    #                rule that silently starts re-tracing moves the figure

    def trace():
        nonlocal n_traced
        n_traced += 1
        return jax.make_jaxpr(fn)(*args)

    import time as _time

    t0 = _time.perf_counter()
    closed = trace()
    findings: list[Finding] = []
    n_sigs = None
    hlo = hlo_err = None
    trace_reuse = 0   # tally of rule/card consumers SHARING the baseline
    #                   trace (documents the single-trace design; the
    #                   measured evidence is traces_performed below)
    if (card or "resharding" in active) \
            and _rules._mesh_devices_of(closed, args) > 1:
        hlo, hlo_err = _rules.compiled_hlo(fn, args)
    if "dtype_upcast" in active:
        findings += _rules.check_dtype_upcast(closed, args, target=target)
        trace_reuse += 1
    if "donation" in active:
        findings += _rules.check_donation(closed, args, target=target,
                                          min_bytes=min_donation_bytes)
        trace_reuse += 1
    if "recompile" in active:
        churn, n_sigs = _rules.check_recompile(fn, args, target=target,
                                               trace=trace, baseline=closed)
        findings += churn
        trace_reuse += 1
    if "host_sync" in active:
        findings += _rules.check_host_sync(closed, target=target)
        trace_reuse += 1
    if "resharding" in active:
        findings += _rules.check_resharding(fn, args, closed=closed,
                                            target=target,
                                            min_bytes=min_gather_bytes,
                                            hlo=hlo, hlo_error=hlo_err)
        trace_reuse += 1
    kc_sections = None
    if "kernel_contracts" in active:
        from .kernel_contracts import check_kernel_contracts

        kc_findings, kc_sections = check_kernel_contracts(closed,
                                                          target=target)
        findings += kc_findings
        trace_reuse += 1
    hc_sections = None
    if host:
        hc_findings, hc_sections = check_host_contracts(target=target)
        findings += hc_findings
    built_card = None
    if card:
        # compile_collectives=False: the one compile this pass needed
        # already happened above — a failure must not be retried per card;
        # kernel_contracts reuses the verifier sections the rule derived
        # (host_contracts likewise when the host pass ran)
        built_card = build_card(fn, args, target=target, closed=closed,
                                hlo=hlo, trace_families=n_sigs,
                                vmem_cap=vmem_cap, compile_collectives=False,
                                kernel_contracts=kc_sections,
                                host_contracts=hc_sections)
        findings += card_findings(built_card)
        trace_reuse += 1
    sev = {Severity.ERROR: 0, Severity.WARNING: 1, Severity.INFO: 2}
    findings.sort(key=lambda f: (sev[f.severity], f.rule, f.where))
    report = Report(target or getattr(fn, "__name__", "anonymous"), findings,
                    allowlist=allowlist, n_traces=n_sigs)
    report.card = built_card
    report.trace_reuse = trace_reuse
    report.traces_performed = n_traced
    report.seconds = _time.perf_counter() - t0
    return report


def n_traces(*jitted) -> int | None:
    """Total compiled-variant count across jit-wrapped callables (the
    bench's jit-cache-churn telemetry: a rung whose detail reports more
    traces than compiled program variants it legitimately needs is paying
    silent re-trace/re-compile time).  Objects without a cache counter are
    skipped; returns None when nothing was countable."""
    total, counted = 0, False
    for f in jitted:
        size = getattr(f, "_cache_size", None)
        if callable(size):
            try:
                total += int(size())
                counted = True
            except Exception:
                pass
    return total if counted else None
