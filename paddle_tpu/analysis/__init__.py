"""paddle_tpu.analysis — jaxpr-level TPU lint + serving-engine auditor.

Static analysis over any jittable callable: trace to a ClosedJaxpr (no device
execution — runs under ``JAX_PLATFORMS=cpu``) and walk it for the properties
that keep a program on the TPU fast path:

* ``dtype_upcast`` — f32 MXU ops reachable from bf16/int-quant inputs, and
  weak-type (python-scalar) promotions;
* ``donation``     — bitwise-dead input buffers not donated (HBM doubled);
* ``recompile``    — jit cache-key instability under equivalent inputs;
* ``host_sync``    — callback-class primitives / host round-trips in hot
  loops;
* ``resharding``   — implicit all-gathers the SPMD partitioner inserted.

Three surfaces (docs/analysis.md):

* library — ``analyze(fn, *args) -> Report``;
* CLI     — ``python -m paddle_tpu.analysis --target llama_train_step``;
* runtime — ``PADDLE_TPU_ENGINE_AUDIT=1`` cross-checks the serving engine's
  block-pool/prefix-cache invariants every step (engine_audit.py).

``tools/lint_gate.py`` runs the registered targets (targets.py) and exits
nonzero on non-allowlisted findings; accepted findings live in
``allowlist.toml`` with one-line justifications.
"""

from __future__ import annotations

import jax

from .report import (AllowRule, Finding, Report, Severity, load_allowlist,
                     DEFAULT_ALLOWLIST)
from . import rules as _rules
from .cost_model import (ProgramCard, BudgetEntry, build_card, card_findings,
                         check_budgets, load_budgets, eqn_census,
                         DEFAULT_BUDGETS)
from .engine_audit import EngineAuditError, audit_engine, audit_enabled

__all__ = ["analyze", "Report", "Finding", "Severity", "AllowRule",
           "load_allowlist", "audit_engine", "audit_enabled",
           "EngineAuditError", "n_traces", "ALL_RULES", "ProgramCard",
           "BudgetEntry", "build_card", "card_findings", "check_budgets",
           "load_budgets", "eqn_census", "DEFAULT_BUDGETS"]

ALL_RULES = ("dtype_upcast", "donation", "recompile", "host_sync",
             "resharding")


def analyze(fn, *args, target: str = "", rules=None, allowlist=None,
            allowlist_path: str | None = None,
            min_donation_bytes: int = 1 << 20,
            min_gather_bytes: int = 1 << 20,
            card: bool = False, vmem_cap: int | None = None) -> Report:
    """Trace ``fn(*args)`` and lint the program.  ``fn`` may be jit-wrapped
    (donation/sharding metadata is read off the pjit eqn) or a plain
    callable.  ``rules`` restricts to a subset of :data:`ALL_RULES`;
    ``allowlist`` takes parsed :class:`AllowRule` s (or ``allowlist_path`` a
    TOML file; default: the packaged ``allowlist.toml``).

    ``card=True`` additionally derives the static :class:`ProgramCard`
    (cost_model.py) in the same pass — reusing this trace, the recompile
    rule's signature count, and (on multi-device programs) the ONE compiled
    HLO the resharding rule reads — and attaches it as ``report.card``;
    card-level gating findings (a Pallas launch over the ``vmem_cap``)
    join the report's findings and go through the allowlist like any rule's.
    Budget ceilings are checked by the callers that hold the full card set
    (``tools/lint_gate.py``, the ``--cards`` CLI) via
    :func:`check_budgets`."""
    active = set(rules if rules is not None else ALL_RULES)
    unknown = active - set(ALL_RULES)
    if unknown:
        raise ValueError(f"unknown rules {sorted(unknown)}; "
                         f"expected subset of {ALL_RULES}")
    if allowlist is None:
        allowlist = load_allowlist(allowlist_path)

    def trace():
        return jax.make_jaxpr(fn)(*args)

    closed = trace()
    findings: list[Finding] = []
    n_sigs = None
    hlo = hlo_err = None
    if (card or "resharding" in active) \
            and _rules._mesh_devices_of(closed, args) > 1:
        hlo, hlo_err = _rules.compiled_hlo(fn, args)
    if "dtype_upcast" in active:
        findings += _rules.check_dtype_upcast(closed, args, target=target)
    if "donation" in active:
        findings += _rules.check_donation(closed, args, target=target,
                                          min_bytes=min_donation_bytes)
    if "recompile" in active:
        churn, n_sigs = _rules.check_recompile(fn, args, target=target,
                                               trace=trace, baseline=closed)
        findings += churn
    if "host_sync" in active:
        findings += _rules.check_host_sync(closed, target=target)
    if "resharding" in active:
        findings += _rules.check_resharding(fn, args, closed=closed,
                                            target=target,
                                            min_bytes=min_gather_bytes,
                                            hlo=hlo, hlo_error=hlo_err)
    built_card = None
    if card:
        # compile_collectives=False: the one compile this pass needed
        # already happened above — a failure must not be retried per card
        built_card = build_card(fn, args, target=target, closed=closed,
                                hlo=hlo, trace_families=n_sigs,
                                vmem_cap=vmem_cap, compile_collectives=False)
        findings += card_findings(built_card)
    sev = {Severity.ERROR: 0, Severity.WARNING: 1, Severity.INFO: 2}
    findings.sort(key=lambda f: (sev[f.severity], f.rule, f.where))
    report = Report(target or getattr(fn, "__name__", "anonymous"), findings,
                    allowlist=allowlist, n_traces=n_sigs)
    report.card = built_card
    return report


def n_traces(*jitted) -> int | None:
    """Total compiled-variant count across jit-wrapped callables (the
    bench's jit-cache-churn telemetry: a rung whose detail reports more
    traces than compiled program variants it legitimately needs is paying
    silent re-trace/re-compile time).  Objects without a cache counter are
    skipped; returns None when nothing was countable."""
    total, counted = 0, False
    for f in jitted:
        size = getattr(f, "_cache_size", None)
        if callable(size):
            try:
                total += int(size())
                counted = True
            except Exception:
                pass
    return total if counted else None
