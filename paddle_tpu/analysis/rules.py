"""The five lint rules, each a pure function over a traced program.

All rules run on the ClosedJaxpr (plus, for the resharding rule, the
post-SPMD compiled HLO) — no TPU time is spent: tracing happens under
whatever backend is active, canonically ``JAX_PLATFORMS=cpu``.  GSPMD-style
compilation makes these properties statically visible before execution
(PAPERS.md: GSPMD; TPU-MLIR's per-stage verification argument).

Rules
-----
``dtype_upcast``   f32 dot/conv eqns whose operands derive from bf16/f16/int
                   inputs (the MXU runs bf16 ~8x faster than f32 — one silent
                   ``.astype(float32)`` before a matmul erases a kernel's win),
                   plus weak-typed float inputs (python-scalar provenance).
``donation``       undonated input buffers whose (shape, dtype) reappears in
                   the outputs — the train-step/decode-cache pattern where the
                   old buffer is bitwise-dead but still pins HBM because
                   ``donate_argnums`` missed it.
``recompile``      jit cache-key instability: re-derive the cache signature
                   under perturbed-but-equivalent inputs (python-scalar vs
                   array provenance, permuted dict insertion order) and flag
                   any signature change — each one is a silent recompile in
                   production.
``host_sync``      callback-class primitives (pure/io/debug callbacks,
                   infeed/outfeed) — host round-trips; severity escalates to
                   error inside scan/while bodies (the hot loop).
``resharding``     large collectives in the compiled HLO (multi-device meshes
                   only): all-gathers/all-to-alls the SPMD partitioner
                   inserted that the program never asked for — eqns whose
                   in/out shardings force an implicit gather — plus
                   all-reduces, so deliberate psum boundaries (the TP serving
                   engine's two per layer) stay pinned behind reasoned
                   allowlist entries and any new large reduce fails the gate.
"""

from __future__ import annotations

import re

import jax
import numpy as np
from jax import tree_util as jtu

from .report import Finding, Severity

# dtypes whose values we consider "low precision by design": a program that
# holds params/caches in these and then runs an MXU op in f32 has leaked
LOW_PRECISION = {"bfloat16", "float16", "int8", "uint8", "int4", "uint4",
                 "float8_e4m3fn", "float8_e5m2"}
# MXU-bound primitives: an f32 instance of these is the expensive leak
_MXU_PRIMS = {"dot_general", "conv_general_dilated", "ragged_dot"}
# host-synchronizing primitives (callback family + infeed/outfeed)
_HOST_SYNC_PRIMS = {"pure_callback", "io_callback", "debug_callback",
                    "callback", "infeed", "outfeed"}
# control-flow primitives that define "inside a hot loop"
_LOOP_PRIMS = {"scan", "while", "fori"}


# ---------------------------------------------------------------------------
# jaxpr plumbing
# ---------------------------------------------------------------------------

def _sub_jaxprs(eqn):
    """Sub-jaxprs of an eqn (pjit/scan/while/cond/remat/custom_vjp/...)."""
    from jax._src import core as jcore

    out = []
    for v in eqn.params.values():
        vals = v if isinstance(v, (tuple, list)) else (v,)
        for x in vals:
            if isinstance(x, jcore.ClosedJaxpr):
                out.append(x.jaxpr)
            elif isinstance(x, jcore.Jaxpr):
                out.append(x)
    return out


def _where(eqn) -> str:
    """``file.py:line (fn)`` provenance of an eqn, best-effort."""
    try:
        from jax._src import source_info_util

        frame = source_info_util.user_frame(eqn.source_info)
        if frame is not None:
            return f"{frame.file_name.split('/')[-1]}:{frame.start_line} " \
                   f"({frame.function_name})"
        return source_info_util.summarize(eqn.source_info)
    except Exception:
        return ""


def _aval(var):
    return getattr(var, "aval", None)


def _dtype_name(var) -> str:
    a = _aval(var)
    return str(a.dtype) if a is not None and hasattr(a, "dtype") else ""


def _leaf_paths(args) -> list[str]:
    """Structural names for the flattened example args ('0/params/wq')."""
    flat, _ = jtu.tree_flatten_with_path(tuple(args))
    names = []
    for path, _leaf in flat:
        parts = []
        for p in path:
            key = getattr(p, "key", getattr(p, "idx", getattr(p, "name", p)))
            parts.append(str(key))
        names.append("/".join(parts))
    return names


def _unwrap_pjit(closed):
    """If the traced fn was itself jit-wrapped, the whole program is one pjit
    eqn: descend into it and surface its donation/sharding metadata."""
    jaxpr = closed.jaxpr
    body_eqns = [e for e in jaxpr.eqns if e.primitive.name == "pjit"]
    if len(jaxpr.eqns) == 1 and body_eqns:
        eqn = body_eqns[0]
        return eqn.params["jaxpr"], eqn.params.get("donated_invars")
    return closed, None


# ---------------------------------------------------------------------------
# rule 1: dtype-upcast leak
# ---------------------------------------------------------------------------

def check_dtype_upcast(closed, args=(), target: str = "") -> list[Finding]:
    """Taint-walk the jaxpr: inputs with low-precision dtypes taint every
    derived value; an MXU primitive whose f32/f64 operand is tainted means a
    low-precision value was upcast on the way to the matrix unit."""
    findings: list[Finding] = []
    seen: set[tuple] = set()   # (rule-site) dedup: fwd+bwd of one line -> one

    inner, _ = _unwrap_pjit(closed)
    jaxpr = inner.jaxpr if hasattr(inner, "jaxpr") else inner

    def taint_of(invars):
        return [_dtype_name(v) in LOW_PRECISION for v in invars]

    def walk(jx, taint_in: list[bool]):
        from jax._src.core import Literal

        taint: dict = {}
        for v, t in zip(jx.invars, taint_in):
            taint[v] = t
        for v in jx.constvars:
            taint[v] = _dtype_name(v) in LOW_PRECISION

        def is_tainted(v):
            if isinstance(v, Literal):
                return False
            return taint.get(v, False)

        for eqn in jx.eqns:
            in_taint = [is_tainted(v) for v in eqn.invars]
            prim = eqn.primitive.name
            if prim in _MXU_PRIMS:
                for v, t in zip(eqn.invars, in_taint):
                    dt = _dtype_name(v)
                    if t and dt in ("float32", "float64"):
                        site = (prim, _where(eqn), dt)
                        if site not in seen:
                            seen.add(site)
                            findings.append(Finding(
                                rule="dtype_upcast",
                                severity=Severity.WARNING,
                                message=(f"{prim} runs in {dt} on an operand "
                                         f"upcast from a low-precision input "
                                         f"(MXU fast path lost)"),
                                where=_where(eqn), target=target))
                        break
            subs = _sub_jaxprs(eqn)
            for sub in subs:
                if len(sub.invars) == len(eqn.invars):
                    walk(sub, in_taint)
                else:
                    # conservative: unknown operand mapping (cond branches,
                    # closed-over consts) — taint everything if anything is
                    walk(sub, [any(in_taint)] * len(sub.invars))
            out_t = any(in_taint)
            for v in eqn.outvars:
                taint[v] = out_t

    walk(jaxpr, taint_of(jaxpr.invars))

    # weak-typed float inputs: python-scalar provenance promotes silently and
    # churns the jit cache (see check_recompile); advisory here
    if args:
        names = _leaf_paths(args)
        leaves = jtu.tree_leaves(tuple(args))
        for name, leaf in zip(names, leaves):
            aval = jax.api_util.shaped_abstractify(leaf) \
                if not hasattr(leaf, "aval") else leaf.aval
            if getattr(aval, "weak_type", False) and \
                    np.issubdtype(aval.dtype, np.floating):
                findings.append(Finding(
                    rule="dtype_upcast", severity=Severity.INFO,
                    message=(f"input {name} is weak-typed (python-scalar "
                             f"provenance); promotion rules may upcast "
                             f"silently"),
                    where=name, target=target))
    return findings


# ---------------------------------------------------------------------------
# rule 2: donation miss
# ---------------------------------------------------------------------------

def check_donation(closed, args, target: str = "",
                   min_bytes: int = 1 << 20) -> list[Finding]:
    """Undonated inputs whose (shape, dtype) reappears in the outputs.

    The signature of the train-step/decode-step pattern: the caller rebinds
    ``params, opt_state = step(params, opt_state, ...)`` so the old buffers
    are bitwise-dead — but without ``donate_argnums`` XLA must keep both
    copies live across the step, doubling that tree's HBM.  Shape/dtype
    aliasing is a heuristic (hence warning + allowlist, not error); only
    buffers >= ``min_bytes`` are worth flagging."""
    inner, donated = _unwrap_pjit(closed)
    jaxpr = inner.jaxpr if hasattr(inner, "jaxpr") else inner
    leaves = jtu.tree_leaves(tuple(args))
    names = _leaf_paths(args)
    if donated is None:
        donated = (False,) * len(leaves)
    if len(donated) != len(leaves) or len(jaxpr.invars) != len(leaves):
        # invars don't map 1:1 onto the example-arg leaves (pruned/reordered
        # args, static closures): donation flags can't be attributed to
        # leaves reliably — misaligning would emit false "donation miss"
        # findings that push bogus allowlist entries.  The skip itself must
        # be VISIBLE (an info finding), or a refactor that breaks the
        # mapping silently turns donation coverage off while the gate
        # still reports the target clean.
        return [Finding(
            rule="donation", severity=Severity.INFO,
            message=(f"donation check skipped: traced invars "
                     f"({len(jaxpr.invars)}) do not map 1:1 onto example-"
                     f"arg leaves ({len(leaves)}) — cannot attribute "
                     f"donate_argnums"),
            target=target)]

    def sig(aval):
        return (tuple(aval.shape), str(aval.dtype))

    out_pool: dict[tuple, int] = {}
    for v in jaxpr.outvars:
        a = _aval(v)
        if a is not None and hasattr(a, "shape"):
            out_pool[sig(a)] = out_pool.get(sig(a), 0) + 1
    # donated inputs claim their matching outputs first — they are the
    # buffers XLA will actually alias
    undonated = []
    for i, v in enumerate(jaxpr.invars):
        a = _aval(v)
        if a is None or not hasattr(a, "shape"):
            continue
        if i < len(donated) and donated[i]:
            if out_pool.get(sig(a), 0) > 0:
                out_pool[sig(a)] -= 1
        else:
            undonated.append((i, v, a))

    findings = []
    # biggest first: with more lookalike inputs than outputs, report the
    # buffers whose donation would save the most HBM
    undonated.sort(key=lambda t: -int(np.prod(t[2].shape) or 0)
                   * t[2].dtype.itemsize)
    for i, v, a in undonated:
        nbytes = int(np.prod(a.shape) or 0) * a.dtype.itemsize
        if nbytes < min_bytes:
            continue
        if out_pool.get(sig(a), 0) > 0:
            out_pool[sig(a)] -= 1
            name = names[i] if i < len(names) else f"arg{i}"
            findings.append(Finding(
                rule="donation", severity=Severity.WARNING,
                message=(f"input {name} ({str(a.dtype)}{list(a.shape)}, "
                         f"{nbytes / 2**20:.1f} MiB) matches an output but "
                         f"is not donated — old buffer stays live across "
                         f"the step"),
                where=name, target=target))
    return findings


# ---------------------------------------------------------------------------
# rule 3: recompile churn
# ---------------------------------------------------------------------------

def _cache_signature(args):
    """Proxy for the jit cache key: treedef + per-leaf aval incl. weak_type.
    Two call sites producing different signatures for semantically identical
    inputs will compile (and cache) two programs."""
    leaves, treedef = jtu.tree_flatten(tuple(args))
    sig = [str(treedef)]
    for leaf in leaves:
        aval = leaf.aval if hasattr(leaf, "aval") \
            else jax.api_util.shaped_abstractify(leaf)
        sig.append(f"{aval.dtype}{list(getattr(aval, 'shape', ()))}"
                   f"w{int(getattr(aval, 'weak_type', False))}")
    return "|".join(sig)


def _strongify(args):
    """Replace python scalars with committed numpy scalars — the 'other'
    provenance an equivalent caller might use."""
    return jtu.tree_map(
        lambda x: np.asarray(x) if isinstance(x, (bool, int, float))
        and not isinstance(x, np.generic) else x, tuple(args))


def _permute_dicts(args):
    """Rebuild every mapping with reversed insertion order (key sets equal).
    Plain dicts are canonicalized by jax's pytree flatten (sorted keys), so
    for them this perturbation doubles as a regression check on that
    canonicalization; OrderedDict treedefs ENCODE insertion order, so two
    call sites building one in different orders genuinely churn the cache —
    the case this variant exists to flag."""
    import collections

    def rec(x):
        if isinstance(x, dict):  # covers OrderedDict too
            items = [(k, rec(x[k])) for k in reversed(list(x.keys()))]
            return (collections.OrderedDict(items)
                    if isinstance(x, collections.OrderedDict)
                    else dict(items))
        if isinstance(x, (list, tuple)):
            return type(x)(rec(v) for v in x)
        return x
    return rec(tuple(args))


def signature_families(args) -> int:
    """Distinct jit cache signatures across the equivalence perturbations
    (python-scalar vs array provenance, dict insertion order) — 1 means the
    program compiles exactly one trace family for these inputs.  This is
    the ``trace_families`` figure on a :class:`~.cost_model.ProgramCard`;
    :func:`check_recompile` reports the same count alongside its per-leaf
    findings."""
    base = _cache_signature(args)
    return len({base, _cache_signature(_strongify(args)),
                _cache_signature(_permute_dicts(args))})


def check_recompile(fn, args, target: str = "", trace=None,
                    baseline=None) -> tuple[list[Finding], int]:
    """Signature stability under equivalent-input perturbations, plus a
    re-trace determinism check (``baseline``: an already-traced jaxpr to
    reuse as the first determinism sample, saving one trace of the target).
    Returns (findings, n_distinct_signatures)."""
    findings: list[Finding] = []
    base = _cache_signature(args)
    variants = [("python-scalar vs array provenance", _strongify(args)),
                ("dict insertion order", _permute_dicts(args))]
    sigs = {base}
    for label, v_args in variants:
        s = _cache_signature(v_args)
        sigs.add(s)
        if s != base:
            # attribute by PATH, not position: a reordering perturbation
            # (OrderedDict) shuffles leaf order, and a positional zip would
            # name an arbitrary leaf — which then poisons allowlist `match`
            # substrings.  Same path set on both sides by construction.
            sig_a = dict(zip(_leaf_paths(args),
                             (_cache_signature((x,))
                              for x in jtu.tree_leaves(tuple(args)))))
            sig_b = dict(zip(_leaf_paths(v_args),
                             (_cache_signature((x,))
                              for x in jtu.tree_leaves(v_args))))
            culprit = next((p for p in sig_a
                            if sig_b.get(p) != sig_a[p]), "")
            findings.append(Finding(
                rule="recompile", severity=Severity.WARNING,
                message=(f"jit cache key unstable under {label}"
                         + (f" (leaf {culprit})" if culprit else "")
                         + " — equivalent callers recompile"),
                where=culprit, target=target))
    # determinism: tracing twice must produce the same program (a trace that
    # reads wall clock / RNG / mutable globals churns the cache from inside)
    if trace is not None:
        try:
            j1 = baseline if baseline is not None else trace()
            j2 = trace()
            n1 = sum(1 for _ in _iter_all_eqns(j1.jaxpr))
            n2 = sum(1 for _ in _iter_all_eqns(j2.jaxpr))
            if n1 != n2:
                findings.append(Finding(
                    rule="recompile", severity=Severity.ERROR,
                    message=(f"re-tracing produced a different program "
                             f"({n1} vs {n2} eqns) — trace-time "
                             f"nondeterminism"),
                    target=target))
        except Exception:
            pass
    return findings, len(sigs)


def _iter_all_eqns(jaxpr, path=()):
    for eqn in jaxpr.eqns:
        yield eqn, path
        for sub in _sub_jaxprs(eqn):
            yield from _iter_all_eqns(sub, path + (eqn.primitive.name,))


def iter_pallas_eqns(closed):
    """Every ``pallas_call`` eqn anywhere in a (Closed)Jaxpr, in program
    order, descending scan/pjit/remat/cond/shard_map bodies but never a
    kernel body (in-kernel eqns are not launches).  THE shared walk —
    ``cost_model.vmem_estimates`` and the kernel-contract verifier both
    consume it, so a traversal fix can never make the VMEM census and the
    contract verdicts disagree about which launches exist."""
    jaxpr = closed.jaxpr if hasattr(closed, "jaxpr") else closed

    def walk(jx):
        for e in jx.eqns:
            if e.primitive.name == "pallas_call":
                yield e
                continue
            for sub in _sub_jaxprs(e):
                yield from walk(sub)

    yield from walk(jaxpr)


# ---------------------------------------------------------------------------
# rule 4: host-sync points
# ---------------------------------------------------------------------------

def check_host_sync(closed, target: str = "") -> list[Finding]:
    inner, _ = _unwrap_pjit(closed)
    jaxpr = inner.jaxpr if hasattr(inner, "jaxpr") else inner
    findings = []
    for eqn, path in _iter_all_eqns(jaxpr):
        name = eqn.primitive.name
        if name in _HOST_SYNC_PRIMS:
            in_loop = any(p in _LOOP_PRIMS for p in path)
            findings.append(Finding(
                rule="host_sync",
                severity=Severity.ERROR if in_loop else Severity.WARNING,
                message=(f"{name} forces a host round-trip"
                         + (" inside a scan/while hot loop" if in_loop
                            else "")),
                where=_where(eqn), target=target))
    return findings


# ---------------------------------------------------------------------------
# rule 5: resharding surprise (implicit all-gather)
# ---------------------------------------------------------------------------

_DTYPE_BYTES = {"f64": 8, "f32": 4, "f16": 2, "bf16": 2, "s64": 8, "u64": 8,
                "s32": 4, "u32": 4, "s16": 2, "u16": 2, "s8": 1, "u8": 1,
                "pred": 1, "f8e4m3fn": 1, "f8e5m2": 1}
_HLO_OP_RE = re.compile(
    r"%?[\w.-]+\s*=\s*([a-z0-9]+)\[([\d,]*)\][^=]*"
    r"\s(all-gather|all-to-all|all-reduce)(?:-start)?\(")
# combined/tuple-result form the all-gather combiner emits:
#   %ag = (f32[1024,64], bf16[512,64]) all-gather(%a, %b)
_HLO_TUPLE_OP_RE = re.compile(
    r"%?[\w.-]+\s*=\s*\(([^)]*)\)[^=]*"
    r"\s(all-gather|all-to-all|all-reduce)(?:-start)?\(")
_SHAPE_RE = re.compile(r"([a-z0-9]+)\[([\d,]*)\]")
_META_RE = re.compile(r'op_name="([^"]*)"')


def _shape_bytes(dtype: str, dims: str) -> int:
    n = 1
    for d in filter(None, dims.split(",")):
        n *= int(d)
    return n * _DTYPE_BYTES.get(dtype, 4)


def _mesh_devices_of(closed, args=()) -> int:
    """Device count the program will partition over: the pjit eqn's explicit
    shardings OR (the equally common pattern) the shardings committed on the
    example args — jit without in_shardings still partitions over whatever
    mesh the inputs live on.  1 when unsharded/unknown."""
    best = 1
    jaxpr = closed.jaxpr
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "pjit":
            for sh in tuple(eqn.params.get("in_shardings") or ()) + \
                    tuple(eqn.params.get("out_shardings") or ()):
                mesh = getattr(sh, "mesh", None)
                if mesh is not None:
                    best = max(best, int(getattr(mesh, "size", 1) or 1))
    for leaf in jtu.tree_leaves(tuple(args)):
        sh = getattr(leaf, "sharding", None)
        if sh is None:
            continue
        mesh = getattr(sh, "mesh", None)
        if mesh is not None and getattr(mesh, "size", None):
            best = max(best, int(mesh.size))
        else:
            try:
                best = max(best, len(sh.device_set))
            except Exception:
                pass
    return best


def compiled_hlo(fn, args) -> tuple[str | None, Exception | None]:
    """Post-SPMD compiled HLO text of ``fn(*args)`` — (text, None) on
    success, (None, error) when the backend can't compile (e.g. device
    limits).  Shared by the resharding rule and the program card's
    collective-bytes attribution so one multi-device target pays exactly
    one compile per gate run."""
    import jax

    try:
        lowered = fn.lower(*args) if hasattr(fn, "lower") \
            else jax.jit(fn).lower(*args)
        return lowered.compile().as_text(), None
    except Exception as e:
        return None, e


def check_resharding(fn, args, closed=None, target: str = "",
                     min_bytes: int = 1 << 20, hlo: str | None = None,
                     hlo_error: Exception | None = None) -> list[Finding]:
    """Compile under the fn's own mesh and scan the post-SPMD HLO for
    all-gather/all-to-all/all-reduce ops over large tensors.
    Gathers/all-to-alls are the collectives GSPMD *inserted* — the program
    never wrote them; each one is an eqn whose in/out shardings don't
    compose, silently paying ICI bandwidth (the 'involuntary
    rematerialization' class the GQA KV replication note in
    models/llama.param_specs documents).  All-reduces are reported too so
    DELIBERATE reduction boundaries stay budgeted: a program that means to
    pay one (the TP serving engine's two per-layer psums,
    docs/tp_serving.md) carries a reasoned allowlist entry, and any other
    large reduce — a sharding change widening a psum operand, a new
    replicated reduction — fails the gate instead of shipping silently.
    Skipped on single-device meshes (nothing to reshard).  ``hlo`` /
    ``hlo_error`` carry a precomputed :func:`compiled_hlo` result (the
    card-building path in ``analyze`` shares one compile); when neither is
    given the rule compiles here."""
    if closed is not None and _mesh_devices_of(closed, args) <= 1:
        return []
    if hlo is None and hlo_error is None:
        hlo, hlo_error = compiled_hlo(fn, args)
    if hlo is None:  # compile unavailable (backend limits) — skip, visibly
        e = hlo_error
        return [Finding(rule="resharding", severity=Severity.INFO,
                        message=f"sharding check skipped: compile failed "
                                f"({type(e).__name__}: {str(e)[:120]})",
                        target=target)]
    findings = []
    for line in hlo.splitlines():
        m = _HLO_OP_RE.search(line)
        if m is not None:
            dtype, dims, op = m.group(1), m.group(2), m.group(3)
            nbytes = _shape_bytes(dtype, dims)
            shape = f"{dtype}[{dims}]"
        else:
            # combiner-fused tuple-result form: sum the tuple's shapes
            mt = _HLO_TUPLE_OP_RE.search(line)
            if mt is None:
                continue
            shapes = _SHAPE_RE.findall(mt.group(1))
            if not shapes:
                continue
            op = mt.group(2)
            nbytes = sum(_shape_bytes(d, s) for d, s in shapes)
            shape = "(" + ", ".join(f"{d}[{s}]" for d, s in shapes) + ")"
        if nbytes < min_bytes:
            continue
        meta = _META_RE.search(line)
        if op == "all-reduce":
            # reduces are often intended (psum boundaries) — the message
            # points at the allowlist instead of calling them implicit
            message = (f"{op} of {shape} ({nbytes / 2**20:.1f} MiB) "
                       f"crosses the mesh — a deliberate reduction boundary "
                       f"needs a reasoned allowlist entry, anything else is "
                       f"paying unbudgeted ICI bandwidth")
        else:
            message = (f"SPMD partitioner inserted {op} of {shape} "
                       f"({nbytes / 2**20:.1f} MiB) — in/out shardings "
                       f"force an implicit gather")
        findings.append(Finding(
            rule="resharding", severity=Severity.WARNING, message=message,
            where=(meta.group(1)[:160] if meta else ""), target=target))
    return findings
