"""Registered lint targets: the framework's own hot paths.

Each target builds a jittable callable + example args at a *tiny* config —
the lint is shape-generic (dtype flows, donation, cache keys, and callback
primitives are invariant to width/depth), so tracing the tiny config under
``JAX_PLATFORMS=cpu`` proves the same properties the production config has,
in seconds and with zero device time.

``build(name)`` returns an :class:`AnalysisTarget`; ``run(name)`` builds and
analyzes it.  ``tools/lint_gate.py`` iterates :data:`GATE_TARGETS` (and the
tier-1 suite runs the gate), so a change that knocks a train step or the
serving decode path off the fast path fails CI, not a later bench round.
"""

from __future__ import annotations

import contextlib
import dataclasses
import typing

import numpy as np

__all__ = ["AnalysisTarget", "TARGETS", "GATE_TARGETS", "HOST_TARGETS",
           "build", "run", "run_card"]


@dataclasses.dataclass
class AnalysisTarget:
    name: str
    fn: typing.Any
    args: tuple
    analyze_kwargs: dict = dataclasses.field(default_factory=dict)
    #: env pins to hold while ANALYZING (value None = unset).  Kill
    #: switches are trace-time state, and analysis re-traces the target
    #: AFTER its builder returned — without re-pinning here, an ambient
    #: PADDLE_TPU_DISABLE_PALLAS (or a bare environment) would silently
    #: swap which decode program the gate traces (e.g. the pre-fusion
    #: serving_decode_step picking up the flash kernel), and the program
    #: card would drift with whatever ran before it.
    env: dict = dataclasses.field(default_factory=dict)


@contextlib.contextmanager
def _pinned_env(env: dict):
    import os

    saved = {k: os.environ.get(k) for k in env}
    try:
        for k, v in env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        yield
    finally:
        for k, p in saved.items():
            if p is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = p


def _t_llama_train_step() -> AnalysisTarget:
    import jax

    from ..models import llama

    cfg = llama.LlamaConfig.tiny()
    mesh = llama.make_mesh(devices=jax.devices()[:1])
    step_fn, opt_init, psh, dsh = llama.build_train_step(cfg, mesh)
    params = llama.init_params(cfg, jax.random.key(0))
    opt_state = opt_init(params)
    rs = np.random.RandomState(0)
    ids = jax.numpy.asarray(rs.randint(0, cfg.vocab_size, (2, 32)))
    labels = jax.numpy.asarray(rs.randint(0, cfg.vocab_size, (2, 32)))
    return AnalysisTarget("llama_train_step", step_fn,
                          (params, opt_state, ids, labels))


def _t_moe_train_step() -> AnalysisTarget:
    import jax

    from ..models import moe_llama

    cfg = moe_llama.MoEConfig.tiny()
    mesh = moe_llama.make_mesh(devices=jax.devices()[:1])
    step_fn, opt_init, psh, dsh = moe_llama.build_train_step(cfg, mesh)
    params = moe_llama.init_params(cfg, jax.random.key(0))
    opt_state = opt_init(params)
    rs = np.random.RandomState(0)
    ids = jax.numpy.asarray(rs.randint(0, cfg.vocab_size, (2, 32)))
    labels = jax.numpy.asarray(rs.randint(0, cfg.vocab_size, (2, 32)))
    return AnalysisTarget("moe_llama_train_step", step_fn,
                          (params, opt_state, ids, labels))


def _serving_engine(_force_flags=(), _cfg_kwargs=None, _disable_pallas=(),
                    **kwargs):
    import contextlib
    import os
    import jax

    from ..models import llama
    from ..inference.serving import ContinuousBatchingEngine

    cfg = llama.LlamaConfig.tiny(**(_cfg_kwargs or dict(
        vocab=128, hidden=32, layers=2, heads=4, kv_heads=2, inter=64)))
    params = llama.init_params(cfg, jax.random.key(0))
    # the lint gate analyzes a feature's compiled program even when the
    # operator's kill switch (e.g. PADDLE_TPU_CHUNKED_PREFILL=0) has it off
    # at runtime — without the override the ctor would skip building the
    # program and the target builder would crash the whole gate.
    # PADDLE_TPU_GRACEFUL is forced for EVERY serving target: the graceful
    # programs carry the in-graph NaN/inf logit guard, and the host_sync
    # rule must see exactly what production traces (the guard's flags ride
    # back with the step's tokens — a callback sneaking in would be the
    # regression the gate exists to catch).  PADDLE_TPU_METRICS is forced
    # for the same reason (ISSUE 11): observability's recording contract
    # is host-side post-step — the gate analyzes the metrics-ON engine so
    # a metric recorded via callback from INSIDE a compiled step would
    # fail host_sync here, not in production.
    with contextlib.ExitStack() as stack:
        for flag in (*_force_flags, "PADDLE_TPU_GRACEFUL",
                     "PADDLE_TPU_METRICS"):
            prev = os.environ.get(flag)
            os.environ[flag] = "1"
            stack.callback(lambda f=flag, p=prev: (
                os.environ.__setitem__(f, p) if p is not None
                else os.environ.pop(f, None)))
        # the Pallas kill switches are trace-time state like the flags
        # above: every serving target pins PADDLE_TPU_DISABLE_PALLAS to
        # EXACTLY the token set it declares — serving_decode_step
        # disables flash/fused (the pre-fusion program whose lint shape
        # is locked in), serving_flash_decode_step declares none (the
        # production default), and an operator's ambient opt-out for ANY
        # kernel is cleared rather than merged: the gate only traces
        # (never executes a kernel), so ambient paged_attention must not
        # demote a target to the gather oracle, flip the ctor's fused
        # mode, or fail the budget gate spuriously.
        prev_dp = os.environ.get("PADDLE_TPU_DISABLE_PALLAS")
        tokens = set(_disable_pallas)
        if tokens:
            os.environ["PADDLE_TPU_DISABLE_PALLAS"] = ",".join(sorted(tokens))
        else:
            os.environ.pop("PADDLE_TPU_DISABLE_PALLAS", None)
        stack.callback(lambda p=prev_dp: (
            os.environ.__setitem__("PADDLE_TPU_DISABLE_PALLAS", p)
            if p is not None
            else os.environ.pop("PADDLE_TPU_DISABLE_PALLAS", None)))
        # an ambient PADDLE_TPU_TP would OVERRIDE every builder's
        # tensor_parallel (the env wins by design) — e.g. PADDLE_TPU_TP=1
        # would collapse serving_tp_step to a single-chip program whose
        # resharding gate polices nothing, and PADDLE_TPU_TP=2 would turn
        # the single-chip targets into TP engines.  The gate must analyze
        # exactly the program each target declares: clear the override.
        prev_tp = os.environ.pop("PADDLE_TPU_TP", None)
        if prev_tp is not None:
            stack.callback(lambda: os.environ.__setitem__("PADDLE_TPU_TP",
                                                          prev_tp))
        eng = ContinuousBatchingEngine(cfg, params, max_batch=2, max_seq=64,
                                       chunk=2, paged=True, block_size=8,
                                       **kwargs)
        # the pins above only cover CONSTRUCTION (this stack unwinds on
        # return) — but the kill switches are also read at TRACE time,
        # and analysis traces the target later.  Record pins on the
        # engine so the AnalysisTarget can re-apply them around
        # analyze()/build_card() (AnalysisTarget.env): otherwise an
        # ambient opt-out — or its absence — swaps which program the
        # gate traces after the builder already returned.  The pinned
        # token set is the target's DECLARED tokens only, not the
        # construction-time ambient merge: analysis is pure tracing
        # (never executes a kernel), so an operator's ambient
        # paged_attention opt-out must not demote the gate's traced
        # program to the gather oracle and fail the budget gate
        # spuriously.
        eng._lint_env = {
            **{flag: "1" for flag in (*_force_flags, "PADDLE_TPU_GRACEFUL",
                                      "PADDLE_TPU_METRICS")},
            "PADDLE_TPU_DISABLE_PALLAS": (",".join(sorted(_disable_pallas))
                                          if _disable_pallas else None),
            "PADDLE_TPU_TP": None,
        }
        return eng


def _t_serving_decode_step() -> AnalysisTarget:
    import jax.numpy as jnp

    # the PRE-fusion decode program (rope + KV scatters + sequential paged
    # kernel): its lint shape stays pinned even though production now
    # defaults to the fused/split-K path (serving_flash_decode_step below)
    eng = _serving_engine(_disable_pallas=("flash_decode",
                                           "fused_decode_step"))
    B = eng.max_batch
    tokens = jnp.zeros((B,), jnp.int32)
    pos = jnp.asarray([5, 0], jnp.int32)
    active = jnp.asarray([True, False])
    temp = jnp.zeros((B,), jnp.float32)
    topp = jnp.ones((B,), jnp.float32)
    seeds = jnp.zeros((B,), jnp.int32)
    table = jnp.asarray(eng._table)
    return AnalysisTarget(
        "serving_decode_step", eng._decode_greedy,
        (eng.params, eng.cache_k, eng.cache_v, tokens, pos, active,
         temp, topp, seeds, table), env=eng._lint_env)


def _t_serving_flash_decode_step() -> AnalysisTarget:
    import jax.numpy as jnp

    # the production-default decode program (ISSUE 10): fused rope +
    # KV-append + split-K attention with the log-sum-exp combine.  The
    # gate polices it like every hot path: the combine's f32 online-
    # softmax dots are the ONLY allowlisted upcasts (allowlist.toml), and
    # any other collective/upcast that sneaks into the fused step fails CI.
    eng = _serving_engine()
    assert eng._fused, "flash target must build the fused decode engine"
    B = eng.max_batch
    tokens = jnp.zeros((B,), jnp.int32)
    pos = jnp.asarray([5, 0], jnp.int32)
    active = jnp.asarray([True, False])
    temp = jnp.zeros((B,), jnp.float32)
    topp = jnp.ones((B,), jnp.float32)
    seeds = jnp.zeros((B,), jnp.int32)
    table = jnp.asarray(eng._table)
    return AnalysisTarget(
        "serving_flash_decode_step", eng._decode_greedy,
        (eng.params, eng.cache_k, eng.cache_v, tokens, pos, active,
         temp, topp, seeds, table), env=eng._lint_env)


def _t_serving_async_step() -> AnalysisTarget:
    import jax.numpy as jnp

    # the production decode program as the ASYNC host runtime launches it
    # (ISSUE 16, docs/async_runtime.md): PADDLE_TPU_ASYNC_HOST=1 pinned at
    # construction AND trace time.  The async runtime is host-side only —
    # journal upkeep and late token fetches never touch the jaxpr — so
    # this target's compiled program must stay IDENTICAL to
    # serving_flash_decode_step's (its budget mirrors that entry), and the
    # host_sync rule polices exactly that: a device-blocking callback or
    # sync sneaking into the overlapped step is the regression that would
    # silently serialize the pipeline again.
    eng = _serving_engine(_force_flags=("PADDLE_TPU_ASYNC_HOST",))
    assert eng._async_host, "async target must build the async-host engine"
    B = eng.max_batch
    tokens = jnp.zeros((B,), jnp.int32)
    pos = jnp.asarray([5, 0], jnp.int32)
    active = jnp.asarray([True, False])
    temp = jnp.zeros((B,), jnp.float32)
    topp = jnp.ones((B,), jnp.float32)
    seeds = jnp.zeros((B,), jnp.int32)
    table = jnp.asarray(eng._table)
    return AnalysisTarget(
        "serving_async_step", eng._decode_greedy,
        (eng.params, eng.cache_k, eng.cache_v, tokens, pos, active,
         temp, topp, seeds, table), env=eng._lint_env)


def _t_serving_quant_decode_step() -> AnalysisTarget:
    import jax.numpy as jnp

    # the quantized-pool decode program at the stage-2 default (ISSUE 15):
    # fused rope + IN-KERNEL requantized append + dequant-on-read
    # attention, plus the fused MLP layer half — scatters = 0 IS the
    # contract (a requant scatter reappearing on this path is the
    # regression the budget gate names), and the kernel-contract rule
    # verifies the quant kernel's four aliased outputs every gate run.
    eng = _serving_engine(kv_quant="int8")
    assert eng._fused and eng._fused_mlp, (
        "quant target must build the fused stage-2 engine")
    B = eng.max_batch
    tokens = jnp.zeros((B,), jnp.int32)
    pos = jnp.asarray([5, 0], jnp.int32)
    active = jnp.asarray([True, False])
    temp = jnp.zeros((B,), jnp.float32)
    topp = jnp.ones((B,), jnp.float32)
    seeds = jnp.zeros((B,), jnp.int32)
    table = jnp.asarray(eng._table)
    return AnalysisTarget(
        "serving_quant_decode_step", eng._decode_greedy,
        (eng.params, eng.cache_k, eng.cache_v, tokens, pos, active,
         temp, topp, seeds, table), env=eng._lint_env)


def _t_serving_quant_scatter_step() -> AnalysisTarget:
    import jax.numpy as jnp

    # the PINNED pre-fusion quantized decode program (the kill-switch
    # oracle arm): requant-scatter append — two scatters per pool (codes
    # + per-page scale), four per step — with sequential-kernel
    # dequant-on-read attention.  This budget freezes the fallback's
    # shape exactly like serving_decode_step does for fp pools.
    eng = _serving_engine(_disable_pallas=("flash_decode",
                                           "fused_decode_step"),
                          kv_quant="int8")
    assert not eng._fused and not eng._fused_mlp
    B = eng.max_batch
    tokens = jnp.zeros((B,), jnp.int32)
    pos = jnp.asarray([5, 0], jnp.int32)
    active = jnp.asarray([True, False])
    temp = jnp.zeros((B,), jnp.float32)
    topp = jnp.ones((B,), jnp.float32)
    seeds = jnp.zeros((B,), jnp.int32)
    table = jnp.asarray(eng._table)
    return AnalysisTarget(
        "serving_quant_scatter_step", eng._decode_greedy,
        (eng.params, eng.cache_k, eng.cache_v, tokens, pos, active,
         temp, topp, seeds, table), env=eng._lint_env)


def _t_serving_prefill_step() -> AnalysisTarget:
    import jax.numpy as jnp

    eng = _serving_engine()
    bucket = 16
    ids = jnp.zeros((1, bucket), jnp.int32)
    table_row = jnp.asarray(eng._table[0])
    length = jnp.asarray(bucket - 1, jnp.int32)

    # bucket is a static argnum of the compiled prefill: close over it so
    # the analyzed callable is purely array-in/array-out
    def prefill(params, ids, cache_k, cache_v, table_row, length):
        return eng._prefill(params, ids, cache_k, cache_v, table_row,
                            length, bucket)

    return AnalysisTarget(
        "serving_prefill_step", prefill,
        (eng.params, ids, eng.cache_k, eng.cache_v, table_row, length),
        env=eng._lint_env)


def _t_serving_verify_step() -> AnalysisTarget:
    import jax.numpy as jnp

    eng = _serving_engine(_force_flags=("PADDLE_TPU_SPECULATE",),
                          enable_speculation=True, num_draft_tokens=3)
    B = eng.max_batch
    Q = eng._spec_qmax
    # slot 0 mid-decode carrying a full draft, slot 1 idle — the exact data
    # regime the speculative hot loop runs (q_lens/active are DATA, so this
    # one trace covers every per-step raggedness)
    tokens = jnp.zeros((B, Q), jnp.int32)
    pos = jnp.asarray([5, 0], jnp.int32)
    active = jnp.asarray([True, False])
    q_lens = jnp.asarray([Q, 1], jnp.int32)
    temp = jnp.zeros((B,), jnp.float32)
    topp = jnp.ones((B,), jnp.float32)
    seeds = jnp.zeros((B,), jnp.int32)
    table = jnp.asarray(eng._table)
    return AnalysisTarget(
        "serving_verify_step", eng._verify_greedy,
        (eng.params, eng.cache_k, eng.cache_v, tokens, pos, active, q_lens,
         temp, topp, seeds, table), env=eng._lint_env)


def _t_serving_mixed_step() -> AnalysisTarget:
    import jax.numpy as jnp

    eng = _serving_engine(_force_flags=("PADDLE_TPU_CHUNKED_PREFILL",),
                          enable_chunked_prefill=True, prefill_chunk=8)
    B = eng.max_batch
    T = eng._prefill_chunk
    # slot 0 decoding (one live row), slot 1 streaming a full prefill chunk
    # — the exact mixed regime the unified step compiles once for (pos /
    # q_lens / active are DATA, so this one trace covers every token-budget
    # packing the scheduler can emit)
    tokens = jnp.zeros((B, T), jnp.int32)
    pos = jnp.asarray([5, 0], jnp.int32)
    active = jnp.asarray([True, True])
    q_lens = jnp.asarray([1, T], jnp.int32)
    temp = jnp.zeros((B,), jnp.float32)
    topp = jnp.ones((B,), jnp.float32)
    seeds = jnp.zeros((B,), jnp.int32)
    table = jnp.asarray(eng._table)
    return AnalysisTarget(
        "serving_mixed_step", eng._mixed_greedy,
        (eng.params, eng.cache_k, eng.cache_v, tokens, pos, active, q_lens,
         temp, topp, seeds, table), env=eng._lint_env)


def _t_serving_tier_restore() -> AnalysisTarget:
    import jax.numpy as jnp

    # the host-KV-tier re-admit program (ISSUE 13, docs/kv_tier.md): the
    # donated H2D pool write ship_in dispatches per restored page.  The
    # gate pins its shape — ONE in-place dynamic-update per pool, no
    # callbacks: the H2D itself happens OUTSIDE jit (jnp.asarray on the
    # host payload), so the compiled program must stay host_sync-clean,
    # and a device-to-host sync sneaking into the restore hot path is
    # exactly the regression this target exists to catch.
    eng = _serving_engine(
        _force_flags=("PADDLE_TPU_PREFIX_CACHE", "PADDLE_TPU_HOST_KV_TIER"),
        enable_prefix_caching=True, enable_host_kv_tier=True)
    assert eng._tier is not None, "tier target must build the tier engine"
    L, _nb, nkv, bs, hd = eng.cache_k.shape
    page = jnp.zeros((L, nkv, bs, hd), eng.cfg.dtype)
    dst = jnp.asarray(0, jnp.int32)
    return AnalysisTarget(
        "serving_tier_restore", eng._tier_write,
        (eng.cache_k, dst, page), env=eng._lint_env)


def _t_serving_tp_step() -> AnalysisTarget:
    import jax
    import jax.numpy as jnp

    if jax.device_count() < 2:
        # RuntimeError, not SystemExit: lint_gate.py's per-target handler
        # must classify this as "FAILED to build/trace" (exit 2) instead
        # of the exception tunneling past it — both CLI entry points force
        # an 8-device host platform pre-init, so this only fires when the
        # backend initialized single-device before the gate ran
        raise RuntimeError(
            "serving_tp_step needs >= 2 devices; run under the test "
            "harness (tests/conftest.py forces 8 CPU devices) or set "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8")
    # the TP mixed prefill/decode step over a 2-shard ("tp",) mesh — the
    # one compiled program whose collectives the resharding rule must
    # police (ISSUE 8).  Config sized so the layer's psum operand
    # [B, T, h] (2*64*512 bf16 = 128 KiB) clears the target's lowered
    # gather threshold: the production bar is >=1MiB, and a lint-sized
    # model translates it the way every target here translates shape
    # bounds — same rule, proportionally smaller floor (analyze_kwargs).
    eng = _serving_engine(
        _force_flags=("PADDLE_TPU_CHUNKED_PREFILL",),
        _cfg_kwargs=dict(vocab=128, hidden=512, layers=2, heads=4,
                         kv_heads=2, inter=256),
        enable_chunked_prefill=True, prefill_chunk=64, tensor_parallel=2)
    B = eng.max_batch
    T = eng._prefill_chunk
    tokens = jnp.zeros((B, T), jnp.int32)
    pos = jnp.asarray([5, 0], jnp.int32)
    active = jnp.asarray([True, True])
    q_lens = jnp.asarray([1, T], jnp.int32)
    temp = jnp.zeros((B,), jnp.float32)
    topp = jnp.ones((B,), jnp.float32)
    seeds = jnp.zeros((B,), jnp.int32)
    table = jnp.asarray(eng._table)
    return AnalysisTarget(
        "serving_tp_step", eng._mixed_greedy,
        (eng.params, eng.cache_k, eng.cache_v, tokens, pos, active, q_lens,
         temp, topp, seeds, table),
        analyze_kwargs={"min_gather_bytes": 1 << 16}, env=eng._lint_env)


TARGETS = {
    "llama_train_step": _t_llama_train_step,
    "moe_llama_train_step": _t_moe_train_step,
    "serving_decode_step": _t_serving_decode_step,
    "serving_flash_decode_step": _t_serving_flash_decode_step,
    "serving_quant_decode_step": _t_serving_quant_decode_step,
    "serving_quant_scatter_step": _t_serving_quant_scatter_step,
    "serving_prefill_step": _t_serving_prefill_step,
    "serving_verify_step": _t_serving_verify_step,
    "serving_mixed_step": _t_serving_mixed_step,
    "serving_tier_restore": _t_serving_tier_restore,
    "serving_tp_step": _t_serving_tp_step,
    "serving_async_step": _t_serving_async_step,
}

# the CI gate runs every registered target; kept as an explicit list so an
# expensive future target (multi-device compile) can register without
# slowing the tier-1 suite
GATE_TARGETS = ("llama_train_step", "moe_llama_train_step",
                "serving_decode_step", "serving_flash_decode_step",
                "serving_quant_decode_step", "serving_quant_scatter_step",
                "serving_prefill_step", "serving_verify_step",
                "serving_mixed_step", "serving_tier_restore",
                "serving_tp_step", "serving_async_step")

# targets that serve from the async host runtime: these additionally run
# the module-scoped host-contract pass (host_contracts.py) — overlap-window
# race/blocking analysis + state-machine protocol verification.  Train
# steps have no host runtime, so they skip it; the pass is memoized, so
# the N serving targets share one AST run per gate sweep.
HOST_TARGETS = tuple(n for n in GATE_TARGETS if n.startswith("serving_"))


def build(name: str) -> AnalysisTarget:
    try:
        builder = TARGETS[name]
    except KeyError:
        raise SystemExit(
            f"unknown target {name!r}; registered: {sorted(TARGETS)}") \
            from None
    return builder()


def run(name: str, **overrides):
    """Build and analyze one registered target (under its env pins — the
    trace must see exactly the program the target declares)."""
    from . import analyze

    t = build(name)
    kwargs = {**t.analyze_kwargs, **overrides}
    kwargs.setdefault("host", t.name in HOST_TARGETS)
    with _pinned_env(t.env):
        return analyze(t.fn, *t.args, target=t.name, **kwargs)


def run_card(name: str, **card_kwargs):
    """Build one registered target and derive just its ProgramCard —
    the cards-only path (``--cards`` CLI, the card-gate tier-1 test): no
    lint rules, no perturbation re-traces; multi-device targets still pay
    one compile for the collective-bytes attribution unless
    ``compile_collectives=False``.  Runs under the target's env pins like
    :func:`run`."""
    from .cost_model import build_card

    t = build(name)
    if name in HOST_TARGETS and "host_contracts" not in card_kwargs:
        from .host_contracts import check_host_contracts

        card_kwargs["host_contracts"] = \
            check_host_contracts(target=name)[1]
    with _pinned_env(t.env):
        return build_card(t.fn, t.args, target=t.name, **card_kwargs)
