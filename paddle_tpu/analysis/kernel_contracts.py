"""Kernel contracts: static verification of every ``pallas_call``.

The decode megakernel roadmap (ROADMAP item 3, MPK stage 2) collapses ever
more of the decode step into single Pallas programs — exactly the regime
where a hand-fused kernel gets correctness wrong *silently*: an index map
that walks one page past the table reads another request's KV, two grid
points writing the same output block race, and an alias pair whose shapes
drift corrupts the pool in place.  None of that is visible to the lint
rules or the program card, which treat a ``pallas_call`` as an opaque
launch.  This module opens the launch: for each ``pallas_call`` eqn in an
already-traced program (the ONE ClosedJaxpr the lint/cards pass produces —
zero extra traces, zero compiles) it extracts the grid, BlockSpec index
maps, scratch shapes, and ``input_output_aliases``, and proves three
contract families by concrete enumeration of the grid:

``kernel_bounds``
    every evaluated index map x block shape stays inside its operand for
    every sampled grid point.  Index maps that read scalar-prefetch
    operands (block tables, write pages) are data-dependent: they are
    evaluated under adversarial valuations — all-zero, a distinct ramp,
    ``+BIG`` and ``-BIG`` fills — so a map is only clean when it clamps,
    i.e. when NO runtime table content can take it out of bounds.  This
    catches the off-by-one page walk and the ragged-tail overread.

``kernel_race`` / ``kernel_lost_write``
    each output's index map must be injective across grid points.
    Revisits are legal only when they are deterministic on TPU: along
    sequential (non-``parallel``) grid axes when the revisits are
    CONSECUTIVE in iteration order (the accumulate-then-finalize pattern
    — the block stays resident in VMEM, e.g. the split-K ``_flash_kernel``
    partials), or when the output block is readable (input-aliased, or
    the kernel body reads the output ref).  Two grid points separated
    along a ``parallel``-declared axis writing one block is a race
    (``kernel_race``); a non-consecutive sequential revisit of a
    write-only, unaliased block is a lost write (``kernel_lost_write``)
    — the earlier visit's bytes are flushed and clobbered.

``kernel_alias``
    every ``input_output_aliases`` pair must agree in aval (shape/dtype —
    pallas itself enforces this at trace time; re-checked for
    defense-in-depth) AND in block geometry (pallas does NOT check that:
    an aliased pair whose BlockSpecs drifted writes different elements
    than were read), and no input spec on the aliased buffer may map
    blocks overlapping the aliased output's written blocks at a
    *different* grid point — the exact failure mode a fused
    append+attention megakernel risks (the fused decode kernel's
    deliberate masked tail re-fetch of the write page is the live,
    allowlisted instance; see ``allowlist.toml``).

Enumeration is full up to a cap (default 2048 grid points; the validated
``PADDLE_TPU_KERNEL_VERIFY_SAMPLES`` env knob overrides, utils/envflags),
and deterministic corner-plus-stratified sampling above it: every corner
of the grid plus evenly spaced linear indices — no RNG, so CI findings
are reproducible.  Findings flow through the same severity/allowlist
machinery as every lint rule; per-kernel results land as the
``kernel_contracts`` section on each ProgramCard with the
``kernel_contract_violations`` count budgeted in ``budgets.toml``
(docs/analysis.md §"Kernel contracts").

Also here: :func:`registry_drift_findings`, the KNOWN_KERNELS drift lint
— ``envflags``'s kill-switch vocabulary cross-referenced against the
``kernel_disabled("...")`` call sites actually dispatched in the package
(AST-level, so docstrings/comments don't count), in both directions: a
renamed or retired kernel must not leave a dead kill switch behind, and
a new kernel's opt-out must be registered so typos get the did-you-mean.
"""

from __future__ import annotations

import itertools
import os

import numpy as np

from .report import Finding, Severity

__all__ = ["check_kernel_contracts", "contracts_summary",
           "registry_drift_findings", "verify_samples_cap",
           "DEFAULT_SAMPLES_CAP"]

#: default grid-point enumeration cap (full enumeration at or below it);
#: override with PADDLE_TPU_KERNEL_VERIFY_SAMPLES (validated env_int)
DEFAULT_SAMPLES_CAP = 2048
#: adversarial fill for data-dependent (scalar-prefetch) index maps: far
#: past any real operand extent, small enough that idx * block_size stays
#: inside int64 (and any in-map int32 arithmetic does not wrap)
_BIG = 1 << 20
#: ceiling on enumerated grid corners when sampling (2^ndim corners on a
#: high-rank grid would otherwise eat the whole sample budget)
_CORNER_CAP = 256


def verify_samples_cap() -> int:
    """The grid enumeration cap: full enumeration up to this many grid
    points, deterministic corner-plus-stratified sampling above it.
    ``PADDLE_TPU_KERNEL_VERIFY_SAMPLES`` overrides (validated integer,
    minimum 16 — a sub-minimum or non-integer value warns once and keeps
    the default, utils/envflags.env_int)."""
    from ..utils.envflags import env_int

    return env_int("PADDLE_TPU_KERNEL_VERIFY_SAMPLES", DEFAULT_SAMPLES_CAP,
                   minimum=16)


# ---------------------------------------------------------------------------
# geometry extraction
# ---------------------------------------------------------------------------

def _pallas_eqns(closed):
    """Every ``pallas_call`` eqn in the program — the ONE shared walk
    (``rules.iter_pallas_eqns``) the VMEM census also uses, so the two
    can never disagree about which launches exist."""
    from .rules import iter_pallas_eqns

    return list(iter_pallas_eqns(closed))


def _kernel_name(eqn) -> str:
    nsi = eqn.params.get("name_and_src_info")
    name = getattr(nsi, "name", "") or (str(nsi) if nsi is not None else "")
    return name or "<unnamed>"


def _dim_semantics(eqn, ngrid: int) -> tuple:
    """Per-grid-axis semantics ('parallel' or 'arbitrary').  Mosaic's
    default when ``dimension_semantics`` is not declared is 'arbitrary'
    (sequential) — the conservative direction for the race check: a
    revisit on an undeclared axis is judged by the consecutive-run rule,
    not condemned as a parallel race."""
    cp = eqn.params.get("compiler_params") or {}
    sem = None
    mosaic = cp.get("mosaic") if isinstance(cp, dict) else None
    if isinstance(mosaic, dict):
        sem = mosaic.get("dimension_semantics")
    elif mosaic is not None:
        sem = getattr(mosaic, "dimension_semantics", None)
    if sem is None:
        return ("arbitrary",) * ngrid
    sem = tuple(str(s) for s in sem)
    return sem + ("arbitrary",) * (ngrid - len(sem))


def _sample_grid(grid, cap: int):
    """Deterministic grid-point sample: every point when the grid fits the
    cap, else every corner (all-{0, dim-1} combinations, capped) plus
    evenly spaced linear indices.  Returns (points [N, ndim] int64 in
    C-order linear-index order, sampled: bool, total: int)."""
    dims = [int(d) for d in grid]
    total = 1
    for d in dims:
        total *= d
    if not dims:
        return np.zeros((1, 0), np.int64), False, 1
    if total <= 0:
        return np.zeros((0, len(dims)), np.int64), False, 0
    if total <= cap:
        lin = np.arange(total, dtype=np.int64)
        sampled = False
    else:
        corners = []
        for combo in itertools.product(*[sorted({0, d - 1}) for d in dims]):
            corners.append(int(np.ravel_multi_index(combo, dims)))
            if len(corners) >= _CORNER_CAP:
                break
        strat = np.linspace(0, total - 1,
                            max(cap - len(corners), 2)).astype(np.int64)
        lin = np.unique(np.concatenate(
            [np.asarray(corners, np.int64), strat]))
        sampled = True
    pts = np.stack(np.unravel_index(lin, dims), axis=1).astype(np.int64)
    return pts, sampled, total


def _prefetch_valuations(eqn, n_prefetch: int):
    """Adversarial value sets for the scalar-prefetch operands (the block
    tables / lengths / write pages the index maps may read).  Ordered
    least-coincidental first: the 'ramp' (all-distinct, in-plausible-range)
    valuation models healthy runtime data; 'zero' models maximal
    coincidence (every slot sharing page 0 — how shared write/spill pages
    surface); 'max'/'min' are the out-of-range extremes only a clamped map
    survives.  Empty when the kernel prefetches nothing (one 'static'
    evaluation suffices)."""
    if not n_prefetch:
        return [("static", [])]
    avals = [v.aval for v in eqn.invars[:n_prefetch]]

    def fill(val):
        return [np.full(a.shape, val, dtype=np.dtype(a.dtype))
                for a in avals]

    ramps = []
    for a in avals:
        size = int(np.prod(a.shape, dtype=np.int64)) if a.shape else 1
        ramps.append(np.arange(size, dtype=np.dtype(a.dtype))
                     .reshape(a.shape))
    return [("ramp", ramps), ("zero", fill(0)), ("max", fill(_BIG)),
            ("min", fill(-_BIG))]


def _eval_index_map(bm, pts: np.ndarray, prefetch_vals):
    """Evaluate one BlockSpec index map at every sampled grid point —
    vectorized: the (discharged) index-map jaxpr is vmapped over the grid
    coordinates with the prefetch values broadcast, so the whole batch is
    a handful of eager CPU ops, not one interpreter pass per point.
    Returns int64 [N, n_block_dims] block indices."""
    import jax
    import jax.numpy as jnp
    from jax import core as jcore
    from jax._src.state.discharge import discharge_state

    cj = bm.index_map_jaxpr
    ds_jaxpr, ds_consts = discharge_state(cj.jaxpr, cj.consts)
    n_idx = len(bm.block_shape)
    ngrid = pts.shape[1]
    pf = [jnp.asarray(v) for v in prefetch_vals]

    def run(gi):
        args = [gi[a] for a in range(ngrid)] + pf
        out = jcore.eval_jaxpr(ds_jaxpr, ds_consts, *args)
        # discharge appends the final ref values after the original outs
        return jnp.stack([jnp.asarray(o).astype(jnp.int32)
                          for o in out[:n_idx]])

    if ngrid == 0:
        res = run(jnp.zeros((0,), jnp.int32))[None]
    else:
        res = jax.vmap(run)(jnp.asarray(pts, jnp.int32))
    return np.asarray(res, np.int64)


def _block_steps(bm):
    """Per-dim (step, extent-valid?) multipliers: a Blocked dim's index is
    in block units (element offset = idx * size); squeezed/mapped dims
    (non-int block entries) index single elements (step 1)."""
    return tuple(int(d) if isinstance(d, int) else 1
                 for d in (bm.block_shape or ()))


def _operand_label(bms, k: int, n_inputs: int) -> str:
    bm = bms[k]
    origin = getattr(bm, "origin", "") or ""
    if k < n_inputs:
        return f"input {k}" + (f" ({origin})" if origin else "")
    return f"output {k - n_inputs}" + (f" ({origin})" if origin else "")


def _outputs_read(eqn, gm) -> list[bool]:
    """Which output refs the kernel body READS (``get``, ``addupdate``, or
    a ``swap`` whose old value is used) — the 'accumulated' half of the
    revisit escape.  Tracks the output ref vars through cond bodies
    (``pl.when``) and 1:1 sub-jaxprs; an untrackable operand mapping is
    treated as read (conservative: suppresses a finding rather than
    inventing one)."""
    from jax._src import core as jcore

    from .rules import _sub_jaxprs

    kjx = eqn.params.get("jaxpr")
    jx = kjx.jaxpr if hasattr(kjx, "jaxpr") else kjx
    n0 = gm.num_index_operands + gm.num_inputs
    n_out = gm.num_outputs
    read = [False] * n_out
    if jx is None or len(jx.invars) < n0 + n_out:
        return [True] * n_out

    def walk(j, env):
        for e in j.eqns:
            prim = e.primitive.name
            hit = [env[v] for v in e.invars
                   if not isinstance(v, jcore.Literal) and v in env]
            if hit:
                if prim in ("get", "addupdate"):
                    for oi in hit:
                        read[oi] = True
                elif prim == "swap" and any(
                        not isinstance(ov, jcore.DropVar)
                        for ov in e.outvars):
                    for oi in hit:
                        read[oi] = True
            subs = _sub_jaxprs(e)
            for sub in subs:
                if prim == "cond" and len(sub.invars) == len(e.invars) - 1:
                    pairs = zip(sub.invars, e.invars[1:])
                elif len(sub.invars) == len(e.invars):
                    pairs = zip(sub.invars, e.invars)
                else:
                    for oi in hit:   # unknown mapping: assume read
                        read[oi] = True
                    continue
                walk(sub, {sv: env[v] for sv, v in pairs
                           if not isinstance(v, jcore.Literal)
                           and v in env})

    walk(jx, {v: i for i, v in enumerate(jx.invars[n0:n0 + n_out])})
    return read


# ---------------------------------------------------------------------------
# the three contract families
# ---------------------------------------------------------------------------

def _check_bounds(kname, where, target, label, bm, vname, idx, pts,
                  data_dependent) -> Finding | None:
    """First out-of-bounds sampled grid point of one (mapping, valuation),
    or None.  Blocked dims: block index b is valid iff 0 <= b and
    b * block_size < dim (partial edge blocks are legal — pallas pads)."""
    steps = _block_steps(bm)
    shape = tuple(getattr(bm.array_shape_dtype, "shape", ()))
    # rank agreement is guaranteed by the caller: _verify_eqn pre-filters
    # rank-mismatched operands into the eval_failed/'unchecked' path
    # before this runs, and _eval_index_map emits exactly
    # len(block_shape) indices per point — a silent early-return here
    # would be the clean-verdict-without-checking outcome the unchecked
    # policy forbids
    starts = idx * np.asarray(steps, np.int64)[None, :]
    bad = (idx < 0) | (starts >= np.asarray(shape, np.int64)[None, :])
    rows = np.nonzero(bad.any(axis=1))[0]
    if not rows.size:
        return None
    r = int(rows[0])
    d = int(np.nonzero(bad[r])[0][0])
    pt = tuple(int(x) for x in pts[r])
    via = (f" under scalar-prefetch valuation '{vname}' (data-dependent "
           f"map: only a clamped map is safe for all runtime data)"
           if data_dependent else "")
    return Finding(
        rule="kernel_bounds", severity=Severity.ERROR,
        message=(f"pallas kernel {kname}: index map of {label} leaves the "
                 f"operand at grid point {pt}: block index "
                 f"{tuple(int(x) for x in idx[r])} x block "
                 f"{tuple(bm.block_shape)} exceeds operand shape "
                 f"{shape} on axis {d}{via}"),
        where=where, target=target)


def _revisit_groups(idx: np.ndarray):
    """Group sampled points by written block: yields (block_tuple,
    member_rows) for every block written by more than one sampled point."""
    _, inv, counts = np.unique(idx, axis=0, return_inverse=True,
                               return_counts=True)
    for g in np.nonzero(counts > 1)[0]:
        rows = np.nonzero(inv == g)[0]
        yield tuple(int(x) for x in idx[rows[0]]), rows


def _check_races(kname, where, target, label, vname, idx, pts, lin,
                 sem, aliased, reads_out, data_dependent):
    """kernel_race / kernel_lost_write findings for one output mapping
    under one valuation (at most one of each)."""
    race = lost = None
    for blk, rows in _revisit_groups(idx):
        sub = pts[rows]
        varying = [a for a in range(pts.shape[1])
                   if sub[:, a].max() != sub[:, a].min()]
        par = [a for a in varying if sem[a] == "parallel"]
        coinc = (f" (runtime scalar-prefetch data coinciding — valuation "
                 f"'{vname}')" if data_dependent and vname != "ramp" else "")
        if par:
            # a parallel-axis collision is ALWAYS a race — later groups
            # must not fall through to the sequential lost-write logic
            # just because an earlier group already produced the (one
            # reported) race finding for this output
            if race is None:
                # cite a pair that actually exhibits the race: the group
                # members at the extremes of the parallel axis (sub[0] vs
                # sub[-1] could coincide on it when a third axis varies)
                lo = int(np.argmin(sub[:, par[0]]))
                hi = int(np.argmax(sub[:, par[0]]))
                p0, p1 = (tuple(int(x) for x in sub[lo]),
                          tuple(int(x) for x in sub[hi]))
                race = Finding(
                    rule="kernel_race", severity=Severity.ERROR,
                    message=(f"pallas kernel {kname}: {label} block {blk} "
                             f"is written by grid points {p0} and {p1}, "
                             f"which differ along parallel grid axis "
                             f"{par[0]} — concurrent grid points racing "
                             f"on one output block{coinc}"),
                    where=where, target=target)
            continue
        if race is not None and lost is not None:
            break
        # sequential revisit: legal when consecutive in iteration order
        # (block stays VMEM-resident: accumulate/finalize), or when the
        # block is readable (input-aliased / kernel reads the out ref)
        li = lin[rows]
        inside = (lin >= li.min()) & (lin <= li.max())
        consecutive = int(inside.sum()) == rows.size
        if consecutive or aliased or reads_out or lost is not None:
            continue
        p0, p1 = (tuple(int(x) for x in sub[0]),
                  tuple(int(x) for x in sub[-1]))
        lost = Finding(
            rule="kernel_lost_write", severity=Severity.WARNING,
            message=(f"pallas kernel {kname}: {label} block {blk} is "
                     f"revisited non-consecutively (grid points {p0} and "
                     f"{p1} with other blocks written in between) and the "
                     f"block is write-only (not input-aliased, never read "
                     f"in-kernel) — the earlier visit's bytes are flushed "
                     f"then clobbered{coinc}"),
            where=where, target=target)
    return race, lost


def _check_alias_pair(kname, where, target, eqn, gm, bms, gi, oj,
                      results, valuations, pts, data_dependent):
    """Contract checks for one ``input_output_aliases`` pair: aval match,
    block-geometry match, and read/write block overlap on the shared
    buffer at distinct grid points."""
    findings = []
    npf, n_in = gm.num_index_operands, gm.num_inputs
    in_k = gi - npf
    if not (0 <= in_k < n_in) or not (0 <= oj < gm.num_outputs):
        return [Finding(
            rule="kernel_alias", severity=Severity.ERROR,
            message=(f"pallas kernel {kname}: input_output_aliases pair "
                     f"({gi}, {oj}) does not name a (non-prefetch input, "
                     f"output) operand pair"),
            where=where, target=target)]
    bm_in, bm_out = bms[in_k], bms[n_in + oj]
    in_label = _operand_label(bms, in_k, n_in)
    out_label = _operand_label(bms, n_in + oj, n_in)
    a_in = getattr(eqn.invars[gi], "aval", None)
    a_out = getattr(eqn.outvars[oj], "aval", None)
    if (a_in is not None and a_out is not None
            and (tuple(a_in.shape) != tuple(a_out.shape)
                 or str(a_in.dtype) != str(a_out.dtype))):
        findings.append(Finding(
            rule="kernel_alias", severity=Severity.ERROR,
            message=(f"pallas kernel {kname}: alias pair {in_label} -> "
                     f"{out_label} mismatches: {a_in.str_short()} aliased "
                     f"to {a_out.str_short()} — in-place write through a "
                     f"different shape/dtype corrupts the buffer"),
            where=where, target=target))
    if tuple(bm_in.block_shape) != tuple(bm_out.block_shape):
        findings.append(Finding(
            rule="kernel_alias", severity=Severity.ERROR,
            message=(f"pallas kernel {kname}: alias pair {in_label} -> "
                     f"{out_label} block geometry drifted: input blocks "
                     f"{tuple(bm_in.block_shape)} vs output blocks "
                     f"{tuple(bm_out.block_shape)} — the in-place write "
                     f"lands on different elements than the read fetched"),
            where=where, target=target))
        return findings
    # readers of the SAME buffer: the aliased input itself, plus any other
    # input operand bound to the same traced value (the pool passed twice)
    readers = [in_k] + [k for k in range(n_in) if k != in_k
                        and eqn.invars[npf + k] is eqn.invars[gi]]
    out_key = n_in + oj
    for rk in readers:
        hit = None
        for vname, _ in valuations:
            w_idx = results.get((out_key, vname))
            r_idx = results.get((rk, vname))
            if w_idx is None or r_idx is None:
                continue
            wmap: dict = {}
            for r, blk in enumerate(map(tuple, w_idx.tolist())):
                wmap.setdefault(blk, []).append(r)
            for r, blk in enumerate(map(tuple, r_idx.tolist())):
                ws = wmap.get(blk)
                if ws is None:
                    continue
                other = next((w for w in ws if w != r), None)
                if other is not None:
                    hit = (vname, blk, r, other)
                    break
            if hit:
                break
        if hit is None:
            continue
        vname, blk, r, w = hit
        coinc = f" (valuation '{vname}')" if data_dependent else ""
        findings.append(Finding(
            rule="kernel_alias", severity=Severity.WARNING,
            message=(f"pallas kernel {kname}: {_operand_label(bms, rk, n_in)} "
                     f"at grid point {tuple(int(x) for x in pts[r])} reads "
                     f"block {blk} of the buffer aliased to {out_label}, "
                     f"which grid point {tuple(int(x) for x in pts[w])} "
                     f"writes in place — a read at a different grid point "
                     f"than the write observes updated bytes{coinc}"),
            where=where, target=target))
    return findings


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------

def check_kernel_contracts(closed, target: str = "", samples: int | None
                           = None) -> tuple[list[Finding], list[dict]]:
    """Verify every ``pallas_call`` in an already-traced program.

    Returns ``(findings, sections)``: the findings feed the report /
    allowlist machinery like any lint rule's; ``sections`` is the
    per-kernel ``kernel_contracts`` detail the ProgramCard embeds (one
    dict per launch site: kernel, grid, points checked, sampled flag,
    per-family verdicts, finding count).  Reuses the caller's trace —
    this function never traces or compiles the target."""
    cap = samples if samples is not None else verify_samples_cap()
    findings: list[Finding] = []
    sections: list[dict] = []
    for eqn in _pallas_eqns(closed):
        f, s = _verify_eqn(eqn, target, cap)
        findings += f
        sections.append(s)
    return findings, sections


def _verify_eqn(eqn, target: str, cap: int):
    from .rules import _where

    gm = eqn.params["grid_mapping"]
    kname = _kernel_name(eqn)
    where = _where(eqn)
    grid = tuple(int(d) if isinstance(d, int) else -1
                 for d in (gm.grid or ()))
    section = {"kernel": kname, "where": where, "grid": grid,
               "grid_points": 0, "points_checked": 0, "sampled": False,
               "data_dependent": False, "bounds": "ok", "race": "ok",
               "alias": "ok", "findings": 0}
    if getattr(gm, "num_dynamic_grid_bounds", 0) or any(d < 0 for d in grid):
        section.update(bounds="skipped", race="skipped", alias="skipped")
        return [Finding(
            rule="kernel_bounds", severity=Severity.INFO,
            message=(f"pallas kernel {kname}: dynamic grid bounds — "
                     f"contracts cannot be enumerated statically"),
            where=where, target=target)], section

    pts, sampled, total = _sample_grid(grid, cap)
    lin = (np.ravel_multi_index(pts.T, grid) if grid
           else np.zeros((pts.shape[0],), np.int64))
    section.update(grid_points=total, points_checked=int(pts.shape[0]),
                   sampled=sampled)
    sem = _dim_semantics(eqn, len(grid))
    npf, n_in, n_out = (gm.num_index_operands, gm.num_inputs,
                        gm.num_outputs)
    bms = list(gm.block_mappings)
    valuations = _prefetch_valuations(eqn, npf)
    aliases = [(int(i), int(o))
               for i, o in (eqn.params.get("input_output_aliases") or ())]
    aliased_outs = {o for _, o in aliases}
    reads_out = _outputs_read(eqn, gm)

    findings: list[Finding] = []
    # evaluate every mapping under every valuation once; all checks share
    # the result table
    results: dict = {}
    data_dep = [False] * len(bms)
    eval_failed: set[int] = set()
    for k, bm in enumerate(bms):
        base = None
        for vname, vals in valuations:
            try:
                idx = _eval_index_map(bm, pts, vals)
            except Exception as e:   # unexpected index-map structure:
                findings.append(Finding(   # skip VISIBLY, never silently
                    rule="kernel_bounds", severity=Severity.INFO,
                    message=(f"pallas kernel {kname}: index map of "
                             f"{_operand_label(bms, k, n_in)} could not be "
                             f"evaluated ({type(e).__name__}: "
                             f"{str(e)[:80]}) — contracts unchecked for "
                             f"this operand"),
                    where=where, target=target))
                eval_failed.add(k)
                break
            results[(k, vname)] = idx
            if base is None:
                base = idx
            elif not np.array_equal(base, idx):
                data_dep[k] = True
    # geometry the bounds check cannot interpret (BlockSpec rank differing
    # from the operand rank — unblocked/ANY-space refs a future megakernel
    # style may introduce) is UNCHECKED, not silently 'ok': same policy as
    # an evaluation failure
    for k, bm in enumerate(bms):
        if k in eval_failed:
            continue
        steps = _block_steps(bm)
        shape = tuple(getattr(bm.array_shape_dtype, "shape", ()))
        if len(steps) != len(shape):
            findings.append(Finding(
                rule="kernel_bounds", severity=Severity.INFO,
                message=(f"pallas kernel {kname}: {_operand_label(bms, k, n_in)} "
                         f"block geometry rank {len(steps)} does not match "
                         f"operand rank {len(shape)} — bounds unchecked "
                         f"for this operand"),
                where=where, target=target))
            eval_failed.add(k)
    section["data_dependent"] = any(data_dep)

    # --- bounds: every mapping, every valuation --------------------------
    for k, bm in enumerate(bms):
        if k in eval_failed:
            continue
        label = _operand_label(bms, k, n_in)
        for vname, _ in valuations:
            idx = results.get((k, vname))
            if idx is None:
                continue
            f = _check_bounds(kname, where, target, label, bm, vname, idx,
                              pts, data_dep[k])
            if f is not None:
                findings.append(f)
                section["bounds"] = "violated"
                break   # one bounds finding per operand

    # --- write races: output mappings only -------------------------------
    for j in range(n_out):
        k = n_in + j
        label = _operand_label(bms, k, n_in)
        race = lost = None
        for vname, _ in valuations:
            idx = results.get((k, vname))
            if idx is None:
                continue
            r, lw = _check_races(kname, where, target, label,
                                 vname, idx, pts, lin, sem,
                                 aliased=j in aliased_outs,
                                 reads_out=reads_out[j],
                                 data_dependent=data_dep[k])
            race = race or r
            lost = lost or lw
            if race is not None and lost is not None:
                break
        for f in (race, lost):
            if f is not None:
                findings.append(f)
                section["race"] = "violated"

    # --- alias contracts --------------------------------------------------
    for gi, oj in aliases:
        fs = _check_alias_pair(kname, where, target, eqn, gm, bms, gi, oj,
                               results, valuations, pts,
                               data_dependent=any(data_dep))
        if fs:
            findings += fs
            section["alias"] = "violated"

    # an operand whose map could not be evaluated leaves its families
    # UNCHECKED, never "ok": the cards-only gate, decode_step_card(), and
    # bench detail drop info findings, so the verdict itself must carry
    # the downgrade or an unverified kernel would present as clean
    if eval_failed:
        section["unchecked_operands"] = len(eval_failed)
        affected = {"bounds"}
        if any(k >= n_in for k in eval_failed):
            affected.add("race")
        if aliases:
            affected.add("alias")
        for fam in affected:
            if section[fam] == "ok":
                section[fam] = "unchecked"
    section["findings"] = sum(1 for f in findings
                              if f.severity != Severity.INFO)
    return findings, section


def contracts_summary(sections: list) -> dict:
    """Aggregate of the per-kernel sections for card summaries / bench
    rung detail: launch-site count, grid points checked, whether any
    kernel was sampled (vs fully enumerated), and the violation count
    (``kernel_contract_violations`` is the budgeted figure)."""
    return {"kernels": len(sections),
            "points_checked": sum(s.get("points_checked", 0)
                                  for s in sections),
            "sampled": any(s.get("sampled") for s in sections),
            "unchecked_operands": sum(s.get("unchecked_operands", 0)
                                      for s in sections),
            "violations": sum(s.get("findings", 0) for s in sections)}


# ---------------------------------------------------------------------------
# KNOWN_KERNELS drift (the dead-kill-switch lint)
# ---------------------------------------------------------------------------

def _dispatched_kernel_tokens(root: str | None = None) -> dict[str, str]:
    """Kernel names actually dispatched: every ``kernel_disabled("<name>")``
    call in the package source, AST-level (a mention in a docstring or
    comment is NOT a dispatch site).  Returns {token: 'file.py:line'}."""
    import ast

    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    found: dict[str, str] = {}
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            try:
                with open(path) as f:
                    tree = ast.parse(f.read())
            except (OSError, SyntaxError):
                continue
            rel = os.path.relpath(path, root)
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call):
                    continue
                fname = (node.func.id if isinstance(node.func, ast.Name)
                         else node.func.attr
                         if isinstance(node.func, ast.Attribute) else "")
                if fname != "kernel_disabled" or not node.args:
                    continue
                arg = node.args[0]
                if isinstance(arg, ast.Constant) and isinstance(arg.value,
                                                                str):
                    found.setdefault(arg.value, f"{rel}:{node.lineno}")
    return found


def registry_drift_findings(root: str | None = None) -> list[Finding]:
    """Cross-reference ``envflags``' kill-switch vocabulary
    (``ops/pallas/__init__.KNOWN_KERNELS``) against the kernel names the
    package actually guards with ``kernel_disabled(...)`` — both ways:

    * a registered token with NO dispatch site is a DEAD kill switch — a
      renamed/retired kernel left its opt-out behind, and an operator
      setting it mid-incident disables nothing (silently, since the
      token still parses as known);
    * a dispatch site whose token is NOT registered loses the typo guard
      — ``PADDLE_TPU_DISABLE_PALLAS`` values near it would warn as
      unknown even when the operator spelled the real switch correctly.

    Warnings here; ``tools/lint_gate.py --strict-allowlist`` gates on
    them exactly like stale allowlist entries."""
    from ..ops.pallas import KNOWN_KERNELS

    dispatched = _dispatched_kernel_tokens(root)
    findings = []
    for token in sorted(set(KNOWN_KERNELS) - {"all"} - set(dispatched)):
        findings.append(Finding(
            rule="kernel_registry", severity=Severity.WARNING,
            message=(f"KNOWN_KERNELS registers {token!r} but no "
                     f"kernel_disabled({token!r}) dispatch site exists — "
                     f"a dead kill switch: delete the token (or wire the "
                     f"kernel's dispatch through kernel_disabled)"),
            where="ops/pallas/__init__.py"))
    for token in sorted(set(dispatched) - set(KNOWN_KERNELS)):
        findings.append(Finding(
            rule="kernel_registry", severity=Severity.WARNING,
            message=(f"kernel_disabled({token!r}) is dispatched but the "
                     f"token is not in KNOWN_KERNELS — register it so "
                     f"PADDLE_TPU_DISABLE_PALLAS typo detection covers "
                     f"it"),
            where=dispatched[token]))
    return findings
