"""paddle.hub (reference: python/paddle/hub.py) — hubconf.py protocol.

Supports ``source='local'`` fully (load entrypoints from a directory's
hubconf.py).  ``source='github'/'gitee'`` requires network egress, which this
build intentionally does not have: a clear error tells the user to clone the
repo and use the local path instead.
"""

from __future__ import annotations

import importlib.util
import os
import sys

__all__ = ["list", "help", "load"]

_builtin_list = list


def _load_hubconf(repo_dir: str):
    path = os.path.join(repo_dir, "hubconf.py")
    if not os.path.isfile(path):
        raise FileNotFoundError(f"no hubconf.py under {repo_dir!r}")
    spec = importlib.util.spec_from_file_location("paddle_tpu_hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    sys.path.insert(0, repo_dir)
    try:
        spec.loader.exec_module(mod)
    finally:
        sys.path.remove(repo_dir)
    return mod


def _check_source(source: str):
    if source != "local":
        raise RuntimeError(
            f"hub source {source!r} needs network access, which this build "
            "does not have; clone the repo and call with "
            "repo_dir=<path>, source='local'")


def list(repo_dir: str, source: str = "github"):  # noqa: A001  (reference name)
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    return [k for k, v in vars(mod).items()
            if callable(v) and not k.startswith("_")]


def help(repo_dir: str, model: str, source: str = "github"):  # noqa: A001
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    if not hasattr(mod, model):
        raise ValueError(f"model {model!r} not in hubconf ({repo_dir})")
    return getattr(mod, model).__doc__


def load(repo_dir: str, model: str, source: str = "github", **kwargs):
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    if not hasattr(mod, model):
        raise ValueError(f"model {model!r} not in hubconf ({repo_dir})")
    return getattr(mod, model)(**kwargs)
