"""paddle.save / paddle.load analogs (reference: python/paddle/framework/io.py:773,1020).

State dicts are stored as pickled dicts of numpy arrays — portable across hosts
and framework versions (the distributed sharded checkpoint with reshard-on-load
lives in paddle_tpu.distributed.checkpoint)."""

from __future__ import annotations

import os
import pickle

import numpy as np

from ..core.tensor import Tensor, _unwrap

_PROTOCOL = 4


def _to_storable(obj):
    if isinstance(obj, Tensor):
        return np.asarray(_unwrap(obj))
    if isinstance(obj, dict):
        return {k: _to_storable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_storable(v) for v in obj)
    if hasattr(obj, "state_dict") and callable(obj.state_dict):
        return _to_storable(obj.state_dict())
    return obj


def save(obj, path, protocol=_PROTOCOL, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_storable(obj), f, protocol=protocol)


def load(path, **configs):
    with open(path, "rb") as f:
        return pickle.load(f)
