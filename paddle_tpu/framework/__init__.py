"""framework: misc core utilities surfaced at ``paddle.framework`` in the
reference (random seeds, save/load io)."""

from ..core.rng import get_rng_state, seed, set_rng_state  # noqa: F401
from . import io_utils  # noqa: F401
from .io_utils import load, save  # noqa: F401
