"""Additional vision model families (reference: python/paddle/vision/models/
alexnet.py, squeezenet.py, densenet.py, googlenet.py, shufflenetv2.py)."""

from __future__ import annotations

from ... import nn
from ...ops.manipulation import concat, flatten, reshape, transpose, split


class AlexNet(nn.Layer):
    """Reference: vision/models/alexnet.py."""

    def __init__(self, num_classes=1000):
        super().__init__()
        self.features = nn.Sequential(
            nn.Conv2D(3, 64, 11, stride=4, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, stride=2),
            nn.Conv2D(64, 192, 5, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, stride=2),
            nn.Conv2D(192, 384, 3, padding=1), nn.ReLU(),
            nn.Conv2D(384, 256, 3, padding=1), nn.ReLU(),
            nn.Conv2D(256, 256, 3, padding=1), nn.ReLU(),
            nn.MaxPool2D(3, stride=2),
        )
        self.pool = nn.AdaptiveAvgPool2D((6, 6))
        self.classifier = nn.Sequential(
            nn.Dropout(0.5), nn.Linear(256 * 36, 4096), nn.ReLU(),
            nn.Dropout(0.5), nn.Linear(4096, 4096), nn.ReLU(),
            nn.Linear(4096, num_classes),
        )

    def forward(self, x):
        x = self.pool(self.features(x))
        return self.classifier(flatten(x, 1))


def alexnet(pretrained=False, **kw):
    return AlexNet(**kw)


class _Fire(nn.Layer):
    def __init__(self, in_c, squeeze, e1, e3):
        super().__init__()
        self.squeeze = nn.Sequential(nn.Conv2D(in_c, squeeze, 1), nn.ReLU())
        self.expand1 = nn.Sequential(nn.Conv2D(squeeze, e1, 1), nn.ReLU())
        self.expand3 = nn.Sequential(nn.Conv2D(squeeze, e3, 3, padding=1), nn.ReLU())

    def forward(self, x):
        s = self.squeeze(x)
        return concat([self.expand1(s), self.expand3(s)], axis=1)


class SqueezeNet(nn.Layer):
    """Reference: vision/models/squeezenet.py (v1.0: 96-ch 7x7 stem with
    late pools, reference squeezenet.py:150-167; v1.1: 64-ch 3x3 stem)."""

    def __init__(self, version="1.1", num_classes=1000):
        super().__init__()
        if version == "1.0":
            self.features = nn.Sequential(
                nn.Conv2D(3, 96, 7, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, stride=2),
                _Fire(96, 16, 64, 64), _Fire(128, 16, 64, 64),
                _Fire(128, 32, 128, 128),
                nn.MaxPool2D(3, stride=2),
                _Fire(256, 32, 128, 128), _Fire(256, 48, 192, 192),
                _Fire(384, 48, 192, 192), _Fire(384, 64, 256, 256),
                nn.MaxPool2D(3, stride=2),
                _Fire(512, 64, 256, 256),
            )
        elif version == "1.1":
            self.features = nn.Sequential(
                nn.Conv2D(3, 64, 3, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, stride=2),
                _Fire(64, 16, 64, 64), _Fire(128, 16, 64, 64),
                nn.MaxPool2D(3, stride=2),
                _Fire(128, 32, 128, 128), _Fire(256, 32, 128, 128),
                nn.MaxPool2D(3, stride=2),
                _Fire(256, 48, 192, 192), _Fire(384, 48, 192, 192),
                _Fire(384, 64, 256, 256), _Fire(512, 64, 256, 256),
            )
        else:
            raise ValueError(f"supported versions are ['1.0', '1.1'] but input version is {version}")
        self.classifier = nn.Sequential(
            nn.Dropout(0.5), nn.Conv2D(512, num_classes, 1), nn.ReLU(),
            nn.AdaptiveAvgPool2D((1, 1)),
        )

    def forward(self, x):
        return flatten(self.classifier(self.features(x)), 1)


def squeezenet1_0(pretrained=False, **kw):
    return SqueezeNet("1.0", **kw)


def squeezenet1_1(pretrained=False, **kw):
    return SqueezeNet("1.1", **kw)


class _DenseLayer(nn.Layer):
    def __init__(self, in_c, growth, bn_size):
        super().__init__()
        self.bn1 = nn.BatchNorm2D(in_c)
        self.conv1 = nn.Conv2D(in_c, bn_size * growth, 1, bias_attr=False)
        self.bn2 = nn.BatchNorm2D(bn_size * growth)
        self.conv2 = nn.Conv2D(bn_size * growth, growth, 3, padding=1, bias_attr=False)
        self.relu = nn.ReLU()

    def forward(self, x):
        out = self.conv1(self.relu(self.bn1(x)))
        out = self.conv2(self.relu(self.bn2(out)))
        return concat([x, out], axis=1)


class _Transition(nn.Layer):
    def __init__(self, in_c, out_c):
        super().__init__()
        self.bn = nn.BatchNorm2D(in_c)
        self.conv = nn.Conv2D(in_c, out_c, 1, bias_attr=False)
        self.relu = nn.ReLU()
        self.pool = nn.AvgPool2D(2, stride=2)

    def forward(self, x):
        return self.pool(self.conv(self.relu(self.bn(x))))


class DenseNet(nn.Layer):
    """Reference: vision/models/densenet.py."""

    def __init__(self, layers=121, growth_rate=32, bn_size=4, num_classes=1000):
        super().__init__()
        cfg = {121: (6, 12, 24, 16), 161: (6, 12, 36, 24),
               169: (6, 12, 32, 32), 201: (6, 12, 48, 32),
               264: (6, 12, 64, 48)}[layers]
        if layers == 161:
            growth_rate, init_c = 48, 96
        else:
            init_c = 64
        self.stem = nn.Sequential(
            nn.Conv2D(3, init_c, 7, stride=2, padding=3, bias_attr=False),
            nn.BatchNorm2D(init_c), nn.ReLU(), nn.MaxPool2D(3, stride=2, padding=1))
        blocks = []
        c = init_c
        for i, n in enumerate(cfg):
            for _ in range(n):
                blocks.append(_DenseLayer(c, growth_rate, bn_size))
                c += growth_rate
            if i != len(cfg) - 1:
                blocks.append(_Transition(c, c // 2))
                c = c // 2
        self.blocks = nn.Sequential(*blocks)
        self.bn = nn.BatchNorm2D(c)
        self.relu = nn.ReLU()
        self.pool = nn.AdaptiveAvgPool2D((1, 1))
        self.classifier = nn.Linear(c, num_classes)

    def forward(self, x):
        x = self.blocks(self.stem(x))
        x = self.pool(self.relu(self.bn(x)))
        return self.classifier(flatten(x, 1))


def densenet121(pretrained=False, **kw):
    return DenseNet(121, **kw)


def densenet161(pretrained=False, **kw):
    return DenseNet(161, **kw)


def densenet169(pretrained=False, **kw):
    return DenseNet(169, **kw)


def densenet201(pretrained=False, **kw):
    return DenseNet(201, **kw)


def densenet264(pretrained=False, **kw):
    return DenseNet(264, **kw)


class _ShuffleUnit(nn.Layer):
    def __init__(self, in_c, out_c, stride, act=nn.ReLU):
        super().__init__()
        self.stride = stride
        branch_c = out_c // 2
        if stride == 2:
            self.branch1 = nn.Sequential(
                nn.Conv2D(in_c, in_c, 3, stride=2, padding=1, groups=in_c, bias_attr=False),
                nn.BatchNorm2D(in_c),
                nn.Conv2D(in_c, branch_c, 1, bias_attr=False),
                nn.BatchNorm2D(branch_c), act())
            b2_in = in_c
        else:
            self.branch1 = None
            b2_in = in_c // 2
        self.branch2 = nn.Sequential(
            nn.Conv2D(b2_in, branch_c, 1, bias_attr=False),
            nn.BatchNorm2D(branch_c), act(),
            nn.Conv2D(branch_c, branch_c, 3, stride=stride, padding=1,
                      groups=branch_c, bias_attr=False),
            nn.BatchNorm2D(branch_c),
            nn.Conv2D(branch_c, branch_c, 1, bias_attr=False),
            nn.BatchNorm2D(branch_c), act())

    def forward(self, x):
        if self.stride == 1:
            x1, x2 = split(x, 2, axis=1)
            out = concat([x1, self.branch2(x2)], axis=1)
        else:
            out = concat([self.branch1(x), self.branch2(x)], axis=1)
        # channel shuffle (2 groups)
        b, c, h, w = out.shape
        out = reshape(out, (b, 2, c // 2, h, w))
        out = transpose(out, (0, 2, 1, 3, 4))
        return reshape(out, (b, c, h, w))


class ShuffleNetV2(nn.Layer):
    """Reference: vision/models/shufflenetv2.py (stage channel table at
    shufflenetv2.py:282-291; `act` relu/swish per `create_activation_layer`)."""

    def __init__(self, scale=1.0, num_classes=1000, act="relu"):
        super().__init__()
        # (stem, stage1, stage2, stage3, head) channels per scale
        stage_c = {0.25: (24, 24, 48, 96, 512), 0.33: (24, 32, 64, 128, 512),
                   0.5: (24, 48, 96, 192, 1024), 1.0: (24, 116, 232, 464, 1024),
                   1.5: (24, 176, 352, 704, 1024), 2.0: (24, 224, 488, 976, 2048)}[scale]
        Act = {"relu": nn.ReLU, "swish": nn.Swish}[act]
        self.stem = nn.Sequential(
            nn.Conv2D(3, stage_c[0], 3, stride=2, padding=1, bias_attr=False),
            nn.BatchNorm2D(stage_c[0]), Act(), nn.MaxPool2D(3, stride=2, padding=1))
        c = stage_c[0]
        stages = []
        for out_c, repeats in zip(stage_c[1:4], (4, 8, 4)):
            stages.append(_ShuffleUnit(c, out_c, 2, Act))
            for _ in range(repeats - 1):
                stages.append(_ShuffleUnit(out_c, out_c, 1, Act))
            c = out_c
        self.stages = nn.Sequential(*stages)
        self.head = nn.Sequential(
            nn.Conv2D(c, stage_c[4], 1, bias_attr=False),
            nn.BatchNorm2D(stage_c[4]), Act())
        self.pool = nn.AdaptiveAvgPool2D((1, 1))
        self.fc = nn.Linear(stage_c[4], num_classes)

    def forward(self, x):
        x = self.pool(self.head(self.stages(self.stem(x))))
        return self.fc(flatten(x, 1))


def shufflenet_v2_x0_25(pretrained=False, **kw):
    return ShuffleNetV2(0.25, **kw)


def shufflenet_v2_x0_33(pretrained=False, **kw):
    return ShuffleNetV2(0.33, **kw)


def shufflenet_v2_x0_5(pretrained=False, **kw):
    return ShuffleNetV2(0.5, **kw)


def shufflenet_v2_x1_0(pretrained=False, **kw):
    return ShuffleNetV2(1.0, **kw)


def shufflenet_v2_x1_5(pretrained=False, **kw):
    return ShuffleNetV2(1.5, **kw)


def shufflenet_v2_x2_0(pretrained=False, **kw):
    return ShuffleNetV2(2.0, **kw)


def shufflenet_v2_swish(pretrained=False, **kw):
    return ShuffleNetV2(1.0, act="swish", **kw)
