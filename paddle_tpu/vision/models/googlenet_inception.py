"""GoogLeNet + InceptionV3 + MobileNetV1/V3 (reference:
python/paddle/vision/models/{googlenet,inceptionv3,mobilenetv1,mobilenetv3}.py).

Independent compact implementations of the reference architectures (paper
topologies); API surface matches the reference constructors.
"""

from __future__ import annotations

from ... import nn
from ...ops.manipulation import concat, flatten


class _ConvBN(nn.Sequential):
    def __init__(self, in_c, out_c, kernel, stride=1, padding=0):
        super().__init__(
            nn.Conv2D(in_c, out_c, kernel, stride=stride, padding=padding, bias_attr=False),
            nn.BatchNorm2D(out_c),
            nn.ReLU(),
        )


# ---------------------------------------------------------------- GoogLeNet

class _Inception(nn.Layer):
    """The v1 inception block: 1x1 | 1x1->3x3 | 1x1->5x5 | pool->1x1."""

    def __init__(self, in_c, c1, c3r, c3, c5r, c5, pp):
        super().__init__()
        self.b1 = _ConvBN(in_c, c1, 1)
        self.b2 = nn.Sequential(_ConvBN(in_c, c3r, 1), _ConvBN(c3r, c3, 3, padding=1))
        self.b3 = nn.Sequential(_ConvBN(in_c, c5r, 1), _ConvBN(c5r, c5, 5, padding=2))
        self.b4 = nn.Sequential(nn.MaxPool2D(3, stride=1, padding=1), _ConvBN(in_c, pp, 1))

    def forward(self, x):
        return concat([self.b1(x), self.b2(x), self.b3(x), self.b4(x)], axis=1)


class GoogLeNet(nn.Layer):
    """Inception v1 (reference: googlenet.py).  forward returns
    (main, aux1, aux2) logits like the reference."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            _ConvBN(3, 64, 7, stride=2, padding=3), nn.MaxPool2D(3, 2, padding=1),
            _ConvBN(64, 64, 1), _ConvBN(64, 192, 3, padding=1), nn.MaxPool2D(3, 2, padding=1))
        self.i3a = _Inception(192, 64, 96, 128, 16, 32, 32)
        self.i3b = _Inception(256, 128, 128, 192, 32, 96, 64)
        self.pool3 = nn.MaxPool2D(3, 2, padding=1)
        self.i4a = _Inception(480, 192, 96, 208, 16, 48, 64)
        self.i4b = _Inception(512, 160, 112, 224, 24, 64, 64)
        self.i4c = _Inception(512, 128, 128, 256, 24, 64, 64)
        self.i4d = _Inception(512, 112, 144, 288, 32, 64, 64)
        self.i4e = _Inception(528, 256, 160, 320, 32, 128, 128)
        self.pool4 = nn.MaxPool2D(3, 2, padding=1)
        self.i5a = _Inception(832, 256, 160, 320, 32, 128, 128)
        self.i5b = _Inception(832, 384, 192, 384, 48, 128, 128)
        if with_pool:
            self.pool5 = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.drop = nn.Dropout(0.4)
            self.fc = nn.Linear(1024, num_classes)
            # aux heads (training-time deep supervision)
            self.aux_pool = nn.AdaptiveAvgPool2D((4, 4))
            self.aux1_conv = _ConvBN(512, 128, 1)
            self.aux1_fc1 = nn.Linear(128 * 16, 1024)
            self.aux1_fc2 = nn.Linear(1024, num_classes)
            self.aux2_conv = _ConvBN(528, 128, 1)
            self.aux2_fc1 = nn.Linear(128 * 16, 1024)
            self.aux2_fc2 = nn.Linear(1024, num_classes)

    def _aux(self, x, conv, fc1, fc2):
        x = self.aux_pool(x)
        x = conv(x)
        x = flatten(x, 1)
        x = nn.functional.relu(fc1(x))
        return fc2(x)

    def forward(self, x):
        x = self.stem(x)
        x = self.pool3(self.i3b(self.i3a(x)))
        x = self.i4a(x)
        aux1 = self._aux(x, self.aux1_conv, self.aux1_fc1, self.aux1_fc2) if self.num_classes > 0 else None
        x = self.i4d(self.i4c(self.i4b(x)))
        aux2 = self._aux(x, self.aux2_conv, self.aux2_fc1, self.aux2_fc2) if self.num_classes > 0 else None
        x = self.pool4(self.i4e(x))
        x = self.i5b(self.i5a(x))
        if self.with_pool:
            x = self.pool5(x)
        if self.num_classes > 0:
            x = self.fc(self.drop(flatten(x, 1)))
        return x, aux1, aux2


def googlenet(pretrained=False, **kwargs):
    return GoogLeNet(**kwargs)


# ---------------------------------------------------------------- InceptionV3

class _IncA(nn.Layer):
    def __init__(self, in_c, pool_c):
        super().__init__()
        self.b1 = _ConvBN(in_c, 64, 1)
        self.b5 = nn.Sequential(_ConvBN(in_c, 48, 1), _ConvBN(48, 64, 5, padding=2))
        self.b3 = nn.Sequential(_ConvBN(in_c, 64, 1), _ConvBN(64, 96, 3, padding=1),
                                _ConvBN(96, 96, 3, padding=1))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1), _ConvBN(in_c, pool_c, 1))

    def forward(self, x):
        return concat([self.b1(x), self.b5(x), self.b3(x), self.bp(x)], axis=1)


class _IncB(nn.Layer):  # grid reduction 35->17
    def __init__(self, in_c):
        super().__init__()
        self.b3 = _ConvBN(in_c, 384, 3, stride=2)
        self.b3d = nn.Sequential(_ConvBN(in_c, 64, 1), _ConvBN(64, 96, 3, padding=1),
                                 _ConvBN(96, 96, 3, stride=2))
        self.pool = nn.MaxPool2D(3, 2)

    def forward(self, x):
        return concat([self.b3(x), self.b3d(x), self.pool(x)], axis=1)


class _IncC(nn.Layer):  # factorized 7x7
    def __init__(self, in_c, c7):
        super().__init__()
        self.b1 = _ConvBN(in_c, 192, 1)
        self.b7 = nn.Sequential(
            _ConvBN(in_c, c7, 1), _ConvBN(c7, c7, (1, 7), padding=(0, 3)),
            _ConvBN(c7, 192, (7, 1), padding=(3, 0)))
        self.b7d = nn.Sequential(
            _ConvBN(in_c, c7, 1), _ConvBN(c7, c7, (7, 1), padding=(3, 0)),
            _ConvBN(c7, c7, (1, 7), padding=(0, 3)),
            _ConvBN(c7, c7, (7, 1), padding=(3, 0)),
            _ConvBN(c7, 192, (1, 7), padding=(0, 3)))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1), _ConvBN(in_c, 192, 1))

    def forward(self, x):
        return concat([self.b1(x), self.b7(x), self.b7d(x), self.bp(x)], axis=1)


class _IncD(nn.Layer):  # grid reduction 17->8
    def __init__(self, in_c):
        super().__init__()
        self.b3 = nn.Sequential(_ConvBN(in_c, 192, 1), _ConvBN(192, 320, 3, stride=2))
        self.b7 = nn.Sequential(
            _ConvBN(in_c, 192, 1), _ConvBN(192, 192, (1, 7), padding=(0, 3)),
            _ConvBN(192, 192, (7, 1), padding=(3, 0)), _ConvBN(192, 192, 3, stride=2))
        self.pool = nn.MaxPool2D(3, 2)

    def forward(self, x):
        return concat([self.b3(x), self.b7(x), self.pool(x)], axis=1)


class _IncE(nn.Layer):  # expanded filter bank
    def __init__(self, in_c):
        super().__init__()
        self.b1 = _ConvBN(in_c, 320, 1)
        self.b3_stem = _ConvBN(in_c, 384, 1)
        self.b3_a = _ConvBN(384, 384, (1, 3), padding=(0, 1))
        self.b3_b = _ConvBN(384, 384, (3, 1), padding=(1, 0))
        self.b3d_stem = nn.Sequential(_ConvBN(in_c, 448, 1), _ConvBN(448, 384, 3, padding=1))
        self.b3d_a = _ConvBN(384, 384, (1, 3), padding=(0, 1))
        self.b3d_b = _ConvBN(384, 384, (3, 1), padding=(1, 0))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1), _ConvBN(in_c, 192, 1))

    def forward(self, x):
        s = self.b3_stem(x)
        d = self.b3d_stem(x)
        return concat([self.b1(x),
                       concat([self.b3_a(s), self.b3_b(s)], axis=1),
                       concat([self.b3d_a(d), self.b3d_b(d)], axis=1),
                       self.bp(x)], axis=1)


class InceptionV3(nn.Layer):
    """Inception v3 (reference: inceptionv3.py), 299x299 inputs."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            _ConvBN(3, 32, 3, stride=2), _ConvBN(32, 32, 3), _ConvBN(32, 64, 3, padding=1),
            nn.MaxPool2D(3, 2), _ConvBN(64, 80, 1), _ConvBN(80, 192, 3), nn.MaxPool2D(3, 2))
        self.blocks = nn.Sequential(
            _IncA(192, 32), _IncA(256, 64), _IncA(288, 64),
            _IncB(288),
            _IncC(768, 128), _IncC(768, 160), _IncC(768, 160), _IncC(768, 192),
            _IncD(768),
            _IncE(1280), _IncE(2048))
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.drop = nn.Dropout(0.2)
            self.fc = nn.Linear(2048, num_classes)

    def forward(self, x):
        x = self.blocks(self.stem(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(self.drop(flatten(x, 1)))
        return x


def inception_v3(pretrained=False, **kwargs):
    return InceptionV3(**kwargs)


# ---------------------------------------------------------------- MobileNetV1

class _DWSep(nn.Sequential):
    def __init__(self, in_c, out_c, stride):
        super().__init__(
            nn.Conv2D(in_c, in_c, 3, stride=stride, padding=1, groups=in_c, bias_attr=False),
            nn.BatchNorm2D(in_c), nn.ReLU(),
            nn.Conv2D(in_c, out_c, 1, bias_attr=False),
            nn.BatchNorm2D(out_c), nn.ReLU())


class MobileNetV1(nn.Layer):
    """reference: mobilenetv1.py."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        c = lambda ch: max(8, int(ch * scale))
        cfg = [(32, 64, 1), (64, 128, 2), (128, 128, 1), (128, 256, 2),
               (256, 256, 1), (256, 512, 2)] + [(512, 512, 1)] * 5 + \
              [(512, 1024, 2), (1024, 1024, 1)]
        layers = [nn.Conv2D(3, c(32), 3, stride=2, padding=1, bias_attr=False),
                  nn.BatchNorm2D(c(32)), nn.ReLU()]
        for in_c, out_c, s in cfg:
            layers.append(_DWSep(c(in_c), c(out_c), s))
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(c(1024), num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(flatten(x, 1))
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV1(scale=scale, **kwargs)


# ---------------------------------------------------------------- MobileNetV3

class _SE(nn.Layer):
    def __init__(self, ch, r=4):
        super().__init__()
        self.pool = nn.AdaptiveAvgPool2D((1, 1))
        self.fc1 = nn.Conv2D(ch, ch // r, 1)
        self.fc2 = nn.Conv2D(ch // r, ch, 1)
        self.hs = nn.Hardsigmoid()

    def forward(self, x):
        s = self.hs(self.fc2(nn.functional.relu(self.fc1(self.pool(x)))))
        return x * s


class _V3Block(nn.Layer):
    def __init__(self, in_c, exp, out_c, k, stride, se, act):
        super().__init__()
        self.use_res = stride == 1 and in_c == out_c
        Act = nn.Hardswish if act == "hardswish" else nn.ReLU
        layers = []
        if exp != in_c:
            layers += [nn.Conv2D(in_c, exp, 1, bias_attr=False), nn.BatchNorm2D(exp), Act()]
        layers += [nn.Conv2D(exp, exp, k, stride=stride, padding=k // 2, groups=exp, bias_attr=False),
                   nn.BatchNorm2D(exp), Act()]
        if se:
            layers.append(_SE(exp))
        layers += [nn.Conv2D(exp, out_c, 1, bias_attr=False), nn.BatchNorm2D(out_c)]
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        return x + out if self.use_res else out


_V3_SMALL = [
    # k, exp, out, se, act, stride
    (3, 16, 16, True, "relu", 2), (3, 72, 24, False, "relu", 2),
    (3, 88, 24, False, "relu", 1), (5, 96, 40, True, "hardswish", 2),
    (5, 240, 40, True, "hardswish", 1), (5, 240, 40, True, "hardswish", 1),
    (5, 120, 48, True, "hardswish", 1), (5, 144, 48, True, "hardswish", 1),
    (5, 288, 96, True, "hardswish", 2), (5, 576, 96, True, "hardswish", 1),
    (5, 576, 96, True, "hardswish", 1),
]
_V3_LARGE = [
    (3, 16, 16, False, "relu", 1), (3, 64, 24, False, "relu", 2),
    (3, 72, 24, False, "relu", 1), (5, 72, 40, True, "relu", 2),
    (5, 120, 40, True, "relu", 1), (5, 120, 40, True, "relu", 1),
    (3, 240, 80, False, "hardswish", 2), (3, 200, 80, False, "hardswish", 1),
    (3, 184, 80, False, "hardswish", 1), (3, 184, 80, False, "hardswish", 1),
    (3, 480, 112, True, "hardswish", 1), (3, 672, 112, True, "hardswish", 1),
    (5, 672, 160, True, "hardswish", 2), (5, 960, 160, True, "hardswish", 1),
    (5, 960, 160, True, "hardswish", 1),
]


class MobileNetV3(nn.Layer):
    """reference: mobilenetv3.py (small/large)."""

    def __init__(self, config, last_channel, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        c = lambda ch: max(8, int(ch * scale))
        layers = [nn.Conv2D(3, c(16), 3, stride=2, padding=1, bias_attr=False),
                  nn.BatchNorm2D(c(16)), nn.Hardswish()]
        in_c = c(16)
        for k, exp, out_c, se, act, s in config:
            layers.append(_V3Block(in_c, c(exp), c(out_c), k, s, se, act))
            in_c = c(out_c)
        last_conv = c(config[-1][1])
        layers += [nn.Conv2D(in_c, last_conv, 1, bias_attr=False),
                   nn.BatchNorm2D(last_conv), nn.Hardswish()]
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(last_conv, last_channel), nn.Hardswish(),
                nn.Dropout(0.2), nn.Linear(last_channel, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(flatten(x, 1))
        return x


class MobileNetV3Small(MobileNetV3):
    """reference: mobilenetv3.py `MobileNetV3Small`."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_V3_SMALL, 1024, scale=scale,
                         num_classes=num_classes, with_pool=with_pool)


class MobileNetV3Large(MobileNetV3):
    """reference: mobilenetv3.py `MobileNetV3Large`."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_V3_LARGE, 1280, scale=scale,
                         num_classes=num_classes, with_pool=with_pool)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV3Small(scale=scale, **kwargs)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV3Large(scale=scale, **kwargs)
