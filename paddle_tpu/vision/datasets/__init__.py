"""Vision datasets (reference: python/paddle/vision/datasets/).

Zero-egress environment: download-backed datasets (MNIST, Cifar10, …) fall back
to deterministic synthetic data of the right shapes when files are absent, so
the training recipes and benchmarks run end-to-end offline."""

from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ...io import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "DatasetFolder"]


class MNIST(Dataset):
    def __init__(self, image_path=None, label_path=None, mode="train", transform=None, download=True, backend="cv2", size=None):
        self.mode = mode
        self.transform = transform
        n = size or (60000 if mode == "train" else 10000)
        if image_path and os.path.exists(image_path):
            with gzip.open(image_path, "rb") as f:
                magic, num, rows, cols = struct.unpack(">IIII", f.read(16))
                self.images = np.frombuffer(f.read(), np.uint8).reshape(num, rows, cols)
            with gzip.open(label_path, "rb") as f:
                f.read(8)
                self.labels = np.frombuffer(f.read(), np.uint8)
        else:
            # synthetic fallback: class-dependent blobs, deterministic
            rs = np.random.RandomState(0 if mode == "train" else 1)
            n = min(n, 4096)
            self.labels = rs.randint(0, 10, n).astype(np.int64)
            base = rs.rand(10, 28, 28)
            self.images = np.clip(
                (base[self.labels] * 255 + rs.randn(n, 28, 28) * 16), 0, 255
            ).astype(np.uint8)

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32)[None] / 255.0
        if self.transform is not None:
            img = self.transform(img)
        return img, int(self.labels[idx])

    def __len__(self):
        return len(self.labels)


class FashionMNIST(MNIST):
    pass


class Cifar10(Dataset):
    NUM_CLASSES = 10

    def __init__(self, data_file=None, mode="train", transform=None, download=True, backend="cv2"):
        self.transform = transform
        rs = np.random.RandomState(2 if mode == "train" else 3)
        n = 4096 if mode == "train" else 1024
        self.labels = rs.randint(0, self.NUM_CLASSES, n).astype(np.int64)
        base = rs.rand(self.NUM_CLASSES, 3, 32, 32)
        self.images = np.clip(base[self.labels] * 255 + rs.randn(n, 3, 32, 32) * 24, 0, 255).astype(np.uint8)

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32) / 255.0
        if self.transform is not None:
            img = self.transform(img)
        return img, int(self.labels[idx])

    def __len__(self):
        return len(self.labels)


class Cifar100(Cifar10):
    NUM_CLASSES = 100


class DatasetFolder(Dataset):
    def __init__(self, root, loader=None, extensions=None, transform=None, is_valid_file=None):
        self.root = root
        self.transform = transform
        self.samples = []
        classes = sorted(
            d for d in os.listdir(root) if os.path.isdir(os.path.join(root, d))
        ) if os.path.isdir(root) else []
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        for c in classes:
            cdir = os.path.join(root, c)
            for fname in sorted(os.listdir(cdir)):
                self.samples.append((os.path.join(cdir, fname), self.class_to_idx[c]))
        self.loader = loader or self._default_loader

    @staticmethod
    def _default_loader(path):
        from PIL import Image

        return np.asarray(Image.open(path).convert("RGB"))

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        sample = self.loader(path)
        if self.transform is not None:
            sample = self.transform(sample)
        return sample, target

    def __len__(self):
        return len(self.samples)
