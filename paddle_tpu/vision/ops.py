"""Detection / vision operators (reference: python/paddle/vision/ops.py).

TPU-first design notes
----------------------
The reference implements these as per-ROI C++/CUDA loops
(``paddle/phi/kernels/cpu/roi_align_kernel.cc``, ``yolo_loss_kernel.cc``,
``deform_conv_kernel_impl.h``, ...).  Here every op is a *vectorized* jnp/lax
composition: ROI pooling builds masked reductions over the full feature grid,
deformable conv materialises the im2col sample tensor with one batched gather
and contracts it on the MXU with a single einsum, and YOLO loss scatters the
per-ground-truth targets with ``.at[].set(mode="drop")`` instead of serial
writes.  Everything is differentiable through plain jax AD and traceable under
jit (NMS and distribute_fpn_proposals return data-dependent shapes and are
eager-mode by nature, exactly like the reference's dynamic-shape outputs).

Reference parity anchors:
  roi_align   python/paddle/vision/ops.py:1705  (phi/kernels/cpu/roi_align_kernel.cc)
  roi_pool    python/paddle/vision/ops.py:1572
  psroi_pool  python/paddle/vision/ops.py:1441
  nms         python/paddle/vision/ops.py:1934
  deform_conv2d python/paddle/vision/ops.py:766
  yolo_loss   python/paddle/vision/ops.py:69   (phi/kernels/cpu/yolo_loss_kernel.cc)
  yolo_box    python/paddle/vision/ops.py:277
  prior_box   python/paddle/vision/ops.py:438
  box_coder   python/paddle/vision/ops.py:584
  distribute_fpn_proposals python/paddle/vision/ops.py:1175
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, apply_op, _unwrap
from .. import nn

__all__ = [
    "yolo_loss",
    "yolo_box",
    "prior_box",
    "box_coder",
    "deform_conv2d",
    "DeformConv2D",
    "generate_proposals",
    "distribute_fpn_proposals",
    "psroi_pool",
    "PSRoIPool",
    "roi_pool",
    "RoIPool",
    "roi_align",
    "RoIAlign",
    "nms",
    "matrix_nms",
    "read_file",
    "decode_jpeg",
]


def _pair(v):
    return (v, v) if isinstance(v, int) else tuple(v)


def _roi_batch_index(boxes_num, num_rois, batch):
    """[num_rois] int32 image index for each roi (jit-friendly fixed-length repeat)."""
    return jnp.repeat(
        jnp.arange(batch, dtype=jnp.int32), boxes_num.astype(jnp.int32),
        total_repeat_length=num_rois,
    )


def _bilinear_gather(feat, y, x):
    """Sample ``feat`` [C, H, W] at float coords (y, x) of any shape -> [C, *coords].

    Boundary semantics follow the reference roi_align bilinear interpolate:
    points with y < -1 or y > H (resp. x) contribute 0; otherwise coords are
    clamped into [0, size-1] and corner-interpolated.
    """
    H, W = feat.shape[-2:]
    valid = (y > -1.0) & (y < H) & (x > -1.0) & (x < W)
    y = jnp.clip(y, 0.0, H - 1.0)
    x = jnp.clip(x, 0.0, W - 1.0)
    y0 = jnp.floor(y).astype(jnp.int32)
    x0 = jnp.floor(x).astype(jnp.int32)
    y1 = jnp.minimum(y0 + 1, H - 1)
    x1 = jnp.minimum(x0 + 1, W - 1)
    ly = y - y0
    lx = x - x0
    hy, hx = 1.0 - ly, 1.0 - lx
    v00 = feat[:, y0, x0]
    v01 = feat[:, y0, x1]
    v10 = feat[:, y1, x0]
    v11 = feat[:, y1, x1]
    out = hy * hx * v00 + hy * lx * v01 + ly * hx * v10 + ly * lx * v11
    return jnp.where(valid, out, 0.0)


# --------------------------------------------------------------------------
# ROI pooling family
# --------------------------------------------------------------------------

def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """ROI Align (Mask R-CNN) — reference python/paddle/vision/ops.py:1705.

    Adaptive grids (sampling_ratio <= 0) use a static upper bound of
    ceil(H/ph) x ceil(W/pw) sample points with per-roi masking, so the op
    stays jit-compilable with static shapes.
    """
    ph, pw = _pair(output_size)

    def fn(xv, bv, nv):
        xv, bv = jnp.asarray(xv), jnp.asarray(bv)
        N, C, H, W = xv.shape
        R = bv.shape[0]
        bidx = _roi_batch_index(nv, R, N)
        off = 0.5 if aligned else 0.0
        x1 = bv[:, 0] * spatial_scale - off
        y1 = bv[:, 1] * spatial_scale - off
        x2 = bv[:, 2] * spatial_scale - off
        y2 = bv[:, 3] * spatial_scale - off
        roi_w = x2 - x1
        roi_h = y2 - y1
        if not aligned:
            roi_w = jnp.maximum(roi_w, 1.0)
            roi_h = jnp.maximum(roi_h, 1.0)
        bin_h = roi_h / ph
        bin_w = roi_w / pw
        if sampling_ratio > 0:
            GH = GW = int(sampling_ratio)
            gh = jnp.full((R,), float(GH))
            gw = jnp.full((R,), float(GW))
        else:
            # adaptive sampling (sampling_ratio<=0): the reference uses
            # ceil(roi_h/ph) points per bin, which is data-dependent; a
            # jittable static bound is needed, and ceil(H/ph) covers every
            # ROI that fits the feature map.  An ROI LARGER than the map
            # (bin_h > H/ph) gets its grid clamped to this bound and uses
            # fewer samples than the reference — numerics diverge only for
            # such oversized boxes.
            GH = max(1, math.ceil(H / ph))
            GW = max(1, math.ceil(W / pw))
            gh = jnp.clip(jnp.ceil(bin_h), 1.0, GH)
            gw = jnp.clip(jnp.ceil(bin_w), 1.0, GW)

        ib = jnp.arange(ph, dtype=xv.dtype)
        jb = jnp.arange(pw, dtype=xv.dtype)
        iy = jnp.arange(GH, dtype=xv.dtype)
        ix = jnp.arange(GW, dtype=xv.dtype)
        # y coords: [R, ph, GH]; x coords: [R, pw, GW]
        ys = (y1[:, None, None] + ib[None, :, None] * bin_h[:, None, None]
              + (iy[None, None, :] + 0.5) * bin_h[:, None, None] / gh[:, None, None])
        xs = (x1[:, None, None] + jb[None, :, None] * bin_w[:, None, None]
              + (ix[None, None, :] + 0.5) * bin_w[:, None, None] / gw[:, None, None])
        ymask = iy[None, None, :] < gh[:, None, None]
        xmask = ix[None, None, :] < gw[:, None, None]

        def one(b, yy, xx, ym, xm, g_h, g_w):
            feat = xv[b]
            # broadcast to full sample grid [ph, GH, pw, GW]
            Y = jnp.broadcast_to(yy[:, :, None, None], (ph, GH, pw, GW))
            X = jnp.broadcast_to(xx[None, None, :, :], (ph, GH, pw, GW))
            vals = _bilinear_gather(feat, Y, X)  # [C, ph, GH, pw, GW]
            m = (ym[:, :, None, None] & xm[None, None, :, :]).astype(vals.dtype)
            s = jnp.sum(vals * m[None], axis=(2, 4))  # [C, ph, pw]
            return s / (g_h * g_w)

        return jax.vmap(one)(bidx, ys, xs, ymask, xmask, gh, gw)

    return apply_op("roi_align", fn, [x, boxes, boxes_num])


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """ROI max pooling — reference python/paddle/vision/ops.py:1572."""
    ph, pw = _pair(output_size)

    def fn(xv, bv, nv):
        xv, bv = jnp.asarray(xv), jnp.asarray(bv)
        N, C, H, W = xv.shape
        R = bv.shape[0]
        bidx = _roi_batch_index(nv, R, N)
        xs = jnp.round(bv[:, 0] * spatial_scale).astype(jnp.int32)
        ys = jnp.round(bv[:, 1] * spatial_scale).astype(jnp.int32)
        xe = jnp.round(bv[:, 2] * spatial_scale).astype(jnp.int32)
        ye = jnp.round(bv[:, 3] * spatial_scale).astype(jnp.int32)
        roi_h = jnp.maximum(ye - ys + 1, 1)
        roi_w = jnp.maximum(xe - xs + 1, 1)
        bin_h = roi_h.astype(xv.dtype) / ph
        bin_w = roi_w.astype(xv.dtype) / pw
        ii = jnp.arange(ph)
        jj = jnp.arange(pw)
        hstart = jnp.clip(jnp.floor(ii[None] * bin_h[:, None]).astype(jnp.int32) + ys[:, None], 0, H)
        hend = jnp.clip(jnp.ceil((ii[None] + 1) * bin_h[:, None]).astype(jnp.int32) + ys[:, None], 0, H)
        wstart = jnp.clip(jnp.floor(jj[None] * bin_w[:, None]).astype(jnp.int32) + xs[:, None], 0, W)
        wend = jnp.clip(jnp.ceil((jj[None] + 1) * bin_w[:, None]).astype(jnp.int32) + xs[:, None], 0, W)
        hgrid = jnp.arange(H)
        wgrid = jnp.arange(W)
        # row/col membership masks per bin: [R, ph, H], [R, pw, W]
        rmask = (hgrid[None, None] >= hstart[:, :, None]) & (hgrid[None, None] < hend[:, :, None])
        cmask = (wgrid[None, None] >= wstart[:, :, None]) & (wgrid[None, None] < wend[:, :, None])

        neg = jnp.asarray(-jnp.inf, xv.dtype)

        def one(b, rm, cm):
            feat = xv[b]  # [C, H, W]
            m = rm[:, None, :, None] & cm[None, :, None, :]  # [ph, pw, H, W]
            big = jnp.where(m[None], feat[:, None, None], neg)
            out = jnp.max(big, axis=(3, 4))
            return jnp.where(jnp.isfinite(out), out, 0.0)

        return jax.vmap(one)(bidx, rmask, cmask)

    return apply_op("roi_pool", fn, [x, boxes, boxes_num])


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """Position-sensitive ROI average pooling (R-FCN) — reference :1441."""
    ph, pw = _pair(output_size)

    def fn(xv, bv, nv):
        xv, bv = jnp.asarray(xv), jnp.asarray(bv)
        N, C, H, W = xv.shape
        if C % (ph * pw) != 0:
            raise ValueError(f"input channels {C} must be divisible by {ph}*{pw}")
        oc = C // (ph * pw)
        R = bv.shape[0]
        bidx = _roi_batch_index(nv, R, N)
        xs = jnp.round(bv[:, 0]) * spatial_scale
        ys = jnp.round(bv[:, 1]) * spatial_scale
        xe = jnp.round(bv[:, 2] + 1.0) * spatial_scale
        ye = jnp.round(bv[:, 3] + 1.0) * spatial_scale
        roi_h = jnp.maximum(ye - ys, 0.1)
        roi_w = jnp.maximum(xe - xs, 0.1)
        bin_h = roi_h / ph
        bin_w = roi_w / pw
        ii = jnp.arange(ph)
        jj = jnp.arange(pw)
        hstart = jnp.clip(jnp.floor(ii[None] * bin_h[:, None] + ys[:, None]).astype(jnp.int32), 0, H)
        hend = jnp.clip(jnp.ceil((ii[None] + 1) * bin_h[:, None] + ys[:, None]).astype(jnp.int32), 0, H)
        wstart = jnp.clip(jnp.floor(jj[None] * bin_w[:, None] + xs[:, None]).astype(jnp.int32), 0, W)
        wend = jnp.clip(jnp.ceil((jj[None] + 1) * bin_w[:, None] + xs[:, None]).astype(jnp.int32), 0, W)
        hgrid = jnp.arange(H)
        wgrid = jnp.arange(W)
        rmask = (hgrid[None, None] >= hstart[:, :, None]) & (hgrid[None, None] < hend[:, :, None])
        cmask = (wgrid[None, None] >= wstart[:, :, None]) & (wgrid[None, None] < wend[:, :, None])

        def one(b, rm, cm):
            # position-sensitive: output channel c at bin (i,j) reads input
            # channel (c*ph + i)*pw + j
            feat = xv[b].reshape(oc, ph, pw, H, W)
            m = (rm[:, None, :, None] & cm[None, :, None, :]).astype(feat.dtype)  # [ph,pw,H,W]
            s = jnp.einsum("cijhw,ijhw->cij", feat, m)
            area = jnp.maximum(jnp.sum(m, axis=(2, 3)), 1.0)
            return s / area[None]

        return jax.vmap(one)(bidx, rmask, cmask)

    return apply_op("psroi_pool", fn, [x, boxes, boxes_num])


class RoIAlign(nn.Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num, aligned=True):
        return roi_align(x, boxes, boxes_num, self._output_size,
                         self._spatial_scale, aligned=aligned)


class RoIPool(nn.Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self._output_size, self._spatial_scale)


class PSRoIPool(nn.Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self._output_size, self._spatial_scale)


# --------------------------------------------------------------------------
# NMS
# --------------------------------------------------------------------------

def _iou_matrix(boxes):
    """Pairwise IoU for corner-format boxes [n, 4] -> [n, n]."""
    area = jnp.maximum(boxes[:, 2] - boxes[:, 0], 0.0) * jnp.maximum(boxes[:, 3] - boxes[:, 1], 0.0)
    lt = jnp.maximum(boxes[:, None, :2], boxes[None, :, :2])
    rb = jnp.minimum(boxes[:, None, 2:], boxes[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    union = area[:, None] + area[None, :] - inter
    return inter / jnp.maximum(union, 1e-10)


def _greedy_keep(boxes_sorted, iou_threshold):
    """Greedy suppression over score-sorted boxes; returns bool keep mask [n]."""
    n = boxes_sorted.shape[0]
    iou = _iou_matrix(boxes_sorted)
    idx = jnp.arange(n)

    def body(i, keep):
        overl = (iou[i] > iou_threshold) & keep & (idx < i)
        return keep.at[i].set(~jnp.any(overl))

    return jax.lax.fori_loop(0, n, body, jnp.ones((n,), bool))


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Hard NMS — reference python/paddle/vision/ops.py:1934.

    Returns kept box indices (int64).  Output length is data-dependent, so
    like the reference this is an eager-mode op.
    """
    bv = np.asarray(_unwrap(boxes), dtype=np.float32)
    n = bv.shape[0]
    if scores is None:
        keep = np.asarray(_greedy_keep(jnp.asarray(bv), iou_threshold))
        return Tensor(np.nonzero(keep)[0].astype(np.int64), stop_gradient=True)

    sv = np.asarray(_unwrap(scores), dtype=np.float32)
    if n == 0:
        return Tensor(np.zeros((0,), np.int64), stop_gradient=True)
    if category_idxs is not None:
        # batched NMS via the coordinate-offset trick: boxes of different
        # categories can never overlap after shifting each category to its
        # own disjoint region (normalize to origin first so negative
        # coordinates can't make the regions overlap)
        cv = np.asarray(_unwrap(category_idxs))
        origin = bv - bv.min()
        span = origin.max() + 1.0
        shifted = origin + (cv.astype(np.float32) * span)[:, None]
    else:
        shifted = bv
    order = np.argsort(-sv, kind="stable")
    keep = np.asarray(_greedy_keep(jnp.asarray(shifted[order]), iou_threshold))
    kept = order[keep]
    # kept is already in descending-score order
    if top_k is not None:
        kept = kept[:top_k]
    return Tensor(kept.astype(np.int64), stop_gradient=True)


def matrix_nms(bboxes, scores, score_threshold, post_threshold, nms_top_k,
               keep_top_k, use_gaussian=False, gaussian_sigma=2.0,
               background_label=0, normalized=True, return_index=False,
               return_rois_num=True, name=None):
    """Matrix NMS (SOLOv2) — parallel soft-suppression, a natural TPU fit.

    Reference: python/paddle/vision/ops.py:2358 (phi matrix_nms kernel).
    bboxes [N, M, 4], scores [N, C, M].  Returns (out [K, 6], rois_num[, index]).
    """
    bv = np.asarray(_unwrap(bboxes), dtype=np.float32)
    sv = np.asarray(_unwrap(scores), dtype=np.float32)
    N, C, M = sv.shape
    outs, nums, idxs = [], [], []
    for n in range(N):
        per_cls = []
        for c in range(C):
            if c == background_label:
                continue
            s = sv[n, c]
            sel = np.nonzero(s > score_threshold)[0]
            if sel.size == 0:
                continue
            order = sel[np.argsort(-s[sel], kind="stable")][:nms_top_k]
            b = bv[n, order]
            sc = s[order]
            iou = np.asarray(_iou_matrix(jnp.asarray(b)))
            iou = np.triu(iou, k=1)
            # decay factor per box: how much its best overlapping
            # higher-scored box was itself suppressed
            iou_cmax = iou.max(axis=0)
            if use_gaussian:
                decay = np.exp((iou_cmax**2 - iou**2) * gaussian_sigma)
            else:
                decay = (1.0 - iou) / np.maximum(1.0 - iou_cmax, 1e-10)
            decay = decay.min(axis=0)
            dec = sc * decay
            keep = dec >= post_threshold
            if not keep.any():
                continue
            k = np.nonzero(keep)[0]
            per_cls.append((np.full(k.size, c, np.float32), dec[k], b[k], order[k] + n * M))
        if per_cls:
            cls = np.concatenate([p[0] for p in per_cls])
            dsc = np.concatenate([p[1] for p in per_cls])
            bb = np.concatenate([p[2] for p in per_cls])
            gi = np.concatenate([p[3] for p in per_cls])
            o = np.argsort(-dsc, kind="stable")[:keep_top_k]
            outs.append(np.concatenate([cls[o, None], dsc[o, None], bb[o]], axis=1))
            idxs.append(gi[o])
            nums.append(o.size)
        else:
            nums.append(0)
    out = np.concatenate(outs, axis=0) if outs else np.zeros((0, 6), np.float32)
    index = np.concatenate(idxs) if idxs else np.zeros((0,), np.int64)
    rois_num = np.asarray(nums, np.int32)
    ret = [Tensor(out, stop_gradient=True)]
    if return_index:
        ret.append(Tensor(index.astype(np.int64)[:, None], stop_gradient=True))
    if return_rois_num:
        ret.append(Tensor(rois_num, stop_gradient=True))
    return tuple(ret) if len(ret) > 1 else ret[0]


# --------------------------------------------------------------------------
# Deformable convolution
# --------------------------------------------------------------------------

def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable conv v1 (mask=None) / v2 — reference :766.

    One batched bilinear gather builds the im2col sample tensor
    [N, Cin, kh, kw, Ho, Wo]; the kernel contraction is a single einsum that
    XLA maps onto the MXU (vs the reference's per-position CUDA loops,
    ``deform_conv_kernel_impl.h``).
    """
    sh, sw = _pair(stride)
    ph_, pw_ = _pair(padding)
    dh, dw = _pair(dilation)

    def fn(xv, ov, wv, *rest):
        mv = bv = None
        rest = list(rest)
        if mask is not None:
            mv = rest.pop(0)
        if bias is not None:
            bv = rest.pop(0)
        N, Cin, H, W = xv.shape
        M, Cg, kh, kw = wv.shape
        dg = deformable_groups
        Ho = (H + 2 * ph_ - (dh * (kh - 1) + 1)) // sh + 1
        Wo = (W + 2 * pw_ - (dw * (kw - 1) + 1)) // sw + 1
        # base sampling grid per output position / kernel tap
        hb = (jnp.arange(Ho) * sh - ph_)[:, None] + (jnp.arange(kh) * dh)[None]  # [Ho, kh]
        wb = (jnp.arange(Wo) * sw - pw_)[:, None] + (jnp.arange(kw) * dw)[None]  # [Wo, kw]
        # offsets: [N, dg*2*kh*kw, Ho, Wo]; channel layout per deformable
        # group block: 2*k = y-offset of tap k, 2*k+1 = x-offset
        ov = ov.reshape(N, dg, kh * kw, 2, Ho, Wo)
        # sample coords [N, dg, kh, kw, Ho, Wo]
        yoff = ov[:, :, :, 0].reshape(N, dg, kh, kw, Ho, Wo)
        xoff = ov[:, :, :, 1].reshape(N, dg, kh, kw, Ho, Wo)
        ys = hb.T[None, None, :, None, :, None] + yoff  # hb.T: [kh, Ho]
        xs = wb.T[None, None, None, :, None, :] + xoff
        Cper = Cin // dg

        def sample_one(feat_g, yy, xx):
            # feat_g [Cper, H, W]; yy/xx [kh, kw, Ho, Wo]
            return _bilinear_gather(feat_g, yy, xx)

        def per_image(feat, yy, xx, mm):
            # feat [Cin, H, W] -> [dg, Cper, H, W]
            fg = feat.reshape(dg, Cper, H, W)
            cols = jax.vmap(sample_one)(fg, yy, xx)  # [dg, Cper, kh, kw, Ho, Wo]
            if mm is not None:
                cols = cols * mm[:, None]  # mm [dg, kh, kw, Ho, Wo]
            return cols.reshape(Cin, kh, kw, Ho, Wo)

        mm_all = (mv.reshape(N, dg, kh, kw, Ho, Wo) if mv is not None
                  else [None] * N)
        if mv is not None:
            cols = jax.vmap(per_image)(xv, ys, xs, mm_all)
        else:
            cols = jax.vmap(lambda f, yy, xx: per_image(f, yy, xx, None))(xv, ys, xs)
        # grouped contraction on the MXU
        cols = cols.reshape(N, groups, Cin // groups, kh, kw, Ho, Wo)
        wg = wv.reshape(groups, M // groups, Cg, kh, kw)
        out = jnp.einsum("ngcijhw,gmcij->ngmhw", cols, wg)
        out = out.reshape(N, M, Ho, Wo)
        if bv is not None:
            out = out + bv.reshape(1, M, 1, 1)
        return out

    inputs = [x, offset, weight]
    if mask is not None:
        inputs.append(mask)
    if bias is not None:
        inputs.append(bias)
    return apply_op("deform_conv2d", fn, inputs)


class DeformConv2D(nn.Layer):
    """Deformable conv layer — reference python/paddle/vision/ops.py:973."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        kh, kw = _pair(kernel_size)
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._deformable_groups = deformable_groups
        self._groups = groups
        fan_in = in_channels * kh * kw // groups
        bound = 1.0 / math.sqrt(fan_in)
        from ..nn import initializer as I
        self.weight = self.create_parameter(
            (out_channels, in_channels // groups, kh, kw), attr=weight_attr,
            default_initializer=I.Uniform(-bound, bound))
        self.bias = self.create_parameter(
            (out_channels,), attr=bias_attr, is_bias=True,
            default_initializer=I.Uniform(-bound, bound))

    def forward(self, x, offset, mask=None):
        return deform_conv2d(
            x, offset, self.weight, self.bias, stride=self._stride,
            padding=self._padding, dilation=self._dilation,
            deformable_groups=self._deformable_groups, groups=self._groups,
            mask=mask)


# --------------------------------------------------------------------------
# YOLO
# --------------------------------------------------------------------------

def _sigmoid_ce(logit, label):
    # numerically-stable sigmoid cross entropy (matches the reference's
    # SigmoidCrossEntropy in yolo_loss_kernel.cc)
    return jnp.maximum(logit, 0.0) - logit * label + jnp.log1p(jnp.exp(-jnp.abs(logit)))


def _cwh_iou(b1, b2):
    """IoU of boxes in (cx, cy, w, h) format; broadcast over leading dims."""
    l = jnp.maximum(b1[..., 0] - b1[..., 2] / 2, b2[..., 0] - b2[..., 2] / 2)
    r = jnp.minimum(b1[..., 0] + b1[..., 2] / 2, b2[..., 0] + b2[..., 2] / 2)
    t = jnp.maximum(b1[..., 1] - b1[..., 3] / 2, b2[..., 1] - b2[..., 3] / 2)
    b = jnp.minimum(b1[..., 1] + b1[..., 3] / 2, b2[..., 1] + b2[..., 3] / 2)
    iw = jnp.maximum(r - l, 0.0)
    ih = jnp.maximum(b - t, 0.0)
    inter = iw * ih
    union = b1[..., 2] * b1[..., 3] + b2[..., 2] * b2[..., 3] - inter
    return inter / jnp.maximum(union, 1e-10)


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """YOLOv3 loss — reference python/paddle/vision/ops.py:69; semantics from
    phi/kernels/cpu/yolo_loss_kernel.cc (vectorized except the per-gt
    objectness scatter, which runs one gt per step so duplicate (anchor,
    cell) matches resolve last-gt-wins like the reference's serial writes).

    Returns per-sample loss [N].
    """
    anchors = list(anchors)
    anchor_mask = list(anchor_mask)
    an_num = len(anchors) // 2
    mask_num = len(anchor_mask)
    scale = float(scale_x_y)
    bias = -0.5 * (scale - 1.0)

    def fn(xv, gbv, glv, *rest):
        gsv = rest[0] if rest else None
        N, _, H, W = xv.shape
        B = gbv.shape[1]
        input_size = downsample_ratio * H
        xr = xv.reshape(N, mask_num, 5 + class_num, H, W)
        if gsv is None:
            score = jnp.ones((N, B), xv.dtype)
        else:
            score = gsv
        valid = (gbv[:, :, 2] > 1e-6) & (gbv[:, :, 3] > 1e-6)  # [N, B]

        aw = jnp.asarray(anchors[0::2], xv.dtype)
        ah = jnp.asarray(anchors[1::2], xv.dtype)
        maw = aw[jnp.asarray(anchor_mask)]
        mah = ah[jnp.asarray(anchor_mask)]

        gx = jnp.arange(W, dtype=xv.dtype)[None, None, None, :]
        gy = jnp.arange(H, dtype=xv.dtype)[None, None, :, None]
        # predicted boxes (normalized) for ignore-mask IoU; grid_size is H
        # (the reference assumes square grids, yolo_loss_kernel.cc:63)
        px = (gx + jax.nn.sigmoid(xr[:, :, 0]) * scale + bias) / H
        py = (gy + jax.nn.sigmoid(xr[:, :, 1]) * scale + bias) / H
        pw = jnp.exp(xr[:, :, 2]) * maw[None, :, None, None] / input_size
        phh = jnp.exp(xr[:, :, 3]) * mah[None, :, None, None] / input_size
        pred = jnp.stack([px, py, pw, phh], axis=-1)  # [N, mask, H, W, 4]
        iou = _cwh_iou(pred[:, :, :, :, None, :], gbv[:, None, None, None, :, :])
        iou = jnp.where(valid[:, None, None, None, :], iou, 0.0)
        best_iou = jnp.max(iou, axis=-1) if B else jnp.zeros_like(px)
        obj = jnp.where(best_iou > ignore_thresh, -1.0, 0.0)  # [N, mask, H, W]

        # -------- per-gt anchor matching --------
        gi = jnp.clip((gbv[:, :, 0] * W).astype(jnp.int32), 0, W - 1)
        gj = jnp.clip((gbv[:, :, 1] * H).astype(jnp.int32), 0, H - 1)
        zero = jnp.zeros_like(aw)
        an_wh = jnp.stack([zero, zero, aw / input_size, ah / input_size], axis=-1)  # [an, 4]
        gt_shift = gbv.at[:, :, 0:2].set(0.0) if B else gbv
        a_iou = _cwh_iou(an_wh[None, None, :, :], gt_shift[:, :, None, :])  # [N, B, an]
        best_n = jnp.argmax(a_iou, axis=-1)  # [N, B]
        # map best anchor index -> position in anchor_mask (-1 if absent)
        lut = -jnp.ones((an_num,), jnp.int32)
        for mi, a in enumerate(anchor_mask):
            lut = lut.at[a].set(mi)
        mask_idx = lut[best_n]  # [N, B]
        pos = valid & (mask_idx >= 0)

        # gather predicted entries at matched cells: [N, B, 5+C]
        nn_idx = jnp.arange(N)[:, None].repeat(B, 1)
        sel = xr[nn_idx, jnp.maximum(mask_idx, 0), :, gj, gi]
        tx = gbv[:, :, 0] * W - gi
        ty = gbv[:, :, 1] * H - gj
        tw = jnp.log(jnp.maximum(gbv[:, :, 2] * input_size / aw[best_n], 1e-9))
        th = jnp.log(jnp.maximum(gbv[:, :, 3] * input_size / ah[best_n], 1e-9))
        loc_scale = (2.0 - gbv[:, :, 2] * gbv[:, :, 3]) * score
        loss_loc = (_sigmoid_ce(sel[:, :, 0], tx) + _sigmoid_ce(sel[:, :, 1], ty)
                    + jnp.abs(sel[:, :, 2] - tw) + jnp.abs(sel[:, :, 3] - th)) * loc_scale

        if use_label_smooth:
            delta = min(1.0 / class_num, 1.0 / 40)
            lpos, lneg = 1.0 - delta, delta
        else:
            lpos, lneg = 1.0, 0.0
        onehot = jax.nn.one_hot(glv.astype(jnp.int32), class_num, dtype=xv.dtype)
        labels = onehot * lpos + (1.0 - onehot) * lneg
        loss_cls = jnp.sum(_sigmoid_ce(sel[:, :, 5:], labels), axis=-1) * score

        loss_pergt = jnp.where(pos, loss_loc + loss_cls, 0.0)
        loss = jnp.sum(loss_pergt, axis=-1)  # [N]

        # scatter gt scores into the objectness map; invalid/masked-out gts
        # are routed to row `mask_num`, which is out of bounds so mode="drop"
        # discards them (-1 would WRAP, not drop — negative indices are
        # normalized before the oob mode applies).  Scattering one gt per
        # step keeps within-step indices unique (distinct n), so when two
        # gts of one image land on the same (anchor, cell) the LAST gt wins
        # deterministically — XLA leaves duplicate-index set order
        # unspecified, while the reference's serial kernel overwrites in gt
        # order (yolo_loss_kernel.cc gt loop).
        drop_m = jnp.where(pos, mask_idx, mask_num)
        n_arr = jnp.arange(N)
        gt_val = jnp.where(pos, score, 0.0)

        def scatter_gt(b, o):
            return o.at[n_arr, drop_m[:, b], gj[:, b], gi[:, b]].set(
                gt_val[:, b], mode="drop")

        obj = jax.lax.fori_loop(0, B, scatter_gt, obj) if B else obj

        ologit = xr[:, :, 4]
        pos_l = _sigmoid_ce(ologit, 1.0) * obj
        neg_l = _sigmoid_ce(ologit, 0.0)
        obj_loss = jnp.where(obj > 1e-5, pos_l, jnp.where(obj > -0.5, neg_l, 0.0))
        loss = loss + jnp.sum(obj_loss, axis=(1, 2, 3))
        return loss

    inputs = [x, gt_box, gt_label]
    if gt_score is not None:
        inputs.append(gt_score)
    return apply_op("yolo_loss", fn, inputs)


def yolo_box(x, img_size, anchors, class_num, conf_thresh, downsample_ratio,
             clip_bbox=True, name=None, scale_x_y=1.0, iou_aware=False,
             iou_aware_factor=0.5):
    """Decode YOLOv3 head output into boxes + scores — reference :277.

    Returns (boxes [N, H*W*an, 4] xyxy in image coords, scores [N, H*W*an, class_num]).
    """
    anchors = list(anchors)
    an_num = len(anchors) // 2
    scale = float(scale_x_y)
    bias = -0.5 * (scale - 1.0)

    def fn(xv, imv):
        N, C, H, W = xv.shape
        input_size = downsample_ratio * H
        per = C // an_num
        xr = xv.reshape(N, an_num, per, H, W)
        if iou_aware:
            # iou-aware layout: the first an_num channels are iou logits,
            # the rest is the standard an_num*(5+cls) block
            ious = xv[:, :an_num].reshape(N, an_num, H, W)
            xr = xv[:, an_num:].reshape(N, an_num, 5 + class_num, H, W)
        aw = jnp.asarray(anchors[0::2], xv.dtype)[None, :, None, None]
        ah = jnp.asarray(anchors[1::2], xv.dtype)[None, :, None, None]
        gx = jnp.arange(W, dtype=xv.dtype)[None, None, None, :]
        gy = jnp.arange(H, dtype=xv.dtype)[None, None, :, None]
        cx = (gx + jax.nn.sigmoid(xr[:, :, 0]) * scale + bias) / W
        cy = (gy + jax.nn.sigmoid(xr[:, :, 1]) * scale + bias) / H
        bw = jnp.exp(xr[:, :, 2]) * aw / input_size
        bh = jnp.exp(xr[:, :, 3]) * ah / input_size
        conf = jax.nn.sigmoid(xr[:, :, 4])
        if iou_aware:
            conf = conf ** (1.0 - iou_aware_factor) * jax.nn.sigmoid(ious) ** iou_aware_factor
        keep = conf >= conf_thresh
        score = conf[:, :, None] * jax.nn.sigmoid(xr[:, :, 5:])  # [N, an, cls, H, W]
        img_h = imv[:, 0].astype(xv.dtype)[:, None, None, None]
        img_w = imv[:, 1].astype(xv.dtype)[:, None, None, None]
        x1 = (cx - bw / 2.0) * img_w
        y1 = (cy - bh / 2.0) * img_h
        x2 = (cx + bw / 2.0) * img_w
        y2 = (cy + bh / 2.0) * img_h
        if clip_bbox:
            x1 = jnp.clip(x1, 0.0, img_w - 1.0)
            y1 = jnp.clip(y1, 0.0, img_h - 1.0)
            x2 = jnp.clip(x2, 0.0, img_w - 1.0)
            y2 = jnp.clip(y2, 0.0, img_h - 1.0)
        boxes = jnp.stack([x1, y1, x2, y2], axis=-1)  # [N, an, H, W, 4]
        boxes = jnp.where(keep[..., None], boxes, 0.0)
        score = jnp.where(keep[:, :, None], score, 0.0)
        boxes = boxes.reshape(N, an_num * H * W, 4)
        score = jnp.moveaxis(score, 2, -1).reshape(N, an_num * H * W, class_num)
        return boxes, score

    return apply_op("yolo_box", fn, [x, img_size])


# --------------------------------------------------------------------------
# Anchors / box coding / FPN routing
# --------------------------------------------------------------------------

def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=[1.0],
              variance=[0.1, 0.1, 0.2, 0.2], flip=False, clip=False,
              steps=[0.0, 0.0], offset=0.5, min_max_aspect_ratios_order=False,
              name=None):
    """SSD prior boxes — reference python/paddle/vision/ops.py:438.

    Returns (boxes [H, W, num_priors, 4], variances same shape), normalized.
    """
    def as_list(v):
        return [float(v)] if isinstance(v, (int, float)) else [float(a) for a in v]

    min_sizes_l = as_list(min_sizes)
    max_sizes_l = as_list(max_sizes) if max_sizes is not None else []
    ars = [1.0]
    for ar in as_list(aspect_ratios):
        if not any(abs(ar - e) < 1e-6 for e in ars):
            ars.append(ar)
            if flip:
                ars.append(1.0 / ar)

    H, W = (int(s) for s in input.shape[2:4])
    img_h, img_w = (int(s) for s in image.shape[2:4])
    steps = as_list(steps) if not isinstance(steps, (int, float)) else [float(steps)] * 2
    step_w = steps[0] if steps[0] > 0 else img_w / W
    step_h = steps[1] if steps[1] > 0 else img_h / H

    # per-position box template: list of (box_w, box_h) in pixels
    wh = []
    for k, s_min in enumerate(min_sizes_l):
        if min_max_aspect_ratios_order:
            wh.append((s_min, s_min))
            if max_sizes_l:
                s = math.sqrt(s_min * max_sizes_l[k])
                wh.append((s, s))
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                wh.append((s_min * math.sqrt(ar), s_min / math.sqrt(ar)))
        else:
            for ar in ars:
                wh.append((s_min * math.sqrt(ar), s_min / math.sqrt(ar)))
            if max_sizes_l:
                s = math.sqrt(s_min * max_sizes_l[k])
                wh.append((s, s))
    num_priors = len(wh)
    wh_arr = np.asarray(wh, np.float32)  # [P, 2]
    cx = (np.arange(W, dtype=np.float32) + offset) * step_w
    cy = (np.arange(H, dtype=np.float32) + offset) * step_h
    CX, CY = np.meshgrid(cx, cy)  # [H, W]
    out = np.empty((H, W, num_priors, 4), np.float32)
    out[..., 0] = (CX[:, :, None] - wh_arr[None, None, :, 0] / 2) / img_w
    out[..., 1] = (CY[:, :, None] - wh_arr[None, None, :, 1] / 2) / img_h
    out[..., 2] = (CX[:, :, None] + wh_arr[None, None, :, 0] / 2) / img_w
    out[..., 3] = (CY[:, :, None] + wh_arr[None, None, :, 1] / 2) / img_h
    if clip:
        out = np.clip(out, 0.0, 1.0)
    var = np.broadcast_to(np.asarray(as_list(variance), np.float32), out.shape).copy()
    return Tensor(out, stop_gradient=True), Tensor(var, stop_gradient=True)


def box_coder(prior_box, prior_box_var, target_box, code_type="encode_center_size",
              box_normalized=True, axis=0, name=None):
    """Encode/decode boxes against priors — reference :584."""
    norm = 0.0 if box_normalized else 1.0

    pv_is_tensor = not isinstance(prior_box_var, (list, tuple)) and prior_box_var is not None

    def fn(pb, tb, *rest):
        if pv_is_tensor:
            pvar = rest[0]
        elif prior_box_var is None:
            pvar = jnp.ones((4,), pb.dtype)
        else:
            pvar = jnp.asarray(prior_box_var, pb.dtype)
        pw = pb[:, 2] - pb[:, 0] + norm
        ph = pb[:, 3] - pb[:, 1] + norm
        pxc = pb[:, 0] + pw * 0.5
        pyc = pb[:, 1] + ph * 0.5
        if code_type == "encode_center_size":
            # tb [N, 4] vs priors [M, 4] -> [N, M, 4]
            tw = tb[:, 2] - tb[:, 0] + norm
            th = tb[:, 3] - tb[:, 1] + norm
            txc = tb[:, 0] + tw * 0.5
            tyc = tb[:, 1] + th * 0.5
            pvar2 = pvar if pvar.ndim == 2 else pvar[None]
            ox = (txc[:, None] - pxc[None]) / pw[None] / pvar2[..., 0]
            oy = (tyc[:, None] - pyc[None]) / ph[None] / pvar2[..., 1]
            ow = jnp.log(jnp.abs(tw[:, None] / pw[None])) / pvar2[..., 2]
            oh = jnp.log(jnp.abs(th[:, None] / ph[None])) / pvar2[..., 3]
            return jnp.stack([ox, oy, ow, oh], axis=-1)
        elif code_type == "decode_center_size":
            # tb [N, M, 4]; priors broadcast along `axis`
            exp = (lambda a: a[None, :]) if axis == 0 else (lambda a: a[:, None])
            pvar2 = pvar if pvar.ndim == 2 else jnp.broadcast_to(pvar, pb.shape)
            vx, vy, vw, vh = (exp(pvar2[:, i]) for i in range(4))
            bx = vx * tb[..., 0] * exp(pw) + exp(pxc)
            by = vy * tb[..., 1] * exp(ph) + exp(pyc)
            bw = jnp.exp(vw * tb[..., 2]) * exp(pw)
            bh = jnp.exp(vh * tb[..., 3]) * exp(ph)
            return jnp.stack([bx - bw / 2, by - bh / 2,
                              bx + bw / 2 - norm, by + bh / 2 - norm], axis=-1)
        raise ValueError(f"unknown code_type {code_type!r}")

    inputs = [prior_box, target_box]
    if pv_is_tensor:
        inputs.append(prior_box_var)
    return apply_op("box_coder", fn, inputs)


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000, nms_thresh=0.5,
                       min_size=0.1, eta=1.0, pixel_offset=False,
                       return_rois_num=False, name=None):
    """RPN proposal generation — reference python/paddle/vision/ops.py:2159
    (phi generate_proposals kernel): top-k by score, anchor decode with
    variances, clip to image, min-size filter, NMS, top post_nms_top_n.

    Eager-mode (data-dependent output length, like the reference's LoD
    outputs).  scores [N,A,H,W], bbox_deltas [N,4A,H,W], anchors/variances
    [H,W,A,4].
    """
    sv = np.asarray(_unwrap(scores), np.float32)
    dv = np.asarray(_unwrap(bbox_deltas), np.float32)
    imv = np.asarray(_unwrap(img_size), np.float32)
    av = np.asarray(_unwrap(anchors), np.float32).reshape(-1, 4)
    vv = np.asarray(_unwrap(variances), np.float32).reshape(-1, 4)
    N, A, H, W = sv.shape
    off = 1.0 if pixel_offset else 0.0
    bbox_clip = math.log(1000.0 / 16.0)  # phi kBBoxClipDefault

    all_rois, all_probs, nums = [], [], []
    for n in range(N):
        s = sv[n].transpose(1, 2, 0).ravel()                       # [H*W*A]
        d = dv[n].reshape(A, 4, H, W).transpose(2, 3, 0, 1).reshape(-1, 4)
        k = min(pre_nms_top_n, s.size)
        order = np.argsort(-s, kind="stable")[:k]
        s, d, anc, var = s[order], d[order], av[order], vv[order]
        aw = anc[:, 2] - anc[:, 0] + off
        ah = anc[:, 3] - anc[:, 1] + off
        acx = anc[:, 0] + aw * 0.5
        acy = anc[:, 1] + ah * 0.5
        cx = d[:, 0] * var[:, 0] * aw + acx
        cy = d[:, 1] * var[:, 1] * ah + acy
        bw = np.exp(np.minimum(d[:, 2] * var[:, 2], bbox_clip)) * aw
        bh = np.exp(np.minimum(d[:, 3] * var[:, 3], bbox_clip)) * ah
        boxes = np.stack([cx - bw / 2 + off * 0.5, cy - bh / 2 + off * 0.5,
                          cx + bw / 2 - off * 0.5, cy + bh / 2 - off * 0.5], 1)
        im_h, im_w = imv[n, 0], imv[n, 1]
        boxes[:, 0::2] = np.clip(boxes[:, 0::2], 0, im_w - off)
        boxes[:, 1::2] = np.clip(boxes[:, 1::2], 0, im_h - off)
        ms = max(float(min_size), 1.0)
        ww = boxes[:, 2] - boxes[:, 0] + off
        hh = boxes[:, 3] - boxes[:, 1] + off
        keep = (ww >= ms) & (hh >= ms)
        boxes, s = boxes[keep], s[keep]
        if boxes.shape[0]:
            # adaptive-eta greedy NMS (already score-sorted), row-lazy: one
            # IoU row per KEPT box (<= post_nms_top_n rows) instead of the
            # full pre_nms_top_n^2 matrix
            areas = np.maximum(boxes[:, 2] - boxes[:, 0], 0) * np.maximum(
                boxes[:, 3] - boxes[:, 1], 0)
            kept = []
            thresh = nms_thresh
            sup = np.zeros(boxes.shape[0], bool)
            for i in range(boxes.shape[0]):
                if sup[i]:
                    continue
                kept.append(i)
                if len(kept) >= post_nms_top_n:
                    break
                lt = np.maximum(boxes[i, :2], boxes[:, :2])
                rb = np.minimum(boxes[i, 2:], boxes[:, 2:])
                wh = np.maximum(rb - lt, 0.0)
                inter = wh[:, 0] * wh[:, 1]
                iou_row = inter / np.maximum(areas[i] + areas - inter, 1e-10)
                sup |= iou_row > thresh
                sup[i] = True
                if eta < 1.0 and thresh > 0.5:
                    thresh *= eta
            kept = np.asarray(kept, np.int64)
            boxes, s = boxes[kept], s[kept]
        all_rois.append(boxes)
        all_probs.append(s[:, None])
        nums.append(boxes.shape[0])
    rois = Tensor(np.concatenate(all_rois, 0) if all_rois else np.zeros((0, 4), np.float32),
                  stop_gradient=True)
    probs = Tensor(np.concatenate(all_probs, 0) if all_probs else np.zeros((0, 1), np.float32),
                   stop_gradient=True)
    if return_rois_num:
        return rois, probs, Tensor(np.asarray(nums, np.int32), stop_gradient=True)
    return rois, probs


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False, rois_num=None,
                             name=None):
    """Route ROIs to FPN levels by scale — reference :1175.

    level = floor(log2(sqrt(area)/refer_scale)) + refer_level, clipped.
    Output lengths are data-dependent -> eager-mode (like the reference's
    dynamic LoD outputs).
    """
    assert max_level > min_level > 0
    rv = np.asarray(_unwrap(fpn_rois), dtype=np.float32)
    off = 1.0 if pixel_offset else 0.0
    w = np.maximum(rv[:, 2] - rv[:, 0] + off, 0.0)
    h = np.maximum(rv[:, 3] - rv[:, 1] + off, 0.0)
    scale = np.sqrt(w * h)
    lvl = np.floor(np.log2(scale / float(refer_scale) + 1e-8)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    num_lvl = max_level - min_level + 1
    multi_rois, restore_parts, nums_per_level = [], [], []
    if rois_num is not None:
        rn = np.asarray(_unwrap(rois_num), dtype=np.int64)
        img_of = np.repeat(np.arange(rn.size), rn)
    for li in range(num_lvl):
        sel = np.nonzero(lvl == min_level + li)[0]
        multi_rois.append(Tensor(rv[sel], stop_gradient=True))
        restore_parts.append(sel)
        if rois_num is not None:
            nums_per_level.append(Tensor(
                np.bincount(img_of[sel], minlength=rn.size).astype(np.int32),
                stop_gradient=True))
    order = np.concatenate(restore_parts) if restore_parts else np.zeros(0, np.int64)
    restore = np.empty_like(order)
    restore[order] = np.arange(order.size)
    restore_t = Tensor(restore.astype(np.int32)[:, None], stop_gradient=True)
    if rois_num is not None:
        return multi_rois, restore_t, nums_per_level
    return multi_rois, restore_t


def read_file(filename, name=None):
    """Raw file bytes as a 1-D uint8 Tensor (reference:
    python/paddle/vision/ops.py read_file — a host IO op there too)."""
    with open(filename, "rb") as f:
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return Tensor(data, stop_gradient=True)


def decode_jpeg(x, mode="unchanged", name=None):
    """Decode a JPEG byte Tensor to uint8 [C, H, W] (reference:
    python/paddle/vision/ops.py decode_jpeg — nvjpeg there; a host decode
    here, since image IO feeds the input pipeline, not the TPU graph)."""
    import io as _io

    try:
        from PIL import Image
    except ImportError as e:  # pragma: no cover - PIL is in the image
        raise RuntimeError("decode_jpeg requires Pillow") from e

    raw = np.asarray(_unwrap(x), dtype=np.uint8).tobytes()
    img = Image.open(_io.BytesIO(raw))
    if mode == "gray":
        img = img.convert("L")
    elif mode == "rgb":
        img = img.convert("RGB")
    elif mode != "unchanged":
        raise ValueError(f"unsupported decode_jpeg mode: {mode!r}")
    arr = np.asarray(img, dtype=np.uint8)
    if arr.ndim == 2:
        arr = arr[None]  # [1, H, W]
    else:
        arr = np.transpose(arr, (2, 0, 1))  # [C, H, W]
    return Tensor(arr, stop_gradient=True)
