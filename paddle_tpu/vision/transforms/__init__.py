"""Vision transforms (reference: python/paddle/vision/transforms/) — numpy CHW float."""

from __future__ import annotations

import numpy as np

__all__ = [
    "Compose", "Normalize", "ToTensor", "Resize", "RandomHorizontalFlip",
    "RandomCrop", "CenterCrop", "Transpose", "RandomVerticalFlip", "Pad",
    "Grayscale", "BrightnessTransform", "ContrastTransform",
    "SaturationTransform", "HueTransform", "ColorJitter", "RandomRotation",
    "RandomResizedCrop", "RandomErasing",
    "RandomAffine",
    "RandomPerspective",
]


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def __call__(self, img):
        img = np.asarray(img, np.float32)
        shape = (-1, 1, 1) if self.data_format == "CHW" else (1, 1, -1)
        return (img - self.mean.reshape(shape)) / self.std.reshape(shape)


class ToTensor:
    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def __call__(self, img):
        a = np.asarray(img, np.float32)
        if a.max() > 1.5:
            a = a / 255.0
        if a.ndim == 3 and self.data_format == "CHW" and a.shape[0] not in (1, 3):
            a = a.transpose(2, 0, 1)
        return a


class Transpose:
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def __call__(self, img):
        return np.asarray(img).transpose(self.order)


class Resize:
    def __init__(self, size, interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        import jax

        a = np.asarray(img, np.float32)
        chw = a.ndim == 3 and a.shape[0] in (1, 3)
        if chw:
            out_shape = (a.shape[0],) + self.size
        else:
            out_shape = self.size + ((a.shape[-1],) if a.ndim == 3 else ())
        return np.asarray(jax.image.resize(a, out_shape, method="bilinear"))


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return np.asarray(img)[..., ::-1].copy()
        return np.asarray(img)


class RandomCrop:
    def __init__(self, size, padding=0, pad_if_needed=False, fill=0,
                 padding_mode="constant"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding
        self.pad_if_needed = pad_if_needed
        self.fill = fill
        self.padding_mode = padding_mode

    def __call__(self, img):
        a = np.asarray(img)
        chw = a.ndim == 3 and a.shape[0] in (1, 3)
        h_ax, w_ax = (1, 2) if chw else (0, 1)
        mode = {"constant": "constant", "reflect": "reflect", "edge": "edge",
                "symmetric": "symmetric"}[self.padding_mode]
        kw = {"constant_values": self.fill} if mode == "constant" else {}
        if self.padding:
            pad = [(0, 0)] * a.ndim
            pad[h_ax] = pad[w_ax] = (self.padding, self.padding)
            a = np.pad(a, pad, mode=mode, **kw)
        th, tw = self.size
        if self.pad_if_needed:  # reference: grow to at least the crop size
            extra_h = max(th - a.shape[h_ax], 0)
            extra_w = max(tw - a.shape[w_ax], 0)
            if extra_h or extra_w:
                pad = [(0, 0)] * a.ndim
                pad[h_ax] = (extra_h, extra_h)
                pad[w_ax] = (extra_w, extra_w)
                a = np.pad(a, pad, mode=mode, **kw)
        i = np.random.randint(0, a.shape[h_ax] - th + 1)
        j = np.random.randint(0, a.shape[w_ax] - tw + 1)
        sl = [slice(None)] * a.ndim
        sl[h_ax] = slice(i, i + th)
        sl[w_ax] = slice(j, j + tw)
        return a[tuple(sl)]


class CenterCrop:
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        a = np.asarray(img)
        chw = a.ndim == 3 and a.shape[0] in (1, 3)
        h_ax, w_ax = (1, 2) if chw else (0, 1)
        th, tw = self.size
        i = (a.shape[h_ax] - th) // 2
        j = (a.shape[w_ax] - tw) // 2
        sl = [slice(None)] * a.ndim
        sl[h_ax] = slice(i, i + th)
        sl[w_ax] = slice(j, j + tw)
        return a[tuple(sl)]


class RandomVerticalFlip:
    """Reference: vision/transforms/transforms.py:RandomVerticalFlip."""

    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return np.ascontiguousarray(np.asarray(img)[::-1])
        return img


class Pad:
    """Pad on all sides (reference transforms.py:Pad); img HWC or CHW-agnostic
    ndarray — pads the two leading spatial dims."""

    def __init__(self, padding, fill=0, padding_mode="constant"):
        self.padding = padding if isinstance(padding, (list, tuple)) else (padding,) * 4
        self.fill = fill
        self.padding_mode = padding_mode

    def __call__(self, img):
        arr = np.asarray(img)
        l, t, r, b = (self.padding if len(self.padding) == 4
                      else (self.padding[0], self.padding[1]) * 2)
        pads = [(t, b), (l, r)] + [(0, 0)] * (arr.ndim - 2)
        mode = {"constant": "constant", "reflect": "reflect",
                "edge": "edge", "symmetric": "symmetric"}[self.padding_mode]
        kw = {"constant_values": self.fill} if mode == "constant" else {}
        return np.pad(arr, pads, mode=mode, **kw)


class Grayscale:
    """Reference transforms.py:Grayscale; HWC input.  Delegates to the
    functional op so dtype preservation lives in one place."""

    def __init__(self, num_output_channels=1):
        self.num_output_channels = num_output_channels

    def __call__(self, img):
        from . import functional as _F

        return _F.to_grayscale(img, self.num_output_channels)


class BrightnessTransform:
    def __init__(self, value):
        self.value = value

    def __call__(self, img):
        from . import functional as _F

        f = 1.0 + np.random.uniform(-self.value, self.value)
        return _F.adjust_brightness(img, f)


class ContrastTransform:
    def __init__(self, value):
        self.value = value

    def __call__(self, img):
        from . import functional as _F

        f = 1.0 + np.random.uniform(-self.value, self.value)
        return _F.adjust_contrast(img, f)


class SaturationTransform:
    def __init__(self, value):
        self.value = value

    def __call__(self, img):
        from . import functional as _F

        f = 1.0 + np.random.uniform(-self.value, self.value)
        return _F.adjust_saturation(img, f)


class HueTransform:
    """Approximate hue shift via channel rotation mix (reference uses HSV;
    the YIQ rotation in functional.adjust_hue matches for small angles).
    ``value`` is bounded to [0, 0.5] like the reference (transforms.py
    HueTransform), so the sampled factor always satisfies adjust_hue's
    [-0.5, 0.5] contract."""

    def __init__(self, value):
        if not 0 <= value <= 0.5:
            raise ValueError("hue value must be in [0, 0.5]")
        self.value = value

    def __call__(self, img):
        from . import functional as _F

        return _F.adjust_hue(img, np.random.uniform(-self.value, self.value))


class ColorJitter:
    """Reference transforms.py:ColorJitter — random order of B/C/S/H."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        self.ts = []
        if brightness:
            self.ts.append(BrightnessTransform(brightness))
        if contrast:
            self.ts.append(ContrastTransform(contrast))
        if saturation:
            self.ts.append(SaturationTransform(saturation))
        if hue:
            self.ts.append(HueTransform(hue))

    def __call__(self, img):
        order = np.random.permutation(len(self.ts))
        for i in order:
            img = self.ts[i](img)
        return img


class RandomRotation:
    """Nearest-neighbor rotation (reference transforms.py:RandomRotation)."""

    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0):
        self.degrees = (degrees if isinstance(degrees, (list, tuple))
                        else (-degrees, degrees))
        self.fill = fill

    def __call__(self, img):
        arr = np.asarray(img)
        angle = np.radians(np.random.uniform(*self.degrees))
        h, w = arr.shape[:2]
        cy, cx = (h - 1) / 2, (w - 1) / 2
        yy, xx = np.mgrid[0:h, 0:w]
        ys = cy + (yy - cy) * np.cos(angle) - (xx - cx) * np.sin(angle)
        xs = cx + (yy - cy) * np.sin(angle) + (xx - cx) * np.cos(angle)
        return _inverse_warp(arr, xs, ys, self.fill)


def _inverse_warp(arr, xs, ys, fill):
    """Nearest-sample arr at float source coords (xs, ys); fill outside."""
    h, w = arr.shape[:2]
    xi = np.clip(np.round(xs).astype(int), 0, w - 1)
    yi = np.clip(np.round(ys).astype(int), 0, h - 1)
    out = arr[yi, xi].copy()
    oob = (xs < 0) | (xs > w - 1) | (ys < 0) | (ys > h - 1)
    out[oob] = fill
    return out


class RandomAffine:
    """Affine warp with random angle/translate/scale/shear (reference
    transforms.py:1555): inverse-mapped nearest sampling, fill outside."""

    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None):
        self.degrees = (degrees if isinstance(degrees, (list, tuple))
                        else (-degrees, degrees))
        self.translate = translate
        self.scale_rng = scale
        self.shear = (None if shear is None else
                      (shear if isinstance(shear, (list, tuple)) else (-shear, shear)))
        self.fill = fill
        self.center = center

    def _matrix(self, h, w):
        ang = np.radians(np.random.uniform(*self.degrees))
        tx = ty = 0.0
        if self.translate is not None:
            tx = np.random.uniform(-self.translate[0], self.translate[0]) * w
            ty = np.random.uniform(-self.translate[1], self.translate[1]) * h
        sc = (np.random.uniform(*self.scale_rng)
              if self.scale_rng is not None else 1.0)
        shx = shy = 0.0
        if self.shear is not None:
            shx = np.radians(np.random.uniform(self.shear[0], self.shear[1]))
            if len(self.shear) == 4:
                shy = np.radians(np.random.uniform(self.shear[2], self.shear[3]))
        cx, cy = (self.center if self.center is not None
                  else ((w - 1) / 2, (h - 1) / 2))
        # forward affine: T(center) R(ang) Scale Shear T(-center) + trans
        rot = np.array([[np.cos(ang), -np.sin(ang)],
                        [np.sin(ang), np.cos(ang)]])
        # two unit-determinant triangular shears (reference
        # functional.py:598 composition) — never singular
        sh = (np.array([[1, np.tan(shx)], [0, 1]])
              @ np.array([[1, 0], [np.tan(shy), 1]]))
        m2 = sc * (rot @ sh)
        offs = np.array([cx + tx, cy + ty]) - m2 @ np.array([cx, cy])
        return m2, offs

    def __call__(self, img):
        arr = np.asarray(img)
        h, w = arr.shape[:2]
        m2, offs = self._matrix(h, w)
        inv = np.linalg.inv(m2)
        yy, xx = np.mgrid[0:h, 0:w]
        # map OUTPUT pixel -> source location (inverse warp); coords are (x, y)
        src = np.stack([xx - offs[0], yy - offs[1]], axis=-1) @ inv.T
        return _inverse_warp(arr, src[..., 0], src[..., 1], self.fill)


class RandomPerspective:
    """Random 4-corner perspective warp with probability ``prob``
    (reference transforms.py:1846): homography solved from the corner
    displacements, inverse-mapped nearest sampling."""

    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="nearest", fill=0):
        self.prob = prob
        self.distortion_scale = distortion_scale
        self.fill = fill

    @staticmethod
    def _homography(src, dst):
        # solve h (8 dof) with dst = H src
        A, b = [], []
        for (x, y), (u, v) in zip(src, dst):
            A.append([x, y, 1, 0, 0, 0, -u * x, -u * y]); b.append(u)
            A.append([0, 0, 0, x, y, 1, -v * x, -v * y]); b.append(v)
        hvec = np.linalg.solve(np.asarray(A, np.float64),
                               np.asarray(b, np.float64))
        return np.append(hvec, 1.0).reshape(3, 3)

    def __call__(self, img):
        arr = np.asarray(img)
        if np.random.rand() >= self.prob:
            return arr
        h, w = arr.shape[:2]
        d = self.distortion_scale
        dx, dy = w * d / 2, h * d / 2
        corners = np.array([[0, 0], [w - 1, 0], [w - 1, h - 1], [0, h - 1]],
                           np.float64)
        jitter = np.random.uniform(0, 1, (4, 2)) * [dx, dy]
        signs = np.array([[1, 1], [-1, 1], [-1, -1], [1, -1]], np.float64)
        dst = corners + jitter * signs
        H = self._homography(corners, dst)
        Hinv = np.linalg.inv(H)
        yy, xx = np.mgrid[0:h, 0:w]
        ones = np.ones_like(xx)
        pts = np.stack([xx, yy, ones], axis=-1) @ Hinv.T
        return _inverse_warp(arr, pts[..., 0] / pts[..., 2],
                             pts[..., 1] / pts[..., 2], self.fill)


class RandomResizedCrop:
    """Reference transforms.py:RandomResizedCrop (HWC)."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear"):
        self.size = size if isinstance(size, (list, tuple)) else (size, size)
        self.scale = scale
        self.ratio = ratio

    def __call__(self, img):
        arr = np.asarray(img)
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]), np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target * ar)))
            ch = int(round(np.sqrt(target / ar)))
            if 0 < cw <= w and 0 < ch <= h:
                y = np.random.randint(0, h - ch + 1)
                x = np.random.randint(0, w - cw + 1)
                crop = arr[y:y + ch, x:x + cw]
                return Resize(self.size)(crop)
        return Resize(self.size)(arr)


class RandomErasing:
    """Reference transforms.py:RandomErasing (operates on CHW tensors/arrays)."""

    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False):
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value
        self.inplace = inplace

    def __call__(self, img):
        arr = np.array(img, copy=True)
        if np.random.rand() >= self.prob:
            return arr
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3)
        h, w = (arr.shape[1], arr.shape[2]) if chw else (arr.shape[0], arr.shape[1])
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.random.uniform(*self.ratio)
            eh, ew = int(round(np.sqrt(target * ar))), int(round(np.sqrt(target / ar)))
            if eh < h and ew < w:
                y, x = np.random.randint(0, h - eh), np.random.randint(0, w - ew)
                if chw:
                    arr[:, y:y + eh, x:x + ew] = self.value
                else:
                    arr[y:y + eh, x:x + ew] = self.value
                return arr
        return arr


class BaseTransform:
    """User-extensible transform base (reference:
    vision/transforms/transforms.py BaseTransform): ``keys`` names each
    element of a tuple input ('image', 'boxes', ...); subclasses implement
    ``_apply_<key>`` and optionally ``_get_params`` for shared randomness."""

    def __init__(self, keys=None):
        if keys is None:
            keys = ("image",)
        elif not isinstance(keys, (list, tuple)):
            raise TypeError("keys must be a list or tuple")
        self.keys = tuple(keys)
        self.params = None

    def _get_params(self, inputs):
        return None

    def __call__(self, inputs):
        single = not isinstance(inputs, (tuple, list))
        ins = (inputs,) if single else tuple(inputs)
        self.params = self._get_params(ins)
        outputs = []
        for i, x in enumerate(ins):
            key = self.keys[i] if i < len(self.keys) else None
            fn = getattr(self, f"_apply_{key}", None) if key and key != "none" else None
            outputs.append(fn(x) if fn is not None else x)
        return outputs[0] if single else tuple(outputs)

    def _apply_image(self, image):
        raise NotImplementedError


from . import functional  # noqa: E402,F401
from .functional import (  # noqa: E402,F401
    adjust_brightness,
    adjust_contrast,
    adjust_hue,
    adjust_saturation,
    affine,
    center_crop,
    crop,
    erase,
    hflip,
    normalize,
    pad,
    perspective,
    resize,
    rotate,
    to_grayscale,
    to_tensor,
    vflip,
)

__all__ += ["BaseTransform", "functional"] + functional.__all__


def _keysify(cls):
    """Give a transform class the BaseTransform ``keys`` protocol
    (reference: every transforms.py class takes keys=None): tuple inputs
    dispatch per key — 'image' entries run the transform, anything else
    passes through.  Note: with MULTIPLE image-typed keys, random
    transforms re-sample per entry here (the reference shares one
    _get_params draw across keys)."""
    import inspect as _inspect

    orig_init = cls.__init__
    orig_call = cls.__call__

    def __init__(self, *args, keys=None, **kwargs):
        orig_init(self, *args, **kwargs)
        if keys is not None and not isinstance(keys, (list, tuple)):
            raise TypeError("keys must be a list or tuple")
        self.keys = tuple(keys) if keys is not None else ("image",)

    # keep introspection honest: expose the original parameters + keys
    # (a bare (*args, **kwargs) signature would also blind the
    # constructor-parity audit to these classes)
    orig_sig = _inspect.signature(orig_init)
    params = [p for p in orig_sig.parameters.values()
              if p.kind not in (p.VAR_POSITIONAL, p.VAR_KEYWORD)]
    params.append(_inspect.Parameter("keys", _inspect.Parameter.KEYWORD_ONLY,
                                     default=None))
    __init__.__signature__ = orig_sig.replace(parameters=params)

    def __call__(self, inputs):
        if isinstance(inputs, (tuple, list)):
            outs = []
            for i, x in enumerate(inputs):
                key = self.keys[i] if i < len(self.keys) else None
                outs.append(orig_call(self, x) if key == "image" else x)
            return tuple(outs)
        return orig_call(self, inputs)

    cls.__init__ = __init__
    cls.__call__ = __call__
    return cls


for _cls in (Normalize, ToTensor, Transpose, Resize, RandomHorizontalFlip,
             RandomCrop, CenterCrop, RandomVerticalFlip, Pad, Grayscale,
             BrightnessTransform, ContrastTransform, SaturationTransform,
             HueTransform, ColorJitter, RandomRotation, RandomAffine,
             RandomPerspective, RandomResizedCrop, RandomErasing):
    _keysify(_cls)
del _cls
