"""Vision transforms (reference: python/paddle/vision/transforms/) — numpy CHW float."""

from __future__ import annotations

import numpy as np

__all__ = ["Compose", "Normalize", "ToTensor", "Resize", "RandomHorizontalFlip", "RandomCrop", "CenterCrop", "Transpose"]


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def __call__(self, img):
        img = np.asarray(img, np.float32)
        shape = (-1, 1, 1) if self.data_format == "CHW" else (1, 1, -1)
        return (img - self.mean.reshape(shape)) / self.std.reshape(shape)


class ToTensor:
    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def __call__(self, img):
        a = np.asarray(img, np.float32)
        if a.max() > 1.5:
            a = a / 255.0
        if a.ndim == 3 and self.data_format == "CHW" and a.shape[0] not in (1, 3):
            a = a.transpose(2, 0, 1)
        return a


class Transpose:
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def __call__(self, img):
        return np.asarray(img).transpose(self.order)


class Resize:
    def __init__(self, size, interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        import jax

        a = np.asarray(img, np.float32)
        chw = a.ndim == 3 and a.shape[0] in (1, 3)
        if chw:
            out_shape = (a.shape[0],) + self.size
        else:
            out_shape = self.size + ((a.shape[-1],) if a.ndim == 3 else ())
        return np.asarray(jax.image.resize(a, out_shape, method="bilinear"))


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return np.asarray(img)[..., ::-1].copy()
        return np.asarray(img)


class RandomCrop:
    def __init__(self, size, padding=0):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def __call__(self, img):
        a = np.asarray(img)
        chw = a.ndim == 3 and a.shape[0] in (1, 3)
        h_ax, w_ax = (1, 2) if chw else (0, 1)
        if self.padding:
            pad = [(0, 0)] * a.ndim
            pad[h_ax] = pad[w_ax] = (self.padding, self.padding)
            a = np.pad(a, pad)
        th, tw = self.size
        i = np.random.randint(0, a.shape[h_ax] - th + 1)
        j = np.random.randint(0, a.shape[w_ax] - tw + 1)
        sl = [slice(None)] * a.ndim
        sl[h_ax] = slice(i, i + th)
        sl[w_ax] = slice(j, j + tw)
        return a[tuple(sl)]


class CenterCrop:
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        a = np.asarray(img)
        chw = a.ndim == 3 and a.shape[0] in (1, 3)
        h_ax, w_ax = (1, 2) if chw else (0, 1)
        th, tw = self.size
        i = (a.shape[h_ax] - th) // 2
        j = (a.shape[w_ax] - tw) // 2
        sl = [slice(None)] * a.ndim
        sl[h_ax] = slice(i, i + th)
        sl[w_ax] = slice(j, j + tw)
        return a[tuple(sl)]
