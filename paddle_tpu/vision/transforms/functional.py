"""Deterministic functional transforms (reference:
python/paddle/vision/transforms/functional.py + functional_cv2.py) — the
random Transform classes in __init__ are parameter samplers over these.
Convention follows the class transforms: numpy arrays, HWC for photometric
and warp ops unless stated."""

from __future__ import annotations

import numbers

import numpy as np

__all__ = [
    "to_tensor", "resize", "pad", "crop", "center_crop", "hflip", "vflip",
    "rotate", "affine", "perspective", "normalize", "erase", "to_grayscale",
    "adjust_brightness", "adjust_contrast", "adjust_hue", "adjust_saturation",
]


def _like_input(out, img):
    """Photometric ops preserve the input dtype (the reference cv2 path
    returns uint8 for uint8 input) — otherwise adjust_*(uint8) → to_tensor()
    silently skips the /255 scaling, which only applies to integer dtypes.
    Integer outputs saturate to the DTYPE's own range (np.iinfo, not a
    hardcoded 255 — int16 images carry values past 255); float outputs are
    returned unclipped, because a deterministic dtype rule cannot tell a
    normalized [0,1] float image from one carrying raw 0-255 values, and
    clipping the latter to 1.0 would destroy it."""
    dt = np.asarray(img).dtype
    if np.issubdtype(dt, np.integer):
        info = np.iinfo(dt)
        return np.rint(np.clip(out, info.min, info.max)).astype(dt)
    return np.asarray(out).astype(dt)


def to_tensor(pic, data_format="CHW"):
    """functional.py to_tensor: HWC uint8 [0,255] → CHW float [0,1].  The
    /255 scaling applies to INTEGER dtypes only (the reference divides for
    uint8 input and passes float input through unchanged)."""
    from ...core.tensor import Tensor

    raw = np.asarray(pic)
    a = raw.astype(np.float32)
    if np.issubdtype(raw.dtype, np.integer):
        a = a / 255.0
    if a.ndim == 2:
        a = a[..., None]
    if data_format == "CHW":
        a = a.transpose(2, 0, 1)
    return Tensor(a)


def resize(img, size, interpolation="bilinear"):
    import jax

    a = np.asarray(img, np.float32)
    if isinstance(size, int):
        h, w = a.shape[:2]
        # shorter side to `size`, aspect preserved (reference semantics)
        if h <= w:
            size = (size, max(1, int(round(w * size / h))))
        else:
            size = (max(1, int(round(h * size / w))), size)
    out_shape = tuple(size) + tuple(a.shape[2:])
    method = {"bilinear": "bilinear", "nearest": "nearest",
              "bicubic": "cubic", "lanczos": "lanczos3"}.get(interpolation,
                                                             "bilinear")
    return np.asarray(jax.image.resize(a, out_shape, method=method))


def pad(img, padding, fill=0, padding_mode="constant"):
    arr = np.asarray(img)
    if isinstance(padding, numbers.Number):
        padding = (padding,) * 4
    elif len(padding) == 2:
        padding = (padding[0], padding[1]) * 2
    l, t, r, b = padding
    pads = [(t, b), (l, r)] + [(0, 0)] * (arr.ndim - 2)
    mode = {"constant": "constant", "reflect": "reflect", "edge": "edge",
            "symmetric": "symmetric"}[padding_mode]
    kw = {"constant_values": fill} if mode == "constant" else {}
    return np.pad(arr, pads, mode=mode, **kw)


def crop(img, top, left, height, width):
    arr = np.asarray(img)
    return arr[top:top + height, left:left + width]


def center_crop(img, output_size):
    arr = np.asarray(img)
    if isinstance(output_size, numbers.Number):
        output_size = (int(output_size), int(output_size))
    th, tw = output_size
    h, w = arr.shape[:2]
    return crop(arr, (h - th) // 2, (w - tw) // 2, th, tw)


def hflip(img):
    return np.ascontiguousarray(np.asarray(img)[:, ::-1])


def vflip(img):
    return np.ascontiguousarray(np.asarray(img)[::-1])


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    """functional.py rotate — inverse-mapped sampling; ``expand`` grows the
    canvas to hold the whole rotated image."""
    from . import _inverse_warp

    arr = np.asarray(img)
    h, w = arr.shape[:2]
    rad = np.radians(angle)
    cy, cx = ((h - 1) / 2, (w - 1) / 2) if center is None \
        else (center[1], center[0])
    if expand:
        nh = int(np.ceil(abs(h * np.cos(rad)) + abs(w * np.sin(rad))))
        nw = int(np.ceil(abs(w * np.cos(rad)) + abs(h * np.sin(rad))))
        oy, ox = (nh - 1) / 2, (nw - 1) / 2
    else:
        nh, nw, oy, ox = h, w, cy, cx
    yy, xx = np.mgrid[0:nh, 0:nw]
    ys = cy + (yy - oy) * np.cos(rad) - (xx - ox) * np.sin(rad)
    xs = cx + (yy - oy) * np.sin(rad) + (xx - ox) * np.cos(rad)
    return _inverse_warp(arr, xs, ys, fill)


def affine(img, angle, translate, scale, shear, interpolation="nearest",
           fill=0, center=None):
    """functional.py affine — same matrix composition as RandomAffine with
    explicit parameters."""
    from . import _inverse_warp

    arr = np.asarray(img)
    h, w = arr.shape[:2]
    ang = np.radians(angle)
    if isinstance(shear, numbers.Number):
        shear = (shear, 0.0)
    shx, shy = np.radians(shear[0]), np.radians(shear[1] if len(shear) > 1
                                                else 0.0)
    cx, cy = ((w - 1) / 2, (h - 1) / 2) if center is None else center
    rot = np.array([[np.cos(ang), -np.sin(ang)],
                    [np.sin(ang), np.cos(ang)]])
    sh = (np.array([[1, np.tan(shx)], [0, 1]])
          @ np.array([[1, 0], [np.tan(shy), 1]]))
    m2 = float(scale) * (rot @ sh)
    offs = np.array([cx + translate[0], cy + translate[1]]) \
        - m2 @ np.array([cx, cy])
    inv = np.linalg.inv(m2)
    yy, xx = np.mgrid[0:h, 0:w]
    src = np.stack([xx - offs[0], yy - offs[1]], axis=-1) @ inv.T
    return _inverse_warp(arr, src[..., 0], src[..., 1], fill)


def perspective(img, startpoints, endpoints, interpolation="nearest", fill=0):
    """functional.py perspective — homography from 4 point pairs."""
    from . import RandomPerspective, _inverse_warp

    arr = np.asarray(img)
    h, w = arr.shape[:2]
    H = RandomPerspective._homography(np.asarray(startpoints, np.float64),
                                      np.asarray(endpoints, np.float64))
    Hinv = np.linalg.inv(H)
    yy, xx = np.mgrid[0:h, 0:w]
    pts = np.stack([xx, yy, np.ones_like(xx)], axis=-1) @ Hinv.T
    return _inverse_warp(arr, pts[..., 0] / pts[..., 2],
                         pts[..., 1] / pts[..., 2], fill)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    a = np.asarray(img, np.float32)
    if to_rgb:  # reference: flip BGR → RGB before normalizing
        a = a[::-1] if data_format == "CHW" else a[..., ::-1]
    shape = (-1, 1, 1) if data_format == "CHW" else (1, 1, -1)
    return (a - np.asarray(mean, np.float32).reshape(shape)) \
        / np.asarray(std, np.float32).reshape(shape)


def erase(img, i, j, h, w, v, inplace=False):
    """functional.py erase — input contract is CHW for 3-D arrays/Tensors
    (the reference documents shape (C, H, W)); 2-D arrays are plain HW.
    Region [i:i+h, j:j+w] ← v."""
    arr = np.asarray(img) if inplace else np.array(img, copy=True)
    if arr.ndim == 3:
        arr[:, i:i + h, j:j + w] = v
    else:
        arr[i:i + h, j:j + w] = v
    return arr


def to_grayscale(img, num_output_channels=1):
    arr = np.asarray(img, np.float32)
    g = arr[..., 0] * 0.299 + arr[..., 1] * 0.587 + arr[..., 2] * 0.114
    return _like_input(np.repeat(g[..., None], num_output_channels, axis=-1),
                       img)


def adjust_brightness(img, brightness_factor):
    arr = np.asarray(img, np.float32)
    return _like_input(arr * brightness_factor, img)


def adjust_contrast(img, contrast_factor):
    arr = np.asarray(img, np.float32)
    mean = arr.mean()
    return _like_input((arr - mean) * contrast_factor + mean, img)


def adjust_saturation(img, saturation_factor):
    """Blend toward the luma channel (factor 0 = grayscale, 1 = identity)."""
    arr = np.asarray(img, np.float32)
    g = (arr[..., 0] * 0.299 + arr[..., 1] * 0.587
         + arr[..., 2] * 0.114)[..., None]
    return _like_input(g + (arr - g) * saturation_factor, img)


def adjust_hue(img, hue_factor):
    """YIQ chroma rotation by hue_factor (in [-0.5, 0.5] turns), matching
    HueTransform's deterministic core."""
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError("hue_factor must be in [-0.5, 0.5]")
    arr = np.asarray(img, np.float32)
    theta = hue_factor * 2 * np.pi
    c, s = np.cos(theta), np.sin(theta)
    yiq_m = np.array([[0.299, 0.587, 0.114],
                      [0.596, -0.274, -0.322],
                      [0.211, -0.523, 0.312]], np.float32)
    rot = np.array([[1, 0, 0], [0, c, -s], [0, s, c]], np.float32)
    m = np.linalg.inv(yiq_m) @ rot @ yiq_m
    return _like_input(arr @ m.T, img)
