"""Quantization framework (reference: python/paddle/quantization/ —
QuantConfig at config.py, QAT at qat.py, PTQ at ptq.py, observers in
observer/, fake quanters in quanter/; plus nn/quant layers).

TPU-native: quantization simulation (fake-quant with straight-through
gradients) runs as pure jnp — XLA fuses the quant/dequant pairs into the
surrounding matmuls.  True low-bit serving on TPU is int8/fp8 matmul via
XLA's native dot quantization; `convert` produces layers that carry int8
weights + scales in that layout."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, apply_op, _unwrap
from ..nn.layer_base import Layer

__all__ = [
    "QuantConfig", "QAT", "PTQ", "quanter",
    "AbsmaxObserver", "HistObserver", "KLObserver", "BaseQuanter",
    "FakeQuanterWithAbsMaxObserver", "QuantizedLinear", "fake_quant",
]


def fake_quant(x, scale, bits=8):
    """Symmetric fake quantization with a straight-through estimator.

    Forward: round(clip(x/step)) * step with step = scale/(2^(b-1)-1).
    Backward: identity inside the clip range (STE) — implemented via
    stop_gradient so it is exact under both the tape and jit."""
    qmax = float(2 ** (bits - 1) - 1)

    def fn(v, s):
        step = s / qmax
        q = jnp.clip(jnp.round(v / step), -qmax, qmax) * step
        # STE: v + stop_grad(q - v) → d/dv == 1, forward == q
        return v + jax.lax.stop_gradient(q - v)

    return apply_op("fake_quant", fn, [x, scale])


# ---- observers (reference quantization/observer/) -------------------------

class BaseObserver(Layer):
    def __init__(self, quant_bits=8):
        super().__init__()
        self.quant_bits = quant_bits
        self._scale = None

    def scale(self):
        return self._scale

    def forward(self, x):
        self._observe(np.asarray(_unwrap(x), np.float32))
        return x


class AbsmaxObserver(BaseObserver):
    """Running abs-max (reference observer/abs_max.py)."""

    def _observe(self, arr):
        m = float(np.max(np.abs(arr))) if arr.size else 0.0
        self._scale = m if self._scale is None else max(self._scale, m)


class HistObserver(BaseObserver):
    """Histogram percentile observer (reference observer/hist.py).  Keeps a
    fixed-bin histogram (O(bins) memory) rather than raw samples; the bin
    range grows by rebinning when a batch exceeds the current maximum."""

    def __init__(self, quant_bits=8, percent=0.999, bins=2048):
        super().__init__(quant_bits)
        self.percent = percent
        self.bins = bins
        self._hist = np.zeros(bins, np.int64)
        self._max = 0.0

    def _observe(self, arr):
        a = np.abs(arr).ravel()
        if not a.size:
            return
        m = float(a.max())
        if m == 0.0 and self._max == 0.0:
            self._scale = 0.0  # all-zero so far; nothing to bin
            return
        if m > self._max:
            if self._max > 0:  # rebin old counts into the wider range
                old_edges = np.linspace(0, self._max, self.bins + 1)[1:]
                new_idx = np.minimum(
                    (old_edges / m * self.bins).astype(int), self.bins - 1)
                rebinned = np.zeros(self.bins, np.int64)
                np.add.at(rebinned, new_idx, self._hist)
                self._hist = rebinned
            self._max = m
        idx = np.minimum((a / self._max * self.bins).astype(int), self.bins - 1)
        np.add.at(self._hist, idx, 1)
        # percentile from the cumulative histogram
        c = np.cumsum(self._hist)
        target = self.percent * c[-1]
        bin_i = int(np.searchsorted(c, target))
        self._scale = (bin_i + 1) / self.bins * self._max


class KLObserver(HistObserver):
    """KL-minimizing threshold (reference observer/kl.py); approximated by a
    high percentile of the abs histogram (the KL search optimum lands near
    the tail percentile for typical activations)."""

    def __init__(self, quant_bits=8, bins=2048):
        super().__init__(quant_bits, percent=0.9995, bins=bins)


# ---- quanters (reference quantization/quanter/) ---------------------------

class BaseQuanter(Layer):
    """Abstract quanter contract (reference: quantization/base_quanter.py —
    scales/zero_points/quant_axis/bit_length define how a tensor maps onto
    the integer grid)."""

    def scales(self):
        raise NotImplementedError

    def zero_points(self):
        raise NotImplementedError

    def quant_axis(self):
        return -1

    def bit_length(self):
        return 8


class FakeQuanterWithAbsMaxObserver(BaseQuanter):
    """QAT fake-quant node with a moving-average abs-max scale
    (reference quanter/abs_max.py FakeQuanterWithAbsMaxObserverLayer)."""

    def __init__(self, moving_rate=0.9, bits=8, **kw):
        super().__init__()
        self.moving_rate = moving_rate
        self.bits = bits
        self._scale = None

    def forward(self, x):
        if self._scale is None:
            self._scale = float(
                np.max(np.abs(np.asarray(_unwrap(x), np.float32))) or 1e-8)
        elif self.training:  # scale is frozen in eval (deterministic serving)
            cur = float(np.max(np.abs(np.asarray(_unwrap(x), np.float32))) or 1e-8)
            self._scale = (self.moving_rate * self._scale
                           + (1 - self.moving_rate) * cur)
        return fake_quant(x, Tensor(jnp.float32(self._scale)), self.bits)

    def scale(self):
        return self._scale

    def scales(self):
        return self._scale

    def zero_points(self):
        return None  # symmetric

    def bit_length(self):
        return self.bits


def quanter(name):
    """Decorator registering a custom quanter class (reference
    quantization/factory.py)."""
    def deco(cls):
        globals()[name] = cls
        return cls

    return deco


# ---- config (reference quantization/config.py) ----------------------------

class _LayerConfig:
    def __init__(self, activation=None, weight=None):
        self.activation = activation
        self.weight = weight


class QuantConfig:
    def __init__(self, activation=None, weight=None):
        self.global_config = _LayerConfig(activation, weight)
        self._type_configs: dict = {}
        self._layer_configs: dict = {}

    def add_type_config(self, layer_type, activation=None, weight=None):
        for t in (layer_type if isinstance(layer_type, (list, tuple)) else [layer_type]):
            self._type_configs[t] = _LayerConfig(activation, weight)

    def add_layer_config(self, layer, activation=None, weight=None):
        for l in (layer if isinstance(layer, (list, tuple)) else [layer]):
            self._layer_configs[id(l)] = _LayerConfig(activation, weight)

    def config_for(self, layer):
        return (self._layer_configs.get(id(layer))
                or self._type_configs.get(type(layer))
                or self.global_config)


# ---- quantized layers -----------------------------------------------------

class QuantedLinear(Layer):
    """Linear with activation/weight fake-quant inserted (QAT simulation)."""

    def __init__(self, linear, q_config: _LayerConfig):
        super().__init__()
        self.linear = linear
        self.act_quanter = q_config.activation() if q_config.activation else None
        self.w_quanter = q_config.weight() if q_config.weight else None

    def forward(self, x):
        if self.act_quanter is not None:
            x = self.act_quanter(x)
        w = self.linear.weight
        if self.w_quanter is not None:
            w = self.w_quanter(w)
        from ..nn import functional as F

        return F.linear(x, w, self.linear.bias)


class QuantizedLinear(Layer):
    """Converted (deploy) linear: int8 weights + fp scale, dequant matmul —
    the layout XLA's int8 dot quantization consumes on TPU."""

    def __init__(self, linear, w_scale, bits=8):
        super().__init__()
        if w_scale is None:
            raise ValueError(
                "quant scale is None — run at least one forward (QAT) or "
                "calibration batch (PTQ) before convert()")
        qmax = float(2 ** (bits - 1) - 1)
        w = np.asarray(_unwrap(linear.weight), np.float32)
        step = max(w_scale, 1e-12) / qmax
        self.w_int8 = jnp.asarray(np.clip(np.round(w / step), -qmax, qmax), jnp.int8)
        self.scale = float(step)
        self.bias = linear.bias

    def forward(self, x):
        def fn(v, *rest):
            w = self.w_int8.astype(jnp.float32) * self.scale
            out = v @ w
            if rest:
                out = out + rest[0]
            return out

        inputs = [x] + ([self.bias] if self.bias is not None else [])
        return apply_op("quantized_linear", fn, inputs)


# ---- QAT / PTQ drivers (reference qat.py / ptq.py) ------------------------

def _swap_linears(model: Layer, make):
    from ..nn import Linear

    for name, child in list(model._sub_layers.items()):
        if isinstance(child, Linear):
            model._sub_layers[name] = make(child)
        else:
            _swap_linears(child, make)
    return model


class QAT:
    """Quantization-aware training driver (reference quantization/qat.py)."""

    def __init__(self, q_config: QuantConfig):
        self.q_config = q_config

    def quantize(self, model: Layer, inplace=False):
        if not inplace:
            import copy

            model = copy.deepcopy(model)
        return _swap_linears(
            model, lambda lin: QuantedLinear(lin, self.q_config.config_for(lin)))

    def convert(self, model: Layer, inplace=False):
        if not inplace:
            import copy

            model = copy.deepcopy(model)
        return self._convert_inner(model)

    def _convert_inner(self, model: Layer):
        for name, child in list(model._sub_layers.items()):
            if isinstance(child, QuantedLinear):
                scale = (child.w_quanter.scale() if child.w_quanter is not None
                         else float(np.max(np.abs(
                             np.asarray(_unwrap(child.linear.weight))))))
                model._sub_layers[name] = QuantizedLinear(child.linear, scale)
            else:
                self._convert_inner(child)
        return model


class PTQ:
    """Post-training quantization driver (reference quantization/ptq.py):
    insert observers, run calibration batches, convert."""

    def __init__(self, q_config: QuantConfig):
        self.q_config = q_config

    def quantize(self, model: Layer, inplace=False):
        if not inplace:
            import copy

            model = copy.deepcopy(model)
        cfgs = self.q_config
        return _swap_linears(
            model, lambda lin: _PTQObservedLinear(lin, cfgs.config_for(lin)))

    def convert(self, model: Layer, inplace=False):
        if not inplace:
            import copy

            model = copy.deepcopy(model)
        return self._convert_inner(model)

    def _convert_inner(self, model: Layer):
        for name, child in list(model._sub_layers.items()):
            if isinstance(child, _PTQObservedLinear):
                model._sub_layers[name] = QuantizedLinear(
                    child.linear, child.w_obs.scale() or 1e-8)
            else:
                self._convert_inner(child)
        return model


class _PTQObservedLinear(Layer):
    def __init__(self, linear, cfg):
        super().__init__()
        self.linear = linear
        self.act_obs = cfg.activation() if cfg.activation else AbsmaxObserver()
        self.w_obs = cfg.weight() if cfg.weight else AbsmaxObserver()
        self.w_obs(linear.weight)

    def forward(self, x):
        self.act_obs(x)
        return self.linear(x)
