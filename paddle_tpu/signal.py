"""Signal processing (reference: python/paddle/signal.py — stft/istft over
frame/overlap_add kernels paddle/phi/kernels/frame_kernel.h).

TPU-native: framing is a gather with static window starts (XLA-friendly);
FFTs via paddle_tpu.fft."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .core.tensor import Tensor, apply_op, _unwrap

__all__ = ["frame", "overlap_add", "stft", "istft"]


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """Slice into overlapping frames (reference frame op).  axis=-1 (default):
    input [..., n] → [..., frame_length, num_frames]; axis=0: input [n, ...]
    → [num_frames, frame_length, ...] (the reference's two layouts)."""
    if axis not in (-1, 0):
        raise ValueError("frame: axis must be 0 or -1")

    def fn(v):
        if axis == 0:
            v = jnp.moveaxis(v, 0, -1)
        n = v.shape[-1]
        num = 1 + (n - frame_length) // hop_length
        starts = jnp.arange(num) * hop_length
        idx = starts[:, None] + jnp.arange(frame_length)[None, :]
        out = v[..., idx]  # [..., num_frames, frame_length]
        if axis == 0:
            # [num_frames, frame_length, ...]
            return jnp.moveaxis(out, (-2, -1), (0, 1))
        return jnp.swapaxes(out, -1, -2)  # [..., frame_length, num_frames]

    return apply_op("frame", fn, [x])


def overlap_add(x, hop_length, axis=-1, name=None):
    """Inverse of frame.  axis=-1: x [..., frame_length, num_frames] → [..., n];
    axis=0: x [num_frames, frame_length, ...] → [n, ...]."""
    if axis not in (-1, 0):
        raise ValueError("overlap_add: axis must be 0 or -1")

    def fn(v):
        if axis == 0:
            v = jnp.moveaxis(v, (0, 1), (-1, -2))  # → [..., frame_length, num]
        fl, num = v.shape[-2], v.shape[-1]
        n = fl + hop_length * (num - 1)
        segs = jnp.moveaxis(v, -1, 0)  # [num, ..., fl]

        out = jnp.zeros(v.shape[:-2] + (n,), v.dtype)

        def body(i, acc):
            seg = jax.lax.dynamic_index_in_dim(segs, i, keepdims=False)
            return jax.lax.dynamic_update_slice_in_dim(
                acc, jax.lax.dynamic_slice_in_dim(acc, i * hop_length, fl, -1) + seg,
                i * hop_length, -1)

        sig = jax.lax.fori_loop(0, num, body, out)
        return jnp.moveaxis(sig, -1, 0) if axis == 0 else sig

    return apply_op("overlap_add", fn, [x])


def stft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
         pad_mode="reflect", normalized=False, onesided=True, name=None):
    """Short-time Fourier transform (reference python/paddle/signal.py:stft).
    x: [batch?, n]; returns [..., n_fft//2+1 or n_fft, num_frames] complex."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft

    inputs = [x] + ([window] if window is not None else [])

    def fn(v, *rest):
        win = rest[0] if rest else jnp.ones((win_length,), v.dtype)
        if win_length < n_fft:  # pad window to n_fft, centered
            lp = (n_fft - win_length) // 2
            win = jnp.pad(win, (lp, n_fft - win_length - lp))
        sig = v
        if center:
            pad = n_fft // 2
            sig = jnp.pad(sig, [(0, 0)] * (sig.ndim - 1) + [(pad, pad)], mode=pad_mode)
        n = sig.shape[-1]
        num = 1 + (n - n_fft) // hop_length
        starts = jnp.arange(num) * hop_length
        idx = starts[:, None] + jnp.arange(n_fft)[None, :]
        frames = sig[..., idx] * win  # [..., num, n_fft]
        spec = (jnp.fft.rfft(frames, axis=-1) if onesided
                else jnp.fft.fft(frames, axis=-1))
        if normalized:
            spec = spec / jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
        return jnp.swapaxes(spec, -1, -2)  # [..., freq, num_frames]

    return apply_op("stft", fn, inputs)


def istft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
          normalized=False, onesided=True, length=None, return_complex=False,
          name=None):
    """Inverse STFT with window-square normalization (reference signal.py:istft)."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft

    inputs = [x] + ([window] if window is not None else [])

    def fn(v, *rest):
        win = rest[0] if rest else jnp.ones((win_length,), jnp.float32)
        if win_length < n_fft:
            lp = (n_fft - win_length) // 2
            win = jnp.pad(win, (lp, n_fft - win_length - lp))
        spec = jnp.swapaxes(v, -1, -2)  # [..., num, freq]
        if normalized:
            spec = spec * jnp.sqrt(jnp.asarray(n_fft, jnp.float32))
        if onesided:
            frames = jnp.fft.irfft(spec, n=n_fft, axis=-1)
        else:
            frames = jnp.fft.ifft(spec, axis=-1)
            if not return_complex:
                frames = frames.real
        frames = frames * win
        num = frames.shape[-2]
        n = n_fft + hop_length * (num - 1)
        sig = jnp.zeros(frames.shape[:-2] + (n,), frames.dtype)
        wsum = jnp.zeros((n,), frames.dtype)
        segs = jnp.moveaxis(frames, -2, 0)

        def body(i, carry):
            sig, wsum = carry
            seg = jax.lax.dynamic_index_in_dim(segs, i, keepdims=False)
            cur = jax.lax.dynamic_slice_in_dim(sig, i * hop_length, n_fft, -1)
            sig = jax.lax.dynamic_update_slice_in_dim(sig, cur + seg, i * hop_length, -1)
            wcur = jax.lax.dynamic_slice_in_dim(wsum, i * hop_length, n_fft, -1)
            wsum = jax.lax.dynamic_update_slice_in_dim(wsum, wcur + win * win, i * hop_length, -1)
            return sig, wsum

        sig, wsum = jax.lax.fori_loop(0, num, body, (sig, wsum))
        sig = sig / jnp.maximum(wsum, 1e-11)
        if center:
            pad = n_fft // 2
            sig = sig[..., pad:n - pad]
        if length is not None:
            sig = sig[..., :length]
        return sig

    return apply_op("istft", fn, inputs)
