"""paddle.device namespace (reference: python/paddle/device/__init__.py).

PJRT/XLA owns streams and contexts on TPU, so Stream/Event keep the API
surface with host-side synchronization semantics (synchronize = device
fence via a blocking transfer; events record completion points)."""

from __future__ import annotations

import contextlib

import jax

from ..core.device import (  # noqa: F401
    Place,
    current_device,
    device_count,
    empty_cache,
    get_device,
    local_device_count,
    is_compiled_with_cuda,
    is_compiled_with_tpu,
    max_memory_allocated,
    max_memory_reserved,
    memory_allocated,
    memory_reserved,
    memory_stats,
    set_device,
    synchronize,
)

__all__ = [
    "get_cudnn_version", "set_device", "get_device", "XPUPlace", "IPUPlace",
    "is_compiled_with_xpu", "is_compiled_with_ipu", "is_compiled_with_cinn",
    "is_compiled_with_cuda", "is_compiled_with_rocm",
    "is_compiled_with_distribute", "is_compiled_with_custom_device",
    "get_all_device_type", "get_all_custom_device_type",
    "get_available_device", "get_available_custom_device", "Stream", "Event",
    "current_stream", "set_stream", "stream_guard", "synchronize",
]


def get_cudnn_version():
    """None — no CUDA in this build (the reference returns the cudnn int)."""
    return None


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_ipu() -> bool:
    return False


def is_compiled_with_cinn() -> bool:
    return False  # XLA plays CINN's role (SURVEY §1 L9)


def is_compiled_with_rocm() -> bool:
    return False


def is_compiled_with_distribute() -> bool:
    return True  # collectives/mesh support is built in


def is_compiled_with_custom_device(device_type: str) -> bool:
    return any(d.platform == device_type for d in jax.devices())


def get_all_device_type() -> list[str]:
    return sorted({d.platform for d in jax.devices()})


def get_all_custom_device_type() -> list[str]:
    return [p for p in get_all_device_type() if p not in ("cpu", "gpu")]


def get_available_device() -> list[str]:
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_available_custom_device() -> list[str]:
    return [s for s in get_available_device()
            if not s.startswith(("cpu", "gpu"))]


def XPUPlace(dev_id: int = 0):
    raise NotImplementedError("XPU (Kunlun) hardware has no TPU analog; "
                              "use set_device('tpu')")


def IPUPlace():
    raise NotImplementedError("IPU (Graphcore) hardware has no TPU analog; "
                              "use set_device('tpu')")


class Event:
    """Completion marker (reference device/__init__.py Event).  record()
    snapshots the device's in-flight work; synchronize()/query() fence it."""

    def __init__(self, device=None, enable_timing=False, blocking=False,
                 interprocess=False):
        self._device = device
        self._recorded = False

    def record(self, stream=None):
        self._recorded = True
        # the fence target is whatever was enqueued before record(): on
        # PJRT the only observable fence is a blocking sync
        self._fence = True

    def query(self) -> bool:
        return True  # after a blocking fence nothing is pending

    def synchronize(self):
        if self._recorded:
            synchronize()

    def elapsed_time(self, end_event) -> float:
        raise NotImplementedError("PJRT exposes no device-side timers; use "
                                  "the profiler (paddle_tpu.profiler)")


class Stream:
    """Work queue handle (reference device/__init__.py Stream).  XLA orders
    work internally; the surface keeps priority/synchronize/record_event."""

    def __init__(self, device=None, priority=2, stream_base=None):
        self.device = device
        self.priority = priority

    def synchronize(self):
        synchronize()

    def record_event(self, event=None):
        event = event or Event()
        event.record(self)
        return event

    def wait_event(self, event):
        event.synchronize()

    def wait_stream(self, stream):
        stream.synchronize()

    def query(self) -> bool:
        return True


_current_stream = Stream()


def current_stream(device=None) -> Stream:
    return _current_stream


def set_stream(stream: Stream) -> Stream:
    global _current_stream
    prev, _current_stream = _current_stream, stream
    return prev


@contextlib.contextmanager
def stream_guard(stream: Stream):
    prev = set_stream(stream)
    try:
        yield
    finally:
        set_stream(prev)


class _CudaNamespace:
    """paddle.device.cuda compatibility view — the accelerator here is the
    TPU; memory stats come from PJRT."""

    Stream = Stream
    Event = Event
    max_memory_allocated = staticmethod(max_memory_allocated)
    max_memory_reserved = staticmethod(max_memory_reserved)
    memory_allocated = staticmethod(memory_allocated)
    memory_reserved = staticmethod(memory_reserved)
    empty_cache = staticmethod(empty_cache)
    synchronize = staticmethod(synchronize)
    current_stream = staticmethod(current_stream)
    stream_guard = staticmethod(stream_guard)

    @staticmethod
    def device_count():
        return jax.device_count()

    @staticmethod
    def get_device_name(device=None):
        d = current_device()
        return getattr(d, "device_kind", d.platform)

    @staticmethod
    def get_device_capability(device=None):
        return (0, 0)  # CUDA compute capability has no TPU analog


cuda = _CudaNamespace()
