"""Unique name generator (reference: python/paddle/utils/unique_name.py —
base/unique_name.py generator with guards)."""

from __future__ import annotations

import contextlib
from collections import defaultdict

__all__ = ["generate", "guard", "switch"]


class _Generator:
    def __init__(self):
        self.ids = defaultdict(int)

    def generate(self, key: str) -> str:
        n = self.ids[key]
        self.ids[key] += 1
        return f"{key}_{n}"


_generator = _Generator()


def generate(key: str) -> str:
    return _generator.generate(key)


def switch(new_generator=None):
    global _generator
    old = _generator
    _generator = new_generator or _Generator()
    return old


@contextlib.contextmanager
def guard(new_generator=None):
    old = switch(new_generator)
    try:
        yield
    finally:
        global _generator
        _generator = old
