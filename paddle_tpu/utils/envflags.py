"""Validated parsing for PADDLE_TPU_* operational env switches.

The switches are operator-facing kill/debug levers read at trace or init
time; a typo (``paged_attn`` for ``paged_attention``, ``off`` for ``0``)
used to be silently ignored — the worst failure mode for an escape hatch you
reach for mid-incident.  Every parse here warns (once per distinct value, so
trace-time re-reads don't spam) naming the offending token and the closest
valid spelling.

:data:`BOOL_FLAGS` is the registry of '0'/'1' switches and their defaults —
the single place a new kill switch gets documented (the engine reads them
through :func:`env_bool`, which enforces the '0'/'1' vocabulary):

* ``PADDLE_TPU_PREFIX_CACHE`` (default on) — automatic prefix cache
  (inference/prefix_cache.py); ``0`` forces it off even when the engine was
  constructed with ``enable_prefix_caching=True``.
* ``PADDLE_TPU_ENGINE_AUDIT`` (default off) — per-step serving-engine
  invariant auditor (analysis/engine_audit.py).
* ``PADDLE_TPU_SPECULATE`` (default on) — speculative decoding
  (inference/speculative.py, docs/speculative.md); ``0`` forces it off even
  when the engine was constructed with ``enable_speculation=True``, and the
  spec-off engine is byte-identical to one built before the feature existed.
* ``PADDLE_TPU_CHUNKED_PREFILL`` (default on) — chunked prefill + unified
  mixed prefill/decode step (docs/chunked_prefill.md); ``0`` forces it off
  even when the engine was constructed with ``enable_chunked_prefill=True``,
  reverting to the bucketed whole-prompt prefill path byte-for-byte.
* ``PADDLE_TPU_GRACEFUL`` (default on) — fault-tolerant serving
  (docs/fault_tolerance.md): per-request failure isolation, the overload
  degradation ladder, the in-graph NaN/inf logit guard, and graceful
  rejection in ``serve()``; ``0`` restores the pre-fault-tolerance engine
  byte-identically (faults raise out of ``step()`` again).
* ``PADDLE_TPU_METRICS`` (default on) — serving observability
  (inference/observability.py, docs/observability.md): the typed
  MetricsRegistry behind ``engine.stats``/``fleet.stats``, request-
  lifecycle tracing spans, and SLO (TTFT/TBT/queue-wait) accounting.
  All recording is host-side post-step, so token streams are identical
  either way; ``0`` restores the plain pre-observability stats dicts.
* ``PADDLE_TPU_FLIGHT_RECORDER`` (default on) — the bounded ring buffer
  of recent engine/fleet events dumped (with a metrics snapshot) on
  request failure, ``EngineAuditError``, or replica death; ``0`` disables
  the recorder and its dumps entirely.
* ``PADDLE_TPU_HOST_KV_TIER`` (default on) — hierarchical KV: the
  host-RAM spill tier behind the prefix cache (inference/kv_tier.py,
  docs/kv_tier.md).  ``0`` forces it off even when the engine was
  constructed with ``enable_host_kv_tier=True`` (or a FleetRouter shares
  one), restoring the pre-tier engine byte-identically: eviction frees
  pages again and admission stops at the HBM match.
  ``PADDLE_TPU_PREFIX_CACHE=0`` neutralizes the tier too — with no
  content address there is nothing to demote or match through.
* ``PADDLE_TPU_ASYNC_HOST`` (default on) — the async host runtime
  (docs/async_runtime.md): the engine maintains its failover journal
  incrementally (O(changed rids) per step instead of a full
  ``snapshot()`` rebuild per fleet step/dispatch) and overlaps the
  token-independent half of each step's host work (journal maintenance,
  metrics, queue bookkeeping) with the in-flight device step via JAX
  async dispatch, fetching tokens as late as possible.  Token streams
  are identical either way — only host scheduling moves; ``0`` restores
  the serial fetch-then-bookkeep loop and the per-step full-``snapshot``
  fleet journal byte-identically.

(``PADDLE_TPU_DISABLE_PALLAS`` is the token-set switch; its vocabulary lives
with the kernels — ops/pallas/__init__.py ``KNOWN_KERNELS``, cross-checked
against the actual ``kernel_disabled()`` dispatch sites by the
KNOWN_KERNELS drift lint (analysis/kernel_contracts.py, run by
tools/lint_gate.py) so a retired kernel cannot leave a dead kill switch
registered.  Four of its
tokens are per-path decode kill switches rather than whole-kernel opt-outs
(docs/paged_attention.md): ``flash_decode`` pins the paged decode kernel to
the sequential page walk (split-K off), ``fused_decode_step`` rebuilds
the serving engine's unfused rope + KV-scatter + attention decode path,
``fused_layer_mlp`` restores the stage-1 per-layer program (separate
rms_norm launch + XLA-composed MLP; "Megastep stage 2" in the doc), and
``fused_quant_append`` unfuses the whole decode step for int8/packed-int4
KV pools — the requant-scatter append comes back (4 scatters/step) along
with the separate per-layer launches, exactly like ``fused_decode_step``
does for fp pools; dequant-on-read attention itself survives in the
unfused kernel (``paged_attention`` still opts the whole family out to the
gather oracle).
All four are registered in ``KNOWN_KERNELS`` so a typo gets the did-you-mean
warning instead of silently leaving the kernel it meant to disable running.
``PADDLE_TPU_FAULT_INJECT`` is the structured fault-injection plan; its
clause grammar is validated by :func:`env_fault_spec` and its fault-kind
vocabulary lives with the injector — inference/faults.py ``KNOWN_KINDS``
for the engine seams, plus ``REPLICA_KINDS`` and the ``replica`` clause key
for the fleet tier (inference/fleet.py): replica-scoped clauses are only
accepted by the FleetRouter's parse — the single-engine parse rejects them
with a warning naming the fleet requirement, because a clause nobody polls
would make a chaos run's evidence silently incomplete.
``PADDLE_TPU_TP`` is the integer tensor-parallel override for the serving
engine (docs/tp_serving.md): when set it REPLACES the
``ContinuousBatchingEngine(tensor_parallel=...)`` ctor value, the
operator's one-knob way to fan an existing deployment across a mesh.
Validated by :func:`env_tp`: a non-integer value, a degree that does not
divide the model's kv_heads, or a degree exceeding the device count warns
once — naming the valid divisors — and falls back to 1 (single chip), the
same never-silently-misconfigure contract as the switches above.
``PADDLE_TPU_VMEM_CAP_MIB`` is the integer override for the per-generation
VMEM ceiling the program-card gate checks every Pallas launch against
(analysis/cost_model.py, docs/analysis.md §"Program cards & budgets";
default: the 16 MiB v4 floor from ``VMEM_CAPS``).  Parsed by
:func:`env_int`: a non-integer or sub-minimum value warns once and keeps
the default — a typo'd cap must not silently stop gating VMEM fits.
``PADDLE_TPU_KERNEL_VERIFY_SAMPLES`` is the integer grid-enumeration cap
for the kernel-contract verifier (analysis/kernel_contracts.py,
docs/analysis.md §"Kernel contracts"; default 2048): a ``pallas_call``
grid at or under the cap is enumerated exhaustively, a larger one gets
deterministic corner-plus-stratified sampling down to the cap.  Parsed by
:func:`env_int` with minimum 16 — a typo or sub-minimum value warns once
and keeps the default, so a misconfigured cap can neither explode gate
time nor silently shrink coverage to nothing.
``PADDLE_TPU_HOST_VERIFY_DEPTH`` is the integer call-graph resolution
depth for the host-contract verifier (analysis/host_contracts.py,
docs/analysis.md §"Host contracts"; default 8): how many call edges the
effect analysis follows from each ``_host_overlap()`` window (and each
state-machine choke chain) when computing read/write closures.  Parsed
by :func:`env_int` with minimum 1 — a typo or sub-minimum value warns
once and keeps the default, so a misconfigured depth can neither hide
races behind an unresolved call nor explode the closure.
``PADDLE_TPU_HOST_TIER_MIB`` is the host-KV-tier byte budget in MiB
(inference/kv_tier.py, docs/kv_tier.md; default 256): the ceiling the
tier's own LRU evicts against.  Parsed by :func:`env_int` with minimum 1
— a typo or non-integer warns once and keeps the default, so a
misconfigured budget degrades to the documented one instead of silently
zeroing (or unbounding) the tier.)
"""

from __future__ import annotations

import difflib
import os
import warnings

__all__ = ["env_token_set", "env_bool", "env_fault_spec", "env_tp",
           "env_int", "BOOL_FLAGS"]

#: '0'/'1' switches -> their library defaults (documentation + test anchor;
#: callers still pass the default explicitly at the read site so a flag read
#: can never silently drift from the registry without a test catching it)
BOOL_FLAGS = {
    "PADDLE_TPU_PREFIX_CACHE": True,
    "PADDLE_TPU_ENGINE_AUDIT": False,
    "PADDLE_TPU_SPECULATE": True,
    "PADDLE_TPU_CHUNKED_PREFILL": True,
    "PADDLE_TPU_GRACEFUL": True,
    "PADDLE_TPU_METRICS": True,
    "PADDLE_TPU_FLIGHT_RECORDER": True,
    "PADDLE_TPU_HOST_KV_TIER": True,
    "PADDLE_TPU_ASYNC_HOST": True,
}

_warned: set[tuple[str, str]] = set()


def _warn_once(name: str, raw: str, msg: str) -> None:
    if (name, raw) in _warned:
        return
    _warned.add((name, raw))
    warnings.warn(msg, stacklevel=3)


def env_token_set(name: str, known: frozenset[str] | set[str]) -> set[str]:
    """Comma-separated token list (e.g. PADDLE_TPU_DISABLE_PALLAS).  Unknown
    tokens are kept (forward compatibility: an old binary must still honor a
    newer kernel name as an opt-out) but warned about with a did-you-mean."""
    raw = os.environ.get(name, "")
    if not raw:
        return set()
    tokens = {s.strip() for s in raw.split(",") if s.strip()}
    unknown = tokens - set(known)
    if unknown:
        hints = []
        for t in sorted(unknown):
            close = difflib.get_close_matches(t, known, n=1, cutoff=0.5)
            hints.append(f"{t!r}" + (f" (did you mean {close[0]!r}?)"
                                     if close else ""))
        _warn_once(name, raw,
                   f"{name}={raw!r} contains unrecognized value(s) "
                   f"{', '.join(hints)}; known: {sorted(known)}")
    return tokens


def env_bool(name: str, default: bool) -> bool:
    """Boolean switch: '' -> default, '0' -> False, '1' -> True.  Any other
    value warns and falls back to the default — a typo must not silently
    flip a kill switch either way."""
    raw = os.environ.get(name, "")
    if raw == "":
        return default
    if raw == "0":
        return False
    if raw == "1":
        return True
    _warn_once(name, raw,
               f"{name}={raw!r} is not '0' or '1'; using the default "
               f"({'1' if default else '0'})")
    return default


def env_int(name: str, default: int, minimum: int | None = None) -> int:
    """Integer knob: '' -> default; a non-integer value, or one below
    ``minimum``, warns once and falls back to the default — the same
    never-silently-misconfigure contract as :func:`env_bool` (used by the
    program-card gate's ``PADDLE_TPU_VMEM_CAP_MIB`` VMEM-cap override)."""
    raw = os.environ.get(name, "")
    if raw == "":
        return default
    try:
        value = int(raw)
    except ValueError:
        _warn_once(name, raw,
                   f"{name}={raw!r} is not an integer; using the default "
                   f"({default})")
        return default
    if minimum is not None and value < minimum:
        _warn_once(name, raw,
                   f"{name}={raw!r} is below the minimum ({minimum}); "
                   f"using the default ({default})")
        return default
    return value


def env_tp(kv_heads: int, device_count: int,
           name: str = "PADDLE_TPU_TP") -> int | None:
    """Tensor-parallel degree override for the serving engine.  Returns
    None when the variable is unset (the ctor's ``tensor_parallel`` value
    stands); otherwise the validated degree.  An invalid value — not an
    integer, < 1, not a divisor of ``kv_heads`` (the paged KV pool and the
    K/V projections shard along kv_heads, so a non-divisor would sub-head
    split), or more shards than devices — warns ONCE naming the valid
    degrees and falls back to 1: an operator typo must degrade to the
    single-chip engine, never crash the serve or silently sub-shard."""
    raw = os.environ.get(name, "")
    if raw == "":
        return None
    valid = sorted(d for d in range(1, max(kv_heads, 1) + 1)
                   if kv_heads % d == 0 and d <= device_count)

    def _fallback(msg: str) -> int:
        _warn_once(name, raw,
                   f"{name}={raw!r}: {msg}; falling back to tensor_parallel"
                   f"=1 (valid degrees for kv_heads={kv_heads} on "
                   f"{device_count} device(s): {valid})")
        return 1

    try:
        tp = int(raw)
    except ValueError:
        return _fallback("not an integer")
    if tp < 1:
        return _fallback(f"degree {tp} < 1")
    if kv_heads % tp != 0:
        return _fallback(f"degree {tp} does not divide kv_heads={kv_heads} "
                         f"(a sub-head split would break the shard-local "
                         f"paged-attention page walk)")
    if tp > device_count:
        return _fallback(f"degree {tp} exceeds the {device_count} visible "
                         f"device(s)")
    return tp


def env_fault_spec(name: str, known_kinds, known_keys,
                   fleet_only_kinds=frozenset(),
                   fleet_only_keys=frozenset()) -> list[dict]:
    """Parse a fault-injection plan: ``kind@key=val,key=val;kind@...``
    (e.g. ``alloc_fail@step=7;nan_logits@slot=2,step=11``).  Returns one dict
    per clause — ``{"kind": ..., <int-valued keys>}`` (``p`` parses as float).

    A fault plan is an operator-facing chaos lever: an unknown kind, unknown
    key, or malformed clause warns ONCE with a did-you-mean and returns []
    — injection disabled, the engine serves normally.  Partial acceptance
    would be worse than none: a typo'd clause silently skipped while its
    siblings fire would make a chaos run's evidence unreadable.

    ``fleet_only_kinds`` / ``fleet_only_keys`` name the replica-scoped
    vocabulary (inference/faults.REPLICA_KINDS, the ``replica`` key) for a
    parse where NO fleet is running: those clauses get the same
    warn-and-disable treatment, with the message naming the FleetRouter
    requirement instead of a did-you-mean — a replica-scoped clause the
    single-engine serve would never poll must not be a silent no-op (and
    must not crash the engine either)."""
    raw = os.environ.get(name, "")
    if not raw:
        return []

    def _reject(msg: str) -> list[dict]:
        _warn_once(name, raw, f"{name}={raw!r}: {msg}; fault injection "
                              f"DISABLED (the engine serves normally)")
        return []

    out: list[dict] = []
    for clause in raw.split(";"):
        clause = clause.strip()
        if not clause:
            continue
        kind, sep, tail = clause.partition("@")
        kind = kind.strip()
        if kind in fleet_only_kinds:
            return _reject(
                f"fault kind {kind!r} is replica-scoped and requires a "
                f"running FleetRouter (inference/fleet.py) to poll it — "
                f"no fleet is running, so the clause could never fire")
        if kind not in known_kinds:
            close = difflib.get_close_matches(
                kind, set(known_kinds) | set(fleet_only_kinds), n=1,
                cutoff=0.5)
            hint = f" (did you mean {close[0]!r}?)" if close else ""
            return _reject(f"unknown fault kind {kind!r}{hint}; known: "
                           f"{sorted(known_kinds)}")
        kv: dict = {"kind": kind}
        for item in tail.split(",") if sep else []:
            item = item.strip()
            if not item:
                continue
            k, eq, v = item.partition("=")
            k = k.strip()
            if eq and k in fleet_only_keys:
                return _reject(
                    f"clause key {k!r} in {clause!r} is replica-scoped and "
                    f"requires a running FleetRouter (inference/fleet.py) — "
                    f"no fleet is running, so the scope could never match")
            if not eq or k not in known_keys:
                close = difflib.get_close_matches(
                    k, set(known_keys) | set(fleet_only_keys), n=1,
                    cutoff=0.5)
                hint = f" (did you mean {close[0]!r}?)" if close else ""
                return _reject(f"bad clause key {k!r}{hint} in {clause!r}; "
                               f"known: {sorted(known_keys)}")
            try:
                kv[k] = float(v) if k == "p" else int(v)
            except ValueError:
                return _reject(f"non-numeric value {v.strip()!r} for key "
                               f"{k!r} in {clause!r}")
        out.append(kv)
    return out
