"""Validated parsing for PADDLE_TPU_* operational env switches.

The switches are operator-facing kill/debug levers read at trace or init
time; a typo (``paged_attn`` for ``paged_attention``, ``off`` for ``0``)
used to be silently ignored — the worst failure mode for an escape hatch you
reach for mid-incident.  Every parse here warns (once per distinct value, so
trace-time re-reads don't spam) naming the offending token and the closest
valid spelling.
"""

from __future__ import annotations

import difflib
import os
import warnings

__all__ = ["env_token_set", "env_bool"]

_warned: set[tuple[str, str]] = set()


def _warn_once(name: str, raw: str, msg: str) -> None:
    if (name, raw) in _warned:
        return
    _warned.add((name, raw))
    warnings.warn(msg, stacklevel=3)


def env_token_set(name: str, known: frozenset[str] | set[str]) -> set[str]:
    """Comma-separated token list (e.g. PADDLE_TPU_DISABLE_PALLAS).  Unknown
    tokens are kept (forward compatibility: an old binary must still honor a
    newer kernel name as an opt-out) but warned about with a did-you-mean."""
    raw = os.environ.get(name, "")
    if not raw:
        return set()
    tokens = {s.strip() for s in raw.split(",") if s.strip()}
    unknown = tokens - set(known)
    if unknown:
        hints = []
        for t in sorted(unknown):
            close = difflib.get_close_matches(t, known, n=1, cutoff=0.5)
            hints.append(f"{t!r}" + (f" (did you mean {close[0]!r}?)"
                                     if close else ""))
        _warn_once(name, raw,
                   f"{name}={raw!r} contains unrecognized value(s) "
                   f"{', '.join(hints)}; known: {sorted(known)}")
    return tokens


def env_bool(name: str, default: bool) -> bool:
    """Boolean switch: '' -> default, '0' -> False, '1' -> True.  Any other
    value warns and falls back to the default — a typo must not silently
    flip a kill switch either way."""
    raw = os.environ.get(name, "")
    if raw == "":
        return default
    if raw == "0":
        return False
    if raw == "1":
        return True
    _warn_once(name, raw,
               f"{name}={raw!r} is not '0' or '1'; using the default "
               f"({'1' if default else '0'})")
    return default
