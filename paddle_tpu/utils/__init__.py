"""paddle.utils (reference: python/paddle/utils/) — the pieces scripts
actually touch: deprecated decorator, try_import, unique_name, run_check,
dlpack bridge, download (local-cache only: zero-egress build)."""

from __future__ import annotations

import functools
import importlib
import os
import warnings

from . import dlpack  # noqa: F401
from . import download  # noqa: F401
from . import unique_name  # noqa: F401

__all__ = ["deprecated", "try_import", "run_check", "require_version",
           "dlpack", "download", "unique_name"]


def require_version(min_version: str, max_version: str | None = None) -> None:
    """Raise unless the installed framework version is within
    [min_version, max_version] (reference: base/framework.py:573)."""
    if not isinstance(min_version, str):
        raise TypeError(f"min_version must be str, but received type of "
                        f"min_version: {type(min_version)}")
    if not isinstance(max_version, (str, type(None))):
        raise TypeError(f"max_version must be str or type(None), but received "
                        f"type of max_version: {type(max_version)}")
    import re

    fmt = re.compile(r"\d+(\.\d+){0,3}")
    for label, v in (("min_version", min_version), ("max_version", max_version)):
        if v is not None and fmt.fullmatch(v) is None:
            raise ValueError(f"{label} should be like '1.5.2.0', but received "
                             f"{v!r}")

    from .. import __version__

    def key(v):
        parts = [int(x) for x in v.split(".")]
        return parts + [0] * (4 - len(parts))

    installed = key(__version__.split("+")[0].split("rc")[0] or "0")
    if installed < key(min_version) or (
            max_version is not None and installed > key(max_version)):
        bound = (f"in [{min_version}, {max_version}]" if max_version
                 else f">= {min_version}")
        raise Exception(  # noqa: TRY002 — reference raises bare Exception
            f"VersionError: installed version {__version__} does not satisfy "
            f"the requirement {bound}")


def deprecated(update_to: str = "", since: str = "", reason: str = "",
               level: int = 0):
    """Mark an API deprecated (reference: utils/deprecated.py)."""

    def decorator(func):
        msg = f"API {func.__module__}.{func.__name__} is deprecated"
        if since:
            msg += f" since {since}"
        if update_to:
            msg += f", use {update_to} instead"
        if reason:
            msg += f". reason: {reason}"
        @functools.wraps(func)
        def wrapper(*args, **kwargs):
            if level == 2:  # raise at CALL time, like the reference
                raise RuntimeError(msg)
            if level == 1:
                warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return func(*args, **kwargs)

        wrapper.__doc__ = (f"\n.. warning:: {msg}\n\n" + (func.__doc__ or ""))
        return wrapper

    return decorator


def try_import(module_name: str, err_msg: str | None = None):
    """Import an optional dependency with a helpful error (reference:
    utils/lazy_import.py)."""
    try:
        return importlib.import_module(module_name)
    except ImportError as e:
        raise ImportError(
            err_msg or f"optional dependency {module_name!r} is not installed "
                       "(this build cannot pip install; vendor it or gate the "
                       "feature)") from e


def run_check():
    """Smoke-check the install (reference: utils/install_check.py): run one
    jitted matmul on the default backend and report."""
    import jax
    import jax.numpy as jnp

    dev = jax.devices()[0]
    y = jax.jit(lambda a: (a @ a).sum())(jnp.ones((128, 128), jnp.float32))
    float(y)
    print(f"paddle_tpu is installed successfully! backend={jax.default_backend()} "
          f"device={getattr(dev, 'device_kind', dev)}")
