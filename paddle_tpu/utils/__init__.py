"""paddle.utils (reference: python/paddle/utils/) — the pieces scripts
actually touch: deprecated decorator, try_import, unique_name, run_check,
dlpack bridge, download (local-cache only: zero-egress build)."""

from __future__ import annotations

import functools
import importlib
import os
import warnings

from . import dlpack  # noqa: F401
from . import download  # noqa: F401
from . import unique_name  # noqa: F401

__all__ = ["deprecated", "try_import", "run_check", "dlpack", "download",
           "unique_name"]


def deprecated(update_to: str = "", since: str = "", reason: str = "",
               level: int = 0):
    """Mark an API deprecated (reference: utils/deprecated.py)."""

    def decorator(func):
        msg = f"API {func.__module__}.{func.__name__} is deprecated"
        if since:
            msg += f" since {since}"
        if update_to:
            msg += f", use {update_to} instead"
        if reason:
            msg += f". reason: {reason}"
        @functools.wraps(func)
        def wrapper(*args, **kwargs):
            if level == 2:  # raise at CALL time, like the reference
                raise RuntimeError(msg)
            if level == 1:
                warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return func(*args, **kwargs)

        wrapper.__doc__ = (f"\n.. warning:: {msg}\n\n" + (func.__doc__ or ""))
        return wrapper

    return decorator


def try_import(module_name: str, err_msg: str | None = None):
    """Import an optional dependency with a helpful error (reference:
    utils/lazy_import.py)."""
    try:
        return importlib.import_module(module_name)
    except ImportError as e:
        raise ImportError(
            err_msg or f"optional dependency {module_name!r} is not installed "
                       "(this build cannot pip install; vendor it or gate the "
                       "feature)") from e


def run_check():
    """Smoke-check the install (reference: utils/install_check.py): run one
    jitted matmul on the default backend and report."""
    import jax
    import jax.numpy as jnp

    dev = jax.devices()[0]
    y = jax.jit(lambda a: (a @ a).sum())(jnp.ones((128, 128), jnp.float32))
    float(y)
    print(f"paddle_tpu is installed successfully! backend={jax.default_backend()} "
          f"device={getattr(dev, 'device_kind', dev)}")
