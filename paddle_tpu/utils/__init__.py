"""paddle.utils (reference: python/paddle/utils/) — the pieces scripts
actually touch: deprecated decorator, try_import, unique_name, run_check,
dlpack bridge, download (local-cache only: zero-egress build)."""

from __future__ import annotations

import functools
import importlib
import os
import warnings

from . import dlpack  # noqa: F401
from . import download  # noqa: F401
from . import unique_name  # noqa: F401

__all__ = ["deprecated", "try_import", "run_check", "require_version",
           "register_custom_op", "dlpack", "download", "unique_name"]


def register_custom_op(name: str, fn, vjp=None, tensor_method=None):
    """Minimal custom-op extension point (VERDICT Missing #5; reference:
    ``paddle.utils.cpp_extension`` / PyLayer custom-op registration —
    python/paddle/utils/cpp_extension/extension_utils.py).

    Registers a user-provided pure JAX function (or a Pallas-kernel wrapper —
    anything traceable) into the op registry (:mod:`paddle_tpu.ops.registry`)
    and returns a public wrapper that dispatches through the eager autograd
    tape (:func:`paddle_tpu.core.tensor.apply_op`), so the op composes with
    Tensor inputs, ``backward()``, AMP casts, and static-program recording
    exactly like a built-in.

    ``fn(*arrays, **static_kwargs) -> array | tuple``: the forward, pure jnp.
    ``vjp(*arrays, cotangent, **static_kwargs) -> grad | tuple_of_grads``:
    optional custom backward (one cotangent per output, matching fn's output
    structure; receives the same static kwargs the call passed to ``fn``).
    Without it, the backward is ``jax.vjp`` of ``fn`` (XLA autodiff).  With
    it, ``fn`` is wrapped in ``jax.custom_vjp`` with the inputs as residuals —
    the route for Pallas kernels whose reverse pass is hand-written.
    ``tensor_method``: install the wrapper as a Tensor method under this name
    (True → same name as the op).

    Returns the registered wrapper; raises ``ValueError`` on a name already
    in the registry (builtin or custom)."""
    from ..core.tensor import Tensor, apply_op
    from ..ops import registry

    if name in registry.OPS:
        raise ValueError(f"op {name!r} is already registered "
                         f"(custom ops may not shadow existing ops)")
    def make_custom(**static_kwargs):
        # jax.custom_vjp resolves kwargs into positional primals (which would
        # leak them into the residuals and break the vjp arity), so static
        # kwargs are closed over instead and forwarded to BOTH fn and vjp
        import jax

        wrapped = jax.custom_vjp(lambda *a: fn(*a, **static_kwargs))

        def _fwd(*args):
            return fn(*args, **static_kwargs), args

        def _bwd(res, ct):
            g = vjp(*res, ct, **static_kwargs)
            return tuple(g) if isinstance(g, (tuple, list)) else (g,)

        wrapped.defvjp(_fwd, _bwd)
        return wrapped

    inner = make_custom() if vjp is not None else fn

    @functools.wraps(fn)
    def op(*args, **static_kwargs):
        if vjp is not None:
            if static_kwargs:
                return apply_op(name, make_custom(**static_kwargs), list(args))
            return apply_op(name, inner, list(args))
        return apply_op(name, inner, list(args), **static_kwargs)

    op.__name__ = op.__qualname__ = name
    registry.register_op(name, tensor_method=tensor_method)(op)
    registry.install_tensor_methods(Tensor)
    return op


def require_version(min_version: str, max_version: str | None = None) -> None:
    """Raise unless the installed framework version is within
    [min_version, max_version] (reference: base/framework.py:573)."""
    if not isinstance(min_version, str):
        raise TypeError(f"min_version must be str, but received type of "
                        f"min_version: {type(min_version)}")
    if not isinstance(max_version, (str, type(None))):
        raise TypeError(f"max_version must be str or type(None), but received "
                        f"type of max_version: {type(max_version)}")
    import re

    fmt = re.compile(r"\d+(\.\d+){0,3}")
    for label, v in (("min_version", min_version), ("max_version", max_version)):
        if v is not None and fmt.fullmatch(v) is None:
            raise ValueError(f"{label} should be like '1.5.2.0', but received "
                             f"{v!r}")

    from .. import __version__

    def key(v):
        parts = [int(x) for x in v.split(".")]
        return parts + [0] * (4 - len(parts))

    installed = key(__version__.split("+")[0].split("rc")[0] or "0")
    if installed < key(min_version) or (
            max_version is not None and installed > key(max_version)):
        bound = (f"in [{min_version}, {max_version}]" if max_version
                 else f">= {min_version}")
        raise Exception(  # noqa: TRY002 — reference raises bare Exception
            f"VersionError: installed version {__version__} does not satisfy "
            f"the requirement {bound}")


def deprecated(update_to: str = "", since: str = "", reason: str = "",
               level: int = 0):
    """Mark an API deprecated (reference: utils/deprecated.py)."""

    def decorator(func):
        msg = f"API {func.__module__}.{func.__name__} is deprecated"
        if since:
            msg += f" since {since}"
        if update_to:
            msg += f", use {update_to} instead"
        if reason:
            msg += f". reason: {reason}"
        @functools.wraps(func)
        def wrapper(*args, **kwargs):
            if level == 2:  # raise at CALL time, like the reference
                raise RuntimeError(msg)
            if level == 1:
                warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return func(*args, **kwargs)

        wrapper.__doc__ = (f"\n.. warning:: {msg}\n\n" + (func.__doc__ or ""))
        return wrapper

    return decorator


def try_import(module_name: str, err_msg: str | None = None):
    """Import an optional dependency with a helpful error (reference:
    utils/lazy_import.py)."""
    try:
        return importlib.import_module(module_name)
    except ImportError as e:
        raise ImportError(
            err_msg or f"optional dependency {module_name!r} is not installed "
                       "(this build cannot pip install; vendor it or gate the "
                       "feature)") from e


def run_check():
    """Smoke-check the install (reference: utils/install_check.py): run one
    jitted matmul on the default backend and report."""
    import jax
    import jax.numpy as jnp

    dev = jax.devices()[0]
    y = jax.jit(lambda a: (a @ a).sum())(jnp.ones((128, 128), jnp.float32))
    float(y)
    print(f"paddle_tpu is installed successfully! backend={jax.default_backend()} "
          f"device={getattr(dev, 'device_kind', dev)}")
