"""DLPack interop (reference: python/paddle/utils/dlpack.py) — zero-copy
exchange with torch/numpy/cupy via jax's dlpack support."""

from __future__ import annotations

__all__ = ["to_dlpack", "from_dlpack"]


def to_dlpack(x):
    """Return the array as a DLPack-protocol object (has ``__dlpack__`` /
    ``__dlpack_device__``), consumable by np.from_dlpack / torch.from_dlpack
    and :func:`from_dlpack` below.  The legacy raw-PyCapsule contract is
    gone from the ecosystem (modern jax/numpy refuse bare capsules); a
    capsule-only consumer can call ``to_dlpack(x).__dlpack__()`` itself."""
    from ..core.tensor import _unwrap

    return _unwrap(x)


def from_dlpack(capsule):
    import jax

    from ..core.tensor import Tensor

    try:
        arr = jax.dlpack.from_dlpack(capsule)
    except TypeError:
        import jax.numpy as jnp

        arr = jnp.from_dlpack(capsule)
    return Tensor(arr)
