"""DLPack interop (reference: python/paddle/utils/dlpack.py) — zero-copy
exchange with torch/numpy/cupy via jax's dlpack support."""

from __future__ import annotations

__all__ = ["to_dlpack", "from_dlpack"]


def to_dlpack(x):
    # jax arrays implement the capsule protocol (__dlpack__) directly; the
    # old jax.dlpack.to_dlpack helper no longer exists
    from ..core.tensor import _unwrap

    return _unwrap(x)


def from_dlpack(capsule):
    import jax

    from ..core.tensor import Tensor

    try:
        arr = jax.dlpack.from_dlpack(capsule)
    except TypeError:
        import jax.numpy as jnp

        arr = jnp.from_dlpack(capsule)
    return Tensor(arr)
