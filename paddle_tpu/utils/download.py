"""Weight-file resolution (reference: python/paddle/utils/download.py).

This build has no network egress, so resolution is cache-only: a URL maps to
``$DATA_HOME/<basename>`` and must already exist there (place files manually
or mount a cache).  The error says exactly where to put the file.
"""

from __future__ import annotations

import os

__all__ = ["get_weights_path_from_url", "DATA_HOME"]

DATA_HOME = os.environ.get(
    "PADDLE_TPU_DATA_HOME",
    os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu"))


def get_weights_path_from_url(url: str, md5sum: str | None = None) -> str:
    fname = os.path.basename(url.split("?")[0])
    path = os.path.join(DATA_HOME, "weights", fname)
    if os.path.isfile(path):
        return path
    raise FileNotFoundError(
        f"cannot download {url!r}: this build has no network access. "
        f"Place the file at {path!r} and retry.")
