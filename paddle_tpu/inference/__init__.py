"""Inference engine (reference: paddle/fluid/inference/ —
`AnalysisPredictor` at inference/api/analysis_predictor.h, Config at
inference/api/paddle_analysis_config.h, file format model+params).

TPU-native mapping (SURVEY.md §2.4): the reference's analysis pipeline
(~290 IR fusion passes, memory-optimize, TensorRT subgraphs) is XLA's job —
the program is compiled AOT by PJRT with fusion + layout assignment + buffer
assignment.  What this module keeps is the *deployment surface*:

* a serialized program artifact (`.pdmodel` = StableHLO bytes via
  ``jax.export``, versioned and loadable without the Python model code) plus
  a weights file (`.pdiparams`) — the same two-file contract as the
  reference;
* ``Config`` with the reference's knobs mapped to their XLA equivalents
  (memory-optim → buffer donation, ir-optim → XLA autotuning level,
  precision → bf16 cast);
* ``Predictor`` with the reference's handle-style API
  (get_input_names/get_input_handle/run/get_output_handle) and AOT
  compile-on-load;
* an LLM ``GenerationEngine`` (prefill + KV-cache decode loop over the
  decode-attention ops) — the serving path the reference covers with
  block_multihead_attention + PaddleNLP's predictor.
"""

from __future__ import annotations

import os
import pickle

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "Config",
    "Predictor",
    "create_predictor",
    "save_inference_model",
    "load_inference_model",
    "GenerationEngine",
    "ContinuousBatchingEngine",
    "Request",
    "FleetRouter",
    "MetricsRegistry",
    "SLOTracker",
    "FlightRecorder",
    "HostKVTier",
]


def save_inference_model(path_prefix: str, fn, example_inputs, params=None,
                         precision: str | None = None):
    """Export ``fn(params, *inputs)`` (or ``fn(*inputs)`` when params is None)
    as a deployable artifact.

    Writes ``<prefix>.pdmodel`` — serialized StableHLO (jax.export), callable
    without the defining Python code — and ``<prefix>.pdiparams`` — pickled
    numpy weights.  Mirrors the reference's save_inference_model contract
    (python/paddle/static/io.py:save_inference_model).

    ``precision`` ("bfloat16"/"float16"): cast floating params to the low
    precision *before* tracing, so the exported program carries the low-
    precision signature (the export is an AOT artifact — dtype cannot change
    after the fact)."""
    from jax import export as jexport

    os.makedirs(os.path.dirname(os.path.abspath(path_prefix)) or ".", exist_ok=True)

    if precision and params is not None:
        params = jax.tree_util.tree_map(
            lambda x: jnp.asarray(x).astype(precision)
            if jnp.issubdtype(jnp.result_type(x), jnp.floating) else x, params)

    def spec(x):
        return jax.ShapeDtypeStruct(jnp.shape(x), jnp.result_type(x))

    if params is not None:
        jitted = jax.jit(lambda p, *a: fn(p, *a))
        args = (jax.tree_util.tree_map(spec, params),
                *[spec(a) for a in example_inputs])
    else:
        jitted = jax.jit(fn)
        args = tuple(spec(a) for a in example_inputs)
    exported = jexport.export(jitted)(*args)
    with open(path_prefix + ".pdmodel", "wb") as f:
        f.write(exported.serialize())
    weights = (jax.tree_util.tree_map(lambda x: np.asarray(x), params)
               if params is not None else None)
    with open(path_prefix + ".pdiparams", "wb") as f:
        pickle.dump(weights, f, protocol=4)


def load_inference_model(path_prefix: str):
    """Returns (exported_callable, params)."""
    from jax import export as jexport

    with open(path_prefix + ".pdmodel", "rb") as f:
        exported = jexport.deserialize(f.read())
    with open(path_prefix + ".pdiparams", "rb") as f:
        params = pickle.load(f)
    return exported, params


class Config:
    """Deployment config (reference: AnalysisConfig /
    paddle_infer.Config — inference/api/paddle_analysis_config.h)."""

    def __init__(self, prog_file: str | None = None, params_file: str | None = None):
        if prog_file and prog_file.endswith(".pdmodel"):
            prog_file = prog_file[: -len(".pdmodel")]
        self.model_prefix = prog_file
        self._memory_optim = True
        self._ir_optim = True
        self._precision = "float32"
        self._device = "tpu"
        self._device_id = 0
        self._enable_profile = False

    # -- reference-parity knobs ------------------------------------------
    def enable_use_gpu(self, memory_pool_mb: int = 0, device_id: int = 0):
        """Accepted for API compat; maps to the default accelerator (TPU)."""
        self._device, self._device_id = "tpu", device_id

    def enable_xpu(self, *a, **kw):
        self._device = "tpu"

    def disable_gpu(self):
        self._device = "cpu"

    def enable_memory_optim(self, x: bool = True):
        self._memory_optim = x

    def switch_ir_optim(self, x: bool = True):
        self._ir_optim = x

    def enable_profile(self):
        self._enable_profile = True

    def set_cpu_math_library_num_threads(self, n: int):
        pass  # XLA threadpool is managed by the runtime

    def enable_low_precision(self, dtype="bfloat16"):
        """Note: the .pdmodel is an AOT artifact with a fixed dtype signature —
        this knob only takes effect when the model was exported with
        ``save_inference_model(..., precision=...)``; otherwise it is ignored
        with a warning at load."""
        self._precision = dtype

    def summary(self) -> str:
        return (f"Config(model={self.model_prefix!r}, device={self._device}, "
                f"precision={self._precision}, memory_optim={self._memory_optim})")


class _Handle:
    """Input/output tensor handle (reference: ZeroCopyTensor /
    paddle_infer.Tensor)."""

    def __init__(self, name):
        self.name = name
        self._value = None

    def copy_from_cpu(self, arr):
        self._value = np.ascontiguousarray(arr)

    def reshape(self, shape):
        pass  # shapes come from the AOT signature

    def copy_to_cpu(self):
        return np.asarray(self._value)

    def shape(self):
        return None if self._value is None else tuple(self._value.shape)


class Predictor:
    """AOT predictor (reference: AnalysisPredictor,
    inference/api/analysis_predictor.h).

    Loads the serialized StableHLO program + weights, places weights on the
    target device once, and runs the compiled executable per call — the
    reference's Run() path (feed → execute → fetch) without the per-op
    interpreter."""

    def __init__(self, config: Config):
        self.config = config
        if config.model_prefix is None:
            raise ValueError("Config has no model path")
        self._exported, params = load_inference_model(config.model_prefix)
        dev = (jax.devices("cpu")[0] if config._device == "cpu"
               else jax.devices()[config._device_id])
        self._device = dev
        if config._precision in ("bfloat16", "float16") and params is not None:
            # only honor if the exported signature already is low-precision
            # (set via save_inference_model(precision=...)); the AOT program's
            # avals are fixed at export time.
            leaf_dtypes = {np.asarray(x).dtype for x in
                           jax.tree_util.tree_leaves(params)
                           if np.issubdtype(np.asarray(x).dtype, np.floating)}
            if leaf_dtypes and all(str(d) == config._precision for d in leaf_dtypes):
                pass  # already exported at this precision
            else:
                import warnings

                warnings.warn(
                    "enable_low_precision ignored: model was exported at "
                    f"{[str(d) for d in leaf_dtypes]}; re-export with "
                    "save_inference_model(precision=...)")
        self._params = (jax.device_put(params, dev) if params is not None else None)
        n_model_inputs = len(self._exported.in_avals)
        self._n_data_inputs = (n_model_inputs
                               - (len(jax.tree_util.tree_leaves(self._params))
                                  if self._params is not None else 0))
        self._input_handles = {f"x{i}": _Handle(f"x{i}")
                               for i in range(self._n_data_inputs)}
        self._output_handles: dict[str, _Handle] = {}

    # -- handle-style API (reference predictor surface) -------------------
    def get_input_names(self):
        return list(self._input_handles)

    def get_input_handle(self, name):
        return self._input_handles[name]

    def get_output_names(self):
        return list(self._output_handles) or ["out0"]

    def get_output_handle(self, name):
        return self._output_handles[name]

    def run(self, inputs=None):
        """Either pass arrays directly (returns outputs) or use handles."""
        if inputs is None:
            inputs = [self._input_handles[n]._value for n in self._input_handles]
        inputs = [jax.device_put(np.asarray(a), self._device) for a in inputs]
        if self._params is not None:
            out = self._exported.call(self._params, *inputs)
        else:
            out = self._exported.call(*inputs)
        outs = out if isinstance(out, (tuple, list)) else (out,)
        self._output_handles = {}
        for i, o in enumerate(outs):
            h = _Handle(f"out{i}")
            h._value = np.asarray(o)
            self._output_handles[h.name] = h
        return [np.asarray(o) for o in outs]


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)


# ---------------------------------------------------------------------------
# LLM serving: prefill + KV-cache decode (block_multihead_attention path)
# ---------------------------------------------------------------------------

def transformer_apply(cfg, params, x, cache_k, cache_v, write_fn, mask, cos,
                      sin, attend_fn=None, tp_axis=None, fused_fn=None,
                      mlp_fused_fn=None):
    """Cache-threading transformer body shared by GenerationEngine and the
    continuous-batching engine (serving.py) — one copy of the GQA attend +
    rms/rope/swiglu scan so masking/grouping fixes can't diverge.

    ``write_fn(cache_layer, kv) -> (committed, attend_view)`` commits new K/V
    into a per-layer cache [B, nkv, S, hd] and returns the view attention
    should read (usually the committed cache itself; the slot-prefill path
    returns its single lane so a batch-1 prompt can prefill into a wider
    pool).  ``mask`` broadcasts against logits [b, nkv, rep, s, S].
    ``attend_fn(q [b, s, nh, hd], k_view, v_view) -> [b, s, nh*hd]``
    overrides the dense masked attend — the paged decode path passes the
    ragged paged-attention kernel here, with write_fn returning the RAW
    paged pool (no gathered view) as k_view/v_view; ``mask`` is then unused.
    Returns (final-normed hidden [b, s, h], all_k, all_v).

    ``fused_fn(q_pre, k_pre, v, cache_k_layer, cache_v_layer) ->
    (attn_out [b, s, nh*hd], new_cache_k, new_cache_v)`` replaces the whole
    rope -> write_fn -> attend sequence with ONE call — the paged decode
    path passes the fused rope+append+attention Pallas step here
    (ops/pallas/paged_attention.fused_decode_step, docs/paged_attention.md
    "Fused decode step"); q/k arrive PRE-rope and ``mask``/``write_fn``/
    ``attend_fn`` are unused.  ``fused_fn=None`` (every other engine)
    traces the exact pre-fusion program.

    ``mlp_fused_fn(h_res, attn_y, lp) -> (h1, y)`` (decode megastep
    stage 2, docs/paged_attention.md "Megastep stage 2") fuses the
    post-attention half of each layer — residual add, post RMSNorm and
    the SwiGLU MLP between the two TP psum boundaries — into ONE call
    (the serving decode path passes ops/pallas/paged_attention.
    fused_layer_mlp through models/llama.decoder_layer_tail's seam).
    With it set, the per-layer INPUT rms_norm also runs as the inline
    jnp composition (rms_norm_ref) instead of its own Pallas launch —
    at decode's [B, 1, h] activations a separate launch is pure
    dispatch tax, and XLA fuses the inline norm into the QKV matmuls —
    so a fused decode layer traces exactly two Pallas launches.
    ``mlp_fused_fn=None`` traces the pre-stage-2 program byte-for-byte.

    ``tp_axis`` (docs/tp_serving.md): name of the mesh axis when this body
    runs INSIDE a shard_map region of the continuous-batching engine's
    ``tensor_parallel`` mode.  ``cfg`` then carries tp-LOCAL head counts
    (nh/tp query heads, nkv/tp kv heads over the same full head_dim), the
    caches/params are the local shards, and the residual stream stays
    replicated through the two per-layer psum boundaries the shared decoder
    halves insert (models/llama.decoder_attn_residual /
    decoder_mlp_residual).  ``tp_axis=None`` (every single-chip engine)
    traces the exact pre-TP program.
    """
    from ..models.llama import decoder_layer_tail
    from ..ops.pallas import rms_norm as rms
    from ..ops.pallas import rope as rope_mod

    b, s = x.shape[:2]
    nh, nkv, hd = (cfg.num_attention_heads, cfg.num_key_value_heads,
                   cfg.head_dim)
    rep = nh // nkv

    def attend(q, k_all, v_all):
        # fused GQA decode (masked_multihead_attention analog): q heads are
        # grouped per kv head in the einsum itself — the cache is read once
        # and never repeated in HBM, which is what bounds decode throughput
        qg = q.reshape(b, s, nkv, rep, hd)
        logits = jnp.einsum("bsngd,bnSd->bngsS", qg.astype(jnp.float32),
                            k_all.astype(jnp.float32)) / np.sqrt(hd)
        logits = jnp.where(mask, logits, -1e30)
        p = jax.nn.softmax(logits, axis=-1)
        out = jnp.einsum("bngsS,bnSd->bsngd", p.astype(v_all.dtype), v_all)
        return out.reshape(b, s, nh * hd)

    attend = attend_fn or attend

    def wmat(entry, dt):
        """Dense [in, out] matrix from a param leaf — either fp as stored,
        or a weight-only quantized {'qweight': int8/int4 [out, in],
        'scale': [out]} dict whose dequant multiply XLA fuses into the
        matmul's HBM read (the weight streams at 1/2 or 1/4 the bytes:
        the lever in bandwidth-bound decode)."""
        if isinstance(entry, dict):
            from ..nn.quant import _dequant_2d

            return _dequant_2d(entry["qweight"], entry["scale"], dt)
        return entry

    def body(carry, layer_in):
        x = carry
        lp, ck, cv = layer_in
        dt = x.dtype
        if mlp_fused_fn is None:
            xn = rms.rms_norm(x, lp["input_norm"], cfg.rms_norm_eps)
        else:
            # fused-layer mode: the input norm runs inline (XLA fuses it
            # into the QKV matmuls) instead of as its own Pallas launch —
            # at [B, 1, h] decode activations the launch IS the cost
            xn = rms.rms_norm_ref(x, lp["input_norm"], cfg.rms_norm_eps)
        q = (xn @ wmat(lp["wq"], dt)).reshape(b, s, nh, hd)
        k = (xn @ wmat(lp["wk"], dt)).reshape(b, s, nkv, hd)
        v = (xn @ wmat(lp["wv"], dt)).reshape(b, s, nkv, hd)
        if fused_fn is not None:
            # rope + KV append + attention in one fused launch (q/k pre-rope)
            attn, ck, cv = fused_fn(q, k, v, ck, cv)
        else:
            q, k = rope_mod.apply_rotary_pos_emb(q, k, cos, sin)
            ck, k_att = write_fn(ck, k)
            cv, v_att = write_fn(cv, v)
            attn = attend(q, k_att, v_att)
        # the post-attention half routes through the ONE shared seam
        # (models/llama.decoder_layer_tail): mlp_fn=None composes the
        # factored decoder halves byte-identically (the pre-stage-2
        # program, under TP holding the layer's two psums); the fused
        # serving decode path passes the fused MLP launch here
        x = decoder_layer_tail(cfg, x, attn, lp, wmat=wmat,
                               tp_axis=tp_axis, mlp_fn=mlp_fused_fn)
        return x, (ck, cv)

    x, (all_k, all_v) = jax.lax.scan(body, x, (params["layers"], cache_k, cache_v))
    return rms.rms_norm(x, params["final_norm"], cfg.rms_norm_eps), all_k, all_v


_QUANT_ALGOS = {"int8": "weight_only_int8", "int4": "weight_only_int4"}
_MATMUL_LEAVES = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def quantize_layer_params(params, quant: str):
    """Weight-only-quantize the stacked per-layer matmul weights of a llama
    param tree (embed / lm_head / norms stay fp).  Each [L, K, N] leaf
    becomes {'qweight': [L, N, K] int8|int4, 'scale': [L, N] f32} — the
    serving analog of the reference's weight_quantize + weight_only_linear
    deployment flow (nn/quant/quantized_linear.py)."""
    from ..nn.quant import _quantize_2d

    algo = _QUANT_ALGOS[quant]
    out = dict(params)
    layers = dict(params["layers"])
    for name in _MATMUL_LEAVES:
        q, s = jax.vmap(lambda w: _quantize_2d(w, algo))(layers[name])
        layers[name] = {"qweight": q, "scale": s}
    out["layers"] = layers
    return out


def lm_head_logits(cfg, params, x_last):
    """Project final hidden state(s) through the (possibly tied) LM head."""
    head = params.get("lm_head")
    if head is None:
        head = params["embed"].T.astype(cfg.dtype)
    return x_last @ head


class GenerationEngine:
    """Greedy/temperature decoding for the Llama family with a dense KV cache.

    Reference analog: PaddleNLP's predictor over the reference's
    block/masked_multihead_attention fused ops.  Prefill and decode are two
    AOT-compiled programs with static shapes (max_seq padding), the TPU-serving
    pattern; the decode step threads the cache functionally (donated buffers)."""

    def __init__(self, cfg, params, max_seq: int = 512, quant: str | None = None):
        """``quant``: None (fp), 'int8' or 'int4' — weight-only quantize the
        per-layer matmul weights at load (reference deployment flow:
        weight_quantize + weight_only_linear; on a 16GB v5e this is what
        makes >7B models servable at all)."""
        from ..models import llama as _llama

        self.cfg = cfg
        self.max_seq = max_seq
        if quant is not None:
            params = quantize_layer_params(params, quant)
        self.params = params
        self.quant = quant
        self._llama = _llama
        self._prefill = jax.jit(self._prefill_impl, static_argnums=())
        self._decode = jax.jit(self._decode_impl, donate_argnums=(1, 2))

    # cache: k/v [L, b, nkv, S, hd]
    def init_cache(self, batch):
        cfg = self.cfg
        shape = (cfg.num_hidden_layers, batch, cfg.num_key_value_heads,
                 self.max_seq, cfg.head_dim)
        return (jnp.zeros(shape, cfg.dtype), jnp.zeros(shape, cfg.dtype))

    def _forward_tokens(self, params, ids, cache_k, cache_v, start_pos):
        """Run s tokens starting at start_pos; returns logits of last token and
        the updated caches."""
        cfg = self.cfg
        from ..ops.pallas import rope as rope_mod

        b, s = ids.shape
        S = self.max_seq
        x = jnp.take(params["embed"], ids, axis=0).astype(cfg.dtype)
        cos_full, sin_full = rope_mod.rope_cos_sin(S, cfg.head_dim,
                                                   base=cfg.rope_theta,
                                                   dtype=cfg.dtype)
        # rope_cos_sin returns [1, S, d]; slice the sequence axis
        cos = jax.lax.dynamic_slice_in_dim(cos_full, start_pos, s, axis=1)
        sin = jax.lax.dynamic_slice_in_dim(sin_full, start_pos, s, axis=1)
        # causal-with-offset mask over the cache: key j visible to query i iff
        # j <= start_pos + i; broadcast to logits [b, nkv, rep, s, S]
        kv_pos = jnp.arange(S)[None, None, None, None, :]
        q_pos = start_pos + jnp.arange(s)[None, None, None, :, None]
        mask = kv_pos <= q_pos

        def write(ck, k):
            out = jax.lax.dynamic_update_slice_in_dim(
                ck, k.transpose(0, 2, 1, 3), start_pos, axis=2)
            return out, out

        x, all_k, all_v = transformer_apply(cfg, params, x, cache_k, cache_v,
                                            write, mask, cos, sin)
        return lm_head_logits(cfg, params, x[:, -1]), all_k, all_v

    def _prefill_impl(self, params, ids, cache_k, cache_v):
        return self._forward_tokens(params, ids, cache_k, cache_v, 0)

    def _decode_impl(self, params, cache_k, cache_v, token, pos):
        return self._forward_tokens(params, token, cache_k, cache_v, pos)

    def generate(self, prompt_ids: np.ndarray, max_new_tokens: int = 32,
                 temperature: float = 0.0, seed: int = 0):
        """prompt_ids: [b, s0] int32. Returns [b, s0 + max_new_tokens]."""
        cfg = self.cfg
        b, s0 = prompt_ids.shape
        assert s0 + max_new_tokens <= self.max_seq
        cache_k, cache_v = self.init_cache(b)
        ids = jnp.asarray(prompt_ids, jnp.int32)
        logits, cache_k, cache_v = self._prefill(self.params, ids, cache_k, cache_v)
        rng = jax.random.key(seed)
        out = [ids]
        pos = s0
        for _ in range(max_new_tokens):
            if temperature > 0:
                rng, k = jax.random.split(rng)
                nxt = jax.random.categorical(k, logits / temperature, axis=-1)
            else:
                nxt = jnp.argmax(logits, axis=-1)
            nxt = nxt[:, None].astype(jnp.int32)
            out.append(nxt)
            logits, cache_k, cache_v = self._decode(self.params, cache_k,
                                                    cache_v, nxt, pos)
            pos += 1
        return np.asarray(jnp.concatenate(out, axis=1))


def __getattr__(name):
    # lazy serving-tier exports: the continuous-batching engine and the
    # fleet router pull in the whole paged/serving stack (serving.py,
    # fleet.py), which plain Predictor/GenerationEngine users never need —
    # importing paddle_tpu.inference stays cheap until the first touch
    if name in ("ContinuousBatchingEngine", "Request"):
        from . import serving

        return getattr(serving, name)
    if name == "FleetRouter":
        from .fleet import FleetRouter

        return FleetRouter
    if name in ("MetricsRegistry", "SLOTracker", "FlightRecorder"):
        from . import observability

        return getattr(observability, name)
    if name == "HostKVTier":
        from .kv_tier import HostKVTier

        return HostKVTier
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
