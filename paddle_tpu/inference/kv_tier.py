"""Hierarchical KV: the host-RAM spill tier behind the prefix cache
(ISSUE 13 tentpole; docs/kv_tier.md; ROADMAP item 2).

Prefix-cache capacity used to be hard-capped at leftover HBM: LRU eviction
(PR 2) *freed* zero-ref chains, so every evicted system prompt was a full
re-prefill, and PR 8's failover recomputed KV teacher-forced because
finished pages could not move between replicas.  :class:`HostKVTier` is the
missing tier — a host-memory page store keyed by the prefix cache's
content-address hash chain, holding demoted KV pages under a byte budget
(``PADDLE_TPU_HOST_TIER_MIB``) with its own LRU.  Host RAM is roughly an
order of magnitude larger than leftover HBM per chip, so the set of
resident system prompts scales with the host, not the accelerator.

The transport contract (the piece ROADMAP item 1's disaggregated
prefill/decode shipping consumes unchanged):

* :meth:`ship_out` — device -> host.  One **page** (one pool block's K and
  V slabs, ``[L, nkv, block_size, hd]`` each — every layer's bytes for
  that block, the unit the block table addresses) moves D2H under its
  chain hash.  Quantized pools ship their per-page scales alongside the
  payload (``k_scale``/``v_scale``), so a dequant-on-read pool stays
  byte-exact through the round trip.  Content-addressed: shipping a hash
  the tier already holds refreshes recency and returns the existing entry
  (identical bytes by the hash-chain contract — the vLLM trade PR 2
  documents).
* :meth:`ship_in` — host -> device.  Looks the hash up, refreshes recency
  and returns the entry whose host arrays the caller uploads (the engine
  dispatches them through a donated jitted pool write, so the H2D overlaps
  the next compiled step by JAX async dispatch).  A **private** tier
  (single engine) removes the entry — demotion *moves* a block D2H and
  re-admission moves it back, the exactly-one-home contract audit
  invariant I10 checks; a **shared** tier (``shared=True``, the
  :class:`~paddle_tpu.inference.fleet.FleetRouter`'s fleet-wide prefix
  store) keeps it, because the same chain must stay re-admittable by every
  other replica (content-addressed duplicates across replicas are
  byte-identical by construction, so exclusivity deliberately relaxes —
  docs/kv_tier.md "I10").

Eviction is plain LRU over unpinned entries, byte-accounted: an insert
that would exceed the budget evicts least-recently-used entries first and
refuses (returns None — the block goes *dead*, exactly what the
pre-tier engine did on every eviction) when even that cannot fit the
page.  :meth:`pin`/:meth:`unpin` protect entries an engine has matched
but not yet restored (the chunked-prefill cursor restores one block per
mixed step, so a match-to-restore window spans steps); ``discard``
force-drops an entry regardless of pins — the ``tier_drop`` fault
injection seam (inference/faults.py), which the engine must survive by
falling back to ordinary prefill.

Everything here is host-side bookkeeping plus numpy buffers; no JAX in
this module.  The device halves of the transport (the D2H gather, the
donated H2D pool write) live with the engine, which owns the pools.
"""

from __future__ import annotations

import heapq

import numpy as np

__all__ = ["HostKVTier", "TierEntry", "DEFAULT_TIER_MIB"]

#: default byte budget (MiB) when ``PADDLE_TPU_HOST_TIER_MIB`` is unset —
#: small enough for CI hosts, an order of magnitude beyond the test pools
DEFAULT_TIER_MIB = 256


def _tier_budget_bytes() -> int:
    """Parse ``PADDLE_TPU_HOST_TIER_MIB`` (validated: a non-integer or
    sub-1 value warns once and keeps the default — utils/envflags.env_int,
    the same never-silently-misconfigure contract as every other
    PADDLE_TPU_* knob)."""
    from ..utils.envflags import env_int

    return env_int("PADDLE_TPU_HOST_TIER_MIB", DEFAULT_TIER_MIB,
                   minimum=1) * (1 << 20)


class TierEntry:
    """One demoted page: host copies of a block's K and V slabs (plus
    per-page quant scales when the pool is dequant-on-read), keyed by the
    block's chain hash.  ``owner`` records the last demoter (the replica
    label) so a shared tier can count cross-replica re-admits."""

    __slots__ = ("hash", "k", "v", "k_scale", "v_scale", "nbytes", "pins",
                 "last_used", "owner")

    def __init__(self, hash_: str, k: np.ndarray, v: np.ndarray,
                 k_scale: np.ndarray | None, v_scale: np.ndarray | None,
                 owner=None):
        self.hash = hash_
        # ascontiguousarray, not asarray: the engine demotes a BATCH of
        # pages with one gathered D2H and hands this ctor per-page numpy
        # VIEWS of the slab — storing the view would pin the entire batch
        # slab in host RAM per entry while nbytes counts only the slice,
        # silently unbounding the byte budget.  A contiguous copy owns
        # exactly the bytes it accounts (no-op for already-owned arrays).
        self.k = np.ascontiguousarray(k)
        self.v = np.ascontiguousarray(v)
        self.k_scale = (None if k_scale is None
                        else np.ascontiguousarray(k_scale))
        self.v_scale = (None if v_scale is None
                        else np.ascontiguousarray(v_scale))
        self.nbytes = int(self.k.nbytes + self.v.nbytes
                          + (self.k_scale.nbytes
                             if self.k_scale is not None else 0)
                          + (self.v_scale.nbytes
                             if self.v_scale is not None else 0))
        self.pins = 0
        self.last_used = 0
        self.owner = owner

    def __repr__(self):  # debugging aid only
        return (f"TierEntry({self.hash[:8]}, {self.nbytes}B, "
                f"pins={self.pins})")


class HostKVTier:
    """Byte-budgeted host-RAM page store keyed by chain hash (module
    docstring; docs/kv_tier.md).

    ``budget_bytes``: LRU ceiling; ``None`` reads
    ``PADDLE_TPU_HOST_TIER_MIB`` (default :data:`DEFAULT_TIER_MIB`).
    ``shared=True`` marks the fleet-wide prefix store: :meth:`ship_in`
    keeps the entry resident so other replicas can still re-admit it, and
    the I10 audit relaxes HBM/tier exclusivity to per-replica accounting
    (a private tier enforces strict move semantics).

    Counters (host-side, read by the engines' stats mirrors and the bench
    rungs): ``demotions`` / ``readmits`` / ``cross_readmits`` (shared tier:
    re-admits of a chain a *different* replica demoted) / ``evictions``
    (budget-pressure LRU drops) / ``drops`` (ship_out refusals: the block
    went dead because even an empty-but-pinned tier could not fit it)."""

    def __init__(self, budget_bytes: int | None = None,
                 shared: bool = False):
        self.budget_bytes = (int(budget_bytes) if budget_bytes is not None
                             else _tier_budget_bytes())
        if self.budget_bytes < 1:
            raise ValueError(
                f"budget_bytes must be >= 1, got {self.budget_bytes}")
        self.shared = bool(shared)
        self._by_hash: dict[str, TierEntry] = {}
        self.used_bytes = 0
        self._tick = 0
        # lazy min-heap of (last_used, hash): stale records (re-touched,
        # pinned, already gone) are skipped on pop — same amortized-O(log n)
        # pattern as the prefix cache's eviction heap
        self._lru_heap: list[tuple[int, str]] = []
        self.demotions = 0
        self.readmits = 0
        self.cross_readmits = 0
        self.evictions = 0
        self.drops = 0

    # ---------------- internals ----------------

    def _touch(self, e: TierEntry) -> None:
        self._tick += 1
        e.last_used = self._tick
        heapq.heappush(self._lru_heap, (e.last_used, e.hash))

    def _remove(self, e: TierEntry) -> None:
        del self._by_hash[e.hash]
        self.used_bytes -= e.nbytes

    def _evict_for(self, need: int) -> bool:
        """Pop LRU unpinned entries until ``need`` bytes fit under the
        budget; False when they cannot (everything left is pinned)."""
        while self.used_bytes + need > self.budget_bytes:
            evicted = False
            while self._lru_heap:
                tick, h = heapq.heappop(self._lru_heap)
                victim = self._by_hash.get(h)
                if (victim is None or victim.last_used != tick
                        or victim.pins > 0):
                    continue            # stale heap record / pinned
                self._remove(victim)
                self.evictions += 1
                evicted = True
                break
            if not evicted:
                return False
        return True

    # ---------------- transport (the ROADMAP item 1 contract) ----------

    def ship_out(self, hash_: str, k_page, v_page, *, k_scale=None,
                 v_scale=None, owner=None) -> TierEntry | None:
        """Device -> host: demote one page under its chain hash.  Arrays
        are materialized to host numpy (``np.asarray`` on a device array IS
        the D2H copy); quantized pools pass their per-page scales so the
        round trip is byte-exact.  Returns the resident entry, or None
        when the page cannot fit even after LRU eviction (the block is
        dead — the caller frees the device page exactly as the pre-tier
        engine did).  Shipping an already-resident hash refreshes recency
        and returns the existing entry (content-addressed dedup: the chain
        hash IS a digest of the bytes)."""
        e = self._by_hash.get(hash_)
        if e is not None:
            # content-addressed dedup: identical bytes by the chain-hash
            # contract — refresh recency, RE-STAMP the owner (the contract
            # is "last demoter", and a stale owner would make the new
            # demoter's own later re-admit count as cross-replica), and
            # count the demotion event so the tier's counter agrees with
            # the engines' per-demotion stats mirrors
            e.owner = owner
            self.demotions += 1
            self._touch(e)
            return e
        e = TierEntry(hash_,
                      np.asarray(k_page), np.asarray(v_page),
                      None if k_scale is None else np.asarray(k_scale),
                      None if v_scale is None else np.asarray(v_scale),
                      owner=owner)
        if not self._evict_for(e.nbytes):
            self.drops += 1
            return None
        self._by_hash[hash_] = e
        self.used_bytes += e.nbytes
        self.demotions += 1
        self._touch(e)
        return e

    def ship_in(self, hash_: str, *, owner=None,
                keep: bool | None = None) -> TierEntry | None:
        """Host -> device half: look one page up for re-admission.  The
        caller uploads ``entry.k``/``entry.v`` (and scales) through its own
        donated pool write — the tier never touches a device.  Returns
        None on a miss (evicted, or a ``tier_drop`` injection discarded
        it): the caller MUST fall back to ordinary prefill, never hang.

        ``keep`` defaults to ``self.shared``: a private tier removes the
        entry (move semantics — the exactly-one-home half of invariant
        I10), a shared tier keeps it resident so every other replica can
        still re-admit the same chain."""
        e = self._by_hash.get(hash_)
        if e is None:
            return None
        self.readmits += 1
        if (self.shared and e.owner is not None and owner is not None
                and e.owner != owner):
            self.cross_readmits += 1
        if keep is None:
            keep = self.shared
        if keep:
            self._touch(e)
        else:
            self._remove(e)
        return e

    # ---------------- pinning / invalidation ----------------

    def pin(self, hash_: str) -> bool:
        """Protect an entry from LRU eviction while an engine holds a
        match-to-restore plan over it (the chunked cursor paces restores
        by the step token budget, so a long plan's window spans many
        steps).  False on a miss."""
        e = self._by_hash.get(hash_)
        if e is None:
            return False
        e.pins += 1
        return True

    def unpin(self, hash_: str) -> None:
        e = self._by_hash.get(hash_)
        if e is not None and e.pins > 0:
            e.pins -= 1
            if e.pins == 0:
                # re-enter the LRU race at current recency
                self._touch(e)

    def discard(self, hash_: str) -> bool:
        """Force-drop an entry regardless of pins — the ``tier_drop``
        fault seam (a tier entry vanishing between match and ship_in) and
        the private-tier dedup when an engine re-computes a block fresh.
        True when something was removed."""
        e = self._by_hash.get(hash_)
        if e is None:
            return False
        self._remove(e)
        return True

    # ---------------- introspection ----------------

    def __contains__(self, hash_: str) -> bool:
        return hash_ in self._by_hash

    def __len__(self) -> int:
        return len(self._by_hash)

    def stats(self) -> dict:
        """Host-side counter snapshot (bench rung detail)."""
        return {
            "entries": len(self._by_hash),
            "used_bytes": int(self.used_bytes),
            "budget_bytes": int(self.budget_bytes),
            "demotions": int(self.demotions),
            "readmits": int(self.readmits),
            "cross_readmits": int(self.cross_readmits),
            "evictions": int(self.evictions),
            "drops": int(self.drops),
        }
