"""Speculative decoding: prompt-lookup n-gram drafting (ISSUE 4 tentpole).

Reference analog: the reference ships a full speculative-decoding op family
(``speculate_*`` / ``top_p_candidates`` in paddle/phi/ops/yaml) behind
PaddleNLP's speculative serving mode.  The cheapest production drafter is
DRAFT-MODEL-FREE prompt lookup (the reference's ``ngram_match`` op): most
serving traffic — summarization, extraction, code edit, RAG over retrieved
text — repeats long spans of its own context verbatim, so the best predictor
of the next K tokens is often the continuation of the last place the current
suffix already appeared in prompt + generated history.

Division of labor (docs/speculative.md):

* **Drafting is host-side numpy** (this module).  It needs the token history
  the device never stores as a sequence, it is O(context) per slot per step
  (microseconds next to a device forward), and keeping it off-device means
  the compiled verify step has ONE static shape ``[B, K+1]`` regardless of
  how many drafts each slot produced — per-slot raggedness rides in as a
  ``q_lens`` DATA vector, never as a shape.
* **Verification is one compiled device step** (`serving.py`
  ``_verify_impl_paged`` over `ops/pallas/paged_attention.
  paged_attention_verify`): the target model scores all K+1 tokens in a
  single forward — one weight stream from HBM for up to K+1 tokens instead
  of one per token, which is the whole speculative win in bandwidth-bound
  decode — and the acceptance rule runs in-graph (no host sync per token).

The drafter proposes, never decides: a wrong draft costs one wasted lane of
the verify forward, never a wrong token (the engine's acceptance rule emits
exactly the tokens the non-speculative engine would have).
"""

from __future__ import annotations

import numpy as np

__all__ = ["NGramDrafter"]


class NGramDrafter:
    """Prompt-lookup drafter: longest-suffix n-gram match over the request's
    own prompt + generated history.

    For n from ``max_ngram`` down to ``min_ngram``: take the context's last n
    tokens and look for the MOST RECENT earlier occurrence; on a hit, propose
    the up-to-``num_draft_tokens`` tokens that followed it.  No match at any
    n → empty proposal (the engine then runs its normal decode step — a miss
    must cost nothing).  Pure host-side numpy; stateless across calls, so
    preemption/resume needs no drafter bookkeeping.
    """

    def __init__(self, num_draft_tokens: int = 4, max_ngram: int = 3,
                 min_ngram: int = 1):
        assert num_draft_tokens >= 1, num_draft_tokens
        assert 1 <= min_ngram <= max_ngram, (min_ngram, max_ngram)
        self.num_draft_tokens = int(num_draft_tokens)
        self.max_ngram = int(max_ngram)
        self.min_ngram = int(min_ngram)

    def propose(self, context) -> np.ndarray:
        """Draft tokens continuing ``context`` (1-D int token ids).  Returns
        an int32 array of 0..num_draft_tokens proposals."""
        ids = np.asarray(context, np.int32).ravel()
        L = ids.size
        # windows over ids[:-1]: a match starting at i has its continuation
        # at i+n <= L-1, and the context's own trailing n-gram (start L-n)
        # can never match itself
        for n in range(min(self.max_ngram, L - 1), self.min_ngram - 1, -1):
            pat = ids[L - n:]
            win = np.lib.stride_tricks.sliding_window_view(ids[:-1], n)
            hits = np.nonzero((win == pat).all(axis=1))[0]
            if hits.size:
                start = int(hits[-1]) + n      # most recent occurrence wins
                return ids[start:start + self.num_draft_tokens].copy()
        return np.zeros(0, np.int32)
