"""Fleet serving: health-checked prefix-affinity router over N engine
replicas (ISSUE 9 tentpole; docs/fleet_serving.md; ROADMAP item 2).

Millions of users means N :class:`~paddle_tpu.inference.serving.
ContinuousBatchingEngine` replicas behind a router, not one engine — and at
fleet scale the dominant failure mode is no longer a poisoned request
(PR 6's per-request isolation handles that inside one engine) but a whole
replica dying, stalling, or going slow.  The :class:`FleetRouter` is a
deterministic in-process fleet: one host control plane fronting N replicas
(each of which may itself be tensor-parallel, docs/tp_serving.md), built on
two primitives earlier PRs already shipped:

* the prefix cache's **hash-chained block ids** (PR 2) are a *global*
  content address — the same prompt hashes to the same chain on every
  replica, so "which replica holds this prefix" is a pure host-side lookup
  (`PrefixCache.match`, side-effect free);
* the snapshot **journal** (PR 6) resumes accepted work by teacher-forced
  recompute, token-identically for greedy AND seeded sampling — so losing a
  replica's KV pool loses *bytes*, never *streams*.

Three pillars:

**1. Cache-aware routing.**  An incoming prompt routes to the replica
holding the longest cached chain of its blocks (prefix affinity — reusing
resident KV beats rebalancing load), spilling to the least-loaded replica
when nothing matches.  Health gates affinity: a DEGRADED replica is chosen
only when no HEALTHY one can take the work (latency protection outranks a
warm cache).  Fleet admission layers on each engine's ``max_queue``: a
replica whose queue is full is not routable, and when EVERY routable
replica is full the fleet itself sheds the request as REJECTED
(``stats["fleet_rejected"]``) — backpressure composes, it does not hide.

**2. Replica health + failover.**  Replicas walk ``HEALTHY → DEGRADED →
DRAINING → DEAD``, driven by per-step heartbeats and surfaced engine
faults:

* a ``replica_slow`` streak (elevated step latency) degrades; a clean
  streak heals back to HEALTHY;
* ``drain(r)`` marks DRAINING: the replica accepts no new work but keeps
  stepping until its in-flight requests finish (rolling restart / scale-in
  primitive);
* a replica that makes **no progress** for ``stall_steps`` fleet steps
  while holding live work is stalled: the router hedge-dispatches its
  in-flight requests onto survivors (journal replay), keeping the primary
  as owner until **first-writer-wins** resolves — whichever copy first
  extends a request's stream becomes the owner and the loser is cancelled,
  so a stalled replica's late answer is discarded, never double-banked;
* a DEAD replica (``replica_crash`` injection, or an engine fault that
  escapes ``step()`` — only a persistent kernel failure can) triggers
  **failover**: the router replays the replica's journal — accepted
  prompts, emitted tokens, prefill cursors, maintained incrementally via
  ``snapshot()`` after every step — onto survivors through
  ``engine.adopt()``'s teacher-forced recompute.  Every replayed request's
  completed output stream is token-identical (greedy and seeded) to an
  uninterrupted fleet's, because each stream depends only on its own
  ``(seed, position)`` keys and its own tokens — never on which replica
  computed it.  Replayed/hedged work is EXEMPT from backpressure (accepted
  work is never rejected) and deadlines re-arm with the journaled
  REMAINING budget only.

**3. Fleet chaos.**  The same ``PADDLE_TPU_FAULT_INJECT`` grammar grows
replica-scoped clauses (faults.REPLICA_KINDS): ``replica_crash`` /
``replica_stall`` / ``replica_slow`` ``@ step/replica/count/p+seed``,
polled once per replica per fleet step in replica-index order — a
randomized fleet chaos run is exactly replayable from its env string.
Engine-scoped kinds inside a fleet spec fan out to every replica's own
injector (scope one with ``replica=k``); a replica-scoped clause with NO
fleet running is rejected by the engine's parse (warn once, injection
disabled) instead of being a silent no-op.

With ``enable_host_kv_tier=True`` (ISSUE 13, docs/kv_tier.md) the fleet
shares ONE :class:`~paddle_tpu.inference.kv_tier.HostKVTier` across its
replicas — the fleet-wide prefix store.  Chain hashes are already the
routing key, so a chain any replica computed and demoted is re-admittable
by every other replica: affinity misses stop being full prefills, and
failover replay restores the dead replica's demoted chains page-by-page
through the ordinary tier-extended admission (O(pages shipped) for the
covered prefix; only the uncovered tail is teacher-forced).

Non-goals (docs/fleet_serving.md): the router does not move *live* KV
bytes between replicas (failover replays the journal; the shared host
tier moves only content-addressed finished pages), does not rebalance
running work (only failure moves it), and trusts one process's clock (it
is an in-process fleet — the distributed-systems problems it models are
scheduling ones, not Byzantine ones).

Audited invariant **I9** (``PADDLE_TPU_ENGINE_AUDIT=1``,
analysis/engine_audit.audit_fleet): every live rid is owned by exactly one
replica — a hedge-pending rid counts as the primary's until
first-writer-wins resolves — and no replica serves a rid the router does
not route to it.
"""

from __future__ import annotations

import os
import time

import numpy as np

from ..profiler import RecordEvent
from .faults import KNOWN_KEYS, KNOWN_KINDS, REPLICA_KINDS, FaultPlan
from .observability import (FLEET_STAT_SCHEMA, FlightRecorder,
                            MetricsRegistry, RequestTracer, SLOTracker,
                            StatsView, flight_recorder_enabled,
                            metrics_enabled)
from .serving import (TERMINAL_STATUSES, ContinuousBatchingEngine, Request,
                      journal_entry)

__all__ = ["FleetRouter", "REPLICA_STATES", "HEALTH_EDGES"]

#: replica health states, in degradation order (docs/fleet_serving.md)
REPLICA_STATES = ("HEALTHY", "DEGRADED", "DRAINING", "DEAD")

#: declared replica-health transition table, verified exhaustively against
#: every ``self.health[...]`` write site by the host-contract pass
#: (analysis/host_contracts.py; docs/analysis.md §"Host contracts").
#: Transitions move strictly down the degradation ladder except the single
#: declared heal edge DEGRADED->HEALTHY (_note_heartbeat after heal_after
#: clean beats); DEAD is absorbing.  DRAINING->DEAD covers killing a
#: replica mid-drain; HEALTHY/DEGRADED->DEAD is a hard _kill.
HEALTH_EDGES = frozenset({
    ("HEALTHY", "DEGRADED"), ("DEGRADED", "HEALTHY"),
    ("HEALTHY", "DRAINING"), ("DEGRADED", "DRAINING"),
    ("HEALTHY", "DEAD"), ("DEGRADED", "DEAD"), ("DRAINING", "DEAD"),
})


class FleetRouter:
    """Deterministic in-process fleet of ``n_replicas`` continuous-batching
    engines behind one cache-aware, health-checked router (module
    docstring; docs/fleet_serving.md).

    ``engine_kw`` passes through to every
    :class:`~paddle_tpu.inference.serving.ContinuousBatchingEngine`
    (replicas are homogeneous — heterogeneous fleets would break the
    token-identity failover contract only via *model* differences, which
    ``snapshot()``'s topology check already polices, but homogeneity keeps
    load comparable too).  ``params`` is shared by reference across
    replicas: JAX arrays are immutable and the engines donate only their
    own KV pools, so N replicas cost N pools + one weight set.

    ``stall_steps``: fleet steps without progress (while holding live
    work) before a replica counts as stalled and its in-flight requests
    hedge onto survivors; at ``stall_dead_steps`` the stall is declared
    crash-equivalent and the replica DEAD (so un-hedgeable work fails
    with a diagnosis instead of hanging the serve loop).  ``slow_after``
    / ``heal_after``: consecutive slow / clean heartbeats before
    DEGRADED / back to HEALTHY.

    Requires graceful mode (``PADDLE_TPU_GRACEFUL=1``, the default): the
    failover and hedge paths are built on the status lifecycle,
    ``cancel()``, and per-request isolation that the graceful-off engine
    predates."""

    def __init__(self, cfg, params, n_replicas: int = 2, *,
                 stall_steps: int = 3, stall_dead_steps: int = 12,
                 slow_after: int = 2, heal_after: int = 2, **engine_kw):
        if int(n_replicas) < 1:
            raise ValueError(f"n_replicas must be >= 1, got {n_replicas}")
        self.n_replicas = int(n_replicas)
        self.stall_steps = int(stall_steps)
        self.stall_dead_steps = int(stall_dead_steps)
        if self.stall_dead_steps <= self.stall_steps:
            raise ValueError(
                f"stall_dead_steps ({stall_dead_steps}) must exceed "
                f"stall_steps ({stall_steps}): hedging must get a chance "
                f"before the replica is declared dead")
        self.slow_after = int(slow_after)
        self.heal_after = int(heal_after)
        # observability (ISSUE 11, docs/observability.md): ONE shared
        # registry — every replica's engine registers the same metric
        # families with a {"replica": k} label set, so metrics.expose()
        # is the whole fleet's Prometheus snapshot; the fleet's own
        # stats/SLO/flight tiers layer on top with fleet-prefixed names.
        self._metrics_on = metrics_enabled()
        self.metrics = engine_kw.pop("metrics", None)
        if self.metrics is None and self._metrics_on:
            self.metrics = MetricsRegistry()
        # metrics-off: self.metrics stays None (absent evidence must read
        # as absent — bench embeds null, never an empty exposition).
        # The router owns the replica label — a caller-provided label set
        # would collapse N replicas onto one labelled series.
        engine_kw.pop("metrics_labels", None)
        # hierarchical KV (ISSUE 13, docs/kv_tier.md): the fleet shares
        # ONE host tier across its replicas — chain hashes are already
        # the routing key, so a chain ANY replica computed and demoted is
        # re-admittable by every other replica (affinity misses stop
        # being full prefills, and adopt() failover restores the dead
        # replica's demoted chains in O(pages shipped) instead of
        # teacher-forced recompute).  shared=True switches ship_in to
        # keep-resident semantics and relaxes the I10 exclusivity check
        # to per-replica accounting (content-addressed duplicates across
        # replicas are byte-identical by construction).
        from ..utils.envflags import env_bool as _env_bool

        self.host_tier = engine_kw.pop("host_tier", None)
        if not _env_bool("PADDLE_TPU_HOST_KV_TIER", True):
            # the kill switch neutralizes the fleet tier TOTALLY — even an
            # explicitly-passed tier object is dropped (and left
            # unmutated), so `router.host_tier is None` is a truthful
            # "tier off" signal and the bench detail never presents a
            # live-but-idle store in a kill-switched run (the engines
            # would each disable it anyway)
            self.host_tier = None
        elif self.host_tier is not None:
            self.host_tier.shared = True
        elif engine_kw.get("enable_host_kv_tier"):
            from .kv_tier import HostKVTier

            self.host_tier = HostKVTier(shared=True)
        if self.host_tier is not None:
            engine_kw["host_tier"] = self.host_tier
        # the engines must NOT parse a fleet chaos spec themselves: a
        # replica-scoped clause would (correctly) disable their whole plan
        # with a warning.  The router parses once with the full vocabulary
        # and installs each replica's engine-scoped share below.
        spec = os.environ.pop("PADDLE_TPU_FAULT_INJECT", None)
        try:
            self.replicas: list[ContinuousBatchingEngine | None] = [
                ContinuousBatchingEngine(cfg, params, metrics=self.metrics,
                                         metrics_labels={"replica": str(r)},
                                         **engine_kw)
                for r in range(self.n_replicas)]
        finally:
            if spec is not None:
                os.environ["PADDLE_TPU_FAULT_INJECT"] = spec
        if not self.replicas[0]._graceful:
            raise RuntimeError(
                "FleetRouter requires PADDLE_TPU_GRACEFUL=1: failover, "
                "hedging and draining are built on the graceful engine's "
                "status lifecycle and cancel()")
        self.health: list[str] = ["HEALTHY"] * self.n_replicas
        # fleet-level request registry: rid -> caller's Request, LIVE only
        # (terminal requests are pruned, mirroring the engine's journal)
        self._reqs: dict[int, Request] = {}
        # rid -> owning replica index (I9: exactly one owner per live rid)
        self._owner: dict[int, int] = {}
        # rid -> {replica index: replica-local Request copy}; owner always
        # holds one, a hedge-pending rid holds a second on the hedge target
        self._copies: dict[int, dict[int, Request]] = {}
        # rid -> hedge replica (first-writer-wins pending); ownership stays
        # with the primary until a copy extends the stream
        self._hedge: dict[int, int] = {}
        # per-replica journal: the last snapshot(), refreshed after every
        # completed step AND every dispatch — on death this is at most zero
        # completed steps stale, so replay loses nothing the fleet had
        # mirrored
        self._journal: list[dict | None] = [None] * self.n_replicas
        # async host runtime (docs/async_runtime.md): with the flag on the
        # replicas maintain their journals incrementally (O(changed rids),
        # flushed inside each engine's host-overlap window) and the router
        # pulls them ONLY at the boundaries that consume them — replica
        # death and stall hedging (_journal_pull) — instead of paying a
        # full snapshot() rebuild per step and per dispatch.  Off, the
        # historical per-step/per-dispatch snapshot() refreshes run
        # byte-identically.
        self._async_host = _env_bool("PADDLE_TPU_ASYNC_HOST", True)
        self._last_progress = [0] * self.n_replicas
        self._slow_streak = [0] * self.n_replicas
        self._ok_streak = [0] * self.n_replicas
        self._step_no = 0          # fleet step counter (replica-clause key)
        # fleet stats on the shared registry behind the same dict view the
        # engines use (keys + help: observability.FLEET_STAT_SCHEMA);
        # PADDLE_TPU_METRICS=0 restores the plain pre-observability dict.
        # The fleet SLO tracker is the authority the chaos bench's
        # goodput-at-SLO headline now reads from (fed in _mirror with the
        # SAME timestamps that set each request's ttft_s).
        if self._metrics_on:
            self.stats = StatsView(self.metrics, FLEET_STAT_SCHEMA,
                                   prefix="paddle_tpu_fleet")
            self.slo = SLOTracker(self.metrics, prefix="paddle_tpu_fleet")
            self._h_jupdate = self.metrics.histogram(
                "paddle_tpu_fleet_journal_update_seconds",
                "Host seconds per router journal refresh: async-on, one "
                "incremental pull per consumption boundary (failover/"
                "hedge); async-off, one full snapshot() rebuild per step "
                "and per dispatch — the critical-path journal tax "
                "(docs/async_runtime.md)"
            ).labels()
        else:
            self.stats = {k: 0 for k in FLEET_STAT_SCHEMA}
            self.slo = None
            self._h_jupdate = None
        # one flow-link tracer per replica lane (the engines' own tracers
        # already own the span traffic on those pids; the router only adds
        # the cross-replica failover/hedge arrows and health markers)
        self._tracers = [RequestTracer(enabled=self._metrics_on, pid=r)
                         for r in range(self.n_replicas)]
        self._flow_seq = 0
        self._flight = (FlightRecorder(registry=(self.metrics
                                                 if self._metrics_on
                                                 else None), name="fleet")
                        if flight_recorder_enabled() else None)
        self._faults = FaultPlan()
        self._arm_faults_from_env()
        from ..analysis.engine_audit import audit_enabled

        self._audit_every_step = audit_enabled()

    # ---------------- chaos plumbing ----------------

    def _arm_faults_from_env(self) -> None:
        """Parse ``PADDLE_TPU_FAULT_INJECT`` with the full fleet vocabulary
        and partition it: replica-scoped clauses arm the router's own plan,
        engine-scoped clauses fan out to each replica's injector — a clause
        carrying ``replica=k`` arms only replica k's engine, one without it
        arms every replica (each with its own independent clause state, so
        counts and seeded streams stay per-replica deterministic)."""
        from ..utils.envflags import env_fault_spec

        clauses = env_fault_spec("PADDLE_TPU_FAULT_INJECT",
                                 KNOWN_KINDS | REPLICA_KINDS,
                                 KNOWN_KEYS | {"replica"})
        self._faults = FaultPlan(
            [c for c in clauses if c["kind"] in REPLICA_KINDS])
        eng_clauses = [c for c in clauses if c["kind"] not in REPLICA_KINDS]
        for r, eng in enumerate(self.replicas):
            if eng is None:
                continue
            mine = []
            for c in eng_clauses:
                if c.get("replica") not in (None, r):
                    continue
                c2 = dict(c)
                # the engine polls never pass a replica key: strip the
                # scope so the clause matches its chosen engine's seams
                c2.pop("replica", None)
                mine.append(c2)
            eng._faults = FaultPlan(mine)

    # ---------------- routing (pillar 1) ----------------

    def _load(self, r: int) -> int:
        """Live accepted requests (running + queued) on replica ``r``."""
        return len(self.replicas[r]._reqs)

    def _full(self, r: int) -> bool:
        eng = self.replicas[r]
        return (eng.max_queue is not None
                and len(eng._queue) >= eng.max_queue)

    def _match_len(self, r: int, ids: np.ndarray) -> int:
        """Cached-chain length (full blocks) replica ``r`` holds for this
        stream — the global content address the router keys on.  Pure
        lookup: ``match`` touches no refcounts."""
        pc = self.replicas[r]._pcache
        return len(pc.match(ids)) if pc is not None else 0

    def _route(self, ids: np.ndarray, exclude=frozenset(),
               accepted: bool = False) -> tuple[int | None, int]:
        """Pick the target replica for a stream: HEALTHY before DEGRADED,
        then longest cached chain, then least-loaded, then lowest index
        (fully deterministic).  ``accepted=True`` (failover replay /
        hedging) lifts the queue-full filter — accepted work is never
        rejected — and falls back to a DRAINING replica when nothing else
        survives, because dropping accepted work is strictly worse than
        delaying a drain.  Returns (replica | None, match_len)."""
        alive = [r for r in range(self.n_replicas)
                 if self.replicas[r] is not None and r not in exclude]
        cands = [r for r in alive if self.health[r] in ("HEALTHY",
                                                        "DEGRADED")]
        if not accepted:
            cands = [r for r in cands if not self._full(r)]
        elif not cands:
            cands = [r for r in alive if self.health[r] == "DRAINING"]
        if not cands:
            return None, 0
        match = {r: self._match_len(r, ids) for r in cands}
        best = min(cands, key=lambda r: (
            0 if self.health[r] == "HEALTHY" else 1,
            -match[r], self._load(r), r))
        return best, match[best]

    def _reject(self, req: Request, msg: str) -> None:
        with RecordEvent("fleet/rejected"):
            req.status = "REJECTED"
            req.finished = True
            req.error = msg
            self.stats["fleet_rejected"] += 1
            if self.slo is not None:
                self.slo.finish(req.rid, "REJECTED", time.perf_counter())
            if self._flight is not None:
                self._flight.record("terminal", rid=req.rid,
                                    status="REJECTED", error=msg)

    @staticmethod
    def _copy_req(req: Request) -> Request:
        """Replica-local twin of a fleet request.  Same rid and sampling
        params, so the engine's default ``seed = rid`` and its
        ``(seed, position)`` keys derive the SAME stream on every replica —
        the property that makes hedging and failover token-identical by
        construction."""
        return Request(
            rid=req.rid,
            prompt_ids=np.asarray(req.prompt_ids, np.int32).ravel(),
            max_new_tokens=req.max_new_tokens,
            eos_token_id=req.eos_token_id,
            temperature=req.temperature, top_p=req.top_p, seed=req.seed,
            deadline_s=req.deadline_s, trace_id=req.trace_id)

    def add_request(self, req: Request) -> None:
        """Route one request into the fleet (or shed it as REJECTED when
        no routable replica can take it — fleet-level backpressure)."""
        if req.rid in self._reqs:
            raise ValueError(f"request {req.rid}: rid already live in the "
                             f"fleet")
        req._submit_s = time.perf_counter()
        if req.trace_id is None:
            req.trace_id = f"req-{req.rid:x}"
        if self.slo is not None:
            self.slo.begin(req.rid, req._submit_s)
        probe = next((e for e in self.replicas if e is not None), None)
        if probe is None:
            self._reject(req, "every replica is DEAD (fleet lost)")
            return
        try:
            probe._validate(req)
        except ValueError as e:
            # the graceful-serve contract, fleet edition: one bad request
            # must not raise out of the router
            self._reject(req, str(e))
            return
        ids = np.asarray(req.prompt_ids, np.int32).ravel()
        target, m = self._route(ids)
        if target is None:
            # name the ACTUAL cause: an operator who drained the whole
            # fleet must not be sent debugging max_queue backpressure
            routable = [r for r in range(self.n_replicas)
                        if self.replicas[r] is not None
                        and self.health[r] in ("HEALTHY", "DEGRADED")]
            if routable:
                msg = ("fleet backpressure: every routable replica's "
                       "queue is full")
            else:
                n_drain = self.health.count("DRAINING")
                n_dead = self.health.count("DEAD")
                msg = (f"no routable replica: {n_drain} DRAINING, "
                       f"{n_dead} DEAD of {self.n_replicas} (draining "
                       f"replicas accept no new work)")
            self._reject(req, msg)
            return
        self.stats["routed_affinity" if m > 0 else "routed_spill"] += 1
        if self._flight is not None:
            self._flight.record("route", rid=req.rid, replica=target,
                                match_blocks=int(m))
        copy = self._copy_req(req)
        self.replicas[target].add_request(copy)
        if copy.status == "REJECTED":       # defensive: _route pre-filtered
            self._reject(req, copy.error or "replica rejected the request")
            return
        self._reqs[req.rid] = req
        self._owner[req.rid] = target
        self._copies[req.rid] = {target: copy}
        # keep the journal current through dispatch, not just steps: a
        # crash before the replica's next step must still replay this.
        # Async host runtime: the replica's incremental journal already
        # tracks the dispatch (add_request _jmarks the rid) and the
        # router pulls it at the death/stall boundary instead — no full
        # rebuild on the dispatch path.
        if not self._async_host:
            t0 = time.perf_counter()
            self.stats["journal_full_rebuilds"] += 1
            self._journal[target] = self.replicas[target].snapshot()
            if self._h_jupdate is not None:
                self._h_jupdate.observe(time.perf_counter() - t0)

    def cancel(self, rid: int) -> bool:
        """Fleet-level cancel: every replica copy (owner and any pending
        hedge) cancels, the fleet request goes terminal CANCELLED with its
        partial output.  False when the rid is unknown or already
        terminal."""
        f = self._reqs.get(rid)
        if f is None:
            return False
        for rr, cc in self._copies.pop(rid, {}).items():
            eng = self.replicas[rr]
            if eng is not None and not cc.finished:
                eng.cancel(rid)
        self._owner.pop(rid, None)
        self._hedge.pop(rid, None)
        self._reqs.pop(rid, None)
        f.status = "CANCELLED"
        f.finished = True
        f.error = "cancelled by caller"
        if self.slo is not None:
            self.slo.finish(rid, "CANCELLED", time.perf_counter())
        return True

    # ---------------- health + failover (pillar 2) ----------------

    def drain(self, replica: int) -> None:
        """Mark a replica DRAINING: it accepts no new work (routing skips
        it; only a last-resort failover replay may still land) but keeps
        stepping until its in-flight requests finish — the rolling-restart
        / scale-in primitive."""
        if self.replicas[replica] is None or self.health[replica] == "DEAD":
            raise ValueError(f"replica {replica} is DEAD")
        self._health_to(replica, "DRAINING", "drain() by operator")

    def _has_live(self, r: int) -> bool:
        eng = self.replicas[r]
        return eng is not None and bool(eng._reqs)

    def _health_to(self, r: int, state: str, why: str) -> None:
        """Single choke point for health transitions, so every one lands
        in the flight recorder and on the replica's trace lane."""
        prev = self.health[r]
        if prev == state:
            return
        self.health[r] = state
        now = time.perf_counter()
        if self._flight is not None:
            self._flight.record("health", replica=r, frm=prev, to=state,
                                why=why)
        self._tracers[r].instant(0, f"health:{state}", now,
                                 args={"replica": r, "from": prev,
                                       "why": why})

    def _note_heartbeat(self, r: int, ok: bool) -> None:
        """Latency-heartbeat bookkeeping: a slow/stalled step degrades
        after ``slow_after`` in a row, a clean streak of ``heal_after``
        heals a DEGRADED replica (DRAINING and DEAD never heal — one is an
        operator decision, the other is terminal)."""
        if ok:
            self._ok_streak[r] += 1
            self._slow_streak[r] = 0
            if (self.health[r] == "DEGRADED"
                    and self._ok_streak[r] >= self.heal_after):
                self._health_to(r, "HEALTHY",
                                f"{self._ok_streak[r]} clean heartbeats")
        else:
            self._slow_streak[r] += 1
            self._ok_streak[r] = 0
            if (self.health[r] == "HEALTHY"
                    and self._slow_streak[r] >= self.slow_after):
                self._health_to(r, "DEGRADED",
                                f"{self._slow_streak[r]} slow/stalled "
                                f"heartbeats")

    def _journal_pull(self, r: int) -> None:
        """Async host runtime: pull replica ``r``'s incrementally-
        maintained journal — the O(changed rids) replacement for the
        per-step/per-dispatch ``snapshot()`` rebuilds, taken only at the
        boundaries that actually consume it (replica death, stall
        hedging; docs/async_runtime.md)."""
        eng = self.replicas[r]
        if eng is None:
            return
        t0 = time.perf_counter()
        self._journal[r] = (eng.journal() if eng._reqs
                            else {"running": [], "queued": []})
        self.stats["journal_incremental_updates"] += 1
        if self._h_jupdate is not None:
            self._h_jupdate.observe(time.perf_counter() - t0)
        if self._flight is not None:
            self._flight.record("journal_pull", replica=r)

    def _audit_journal_equiv(self, r: int) -> None:
        """Under PADDLE_TPU_ENGINE_AUDIT=1: assert replica ``r``'s
        incremental journal and a freshly-built ``snapshot()`` agree —
        the equivalence contract failover replay rides on once the
        router stops rebuilding snapshots itself.
        ``deadline_remaining_s`` is normalized out: both sides lazily
        recompute it from ``time.perf_counter()`` at their own read
        instants, so it legitimately differs by the nanoseconds between
        the two calls."""
        eng = self.replicas[r]
        if eng is None or not eng._reqs:
            return

        def _norm(d: dict) -> dict:
            return {**d,
                    "running": [dict(e, deadline_remaining_s=None)
                                for e in d["running"]],
                    "queued": [dict(e, deadline_remaining_s=None)
                               for e in d["queued"]]}

        j, s = _norm(eng.journal()), _norm(eng.snapshot())
        if j != s:
            from ..analysis.engine_audit import EngineAuditError

            if self._flight is not None:
                self._flight.dump(f"journal_divergence replica={r}")
            raise EngineAuditError(
                f"incremental journal diverged from snapshot() on "
                f"replica {r} (async host runtime): "
                f"journal={j!r} snapshot={s!r}")

    def _journal_entry(self, r: int, rid: int) -> dict:
        """The journal entry to replay for ``rid`` of replica ``r``: the
        incrementally-maintained snapshot's, falling back to synthesizing
        one from the fleet-mirrored request via the SAME
        ``serving.journal_entry`` schema the snapshot uses (equivalent
        content minus the prefill-cursor provenance — the journal
        refreshes after every step and dispatch, and the mirror runs
        first)."""
        j = self._journal[r] or {}
        for e in j.get("running", []) + j.get("queued", []):
            if e["rid"] == rid:
                return e
        return journal_entry(self._reqs[rid])

    def _replay(self, rid: int, entry: dict, exclude: set,
                source: int | None = None,
                link: str = "failover") -> int | None:
        """Adopt one journal entry onto the best survivor (affinity over
        the full prompt+generated stream, since retired generated blocks
        are content-addressed too).  Returns the target replica or None
        when nothing survives.  ``source`` (the dead/stalled replica)
        draws the cross-replica trace link: a chrome flow arrow from the
        source's lane to the adopting replica's, so a failover/hedge reads
        as one continuous request line across the fleet timeline."""
        ids = np.asarray(list(entry["prompt_ids"])
                         + list(entry["output_ids"]), np.int32)
        target, _ = self._route(ids, exclude=exclude, accepted=True)
        if target is None:
            return None
        copy = self.replicas[target].adopt(entry)
        self._copies.setdefault(rid, {})[target] = copy
        self.stats["replayed_tokens"] += len(entry["output_ids"])
        if source is not None:
            now = time.perf_counter()
            self._flow_seq += 1
            fid = f"{link}-{rid}-{self._flow_seq}"
            self._tracers[source].flow_out(rid, link, now, fid)
            self._tracers[target].flow_in(rid, link, now + 1e-6, fid)
        if self._flight is not None:
            self._flight.record(link, rid=rid, frm=source, to=target,
                                replayed_tokens=len(entry["output_ids"]))
        return target

    def _kill(self, r: int, reason: str) -> None:
        """Replica death: mark DEAD, drop the engine, and replay its
        journal onto survivors.  A rid with a pending hedge needs no
        replay — its hedge twin already carries the stream and inherits
        ownership; a rid hedged ONTO the dead replica just loses the
        hedge.  With no survivors at all, the affected requests terminate
        FAILED (the fleet is lost; accepted work cannot outlive every
        replica)."""
        with RecordEvent("fleet/failover"):
            dead_eng = self.replicas[r]   # for the flight-recorder dump
            if self._async_host:
                # the death boundary IS the async runtime's journal
                # consumption point: pull the incremental journal while
                # the engine object is still here, then replay from it
                self._journal_pull(r)
            self._health_to(r, "DEAD", reason)
            self.replicas[r] = None
            self.stats["failovers"] += 1
            for rid, h in list(self._hedge.items()):
                if h == r:                  # hedge twin died: drop it
                    del self._hedge[rid]
                    self._copies.get(rid, {}).pop(r, None)
            for rid in [rid for rid, o in list(self._owner.items())
                        if o == r]:
                self._copies.get(rid, {}).pop(r, None)
                h = self._hedge.pop(rid, None)
                if h is not None:
                    # first-writer-wins resolves by default: the survivor
                    # is the only writer left
                    self._owner[rid] = h
                    continue
                entry = self._journal_entry(r, rid)
                target = self._replay(rid, entry, exclude={r}, source=r)
                if target is None:
                    f = self._reqs.pop(rid)
                    self._owner.pop(rid, None)
                    self._copies.pop(rid, None)
                    f.status = "FAILED"
                    f.finished = True
                    f.error = (f"replica {r} died ({reason}) with no "
                               f"surviving replica to replay onto")
                    if self.slo is not None:
                        self.slo.finish(rid, "FAILED",
                                        time.perf_counter())
                    continue
                self._owner[rid] = target
            # replica death is a flight-recorder dump trigger: the
            # router's recent events + the DEAD replica's own ring + a
            # fleet metrics snapshot, so chaos triage reads what the
            # engine was doing when it died without a rerun
            if self._flight is not None:
                self._flight.dump(
                    f"replica {r} DEAD: {reason}",
                    extra={"replica": r,
                           "engine_events": (
                               dead_eng._flight.events()
                               if dead_eng is not None
                               and dead_eng._flight is not None
                               else None)})
            # every live entry is replayed: holding the dead replica's
            # final snapshot past this point would retain its requests'
            # full token lists for the router's lifetime (the retention
            # class PR 6 fixed in the engine's rid journal)
            self._journal[r] = None

    def _detect_stalls(self) -> None:
        """Heartbeat-gap stall detection: a replica holding live work that
        has not completed a step for ``stall_steps`` fleet steps gets its
        in-flight requests hedge-dispatched onto survivors.  The primary
        stays the owner (I9) until first-writer-wins resolves in
        ``_mirror``.  A stall that persists to ``stall_dead_steps`` is
        crash-equivalent: the replica is declared DEAD (``_kill``), so its
        un-hedgeable work — a one-replica fleet, or every survivor already
        gone — terminates FAILED with a diagnosis instead of spinning
        ``serve()`` forever (the never-a-hang contract; deadlines cannot
        save it either, since expiry runs inside the engine step the
        stalled replica never executes)."""
        for r in range(self.n_replicas):
            if (self.replicas[r] is None or not self._has_live(r)):
                continue
            gap = self._step_no - self._last_progress[r]
            if gap < self.stall_steps:
                continue
            if gap >= self.stall_dead_steps:
                self._kill(r, f"stalled for {gap} fleet steps "
                              f"(stall_dead_steps={self.stall_dead_steps})")
                continue
            if self.health[r] == "HEALTHY":
                self._health_to(r, "DEGRADED",
                                f"no progress for {gap} fleet steps")
            if self._async_host:
                # hedge boundary: refresh the stalled replica's journal
                # from its incremental entries before replaying them
                # (the stalled engine's host side is still reachable —
                # it is the device step that is not completing)
                self._journal_pull(r)
            for rid in [rid for rid, o in self._owner.items() if o == r]:
                if rid in self._hedge:
                    continue               # already hedge-pending
                with RecordEvent("fleet/hedge"):
                    entry = self._journal_entry(r, rid)
                    target = self._replay(rid, entry, exclude={r},
                                          source=r, link="hedge")
                    if target is None:
                        continue           # nobody to hedge onto: wait
                    self._hedge[rid] = target
                    self.stats["hedges"] += 1

    def _resolve_hedge(self, rid: int, winner: int) -> None:
        """First-writer-wins: ``winner`` extended the stream first and
        becomes the owner; the loser's copy is cancelled (its late answer
        — token-identical anyway, by the determinism contract — is
        discarded, its pages free)."""
        h = self._hedge.pop(rid)
        owner = self._owner[rid]
        loser = owner if winner == h else h
        self._owner[rid] = winner
        cc = self._copies.get(rid, {}).pop(loser, None)
        eng = self.replicas[loser]
        if cc is not None and eng is not None and not cc.finished:
            eng.cancel(rid)

    def _promote(self, rid: int, new_owner: int) -> None:
        """The primary terminated on its own (e.g. its engine failed the
        copy) while a hedge twin is mid-replay: promote the twin instead
        of failing the fleet request."""
        old = self._owner[rid]
        self._copies.get(rid, {}).pop(old, None)
        self._owner[rid] = new_owner

    def _finish(self, rid: int, copy: Request) -> None:
        """Mirror a terminal replica copy onto the fleet request and prune
        every live registry (I9: terminal means gone from the routing
        plane).  Any other copy still live (an unresolved hedge twin) is
        cancelled."""
        f = self._reqs.pop(rid)
        self._hedge.pop(rid, None)
        self._owner.pop(rid, None)
        for rr, cc in self._copies.pop(rid, {}).items():
            if cc is copy:
                continue
            eng = self.replicas[rr]
            if eng is not None and not cc.finished:
                eng.cancel(rid)
        f.status = copy.status
        f.finished = True
        f.error = copy.error
        if self.slo is not None:
            self.slo.finish(rid, copy.status, time.perf_counter())

    def _mirror(self, r: int) -> None:
        """After replica ``r`` steps: bank its copies' new tokens onto the
        fleet requests (resolving first-writer-wins for hedge-pending
        rids) and mirror terminal transitions."""
        for rid in [rid for rid in list(self._reqs)
                    if self._owner.get(rid) == r
                    or self._hedge.get(rid) == r]:
            f = self._reqs.get(rid)
            c = self._copies.get(rid, {}).get(r)
            if f is None or c is None:
                continue
            if len(c.output_ids) > len(f.output_ids):
                if rid in self._hedge:
                    self._resolve_hedge(rid, winner=r)
                delta = len(c.output_ids) - len(f.output_ids)
                f.output_ids.extend(c.output_ids[len(f.output_ids):])
                now = time.perf_counter()
                if f.ttft_s is None:
                    # fleet-level TTFT: includes routing + queueing +
                    # (on failover) replay recompute — the number an SLO
                    # is written against
                    f.ttft_s = now - f._submit_s
                if self.slo is not None:
                    # the SAME `now` that stamps ttft_s: the SLO tracker's
                    # records are exactly the figures the caller observes
                    self.slo.tokens(rid, delta, now)
            if self._owner.get(rid) != r:
                # hedge twin that has not won: a self-inflicted terminal
                # (failed/expired on the hedge target) just drops the hedge
                if c.status in TERMINAL_STATUSES:
                    self._hedge.pop(rid, None)
                    self._copies.get(rid, {}).pop(r, None)
                continue
            if c.status in TERMINAL_STATUSES and c.status != "CANCELLED":
                if c.status != "FINISHED" and rid in self._hedge:
                    self._promote(rid, self._hedge.pop(rid))
                else:
                    self._finish(rid, c)
            elif not c.finished:
                f.status = c.status        # PENDING/RUNNING observability

    def step(self) -> bool:
        """One fleet round: poll replica-scoped chaos, step every live
        replica once (replica-index order — the deterministic clock every
        clause keys on), mirror outputs, refresh journals, advance health,
        and hedge stalled work.  Returns False when the whole fleet is
        idle."""
        self._step_no += 1
        busy = False
        stepped_any = False    # any live replica stepped (overlap counter)
        for r in range(self.n_replicas):
            if self.replicas[r] is None:
                continue
            if self._faults and self._faults.fire(
                    "replica_crash", step=self._step_no, replica=r):
                self._kill(r, f"injected replica_crash (fleet step "
                              f"{self._step_no})")
                busy = True
                continue
            stalled = bool(self._faults) and self._faults.fire(
                "replica_stall", step=self._step_no, replica=r)
            # a stalled step is already a missed heartbeat: polling the
            # slow clause too would burn its count on steps where it has
            # no distinct effect, silently skewing the spec's schedule
            slow = (not stalled and bool(self._faults)
                    and self._faults.fire("replica_slow",
                                          step=self._step_no, replica=r))
            if stalled:
                # the replica's step "hangs": no progress, no heartbeat,
                # no journal refresh — exactly what the router would see
                # from a wedged device
                self._note_heartbeat(r, ok=False)
                busy = busy or self._has_live(r)
                continue
            eng = self.replicas[r]
            try:
                stepped = eng.step()
            except Exception as e:
                # a fault that escapes the graceful engine's step() is a
                # replica-fatal condition (persistent kernel failure):
                # surface it as death, not a router crash
                self._kill(r, f"engine fault escaped step(): {e}")
                busy = True
                continue
            self._last_progress[r] = self._step_no
            self._note_heartbeat(r, ok=not slow)
            self._mirror(r)
            if self._async_host:
                # async host runtime: the replica flushed its dirty rids
                # inside its own host-overlap window; the router defers
                # consumption to the death/stall boundaries
                # (_journal_pull) — zero per-step rebuild cost here
                stepped_any = True
                if self._audit_every_step:
                    self._audit_journal_equiv(r)
            else:
                # journal refresh: O(live tokens) host work per replica
                # per step — bounded by max_batch x max_seq ints, small
                # next to a device step, and the price of a journal that
                # is never a completed step stale when its replica dies.
                # Idle replicas skip it (their journal is empty).
                # Timed into journal_update_seconds either way: with the
                # flag off this histogram IS the critical-path journal
                # tax per step the async runtime exists to remove (the
                # asynchost A/B reads its sum).
                if eng._reqs:
                    t0 = time.perf_counter()
                    self.stats["journal_full_rebuilds"] += 1
                    self._journal[r] = eng.snapshot()
                    if self._h_jupdate is not None:
                        self._h_jupdate.observe(time.perf_counter() - t0)
                else:
                    self._journal[r] = {"running": [], "queued": []}
            busy = busy or stepped or self._has_live(r)
        if self._async_host and stepped_any:
            self.stats["host_overlap_steps"] += 1
        self._detect_stalls()
        if self._audit_every_step:
            from ..analysis.engine_audit import (EngineAuditError,
                                                 audit_fleet)

            try:
                audit_fleet(self)
            except EngineAuditError:
                if self._flight is not None:
                    self._flight.dump("fleet_audit_error")
                raise
        return busy or bool(self._reqs)

    def export_trace(self, path: str) -> None:
        """Export (and drain) the buffered host spans — every replica's
        request-lifecycle spans plus the router's cross-replica
        failover/hedge flow links and health markers — as ONE chrome
        trace (chrome://tracing / Perfetto): pid = replica lane, tid =
        request lane (docs/observability.md)."""
        from ..profiler import Profiler

        Profiler().export(path)

    # ---------------- serve loop ----------------

    def serve(self, requests: list[Request]) -> dict[int, list[int]]:
        """Route and run all requests to completion;
        returns ``{rid: generated tokens}`` (the fleet-mirrored streams)."""
        for r in requests:
            self.add_request(r)
        while self.step():
            pass
        return {r.rid: r.output_ids for r in requests}
