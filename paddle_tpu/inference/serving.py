"""Continuous-batching decode scheduler (VERDICT r2 #6).

Reference analog: the serving stack behind the reference's fused block
attention family — `paddle/phi/ops/yaml/fused_ops.yaml:45`
(``block_multihead_attention_``) and `:394` (``fused_multi_transformer_``) —
which backs PaddleNLP's continuous-batching servers.

TPU-first design
----------------
A TPU serving engine wants *static shapes*: one compiled decode step over a
fixed slot pool, re-run every iteration.  So instead of the reference's
dynamic batch, we keep:

  * a slot pool of ``max_batch`` lanes in one shared dense KV cache
    [L, max_batch, nkv, S, hd] — a lane is the TPU analog of a block table
    entry (HBM is pre-reserved; XLA gets a fixed layout to tile),
  * one jitted decode step with a *per-slot position vector* — slots at
    different depths decode together in a single batched program (this is
    what "continuous batching" means at the kernel level: the batch never
    drains to admit a newcomer),
  * prefill into a single lane with bucketed prompt padding (powers of two),
    bounding the number of compiled prefill variants to log2(max_seq).

``paged=True`` swaps the per-slot dense lanes for a BLOCK-TABLE cache (the
reference's ``block_multihead_attention_`` memory model, fused_ops.yaml:45):
K/V live in a fixed pool of [num_blocks, nkv, block_size, hd] pages per
layer, each slot owns a host-managed list of block ids, and the compiled
programs receive the [max_batch, max_blocks] table AS DATA — shapes stay
static (the TPU requirement) while HBM is shared by actual usage, so
admission is bounded by free blocks rather than worst-case max_seq lanes.
Decode attention dispatches to the ragged paged-attention Pallas kernel
(`ops/pallas/paged_attention.py`, docs/paged_attention.md), which walks only
each slot's LIVE block-table pages — HBM bytes per step scale with resident
tokens, not the longest request; with the kernel disabled
(``PADDLE_TPU_DISABLE_PALLAS=paged_attention``) or on unsupported shapes,
attention reads a gathered view of the slot's blocks (XLA fuses the block
gather into the attention contraction's operand read); when the pool runs
dry the youngest slot is preempted vLLM-style (blocks freed, request
requeued with prompt+generated so far; the stored tokens are teacher-forced
on resume, which makes the recompute exact for greedy AND sampled decode).

Long-context flash-decode + the fused decode step (docs/paged_attention.md)
are the paged decode path's pure-speed levers, both on by default and both
token-identical to the paths they replace: decode attention dispatches
split-K (a long slot's page walk runs as S parallel shards merged by an
exact log-sum-exp combine — ``PADDLE_TPU_DISABLE_PALLAS=flash_decode``
restores the sequential walk), and the whole per-layer decode prologue —
RoPE, the two KV-append scatters and the attention kernel — runs as ONE
fused Pallas launch (``PADDLE_TPU_DISABLE_PALLAS=fused_decode_step``
rebuilds the unfused engine byte-identically; in fused mode the pool
carries one extra SPILL page dropped writes land on, since a Pallas output
index map cannot drop).  Verify/prefill/mixed programs are byte-untouched;
TP, speculation, chunked prefill, prefix-cache COW and the graceful ladder
compose with both by construction (the fused launch runs per shard inside
shard_map exactly like the rest of the kernel family).

``enable_prefix_caching=True`` (paged mode only) layers an automatic prefix
cache over the block pool (prefix_cache.py, docs/prefix_cache.md): every full
block gets a hash-chained content id, admission maps the longest cached
prefix into the slot's block-table row read-only (refcounted), prefill starts
at the first uncached token (partial-bucket prefill), release/retire/preempt
decrement refs instead of freeing, zero-ref blocks stay resident until
allocation pressure LRU-evicts them, and a fully-matched block that decode
would write into is copy-on-write duplicated first.  The paged-attention
kernel reads shared pages unchanged — sharing is purely block-table aliasing.
Opt-out: ``PADDLE_TPU_PREFIX_CACHE=0``; with caching off (the default) the
engine is byte-identical to the PR 1 engine.

``enable_host_kv_tier=True`` (paged + prefix-cache only) layers the
hierarchical-KV host tier under the cache (kv_tier.py, docs/kv_tier.md):
LRU eviction DEMOTES zero-ref chains to a byte-budgeted host-RAM page
store (``PADDLE_TPU_HOST_TIER_MIB``) instead of freeing them, and
admission's prefix match extends through that tier — a tier hit re-admits
pages by async H2D copy driven by the chunked-prefill cursor, so
"restoring from host" is scheduled exactly like "prefilling" (one cursor,
zero new compiled step shapes, chunk-granular preemption/cancel compose
for free).  Resident-prefix capacity then scales with host RAM rather
than leftover HBM, and the same ``ship_out``/``ship_in`` page transport
is the fleet tier's shared prefix store and ROADMAP item 1's
prefill/decode shipping primitive.  Opt-out: ``PADDLE_TPU_HOST_KV_TIER=0``
restores the pre-tier engine byte-identically.

``enable_speculation=True`` (paged mode only) adds draft-model-free
speculative decoding (speculative.py, docs/speculative.md; reference: the
``speculate_*`` op family in paddle/phi/ops/yaml): a host-side prompt-lookup
n-gram drafter proposes up to K continuation tokens per slot from the
request's own prompt+generated history, and ONE compiled multi-token verify
step scores all of them — the pending token plus the drafts ride through the
ragged paged-attention verify kernel as ``[B, K+1]`` queries with per-slot
``q_lens`` as DATA (one static program, no shape-family churn) — then the
acceptance rule runs in-graph: position-derived sampling keys make the
accepted stream TOKEN-IDENTICAL to the non-speculative engine for greedy AND
seeded sampled requests, so speculation only changes how many tokens each
host round-trip banks.  Rejected drafts roll ``pos`` back (their K/V writes
beyond the accepted point are dead until overwritten, tracked by the
``_written`` high-water mark the runtime auditor checks) and are never
content-addressed into the prefix cache.  Steps where no slot drafts run the
ordinary chunked decode — a drafter miss costs nothing.  Opt-out:
``PADDLE_TPU_SPECULATE=0``; spec-off the engine is byte-identical to the
non-speculative engine.

``enable_chunked_prefill=True`` (paged mode only) removes the last
monolithic hot path: instead of one bucketed whole-prompt prefill per
admission — which stalls every running decode slot for the full prompt
length and compiles a log2(max_seq) family of prefill variants — every
prompt streams in as fixed-size ``prefill_chunk``-token chunks co-scheduled
with decode inside ONE compiled **mixed step** (docs/chunked_prefill.md;
the Sarathi-style stall-free batching the ragged paged-attention papers
argue for).  Each engine step packs up to ``token_budget`` tokens as
[decode slots | prefill chunks]: every decode-ready slot advances exactly
one token (row 0 of its lane), prefilling slots carry up to
``prefill_chunk`` prompt rows, and the whole [B, T] launch runs the ragged
chunked-prefill kernel (`ops/pallas/paged_attention.paged_attention_prefill`
— per-slot positions/q_lens are DATA, so prefill compiles O(1) variants
regardless of prompt length).  A prefill lane's final row sits at the last
prompt token's position, so its logits ARE the first decode step's — TTFT
costs no extra launch.  Prefix-cache hits start the first chunk at the
first uncached token and register pages as chunks complete them;
speculation skips slots still prefilling (mixed steps run while any prompt
streams, the spec path resumes once prefill drains).  Opt-out:
``PADDLE_TPU_CHUNKED_PREFILL=0``; chunked-off the engine is byte-identical
to the bucketed-prefill engine.

Fault tolerance (docs/fault_tolerance.md; default on, kill switch
``PADDLE_TPU_GRACEFUL=0`` restores the brittle pre-fault-tolerance engine
byte-identically): every request ends in a terminal ``status``
(``FINISHED | FAILED | REJECTED | CANCELLED | EXPIRED``) and no per-request
fault escapes ``step()`` — the offending request is failed, its pages and
cache refs released, and every surviving request's token stream is
IDENTICAL to a run that never contained the poison request (each slot's
stream depends only on its own (seed, position) keys and its own pages, so
isolation is exact, not best-effort).  Overload walks a degradation ladder
in strict order — evict prefix-cache leaves, suspend speculation for the
step, shrink the mixed-step token budget, preempt the youngest slot, and
only then fail the one unsatisfiable request.  Requests carry an optional
``deadline_s`` (expire with partial output), ``cancel(rid)`` frees even a
mid-prefill slot via the chunked-prefill cursor, a bounded queue
(``max_queue``) applies REJECTED-on-full backpressure, and an IN-GRAPH
NaN/inf logit guard quarantines a poisoned slot instead of emitting garbage
(the guard's flags ride back with the step's tokens — no extra host sync).
``snapshot()``/``restore()`` journal accepted work (prompt, emitted tokens,
chunk cursor) and resume through the preemption path's teacher-forced
recompute — the replica-restart primitive the fleet tier needs.  Faults are
injected deterministically at the allocator / kernel-dispatch / sampler
seams via ``PADDLE_TPU_FAULT_INJECT`` (faults.py).

``tensor_parallel=N`` (docs/tp_serving.md; paged mode only, kill/override
knob ``PADDLE_TPU_TP``) fans the whole engine across N devices on a 1-D
``("tp",)`` mesh: weights take the Megatron column/row split
(models/llama.serving_param_specs), the paged KV pool and every new-page
append shard along **kv_heads** — the one axis the ragged paged-attention
kernels' page walk never crosses, so decode/verify/prefill kernel bodies
run byte-unchanged per shard inside shard_map — and each layer pays exactly
two psum boundaries (attention output, MLP output).  Block tables, the
scheduler, the prefix cache, the fault ladder and drafter state stay
replicated host-side, so prefix caching, speculation, chunked prefill,
graceful degradation and snapshot/restore all compose with TP by
construction; TP=1 builds the byte-identical single-chip engine and TP>1
is token-identical to it (every shard computes the same full-vocab logits
row after the psums, so the in-graph sampler agrees by construction).

Per-request sampling (reference: ``top_p_sampling``, ops.yaml:4947) runs
inside the jitted step: temperature/top-p/seed are per-slot DATA vectors, so
one compiled program serves mixed greedy/sampled batches, and RNG keys
derive from (slot seed, position) — deterministic, replayable streams.

Admission/retirement/allocation is plain Python around the compiled
programs — scheduling is control-plane work and costs microseconds next to
a device step, the same split the reference makes between its C++ scheduler
and CUDA kernels.
"""

from __future__ import annotations

import functools
import math
import time
import warnings
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from jax.experimental.shard_map import shard_map as _shard_map

from ..profiler import RecordEvent
from .faults import FaultInjected

__all__ = ["Request", "ContinuousBatchingEngine", "TERMINAL_STATUSES",
           "REQUEST_EDGES"]

#: terminal request statuses (docs/fault_tolerance.md status lifecycle);
#: a request in one of these owns zero pages and zero cache refs — the
#: runtime auditor's I8 (analysis/engine_audit.py)
TERMINAL_STATUSES = frozenset({"FINISHED", "FAILED", "REJECTED", "CANCELLED",
                               "EXPIRED"})

#: declared request-lifecycle transition table, verified exhaustively
#: against every ``.status`` assignment site by the host-contract pass
#: (analysis/host_contracts.py; docs/analysis.md §"Host contracts").
#: PENDING<->RUNNING covers admission (_admit) and preemption (_preempt);
#: both live states may fall to any terminal status (rejection and expiry
#: can hit queued requests, failure/cancel/finish hit seated ones).
#: Terminal statuses are absorbing — there is deliberately no edge out.
REQUEST_EDGES = frozenset(
    {("PENDING", "RUNNING"), ("RUNNING", "PENDING")}
    | {(live, term) for live in ("PENDING", "RUNNING")
       for term in TERMINAL_STATUSES})

#: terminal status -> engine stats counter (FINISHED ticks decode counters
#: through the normal retire path instead)
_STATUS_STAT = {"FAILED": "requests_failed", "REJECTED": "requests_rejected",
                "CANCELLED": "requests_cancelled",
                "EXPIRED": "requests_expired"}


@dataclass
class Request:
    rid: int
    prompt_ids: np.ndarray  # [s0] int32
    max_new_tokens: int = 32
    eos_token_id: int | None = None
    # per-request sampling (reference: top_p_sampling,
    # paddle/phi/ops/yaml/ops.yaml:4947).  temperature == 0 -> greedy.
    temperature: float = 0.0
    top_p: float = 1.0
    seed: int | None = None
    # wall-clock budget from submission; overdue requests expire with the
    # partial output they have (status EXPIRED) instead of holding pages
    deadline_s: float | None = None
    # filled by the engine
    output_ids: list = field(default_factory=list)
    finished: bool = False
    ttft_s: float | None = None  # submit -> first generated token (wall s)
    # lifecycle: PENDING (queued) -> RUNNING (seated) -> one of
    # TERMINAL_STATUSES; ``error`` is set for every non-FINISHED terminal
    status: str = "PENDING"
    error: str | None = None
    # request-lifecycle trace id (docs/observability.md): assigned at
    # admission when None; replica copies and failover replays carry the
    # SAME id, so one request's spans correlate across the whole fleet
    trace_id: str | None = None


def _bucket(n: int, lo: int = 16) -> int:
    b = lo
    while b < n:
        b *= 2
    return b


def journal_entry(req: Request, prefilled: int = 0,
                  now: float | None = None) -> dict:
    """One request's snapshot-journal entry — THE schema
    :meth:`ContinuousBatchingEngine.snapshot` emits and
    :meth:`ContinuousBatchingEngine.adopt` consumes (docs/
    fault_tolerance.md "Snapshot / restore").  Shared with the fleet
    router's journal fallback (inference/fleet.py) so the field set and
    coercions can never diverge between the two producers.

    ``deadline_remaining_s`` is the UNSPENT wall-clock budget at ``now``:
    adoption re-arms the deadline with what is actually left, so a
    restored request expires at ~100% of its original SLO, never ~180%
    (``deadline_s`` stays as provenance)."""
    if now is None:
        now = time.perf_counter()
    if req.deadline_s is None:
        remaining = None
    else:
        remaining = max(0.0, float(req.deadline_s)
                        - (now - getattr(req, "_submit_s", now)))
    return {
        "rid": int(req.rid),
        "prompt_ids": np.asarray(req.prompt_ids,
                                 np.int32).ravel().tolist(),
        "output_ids": [int(t) for t in req.output_ids],
        "max_new_tokens": int(req.max_new_tokens),
        "eos_token_id": (None if req.eos_token_id is None
                         else int(req.eos_token_id)),
        "temperature": float(req.temperature or 0.0),
        "top_p": float(1.0 if req.top_p is None else req.top_p),
        "seed": None if req.seed is None else int(req.seed),
        "deadline_s": (None if req.deadline_s is None
                       else float(req.deadline_s)),
        "deadline_remaining_s": remaining,
        # the chunk cursor: restore re-prefills from the first uncached
        # token, so this is provenance (how far the dead replica got),
        # not a resume offset into lost KV bytes
        "prefilled": int(prefilled),
    }


class _TPShardView:
    """Per-shard config view inside the ``("tp",)`` shard_map region
    (docs/tp_serving.md): the compiled-step bodies read head counts off the
    config, and inside the region every shard holds nh/tp query heads and
    nkv/tp kv heads of the SAME full head_dim — so the view pins tp-local
    counts and the true head_dim (the dataclass property would miscompute
    it from hidden_size // local_heads) and proxies everything else
    (dtype, rope_theta, layer count, ...) to the real config.  The GQA
    group ratio nh/nkv is tp-invariant, which is why the paged-attention
    kernels run byte-unchanged per shard."""

    def __init__(self, cfg, tp: int):
        self._cfg = cfg
        self.num_attention_heads = cfg.num_attention_heads // tp
        self.num_key_value_heads = cfg.num_key_value_heads // tp
        self.head_dim = cfg.head_dim

    def __getattr__(self, name):
        return getattr(self._cfg, name)


class ContinuousBatchingEngine:
    """Slot-pool continuous batching over a Llama-family model.

    ``cfg``/``params`` follow paddle_tpu.models.llama conventions (the same
    pytree the AOT GenerationEngine uses, inference/__init__.py:249).
    """

    def __init__(self, cfg, params, max_batch: int = 8, max_seq: int = 512,
                 chunk: int = 1, quant: str | None = None, paged: bool = False,
                 kv_quant: str | None = None,
                 block_size: int = 64, num_blocks: int | None = None,
                 enable_prefix_caching: bool = False,
                 enable_speculation: bool = False, num_draft_tokens: int = 4,
                 spec_ngram: int = 3, enable_chunked_prefill: bool = False,
                 prefill_chunk: int = 128, token_budget: int | None = None,
                 max_queue: int | None = None, tensor_parallel: int = 1,
                 enable_host_kv_tier: bool = False, host_tier=None,
                 metrics=None, metrics_labels: dict | None = None):
        """``chunk``: decode steps per compiled call.  Tokens feed back
        on-device inside a lax.scan and the host fetches ``chunk`` tokens per
        round-trip — the lever against host-device latency (one RTT per token
        is what bounds single-step decode on a relay-attached TPU).  Retire
        and admission happen at chunk granularity; generated tokens past a
        request's EOS/budget inside a chunk are trimmed host-side.
        ``quant``: None | 'int8' | 'int4' — weight-only quantized matmuls
        (weights stream from HBM at 1/2 or 1/4 the bytes).
        ``kv_quant``: None | 'int8' | 'int4' — QUANTIZED KV pools (paged
        mode only; docs/paged_attention.md "Megastep stage 2"): pages
        store int8 codes (int4 packs two nibbles per byte) plus per-
        (page, kv_head) f32 scales, halving or quartering resident KV
        bytes — the production memory configuration.  Every attention
        path dequantizes on read (the kernels' ``kv_quant`` mode);
        appends REQUANTIZE the dirty page (dequantize with the old
        scale, insert, recompute the scale, rewrite) — in-kernel on the
        fused decode path (``fused_quant_append``: zero scatters per
        decode step), as a requant-scatter pair on the kill-switched
        path, page-batched in XLA on prefill/verify/mixed writes.
        Because requantization is lossy per write EVENT, the emitted
        stream depends on event grouping (chunking/speculation change
        quantization noise); the guaranteed identity is between the
        fused, kill-switched and gather-oracle ARMS of one
        configuration — each computes byte-identical pool contents.
        ``paged``: block-table KV cache (``block_size`` tokens per page,
        ``num_blocks`` pages shared by all slots; default num_blocks gives
        half the dense pool's capacity — the paged mode's point is serving
        more logical context than physically reserved HBM).
        ``enable_prefix_caching``: content-addressed reuse of full KV blocks
        across requests (paged mode only; see prefix_cache.py).  Kill switch:
        ``PADDLE_TPU_PREFIX_CACHE=0`` forces it off regardless.
        ``enable_speculation``: prompt-lookup n-gram drafting + multi-token
        verification (paged mode only; see speculative.py and
        docs/speculative.md).  ``num_draft_tokens`` (K) bounds drafts per
        step — the verify step's static query width is K+1;``spec_ngram`` is
        the longest suffix the drafter matches.  Kill switch:
        ``PADDLE_TPU_SPECULATE=0`` forces it off regardless.
        ``enable_chunked_prefill``: stream prompts in ``prefill_chunk``-token
        chunks co-scheduled with decode in one compiled mixed step per
        iteration (paged mode only; docs/chunked_prefill.md).
        ``token_budget`` caps total tokens per mixed step (decode rows pack
        first, prefill chunks fill the remainder; default
        ``prefill_chunk + max_batch``).  While any prompt streams, every
        engine step is a mixed step — ONE host round-trip per decode token
        — so a ``chunk > 1`` engine trades its scan's RTT amortization for
        stall-freedom exactly while prompts are in flight (the Sarathi
        tradeoff; the untouched chunk-length scan resumes once prefill
        drains — docs/chunked_prefill.md "token-budget semantics").  Kill
        switch: ``PADDLE_TPU_CHUNKED_PREFILL=0`` forces it off
        regardless.
        ``max_queue``: admission backpressure — when the wait queue already
        holds this many requests, ``add_request`` marks the newcomer
        ``REJECTED`` (with ``error``) instead of queueing it; None (the
        default) keeps the queue unbounded.  Preemption re-inserts are
        exempt: accepted work is never rejected.
        ``tensor_parallel``: shard the engine over N devices on a 1-D
        ``("tp",)`` mesh (docs/tp_serving.md; paged mode only).  Weights
        take the Megatron column/row split (models/llama.
        serving_param_specs), the paged KV pool and every new-page append
        shard along **kv_heads**, and each compiled step runs the
        single-chip per-shard programs inside shard_map with exactly two
        psum boundaries per layer (attention output, MLP output) — block
        tables, scheduler, prefix cache, fault ladder and drafter state
        stay replicated host-side, so every feature above composes with TP
        by construction and TP>1 is token-identical to TP=1.  N must
        divide num_key_value_heads (and intermediate_size) and not exceed
        the visible device count.  ``PADDLE_TPU_TP=<int>`` overrides this
        value (validated: an invalid degree warns once with the valid
        divisors and falls back to 1 — utils/envflags.env_tp).
        ``enable_host_kv_tier`` (docs/kv_tier.md; requires paged mode AND
        ``enable_prefix_caching``): hierarchical KV — prefix-cache
        eviction DEMOTES zero-ref chains to a byte-budgeted host-RAM page
        store (``PADDLE_TPU_HOST_TIER_MIB``) instead of freeing them, and
        admission's prefix match extends through that tier: a tier hit
        re-admits pages by async H2D copy scheduled through the
        chunked-prefill cursor exactly like prefilling (one cursor, zero
        new compiled shapes).  ``host_tier`` passes a pre-built
        :class:`~paddle_tpu.inference.kv_tier.HostKVTier` — how the
        FleetRouter shares ONE tier across replicas so any replica
        re-admits chains another replica computed.  Kill switch:
        ``PADDLE_TPU_HOST_KV_TIER=0`` forces it off regardless
        (byte-identical to the pre-tier engine), and
        ``PADDLE_TPU_PREFIX_CACHE=0`` neutralizes it too (no content
        address, nothing to demote).
        ``metrics`` / ``metrics_labels`` (docs/observability.md): an
        optional shared :class:`~paddle_tpu.inference.observability.
        MetricsRegistry` plus constant label set (e.g. ``{"replica": k}``
        — how the FleetRouter aggregates N replicas into one exposition);
        by default the engine creates its own registry.  Ignored with
        ``PADDLE_TPU_METRICS=0``, which restores the plain pre-
        observability ``stats`` dict."""
        from ..models import llama as _llama  # noqa: F401  (cfg type lives there)

        self.cfg = cfg
        if quant is not None:
            from . import quantize_layer_params

            params = quantize_layer_params(params, quant)
        self.quant = quant
        self.params = params
        self.max_batch = max_batch
        self.max_seq = max_seq
        self.chunk = int(chunk)
        self.paged = bool(paged)
        L = cfg.num_hidden_layers
        nkv, hd = cfg.num_key_value_heads, cfg.head_dim
        # quantized KV pools (docs/paged_attention.md "Megastep stage 2"):
        # validated before any pool geometry is derived
        if kv_quant is not None:
            if kv_quant not in ("int8", "int4"):
                raise ValueError(f"kv_quant must be None, 'int8' or "
                                 f"'int4', got {kv_quant!r}")
            if not paged:
                raise ValueError("kv_quant requires paged=True (per-page "
                                 "scales live on block-table pages)")
            if kv_quant == "int4" and hd % 2:
                raise ValueError(f"kv_quant='int4' needs an even head_dim "
                                 f"(got {hd}): two nibbles pack per byte")
        self.kv_quant = kv_quant
        # ---- tensor parallelism (docs/tp_serving.md) ----
        # resolve the degree FIRST: the KV pool is created already sharded
        # and every compiled program below is built per-shard.  tp == 1
        # must construct the exact pre-TP engine (no mesh, no device_put,
        # no shard_map) — every TP behavior hangs off self.tp > 1.
        from ..utils.envflags import env_tp

        tp = int(tensor_parallel)
        if tp < 1:
            # a caller's arithmetic bug (devices // n == 0) must raise,
            # not degrade to a nonsense degree (env typos degrade instead
            # — env_tp already floors those at 1 with a warning)
            raise ValueError(f"tensor_parallel must be >= 1, got {tp}")
        tp_env = env_tp(nkv, jax.device_count())
        if tp_env is not None:
            tp = tp_env     # operator override replaces the ctor value
        if tp > 1:
            problems = []
            if not paged:
                problems.append(
                    "tensor_parallel > 1 requires paged=True (TP shards "
                    "the paged KV pool along kv_heads)")
            if nkv % tp:
                divs = sorted(d for d in range(1, nkv + 1) if nkv % d == 0)
                problems.append(
                    f"tensor_parallel={tp} does not divide "
                    f"num_key_value_heads={nkv} — a sub-head split would "
                    f"break the shard-local page walk (valid divisors: "
                    f"{divs})")
            if cfg.intermediate_size % tp:
                problems.append(
                    f"tensor_parallel={tp} does not divide "
                    f"intermediate_size={cfg.intermediate_size} (the MLP "
                    f"column split needs an even ffn slice per shard)")
            if tp > jax.device_count():
                problems.append(
                    f"tensor_parallel={tp} exceeds the "
                    f"{jax.device_count()} visible device(s)")
            if problems:
                if tp_env is not None:
                    # an env override must degrade to the single-chip
                    # engine, never crash the serve (same contract as
                    # env_tp's own validation)
                    warnings.warn(f"PADDLE_TPU_TP={tp}: "
                                  + "; ".join(problems)
                                  + "; falling back to tensor_parallel=1")
                    tp = 1
                else:
                    raise ValueError("; ".join(problems))
        self.tp = tp
        self._tp_axis = None
        self._mesh = None
        self._body_cfg = cfg       # the cfg the compiled-step bodies read
        if tp > 1:
            from jax.sharding import Mesh, NamedSharding
            from jax.sharding import PartitionSpec as _P

            self._mesh = Mesh(np.asarray(jax.devices()[:tp]), ("tp",))
            self._tp_axis = "tp"
            # inside the shard_map region every step body sees tp-local
            # head counts over the same head_dim (GQA ratio unchanged —
            # the Pallas kernels run byte-identically per shard)
            self._body_cfg = _TPShardView(cfg, tp)
            specs = _llama.serving_param_specs(cfg, quant=quant)
            if "lm_head" not in params:
                specs.pop("lm_head", None)
            self._param_specs = specs
            self._param_shardings = jax.tree_util.tree_map(
                lambda s: NamedSharding(self._mesh, s), specs,
                is_leaf=lambda s: isinstance(s, jax.sharding.PartitionSpec))
            # pool layout [L, num_blocks, nkv, bs, hd]: ONLY kv_heads
            # shards — per-shard page capacity equals num_blocks, so the
            # host allocator's accounting holds exactly on every shard
            self._cache_spec = _P(None, None, "tp")
            self._cache_sharding = NamedSharding(self._mesh,
                                                 self._cache_spec)
            self.params = jax.device_put(self.params, self._param_shardings)
        self._fused = False   # fused decode step: paged-mode only, see below
        self._fused_mlp = False   # fused MLP layer half: ditto (stage 2)
        if paged:
            assert max_seq % block_size == 0, (max_seq, block_size)
            self.block_size = block_size
            self.max_blocks = max_seq // block_size     # per-slot logical cap
            # default pool: half the worst-case footprint (continuous
            # batching oversubscribes), floored at ONE full request so a
            # max_batch=1 engine is constructible
            self.num_blocks = (num_blocks if num_blocks is not None
                               else max((max_batch * self.max_blocks) // 2,
                                        self.max_blocks))
            assert self.num_blocks >= self.max_blocks, (
                f"pool of {self.num_blocks} blocks cannot hold one full "
                f"request ({self.max_blocks} blocks)")
            # fused decode step (docs/paged_attention.md "Fused decode
            # step"): rope + KV-append + attention in ONE Pallas launch per
            # layer on the plain decode path.  Decided at ctor time because
            # the pool grows a SPILL page (physical index num_blocks) that
            # dropped writes land on — Pallas output index maps cannot
            # drop.  The allocator never hands the spill page out (its free
            # list stays range(num_blocks)), reads of sentinel table rows
            # resolve to it (finite garbage, masked), and every other
            # compiled program treats it exactly like `.at[...,
            # mode='drop']` did.  PADDLE_TPU_DISABLE_PALLAS=
            # fused_decode_step (or =paged_attention, or an unsupported
            # shape) rebuilds the pre-fusion engine byte-identically:
            # no spill page, unfused rope + scatter + attention decode.
            from ..ops.pallas import paged_attention as _pa_mod

            self._fused = (_pa_mod.kernel_supported(
                cfg.num_attention_heads, nkv, hd, block_size)
                and not _pa_mod.kernel_disabled("fused_decode_step"))
            if self.kv_quant is not None:
                # quantized pools take the fused path only with the
                # in-kernel requantized append (stage 2): killing
                # fused_quant_append restores the requant-scatter decode
                # (and drops the spill page) exactly like
                # fused_decode_step does for fp pools
                self._fused = (self._fused and not _pa_mod.kernel_disabled(
                    "fused_quant_append"))
            # decode megastep stage 2: fuse the post-attention layer half
            # (residual + post RMSNorm + SwiGLU MLP) into one per-layer
            # launch on the decode path.  Requires the fused attention
            # step (so the kill-switched serving_decode_step program
            # stays the exact pre-fusion oracle) and fp matmul leaves
            # (weight-only-quant leaves resolve through wmat's dequant;
            # streaming them dense through the kernel would defeat the
            # quantized weight footprint).
            self._fused_mlp = (self._fused and quant is None
                               and _pa_mod.fused_mlp_supported(
                                   cfg.hidden_size,
                                   cfg.intermediate_size // self.tp))
            nbp = self.num_blocks + (1 if self._fused else 0)
            if self.kv_quant is None:
                shape = (L, nbp, nkv, block_size, hd)
            else:
                hd_store = hd // 2 if self.kv_quant == "int4" else hd
                shape = (L, nbp, nkv, block_size, hd_store)
            # host allocator state
            self._free: list[int] = list(range(self.num_blocks))
            self._slot_blocks: list[list[int]] = [[] for _ in range(max_batch)]
            # shared (refcounted, read-only) cached blocks mapped at the FRONT
            # of each slot's row; private writable pages follow — row layout
            # [shared..., private...] is the allocator invariant
            self._slot_shared: list[list[str]] = [[] for _ in range(max_batch)]
            # sentinel num_blocks = unallocated (oob: writes drop, reads are
            # masked by the causal/active mask before they matter)
            self._table = np.full((max_batch, self.max_blocks),
                                  self.num_blocks, np.int32)
            self._admit_seq = 0
            self._slot_age = np.zeros(max_batch, np.int64)
        else:
            shape = (L, max_batch, nkv, max_seq, hd)
        if self.paged and self.kv_quant is not None:
            # quantized pools: int8 codes + per-(page, head) f32 scales as
            # ONE pytree per pool — compiled steps, donation, the COW
            # copy and TP sharding all treat the pair as the cache
            # operand, so the scheduler/allocator plumbing is untouched
            self.cache_k = {"q": jnp.zeros(shape, jnp.int8),
                            "scale": jnp.zeros(shape[:3], jnp.float32)}
            self.cache_v = {"q": jnp.zeros(shape, jnp.int8),
                            "scale": jnp.zeros(shape[:3], jnp.float32)}
        else:
            self.cache_k = jnp.zeros(shape, cfg.dtype)
            self.cache_v = jnp.zeros(shape, cfg.dtype)
        if self.tp > 1:
            # the pool lives sharded from birth; donation keeps it sharded
            # through every step, so no per-step resharding ever happens
            self.cache_k = jax.device_put(self.cache_k, self._cache_sharding)
            self.cache_v = jax.device_put(self.cache_v, self._cache_sharding)
        # automatic prefix cache (content-addressed KV block reuse).  The
        # cache-off path must stay byte-identical to the plain paged engine,
        # so EVERY cache behavior hangs off self._pcache being non-None.
        self._pcache = None
        # the env kill switch is checked FIRST so =0 neutralizes the feature
        # totally — even an (invalid) paged=False request runs cache-off
        # instead of raising, honoring "forces it off regardless".
        # env_bool validates the value: a typo ('off') warns instead of
        # silently leaving the cache enabled (utils/envflags.py)
        from ..utils.envflags import env_bool

        if enable_prefix_caching and env_bool("PADDLE_TPU_PREFIX_CACHE",
                                              True):
            if not paged:
                raise ValueError("enable_prefix_caching requires paged=True "
                                 "(the cache shares block-table pages)")
            from .prefix_cache import PrefixCache

            self._pcache = PrefixCache(block_size)
            # page-granular COW: duplicate pool page src into dst across
            # all layers (donated — no full-pool copy materializes).  TP:
            # page indices address the unsharded num_blocks axis, so the
            # copy is shard-local; the output pins the pool sharding so
            # GSPMD can never decide to re-lay the donated buffer out.
            # tree_map so a quantized pool's codes AND per-page scales
            # copy together (a bare fp pool maps through unchanged —
            # identical jaxpr to the direct .at[] form)
            self._copy_page = jax.jit(
                lambda c, dst, src: jax.tree_util.tree_map(
                    lambda a: a.at[:, dst].set(a[:, src]), c),
                donate_argnums=(0,),
                **({"out_shardings": self._cache_sharding}
                   if self.tp > 1 else {}))
            # partial-bucket prefill: compiled per bucket; start/length
            # are DATA so one program serves every hit depth
            if self.tp == 1:
                self._prefill_prefix = jax.jit(
                    self._prefill_impl_paged_prefix, donate_argnums=(2, 3),
                    static_argnums=(7,))
            else:
                self._prefill_prefix = jax.jit(
                    self._tp_shard_prefill(self._prefill_impl_paged_prefix),
                    donate_argnums=(2, 3), static_argnums=(7,))
        # hierarchical KV: host-RAM spill tier behind the prefix cache
        # (ISSUE 13, docs/kv_tier.md).  EVERY tier behavior hangs off
        # self._tier being non-None, and the env kill switch is checked
        # FIRST so PADDLE_TPU_HOST_KV_TIER=0 neutralizes the feature
        # totally — tier-off the engine is byte-identical to the pre-tier
        # engine (eviction frees, admission stops at the HBM match).
        self._tier = None
        if ((enable_host_kv_tier or host_tier is not None)
                and env_bool("PADDLE_TPU_HOST_KV_TIER", True)):
            if not paged or not enable_prefix_caching:
                raise ValueError(
                    "enable_host_kv_tier requires paged=True and "
                    "enable_prefix_caching=True (the tier is keyed by the "
                    "prefix cache's chain hashes and holds its evicted "
                    "pages)")
            if self._pcache is not None:
                # PADDLE_TPU_PREFIX_CACHE=0 neutralizes the tier too:
                # with no content address there is nothing to demote to
                # or match through — the engine runs tier-off rather than
                # raising, honoring "forces it off regardless"
                from .kv_tier import HostKVTier

                self._tier = (host_tier if host_tier is not None
                              else HostKVTier())
                # donated H2D page write (ship_in's device half): upload
                # one host page into pool page dst across all layers.
                # TP: page indices address the unsharded num_blocks axis
                # and the replicated page operand shards onto the pool's
                # kv_heads spec in-graph; out_shardings pins the layout
                # so the donated buffer is never re-laid out (the same
                # contract as _copy_page).
                # tree_map like _copy_page: a quantized pool restores
                # codes + scales in one donated write
                self._tier_write = jax.jit(
                    lambda c, dst, page: jax.tree_util.tree_map(
                        lambda a, p: a.at[:, dst].set(p), c, page),
                    donate_argnums=(0,),
                    **({"out_shardings": self._cache_sharding}
                       if self.tp > 1 else {}))
                # per-slot match-to-restore plans: [(block_idx, hash,
                # parent), ...] — consumed front-first by the chunked
                # cursor at the step token budget's pace (restores bill
                # like prefill rows, one-block floor), dropped whole on
                # preempt/cancel/terminal or a tier miss (see
                # _tier_restore_step / _drop_tier_plan)
                self._tier_plan: list[list] = [[] for _ in range(max_batch)]
        # slot state (host side)
        self._slot_req: list[Request | None] = [None] * max_batch
        self._pos = np.zeros(max_batch, np.int32)      # next write position
        # KV-write high-water mark per slot: positions [0, _written) hold
        # device-written (or cache-mapped) K/V.  Equals pos everywhere except
        # after a speculative verify step with rejections, where pos rolls
        # back to the accepted point but the rejected drafts' writes remain
        # (dead until overwritten).  The engine auditor's I6 cross-checks
        # pos <= written <= mapped-page coverage.
        self._written = np.zeros(max_batch, np.int32)
        self._last_tok = np.zeros(max_batch, np.int32)
        # per-slot sampling state (temperature 0 = greedy; one compiled
        # program serves mixed greedy/sampled batches — the knobs are DATA)
        self._temp = np.zeros(max_batch, np.float32)
        self._topp = np.ones(max_batch, np.float32)
        self._seed = np.zeros(max_batch, np.int32)
        self._queue: list[Request] = []
        # fault tolerance (docs/fault_tolerance.md).  ``_graceful`` is a
        # TRACE-TIME static: with PADDLE_TPU_GRACEFUL=0 every compiled
        # program below traces the pre-fault-tolerance jaxpr byte-for-byte
        # (no poison operand, no guard flags) and faults raise out of
        # step() exactly as they always did.
        self._graceful = env_bool("PADDLE_TPU_GRACEFUL", True)
        from .faults import FaultPlan

        self._faults = FaultPlan.from_env()
        self._step_no = 0          # engine step counter (fault-plan key)
        self.max_queue = max_queue
        # rid -> Request for every request ever accepted: cancel()'s lookup,
        # snapshot()'s journal source, and the auditor's I8 witness set
        self._reqs: dict[int, Request] = {}
        # per-slot sampler-seam poison bits (nan_logits injection): DATA to
        # the graceful compiled steps, where they become a genuinely
        # non-finite logits row the in-graph guard must catch
        self._poison = np.zeros(max_batch, bool)
        self._kernel_err_streak = 0
        # consecutive failed launches tolerated before giving up: a raise at
        # the dispatch seam leaves state untouched (retry is free), but a
        # persistent failure means the program itself cannot run
        self._kernel_err_limit = 3
        # consecutive steps where admission made no progress with nothing
        # resident (see step(): waiting cannot help — ladder rung 5 applies
        # at admission after this many stuck steps)
        self._admit_stalls = 0
        impl = self._decode_impl_paged if paged else self._decode_impl
        # two decode variants behind a STATIC sampling flag: the full-vocab
        # sort/softmax/categorical of the sampler must not run (XLA cannot
        # DCE work behind a data-dependent where) when every resident slot
        # is greedy — the bench headline's configuration
        self._decode_greedy = self._jit_step(
            impl, n_rep=2 if self._graceful else 1, sampling=False,
            graceful=self._graceful)
        self._decode_sampling = self._jit_step(
            impl, n_rep=2 if self._graceful else 1, sampling=True,
            graceful=self._graceful)
        # prefill writes its lane directly into the donated pool arrays —
        # no slice-out/scatter-back copies of the full pool per admission
        pimpl = self._prefill_impl_paged if paged else self._prefill_impl
        if self.tp == 1:
            self._prefill = jax.jit(pimpl, donate_argnums=(2, 3),
                                    static_argnums=(6,))
        else:
            self._prefill = jax.jit(self._tp_shard_prefill(pimpl),
                                    donate_argnums=(2, 3),
                                    static_argnums=(6,))
        # speculative decoding (prompt-lookup drafting + multi-token verify).
        # Like the prefix cache, EVERY spec behavior hangs off self._spec
        # being non-None, and the env kill switch is checked FIRST so
        # PADDLE_TPU_SPECULATE=0 neutralizes the feature totally (even an
        # invalid paged=False request runs spec-off instead of raising).
        self._spec = None
        self._spec_qmax = 0
        if enable_speculation and env_bool("PADDLE_TPU_SPECULATE", True):
            if not paged:
                raise ValueError(
                    "enable_speculation requires paged=True (the multi-token "
                    "verify step runs through the paged-attention kernel)")
            from .speculative import NGramDrafter

            self._spec = NGramDrafter(num_draft_tokens=num_draft_tokens,
                                      max_ngram=spec_ngram)
            # the verify step's query width is STATIC at K+1 (per-slot
            # raggedness is the q_lens data vector): one compiled variant
            # per sampling mode for the whole serve, no shape-family churn
            self._spec_qmax = int(num_draft_tokens) + 1
            self._verify_greedy = self._jit_step(
                self._verify_impl_paged, n_rep=3 if self._graceful else 2,
                sampling=False, graceful=self._graceful)
            self._verify_sampling = self._jit_step(
                self._verify_impl_paged, n_rep=3 if self._graceful else 2,
                sampling=True, graceful=self._graceful)
        # chunked prefill + unified mixed prefill/decode step (stall-free
        # continuous batching; docs/chunked_prefill.md).  Like the prefix
        # cache and speculation, EVERY chunked behavior hangs off
        # self._chunked, and the env kill switch is checked FIRST so
        # PADDLE_TPU_CHUNKED_PREFILL=0 neutralizes the feature totally —
        # chunked-off the engine is byte-identical to the bucketed engine.
        self._chunked = False
        if enable_chunked_prefill and env_bool("PADDLE_TPU_CHUNKED_PREFILL",
                                               True):
            if not paged:
                raise ValueError(
                    "enable_chunked_prefill requires paged=True (prefill "
                    "chunks stream into block-table pages)")
            if prefill_chunk < 1:
                raise ValueError(
                    f"prefill_chunk must be >= 1, got {prefill_chunk}")
            self._chunked = True
            self._prefill_chunk = int(prefill_chunk)
            # per-step token cap: decode rows pack FIRST (decode never
            # stalls), prefill chunks fill the remainder with a 1-token
            # floor so admission can never livelock on a tiny budget
            self._token_budget = (int(token_budget)
                                  if token_budget is not None
                                  else self._prefill_chunk + max_batch)
            # per-slot prefill progress: _prefill_ids[s] holds the FULL id
            # stream (prompt, or prompt + generated-so-far on a preemption
            # resume) while the slot is still streaming in; _prefilled[s]
            # is the cursor — the next position whose K/V must be computed.
            # A slot is "prefilling" iff _prefill_ids[s] is not None.
            self._prefill_ids: list[np.ndarray | None] = [None] * max_batch
            self._prefilled = np.zeros(max_batch, np.int32)
            # the last mixed step's packing (decode slots, prefill slots) —
            # the runtime auditor's I7 checks the two sets stay disjoint
            self._last_pack: tuple[tuple[int, ...], tuple[int, ...]] = ((),
                                                                        ())
            # ONE compiled [B, T] program per sampling mode for the whole
            # serve: chunk packing / per-slot progress are q_lens/pos DATA,
            # so prefill goes from log2(max_seq) bucketed variants to O(1)
            self._mixed_greedy = self._jit_step(
                self._mixed_impl_paged, n_rep=2 if self._graceful else 1,
                sampling=False, graceful=self._graceful)
            self._mixed_sampling = self._jit_step(
                self._mixed_impl_paged, n_rep=2 if self._graceful else 1,
                sampling=True, graceful=self._graceful)
        # ---- observability (ISSUE 11, docs/observability.md) ----
        # stats live on a typed MetricsRegistry behind a dict-compatible
        # view (keys + help strings: observability.ENGINE_STAT_SCHEMA), so
        # every existing ``eng.stats[...]`` read keeps working while the
        # same counters show up labelled in ``metrics.expose()``; the SLO
        # tracker and request tracer feed off the same host events.  ALL
        # recording is host-side post-step — the compiled programs above
        # are untouched either way, so token streams are byte-identical
        # with PADDLE_TPU_METRICS=0 (which restores the plain dict) or 1.
        from .observability import (ENGINE_STAT_SCHEMA, FlightRecorder,
                                    MetricsRegistry, RequestTracer,
                                    SLOTracker, StatsView,
                                    flight_recorder_enabled, metrics_enabled)

        self._obs_labels = dict(metrics_labels or {})
        replica = self._obs_labels.get("replica")
        obs_name = (f"replica-{replica}" if replica is not None
                    else "engine")
        if metrics_enabled():
            self.metrics = (metrics if metrics is not None
                            else MetricsRegistry())
            self.stats = StatsView(self.metrics, ENGINE_STAT_SCHEMA,
                                   self._obs_labels)
            self.slo = SLOTracker(self.metrics, self._obs_labels)
            self._h_hostgap = self.metrics.histogram(
                "paddle_tpu_serving_host_gap_seconds",
                "Host-side gap between the end of one compiled serving "
                "step and the next launch (scheduler/drafter/router time "
                "the device sits idle — ROADMAP item 5's target)"
            ).labels(**self._obs_labels)
            self._h_step = self.metrics.histogram(
                "paddle_tpu_serving_step_seconds",
                "Wall seconds per compiled serving step (launch to host "
                "fetch)").labels(**self._obs_labels)
            self._h_h2d = (self.metrics.histogram(
                "paddle_tpu_serving_h2d_restore_seconds",
                "Host->device dispatch seconds per tier page restore "
                "(kv_tier ship_in: two donated pool writes, overlapped "
                "with the next compiled step by async dispatch)")
                .labels(**self._obs_labels) if self._tier is not None
                else None)
            self._h_jupdate = self.metrics.histogram(
                "paddle_tpu_serving_journal_update_seconds",
                "Host seconds per incremental journal flush (dirty-rid "
                "entry rebuilds overlapped with the in-flight device "
                "step, docs/async_runtime.md)").labels(**self._obs_labels)
            self._tracer = RequestTracer(
                enabled=True,
                pid=int(replica) if replica is not None else 0,
                process_name=obs_name)
        else:
            self.metrics = None
            self.slo = None
            self._h_hostgap = self._h_step = self._h_h2d = None
            self._h_jupdate = None
            self._tracer = RequestTracer(enabled=False)
            self.stats = {k: (0.0 if kind == "gauge" else 0)
                          for k, (kind, _) in ENGINE_STAT_SCHEMA.items()}
        self._last_step_end = None     # host-gap histogram anchor
        # flight recorder: bounded ring of recent engine events, dumped
        # (with a metrics snapshot) on request failure / audit error —
        # chaos triage without a rerun.  Independent kill switch.
        self._flight = (FlightRecorder(registry=self.metrics, name=obs_name)
                        if flight_recorder_enabled() else None)
        # opt-in runtime invariant auditor (PADDLE_TPU_ENGINE_AUDIT=1):
        # cross-checks allocator / block-table / prefix-cache bookkeeping
        # after admission and after every decode chunk, raising
        # EngineAuditError on corruption (analysis/engine_audit.py)
        from ..analysis.engine_audit import audit_enabled

        self._audit_every_step = audit_enabled()
        # ---- async host runtime (docs/async_runtime.md) ----
        # Incremental event-sourced journal: _jentries mirrors what
        # snapshot() would emit per live rid, maintained in O(changed
        # rids) — every admission / token bank / chunk-cursor advance /
        # terminal marks the rid dirty and _jflush rebuilds just those
        # entries.  The flush runs inside _host_overlap(), i.e. while
        # the device executes the already-launched step, so steady-state
        # journal upkeep costs the host-gap nothing.  The dirty marks
        # themselves are unconditional (a set.add); the flag only gates
        # the overlap window and the fleet's consumption, so
        # PADDLE_TPU_ASYNC_HOST=0 leaves the serial loop byte-identical.
        self._async_host = env_bool("PADDLE_TPU_ASYNC_HOST", True)
        self._jentries: dict[int, dict] = {}
        self._jdirty: set[int] = set()

    # ------------- tensor-parallel wrapping (docs/tp_serving.md) -----------

    #: argnums every compiled step donates (cache_k, cache_v) — shared
    #: between _jit_step and the static-telemetry trace, which rebuilds
    #: the donation mask for an unjitted trace of the same program
    _STEP_DONATE_ARGNUMS = (1, 2)

    def _jit_step(self, impl, n_rep: int, **statics):
        """jit one ``(params, cache_k, cache_v, *data[, poison=...])``
        compiled step with the standard cache donation.  Single-chip
        (``tp == 1``): exactly the pre-TP ``jax.jit(functools.partial(...))``
        — byte-identical programs.  TP: the SAME per-shard body runs inside
        shard_map (``_tp_shard``); ``n_rep`` is the number of leading
        replicated outputs before the two cache pools."""
        body = functools.partial(impl, **statics)
        donate = self._STEP_DONATE_ARGNUMS
        if self.tp == 1:
            return jax.jit(body, donate_argnums=donate)
        return jax.jit(self._tp_shard(body, n_rep), donate_argnums=donate)

    def _tp_shard(self, body, n_rep: int):
        """shard_map a compiled-step body over the 1-D ``("tp",)`` mesh.

        Operand contract: ``params`` take the Megatron specs
        (models/llama.serving_param_specs — QKV/gate/up column-split,
        O/down row-split, embed/norms/lm_head replicated), the two KV pools
        shard **kv_heads** (the axis the paged-attention page walk is
        blind to), and every other operand — tokens, positions, active
        mask, sampling knobs, the block table, poison bits — replicates:
        the scheduler stays host-side and identical on every shard.
        Outputs: ``n_rep`` replicated leaves (tokens/counts/guard flags —
        every shard computes the identical full [B, V] logits row after
        the per-layer psums, so the sampler's choice agrees by
        construction) followed by the two sharded pools.  The body is the
        byte-same single-chip program over tp-local head counts; its only
        collectives are transformer_apply's two per-layer psums."""
        from jax.sharding import PartitionSpec as P

        mesh, pspec, cspec = self._mesh, self._param_specs, self._cache_spec

        def run(params, cache_k, cache_v, *data, poison=None):
            extra = (poison,) if poison is not None else ()
            if poison is None:
                fn = body
            else:
                def fn(*a):
                    return body(*a[:-1], poison=a[-1])
            in_specs = ((pspec, cspec, cspec)
                        + (P(),) * (len(data) + len(extra)))
            out_specs = (P(),) * n_rep + (cspec, cspec)
            return _shard_map(fn, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_rep=False)(
                params, cache_k, cache_v, *data, *extra)

        return run

    def _tp_shard_prefill(self, impl):
        """shard_map wrapper for the prefill-family impls
        ``(params, ids, cache_k, cache_v, *data, bucket)`` — same operand
        contract as ``_tp_shard`` (ids/table rows/lengths replicate, pools
        shard kv_heads, no replicated outputs), with the trailing static
        ``bucket`` closed over so the shard_map region is purely
        array-in/array-out."""
        from jax.sharding import PartitionSpec as P

        mesh, pspec, cspec = self._mesh, self._param_specs, self._cache_spec

        def run(params, ids, cache_k, cache_v, *rest):
            data, bucket = rest[:-1], rest[-1]

            def fn(p, i, ck, cv, *d):
                return impl(p, i, ck, cv, *d, bucket)

            in_specs = (pspec, P(), cspec, cspec) + (P(),) * len(data)
            return _shard_map(fn, mesh=mesh, in_specs=in_specs,
                              out_specs=(cspec, cspec), check_rep=False)(
                params, ids, cache_k, cache_v, *data)

        return run

    # ---------------- compiled programs ----------------

    def _decode_one(self, params, cache_k, cache_v, tokens, pos, active,
                    table=None):
        """One batched decode step: tokens [B], pos [B], active [B] ->
        (logits [B, V], caches).  Inactive slots compute garbage that is
        masked out — the static batch is the price of a single compiled
        program, and idle lanes are cheap next to recompiling (the standard
        TPU serving trade).

        With ``table`` (paged mode) the K/V write lands in pool page
        table[b, pos//bs] at offset pos%bs and attention reads a gathered
        [B, nkv, max_seq, hd] view of each slot's pages (the reference's
        block_multihead_attention memory model; the gather fuses into the
        attention contraction).  On the fused default (``self._fused``,
        docs/paged_attention.md) rope + the page append + split-K
        attention run as ONE Pallas launch per layer instead — dropped
        writes land on the pool's spill page."""
        from .. import inference as _inf
        from ..ops.pallas import rope as rope_mod

        cfg = self._body_cfg    # TP: tp-local head counts (else self.cfg)
        B = self.max_batch
        S = self.max_seq
        nkv, hd = cfg.num_key_value_heads, cfg.head_dim
        x = jnp.take(params["embed"], tokens[:, None], axis=0).astype(cfg.dtype)
        cos_full, sin_full = rope_mod.rope_cos_sin(S, cfg.head_dim,
                                                   base=cfg.rope_theta,
                                                   dtype=cfg.dtype)
        safe_pos = jnp.where(active & (pos < S), pos, 0)
        cos = jnp.take(cos_full[0], safe_pos, axis=0)[:, None]  # [B, 1, d]
        sin = jnp.take(sin_full[0], safe_pos, axis=0)[:, None]
        kv_pos = jnp.arange(S)[None, None, None, None, :]
        mask = ((kv_pos <= pos[:, None, None, None, None])
                & active[:, None, None, None, None])
        lane = jnp.arange(B)
        writeable = active & (pos < S)
        attend_fn = None
        fused_fn = None

        if table is None:
            def write(ck, k):
                # ck [B, nkv, S, hd]; k [B, 1, nkv, hd] — per-slot scatter at
                # each slot's own depth (drop writes from inactive/oob lanes)
                upd = jnp.where(writeable[:, None, None], k[:, 0],
                                ck[lane, :, safe_pos])
                out = ck.at[lane, :, safe_pos].set(upd)
                return out, out
        elif self.kv_quant is not None:
            # quantized KV pools (docs/paged_attention.md "Megastep
            # stage 2"): pools are {"q": codes, "scale": per-page f32}
            # pytrees.  The kill-switched arm appends via the requant-
            # scatter composition (the scatter pair the fused path
            # eliminates) and attends dequant-on-read through the paged
            # front door (which itself falls back to the quant gather
            # oracle off-TPU-shapes / under =paged_attention); the fused
            # default runs rope + requantized append + attention in ONE
            # launch with codes AND scales committed through aliased
            # outputs.
            from ..ops import decode_attention as _da
            from ..ops.pallas import paged_attention as _pa

            bs_ = self.block_size
            kvq = self.kv_quant
            nh = cfg.num_attention_heads
            blk = table[lane, safe_pos // bs_]                   # [B]
            off = safe_pos % bs_
            seq_now = safe_pos + 1  # incl. the token written this step

            def write(ck, k):
                qp, sc = _pa.quant_append_decode(ck["q"], ck["scale"],
                                                 k[:, 0], blk, off,
                                                 writeable, kvq)
                out = {"q": qp, "scale": sc}
                return out, out

            def attend_fn(q, k_pool, v_pool):
                o = _da.paged_decode_attention(
                    q[:, 0], k_pool["q"], v_pool["q"], table, seq_now,
                    kv_quant=kvq, k_scale=k_pool["scale"],
                    v_scale=v_pool["scale"])
                return o.reshape(B, 1, nh * hd)

            if self._fused:
                spill = jnp.int32(self.num_blocks)
                wblk = jnp.where(writeable, jnp.minimum(blk, spill), spill)
                lens_pre = safe_pos   # append position; inactive lanes 0

                def fused_fn(q, k, v, ck, cv):
                    # q [B, 1, nh, hd] / k, v [B, 1, nkv, hd] PRE-rope
                    o, kq, ksc, vq, vsc = _da.fused_paged_quant_decode_step(
                        q[:, 0], k[:, 0], v[:, 0], cos[:, 0], sin[:, 0],
                        ck["q"], ck["scale"], cv["q"], cv["scale"],
                        table, lens_pre, wblk, writeable, kvq)
                    return (o.reshape(B, 1, nh * hd),
                            {"q": kq, "scale": ksc},
                            {"q": vq, "scale": vsc})
        else:
            from ..ops import decode_attention as _da
            from ..ops.pallas import paged_attention as _pa

            bs_ = self.block_size
            blk = table[lane, safe_pos // bs_]                   # [B]
            off = safe_pos % bs_
            drop_blk = jnp.where(writeable, blk, self.num_blocks)  # oob -> drop
            nh = cfg.num_attention_heads
            # trace-time dispatch: the ragged Pallas kernel walks only each
            # slot's live pages (PADDLE_TPU_DISABLE_PALLAS=paged_attention
            # routes back to the gather oracle below)
            use_kernel = _pa.kernel_supported(nh, nkv, hd, bs_)

            def write(ck, k):
                # ck [num_blocks, nkv, bs, hd].  Allocator invariant:
                # distinct slots own disjoint pages — no scatter collisions.
                out = ck.at[drop_blk, :, off].set(k[:, 0], mode="drop")
                if use_kernel:
                    # attention reads the paged pool directly — no
                    # [B, nkv, S, hd] gather materializes per layer per step
                    return out, out
                # unallocated (sentinel) pages read as ZEROS — jnp.take's
                # default oob mode fills NaN, and 0*NaN through the masked
                # softmax would poison the whole row
                view = jnp.take(out, table, axis=0, mode="fill", fill_value=0)
                view = view.transpose(0, 2, 1, 3, 4).reshape(B, nkv, S, hd)
                return out, view

            if self._fused and use_kernel:
                # decode megastep stage 1: rope + page append + split-K
                # attention in ONE Pallas launch per layer (docs/
                # paged_attention.md "Fused decode step").  Dropped writes
                # (inactive lanes, pos >= max_seq) land on the pool's
                # spill page — the ctor sized the pool with it.
                spill = jnp.int32(self.num_blocks)
                wblk = jnp.where(writeable, jnp.minimum(blk, spill), spill)
                lens_pre = safe_pos   # append position; inactive lanes 0

                def fused_fn(q, k, v, ck, cv):
                    # q [B, 1, nh, hd] / k, v [B, 1, nkv, hd] PRE-rope
                    o, ck, cv = _da.fused_paged_decode_step(
                        q[:, 0], k[:, 0], v[:, 0], cos[:, 0], sin[:, 0],
                        ck, cv, table, lens_pre, wblk, writeable)
                    return o.reshape(B, 1, nh * hd), ck, cv
            elif use_kernel:
                seq_now = safe_pos + 1  # incl. the token written this step

                def attend_fn(q, k_pool, v_pool):
                    # q [B, 1, nh, hd] post-rope; sentinel table entries are
                    # clamped in-kernel and masked by seq_now; inactive
                    # lanes attend one stale position (finite, masked out
                    # downstream like the dense path's garbage lanes)
                    o = _da.paged_decode_attention(q[:, 0], k_pool, v_pool,
                                                   table, seq_now)
                    return o.reshape(B, 1, nh * hd)

        mlp_fused_fn = None
        if table is not None and self._fused_mlp:
            # decode megastep stage 2: the post-attention layer half
            # (residual + post RMSNorm + SwiGLU MLP) as ONE launch per
            # layer through the decoder_layer_tail seam — with it, a
            # decode layer is two Pallas launches separated only by the
            # TP psum boundaries.  PADDLE_TPU_DISABLE_PALLAS=
            # fused_layer_mlp restores the stage-1 program byte-
            # identically (mlp_fused_fn stays None).
            from ..ops.pallas import paged_attention as _pa_mlp

            def mlp_fused_fn(h_res, attn_y, lp):
                # [B, 1, h] <-> [B, h]: the decode step's single live row
                h1, y = _pa_mlp.fused_layer_mlp(
                    h_res[:, 0], attn_y[:, 0], lp["post_norm"],
                    lp["w_gate"], lp["w_up"], lp["w_down"],
                    cfg.rms_norm_eps)
                return h1[:, None], y[:, None]

        x, ak, av = _inf.transformer_apply(cfg, params, x, cache_k, cache_v,
                                           write, mask, cos, sin,
                                           attend_fn=attend_fn,
                                           tp_axis=self._tp_axis,
                                           fused_fn=fused_fn,
                                           mlp_fused_fn=mlp_fused_fn)
        return _inf.lm_head_logits(cfg, params, x[:, -1]), ak, av

    def _quant_rows_write(self, table, row_pos, valid, view=True):
        """write_fn factory for MULTI-row events into quantized KV pools
        (docs/paged_attention.md "Megastep stage 2"): bucketed/prefix
        prefill (``view=True`` — the dense attend reads a dequantized
        gathered view of the slot's pages, batch-1) and the verify/mixed
        steps (``view=False`` — the paged front doors read the raw pool
        pytree).  The append itself is the page-batched requantize
        (ops/pallas/paged_attention.quant_append_rows): only dirty pages
        rewrite, so shared prefix pages keep their exact bytes."""
        from ..ops.pallas import paged_attention as _pa

        cfg = self._body_cfg    # TP: tp-local head counts (else self.cfg)
        S = self.max_seq
        nkv, hd = cfg.num_key_value_heads, cfg.head_dim
        kvq = self.kv_quant

        def write(ck, k):
            qp, sc = _pa.quant_append_rows(ck["q"], ck["scale"], k, table,
                                           row_pos, valid, kvq)
            out = {"q": qp, "scale": sc}
            if not view:
                return out, out
            # sentinel pages read as zeros (codes 0 * scale 0), matching
            # the fp path's fill_value=0 gather
            codes = jnp.take(qp, table[0], axis=0, mode="fill",
                             fill_value=0)
            scales = jnp.take(sc, table[0], axis=0, mode="fill",
                              fill_value=0.0)
            v = _pa._dequant_page_content(codes, scales, kvq)
            v = v.transpose(1, 0, 2, 3).reshape(1, nkv, S, hd)
            return out, v.astype(cfg.dtype)

        return write

    def _sample_tokens(self, logits, pos, temp, topp, seeds):
        """Per-slot next-token choice inside the compiled step: greedy where
        temperature == 0, temperature + nucleus (top-p) sampling elsewhere
        (reference: top_p_sampling, ops.yaml:4947).  The RNG key is derived
        deterministically from (slot seed, position): sampling is replayable,
        and a preempted-then-resumed request continues its stream exactly
        (resume teacher-forces the stored tokens, then position-derived keys
        make the continuation draw what it would have drawn)."""
        B = self.max_batch
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        scaled = (logits.astype(jnp.float32)
                  / jnp.maximum(temp, 1e-6)[:, None])
        # nucleus mask via sorted cumsum: keep the smallest prefix of
        # descending-prob tokens whose mass reaches top_p (top-1 always kept)
        order = jnp.argsort(-scaled, axis=-1)
        sprob = jax.nn.softmax(jnp.take_along_axis(scaled, order, axis=-1),
                               axis=-1)
        keep_sorted = (jnp.cumsum(sprob, axis=-1) - sprob) < topp[:, None]
        keep = jnp.zeros_like(keep_sorted).at[
            jnp.arange(B)[:, None], order].set(keep_sorted)
        masked = jnp.where(keep, scaled, -jnp.inf)
        keys = jax.vmap(lambda s, p: jax.random.fold_in(
            jax.random.fold_in(jax.random.PRNGKey(0), s), p))(seeds, pos)
        sampled = jax.vmap(jax.random.categorical)(keys, masked)
        return jnp.where(temp > 0.0, sampled.astype(jnp.int32), greedy)

    def _guard_logits(self, logits, active, poison):
        """In-graph NaN/inf logit guard (graceful mode only): flag every
        ACTIVE slot whose logits row is non-finite — numerically poisoned by
        the model, or by the ``nan_logits`` fault-injection poison bit —
        and replace the row with zeros so the sampler stays finite (the
        host discards a flagged slot's token and quarantines the request).
        Pure element-wise ops: no callback, no host sync — the flags ride
        back with the step's tokens in the same device fetch.  Inactive
        lanes are excluded: their garbage logits may be legitimately
        non-finite (fully-masked softmax rows).  The poison bit is applied
        FIRST, turning the slot's row genuinely NaN, so injection exercises
        the same finiteness check a real numerical blowup hits — never a
        parallel flag-only path."""
        row = jnp.where(poison, jnp.float32(jnp.nan), jnp.float32(0.0))
        logits = logits + row[:, None].astype(logits.dtype)
        bad = active & ~jnp.isfinite(logits).all(axis=-1)
        return jnp.where(bad[:, None], jnp.zeros_like(logits), logits), bad

    def _chunk_scan(self, params, cache_k, cache_v, tokens, pos, active,
                    temp, topp, seeds, table=None, poison=None,
                    sampling=False, graceful=False):
        """``chunk`` decode steps in one compiled program; the chosen token
        feeds back on-device (no host round-trip inside the chunk).
        ``sampling`` is STATIC: the greedy variant compiles without the
        sampler's full-vocab sort.  ``graceful`` is STATIC too: off, the
        program is byte-identical to the pre-fault-tolerance engine; on, a
        ``poison`` operand feeds the in-graph NaN/inf guard and per-step
        guard flags [chunk, B] come back with the tokens.  Returns
        (tokens [chunk, B][, bad [chunk, B]], caches)."""
        if graceful and poison is None:
            # direct callers (lint targets, tests) may omit the injection
            # operand; a zeros vector traces the same guarded program
            poison = jnp.zeros_like(active)

        def one(carry, _):
            ck, cv, tok, p = carry
            logits, ck, cv = self._decode_one(params, ck, cv, tok, p, active,
                                              table)
            if graceful:
                logits, bad = self._guard_logits(logits, active, poison)
            if sampling:
                nxt = self._sample_tokens(logits, p, temp, topp, seeds)
            else:
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return ((ck, cv, nxt, p + 1),
                    (nxt, bad) if graceful else nxt)

        (ck, cv, _, _), out = jax.lax.scan(
            one, (cache_k, cache_v, tokens, pos), None, length=self.chunk)
        if graceful:
            toks, bad = out
            return toks, bad, ck, cv
        return out, ck, cv

    def _decode_impl(self, params, cache_k, cache_v, tokens, pos, active,
                     temp, topp, seeds, poison=None, sampling=False,
                     graceful=False):
        return self._chunk_scan(params, cache_k, cache_v, tokens, pos, active,
                                temp, topp, seeds, poison=poison,
                                sampling=sampling, graceful=graceful)

    def _prefill_body(self, params, ids, cache_k, cache_v, length, bucket,
                      write, start=None):
        """Shared prefill: embed/rope/mask once, write-path injected (dense
        lane vs paged block table) so mask/rope fixes cannot diverge.

        Tokens at or beyond ``length`` are padding and masked out of attention
        (they still write cache positions, which the causal mask makes
        unreachable until the slot's pos pointer passes them — it never does,
        decode overwrites).  No logits are computed: the last real prompt
        token is fed to the first decode step instead (standard split).

        ``start`` (traced scalar, prefix-cache hits only): ``ids`` holds
        tokens at ABSOLUTE positions start..start+bucket-1 — rope tables and
        the causal mask shift accordingly, and ``length`` stays the absolute
        total.  ``start=None`` keeps the original program byte-for-byte."""
        from .. import inference as _inf
        from ..ops.pallas import rope as rope_mod

        cfg = self._body_cfg    # TP: tp-local head counts (else self.cfg)
        S = self.max_seq
        x = jnp.take(params["embed"], ids, axis=0).astype(cfg.dtype)
        cos_full, sin_full = rope_mod.rope_cos_sin(S, cfg.head_dim,
                                                   base=cfg.rope_theta,
                                                   dtype=cfg.dtype)
        if start is None:
            cos = cos_full[:, :bucket]
            sin = sin_full[:, :bucket]
            q_pos = jnp.arange(bucket)[None, None, None, :, None]
        else:
            pos_j = start + jnp.arange(bucket)      # absolute positions
            safe_j = jnp.minimum(pos_j, S - 1)      # bucket may overrun S
            cos = jnp.take(cos_full[0], safe_j, axis=0)[None]
            sin = jnp.take(sin_full[0], safe_j, axis=0)[None]
            q_pos = pos_j[None, None, None, :, None]
        kv_pos = jnp.arange(S)[None, None, None, None, :]
        mask = (kv_pos <= q_pos) & (kv_pos < length)
        _, ak, av = _inf.transformer_apply(cfg, params, x, cache_k, cache_v,
                                           write, mask, cos, sin,
                                           tp_axis=self._tp_axis)
        return ak, av

    def _prefill_impl(self, params, ids, cache_k, cache_v, slot, length, bucket):
        """Prefill one request (batch 1, prompt padded to ``bucket``) directly
        into lane ``slot`` of the (donated) cache pools."""
        cfg = self._body_cfg    # TP: tp-local head counts (else self.cfg)
        S = self.max_seq
        nkv = cfg.num_key_value_heads

        def write(ck, k):
            # ck [B, nkv, S, hd] pool layer; commit this request's K/V
            # into lane `slot` positions [0:bucket], attend on that lane
            out = jax.lax.dynamic_update_slice(
                ck, k.transpose(0, 2, 1, 3), (slot, 0, 0, 0))
            view = jax.lax.dynamic_slice(
                out, (slot, 0, 0, 0), (1, nkv, S, cfg.head_dim))
            return out, view

        return self._prefill_body(params, ids, cache_k, cache_v, length,
                                  bucket, write)

    # ---------------- paged (block-table) compiled programs ----------------

    def _decode_impl_paged(self, params, cache_k, cache_v, tokens, pos, active,
                           temp, topp, seeds, table, poison=None,
                           sampling=False, graceful=False):
        return self._chunk_scan(params, cache_k, cache_v, tokens, pos, active,
                                temp, topp, seeds, table, poison=poison,
                                sampling=sampling, graceful=graceful)

    def _prefill_impl_paged(self, params, ids, cache_k, cache_v, table_row,
                            length, bucket):
        """Prefill into the slot's pages: prompt position j writes page
        table_row[j // bs] offset j % bs; padding positions whose page is
        the unallocated sentinel drop (and are masked from attention)."""
        cfg = self._body_cfg    # TP: tp-local head counts (else self.cfg)
        S = self.max_seq
        bs_ = self.block_size
        nkv, hd = cfg.num_key_value_heads, cfg.head_dim
        j = jnp.arange(bucket)
        blk_j = table_row[j // bs_]                          # [bucket]
        off_j = j % bs_

        if self.kv_quant is not None:
            # mask PAD rows (j >= length), not just oob ones: a requant
            # write is not free like the fp scatter — a garbage pad row
            # in the prompt's tail page would inflate that page's absmax
            # scale and permanently coarsen the REAL rows' codes
            write = self._quant_rows_write(
                table_row[None], j[None, :],
                ((j < length) & (j < S))[None, :])
        else:
            def write(ck, k):
                # k [1, bucket, nkv, hd] -> scatter each prompt position
                # into its page; view = this slot's gathered pages, batch-1
                out = ck.at[blk_j, :, off_j].set(k[0], mode="drop")
                view = jnp.take(out, table_row, axis=0,  # [maxblk,nkv,bs,hd]
                                mode="fill", fill_value=0)  # sentinel -> 0
                view = view.transpose(1, 0, 2, 3).reshape(1, nkv, S, hd)
                return out, view

        return self._prefill_body(params, ids, cache_k, cache_v, length,
                                  bucket, write)

    def _prefill_impl_paged_prefix(self, params, ids, cache_k, cache_v,
                                   table_row, start, length, bucket):
        """Partial-bucket prefill for a prefix-cache hit: ``ids`` [1, bucket]
        holds the prompt's UNCACHED tail — tokens at ABSOLUTE positions
        start..start+bucket-1, padded to ``bucket`` (the only static arg, so
        compile variants stay log2-bounded; start/length are data).  Attention
        reads the full gathered view, whose leading pages are the shared
        cached prefix; writes land only at positions in [start, length), so a
        shared page is never written (COW at admission guarantees the first
        decode position's block is private too).  Embed/rope/mask come from
        the shared ``_prefill_body`` (its ``start`` mode) — only the
        position-offset page scatter lives here."""
        cfg = self._body_cfg    # TP: tp-local head counts (else self.cfg)
        S = self.max_seq
        bs_ = self.block_size
        nkv, hd = cfg.num_key_value_heads, cfg.head_dim
        pos_j = start + jnp.arange(bucket)  # absolute positions  [bucket]
        safe_j = jnp.minimum(pos_j, S - 1)
        blk_j = table_row[safe_j // bs_]
        # padding (pos >= length) and anything past max_seq must not write
        blk_j = jnp.where((pos_j < length) & (pos_j < S), blk_j,
                          self.num_blocks)
        off_j = safe_j % bs_

        if self.kv_quant is not None:
            write = self._quant_rows_write(
                table_row[None], pos_j[None, :],
                ((pos_j < length) & (pos_j < S))[None, :])
        else:
            def write(ck, k):
                out = ck.at[blk_j, :, off_j].set(k[0], mode="drop")
                view = jnp.take(out, table_row, axis=0,  # [maxblk,nkv,bs,hd]
                                mode="fill", fill_value=0)
                view = view.transpose(1, 0, 2, 3).reshape(1, nkv, S, hd)
                return out, view

        return self._prefill_body(params, ids, cache_k, cache_v, length,
                                  bucket, write, start=start)

    # ---------------- speculative verify (compiled program) ----------------

    def _verify_one(self, params, cache_k, cache_v, tokens, pos, active,
                    q_lens, table):
        """One multi-token verify forward: tokens [B, Q] (row 0 = the pending
        last token, rows 1.. = n-gram drafts), pos [B] (row 0's write
        position), q_lens [B] live rows per slot -> (logits [B, Q, V],
        caches).  The multi-token analog of ``_decode_one``: every row's K/V
        is scattered into its page at absolute position pos+t (row t of a
        slot with t >= q_lens, an inactive lane, or a position past max_seq
        drops), and attention runs the ragged verify kernel over the paged
        pool — one weight stream from HBM serves up to Q tokens per slot,
        which is the speculative win in bandwidth-bound decode."""
        from .. import inference as _inf
        from ..ops import decode_attention as _da
        from ..ops.pallas import rope as rope_mod

        cfg = self._body_cfg    # TP: tp-local head counts (else self.cfg)
        B = self.max_batch
        S = self.max_seq
        Q = tokens.shape[1]
        nh = cfg.num_attention_heads
        bs_ = self.block_size
        x = jnp.take(params["embed"], tokens, axis=0).astype(cfg.dtype)
        cos_full, sin_full = rope_mod.rope_cos_sin(S, cfg.head_dim,
                                                   base=cfg.rope_theta,
                                                   dtype=cfg.dtype)
        pos_t = pos[:, None] + jnp.arange(Q)[None, :]          # [B, Q] abs
        valid_t = (active[:, None] & (jnp.arange(Q)[None, :] < q_lens[:, None])
                   & (pos_t < S))
        safe_t = jnp.where(valid_t, pos_t, 0)
        cos = jnp.take(cos_full[0], safe_t, axis=0)            # [B, Q, d]
        sin = jnp.take(sin_full[0], safe_t, axis=0)
        lane = jnp.arange(B)[:, None]
        blk = table[lane, safe_t // bs_]                       # [B, Q]
        off = safe_t % bs_
        drop_blk = jnp.where(valid_t, blk, self.num_blocks)    # oob -> drop

        if self.kv_quant is not None:
            write = self._quant_rows_write(table, pos_t, valid_t,
                                           view=False)
        else:
            def write(ck, k):
                # ck [num_blocks, nkv, bs, hd]; k [B, Q, nkv, hd].
                # Allocator invariant: distinct slots own disjoint pages,
                # distinct rows hit distinct positions — no scatter
                # collisions among live writes.
                out = ck.at[drop_blk, :, off].set(k, mode="drop")
                # the verify kernel reads the paged pool directly (no
                # gathered view materializes; its fallback oracle gathers
                # internally)
                return out, out

        # total written length per slot incl. every draft; inactive lanes
        # attend one stale position (finite, masked out downstream like the
        # dense path's garbage lanes)
        seq_base = jnp.where(active & (pos < S), pos, 0)
        seq_now = jnp.minimum(seq_base + jnp.where(active, q_lens, 1), S)

        def attend_fn(q, k_pool, v_pool):
            # q [B, Q, nh, hd] post-rope
            if self.kv_quant is not None:
                # verify is the T = K+1 special case of the chunked-
                # prefill kernel, and ONLY the prefill member carries
                # dequant-on-read (docs/chunked_prefill.md) — quantized
                # verify routes through it rather than growing a fourth
                # kernel variant (identical mask law, same page walk)
                o = _da.paged_prefill_attention(
                    q, k_pool["q"], v_pool["q"], table, seq_now, q_lens,
                    kv_quant=self.kv_quant, k_scale=k_pool["scale"],
                    v_scale=v_pool["scale"])
            else:
                o = _da.paged_verify_attention(q, k_pool, v_pool, table,
                                               seq_now, q_lens)
            return o.reshape(B, Q, nh * cfg.head_dim)

        x, ak, av = _inf.transformer_apply(cfg, params, x, cache_k, cache_v,
                                           write, None, cos, sin,
                                           attend_fn=attend_fn,
                                           tp_axis=self._tp_axis)
        return _inf.lm_head_logits(cfg, params, x), ak, av

    def _verify_impl_paged(self, params, cache_k, cache_v, tokens, pos,
                           active, q_lens, temp, topp, seeds, table,
                           poison=None, sampling=False, graceful=False):
        """Verify + accept in ONE compiled program.  Row t's logits condition
        on draft tokens <= t; the emitted token for position pos+t+1 is drawn
        with the SAME (seed, pos+t)-derived key ``_sample_tokens`` would use
        in the non-speculative step — so row 0's token is always what plain
        decode would have produced, and each draft is accepted exactly when
        it equals that token.  The accepted stream is therefore
        token-identical to the non-speculative engine (greedy AND seeded
        sampled), not merely distribution-preserving.  Returns
        (out [B, Q] chosen tokens per row, n_emitted [B] in 1..q_lens,
        caches); host code consumes out[:, :n_emitted]."""
        logits, ck, cv = self._verify_one(params, cache_k, cache_v, tokens,
                                          pos, active, q_lens, table)
        Q = tokens.shape[1]
        if graceful:
            # per-slot guard over the LIVE rows only (rows past q_lens are
            # computed from garbage positions and may be legitimately
            # non-finite); a flagged slot's whole verify output is discarded
            # by the host, so one [B] flag per slot suffices
            if poison is None:
                poison = jnp.zeros_like(active)
            # poison bit FIRST, as a genuinely NaN row (same contract as
            # _guard_logits): injection exercises the finiteness check a
            # real numerical blowup hits — never a parallel flag-only path
            row = jnp.where(poison, jnp.float32(jnp.nan), jnp.float32(0.0))
            logits = logits + row[:, None, None].astype(logits.dtype)
            live = jnp.arange(Q)[None, :] < q_lens[:, None]
            rowbad = (~jnp.isfinite(logits).all(axis=-1)) & live
            bad = active & rowbad.any(axis=-1)
            logits = jnp.where(bad[:, None, None], jnp.zeros_like(logits),
                               logits)
        if sampling:
            pos_t = pos[:, None] + jnp.arange(Q)[None, :]
            out = jax.vmap(
                lambda lg, p: self._sample_tokens(lg, p, temp, topp, seeds),
                in_axes=(1, 1), out_axes=1)(logits, pos_t)
        else:
            out = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        # acceptance: draft t+1 survives iff it equals the token the target
        # chose at row t AND every earlier draft survived (leading-run via
        # cumprod); row 0 is always emitted.  t+1 < q_lens bounds n_emitted
        # by the slot's live rows, so padding rows can never count.
        ok = ((tokens[:, 1:] == out[:, :-1])
              & (jnp.arange(1, Q)[None, :] < q_lens[:, None]))
        n_emitted = 1 + jnp.cumprod(ok.astype(jnp.int32), axis=1).sum(axis=1)
        if graceful:
            # the guard flags ride back with the step's tokens — no extra
            # device fetch; the host quarantines flagged slots
            return out, n_emitted.astype(jnp.int32), bad, ck, cv
        return out, n_emitted.astype(jnp.int32), ck, cv

    # -------- unified mixed prefill/decode step (compiled program) --------

    def _mixed_one(self, params, cache_k, cache_v, tokens, pos, active,
                   q_lens, table):
        """One unified prefill/decode forward: tokens [B, T] (row t of slot
        b = the token at absolute position pos[b]+t), pos [B] row-0
        positions, q_lens [B] live rows -> (emit-row logits [B, V], caches).
        Decode-ready slots ride as q_lens == 1 lanes (row 0 = the pending
        token — exactly ``_decode_one``'s computation at their position);
        prefilling slots carry a prefill_chunk-row slice of their prompt.
        Every live row's K/V scatters into its page and attention runs the
        ragged chunked-prefill kernel (per-row visibility pos+t+1 — the
        verify kernel's causal law with T free).  ONLY each slot's last
        live row projects through the lm_head: a mid-prompt chunk's emit is
        garbage the host ignores, the FINAL chunk's emit row sits at the
        last prompt token's position so its logits ARE the first decode
        step's (TTFT costs no extra launch), and a [B, V] head is T times
        cheaper than the [B, T, V] one the mixed step never needs."""
        from .. import inference as _inf
        from ..ops import decode_attention as _da
        from ..ops.pallas import rope as rope_mod

        cfg = self._body_cfg    # TP: tp-local head counts (else self.cfg)
        B = self.max_batch
        S = self.max_seq
        T = tokens.shape[1]
        nh = cfg.num_attention_heads
        bs_ = self.block_size
        x = jnp.take(params["embed"], tokens, axis=0).astype(cfg.dtype)
        cos_full, sin_full = rope_mod.rope_cos_sin(S, cfg.head_dim,
                                                   base=cfg.rope_theta,
                                                   dtype=cfg.dtype)
        pos_t = pos[:, None] + jnp.arange(T)[None, :]          # [B, T] abs
        valid_t = (active[:, None] & (jnp.arange(T)[None, :] < q_lens[:, None])
                   & (pos_t < S))
        safe_t = jnp.where(valid_t, pos_t, 0)
        cos = jnp.take(cos_full[0], safe_t, axis=0)            # [B, T, d]
        sin = jnp.take(sin_full[0], safe_t, axis=0)
        lane = jnp.arange(B)[:, None]
        blk = table[lane, safe_t // bs_]                       # [B, T]
        off = safe_t % bs_
        drop_blk = jnp.where(valid_t, blk, self.num_blocks)    # oob -> drop

        if self.kv_quant is not None:
            write = self._quant_rows_write(table, pos_t, valid_t,
                                           view=False)
        else:
            def write(ck, k):
                # ck [num_blocks, nkv, bs, hd]; k [B, T, nkv, hd].
                # Allocator invariant: distinct slots own disjoint pages,
                # distinct rows hit distinct positions — no scatter
                # collisions among live writes; the kernel reads the paged
                # pool directly.
                out = ck.at[drop_blk, :, off].set(k, mode="drop")
                return out, out

        # total written length per slot incl. this chunk; inactive lanes
        # attend one stale position (finite, masked out downstream like the
        # dense path's garbage lanes)
        seq_base = jnp.where(active & (pos < S), pos, 0)
        seq_now = jnp.minimum(seq_base + jnp.where(active, q_lens, 1), S)

        def attend_fn(q, k_pool, v_pool):
            # q [B, T, nh, hd] post-rope (the prefill kernel's kv_quant
            # mode dequantizes quantized pools on read)
            if self.kv_quant is not None:
                o = _da.paged_prefill_attention(
                    q, k_pool["q"], v_pool["q"], table, seq_now, q_lens,
                    kv_quant=self.kv_quant, k_scale=k_pool["scale"],
                    v_scale=v_pool["scale"])
            else:
                o = _da.paged_prefill_attention(q, k_pool, v_pool, table,
                                                seq_now, q_lens)
            return o.reshape(B, T, nh * cfg.head_dim)

        x, ak, av = _inf.transformer_apply(cfg, params, x, cache_k, cache_v,
                                           write, None, cos, sin,
                                           attend_fn=attend_fn,
                                           tp_axis=self._tp_axis)
        last = jnp.take_along_axis(
            x, (q_lens - 1).astype(jnp.int32)[:, None, None], axis=1)[:, 0]
        return _inf.lm_head_logits(cfg, params, last), ak, av

    def _mixed_impl_paged(self, params, cache_k, cache_v, tokens, pos,
                          active, q_lens, temp, topp, seeds, table,
                          poison=None, sampling=False, graceful=False):
        """Mixed step + emit in ONE compiled program.  The emitted token for
        slot b is drawn from its emit row's logits with the SAME
        (seed, pos + q_lens - 1)-derived key ``_sample_tokens`` uses in the
        plain decode step at that position — so a decode lane's token
        (q_lens == 1, key (seed, pos)) and a completing prefill's first
        token (emit row at the last prompt token's position, the exact key
        the unchunked engine's first decode step derives) are
        token-identical to the bucketed-prefill engine, greedy AND seeded
        sampled.  Returns (next token [B], caches); the host consumes a
        lane's token only when it decoded or finished its prompt."""
        logits, ck, cv = self._mixed_one(params, cache_k, cache_v, tokens,
                                         pos, active, q_lens, table)
        if graceful:
            # the emit row is each slot's ONLY row through the lm_head: a
            # non-finite emit (numerical blowup or the nan_logits poison
            # bit) flags the slot; the host quarantines the request instead
            # of banking garbage.  One [B] flag, fetched with the tokens.
            if poison is None:
                poison = jnp.zeros_like(active)
            logits, bad = self._guard_logits(logits, active, poison)
        if sampling:
            nxt = self._sample_tokens(logits, pos + q_lens - 1, temp, topp,
                                      seeds)
        else:
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        if graceful:
            return nxt, bad, ck, cv
        return nxt, ck, cv

    # ---------------- block allocator (host control plane) ----------------

    def _blocks_needed(self, last_pos: int) -> int:
        return min(last_pos, self.max_seq - 1) // self.block_size + 1

    def _alloc_to(self, slot: int, n_blocks: int) -> bool:
        """Grow slot to n_blocks pages (shared cached prefix counts); False if
        the pool runs dry.  Under prefix caching, allocation pressure first
        LRU-evicts zero-ref cached blocks — eviction happens ONLY here, so
        resident hot prefixes are sacrificed last, never proactively."""
        owned = self._slot_blocks[slot]
        base = len(self._slot_shared[slot])
        if (base + len(owned) < n_blocks and self._faults
                and self._faults.fire("alloc_fail", step=self._step_no,
                                      slot=slot)):
            # allocator seam (faults.py): report the pool dry even though
            # pages may be free — drives the overload ladder adversarially
            # without needing a genuinely tiny pool.  Polled only when a
            # real grab would happen, so no-op calls never consume firings.
            if self._flight is not None:
                self._flight.record("fault", fault="alloc_fail", slot=slot,
                                    step=self._step_no)
            return False
        while base + len(owned) < n_blocks:
            if not self._free:
                # with the tier attached, reclaim the WHOLE remaining
                # deficit in one call: eviction demotes D2H, and one
                # batched gather per admission beats a serialized
                # per-page transfer ladder.  Tier-off keeps the one-page
                # pre-PR reclaim so page-assignment order — hence the
                # pool layout — stays byte-identical to the pre-tier
                # engine.
                want = (n_blocks - base - len(owned)
                        if self._tier is not None else 1)
                if not self._reclaim(want):
                    return False
            b = self._free.pop()
            self._table[slot, base + len(owned)] = b
            owned.append(b)
        return True

    def _reclaim(self, n: int) -> int:
        """Evict up to n zero-ref cached blocks into the free list.  With
        the host tier attached (docs/kv_tier.md), eviction DEMOTES instead
        of killing: every victim's page ships D2H under its chain hash
        before the page is recycled, so the chain stays re-admittable —
        the whole point of returning (hash, page) pairs from evict()."""
        if self._pcache is None:
            return 0
        with RecordEvent("prefix_cache/evict"):
            pairs = self._pcache.evict(n)
        if pairs:
            if self._tier is not None:
                self._demote(pairs)
            self._free.extend(page for _, page in pairs)
            self.stats["prefix_evictions"] += len(pairs)
            if self._flight is not None:
                self._flight.record("evict", pages=len(pairs))
        return len(pairs)

    # -------- hierarchical KV: demote / re-admit (docs/kv_tier.md) --------

    def _demote(self, pairs) -> None:
        """ship_out the evicted pages: ONE gathered device read for the
        whole batch, then per-page host slices into the tier.  np.asarray
        blocks on the D2H, so a later compiled step can never overwrite a
        page mid-demotion — the pages re-enter the free list only after
        their bytes are safe on the host.  A page the tier cannot fit
        (budget exhausted by pinned entries) goes dead, exactly the
        pre-tier eviction, counted by the tier's ``drops``."""
        with RecordEvent("kv_tier/demote"):
            idx = jnp.asarray([page for _, page in pairs], jnp.int32)
            owner = self._obs_labels.get("replica")
            if self.kv_quant is not None:
                # quantized pools demote codes + per-page scales together
                # (the tier's transport has carried scales since PR 12 —
                # byte-exact roundtrip asserted there)
                k_slab = np.asarray(self.cache_k["q"][:, idx])
                v_slab = np.asarray(self.cache_v["q"][:, idx])
                ks_slab = np.asarray(self.cache_k["scale"][:, idx])
                vs_slab = np.asarray(self.cache_v["scale"][:, idx])
                for i, (h, _page) in enumerate(pairs):
                    if self._tier.ship_out(h, k_slab[:, i], v_slab[:, i],
                                           k_scale=ks_slab[:, i],
                                           v_scale=vs_slab[:, i],
                                           owner=owner) is not None:
                        self.stats["tier_demotions"] += 1
            else:
                k_slab = np.asarray(self.cache_k[:, idx])
                v_slab = np.asarray(self.cache_v[:, idx])
                for i, (h, _page) in enumerate(pairs):
                    if self._tier.ship_out(h, k_slab[:, i], v_slab[:, i],
                                           owner=owner) is not None:
                        self.stats["tier_demotions"] += 1
        self.stats["tier_bytes"] = self._tier.used_bytes
        self.stats["tier_evictions"] = self._tier.evictions
        if self._flight is not None:
            self._flight.record("tier_demote", pages=len(pairs),
                                tier_bytes=int(self._tier.used_bytes))

    def _restore_tier_block(self, slot: int, req, ids, b: int, h: str,
                            parent: str | None) -> bool:
        """Re-admit ONE demoted block: allocate a free page, dispatch the
        async H2D pool writes (ship_in's device half), and register the
        block into the prefix cache with this slot holding a reference —
        from here on it is indistinguishable from a freshly-prefilled
        shared block.  False when the restore cannot proceed (pool dry,
        tier miss / injected ``tier_drop``, private pages ahead of the
        shared front): the caller falls back to ordinary prefill compute
        for the block — token-identical either way, the tier only ever
        changes who produces the bytes, never which bytes."""
        bs_ = self.block_size
        if self._faults and self._faults.fire("tier_drop",
                                              step=self._step_no,
                                              slot=slot, rid=req.rid):
            # chaos seam (faults.py): the entry vanishes between match
            # and ship_in — the engine must fall back to normal prefill,
            # never hang or corrupt
            self._tier.discard(h)
            if self._flight is not None:
                self._flight.record("fault", fault="tier_drop", slot=slot,
                                    step=self._step_no)
        if h in self._pcache._by_hash:
            # another slot restored or computed the same chain block since
            # this plan was made: map the HBM-resident copy instead (a
            # late HBM hit — strictly cheaper than the H2D)
            e = self._pcache._by_hash[h]
            self._pcache.acquire(e)
            self._table[slot, len(self._slot_shared[slot])] = e.page
            self._slot_shared[slot].append(h)
            return True
        if not self._free or self._slot_blocks[slot]:
            # pool pressure, or unregistered private pages ahead of the
            # shared front (a cache_error degradation left them there —
            # appending shared past them would break the [shared...,
            # private...] row layout): compute instead
            return False
        entry = self._tier.ship_in(h,
                                   owner=self._obs_labels.get("replica"))
        if entry is None:
            return False        # dropped or LRU-evicted: compute instead
        # storage-format guard (docs/paged_attention.md "Megastep
        # stage 2"): tier entries are keyed by token-chain hash alone, so
        # a SHARED fleet tier can hold pages demoted by a replica with a
        # different pool storage (fp vs int8 vs packed int4 — scales
        # present/absent, hd vs hd//2 payload, bf16 vs int8 dtype).
        # Restoring one would silently corrupt this engine's pool (the
        # donated page write casts); treat a mismatched entry as a miss
        # and compute the block instead — on a shared tier the entry
        # stays for compatible replicas
        pool = self.cache_k["q"] if self.kv_quant is not None \
            else self.cache_k
        page_shape = (pool.shape[0],) + pool.shape[2:]
        if ((entry.k_scale is not None) != (self.kv_quant is not None)
                or entry.k.shape != page_shape
                or entry.k.dtype != np.dtype(pool.dtype)):
            return False
        dst = self._free.pop()
        t0 = time.perf_counter()
        with RecordEvent("kv_tier/restore"):
            d = jnp.asarray(dst, jnp.int32)
            if self.kv_quant is not None:
                k_page = {"q": jnp.asarray(entry.k),
                          "scale": jnp.asarray(entry.k_scale)}
                v_page = {"q": jnp.asarray(entry.v),
                          "scale": jnp.asarray(entry.v_scale)}
            else:
                k_page, v_page = jnp.asarray(entry.k), jnp.asarray(entry.v)
            self.cache_k = self._tier_write(self.cache_k, d, k_page)
            self.cache_v = self._tier_write(self.cache_v, d, v_page)
        e = self._pcache.register(parent, ids[b * bs_:(b + 1) * bs_], dst,
                                  refcount=1)
        if e is None:
            # defensive: the parent left the index between plan and
            # restore — the page would be unreachable by radix descent;
            # hand it back and compute the block instead
            self._free.append(dst)
            return False
        self._table[slot, len(self._slot_shared[slot])] = dst
        self._slot_shared[slot].append(h)
        self.stats["tier_readmits"] += 1
        self.stats["tier_bytes"] = self._tier.used_bytes
        if self._h_h2d is not None:
            self._h_h2d.observe(time.perf_counter() - t0)
        if self._flight is not None:
            self._flight.record("tier_readmit", rid=req.rid, slot=slot,
                                block=b, page=dst)
        return True

    def _tier_restore_step(self, s: int, ids,
                           budget: int) -> tuple[int, int, bool]:
        """Advance slot ``s``'s prefill cursor through its pending
        tier-restore plan (the chunked path's ship_in driver): plan blocks
        the cursor already passed (computed by a fallback chunk) drop;
        while the cursor sits exactly at a planned block's boundary,
        restore it by H2D page copy and advance the cursor a whole block.
        "Restoring from host" is thereby scheduled exactly like
        "prefilling" — one cursor, zero new compiled step shapes,
        chunk-granular preemption/cancel compose for free, AND restores
        are paced by the step's token budget exactly like prefill rows
        (each restored block bills ``block_size`` tokens, with a
        one-block-per-step floor so plans always drain — a long demoted
        chain must not burst hundreds of H2D uploads into one step and
        recreate the decode stall chunked prefill exists to erase).  The
        H2D dispatch is async: donation order guarantees this step's
        mixed launch reads the restored pages, while the bytes stream in
        parallel with the host's packing work.  Returns ``(cursor,
        remaining budget, pending)`` — ``pending`` means a planned block
        still sits AT the cursor (deferred by the budget), so the caller
        must idle the lane this step instead of computing the block a
        later step will restore."""
        bs_ = self.block_size
        req = self._slot_req[s]
        plan = self._tier_plan[s]
        cur = int(self._prefilled[s])
        restored = 0
        while plan:
            b, h, _parent = plan[0]
            if b * bs_ < cur:
                plan.pop(0)                 # computed by a fallback chunk
                self._tier.unpin(h)
                continue
            if b * bs_ != cur:
                break                       # mid-block cursor: compute on
            if restored > 0 and budget < bs_:
                # budget drained: defer the rest of the plan to the next
                # step (the floor above already banked one block, so the
                # plan strictly drains — no livelock on a tiny budget)
                return cur, budget, True
            if not self._restore_tier_block(s, req, ids, b, h, _parent):
                # pool dry this step, or the entry vanished (tier_drop /
                # LRU): drop the WHOLE plan and fall back to prefill
                # compute — token-identical, never a hang
                self._drop_tier_plan(s)
                break
            plan.pop(0)
            self._tier.unpin(h)
            restored += 1
            budget = max(budget - bs_, 0)
            cur += bs_
            self._prefilled[s] = cur
            self._pos[s] = cur
            self._written[s] = max(int(self._written[s]), cur)
            # admission pre-counted the whole uncovered tail as computed
            # (it could not know which blocks the cursor would restore):
            # move this block's tokens to the cached column so the
            # prefill hit-rate reads what actually happened
            self.stats["prefill_tokens_computed"] -= bs_
            self.stats["prefill_tokens_cached"] += bs_
        return cur, budget, False

    def _drop_tier_plan(self, slot: int) -> None:
        """Invalidate a slot's pending tier-restore plan (preempt, cancel,
        terminal, restore fallback): unpin every remaining entry so the
        tier's LRU may reclaim them.  The cursor keeps whatever progress
        restores already banked — the blocks it covered are ordinary
        shared cache blocks now."""
        if self._tier is None:
            return
        for _b, h, _p in self._tier_plan[slot]:
            self._tier.unpin(h)
        self._tier_plan[slot] = []

    def _evictable(self) -> int:
        return self._pcache.evictable_count() if self._pcache is not None else 0

    def _release(self, slot: int):
        self._drop_tier_plan(slot)  # no-op tier-off / plan already drained
        self._free.extend(self._slot_blocks[slot])
        self._slot_blocks[slot] = []
        if self._slot_shared[slot]:
            # shared pages are refcounted, not freed: at zero refs they stay
            # resident in the cache until eviction needs them
            for h in self._slot_shared[slot]:
                self._pcache.release(h)
            self._slot_shared[slot] = []
        self._table[slot, :] = self.num_blocks

    def _register_prefix_blocks(self, slot: int, ids: np.ndarray,
                                valid_len: int):
        """After an admission's prefill: move the newly-computed full prompt
        blocks (beyond the matched shared prefix) into the cache with this
        slot holding a reference — a request admitted later in the SAME step
        already hits.  Transfers are a contiguous front of the private list,
        preserving the [shared..., private...] row layout."""
        bs_ = self.block_size
        n_shared = len(self._slot_shared[slot])
        limit = valid_len // bs_            # blocks fully written by prefill
        if limit <= n_shared:
            return
        if self._faults and self._faults.fire("cache_error",
                                              step=self._step_no, slot=slot):
            # prefix-cache seam (faults.py): a registration fault degrades
            # — the blocks stay private (a future request misses where it
            # could have hit) and NO request fails; graceful-off restores
            # the raise-out-of-step behavior
            if not self._graceful:
                raise FaultInjected(f"injected cache_error (step "
                                    f"{self._step_no}, slot {slot})")
            return
        # continue the chain from the mapped shared prefix — each new block
        # is hashed exactly once (inside register), nothing is re-hashed
        parent = self._slot_shared[slot][-1] if n_shared else None
        for b in range(n_shared, limit):
            e = self._pcache.register(parent, ids[b * bs_:(b + 1) * bs_],
                                      self._slot_blocks[slot][0], refcount=1)
            if e is None:
                # defensive only: in the single-threaded admit flow nothing
                # can insert between match() and here, and leaf-first
                # eviction can't orphan a parent mid-chain — but if either
                # invariant ever breaks, keeping the page private (freed by
                # _release) is the safe degradation
                break
            if self._tier is not None and not self._tier.shared:
                # a freshly-computed block whose demoted twin still sits
                # in a PRIVATE tier: drop the stale host copy — demote/
                # re-admit is move semantics there (I10's exactly-one
                # home; a shared tier keeps it for the other replicas)
                self._tier.discard(e.hash)
            parent = e.hash
            self._slot_blocks[slot].pop(0)
            self._slot_shared[slot].append(e.hash)

    def _register_retired_blocks(self, slot: int):
        """Before releasing a finishing/preempted slot: donate its full,
        content-known private blocks to the cache as zero-ref residents, so
        the prefix (prompt AND generated tokens — the preempt-resume path
        re-admits exactly this stream) survives for future requests.
        Positions are trusted only up to min(pos, len(prompt+output),
        max_seq): chunk-tail writes past the delivered tokens hold post-EOS
        garbage and must never be content-addressed."""
        if self._pcache is None:
            return
        req = self._slot_req[slot]
        seq = np.concatenate([np.asarray(req.prompt_ids, np.int32).ravel(),
                              np.asarray(req.output_ids, np.int32)])
        trusted = min(int(self._pos[slot]), seq.size, self.max_seq)
        bs_ = self.block_size
        n_shared = len(self._slot_shared[slot])
        limit = trusted // bs_
        if limit <= n_shared:
            return
        # the slot's shared prefix IS the chain over seq's first n_shared
        # blocks — continue from its tip instead of re-hashing the prefix
        parent = self._slot_shared[slot][-1] if n_shared else None
        keep: list[int] = []
        for i, page in enumerate(self._slot_blocks[slot]):
            b = n_shared + i
            if b < limit:
                tokens = seq[b * bs_:(b + 1) * bs_]
                e = self._pcache.register(parent, tokens, page, refcount=0)
                if e is not None:
                    if self._tier is not None and not self._tier.shared:
                        # same private-tier dedup as _register_prefix_blocks
                        self._tier.discard(e.hash)
                    parent = e.hash
                    continue               # ownership moved to the cache
                # duplicate content (identical stream retired earlier): the
                # page stays private, but later blocks still chain through
                # the EXISTING entry's id
                parent = self._pcache.chain_hash(parent, tokens)
            keep.append(page)              # partial tail / duplicate content
        self._slot_blocks[slot] = keep

    def _preempt(self, slot: int):
        """vLLM-style recompute preemption: free the slot, requeue the
        request with prompt + generated-so-far.  Sampling-safe: resume
        teacher-forces the STORED sampled tokens (no re-decode of history),
        and the continuation's RNG keys derive from (seed, position), so the
        stream picks up exactly where it left off."""
        req = self._slot_req[slot]
        ids = np.concatenate([np.asarray(req.prompt_ids, np.int32).ravel(),
                              np.asarray(req.output_ids, np.int32)])
        req._resume_ids = ids
        # keep seniority across the round trip: a resumed request must not
        # become the youngest slot and the repeat victim (preemption thrash)
        req._resume_age = int(self._slot_age[slot])
        # donate the computed prefix to the cache first: the resume re-admits
        # prompt+generated, so its prefill restarts at the first uncached
        # token instead of recomputing the whole stream
        self._register_retired_blocks(slot)
        self._release(slot)
        self._slot_req[slot] = None
        self._written[slot] = 0
        self._temp[slot] = 0.0  # re-set on readmission
        if self._chunked:
            # a mid-prefill victim resumes as a fresh admission: the donated
            # full blocks above make its re-prefill restart at the first
            # uncached token, not the prompt's head
            self._prefill_ids[slot] = None
            self._prefilled[slot] = 0
        req.status = "PENDING"   # back in the queue; re-seated by _admit
        self._queue.insert(0, req)
        self._jmark(req.rid)
        self.stats["preemptions"] += 1
        if self._flight is not None:
            self._flight.record("degrade", rung=4, what="preempt",
                                rid=req.rid, slot=slot)
        if self._graceful:
            # every preemption is pool-pressure-driven, so in graceful mode
            # it IS ladder rung 4 (rungs 1-3 already ran and left a deficit)
            self.stats["degrade_preempt"] += 1

    def _ensure_growth(self, k):
        """Before a decode chunk: every active slot needs pages covering
        positions up to pos+k-1 (``k`` may be a per-slot vector — the
        speculative verify step appends q_lens tokens to each slot, so a
        non-drafting slot must not be forced to allocate the drafting
        slots' pages).  Oldest slots win; when the pool is dry the youngest
        active slot is preempted and its pages recycled."""
        karr = np.broadcast_to(np.asarray(k, np.int64), (self.max_batch,))
        order = sorted((s for s in range(self.max_batch)
                        if self._slot_req[s] is not None),
                       key=lambda s: self._slot_age[s])
        for slot in order:
            if self._slot_req[slot] is None:
                continue  # preempted by an older slot this pass
            need = self._blocks_needed(int(self._pos[slot])
                                       + int(karr[slot]) - 1)
            while not self._alloc_to(slot, need):
                victims = [s for s in range(self.max_batch)
                           if s != slot and self._slot_req[s] is not None]
                if not victims:
                    req = self._slot_req[slot]
                    have = (len(self._slot_shared[slot])
                            + len(self._slot_blocks[slot]))
                    pinned = (self._pcache.resident_blocks()
                              - self._pcache.evictable_count()
                              if self._pcache is not None else 0)
                    msg = (f"KV block pool exhausted by a single request: "
                           f"rid={req.rid} needs {need} block(s) to cover "
                           f"position {int(self._pos[slot]) + int(karr[slot]) - 1} "
                           f"({have} mapped, {len(self._free)} free, "
                           f"{self._evictable()} evictable cached, {pinned} "
                           f"pinned cached, {self.num_blocks} total); "
                           f"increase num_blocks")
                    if self._graceful:
                        # ladder rung 5 (docs/fault_tolerance.md): eviction,
                        # degradation and preemption are all exhausted —
                        # fail ONLY the unsatisfiable request.  Its pages
                        # free immediately; survivors never see the fault.
                        self._fail_slot(slot, "FAILED", msg, donate=True)
                        break
                    raise RuntimeError(msg)
                self._preempt(max(victims, key=lambda s: self._slot_age[s]))

    # ---------------- scheduler ----------------

    def _validate(self, req: Request):
        ids = np.asarray(req.prompt_ids, np.int32).ravel()
        if ids.size == 0:
            raise ValueError(f"request {req.rid}: empty prompt")
        if ids.size > self.max_seq - 1:
            raise ValueError(
                f"request {req.rid}: prompt length {ids.size} exceeds "
                f"max_seq-1 = {self.max_seq - 1}")
        temp = req.temperature if req.temperature is not None else 0.0
        # math.isfinite, not just `< 0`: temperature=NaN satisfies neither
        # comparison and would sail into the compiled sampler as a per-slot
        # DATA value, poisoning that slot's logits scaling
        if not math.isfinite(temp) or temp < 0:
            raise ValueError(f"request {req.rid}: temperature must be "
                             f"finite and >= 0, got {temp!r}")
        topp = req.top_p if req.top_p is not None else 1.0
        if not (math.isfinite(topp) and 0 < topp <= 1):
            raise ValueError(f"request {req.rid}: top_p must be finite and "
                             f"in (0, 1], got {topp!r}")
        if (req.deadline_s is not None
                and not (math.isfinite(req.deadline_s)
                         and req.deadline_s >= 0)):
            raise ValueError(f"request {req.rid}: deadline_s must be finite "
                             f"and >= 0, got {req.deadline_s!r}")

    def add_request(self, req: Request):
        self._validate(req)
        # normalize to a host int32 array at acceptance: journal_entry
        # re-runs np.asarray on prompt_ids inside the _host_overlap()
        # window, and a device-array prompt would turn that into a blocking
        # transfer mid-pipeline (host_blocking, analysis/host_contracts.py)
        req.prompt_ids = np.asarray(req.prompt_ids, np.int32).ravel()
        req._submit_s = time.perf_counter()  # TTFT epoch (bench rung detail)
        if req.trace_id is None:
            req.trace_id = f"req-{req.rid:x}"
        if self.slo is not None:
            self.slo.begin(req.rid, req._submit_s)
        self._reqs[req.rid] = req
        if (self.max_queue is not None
                and len(self._queue) >= self.max_queue):
            # bounded-queue backpressure: shedding load at admission keeps
            # the accepted requests' SLOs intact (preemption re-inserts
            # bypass add_request — accepted work is never rejected)
            msg = (f"queue full ({len(self._queue)} waiting, "
                   f"max_queue={self.max_queue})")
            if not self._graceful:
                raise RuntimeError(f"request {req.rid}: {msg}")
            with RecordEvent("serving/rejected"):
                self._terminal(req, "REJECTED", msg)
            return
        self._queue.append(req)
        self._jmark(req.rid)

    def _admit(self):
        """Fill free slots from the queue (prefill path).  Paged mode admits
        by free-page count: a request enters only when its prompt's pages
        are allocatable — the block-table analog of "is a lane free"."""
        for slot in range(self.max_batch):
            if self._slot_req[slot] is not None or not self._queue:
                continue
            req = self._queue[0]
            # a preempted request resumes with prompt + generated-so-far
            ids = getattr(req, "_resume_ids", None)
            if ids is None:
                ids = np.asarray(req.prompt_ids, np.int32).ravel()
            s0 = ids.size
            start = 0            # first token whose K/V must be computed
            if self.paged:
                # admit only if the prompt's pages fit AND the active slots'
                # imminent growth (next chunk — or the verify step's K+1
                # appends when speculation is on) keeps its headroom —
                # otherwise a fresh admit would be preempted by
                # _ensure_growth in the same step, wasting its full-prompt
                # prefill.  Spec-off: horizon == chunk, byte-identical.
                horizon = max(self.chunk, self._spec_qmax)
                # per-slot clamp at 0: a mid-prefill slot already owns its
                # whole prompt's pages while pos (the chunk cursor) trails
                # them — surplus must not offset other slots' real growth.
                # (No-op chunked-off: a decode slot never owns pages beyond
                # its growth horizon.)
                headroom = sum(
                    max(0, self._blocks_needed(int(self._pos[s]) + horizon
                                               - 1)
                        - len(self._slot_shared[s])
                        - len(self._slot_blocks[s]))
                    for s in range(self.max_batch)
                    if self._slot_req[s] is not None)
                need = self._blocks_needed(s0 - 1)
                # gate on the new slot's own first-chunk growth too, or
                # _ensure_growth would preempt someone in this same step
                gate = self._blocks_needed(s0 - 2 + horizon)
                # prefix-cache lookup: map the longest cached chain of full
                # blocks into this row read-only.  Acquire BEFORE any
                # allocation — a pinned (refcount > 0) block is unevictable,
                # so _alloc_to's pressure eviction cannot steal the match.
                matched = (self._pcache.match(ids)
                           if self._pcache is not None else [])
                m = len(matched)
                # a fully-matched block-aligned prompt would put the first
                # decode write (position s0-1) inside the last matched block:
                # COW — copy that page into a private one instead of sharing
                # (the engine NEVER writes a shared page)
                cow = m > 0 and m * self.block_size > s0 - 1
                n_map = m - 1 if cow else m
                for e in matched:       # pin all, incl. the COW source
                    self._pcache.acquire(e)
                for i, e in enumerate(matched[:n_map]):
                    self._table[slot, i] = e.page
                    self._slot_shared[slot].append(e.hash)
                # hierarchical KV (docs/kv_tier.md): extend the prefix
                # match THROUGH the host tier.  Walk the chain past the
                # HBM-resident blocks — every hash the tier holds is a
                # block this admission re-admits by H2D copy instead of
                # prefill compute.  The walk stops strictly below the
                # first decode write position (s0-1): a restored block
                # the decode step would write into would need COW, so
                # skipping it costs at most one block of prefill and
                # keeps the restore path write-free; COW admissions
                # (full HBM match) have no tail to extend.
                tier_plan: list[tuple[int, str, str | None]] = []
                if self._tier is not None and not cow:
                    parent = matched[-1].hash if m else None
                    b = m
                    bs_t = self.block_size
                    while (b + 1) * bs_t <= s0 - 1:
                        h = self._pcache.chain_hash(
                            parent, ids[b * bs_t:(b + 1) * bs_t])
                        if h not in self._tier:
                            break
                        tier_plan.append((b, h, parent))
                        parent = h
                        b += 1
                    if tier_plan:
                        self.stats["tier_hits"] += 1
                        for _b, h, _p in tier_plan:
                            # pinned until restored or dropped: the
                            # tier's LRU must not reclaim a matched
                            # entry mid-plan (the chunked cursor spans
                            # steps between match and restore)
                            self._tier.pin(h)
                        if self._flight is not None:
                            self._flight.record("tier_match", rid=req.rid,
                                                blocks=len(tier_plan))
                n_restored = 0
                if tier_plan and not (self._chunked and self._graceful):
                    # bucketed engines — and chunked GRACEFUL-OFF ones,
                    # whose admission allocates the whole prompt's
                    # private pages upfront, leaving no block boundary
                    # the cursor-driven restore could append shared
                    # pages at — restore at admission: each block takes
                    # a free page and registers into the prefix cache
                    # exactly like a freshly-prefilled block, then
                    # prefill (bucketed, or the cursor from ``start``)
                    # begins past the restored coverage.  A mid-walk
                    # failure (pool dry, tier_drop) falls back to
                    # prefill for the remainder — never a hang.
                    for b, h, parent in tier_plan:
                        if not self._restore_tier_block(slot, req, ids, b,
                                                        h, parent):
                            break
                        n_restored += 1
                    for _b, h, _p in tier_plan:
                        self._tier.unpin(h)
                    tier_plan = []
                if self._chunked and self._graceful:
                    # chunk-granular allocation (docs/fault_tolerance.md):
                    # a streaming prompt owns pages only as its cursor
                    # advances — _mixed_step's _ensure_growth allocates
                    # each chunk's pages, so ladder rung 3 can relieve
                    # pool pressure by shrinking the chunk instead of
                    # preempting.  Only the COW duplicate must exist at
                    # admission (its content is copied here).  Admission
                    # still gates on full-prompt fit (avail check below),
                    # so the common case admits at the same step it
                    # always did; graceful-off keeps the pre-PR upfront
                    # allocation byte-identically.
                    need = m if cow else n_map
                avail = len(self._free) + self._evictable()
                if (avail < gate - (n_map + n_restored) + headroom
                        or not self._alloc_to(slot, need)):
                    # roll back refs + any partial allocation on this EMPTY
                    # slot — stranded pages/refs are invisible to every
                    # release path.  Restored tier blocks stay resident in
                    # the HBM cache zero-ref (a retry hits them there);
                    # a chunked plan's pins release so the tier's LRU may
                    # reclaim the unconsumed entries.
                    if cow:
                        self._pcache.release(matched[-1].hash)
                    for _b, h, _p in tier_plan:
                        self._tier.unpin(h)
                    self._release(slot)
                    break  # pool dry: keep queue order, retry next step
                if cow:
                    # private duplicate of the matched block decode will write
                    src = matched[-1]
                    dst = self._slot_blocks[slot][0]   # row index m-1
                    with RecordEvent("prefix_cache/cow_copy"):
                        d = jnp.asarray(dst, jnp.int32)
                        s_ = jnp.asarray(src.page, jnp.int32)
                        self.cache_k = self._copy_page(self.cache_k, d, s_)
                        self.cache_v = self._copy_page(self.cache_v, d, s_)
                    self._pcache.release(src.hash)  # content copied: unpin
                    self.stats["cow_copies"] += 1
                if m:
                    self.stats["prefix_hits"] += 1
                    self.stats["prefix_blocks_reused"] += m
                # cached positions: all of a shared/COW/tier-restored
                # block's K/V is already in the pool — prefill starts at
                # the first uncached token (never past s0-1, decode's
                # first position)
                start = min((m + n_restored) * self.block_size, s0 - 1)
                age = getattr(req, "_resume_age", None)
                self._slot_age[slot] = self._admit_seq if age is None else age
                self._admit_seq += 1
            self._queue.pop(0)
            if hasattr(req, "_resume_ids"):
                del req._resume_ids
            if hasattr(req, "_resume_age"):
                del req._resume_age
            plen = (s0 - 1) - start
            self.stats["prefill_tokens_cached"] += start
            self.stats["prefill_tokens_computed"] += max(plen, 0)
            # a whole-prompt prefill dispatched while other slots hold
            # requests stalls their decode for the full prompt length — the
            # TBT spike chunked prefill erases (the chunked path below never
            # ticks this: prompts stream through the mixed step instead)
            stalls = any(r is not None for r in self._slot_req)
            if self._chunked:
                # enqueue-without-prefill: the mixed step streams positions
                # [start, s0) in prefill_chunk rows; the final row (the last
                # prompt token, position s0-1) emits the first generated
                # token, so admission costs no device step here and decode
                # slots never wait on a prompt.  Same-pass identical-prefix
                # bursts each stream independently — a still-streaming
                # slot's pages are private/writable until its chunk
                # registers them, so they cannot be shared in flight
                # (docs/chunked_prefill.md "deliberate tradeoff")
                self._prefill_ids[slot] = ids
                self._prefilled[slot] = start
                if self._tier is not None:
                    # the match-to-restore plan: the mixed step's cursor
                    # consumes it one block per boundary crossing
                    # (_tier_restore_step), so "restore from host" and
                    # "prefill" share one scheduler
                    self._tier_plan[slot] = tier_plan
            elif start == 0:
                bucket = min(_bucket(s0), self.max_seq)
                padded = np.zeros((1, bucket), np.int32)
                padded[0, :s0] = ids
                # the last real token is fed to decode, not prefill, so its
                # logits come from the decode step (standard split)
                slot_arg = (jnp.asarray(self._table[slot]) if self.paged
                            else jnp.asarray(slot, jnp.int32))
                t_pf = time.perf_counter()
                self.cache_k, self.cache_v = self._prefill(
                    self.params, jnp.asarray(padded), self.cache_k,
                    self.cache_v, slot_arg, jnp.asarray(s0 - 1, jnp.int32),
                    bucket)
                self.stats["prefills"] += 1
                self.stats["decode_stall_steps"] += int(stalls)
                self._tracer.span(req.rid, "prefill", t_pf,
                                  time.perf_counter(),
                                  args={"bucket": bucket, "tokens": s0 - 1})
            elif plen > 0:
                # partial-bucket prefill over the uncached tail only
                t_pf = time.perf_counter()
                with RecordEvent("prefix_cache/partial_prefill"):
                    bucket = min(_bucket(plen), self.max_seq)
                    padded = np.zeros((1, bucket), np.int32)
                    padded[0, :plen] = ids[start:s0 - 1]
                    self.cache_k, self.cache_v = self._prefill_prefix(
                        self.params, jnp.asarray(padded), self.cache_k,
                        self.cache_v, jnp.asarray(self._table[slot]),
                        jnp.asarray(start, jnp.int32),
                        jnp.asarray(s0 - 1, jnp.int32), bucket)
                self.stats["prefills"] += 1
                self.stats["decode_stall_steps"] += int(stalls)
                self._tracer.span(req.rid, "prefill", t_pf,
                                  time.perf_counter(),
                                  args={"bucket": bucket, "tokens": plen,
                                        "cached": start})
            # else: full hit — nothing to compute, decode starts immediately
            if self.paged and self._pcache is not None and not self._chunked:
                # share this admission's freshly-computed full prompt blocks
                # (the chunked path registers as each chunk completes them)
                self._register_prefix_blocks(slot, ids, s0 - 1)
            self._slot_req[slot] = req
            req.status = "RUNNING"
            # lifecycle observability: queue-wait span closes at seating
            # (docs/observability.md — the decode span opens here too)
            now = time.perf_counter()
            req._admit_s = now
            if self.slo is not None:
                self.slo.admitted(req.rid, now)
            self._tracer.span(req.rid, "queued",
                              getattr(req, "_submit_s", now), now,
                              args={"rid": req.rid, "slot": slot,
                                    "cached_tokens": int(start)})
            if self._flight is not None:
                self._flight.record("admit", rid=req.rid, slot=slot,
                                    prompt=int(s0),
                                    cached_tokens=int(start))
            if self._chunked:
                # the prefill cursor IS the position state: pos/_written
                # advance with each chunk, so preemption's trusted-content
                # bound and the auditor's I6 read the same fields they do
                # for decode (cached positions below ``start`` count as
                # written — the pool already holds their K/V)
                self._pos[slot] = start
                self._written[slot] = start
            else:
                self._pos[slot] = s0 - 1
                # prefill committed (or the cache already held) K/V for
                # every position below s0-1; position s0-1 itself is
                # decode's first write
                self._written[slot] = s0 - 1
            self._last_tok[slot] = ids[-1]
            self._temp[slot] = max(float(req.temperature or 0.0), 0.0)
            self._topp[slot] = float(req.top_p if req.top_p is not None
                                     else 1.0)
            # default seed: the request id, so two concurrent sampled
            # requests never share a stream
            self._seed[slot] = np.int32(
                req.seed if req.seed is not None else req.rid)
            self._jmark(req.rid)   # seating sets the journal's cursor

    def _retire(self, slot):
        self._terminal(self._slot_req[slot], "FINISHED")
        if self.paged:
            self._register_retired_blocks(slot)  # needs the request's tokens
        self._slot_req[slot] = None
        self._written[slot] = 0
        self._temp[slot] = 0.0  # freed slot must not pin the sampling variant
        if self._chunked:
            self._prefill_ids[slot] = None
            self._prefilled[slot] = 0
        if self.paged:
            self._release(slot)

    # ---------------- fault tolerance (docs/fault_tolerance.md) ------------

    def _terminal(self, req: Request, status: str, error: str | None = None):
        """Move a request to its terminal status (status lifecycle:
        PENDING -> RUNNING -> terminal, exactly one terminal transition).
        ``finished`` stays the caller-facing "no more tokens coming" flag
        for every terminal status; ``status`` says why."""
        req.status = status
        req.finished = True
        if error is not None:
            req.error = error
        stat = _STATUS_STAT.get(status)
        if stat is not None:
            self.stats[stat] += 1
        # the journal only tracks LIVE requests: a terminal entry would
        # leak one Request per rid forever in a long-lived engine (the
        # caller keeps its own reference; cancel() on a terminal rid
        # correctly reports False via the journal miss)
        self._reqs.pop(req.rid, None)
        self._jdrop(req.rid)
        # lifecycle observability: close the SLO record, emit the decode
        # span (admission -> terminal) + terminal marker, and — for a
        # FAILED request — dump the flight recorder so triage reads the
        # engine's last seconds instead of rerunning the chaos
        now = time.perf_counter()
        if self.slo is not None:
            self.slo.finish(req.rid, status, now)
        if self._tracer.enabled:
            t_admit = getattr(req, "_admit_s", None)
            if t_admit is not None:
                self._tracer.span(req.rid, "decode", t_admit, now,
                                  args={"tokens": len(req.output_ids),
                                        "status": status})
            self._tracer.instant(
                req.rid, f"terminal:{status}", now,
                args={"rid": req.rid,
                      **({"error": error} if error else {})})
        if self._flight is not None:
            self._flight.record("terminal", rid=req.rid, status=status,
                                tokens=len(req.output_ids),
                                **({"error": error} if error else {}))
            if status == "FAILED":
                self._flight.dump(f"request_failed rid={req.rid}")

    def _fail_slot(self, slot: int, status: str, error: str,
                   donate: bool = False):
        """Terminate the request seated on ``slot`` with a non-FINISHED
        terminal status, releasing every page and cache ref it owns (the
        auditor's I8).  ``donate=True`` (cancel / expiry / overload — the
        slot's K/V content is trusted) content-addresses full blocks into
        the prefix cache first, exactly like retirement; ``donate=False``
        (NaN quarantine and other fault paths) drops the pages without
        registering them — a fault step's K/V writes must never be served
        to a future request.  Partial output already banked stays on the
        request (EXPIRED/CANCELLED deliver what they have)."""
        req = self._slot_req[slot]
        with RecordEvent(f"serving/{status.lower()}"):
            if donate and self.paged:
                self._register_retired_blocks(slot)
            self._slot_req[slot] = None
            self._written[slot] = 0
            self._temp[slot] = 0.0
            self._poison[slot] = False
            if self._chunked:
                self._prefill_ids[slot] = None
                self._prefilled[slot] = 0
            if self.paged:
                self._release(slot)
            self._terminal(req, status, error)

    def _host_fault(self, kind: str, slot: int | None = None,
                    rid: int | None = None):
        """Poll one host-side injection seam; raises :class:`FaultInjected`
        when a plan clause fires (no-op without a plan)."""
        if self._faults and self._faults.fire(kind, step=self._step_no,
                                              slot=slot, rid=rid):
            where = "".join((f", slot {slot}" if slot is not None else "",
                             f", rid {rid}" if rid is not None else ""))
            if self._flight is not None:
                self._flight.record("fault", fault=kind,
                                    step=self._step_no,
                                    **({"slot": slot}
                                       if slot is not None else {}),
                                    **({"rid": rid}
                                       if rid is not None else {}))
            raise FaultInjected(
                f"injected {kind} (step {self._step_no}{where})")

    def _arm_poison(self):
        """Sampler seam: set per-slot poison bits for ``nan_logits`` clauses
        firing this step.  The bits are DATA to the compiled step, where
        they turn the slot's logits row genuinely non-finite IN-GRAPH — the
        guard proves itself against the real failure shape.  Graceful-off
        the compiled program has no poison operand (byte-identical to the
        pre-fault-tolerance engine), so the kind is inert there."""
        if not (self._graceful and self._faults):
            return
        for s in range(self.max_batch):
            req = self._slot_req[s]
            if req is not None and self._faults.fire(
                    "nan_logits", step=self._step_no, slot=s, rid=req.rid):
                self._poison[s] = True

    def _retry_launch(self, err: FaultInjected) -> bool:
        """Graceful handling of a kernel-dispatch fault: the raise happened
        BEFORE the compiled call, so host and device state (including the
        donated cache buffers) are untouched and the step can simply run
        again.  A persistent failure (streak past the limit) means the
        program itself cannot run — re-raise rather than spin."""
        if not self._graceful:
            raise err
        self._kernel_err_streak += 1
        self.stats["kernel_error_retries"] += 1
        if self._flight is not None:
            self._flight.record("fault", fault="kernel_error",
                                streak=self._kernel_err_streak,
                                step=self._step_no)
        if self._kernel_err_streak > self._kernel_err_limit:
            raise err
        with RecordEvent("serving/kernel_error_retry"):
            pass
        return True    # state untouched: the next step() retries

    def _growth_need(self, growth) -> int:
        """Block-pool pressure probe: pages the active slots' imminent
        growth needs beyond what they already own (``growth`` may be a
        per-slot vector, matching ``_ensure_growth``)."""
        karr = np.broadcast_to(np.asarray(growth, np.int64),
                               (self.max_batch,))
        need = 0
        for s in range(self.max_batch):
            if self._slot_req[s] is None or karr[s] <= 0:
                continue
            need += max(0, self._blocks_needed(int(self._pos[s])
                                               + int(karr[s]) - 1)
                        - len(self._slot_shared[s])
                        - len(self._slot_blocks[s]))
        return need

    def _degrade_reclaim(self, growth) -> int:
        """Ladder rung 1: on pool pressure, proactively evict prefix-cache
        leaves into the free list (oldest zero-ref first — the same
        LRU order allocation-pressure eviction uses, just ahead of the
        allocator instead of inside it, so the rung is observable and
        strictly ordered before rungs 2-5).  Returns the deficit that
        REMAINS after eviction; <= 0 means the step fits."""
        need = self._growth_need(growth)
        short = need - len(self._free)
        if short > 0 and self._evictable() > 0:
            with RecordEvent("serving/degrade_evict"):
                if self._reclaim(short) > 0:
                    self.stats["degrade_evict"] += 1
                    if self._flight is not None:
                        self._flight.record("degrade", rung=1, what="evict",
                                            short=int(short))
        return need - len(self._free)

    def _expire_overdue(self):
        """Deadline enforcement (graceful mode): a request past its
        ``deadline_s`` wall-clock budget (from submission) terminates
        EXPIRED with whatever partial output it has, freeing its pages for
        requests that can still meet their SLO.  Queued and running
        requests expire alike — a queued request that can no longer finish
        in time should not consume a slot at all."""
        now = time.perf_counter()

        def overdue(req):
            return (req.deadline_s is not None
                    and now - getattr(req, "_submit_s", now) > req.deadline_s)

        for s in range(self.max_batch):
            req = self._slot_req[s]
            if req is not None and overdue(req):
                self._fail_slot(s, "EXPIRED",
                                f"deadline_s={req.deadline_s} exceeded "
                                f"({len(req.output_ids)} token(s) delivered)",
                                donate=True)
        if any(overdue(r) for r in self._queue):
            keep = []
            for req in self._queue:
                if overdue(req):
                    with RecordEvent("serving/expired"):
                        self._terminal(req, "EXPIRED",
                                       f"deadline_s={req.deadline_s} "
                                       f"exceeded while queued")
                else:
                    keep.append(req)
            self._queue = keep

    def cancel(self, rid: int) -> bool:
        """Cancel a request by id: queued requests leave the queue, a
        running request frees its slot (even mid-prefill — the chunked
        cursor's pages release like any preemption, and full blocks donate
        to the prefix cache so a re-submission resumes cheaply).  Partial
        output stays on the request.  Returns True when the request was
        still live (False: unknown rid or already terminal).  Requires
        graceful mode — the PADDLE_TPU_GRACEFUL=0 engine predates the
        status lifecycle."""
        if not self._graceful:
            raise RuntimeError("cancel() requires PADDLE_TPU_GRACEFUL=1")
        req = self._reqs.get(rid)
        if req is None or req.status in TERMINAL_STATUSES:
            return False
        for s in range(self.max_batch):
            if self._slot_req[s] is req:
                self._fail_slot(s, "CANCELLED", "cancelled by caller",
                                donate=True)
                return True
        with RecordEvent("serving/cancelled"):
            # identity scan, not `in`/`remove`: the dataclass __eq__
            # compares numpy prompt_ids and would raise on same-shape twins
            for i, q in enumerate(self._queue):
                if q is req:
                    del self._queue[i]
                    break
            self._terminal(req, "CANCELLED", "cancelled by caller")
        return True

    def _topology(self) -> dict:
        """Engine topology/config fingerprint journaled into snapshots
        (v2): everything a restore target must agree on for the journal to
        be replayable — the model identity (a mismatched model would
        teacher-force the wrong logits silently), serving geometry
        (max_seq/paged/block_size/quant) — plus the tp degree, which is
        recorded for diagnosis but deliberately NOT enforced: the KV pool
        is never captured, so teacher-forced recompute makes a
        cross-degree restore token-identical by construction
        (docs/tp_serving.md)."""
        cfg = self.cfg
        # every field that changes the teacher-forced recompute's logits
        # belongs in the id — shapes alone would let a rope_theta or dtype
        # mismatch resume silently wrong
        return {
            "model": (f"llama:v{cfg.vocab_size}:h{cfg.hidden_size}"
                      f":L{cfg.num_hidden_layers}"
                      f":nh{cfg.num_attention_heads}"
                      f":nkv{cfg.num_key_value_heads}"
                      f":i{cfg.intermediate_size}"
                      f":tie{int(bool(cfg.tie_word_embeddings))}"
                      f":dt{jnp.dtype(cfg.dtype).name}"
                      f":rope{cfg.rope_theta:g}"
                      f":eps{cfg.rms_norm_eps:g}"),
            "quant": self.quant,
            # pool storage changes the teacher-forced logits (requantized
            # appends are lossy), so a kv_quant mismatch must raise; old
            # v2 snapshots lack the key and src.get() -> None == the fp
            # engine's value, so pre-stage-2 journals restore unchanged
            "kv_quant": self.kv_quant,
            "paged": self.paged,
            "block_size": self.block_size if self.paged else None,
            "max_seq": int(self.max_seq),
            "tp": int(self.tp),
        }

    def snapshot(self) -> dict:
        """Serialize accepted-but-unfinished work: queue order plus a
        per-request journal (prompt, emitted tokens, sampling params,
        chunked-prefill cursor).  JSON-serializable, device-free — the
        KV pool is deliberately NOT captured: :meth:`restore` resumes by
        teacher-forced recompute (the preemption path), which is exact for
        greedy AND seeded sampling, so a snapshot costs bytes proportional
        to the token streams, not the HBM pool.  The replica-restart
        primitive the fleet tier needs (ROADMAP item 2).

        v2 adds the ``engine`` topology block (:meth:`_topology`) so
        :meth:`restore` can refuse a mismatched replica instead of
        resuming silently wrong, and ``deadline_remaining_s`` — the
        UNSPENT wall-clock budget at snapshot time — so :meth:`adopt`
        re-arms a restored deadline with what is actually left rather
        than granting the full budget again."""

        now = time.perf_counter()
        self.stats["journal_full_rebuilds"] += 1
        with RecordEvent("serving/snapshot"):
            running = [s for s in range(self.max_batch)
                       if self._slot_req[s] is not None]
            if self.paged:
                running.sort(key=lambda s: int(self._slot_age[s]))
            return {
                "version": 2,
                "engine": self._topology(),
                "running": [journal_entry(self._slot_req[s],
                                          self._prefilled[s]
                                          if self._chunked else 0, now)
                            for s in running],
                "queued": [journal_entry(r, 0, now) for r in self._queue],
            }

    # ------------- incremental journal (docs/async_runtime.md) ------------

    def _jmark(self, rid: int):
        """Mark one rid's journal entry stale (admission, token bank,
        chunk-cursor advance, adopt, preempt).  O(1) — the entry rebuild
        happens in :meth:`_jflush`, inside the host-overlap window when
        the async runtime is on."""
        self._jdirty.add(rid)

    def _jdrop(self, rid: int):
        """Retire one rid's journal entry (terminal states)."""
        self._jentries.pop(rid, None)
        self._jdirty.discard(rid)

    def _jflush(self, now: float | None = None):
        """Rebuild the journal entries of every dirty rid — the O(changed
        rids) incremental replacement for :meth:`snapshot`'s full scan.
        Entries freeze ``deadline_remaining_s`` at flush time;
        :meth:`journal` re-derives it at read time, so consumers always
        see the live remaining budget."""
        if not self._jdirty:
            return
        t0 = time.perf_counter()
        if now is None:
            now = t0
        slot_of = {}
        for s in range(self.max_batch):
            r = self._slot_req[s]
            if r is not None:
                slot_of[r.rid] = s
        n = 0
        for rid in self._jdirty:
            req = self._reqs.get(rid)
            if req is None:
                # terminal raced the mark (defensive; _terminal _jdrops)
                self._jentries.pop(rid, None)
                continue
            s = slot_of.get(rid)
            prefilled = (int(self._prefilled[s])
                         if self._chunked and s is not None else 0)
            self._jentries[rid] = journal_entry(req, prefilled, now)
            n += 1
        self._jdirty.clear()
        if n:
            self.stats["journal_incremental_updates"] += n
            if self._h_jupdate is not None:
                self._h_jupdate.observe(time.perf_counter() - t0)
            if self._flight is not None:
                self._flight.record("journal_flush", entries=n)

    def journal(self) -> dict:
        """:meth:`snapshot`-equivalent view assembled from the incremental
        journal — the async host runtime's replacement for the router's
        per-step/per-dispatch full rebuilds (docs/async_runtime.md).  The
        fleet pulls this only at failover/hedge boundaries; equivalence
        with :meth:`snapshot` is asserted every fleet step under
        PADDLE_TPU_ENGINE_AUDIT=1 (fleet._audit_journal_equiv)."""
        now = time.perf_counter()
        self._jflush(now)

        def _entry(req, prefilled: int) -> dict:
            e = self._jentries.get(req.rid)
            if e is None:
                # defensive: a rid that never got marked (should not
                # happen — every mutation site _jmarks) still journals
                e = journal_entry(req, prefilled, now)
                self._jentries[req.rid] = e
            if e["deadline_s"] is not None:
                e = dict(e)
                e["deadline_remaining_s"] = max(
                    0.0, e["deadline_s"]
                    - (now - getattr(req, "_submit_s", now)))
            return e

        running = [s for s in range(self.max_batch)
                   if self._slot_req[s] is not None]
        if self.paged:
            running.sort(key=lambda s: int(self._slot_age[s]))
        return {
            "version": 2,
            "engine": self._topology(),
            "running": [_entry(self._slot_req[s],
                               int(self._prefilled[s])
                               if self._chunked else 0) for s in running],
            "queued": [_entry(r, 0) for r in self._queue],
        }

    def _host_overlap(self):
        """The token-independent half of a step's host work, run between
        the compiled launch and the first token fetch — while the device
        executes the step (JAX async dispatch), so steady-state journal
        upkeep costs the host gap nothing.  A no-op with
        PADDLE_TPU_ASYNC_HOST=0: the serial loop defers all journal work
        to explicit snapshot() calls, byte-identically to the pre-async
        engine."""
        if not self._async_host:
            return
        self.stats["host_overlap_steps"] += 1
        self._jflush()

    def adopt(self, j: dict) -> Request:
        """Adopt ONE journaled request (an entry of :meth:`snapshot`'s
        ``running``/``queued`` lists) into this engine's queue — the fleet
        tier's per-request failover/hedge primitive (inference/fleet.py),
        and the loop body :meth:`restore` runs over a whole snapshot.

        The request re-enters through the preemption-resume path: prompt +
        already-emitted tokens are teacher-forced by (chunked) prefill
        recompute, then position-derived sampling keys continue the stream
        exactly.  Deliberately EXEMPT from ``max_queue`` backpressure:
        journaled work was already accepted once (by the dead or stalled
        replica), and accepted work is never rejected — the same contract
        preemption re-inserts enjoy.  The deadline re-arms with the
        journaled ``deadline_remaining_s`` (v2): the budget the original
        replica already burned stays burned.  (Journals without the field —
        v1 snapshots — fall back to the full ``deadline_s``, the historical
        behavior.)"""
        req = Request(
            rid=j["rid"],
            prompt_ids=np.asarray(j["prompt_ids"], np.int32),
            max_new_tokens=j["max_new_tokens"],
            eos_token_id=j["eos_token_id"],
            temperature=j["temperature"], top_p=j["top_p"],
            seed=j["seed"],
            deadline_s=j.get("deadline_remaining_s", j["deadline_s"]))
        req.output_ids = list(j["output_ids"])
        if req.output_ids:
            # the preempt-resume contract: stored tokens are
            # teacher-forced, the continuation redraws exactly
            req._resume_ids = np.concatenate(
                [np.asarray(req.prompt_ids, np.int32).ravel(),
                 np.asarray(req.output_ids, np.int32)])
        req._submit_s = time.perf_counter()
        if req.trace_id is None:
            req.trace_id = f"req-{req.rid:x}"
        if self.slo is not None:
            self.slo.begin(req.rid, req._submit_s)
        self._tracer.instant(req.rid, "adopt", req._submit_s,
                             args={"rid": req.rid,
                                   "replayed_tokens": len(req.output_ids)})
        if self._flight is not None:
            self._flight.record("adopt", rid=req.rid,
                                replayed_tokens=len(req.output_ids))
        self._reqs[req.rid] = req
        self._queue.append(req)
        self._jmark(req.rid)
        return req

    def restore(self, snap: dict) -> list[Request]:
        """Resume a :meth:`snapshot` on THIS engine (typically a fresh
        replica after a crash/restart).  Every journaled request re-enters
        the queue through the preemption-resume path: prompt + already-
        emitted tokens are teacher-forced by (chunked) prefill recompute,
        then position-derived sampling keys continue the stream exactly —
        a serve completed after restore() emits token-identical output to
        one that was never interrupted.  Deadlines re-arm from restore
        time with the journaled REMAINING budget (the dead replica's
        clock is gone, but the budget it burned stays burned —
        :meth:`adopt`).  Returns the resumed Request objects (in
        admission order: running work first).

        v2 snapshots carry the source engine's topology (:meth:`_topology`)
        and restore onto a mismatched engine raises a diagnosable
        ``ValueError`` naming every differing field — a journal replayed
        through the wrong model or serving geometry would resume silently
        wrong.  The ONE deliberate exception is the tensor-parallel
        degree: the journal holds tokens, not KV bytes, and teacher-forced
        recompute is degree-independent, so a tp=4 snapshot legally
        restores onto a tp=1 (or tp=2) replica token-identically — the
        fleet-tier elasticity primitive.  v1 snapshots (pre-topology)
        restore as before, unchecked."""
        if snap.get("version") not in (1, 2):
            raise ValueError(f"unknown snapshot version "
                             f"{snap.get('version')!r} (expected 1 or 2)")
        src = snap.get("engine")
        if snap.get("version") == 2 and src is not None:
            mine = self._topology()
            mismatch = {k: (src.get(k), mine[k]) for k in mine
                        if k != "tp" and src.get(k) != mine[k]}
            if mismatch:
                diff = "; ".join(
                    f"{k}: snapshot={a!r} vs engine={b!r}"
                    for k, (a, b) in sorted(mismatch.items()))
                raise ValueError(
                    f"snapshot topology does not match this engine "
                    f"({diff}); restoring across topologies would resume "
                    f"silently wrong — only the tensor-parallel degree "
                    f"may differ (snapshot tp={src.get('tp')!r}, engine "
                    f"tp={self.tp})")
        with RecordEvent("serving/restore"):
            return [self.adopt(j) for j in snap["running"] + snap["queued"]]

    def _maybe_audit(self):
        if self._audit_every_step:
            from ..analysis.engine_audit import (EngineAuditError,
                                                 audit_engine)

            try:
                audit_engine(self)
            except EngineAuditError:
                # triage-without-a-rerun: the flight recorder's last
                # N events + a metrics snapshot accompany the raise
                if self._flight is not None:
                    self._flight.dump("engine_audit_error")
                raise

    # ------------- per-step latency accounting (docs/observability.md) ----

    def _note_launch(self, t0: float):
        """Called at each compiled launch's dispatch time: the gap since
        the previous step's host fetch is pure host-side work (packing,
        drafting, journal upkeep) the device spent idle — the host-gap
        histogram ROADMAP item 5 will optimize against."""
        if self._h_hostgap is not None and self._last_step_end is not None:
            self._h_hostgap.observe(t0 - self._last_step_end)

    def _note_step_done(self, t0: float):
        end = time.perf_counter()
        if self._h_step is not None:
            self._h_step.observe(end - t0)
        self._last_step_end = end

    def step(self) -> bool:
        """One admit + decode iteration (a chunked decode scan; with
        speculation on and at least one slot drafting, a single multi-token
        verify step; with chunked prefill on and at least one prompt still
        streaming, a single unified mixed prefill/decode step).  Returns
        False when idle.

        Graceful mode: no per-request fault escapes this method — the
        offending request terminates (pages and cache refs released) and
        every survivor's token stream is identical to a run that never
        contained it (each slot's stream depends only on its own
        (seed, position) keys and its own pages)."""
        self._step_no += 1          # fault-plan step key (1-based)
        if self._graceful:
            self._expire_overdue()
        self._admit()
        if (self._graceful and self.paged and self._queue
                and all(r is None for r in self._slot_req)):
            # admission made no progress with NOTHING resident: no future
            # step can free pages (zero-ref cache leaves were already fair
            # game inside _alloc_to), so waiting is a livelock.  Tolerate a
            # few consecutive stuck steps (a transient injected alloc fault
            # clears), then fail the head request — ladder rung 5 applied
            # at admission.
            self._admit_stalls += 1
            if self._admit_stalls > self._kernel_err_limit:
                req = self._queue.pop(0)
                ids = getattr(req, "_resume_ids", None)
                s0 = (np.asarray(req.prompt_ids, np.int32).ravel().size
                      if ids is None else ids.size)
                with RecordEvent("serving/failed"):
                    self._terminal(
                        req, "FAILED",
                        f"pool exhausted at admission: rid={req.rid} needs "
                        f"{self._blocks_needed(s0 - 1)} block(s) for its "
                        f"{s0}-token stream, {len(self._free)} free + "
                        f"{self._evictable()} evictable of "
                        f"{self.num_blocks} total")
                self._admit_stalls = 0
        else:
            self._admit_stalls = 0
        self._maybe_audit()
        if self._chunked and any(i is not None for i in self._prefill_ids):
            # at least one prompt is streaming in: ONE mixed launch advances
            # every decode slot a token AND moves the prompts forward under
            # the token budget.  Once every prompt drains, the ordinary
            # decode/speculative paths below run their untouched programs —
            # steady-state throughput is byte-identical to chunked-off.
            return self._mixed_step()
        if self._spec is not None:
            drafts = self._draft_proposals()
            if drafts is not None and self._graceful and self.paged:
                qlens = np.ones(self.max_batch, np.int64)
                for s, d in drafts.items():
                    qlens[s] = 1 + d.size
                if self._degrade_reclaim(qlens) > 0:
                    # ladder rung 2: this step's speculative appends do not
                    # fit even after rung 1's eviction — suspend speculation
                    # for the step (growth drops to one token per slot)
                    # before anyone is preempted.  Token streams are
                    # unaffected: speculation only changes how many tokens
                    # each round-trip banks, never which ones.
                    with RecordEvent("serving/degrade_spec_off"):
                        self.stats["degrade_spec_off"] += 1
                        if self._flight is not None:
                            self._flight.record("degrade", rung=2,
                                                what="spec_off",
                                                step=self._step_no)
                    drafts = None
            if drafts is not None:
                return self._spec_step(drafts)
            # no slot drafted: fall through to the ordinary decode path —
            # a drafter miss must cost nothing (same step shape as spec-off)
        k = self.chunk
        if self.paged:
            if self._graceful:
                self._degrade_reclaim(k)    # ladder rung 1 before rung 4
            self._ensure_growth(k)  # may preempt the youngest slot
        active_np = np.asarray([r is not None for r in self._slot_req])
        if not active_np.any():
            return False
        t0 = time.perf_counter()
        self._note_launch(t0)
        extra = (jnp.asarray(self._table),) if self.paged else ()
        # greedy-only resident set takes the sampler-free compiled variant
        any_sampled = bool((self._temp * active_np).max() > 0)
        decode = self._decode_sampling if any_sampled else self._decode_greedy
        self._arm_poison()
        try:
            self._host_fault("kernel_error")   # dispatch seam: pre-launch
            if self._graceful:
                toks, bad, self.cache_k, self.cache_v = decode(
                    self.params, self.cache_k, self.cache_v,
                    jnp.asarray(self._last_tok), jnp.asarray(self._pos),
                    jnp.asarray(active_np), jnp.asarray(self._temp),
                    jnp.asarray(self._topp), jnp.asarray(self._seed),
                    *extra, poison=jnp.asarray(self._poison))
                # async host runtime: the token-independent host half
                # (journal upkeep) runs while the device executes the
                # launch above — the guard/token fetches below block as
                # late as possible (docs/async_runtime.md)
                self._host_overlap()
                bad_np = np.asarray(bad)    # [k, B] guard flags
            else:
                toks, self.cache_k, self.cache_v = decode(
                    self.params, self.cache_k, self.cache_v,
                    jnp.asarray(self._last_tok), jnp.asarray(self._pos),
                    jnp.asarray(active_np), jnp.asarray(self._temp),
                    jnp.asarray(self._topp), jnp.asarray(self._seed), *extra)
                self._host_overlap()
        except FaultInjected as e:
            return self._retry_launch(e)
        self._kernel_err_streak = 0
        self._poison[:] = False
        toks_np = np.asarray(toks)  # [k, B] — ONE host round-trip per chunk
        self.stats["decode_time_s"] += time.perf_counter() - t0
        self._note_step_done(t0)
        now = self._last_step_end   # banking-event timestamp (SLO tracker)
        self.stats["decode_steps"] += k
        for slot, req in enumerate(self._slot_req):
            if req is None:
                continue
            old_pos = int(self._pos[slot])
            # tokens produced from positions >= max_seq are garbage (their
            # K/V writes were dropped): only the first max_seq - old_pos
            # chunk steps are trustworthy
            valid = min(k, self.max_seq - old_pos)
            done = False
            banked = 0
            fail_err = None
            try:
                self._host_fault("slot_error", slot=slot, rid=req.rid)
            except FaultInjected as e:
                if not self._graceful:
                    raise
                fail_err = str(e)
            if fail_err is None:
                for j in range(valid):
                    if self._graceful and bad_np[j, slot]:
                        # quarantine: tokens from the poisoned scan step on
                        # are sampled from a zeroed row — never banked
                        self.stats["nan_guard_trips"] += 1
                        fail_err = (f"non-finite logits at position "
                                    f"{old_pos + j} (in-graph guard)")
                        break
                    tok = int(toks_np[j, slot])
                    req.output_ids.append(tok)
                    banked += 1
                    if req.ttft_s is None:
                        # time-to-first-token: the cached-prefix admission's
                        # headline win (prefill skipped, decode starts
                        # sooner)
                        req.ttft_s = (time.perf_counter()
                                      - getattr(req, "_submit_s", t0))
                    # count only tokens a caller actually receives: chunk
                    # steps past EOS / the token budget / max_seq are
                    # trimmed here, so they must not inflate
                    # decode_tokens_per_s (the headline)
                    self.stats["decode_tokens"] += 1
                    if (len(req.output_ids) >= req.max_new_tokens
                            or (req.eos_token_id is not None
                                and tok == req.eos_token_id)):
                        done = True
                        break
            if fail_err is not None:
                # per-request isolation: fail THIS slot, free its pages;
                # the other lanes' tokens (already fetched) bank normally
                self._fail_slot(slot, "FAILED", fail_err, donate=False)
                continue
            if self.slo is not None and banked:
                # one banking event: the whole chunk arrives in one fetch
                self.slo.tokens(req.rid, banked, now)
            self._pos[slot] = old_pos + k  # device advanced k regardless
            # maximum, not overwrite: a prior verify step's rejected drafts
            # may have written past old_pos+k, and the high-water mark must
            # keep covering them until they are actually overwritten
            self._written[slot] = max(int(self._written[slot]),
                                      min(old_pos + k, self.max_seq))
            self._last_tok[slot] = int(toks_np[-1, slot])
            self._jmark(req.rid)   # token bank advanced the journal entry
            if done or old_pos + k >= self.max_seq:
                self._retire(slot)
        self._maybe_audit()
        return True

    # ---------------- chunked-prefill scheduling (host control plane) ------

    def _mixed_step(self) -> bool:
        """One unified prefill/decode round (docs/chunked_prefill.md): pack
        up to ``token_budget`` rows as [decode slots | prefill chunks] and
        dispatch ONE compiled [B, T] launch.  Decode rows pack FIRST — every
        decode-ready slot advances exactly one token, so decode never waits
        on a prompt (``decode_stall_steps`` stays 0) — then prefill chunks
        fill the remaining budget oldest-slot-first, at most
        ``prefill_chunk`` rows per slot per step, with a 1-token floor so a
        tiny budget degrades to slow prefill instead of livelock.  A lane
        whose chunk reaches the last prompt token consumes its emitted
        token (the fused first decode step); mid-prompt lanes ignore theirs.
        Freshly-completed full blocks register into the prefix cache chunk
        by chunk, so a request admitted later in the same serve already
        hits the streaming prefix."""
        B = self.max_batch
        T = self._prefill_chunk
        decode_slots = [s for s in range(B)
                        if self._slot_req[s] is not None
                        and self._prefill_ids[s] is None]
        budget = max(self._token_budget - len(decode_slots), 1)
        tokens = np.zeros((B, T), np.int32)
        q_lens = np.ones(B, np.int32)
        pos = np.asarray(self._pos, np.int32).copy()   # row-0 positions
        active = np.zeros(B, bool)
        growth = np.zeros(B, np.int64)
        chunk_rows: dict[int, int] = {}
        for s in decode_slots:
            tokens[s, 0] = self._last_tok[s]
            active[s] = True
            growth[s] = 1
        prefilling = sorted((s for s in range(B)
                             if self._prefill_ids[s] is not None),
                            key=lambda s: self._slot_age[s])
        tier_progress = False
        t_r0 = None        # first tier-restore dispatch (host-gap anchor)
        for s in prefilling:
            ids = self._prefill_ids[s]
            cur = int(self._prefilled[s])
            if self._tier is not None and self._tier_plan[s]:
                # hierarchical KV (docs/kv_tier.md): consume this slot's
                # tier-restore plan at the cursor — restored blocks
                # advance the cursor like computed chunks, billed against
                # the same token budget as prefill rows (no packed rows,
                # just the H2D); a budget-deferred plan idles the lane
                # rather than computing a block the next step restores
                cur0 = cur
                if t_r0 is None:
                    t_r0 = time.perf_counter()
                cur, budget, pending = self._tier_restore_step(s, ids,
                                                               budget)
                tier_progress = tier_progress or cur != cur0 or pending
                if cur != cur0:
                    self._jmark(self._slot_req[s].rid)  # cursor advanced
                if pending:
                    continue
            n = min(T, ids.size - cur, budget)
            if n <= 0:
                continue    # budget drained: the lane idles this step
            budget -= n
            tokens[s, :n] = ids[cur:cur + n]
            pos[s] = cur
            q_lens[s] = n
            active[s] = True
            growth[s] = n
            chunk_rows[s] = n
        if self._graceful and self._degrade_reclaim(growth) > 0:
            # ladder rungs 1 + 3: the step's FULL growth (decode lanes'
            # one-token appends + every packed prefill chunk) must fit —
            # _degrade_reclaim already evicted cache leaves (rung 1); if
            # still short, shrink this step's prefill rows to the 1-token
            # floor (prompts crawl, decode never stalls, nobody is
            # preempted for a prompt that could simply wait).  Only when
            # even the floor-packed step does not fit does _ensure_growth
            # below preempt (rung 4).
            shrinkable = [s for s, n in chunk_rows.items() if n > 1]
            if shrinkable:
                with RecordEvent("serving/degrade_budget_shrink"):
                    self.stats["degrade_budget_shrink"] += 1
                    if self._flight is not None:
                        self._flight.record("degrade", rung=3,
                                            what="budget_shrink",
                                            slots=len(shrinkable))
                for s in shrinkable:
                    tokens[s, 1:] = 0
                    q_lens[s] = 1
                    growth[s] = 1
                    chunk_rows[s] = 1
        # the auditor's I7 cross-checks the packing stayed disjoint
        self._last_pack = (tuple(decode_slots), tuple(sorted(chunk_rows)))
        self._ensure_growth(growth)  # may preempt the youngest slot
        for s in range(B):
            if self._slot_req[s] is None:       # preempted after packing
                active[s] = False
                chunk_rows.pop(s, None)
        if not active.any():
            if tier_progress and t_r0 is not None:
                # restore-only step: no compiled launch follows, but the
                # H2D restore dispatches above ARE this step's device work
                # — observe the host gap + step time here so the
                # tier-restore family shows up in the histogram the async
                # runtime's A/B measures (a silent family would make the
                # overlap look better than it is)
                self._note_launch(t_r0)
                self._note_step_done(t_r0)
            # tier restores are progress even when every lane's ROWS were
            # deferred or drained (a restore-only step must keep the serve
            # loop spinning until the plan finishes draining)
            return bool(self._queue) or tier_progress
        t0 = time.perf_counter()
        self._note_launch(t0)
        if self._flight is not None:
            # step-packing summary: O(1) per step, the flight recorder's
            # picture of what the scheduler chose when things went wrong
            self._flight.record("pack", step=self._step_no,
                                decode=len(decode_slots),
                                prefill=len(chunk_rows),
                                prefill_rows=int(sum(chunk_rows.values())))
        any_sampled = bool((self._temp * active).max() > 0)
        mixed = self._mixed_sampling if any_sampled else self._mixed_greedy
        self._arm_poison()
        try:
            self._host_fault("kernel_error")   # dispatch seam: pre-launch
            if self._graceful:
                nxt, bad, self.cache_k, self.cache_v = mixed(
                    self.params, self.cache_k, self.cache_v,
                    jnp.asarray(tokens), jnp.asarray(pos),
                    jnp.asarray(active), jnp.asarray(q_lens),
                    jnp.asarray(self._temp), jnp.asarray(self._topp),
                    jnp.asarray(self._seed), jnp.asarray(self._table),
                    poison=jnp.asarray(self._poison))
                self._host_overlap()   # journal upkeep rides the launch
                bad_np = np.asarray(bad)    # [B] emit-row guard flags
            else:
                nxt, self.cache_k, self.cache_v = mixed(
                    self.params, self.cache_k, self.cache_v,
                    jnp.asarray(tokens), jnp.asarray(pos),
                    jnp.asarray(active), jnp.asarray(q_lens),
                    jnp.asarray(self._temp), jnp.asarray(self._topp),
                    jnp.asarray(self._seed), jnp.asarray(self._table))
                self._host_overlap()
        except FaultInjected as e:
            return self._retry_launch(e)
        self._kernel_err_streak = 0
        self._poison[:] = False
        nxt_np = np.asarray(nxt)   # [B] — ONE host round-trip for the step
        self.stats["decode_time_s"] += time.perf_counter() - t0
        self._note_step_done(t0)
        self.stats["decode_steps"] += 1
        self.stats["mixed_steps"] += 1
        self.stats["prefill_chunks"] += len(chunk_rows)
        for s in decode_slots:
            req = self._slot_req[s]
            if req is None:
                continue            # preempted by _ensure_growth
            if self._graceful and bad_np[s]:
                self.stats["nan_guard_trips"] += 1
                self._fail_slot(s, "FAILED",
                                f"non-finite logits at position "
                                f"{int(self._pos[s])} (in-graph guard)",
                                donate=False)
                continue
            try:
                self._host_fault("slot_error", slot=s, rid=req.rid)
            except FaultInjected as e:
                if not self._graceful:
                    raise
                self._fail_slot(s, "FAILED", str(e), donate=False)
                continue
            old_pos = int(self._pos[s])
            self._pos[s] = old_pos + 1
            self._written[s] = max(int(self._written[s]),
                                   min(old_pos + 1, self.max_seq))
            self._consume_token(s, req, int(nxt_np[s]), t0)
            if (self._slot_req[s] is not None
                    and old_pos + 1 >= self.max_seq):
                self._retire(s)
        for s, n in chunk_rows.items():
            req = self._slot_req[s]
            if req is None:
                continue            # preempted after packing
            if self._graceful and bad_np[s]:
                # a poisoned prefill lane: the forward pass that computed
                # this chunk's K/V is not trusted — quarantine the request
                # before any of its progress (or blocks) is banked
                self.stats["nan_guard_trips"] += 1
                self._fail_slot(s, "FAILED",
                                f"non-finite logits while prefilling "
                                f"(cursor {int(self._prefilled[s])}; "
                                f"in-graph guard)", donate=False)
                continue
            ids = self._prefill_ids[s]
            new_cur = int(self._prefilled[s]) + n
            self._prefilled[s] = new_cur
            self._jmark(req.rid)   # chunk cursor advanced
            self._tracer.span(req.rid, "prefill_chunk", t0,
                              self._last_step_end,
                              args={"rows": n, "cursor": new_cur,
                                    "prompt": int(ids.size)})
            self._pos[s] = new_cur
            self._written[s] = max(int(self._written[s]),
                                   min(new_cur, self.max_seq))
            if self._pcache is not None:
                # register full freshly-computed prompt blocks as chunks
                # complete them (all content below new_cur is prompt tokens;
                # decode's first write lands at position >= ids.size, never
                # inside a block these cover)
                self._register_prefix_blocks(s, ids, new_cur)
            if new_cur >= ids.size:
                # final chunk: its emit row sat at the last prompt token's
                # position — consume the fused first decode token
                self._prefill_ids[s] = None
                self._prefilled[s] = 0
                self._consume_token(s, req, int(nxt_np[s]), t0)
                if (self._slot_req[s] is not None
                        and new_cur >= self.max_seq):
                    self._retire(s)
        self._maybe_audit()
        return True

    def _consume_token(self, slot: int, req: Request, tok: int, t0: float):
        """Bank one generated token on a slot (mixed-step emit): append,
        stamp TTFT, tick the throughput counter, advance the feedback token,
        and retire on EOS / budget — the single-token analog of the decode
        chunk's host trimming loop."""
        req.output_ids.append(tok)
        if req.ttft_s is None:
            req.ttft_s = time.perf_counter() - getattr(req, "_submit_s", t0)
        if self.slo is not None:
            self.slo.tokens(req.rid, 1, self._last_step_end
                            if self._last_step_end is not None
                            else time.perf_counter())
        self.stats["decode_tokens"] += 1
        self._last_tok[slot] = tok
        self._jmark(req.rid)   # token bank advanced the journal entry
        if (len(req.output_ids) >= req.max_new_tokens
                or (req.eos_token_id is not None
                    and tok == req.eos_token_id)):
            self._retire(slot)

    # ---------------- speculative scheduling (host control plane) ----------

    def _draft_proposals(self) -> dict[int, np.ndarray] | None:
        """Run the prompt-lookup drafter over every active slot's
        prompt+generated history.  Returns {slot: drafts} when at least one
        slot proposed something, else None (the caller then takes the
        ordinary decode path).  Drafts are capped so the verify step never
        writes past max_seq and never drafts past the request's remaining
        token budget (both would be pure wasted verify lanes)."""
        out: dict[int, np.ndarray] = {}
        any_draft = False
        for slot, req in enumerate(self._slot_req):
            if req is None:
                continue
            if self._chunked and self._prefill_ids[slot] is not None:
                # a slot still streaming its prompt has no token to draft
                # from (step() routes to the mixed path while any prompt is
                # in flight, so this is belt-and-braces for direct callers)
                out[slot] = np.zeros(0, np.int32)
                continue
            cap = min(self.max_seq - 1 - int(self._pos[slot]),
                      req.max_new_tokens - len(req.output_ids) - 1)
            if cap <= 0:
                out[slot] = np.zeros(0, np.int32)
                continue
            ctx = np.concatenate(
                [np.asarray(req.prompt_ids, np.int32).ravel(),
                 np.asarray(req.output_ids, np.int32)])
            d = self._spec.propose(ctx)[:cap]
            out[slot] = d
            if d.size:
                any_draft = True
        return out if any_draft else None

    def _spec_step(self, drafts: dict[int, np.ndarray]) -> bool:
        """One draft-verify-accept round: grow pages for every slot's
        appends, run the compiled verify step once (ONE host round-trip for
        up to K+1 tokens per slot), emit the accepted run + the target's
        correction token, and roll ``pos`` back past any rejected drafts —
        their K/V writes stay behind as dead bytes above pos (tracked by
        ``_written``, overwritten by the next step, never content-addressed
        into the prefix cache because every cache registration trusts only
        positions below pos)."""
        B = self.max_batch
        Q = self._spec_qmax
        qlens = np.ones(B, np.int64)
        for s, d in drafts.items():
            qlens[s] = 1 + d.size
        self._ensure_growth(qlens)  # may preempt the youngest slot
        active_np = np.asarray([r is not None for r in self._slot_req])
        if not active_np.any():
            return False
        tokens = np.zeros((B, Q), np.int32)
        tokens[:, 0] = self._last_tok
        q_lens = np.ones(B, np.int32)
        for s, d in drafts.items():
            if self._slot_req[s] is None or d.size == 0:
                continue  # preempted after drafting, or no proposal
            tokens[s, 1:1 + d.size] = d
            q_lens[s] = 1 + d.size
        t0 = time.perf_counter()
        self._note_launch(t0)
        any_sampled = bool((self._temp * active_np).max() > 0)
        verify = self._verify_sampling if any_sampled else self._verify_greedy
        self._arm_poison()
        try:
            self._host_fault("kernel_error")   # dispatch seam: pre-launch
            if self._graceful:
                out, n_acc, bad, self.cache_k, self.cache_v = verify(
                    self.params, self.cache_k, self.cache_v,
                    jnp.asarray(tokens), jnp.asarray(self._pos),
                    jnp.asarray(active_np), jnp.asarray(q_lens),
                    jnp.asarray(self._temp), jnp.asarray(self._topp),
                    jnp.asarray(self._seed), jnp.asarray(self._table),
                    poison=jnp.asarray(self._poison))
                self._host_overlap()   # journal upkeep rides the launch
                bad_np = np.asarray(bad)    # [B] per-slot guard flags
            else:
                out, n_acc, self.cache_k, self.cache_v = verify(
                    self.params, self.cache_k, self.cache_v,
                    jnp.asarray(tokens), jnp.asarray(self._pos),
                    jnp.asarray(active_np), jnp.asarray(q_lens),
                    jnp.asarray(self._temp), jnp.asarray(self._topp),
                    jnp.asarray(self._seed), jnp.asarray(self._table))
                self._host_overlap()
        except FaultInjected as e:
            return self._retry_launch(e)
        self._kernel_err_streak = 0
        self._poison[:] = False
        out_np = np.asarray(out)
        n_np = np.asarray(n_acc)
        self.stats["decode_time_s"] += time.perf_counter() - t0
        self._note_step_done(t0)
        now = self._last_step_end   # banking-event timestamp (SLO tracker)
        self.stats["decode_steps"] += 1
        self.stats["spec_steps"] += 1
        for slot, req in enumerate(self._slot_req):
            if req is None:
                continue
            old_pos = int(self._pos[slot])
            if self._graceful and bad_np[slot]:
                # the whole verify output for this slot is discarded (its
                # correction token came from a zeroed row); quarantine it
                self.stats["nan_guard_trips"] += 1
                self._fail_slot(slot, "FAILED",
                                f"non-finite logits at position {old_pos} "
                                f"(verify step; in-graph guard)",
                                donate=False)
                continue
            try:
                self._host_fault("slot_error", slot=slot, rid=req.rid)
            except FaultInjected as e:
                if not self._graceful:
                    raise
                self._fail_slot(slot, "FAILED", str(e), donate=False)
                continue
            n = int(n_np[slot])        # 1..q_lens: accepted run + correction
            drafted = int(q_lens[slot]) - 1
            self.stats["spec_drafted_tokens"] += drafted
            self.stats["spec_accepted_tokens"] += n - 1
            self.stats["spec_rejected_tokens"] += drafted - (n - 1)
            done = False
            banked = 0
            for j in range(n):
                tok = int(out_np[slot, j])
                req.output_ids.append(tok)
                banked += 1
                if req.ttft_s is None:
                    req.ttft_s = (time.perf_counter()
                                  - getattr(req, "_submit_s", t0))
                self.stats["decode_tokens"] += 1
                if (len(req.output_ids) >= req.max_new_tokens
                        or (req.eos_token_id is not None
                            and tok == req.eos_token_id)):
                    done = True
                    break
            if self.slo is not None and banked:
                # one banking event: the accepted run arrives in one fetch
                self.slo.tokens(req.rid, banked, now)
            # rejection rollback: pos advances only past ACCEPTED tokens;
            # the high-water mark remembers how far the device EVER wrote
            # (a shorter draft after a long rejected one must not shrink it)
            self._written[slot] = max(int(self._written[slot]),
                                      min(old_pos + int(q_lens[slot]),
                                          self.max_seq))
            self._pos[slot] = old_pos + n
            self._last_tok[slot] = int(out_np[slot, n - 1])
            self._jmark(req.rid)   # accepted run advanced the journal
            if done or old_pos + n >= self.max_seq:
                self._retire(slot)
        self._maybe_audit()
        return True

    @property
    def spec_acceptance_rate(self) -> float:
        """Fraction of drafted tokens the verify step accepted (0.0 before
        any speculative step — also the spec-off value)."""
        d = self.stats["spec_drafted_tokens"]
        return self.stats["spec_accepted_tokens"] / d if d > 0 else 0.0

    def serve(self, requests: list[Request]) -> dict[int, list[int]]:
        """Run all requests to completion; returns {rid: generated tokens}.

        Graceful mode (the default): an invalid request is marked
        ``REJECTED`` (with ``error``) and the rest are served — one bad
        sampling param must not zero a whole batch's goodput.  With
        ``PADDLE_TPU_GRACEFUL=0`` validation is all-or-nothing: any bad
        request raises before anything is enqueued (the pre-fault-tolerance
        contract)."""
        if self._graceful:
            for r in requests:
                try:
                    self.add_request(r)
                except ValueError as e:
                    self._reqs[r.rid] = r
                    with RecordEvent("serving/rejected"):
                        self._terminal(r, "REJECTED", str(e))
        else:
            for r in requests:
                self._validate(r)  # all-or-nothing: nothing enqueued if any is bad
            for r in requests:
                self.add_request(r)
        while self.step() or self._queue:
            pass
        return {r.rid: r.output_ids for r in requests}

    @property
    def decode_tokens_per_s(self) -> float:
        t = self.stats["decode_time_s"]
        return self.stats["decode_tokens"] / t if t > 0 else 0.0

    def n_traces(self) -> int | None:
        """Total compiled program variants across this engine's jitted
        programs (decode greedy/sampling, prefill(s), COW copy) — the
        bench's jit-cache-churn telemetry: the expected count is small and
        static (one decode variant per sampling mode actually used + one
        prefill per warmed bucket), so growth across a serve is a silent
        recompile in the hot loop (paddle_tpu.analysis.n_traces)."""
        from ..analysis import n_traces as _n

        fns = [self._decode_greedy, self._decode_sampling, self._prefill]
        if self._pcache is not None:
            fns += [self._prefill_prefix, self._copy_page]
        if self._tier is not None:
            # the ship_in pool write: ONE variant for the whole serve
            # (page index and payload are data, shapes are static)
            fns += [self._tier_write]
        if self._spec is not None:
            # the verify step's query width is static (K+1): exactly one
            # variant per sampling mode actually used, regardless of how
            # ragged the per-step drafts were
            fns += [self._verify_greedy, self._verify_sampling]
        if self._chunked:
            # the mixed step's width is static (prefill_chunk): one variant
            # per sampling mode for every prompt length — the O(1) that
            # replaces the bucketed path's log2(max_seq) prefill family
            fns += [self._mixed_greedy, self._mixed_sampling]
        return _n(*fns)

    def _decode_step_trace(self):
        """Trace ONE greedy decode step to a ClosedJaxpr (no compile, no
        device time) under the CURRENT trace-time state (kill switches,
        fused/flash config) — the shared substrate of the static
        telemetry: :meth:`decode_step_launches` runs the launch census
        over it and :meth:`decode_step_card` the full program card.
        Returns ``(closed, donated)``: the impl is traced unjitted, so the
        production program's cache donation (``_jit_step``'s
        ``donate_argnums=(1, 2)``) is reconstructed as a per-leaf mask for
        the card's peak-HBM pass — without it the KV pools would count
        both as caller-held inputs and as fresh outputs."""
        B = self.max_batch
        zi = jnp.zeros((B,), jnp.int32)
        body = functools.partial(
            self._decode_impl_paged if self.paged else self._decode_impl,
            sampling=False, graceful=self._graceful)
        args = [self.params, self.cache_k, self.cache_v, zi, zi,
                jnp.ones((B,), bool), jnp.zeros((B,), jnp.float32),
                jnp.ones((B,), jnp.float32), zi]
        if self.paged:
            args.append(jnp.asarray(self._table))
        if self.tp > 1:
            body = self._tp_shard(body, n_rep=2 if self._graceful else 1)
        # telemetry must not contaminate the dispatch counters: the trace
        # below executes the kernels' Python dispatch, which would tick
        # KERNEL/FLASH/FUSED_*_CALLS by one launch the serve never ran —
        # exactly the per-rung contamination reset_kernel_counters() exists
        # to prevent.  Snapshot and restore around the trace.
        from ..ops.pallas import paged_attention as _pa

        counter_names = ("KERNEL_CALLS", "FALLBACK_CALLS",
                         "FLASH_KERNEL_CALLS", "LAST_FLASH_SHARDS",
                         "FUSED_KERNEL_CALLS", "FUSED_FALLBACK_CALLS",
                         "MLP_KERNEL_CALLS", "MLP_FALLBACK_CALLS",
                         "QUANT_APPEND_KERNEL_CALLS",
                         "QUANT_APPEND_FALLBACK_CALLS")
        saved = {n: getattr(_pa, n) for n in counter_names}
        try:
            closed = jax.make_jaxpr(body)(*args)
        finally:
            for n, v in saved.items():
                setattr(_pa, n, v)
        donated = tuple(i in self._STEP_DONATE_ARGNUMS
                        for i, a in enumerate(args)
                        for _ in jax.tree_util.tree_leaves(a))
        return closed, donated

    def decode_step_launches(self) -> dict:
        """Static dispatch-tax telemetry for ONE greedy decode step: trace
        the decode program and count its equations plus the per-layer
        launch-shaped primitives — every ``pallas_call`` and every scatter
        (the KV appends) — via the ONE census implementation the static
        program card uses (``analysis.cost_model.eqn_census``; a parity
        test pins static card == this telemetry).  The fused decode step's
        win is visible here before any wall clock: the unfused paged path
        traces 1 pallas_call + 2 scatters per layer (plus the rope/gather
        glue XLA must fuse around them), the fused path traces 1
        pallas_call and 0 scatters — the bench rungs report this dict as
        the launch-count detail (eqns inside the chunk scan's per-step
        body count once, matching the per-layer dispatch they model)."""
        from ..analysis.cost_model import eqn_census

        closed, _ = self._decode_step_trace()
        counts = eqn_census(closed)
        counts["fused_decode"] = bool(self._fused)
        counts["fused_mlp"] = bool(self._fused_mlp)
        counts["kv_quant"] = self.kv_quant
        return counts

    def decode_step_card(self) -> dict:
        """Static ProgramCard summary of ONE greedy decode step
        (analysis/cost_model.py): peak live HBM, launch census, per-launch
        VMEM fit, and the kernel-contract aggregate (bounds / race /
        alias verdicts over every pallas launch,
        analysis/kernel_contracts.py) — embedded by the cb bench rungs
        next to ``decode_step_launches`` so a rung's detail carries the
        program's static cost AND its kernel-soundness verdicts alongside
        its measured wall clock.  The host-contract sections
        (analysis/host_contracts.py) ride along the same way: this engine
        IS the async host runtime the pass verifies, so the rung detail
        carries the overlap-window race/blocking verdicts and
        state-machine coverage beside the kernel ones.  Trace-only, like
        the launch telemetry; collective bytes are not compiled here (the
        TP gate target owns that figure) and trace-family accounting
        lives with ``n_traces()``."""
        from ..analysis.cost_model import build_card
        from ..analysis.host_contracts import check_host_contracts

        closed, donated = self._decode_step_trace()
        card = build_card(None, (), target="decode_step", closed=closed,
                          donated=donated, compile_collectives=False,
                          host_contracts=check_host_contracts(
                              target="decode_step")[1])
        d = card.summary()
        d["fused_decode"] = bool(self._fused)
        d["fused_mlp"] = bool(self._fused_mlp)
        d["kv_quant"] = self.kv_quant
        return d
