"""Continuous-batching decode scheduler (VERDICT r2 #6).

Reference analog: the serving stack behind the reference's fused block
attention family — `paddle/phi/ops/yaml/fused_ops.yaml:45`
(``block_multihead_attention_``) and `:394` (``fused_multi_transformer_``) —
which backs PaddleNLP's continuous-batching servers.

TPU-first design
----------------
A TPU serving engine wants *static shapes*: one compiled decode step over a
fixed slot pool, re-run every iteration.  So instead of the reference's
dynamic batch, we keep:

  * a slot pool of ``max_batch`` lanes in one shared dense KV cache
    [L, max_batch, nkv, S, hd] — a lane is the TPU analog of a block table
    entry (HBM is pre-reserved; XLA gets a fixed layout to tile),
  * one jitted decode step with a *per-slot position vector* — slots at
    different depths decode together in a single batched program (this is
    what "continuous batching" means at the kernel level: the batch never
    drains to admit a newcomer),
  * prefill into a single lane with bucketed prompt padding (powers of two),
    bounding the number of compiled prefill variants to log2(max_seq).

``paged=True`` swaps the per-slot dense lanes for a BLOCK-TABLE cache (the
reference's ``block_multihead_attention_`` memory model, fused_ops.yaml:45):
K/V live in a fixed pool of [num_blocks, nkv, block_size, hd] pages per
layer, each slot owns a host-managed list of block ids, and the compiled
programs receive the [max_batch, max_blocks] table AS DATA — shapes stay
static (the TPU requirement) while HBM is shared by actual usage, so
admission is bounded by free blocks rather than worst-case max_seq lanes.
Decode attention dispatches to the ragged paged-attention Pallas kernel
(`ops/pallas/paged_attention.py`, docs/paged_attention.md), which walks only
each slot's LIVE block-table pages — HBM bytes per step scale with resident
tokens, not the longest request; with the kernel disabled
(``PADDLE_TPU_DISABLE_PALLAS=paged_attention``) or on unsupported shapes,
attention reads a gathered view of the slot's blocks (XLA fuses the block
gather into the attention contraction's operand read); when the pool runs
dry the youngest slot is preempted vLLM-style (blocks freed, request
requeued with prompt+generated so far; the stored tokens are teacher-forced
on resume, which makes the recompute exact for greedy AND sampled decode).

Per-request sampling (reference: ``top_p_sampling``, ops.yaml:4947) runs
inside the jitted step: temperature/top-p/seed are per-slot DATA vectors, so
one compiled program serves mixed greedy/sampled batches, and RNG keys
derive from (slot seed, position) — deterministic, replayable streams.

Admission/retirement/allocation is plain Python around the compiled
programs — scheduling is control-plane work and costs microseconds next to
a device step, the same split the reference makes between its C++ scheduler
and CUDA kernels.
"""

from __future__ import annotations

import functools
import time
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["Request", "ContinuousBatchingEngine"]


@dataclass
class Request:
    rid: int
    prompt_ids: np.ndarray  # [s0] int32
    max_new_tokens: int = 32
    eos_token_id: int | None = None
    # per-request sampling (reference: top_p_sampling,
    # paddle/phi/ops/yaml/ops.yaml:4947).  temperature == 0 -> greedy.
    temperature: float = 0.0
    top_p: float = 1.0
    seed: int | None = None
    # filled by the engine
    output_ids: list = field(default_factory=list)
    finished: bool = False


def _bucket(n: int, lo: int = 16) -> int:
    b = lo
    while b < n:
        b *= 2
    return b


class ContinuousBatchingEngine:
    """Slot-pool continuous batching over a Llama-family model.

    ``cfg``/``params`` follow paddle_tpu.models.llama conventions (the same
    pytree the AOT GenerationEngine uses, inference/__init__.py:249).
    """

    def __init__(self, cfg, params, max_batch: int = 8, max_seq: int = 512,
                 chunk: int = 1, quant: str | None = None, paged: bool = False,
                 block_size: int = 64, num_blocks: int | None = None):
        """``chunk``: decode steps per compiled call.  Tokens feed back
        on-device inside a lax.scan and the host fetches ``chunk`` tokens per
        round-trip — the lever against host-device latency (one RTT per token
        is what bounds single-step decode on a relay-attached TPU).  Retire
        and admission happen at chunk granularity; generated tokens past a
        request's EOS/budget inside a chunk are trimmed host-side.
        ``quant``: None | 'int8' | 'int4' — weight-only quantized matmuls
        (weights stream from HBM at 1/2 or 1/4 the bytes).
        ``paged``: block-table KV cache (``block_size`` tokens per page,
        ``num_blocks`` pages shared by all slots; default num_blocks gives
        half the dense pool's capacity — the paged mode's point is serving
        more logical context than physically reserved HBM)."""
        from ..models import llama as _llama  # noqa: F401  (cfg type lives there)

        self.cfg = cfg
        if quant is not None:
            from . import quantize_layer_params

            params = quantize_layer_params(params, quant)
        self.quant = quant
        self.params = params
        self.max_batch = max_batch
        self.max_seq = max_seq
        self.chunk = int(chunk)
        self.paged = bool(paged)
        L = cfg.num_hidden_layers
        nkv, hd = cfg.num_key_value_heads, cfg.head_dim
        if paged:
            assert max_seq % block_size == 0, (max_seq, block_size)
            self.block_size = block_size
            self.max_blocks = max_seq // block_size     # per-slot logical cap
            self.num_blocks = (num_blocks if num_blocks is not None
                               else (max_batch * self.max_blocks) // 2)
            assert self.num_blocks >= self.max_blocks, (
                f"pool of {self.num_blocks} blocks cannot hold one full "
                f"request ({self.max_blocks} blocks)")
            shape = (L, self.num_blocks, nkv, block_size, hd)
            # host allocator state
            self._free: list[int] = list(range(self.num_blocks))
            self._slot_blocks: list[list[int]] = [[] for _ in range(max_batch)]
            # sentinel num_blocks = unallocated (oob: writes drop, reads are
            # masked by the causal/active mask before they matter)
            self._table = np.full((max_batch, self.max_blocks),
                                  self.num_blocks, np.int32)
            self._admit_seq = 0
            self._slot_age = np.zeros(max_batch, np.int64)
        else:
            shape = (L, max_batch, nkv, max_seq, hd)
        self.cache_k = jnp.zeros(shape, cfg.dtype)
        self.cache_v = jnp.zeros(shape, cfg.dtype)
        # slot state (host side)
        self._slot_req: list[Request | None] = [None] * max_batch
        self._pos = np.zeros(max_batch, np.int32)      # next write position
        self._last_tok = np.zeros(max_batch, np.int32)
        # per-slot sampling state (temperature 0 = greedy; one compiled
        # program serves mixed greedy/sampled batches — the knobs are DATA)
        self._temp = np.zeros(max_batch, np.float32)
        self._topp = np.ones(max_batch, np.float32)
        self._seed = np.zeros(max_batch, np.int32)
        self._queue: list[Request] = []
        impl = self._decode_impl_paged if paged else self._decode_impl
        # two decode variants behind a STATIC sampling flag: the full-vocab
        # sort/softmax/categorical of the sampler must not run (XLA cannot
        # DCE work behind a data-dependent where) when every resident slot
        # is greedy — the bench headline's configuration
        self._decode_greedy = jax.jit(
            functools.partial(impl, sampling=False), donate_argnums=(1, 2))
        self._decode_sampling = jax.jit(
            functools.partial(impl, sampling=True), donate_argnums=(1, 2))
        # prefill writes its lane directly into the donated pool arrays —
        # no slice-out/scatter-back copies of the full pool per admission
        pimpl = self._prefill_impl_paged if paged else self._prefill_impl
        self._prefill = jax.jit(pimpl, donate_argnums=(2, 3),
                                static_argnums=(6,))
        self.stats = {"decode_steps": 0, "decode_tokens": 0,
                      "prefills": 0, "decode_time_s": 0.0, "preemptions": 0}

    # ---------------- compiled programs ----------------

    def _decode_one(self, params, cache_k, cache_v, tokens, pos, active,
                    table=None):
        """One batched decode step: tokens [B], pos [B], active [B] ->
        (logits [B, V], caches).  Inactive slots compute garbage that is
        masked out — the static batch is the price of a single compiled
        program, and idle lanes are cheap next to recompiling (the standard
        TPU serving trade).

        With ``table`` (paged mode) the K/V write lands in pool page
        table[b, pos//bs] at offset pos%bs and attention reads a gathered
        [B, nkv, max_seq, hd] view of each slot's pages (the reference's
        block_multihead_attention memory model; the gather fuses into the
        attention contraction)."""
        from .. import inference as _inf
        from ..ops.pallas import rope as rope_mod

        cfg = self.cfg
        B = self.max_batch
        S = self.max_seq
        nkv, hd = cfg.num_key_value_heads, cfg.head_dim
        x = jnp.take(params["embed"], tokens[:, None], axis=0).astype(cfg.dtype)
        cos_full, sin_full = rope_mod.rope_cos_sin(S, cfg.head_dim,
                                                   base=cfg.rope_theta,
                                                   dtype=cfg.dtype)
        safe_pos = jnp.where(active & (pos < S), pos, 0)
        cos = jnp.take(cos_full[0], safe_pos, axis=0)[:, None]  # [B, 1, d]
        sin = jnp.take(sin_full[0], safe_pos, axis=0)[:, None]
        kv_pos = jnp.arange(S)[None, None, None, None, :]
        mask = ((kv_pos <= pos[:, None, None, None, None])
                & active[:, None, None, None, None])
        lane = jnp.arange(B)
        writeable = active & (pos < S)
        attend_fn = None

        if table is None:
            def write(ck, k):
                # ck [B, nkv, S, hd]; k [B, 1, nkv, hd] — per-slot scatter at
                # each slot's own depth (drop writes from inactive/oob lanes)
                upd = jnp.where(writeable[:, None, None], k[:, 0],
                                ck[lane, :, safe_pos])
                out = ck.at[lane, :, safe_pos].set(upd)
                return out, out
        else:
            from ..ops import decode_attention as _da
            from ..ops.pallas import paged_attention as _pa

            bs_ = self.block_size
            blk = table[lane, safe_pos // bs_]                   # [B]
            off = safe_pos % bs_
            drop_blk = jnp.where(writeable, blk, self.num_blocks)  # oob -> drop
            nh = cfg.num_attention_heads
            # trace-time dispatch: the ragged Pallas kernel walks only each
            # slot's live pages (PADDLE_TPU_DISABLE_PALLAS=paged_attention
            # routes back to the gather oracle below)
            use_kernel = _pa.kernel_supported(nh, nkv, hd, bs_)

            def write(ck, k):
                # ck [num_blocks, nkv, bs, hd].  Allocator invariant:
                # distinct slots own disjoint pages — no scatter collisions.
                out = ck.at[drop_blk, :, off].set(k[:, 0], mode="drop")
                if use_kernel:
                    # attention reads the paged pool directly — no
                    # [B, nkv, S, hd] gather materializes per layer per step
                    return out, out
                # unallocated (sentinel) pages read as ZEROS — jnp.take's
                # default oob mode fills NaN, and 0*NaN through the masked
                # softmax would poison the whole row
                view = jnp.take(out, table, axis=0, mode="fill", fill_value=0)
                view = view.transpose(0, 2, 1, 3, 4).reshape(B, nkv, S, hd)
                return out, view

            if use_kernel:
                seq_now = safe_pos + 1  # incl. the token written this step

                def attend_fn(q, k_pool, v_pool):
                    # q [B, 1, nh, hd] post-rope; sentinel table entries are
                    # clamped in-kernel and masked by seq_now; inactive
                    # lanes attend one stale position (finite, masked out
                    # downstream like the dense path's garbage lanes)
                    o = _da.paged_decode_attention(q[:, 0], k_pool, v_pool,
                                                   table, seq_now)
                    return o.reshape(B, 1, nh * hd)

        x, ak, av = _inf.transformer_apply(cfg, params, x, cache_k, cache_v,
                                           write, mask, cos, sin,
                                           attend_fn=attend_fn)
        return _inf.lm_head_logits(cfg, params, x[:, -1]), ak, av

    def _sample_tokens(self, logits, pos, temp, topp, seeds):
        """Per-slot next-token choice inside the compiled step: greedy where
        temperature == 0, temperature + nucleus (top-p) sampling elsewhere
        (reference: top_p_sampling, ops.yaml:4947).  The RNG key is derived
        deterministically from (slot seed, position): sampling is replayable,
        and a preempted-then-resumed request continues its stream exactly
        (resume teacher-forces the stored tokens, then position-derived keys
        make the continuation draw what it would have drawn)."""
        B = self.max_batch
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        scaled = (logits.astype(jnp.float32)
                  / jnp.maximum(temp, 1e-6)[:, None])
        # nucleus mask via sorted cumsum: keep the smallest prefix of
        # descending-prob tokens whose mass reaches top_p (top-1 always kept)
        order = jnp.argsort(-scaled, axis=-1)
        sprob = jax.nn.softmax(jnp.take_along_axis(scaled, order, axis=-1),
                               axis=-1)
        keep_sorted = (jnp.cumsum(sprob, axis=-1) - sprob) < topp[:, None]
        keep = jnp.zeros_like(keep_sorted).at[
            jnp.arange(B)[:, None], order].set(keep_sorted)
        masked = jnp.where(keep, scaled, -jnp.inf)
        keys = jax.vmap(lambda s, p: jax.random.fold_in(
            jax.random.fold_in(jax.random.PRNGKey(0), s), p))(seeds, pos)
        sampled = jax.vmap(jax.random.categorical)(keys, masked)
        return jnp.where(temp > 0.0, sampled.astype(jnp.int32), greedy)

    def _chunk_scan(self, params, cache_k, cache_v, tokens, pos, active,
                    temp, topp, seeds, table=None, sampling=False):
        """``chunk`` decode steps in one compiled program; the chosen token
        feeds back on-device (no host round-trip inside the chunk).
        ``sampling`` is STATIC: the greedy variant compiles without the
        sampler's full-vocab sort.  Returns (tokens [chunk, B], caches)."""

        def one(carry, _):
            ck, cv, tok, p = carry
            logits, ck, cv = self._decode_one(params, ck, cv, tok, p, active,
                                              table)
            if sampling:
                nxt = self._sample_tokens(logits, p, temp, topp, seeds)
            else:
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return (ck, cv, nxt, p + 1), nxt

        (ck, cv, _, _), toks = jax.lax.scan(
            one, (cache_k, cache_v, tokens, pos), None, length=self.chunk)
        return toks, ck, cv

    def _decode_impl(self, params, cache_k, cache_v, tokens, pos, active,
                     temp, topp, seeds, sampling=False):
        return self._chunk_scan(params, cache_k, cache_v, tokens, pos, active,
                                temp, topp, seeds, sampling=sampling)

    def _prefill_body(self, params, ids, cache_k, cache_v, length, bucket,
                      write):
        """Shared prefill: embed/rope/mask once, write-path injected (dense
        lane vs paged block table) so mask/rope fixes cannot diverge.

        Tokens at or beyond ``length`` are padding and masked out of attention
        (they still write cache positions, which the causal mask makes
        unreachable until the slot's pos pointer passes them — it never does,
        decode overwrites).  No logits are computed: the last real prompt
        token is fed to the first decode step instead (standard split)."""
        from .. import inference as _inf
        from ..ops.pallas import rope as rope_mod

        cfg = self.cfg
        S = self.max_seq
        x = jnp.take(params["embed"], ids, axis=0).astype(cfg.dtype)
        cos_full, sin_full = rope_mod.rope_cos_sin(S, cfg.head_dim,
                                                   base=cfg.rope_theta,
                                                   dtype=cfg.dtype)
        cos = cos_full[:, :bucket]
        sin = sin_full[:, :bucket]
        kv_pos = jnp.arange(S)[None, None, None, None, :]
        q_pos = jnp.arange(bucket)[None, None, None, :, None]
        mask = (kv_pos <= q_pos) & (kv_pos < length)
        _, ak, av = _inf.transformer_apply(cfg, params, x, cache_k, cache_v,
                                           write, mask, cos, sin)
        return ak, av

    def _prefill_impl(self, params, ids, cache_k, cache_v, slot, length, bucket):
        """Prefill one request (batch 1, prompt padded to ``bucket``) directly
        into lane ``slot`` of the (donated) cache pools."""
        cfg = self.cfg
        S = self.max_seq
        nkv = cfg.num_key_value_heads

        def write(ck, k):
            # ck [B, nkv, S, hd] pool layer; commit this request's K/V
            # into lane `slot` positions [0:bucket], attend on that lane
            out = jax.lax.dynamic_update_slice(
                ck, k.transpose(0, 2, 1, 3), (slot, 0, 0, 0))
            view = jax.lax.dynamic_slice(
                out, (slot, 0, 0, 0), (1, nkv, S, cfg.head_dim))
            return out, view

        return self._prefill_body(params, ids, cache_k, cache_v, length,
                                  bucket, write)

    # ---------------- paged (block-table) compiled programs ----------------

    def _decode_impl_paged(self, params, cache_k, cache_v, tokens, pos, active,
                           temp, topp, seeds, table, sampling=False):
        return self._chunk_scan(params, cache_k, cache_v, tokens, pos, active,
                                temp, topp, seeds, table, sampling=sampling)

    def _prefill_impl_paged(self, params, ids, cache_k, cache_v, table_row,
                            length, bucket):
        """Prefill into the slot's pages: prompt position j writes page
        table_row[j // bs] offset j % bs; padding positions whose page is
        the unallocated sentinel drop (and are masked from attention)."""
        cfg = self.cfg
        S = self.max_seq
        bs_ = self.block_size
        nkv, hd = cfg.num_key_value_heads, cfg.head_dim
        j = jnp.arange(bucket)
        blk_j = table_row[j // bs_]                          # [bucket]
        off_j = j % bs_

        def write(ck, k):
            # k [1, bucket, nkv, hd] -> scatter each prompt position into
            # its page; view = this slot's gathered pages, batch-1
            out = ck.at[blk_j, :, off_j].set(k[0], mode="drop")
            view = jnp.take(out, table_row, axis=0,          # [maxblk, nkv, bs, hd]
                            mode="fill", fill_value=0)       # sentinel -> zeros
            view = view.transpose(1, 0, 2, 3).reshape(1, nkv, S, hd)
            return out, view

        return self._prefill_body(params, ids, cache_k, cache_v, length,
                                  bucket, write)

    # ---------------- block allocator (host control plane) ----------------

    def _blocks_needed(self, last_pos: int) -> int:
        return min(last_pos, self.max_seq - 1) // self.block_size + 1

    def _alloc_to(self, slot: int, n_blocks: int) -> bool:
        """Grow slot to n_blocks pages; False if the pool runs dry."""
        owned = self._slot_blocks[slot]
        while len(owned) < n_blocks:
            if not self._free:
                return False
            b = self._free.pop()
            self._table[slot, len(owned)] = b
            owned.append(b)
        return True

    def _release(self, slot: int):
        self._free.extend(self._slot_blocks[slot])
        self._slot_blocks[slot] = []
        self._table[slot, :] = self.num_blocks

    def _preempt(self, slot: int):
        """vLLM-style recompute preemption: free the slot, requeue the
        request with prompt + generated-so-far.  Sampling-safe: resume
        teacher-forces the STORED sampled tokens (no re-decode of history),
        and the continuation's RNG keys derive from (seed, position), so the
        stream picks up exactly where it left off."""
        req = self._slot_req[slot]
        ids = np.concatenate([np.asarray(req.prompt_ids, np.int32).ravel(),
                              np.asarray(req.output_ids, np.int32)])
        req._resume_ids = ids
        # keep seniority across the round trip: a resumed request must not
        # become the youngest slot and the repeat victim (preemption thrash)
        req._resume_age = int(self._slot_age[slot])
        self._release(slot)
        self._slot_req[slot] = None
        self._temp[slot] = 0.0  # re-set on readmission
        self._queue.insert(0, req)
        self.stats["preemptions"] += 1

    def _ensure_growth(self, k: int):
        """Before a decode chunk: every active slot needs pages covering
        positions up to pos+k-1.  Oldest slots win; when the pool is dry the
        youngest active slot is preempted and its pages recycled."""
        order = sorted((s for s in range(self.max_batch)
                        if self._slot_req[s] is not None),
                       key=lambda s: self._slot_age[s])
        for slot in order:
            if self._slot_req[slot] is None:
                continue  # preempted by an older slot this pass
            need = self._blocks_needed(int(self._pos[slot]) + k - 1)
            while not self._alloc_to(slot, need):
                victims = [s for s in range(self.max_batch)
                           if s != slot and self._slot_req[s] is not None]
                if not victims:
                    raise RuntimeError(
                        "KV block pool exhausted by a single request; "
                        "increase num_blocks")
                self._preempt(max(victims, key=lambda s: self._slot_age[s]))

    # ---------------- scheduler ----------------

    def _validate(self, req: Request):
        ids = np.asarray(req.prompt_ids, np.int32).ravel()
        if ids.size == 0:
            raise ValueError(f"request {req.rid}: empty prompt")
        if ids.size > self.max_seq - 1:
            raise ValueError(
                f"request {req.rid}: prompt length {ids.size} exceeds "
                f"max_seq-1 = {self.max_seq - 1}")
        if (req.temperature or 0.0) < 0:  # None -> greedy
            raise ValueError(f"request {req.rid}: temperature must be >= 0")
        if not 0 < (req.top_p if req.top_p is not None else 1.0) <= 1:
            raise ValueError(f"request {req.rid}: top_p must be in (0, 1]")

    def add_request(self, req: Request):
        self._validate(req)
        self._queue.append(req)

    def _admit(self):
        """Fill free slots from the queue (prefill path).  Paged mode admits
        by free-page count: a request enters only when its prompt's pages
        are allocatable — the block-table analog of "is a lane free"."""
        for slot in range(self.max_batch):
            if self._slot_req[slot] is not None or not self._queue:
                continue
            req = self._queue[0]
            # a preempted request resumes with prompt + generated-so-far
            ids = getattr(req, "_resume_ids", None)
            if ids is None:
                ids = np.asarray(req.prompt_ids, np.int32).ravel()
            s0 = ids.size
            if self.paged:
                # admit only if the prompt's pages fit AND the active slots'
                # imminent growth (next chunk) keeps its headroom — otherwise
                # a fresh admit would be preempted by _ensure_growth in the
                # same step, wasting its full-prompt prefill
                headroom = sum(
                    self._blocks_needed(int(self._pos[s]) + self.chunk - 1)
                    - len(self._slot_blocks[s])
                    for s in range(self.max_batch)
                    if self._slot_req[s] is not None)
                need = self._blocks_needed(s0 - 1)
                # gate on the new slot's own first-chunk growth too, or
                # _ensure_growth would preempt someone in this same step
                gate = self._blocks_needed(s0 - 2 + self.chunk)
                if (len(self._free) < gate + headroom
                        or not self._alloc_to(slot, need)):
                    # roll back any partial allocation on this EMPTY slot —
                    # stranded pages are invisible to every release path
                    self._release(slot)
                    break  # pool dry: keep queue order, retry next step
                age = getattr(req, "_resume_age", None)
                self._slot_age[slot] = self._admit_seq if age is None else age
                self._admit_seq += 1
            self._queue.pop(0)
            if hasattr(req, "_resume_ids"):
                del req._resume_ids
            if hasattr(req, "_resume_age"):
                del req._resume_age
            bucket = min(_bucket(s0), self.max_seq)
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :s0] = ids
            # the last real token is fed to decode, not prefill, so its
            # logits come from the decode step (standard split)
            slot_arg = (jnp.asarray(self._table[slot]) if self.paged
                        else jnp.asarray(slot, jnp.int32))
            self.cache_k, self.cache_v = self._prefill(
                self.params, jnp.asarray(padded), self.cache_k, self.cache_v,
                slot_arg, jnp.asarray(s0 - 1, jnp.int32), bucket)
            self._slot_req[slot] = req
            self._pos[slot] = s0 - 1
            self._last_tok[slot] = ids[-1]
            self._temp[slot] = max(float(req.temperature or 0.0), 0.0)
            self._topp[slot] = float(req.top_p if req.top_p is not None
                                     else 1.0)
            # default seed: the request id, so two concurrent sampled
            # requests never share a stream
            self._seed[slot] = np.int32(
                req.seed if req.seed is not None else req.rid)
            self.stats["prefills"] += 1

    def _retire(self, slot):
        self._slot_req[slot].finished = True
        self._slot_req[slot] = None
        self._temp[slot] = 0.0  # freed slot must not pin the sampling variant
        if self.paged:
            self._release(slot)

    def step(self) -> bool:
        """One admit + decode-chunk iteration.  Returns False when idle."""
        self._admit()
        k = self.chunk
        if self.paged:
            self._ensure_growth(k)  # may preempt the youngest slot
        active_np = np.asarray([r is not None for r in self._slot_req])
        if not active_np.any():
            return False
        t0 = time.perf_counter()
        extra = (jnp.asarray(self._table),) if self.paged else ()
        # greedy-only resident set takes the sampler-free compiled variant
        any_sampled = bool((self._temp * active_np).max() > 0)
        decode = self._decode_sampling if any_sampled else self._decode_greedy
        toks, self.cache_k, self.cache_v = decode(
            self.params, self.cache_k, self.cache_v,
            jnp.asarray(self._last_tok), jnp.asarray(self._pos),
            jnp.asarray(active_np), jnp.asarray(self._temp),
            jnp.asarray(self._topp), jnp.asarray(self._seed), *extra)
        toks_np = np.asarray(toks)  # [k, B] — ONE host round-trip per chunk
        self.stats["decode_time_s"] += time.perf_counter() - t0
        self.stats["decode_steps"] += k
        for slot, req in enumerate(self._slot_req):
            if req is None:
                continue
            old_pos = int(self._pos[slot])
            # tokens produced from positions >= max_seq are garbage (their
            # K/V writes were dropped): only the first max_seq - old_pos
            # chunk steps are trustworthy
            valid = min(k, self.max_seq - old_pos)
            done = False
            for j in range(valid):
                tok = int(toks_np[j, slot])
                req.output_ids.append(tok)
                # count only tokens a caller actually receives: chunk steps
                # past EOS / the token budget / max_seq are trimmed here, so
                # they must not inflate decode_tokens_per_s (the headline)
                self.stats["decode_tokens"] += 1
                if (len(req.output_ids) >= req.max_new_tokens
                        or (req.eos_token_id is not None
                            and tok == req.eos_token_id)):
                    done = True
                    break
            self._pos[slot] = old_pos + k  # device advanced k regardless
            self._last_tok[slot] = int(toks_np[-1, slot])
            if done or old_pos + k >= self.max_seq:
                self._retire(slot)
        return True

    def serve(self, requests: list[Request]) -> dict[int, list[int]]:
        """Run all requests to completion; returns {rid: generated tokens}."""
        for r in requests:
            self._validate(r)  # all-or-nothing: no request enqueued if any is bad
        for r in requests:
            self.add_request(r)
        while self.step() or self._queue:
            pass
        return {r.rid: r.output_ids for r in requests}

    @property
    def decode_tokens_per_s(self) -> float:
        t = self.stats["decode_time_s"]
        return self.stats["decode_tokens"] / t if t > 0 else 0.0
