"""Continuous-batching decode scheduler (VERDICT r2 #6).

Reference analog: the serving stack behind the reference's fused block
attention family — `paddle/phi/ops/yaml/fused_ops.yaml:45`
(``block_multihead_attention_``) and `:394` (``fused_multi_transformer_``) —
which backs PaddleNLP's continuous-batching servers.

TPU-first design
----------------
A TPU serving engine wants *static shapes*: one compiled decode step over a
fixed slot pool, re-run every iteration.  So instead of the reference's
dynamic batch + paged block tables, we keep:

  * a slot pool of ``max_batch`` lanes in one shared dense KV cache
    [L, max_batch, nkv, S, hd] — a lane is the TPU analog of a block table
    entry (HBM is pre-reserved; XLA gets a fixed layout to tile),
  * one jitted decode step with a *per-slot position vector* — slots at
    different depths decode together in a single batched program (this is
    what "continuous batching" means at the kernel level: the batch never
    drains to admit a newcomer),
  * prefill into a single lane with bucketed prompt padding (powers of two),
    bounding the number of compiled prefill variants to log2(max_seq).

Admission/retirement is plain Python around the two compiled programs —
scheduling is control-plane work and costs microseconds next to a device
step, the same split the reference makes between its C++ scheduler and CUDA
kernels.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["Request", "ContinuousBatchingEngine"]


@dataclass
class Request:
    rid: int
    prompt_ids: np.ndarray  # [s0] int32
    max_new_tokens: int = 32
    eos_token_id: int | None = None
    # filled by the engine
    output_ids: list = field(default_factory=list)
    finished: bool = False


def _bucket(n: int, lo: int = 16) -> int:
    b = lo
    while b < n:
        b *= 2
    return b


class ContinuousBatchingEngine:
    """Slot-pool continuous batching over a Llama-family model.

    ``cfg``/``params`` follow paddle_tpu.models.llama conventions (the same
    pytree the AOT GenerationEngine uses, inference/__init__.py:249).
    """

    def __init__(self, cfg, params, max_batch: int = 8, max_seq: int = 512,
                 chunk: int = 1, quant: str | None = None):
        """``chunk``: decode steps per compiled call.  Tokens feed back
        on-device inside a lax.scan and the host fetches ``chunk`` tokens per
        round-trip — the lever against host-device latency (one RTT per token
        is what bounds single-step decode on a relay-attached TPU).  Retire
        and admission happen at chunk granularity; generated tokens past a
        request's EOS/budget inside a chunk are trimmed host-side.
        ``quant``: None | 'int8' | 'int4' — weight-only quantized matmuls
        (weights stream from HBM at 1/2 or 1/4 the bytes)."""
        from ..models import llama as _llama  # noqa: F401  (cfg type lives there)

        self.cfg = cfg
        if quant is not None:
            from . import quantize_layer_params

            params = quantize_layer_params(params, quant)
        self.quant = quant
        self.params = params
        self.max_batch = max_batch
        self.max_seq = max_seq
        self.chunk = int(chunk)
        L = cfg.num_hidden_layers
        shape = (L, max_batch, cfg.num_key_value_heads, max_seq, cfg.head_dim)
        self.cache_k = jnp.zeros(shape, cfg.dtype)
        self.cache_v = jnp.zeros(shape, cfg.dtype)
        # slot state (host side)
        self._slot_req: list[Request | None] = [None] * max_batch
        self._pos = np.zeros(max_batch, np.int32)      # next write position
        self._last_tok = np.zeros(max_batch, np.int32)
        self._queue: list[Request] = []
        self._decode = jax.jit(self._decode_impl, donate_argnums=(1, 2))
        # prefill writes its lane directly into the donated pool arrays —
        # no slice-out/scatter-back copies of the full pool per admission
        self._prefill = jax.jit(self._prefill_impl, donate_argnums=(2, 3),
                                static_argnums=(6,))
        self.stats = {"decode_steps": 0, "decode_tokens": 0,
                      "prefills": 0, "decode_time_s": 0.0}

    # ---------------- compiled programs ----------------

    def _decode_one(self, params, cache_k, cache_v, tokens, pos, active):
        """One batched decode step: tokens [B], pos [B], active [B] ->
        (logits [B, V], caches).  Inactive slots compute garbage that is
        masked out — the static batch is the price of a single compiled
        program, and idle lanes are cheap next to recompiling (the standard
        TPU serving trade)."""
        from .. import inference as _inf
        from ..ops.pallas import rope as rope_mod

        cfg = self.cfg
        B = self.max_batch
        S = self.max_seq
        x = jnp.take(params["embed"], tokens[:, None], axis=0).astype(cfg.dtype)
        cos_full, sin_full = rope_mod.rope_cos_sin(S, cfg.head_dim,
                                                   base=cfg.rope_theta,
                                                   dtype=cfg.dtype)
        safe_pos = jnp.where(active & (pos < S), pos, 0)
        cos = jnp.take(cos_full[0], safe_pos, axis=0)[:, None]  # [B, 1, d]
        sin = jnp.take(sin_full[0], safe_pos, axis=0)[:, None]
        kv_pos = jnp.arange(S)[None, None, None, None, :]
        mask = ((kv_pos <= pos[:, None, None, None, None])
                & active[:, None, None, None, None])
        lane = jnp.arange(B)
        writeable = active & (pos < S)

        def write(ck, k):
            # ck [B, nkv, S, hd]; k [B, 1, nkv, hd] — per-slot scatter at
            # each slot's own depth (drop writes from inactive/oob lanes)
            upd = jnp.where(writeable[:, None, None], k[:, 0],
                            ck[lane, :, safe_pos])
            out = ck.at[lane, :, safe_pos].set(upd)
            return out, out

        x, ak, av = _inf.transformer_apply(cfg, params, x, cache_k, cache_v,
                                           write, mask, cos, sin)
        return _inf.lm_head_logits(cfg, params, x[:, -1]), ak, av

    def _decode_impl(self, params, cache_k, cache_v, tokens, pos, active):
        """``chunk`` greedy steps in one compiled program; the sampled token
        feeds back on-device (no host round-trip inside the chunk).
        Returns (tokens [chunk, B], caches)."""

        def one(carry, _):
            ck, cv, tok, p = carry
            logits, ck, cv = self._decode_one(params, ck, cv, tok, p, active)
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return (ck, cv, nxt, p + 1), nxt

        (ck, cv, _, _), toks = jax.lax.scan(
            one, (cache_k, cache_v, tokens, pos), None, length=self.chunk)
        return toks, ck, cv

    def _prefill_impl(self, params, ids, cache_k, cache_v, slot, length, bucket):
        """Prefill one request (batch 1, prompt padded to ``bucket``) directly
        into lane ``slot`` of the (donated) cache pools.

        Tokens at or beyond ``length`` are padding and masked out of attention
        (they still write cache positions, which the causal mask makes
        unreachable until the slot's pos pointer passes them — it never does,
        decode overwrites).  No logits are computed: the last real prompt
        token is fed to the first decode step instead (standard split).
        """
        from .. import inference as _inf
        from ..ops.pallas import rope as rope_mod

        cfg = self.cfg
        S = self.max_seq
        x = jnp.take(params["embed"], ids, axis=0).astype(cfg.dtype)
        cos_full, sin_full = rope_mod.rope_cos_sin(S, cfg.head_dim,
                                                   base=cfg.rope_theta,
                                                   dtype=cfg.dtype)
        cos = cos_full[:, :bucket]
        sin = sin_full[:, :bucket]
        kv_pos = jnp.arange(S)[None, None, None, None, :]
        q_pos = jnp.arange(bucket)[None, None, None, :, None]
        mask = (kv_pos <= q_pos) & (kv_pos < length)

        nkv = cfg.num_key_value_heads

        def write(ck, k):
            # ck [B, nkv, S, hd] pool layer; commit this request's K/V into
            # lane `slot` positions [0:bucket], attend over that lane only
            out = jax.lax.dynamic_update_slice(
                ck, k.transpose(0, 2, 1, 3), (slot, 0, 0, 0))
            view = jax.lax.dynamic_slice(
                out, (slot, 0, 0, 0), (1, nkv, S, cfg.head_dim))
            return out, view

        _, ak, av = _inf.transformer_apply(cfg, params, x, cache_k, cache_v,
                                           write, mask, cos, sin)
        return ak, av

    # ---------------- scheduler ----------------

    def _validate(self, req: Request):
        ids = np.asarray(req.prompt_ids, np.int32).ravel()
        if ids.size == 0:
            raise ValueError(f"request {req.rid}: empty prompt")
        if ids.size > self.max_seq - 1:
            raise ValueError(
                f"request {req.rid}: prompt length {ids.size} exceeds "
                f"max_seq-1 = {self.max_seq - 1}")

    def add_request(self, req: Request):
        self._validate(req)
        self._queue.append(req)

    def _admit(self):
        """Fill free slots from the queue (prefill path)."""
        for slot in range(self.max_batch):
            if self._slot_req[slot] is not None or not self._queue:
                continue
            req = self._queue.pop(0)
            ids = np.asarray(req.prompt_ids, np.int32).ravel()
            s0 = ids.size
            bucket = min(_bucket(s0), self.max_seq)
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :s0] = ids
            # the last real token is fed to decode, not prefill, so its
            # logits come from the decode step (standard split)
            self.cache_k, self.cache_v = self._prefill(
                self.params, jnp.asarray(padded), self.cache_k, self.cache_v,
                jnp.asarray(slot, jnp.int32), jnp.asarray(s0 - 1, jnp.int32),
                bucket)
            self._slot_req[slot] = req
            self._pos[slot] = s0 - 1
            self._last_tok[slot] = ids[-1]
            self.stats["prefills"] += 1

    def _retire(self, slot):
        self._slot_req[slot].finished = True
        self._slot_req[slot] = None

    def step(self) -> bool:
        """One admit + decode-chunk iteration.  Returns False when idle."""
        self._admit()
        active_np = np.asarray([r is not None for r in self._slot_req])
        if not active_np.any():
            return False
        k = self.chunk
        t0 = time.perf_counter()
        toks, self.cache_k, self.cache_v = self._decode(
            self.params, self.cache_k, self.cache_v,
            jnp.asarray(self._last_tok), jnp.asarray(self._pos),
            jnp.asarray(active_np))
        toks_np = np.asarray(toks)  # [k, B] — ONE host round-trip per chunk
        self.stats["decode_time_s"] += time.perf_counter() - t0
        self.stats["decode_steps"] += k
        for slot, req in enumerate(self._slot_req):
            if req is None:
                continue
            old_pos = int(self._pos[slot])
            # tokens produced from positions >= max_seq are garbage (their
            # K/V writes were dropped): only the first max_seq - old_pos
            # chunk steps are trustworthy
            valid = min(k, self.max_seq - old_pos)
            done = False
            for j in range(valid):
                tok = int(toks_np[j, slot])
                req.output_ids.append(tok)
                # count only tokens a caller actually receives: chunk steps
                # past EOS / the token budget / max_seq are trimmed here, so
                # they must not inflate decode_tokens_per_s (the headline)
                self.stats["decode_tokens"] += 1
                if (len(req.output_ids) >= req.max_new_tokens
                        or (req.eos_token_id is not None
                            and tok == req.eos_token_id)):
                    done = True
                    break
            self._pos[slot] = old_pos + k  # device advanced k regardless
            self._last_tok[slot] = int(toks_np[-1, slot])
            if done or old_pos + k >= self.max_seq:
                self._retire(slot)
        return True

    def serve(self, requests: list[Request]) -> dict[int, list[int]]:
        """Run all requests to completion; returns {rid: generated tokens}."""
        for r in requests:
            self._validate(r)  # all-or-nothing: no request enqueued if any is bad
        for r in requests:
            self.add_request(r)
        while self.step() or self._queue:
            pass
        return {r.rid: r.output_ids for r in requests}

    @property
    def decode_tokens_per_s(self) -> float:
        t = self.stats["decode_time_s"]
        return self.stats["decode_tokens"] / t if t > 0 else 0.0
