"""Serving observability (ISSUE 11, docs/observability.md): typed metrics,
request-lifecycle tracing, streaming SLO accounting, and a fault flight
recorder for the continuous-batching engine and the fleet router.

The serving stack's only instruments used to be ad-hoc ``self.stats``
counter dicts and scattered host :class:`~paddle_tpu.profiler.RecordEvent`
spans — no way to answer "which request blew its TBT SLO, on which replica,
and what was the engine doing at the time".  This module is the measurement
layer the ROADMAP's control loops (disaggregated fleets, SLO-driven
autoscaling) steer by:

* :class:`MetricsRegistry` — typed counters, gauges and fixed-log2-bucket
  streaming histograms with labels (replica, model, request class) and
  Prometheus-style text exposition (:meth:`MetricsRegistry.expose`).  The
  engines' ``stats`` dicts migrate onto it behind :class:`StatsView`, a
  dict-compatible view, so every existing ``eng.stats["decode_tokens"]``
  read keeps working while the same counter shows up labelled in the
  exposition.
* :class:`RequestTracer` — per-request lifecycle spans (queued → admitted →
  prefill chunk(s) → decode → terminal) with cross-replica *links* (chrome
  flow events) on failover replay and hedged dispatch, exported through the
  existing profiler chrome-trace path so a whole fleet chaos run renders as
  ONE timeline (pid = replica, tid = request id).
* :class:`SLOTracker` — streaming per-request TTFT / TBT / queue-wait
  accounting derived from the same host events that emit the spans, plus
  :meth:`SLOTracker.goodput_at` — the goodput-at-SLO figure the fleet bench
  used to hand-roll, now a first-class engine product.
* :class:`FlightRecorder` — a bounded ring buffer of recent engine events
  (admits, degradation-ladder rungs, health transitions, faults, evictions,
  step-packing summaries) dumped alongside a metrics snapshot whenever a
  request FAILs, an ``EngineAuditError`` fires, or a replica goes DEAD —
  chaos-test triage without a rerun.

The recording contract
----------------------
ALL recording is host-side and post-step: a metric/span/flight event is
written only from the control plane, after (or before) a compiled launch,
never from inside one — zero device syncs, and token streams are
byte-identical with observability on or off (asserted by the test suite
with prefix cache + speculation + chunked prefill + graceful + TP all on;
the ``host_sync`` lint rule keeps any in-graph callback out of the gated
serving programs).  Per-step cost is O(1) appends — small enough to stay
off the hot path the host-gap histogram itself measures.

Kill switches (``utils/envflags.BOOL_FLAGS``): ``PADDLE_TPU_METRICS=0``
restores the plain pre-PR stats dicts (no registry, no spans, no SLO
tracking) byte-identically; ``PADDLE_TPU_FLIGHT_RECORDER=0`` disables the
ring buffer and its dumps.
"""

from __future__ import annotations

import json
import math
import time
from collections import deque
from collections.abc import MutableMapping

__all__ = [
    "MetricsRegistry", "StatsView", "SLOTracker", "FlightRecorder",
    "RequestTracer", "ENGINE_STAT_SCHEMA", "FLEET_STAT_SCHEMA",
    "metrics_enabled", "flight_recorder_enabled",
]


def metrics_enabled() -> bool:
    """``PADDLE_TPU_METRICS`` (default on): the registry + tracing + SLO
    tier.  ``=0`` restores the plain pre-observability stats dicts."""
    from ..utils.envflags import env_bool

    return env_bool("PADDLE_TPU_METRICS", True)


def flight_recorder_enabled() -> bool:
    """``PADDLE_TPU_FLIGHT_RECORDER`` (default on): the bounded event ring
    buffer and its failure-triggered dumps."""
    from ..utils.envflags import env_bool

    return env_bool("PADDLE_TPU_FLIGHT_RECORDER", True)


# ---------------------------------------------------------------- metrics

def _fmt(v) -> str:
    """Prometheus sample value: integral values print as integers so
    counter exposition stays diff-stable across int/float promotion."""
    if isinstance(v, bool):
        return str(int(v))
    if isinstance(v, int):
        return str(v)
    if isinstance(v, float) and v.is_integer() and abs(v) < 2**53:
        return str(int(v))
    return repr(v)


def _label_str(labels: tuple) -> str:
    if not labels:
        return ""
    body = ",".join(f'{k}="{v}"' for k, v in labels)
    return "{" + body + "}"


class _Value:
    """One labelled counter/gauge child.  ``value`` stays a plain Python
    number (int counters keep int-ness — ``dict(stats)`` equality across
    identical runs must hold exactly)."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0

    def inc(self, n=1):
        self.value += n

    def set(self, v):
        self.value = v


class _HistValue:
    """One labelled histogram child: fixed log2 buckets (upper bounds
    ``2**lo .. 2**hi`` plus +Inf).  ``observe`` is O(1) — a frexp, two
    clamps and three increments — so it is safe on the per-step host
    path."""

    __slots__ = ("counts", "sum", "count", "_lo", "_n")

    def __init__(self, lo: int, hi: int):
        self._lo = lo
        self._n = hi - lo + 2          # 2**lo .. 2**hi, then +Inf
        self.counts = [0] * self._n
        self.sum = 0.0
        self.count = 0

    def observe(self, v: float):
        v = float(v)
        if v <= 0.0 or v != v:          # <=0 and NaN land in the first bucket
            idx = 0
        elif v == math.inf:
            idx = self._n - 1
        else:
            m, e = math.frexp(v)        # v = m * 2**e, m in [0.5, 1)
            ub = e - 1 if m == 0.5 else e   # smallest k with v <= 2**k
            idx = min(max(ub - self._lo, 0), self._n - 1)
        self.counts[idx] += 1
        self.sum += v
        self.count += 1

    def buckets(self, lo: int):
        """(upper-bound-label, cumulative-count) pairs, Prometheus order."""
        out, cum = [], 0
        for i, c in enumerate(self.counts):
            cum += c
            le = "+Inf" if i == self._n - 1 else _fmt(2.0 ** (lo + i))
            out.append((le, cum))
        return out


class MetricFamily:
    """One named metric (counter | gauge | histogram) and its labelled
    children.  Obtained via the registry's :meth:`MetricsRegistry.counter`
    / ``gauge`` / ``histogram`` — re-registering the same name returns the
    SAME family (how N fleet replicas share one exposition), and a
    kind/help mismatch raises instead of silently forking the metric."""

    def __init__(self, name: str, help: str, kind: str, lo: int = -20,
                 hi: int = 6):
        self.name = name
        self.help = help
        self.kind = kind
        self.lo, self.hi = lo, hi
        self._children: dict[tuple, object] = {}

    def labels(self, **kv):
        key = tuple(sorted((k, str(v)) for k, v in kv.items()))
        child = self._children.get(key)
        if child is None:
            child = (_HistValue(self.lo, self.hi) if self.kind == "histogram"
                     else _Value())
            self._children[key] = child
        return child

    def expose_into(self, lines: list[str]):
        lines.append(f"# HELP {self.name} {self.help}")
        lines.append(f"# TYPE {self.name} {self.kind}")
        for key in sorted(self._children):
            child = self._children[key]
            if self.kind == "histogram":
                for le, cum in child.buckets(self.lo):
                    lab = _label_str(key + (("le", le),))
                    lines.append(f"{self.name}_bucket{lab} {cum}")
                lines.append(f"{self.name}_sum{_label_str(key)} "
                             f"{_fmt(child.sum)}")
                lines.append(f"{self.name}_count{_label_str(key)} "
                             f"{child.count}")
            else:
                lines.append(f"{self.name}{_label_str(key)} "
                             f"{_fmt(child.value)}")


class MetricsRegistry:
    """Typed metric registry with Prometheus-style text exposition.

    One registry per engine by default; a :class:`~paddle_tpu.inference.
    fleet.FleetRouter` creates ONE and hands it to every replica with a
    ``{"replica": k}`` label set, so ``registry.expose()`` is the whole
    fleet's snapshot.  Single-threaded by design (the engines and router
    are one host control plane); nothing here takes a lock."""

    def __init__(self):
        self._families: dict[str, MetricFamily] = {}

    def _register(self, kind: str, name: str, help: str, **kw) -> MetricFamily:
        fam = self._families.get(name)
        if fam is not None:
            if fam.kind != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {fam.kind}, "
                    f"cannot re-register as {kind}")
            return fam
        if not help:
            raise ValueError(f"metric {name!r} needs a non-empty help "
                             f"string (the exposition contract)")
        fam = MetricFamily(name, help, kind, **kw)
        self._families[name] = fam
        return fam

    def counter(self, name: str, help: str) -> MetricFamily:
        return self._register("counter", name, help)

    def gauge(self, name: str, help: str) -> MetricFamily:
        return self._register("gauge", name, help)

    def histogram(self, name: str, help: str, lo: int = -20,
                  hi: int = 6) -> MetricFamily:
        """Fixed log2 buckets: upper bounds ``2**lo .. 2**hi`` seconds (or
        whatever unit the caller observes) plus +Inf.  The defaults span
        ~1 microsecond to 64 s — the whole serving latency range."""
        return self._register("histogram", name, help, lo=lo, hi=hi)

    def describe(self) -> dict[str, str]:
        """{metric name: help} — the introspection surface the stat-schema
        test audits (every counter a test or bench reads must be here)."""
        return {n: f.help for n, f in sorted(self._families.items())}

    def expose(self) -> str:
        """Prometheus text exposition of every family, name-sorted — the
        snapshot bench rungs embed and flight-recorder dumps attach."""
        lines: list[str] = []
        for name in sorted(self._families):
            self._families[name].expose_into(lines)
        return "\n".join(lines) + ("\n" if lines else "")


# ------------------------------------------------- stats-dict migration

#: engine ``stats`` keys -> (metric kind, help).  THE schema — every
#: counter key read anywhere in tests/ or bench.py must appear here with a
#: real help string (tests/test_observability.py scans the sources and
#: enforces it), so the dict view and the exposition can never drift.
ENGINE_STAT_SCHEMA = {
    "decode_steps": ("counter", "Compiled decode/verify/mixed step "
                                "iterations executed"),
    "decode_tokens": ("counter", "Generated tokens actually delivered to "
                                 "callers (post EOS/budget trimming)"),
    "prefills": ("counter", "Whole-prompt (bucketed or partial-bucket) "
                            "prefill launches"),
    "decode_time_s": ("gauge", "Wall seconds spent in compiled serving "
                               "steps (decode_tokens / this = tok/s)"),
    "preemptions": ("counter", "vLLM-style recompute preemptions (pool "
                               "pressure victims)"),
    "prefix_hits": ("counter", "Admissions that mapped at least one cached "
                               "prefix block"),
    "prefix_blocks_reused": ("counter", "Cached KV blocks mapped read-only "
                                        "into admissions"),
    "prefix_evictions": ("counter", "Zero-ref cached blocks LRU-evicted "
                                    "under allocation pressure"),
    "cow_copies": ("counter", "Copy-on-write page duplications for fully "
                              "matched prompts"),
    "prefill_tokens_computed": ("counter", "Prompt tokens whose K/V was "
                                           "computed by prefill"),
    "prefill_tokens_cached": ("counter", "Prompt tokens served from the "
                                         "prefix cache (prefill skipped)"),
    "spec_steps": ("counter", "Speculative draft-verify-accept rounds"),
    "spec_drafted_tokens": ("counter", "Tokens proposed by the n-gram "
                                       "drafter"),
    "spec_accepted_tokens": ("counter", "Drafted tokens the verify step "
                                        "accepted"),
    "spec_rejected_tokens": ("counter", "Drafted tokens the verify step "
                                        "rejected (pos rolled back)"),
    "prefill_chunks": ("counter", "Prompt chunks streamed through the "
                                  "mixed prefill/decode step"),
    "mixed_steps": ("counter", "Unified mixed prefill/decode launches"),
    "decode_stall_steps": ("counter", "Whole-prompt prefills dispatched "
                                      "while decode slots sat waiting "
                                      "(0 with chunked prefill on)"),
    "requests_failed": ("counter", "Requests terminated FAILED (fault, "
                                   "NaN guard, unsatisfiable allocation)"),
    "requests_rejected": ("counter", "Requests REJECTED at admission "
                                     "(backpressure or invalid params)"),
    "requests_cancelled": ("counter", "Requests CANCELLED by the caller"),
    "requests_expired": ("counter", "Requests EXPIRED past deadline_s"),
    "degrade_evict": ("counter", "Overload ladder rung 1: proactive "
                                 "prefix-cache leaf evictions"),
    "degrade_spec_off": ("counter", "Overload ladder rung 2: speculation "
                                    "suspended for a step"),
    "degrade_budget_shrink": ("counter", "Overload ladder rung 3: mixed-"
                                         "step prefill rows shrunk to the "
                                         "1-token floor"),
    "degrade_preempt": ("counter", "Overload ladder rung 4: youngest slot "
                                   "preempted under pool pressure"),
    "nan_guard_trips": ("counter", "In-graph NaN/inf logit guard "
                                   "quarantines"),
    "kernel_error_retries": ("counter", "Kernel-dispatch faults retried "
                                        "with state untouched"),
    "tier_demotions": ("counter", "Evicted prefix-cache blocks shipped "
                                  "D2H into the host KV tier"),
    "tier_readmits": ("counter", "Tier blocks restored H2D into the pool "
                                 "(prefill compute skipped)"),
    "tier_hits": ("counter", "Admissions whose prefix match extended "
                             "through the host tier"),
    "tier_evictions": ("counter", "Tier entries LRU-dropped under the "
                                  "byte budget (mirrors the possibly "
                                  "fleet-shared tier's global counter)"),
    "tier_bytes": ("gauge", "Host KV tier bytes resident (mirrors the "
                            "possibly fleet-shared tier's global gauge)"),
    "journal_incremental_updates": ("counter",
                                    "Dirty-rid journal entries rebuilt "
                                    "incrementally (O(changed) per step, "
                                    "docs/async_runtime.md)"),
    "journal_full_rebuilds": ("counter",
                              "Full snapshot() journal rebuilds — steady-"
                              "state async serving keeps this at adopt/"
                              "restore boundaries only"),
    "host_overlap_steps": ("counter",
                           "Steps whose token-independent host work "
                           "overlapped the in-flight device step (async "
                           "host runtime)"),
}

#: fleet router ``stats`` keys -> (metric kind, help); same contract.
FLEET_STAT_SCHEMA = {
    "routed_affinity": ("counter", "Requests routed by longest cached "
                                   "prefix chain"),
    "routed_spill": ("counter", "Requests routed least-loaded (no cached "
                                "chain matched)"),
    "failovers": ("counter", "Replica deaths whose journal was replayed "
                             "onto survivors"),
    "hedges": ("counter", "Stalled-replica requests hedge-dispatched onto "
                          "survivors"),
    "replayed_tokens": ("counter", "Journaled tokens teacher-forced onto "
                                   "survivors (replay + hedge)"),
    "fleet_rejected": ("counter", "Fleet-level rejections (backpressure, "
                                  "invalid request, fleet lost)"),
    "journal_incremental_updates": ("counter",
                                    "Incremental journal() pulls consumed "
                                    "from replicas (failover/hedge "
                                    "boundaries, docs/async_runtime.md)"),
    "journal_full_rebuilds": ("counter",
                              "Full replica snapshot() rebuilds taken by "
                              "the router (per step/dispatch with "
                              "PADDLE_TPU_ASYNC_HOST=0; zero steady-state "
                              "async)"),
    "host_overlap_steps": ("counter",
                           "Fleet steps driven with snapshot refreshes "
                           "deferred to failover boundaries (async host "
                           "runtime)"),
}


class StatsView(MutableMapping):
    """Dict-compatible facade over registry counters/gauges: every read and
    write an existing test or bench makes against ``engine.stats`` /
    ``fleet.stats`` keeps working (``stats[k] += 1``, ``stats[k] = 0``,
    ``stats.update(...)``, ``dict(stats)``), while the same numbers appear
    labelled in ``registry.expose()``.  Keys outside the schema register on
    the fly as counters (dict compatibility must never raise), but the
    schema is the documented surface."""

    def __init__(self, registry: MetricsRegistry, schema: dict,
                 labels: dict | None = None,
                 prefix: str = "paddle_tpu_serving"):
        self._registry = registry
        self._prefix = prefix
        self._labels = dict(labels or {})
        self._children: dict[str, _Value] = {}
        self._order: list[str] = []
        for key, (kind, help) in schema.items():
            fam = registry._register(kind, f"{prefix}_{key}", help)
            self._children[key] = fam.labels(**self._labels)
            self._order.append(key)

    def _child(self, key: str) -> _Value:
        child = self._children.get(key)
        if child is None:
            fam = self._registry._register(
                "counter", f"{self._prefix}_{key}",
                f"dynamically added stat {key!r} (not in the static schema)")
            child = self._children[key] = fam.labels(**self._labels)
            self._order.append(key)
        return child

    def __getitem__(self, key):
        child = self._children.get(key)
        if child is None:
            raise KeyError(key)
        return child.value

    def __setitem__(self, key, value):
        self._child(key).set(value)

    def __delitem__(self, key):
        raise TypeError("stats keys are fixed; set to 0 instead of deleting")

    def __iter__(self):
        return iter(self._order)

    def __len__(self):
        return len(self._order)

    def __repr__(self):
        return f"StatsView({dict(self)!r})"


# --------------------------------------------------- lifecycle tracing

class RequestTracer:
    """Per-request lifecycle spans into the profiler's chrome-trace host
    buffer: pid = replica index, tid = request id, so a whole fleet chaos
    run exported via ``Profiler().export(path)`` renders as ONE timeline
    with one process lane per replica and one thread lane per request.
    Cross-replica links (failover replay, hedged dispatch) are chrome flow
    events (``ph s/f``) keyed by the request's trace id.

    Every emit is one bounded host-buffer append (the profiler cap drops
    and counts overflow) — O(1), post-step, zero device sync."""

    def __init__(self, enabled: bool = True, pid: int = 0,
                 process_name: str | None = None):
        self.enabled = bool(enabled)
        self.pid = int(pid)
        self.counts: dict[str, int] = {}
        self._process_name = process_name
        self._meta_gen = None       # buffer generation the metadata is in
        if self.enabled and process_name:
            self._emit_process_name()

    def _emit_process_name(self):
        from .. import profiler as _prof

        self._meta_gen = _prof.host_events_generation()
        _prof.add_trace_event({"name": "process_name", "ph": "M",
                               "pid": self.pid,
                               "args": {"name": self._process_name}})

    def _emit(self, ev: dict, name: str):
        from .. import profiler as _prof

        if (self._process_name
                and self._meta_gen != _prof.host_events_generation()):
            # export()/clear drained the buffer, taking the lane-name
            # metadata with it: a long-lived engine that exports
            # periodically must keep its replica lanes labelled in every
            # subsequent trace, not just the first
            self._emit_process_name()
        if _prof.add_trace_event(ev):
            self.counts[name] = self.counts.get(name, 0) + 1

    def span(self, tid: int, name: str, t0_s: float, t1_s: float,
             args: dict | None = None):
        """Complete span [t0_s, t1_s] (perf_counter seconds) on this
        tracer's replica lane, thread lane ``tid`` (the request id)."""
        if not self.enabled:
            return
        self._emit({"name": name, "ph": "X", "cat": "request",
                    "ts": t0_s * 1e6,
                    "dur": max(t1_s - t0_s, 0.0) * 1e6,
                    "pid": self.pid, "tid": int(tid),
                    **({"args": args} if args else {})}, name)

    def instant(self, tid: int, name: str, t_s: float,
                args: dict | None = None):
        if not self.enabled:
            return
        self._emit({"name": name, "ph": "i", "s": "t", "cat": "request",
                    "ts": t_s * 1e6, "pid": self.pid, "tid": int(tid),
                    **({"args": args} if args else {})}, name)

    def flow_out(self, tid: int, name: str, t_s: float, flow_id: str):
        """Link origin (e.g. the dead replica's last journal state): pairs
        with a :meth:`flow_in` of the same ``flow_id`` on another replica's
        tracer — chrome draws the arrow across process lanes."""
        if not self.enabled:
            return
        self._emit({"name": name, "ph": "s", "cat": "link", "id": flow_id,
                    "ts": t_s * 1e6, "pid": self.pid, "tid": int(tid)},
                   name)

    def flow_in(self, tid: int, name: str, t_s: float, flow_id: str):
        if not self.enabled:
            return
        self._emit({"name": name, "ph": "f", "bp": "e", "cat": "link",
                    "id": flow_id, "ts": t_s * 1e6, "pid": self.pid,
                    "tid": int(tid)}, name)


# ------------------------------------------------------- SLO accounting

class _LiveSLO:
    __slots__ = ("submit_s", "admit_s", "first_tok_s", "last_tok_s",
                 "max_gap_s", "tokens")

    def __init__(self, submit_s: float):
        self.submit_s = submit_s
        self.admit_s = None
        self.first_tok_s = None
        self.last_tok_s = None
        self.max_gap_s = None
        self.tokens = 0


class SLOTracker:
    """Streaming per-request TTFT / TBT / queue-wait accounting, O(1) per
    token-banking event: the tracker keeps only (first ts, last ts, max
    gap, token count) per live request and a bounded deque of completed
    records — no per-token timestamp lists.

    TBT semantics match what a caller observes: a *banking event* (one
    host fetch delivering >= 1 tokens to a request) is one arrival, and
    gaps are measured between consecutive arrivals — exactly how the fleet
    bench's hand-rolled poll loop measured them before this tracker made
    the figure first-class.  :meth:`goodput_at` is the headline:
    tokens of FINISHED requests that met BOTH latency bounds."""

    def __init__(self, registry: MetricsRegistry | None = None,
                 labels: dict | None = None,
                 prefix: str = "paddle_tpu_serving",
                 capacity: int = 65536):
        self._live: dict[int, _LiveSLO] = {}
        self.records: deque = deque(maxlen=capacity)
        self._h_ttft = self._h_tbt = self._h_qwait = None
        if registry is not None:
            lab = dict(labels or {})
            self._h_ttft = registry.histogram(
                f"{prefix}_ttft_seconds",
                "Submit -> first generated token (wall seconds)"
            ).labels(**lab)
            self._h_tbt = registry.histogram(
                f"{prefix}_tbt_seconds",
                "Gap between consecutive token-banking events per request "
                "(wall seconds)").labels(**lab)
            self._h_qwait = registry.histogram(
                f"{prefix}_queue_wait_seconds",
                "Submit -> admission onto a slot (wall seconds)"
            ).labels(**lab)

    def begin(self, rid: int, submit_s: float):
        self._live[rid] = _LiveSLO(submit_s)

    def admitted(self, rid: int, now_s: float):
        rec = self._live.get(rid)
        if rec is None:
            rec = self._live[rid] = _LiveSLO(now_s)
        if rec.admit_s is None:
            rec.admit_s = now_s
            if self._h_qwait is not None:
                self._h_qwait.observe(now_s - rec.submit_s)

    def tokens(self, rid: int, n: int, now_s: float):
        """Bank one arrival of ``n`` tokens at ``now_s``."""
        if n <= 0:
            return
        rec = self._live.get(rid)
        if rec is None:
            return
        if rec.first_tok_s is None:
            rec.first_tok_s = now_s
            if self._h_ttft is not None:
                self._h_ttft.observe(now_s - rec.submit_s)
        else:
            gap = now_s - rec.last_tok_s
            if rec.max_gap_s is None or gap > rec.max_gap_s:
                rec.max_gap_s = gap
            if self._h_tbt is not None:
                self._h_tbt.observe(gap)
        rec.last_tok_s = now_s
        rec.tokens += n

    def finish(self, rid: int, status: str, now_s: float):
        rec = self._live.pop(rid, None)
        if rec is None:
            return
        self.records.append({
            "rid": rid, "status": status,
            "submit_s": rec.submit_s, "admit_s": rec.admit_s,
            "finish_s": now_s,
            "ttft_s": (None if rec.first_tok_s is None
                       else rec.first_tok_s - rec.submit_s),
            "max_gap_s": rec.max_gap_s,
            "tokens": rec.tokens,
        })

    def goodput_at(self, ttft_slo_s: float, tbt_slo_s: float) -> dict:
        """Goodput AT the SLO over completed records: requests that
        FINISHED, produced a first token within ``ttft_slo_s`` of submit,
        and never gapped longer than ``tbt_slo_s`` between arrivals.
        Returns ``{"requests", "tokens", "rids"}`` — divide tokens by the
        serve's wall clock for the bench headline."""
        rids, toks = [], 0
        for rec in self.records:
            if rec["status"] != "FINISHED" or rec["ttft_s"] is None:
                continue
            if rec["ttft_s"] > ttft_slo_s:
                continue
            if rec["max_gap_s"] is not None and rec["max_gap_s"] > tbt_slo_s:
                continue
            rids.append(rec["rid"])
            toks += rec["tokens"]
        return {"requests": len(rids), "tokens": toks,
                "rids": tuple(sorted(rids))}


# ------------------------------------------------------ flight recorder

class FlightRecorder:
    """Bounded ring buffer of recent engine/fleet events, dumped alongside
    a metrics snapshot when something goes wrong (request FAILED,
    ``EngineAuditError``, replica DEAD) so chaos-test triage reads the
    last seconds of engine history instead of requiring a rerun.

    ``record`` is one deque append (O(1), maxlen drops the oldest and
    ticks ``dropped``).  ``dump`` snapshots the ring into ``self.dumps``
    (itself bounded) and returns the dict; callers may also JSON-serialize
    it (:meth:`dump_json`)."""

    def __init__(self, capacity: int = 256, registry=None,
                 name: str = "engine", max_dumps: int = 8):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.name = name
        self.capacity = int(capacity)
        self._ring: deque = deque(maxlen=self.capacity)
        self._registry = registry
        self._seq = 0
        self.dropped = 0
        self.dumps: deque = deque(maxlen=max_dumps)

    def record(self, kind: str, /, **detail):
        self._seq += 1
        if len(self._ring) == self.capacity:
            self.dropped += 1           # deque evicts the oldest silently
        self._ring.append({"seq": self._seq, "ts": time.perf_counter(),
                           "kind": kind, **detail})

    def __len__(self):
        return len(self._ring)

    def events(self) -> list[dict]:
        return list(self._ring)

    def dump(self, reason: str, extra: dict | None = None) -> dict:
        d = {
            "recorder": self.name,
            "reason": reason,
            "ts": time.perf_counter(),
            "events_recorded": self._seq,
            "events_dropped": self.dropped,
            "events": self.events(),
            "metrics": (self._registry.expose()
                        if self._registry is not None else None),
        }
        if extra:
            d.update(extra)
        self.dumps.append(d)
        return d

    def dump_json(self, reason: str, extra: dict | None = None) -> str:
        return json.dumps(self.dump(reason, extra), default=repr)
