"""Automatic prefix cache: content-addressed KV block reuse (ISSUE 2 tentpole).

Reference analog: vLLM's automatic prefix caching over the paged KV pool the
reference's ``block_multihead_attention_`` memory model implies
(`paddle/phi/ops/yaml/fused_ops.yaml:45`) — production serving traffic is
dominated by requests sharing a system prompt / few-shot prefix, and
re-prefilling that prefix burns the same FLOPs and HBM on every request.

Design (docs/prefix_cache.md):

* **Content addressing by hash chain.**  Every FULL block of ``block_size``
  tokens gets an id ``hash(parent_hash, block_token_ids)``.  Chaining makes
  the id a digest of the *entire prefix up to and including this block*, so
  one dict keyed by chained hash IS a radix index over token prefixes: walking
  a prompt block-by-block and chaining hashes descends the radix tree, and the
  first missing hash is the divergence point (two prompts sharing k blocks
  share exactly k chained hashes, never more).
* **Refcounts, not ownership.**  A cached block records how many engine slots
  currently map its physical page read-only.  Release decrements; a zero-ref
  block STAYS RESIDENT (its page is not on the engine free list) so hot
  prefixes survive between requests.
* **LRU eviction only under allocation pressure.**  The engine asks for pages
  only when its free list runs dry; eviction pops least-recently-released
  zero-ref blocks, leaf-first (a parent is never evicted before its cached
  children — an unreachable child would strand a page the radix walk can no
  longer find).  Because a slot that maps block b also maps b's parent,
  ``parent.refcount >= child.refcount`` always holds and leaf-first order is
  achievable.
* **Copy-on-write on divergence.**  The engine never writes a shared page:
  when an admitted request would decode into a fully-matched block (prompt
  length a multiple of ``block_size`` with every prompt block cached), the
  engine copies that page into a private one first (see
  ``ContinuousBatchingEngine._admit``); mid-block prompt divergence needs no
  COW at all — block-granular matching simply stops at the last shared block.

The cache stores only host-side metadata (hashes, page ids, refcounts); the
K/V bytes live in the engine's paged pools and are read by the ragged
paged-attention Pallas kernel unchanged — shared pages are just block-table
entries appearing in more than one row.
"""

from __future__ import annotations

import hashlib
import heapq

import numpy as np

__all__ = ["PrefixCache", "CachedBlock"]


class CachedBlock:
    """One cached full block: physical page + chain metadata."""

    __slots__ = ("hash", "page", "parent", "refcount", "children", "last_used")

    def __init__(self, hash_: str, page: int, parent: str | None):
        self.hash = hash_
        self.page = page            # physical page index in the engine pool
        self.parent = parent        # chained hash of the previous block
        self.refcount = 0           # slots currently mapping this page
        self.children = 0           # cached blocks whose parent is this one
        self.last_used = 0          # LRU tick of the last ref drop to zero

    def __repr__(self):  # debugging aid only
        return (f"CachedBlock({self.hash[:8]}, page={self.page}, "
                f"ref={self.refcount}, kids={self.children})")


class PrefixCache:
    """Block-granular content-addressed index over a paged KV pool.

    Pure host-side control plane: the engine owns the device pools and the
    free list; this class owns the hash→block index and the refcount/LRU
    bookkeeping.  Accounting invariant (asserted by tests): every pool page is
    in exactly one of {engine free list, a slot's private blocks, this cache}.
    """

    def __init__(self, block_size: int):
        self.block_size = int(block_size)
        self._by_hash: dict[str, CachedBlock] = {}
        self._tick = 0
        # lazy min-heap of (last_used, hash) eviction candidates: entries are
        # pushed whenever a block becomes a zero-ref leaf and validated on
        # pop (still resident / still leaf / still zero-ref / tick current),
        # so pressure eviction is O(log n) amortized per page instead of a
        # full-index scan per page in the decode hot loop
        self._evict_heap: list[tuple[int, str]] = []
        # exact zero-ref count, maintained incrementally for the same reason:
        # the engine reads evictable_count() on EVERY admission attempt
        self._n_zero_ref = 0

    # ---------------- hashing / lookup ----------------

    @staticmethod
    def chain_hash(parent: str | None, tokens) -> str:
        """Content id of a full block: digest of (parent chain id, tokens).
        sha256 over the raw int32 bytes — collisions across distinct prefixes
        are cryptographically negligible, so hash equality is treated as
        content equality (the vLLM trade; tests assert non-collision across
        adversarial near-miss prefixes)."""
        h = hashlib.sha256()
        h.update(b"root" if parent is None else parent.encode("ascii"))
        h.update(b"|")
        h.update(np.ascontiguousarray(np.asarray(tokens, np.int32)).tobytes())
        return h.hexdigest()

    def chain_hashes(self, token_ids, n_blocks: int) -> list[str]:
        """Chained hashes of the first ``n_blocks`` full blocks of a stream."""
        ids = np.asarray(token_ids, np.int32).ravel()
        out: list[str] = []
        parent = None
        bs = self.block_size
        for b in range(n_blocks):
            parent = self.chain_hash(parent, ids[b * bs:(b + 1) * bs])
            out.append(parent)
        return out

    def match(self, token_ids) -> list[CachedBlock]:
        """Longest cached chain of full blocks prefixing ``token_ids``.

        Radix descent: walk full blocks, chain hashes, stop at the first id
        not in the index.  Does NOT touch refcounts — the caller acquires the
        blocks it actually maps (and must do so before any allocation that
        could trigger eviction)."""
        ids = np.asarray(token_ids, np.int32).ravel()
        bs = self.block_size
        out: list[CachedBlock] = []
        parent = None
        for b in range(ids.size // bs):
            h = self.chain_hash(parent, ids[b * bs:(b + 1) * bs])
            e = self._by_hash.get(h)
            if e is None:
                break
            out.append(e)
            parent = h
        return out

    # ---------------- refcounting ----------------

    def acquire(self, block: CachedBlock) -> None:
        """Pin a matched block: a nonzero refcount makes it unevictable."""
        if block.refcount == 0:
            self._n_zero_ref -= 1
        block.refcount += 1

    def release(self, hash_: str) -> None:
        """Drop one slot's reference; at zero the block becomes an LRU
        eviction candidate but stays resident (hot prefixes survive)."""
        e = self._by_hash[hash_]
        assert e.refcount > 0, f"release of zero-ref cached block {hash_[:8]}"
        e.refcount -= 1
        if e.refcount == 0:
            self._n_zero_ref += 1
            self._tick += 1
            e.last_used = self._tick
            if e.children == 0:
                heapq.heappush(self._evict_heap, (e.last_used, e.hash))

    # ---------------- registration ----------------

    def register(self, parent: str | None, tokens, page: int,
                 refcount: int = 0) -> CachedBlock | None:
        """Insert one full block (content ``tokens``, physical ``page``).

        Returns the new entry — ownership of ``page`` transfers to the cache —
        or None when the chained hash already exists (identical content was
        registered concurrently; the caller keeps its duplicate page and frees
        it through its normal private-page path, so no page is ever tracked
        twice)."""
        h = self.chain_hash(parent, tokens)
        if h in self._by_hash:
            return None
        e = CachedBlock(h, int(page), parent)
        e.refcount = int(refcount)
        if refcount == 0:
            self._n_zero_ref += 1
            self._tick += 1
            e.last_used = self._tick
            heapq.heappush(self._evict_heap, (e.last_used, h))
        if parent is not None:
            pe = self._by_hash.get(parent)
            if pe is None:
                # parent was evicted between the caller's match and this
                # register: the block would be unreachable by radix descent —
                # refuse (caller keeps the page private)
                return None
            pe.children += 1
        self._by_hash[h] = e
        return e

    # ---------------- eviction (allocation pressure only) ----------------

    def evictable_count(self) -> int:
        """Pages reclaimable right now (zero-ref; leaf-first order means every
        zero-ref block is eventually reachable by repeated leaf eviction, so
        admission headroom may count them all).  O(1): maintained
        incrementally — the engine calls this per admission attempt."""
        return self._n_zero_ref

    def evict(self, n: int) -> list[tuple[str, int]]:
        """Reclaim up to ``n`` pages, least-recently-used zero-ref leaves
        first.  Pops the lazy heap, skipping stale records (re-acquired,
        re-parented, already evicted, or superseded by a fresher tick);
        evicting a leaf may turn its parent into a leaf, which is pushed
        immediately so chains drain oldest-first without any index scan.

        Returns ``(hash, page)`` pairs, NOT bare page ids: the hash is the
        victim's content address, which a demotion consumer — the host KV
        tier (inference/kv_tier.py) ships each victim's page D2H under its
        chain hash before the engine recycles the page — needs to keep the
        block re-admittable.  (Bare ids silently dropped the hash, making
        every eviction an unconditional kill.)"""
        pairs: list[tuple[str, int]] = []
        while len(pairs) < n and self._evict_heap:
            tick, h = heapq.heappop(self._evict_heap)
            victim = self._by_hash.get(h)
            if (victim is None or victim.refcount != 0
                    or victim.children != 0 or victim.last_used != tick):
                continue  # stale heap record
            del self._by_hash[h]
            self._n_zero_ref -= 1
            if victim.parent is not None:
                pe = self._by_hash.get(victim.parent)
                if pe is not None:
                    pe.children -= 1
                    if pe.children == 0 and pe.refcount == 0:
                        heapq.heappush(self._evict_heap,
                                       (pe.last_used, pe.hash))
            pairs.append((victim.hash, victim.page))
        return pairs

    # ---------------- accounting / introspection ----------------

    def resident_blocks(self) -> int:
        """Pages currently owned by the cache (referenced + zero-ref)."""
        return len(self._by_hash)

    def resident_pages(self) -> list[int]:
        return [e.page for e in self._by_hash.values()]

    def __len__(self) -> int:
        return len(self._by_hash)
