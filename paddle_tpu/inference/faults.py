"""Deterministic fault injection for the serving engine (ISSUE 6 tentpole).

The degradation paths the engine ships (preemption, LRU eviction, kernel
fallback, and now the full overload ladder — docs/fault_tolerance.md) are
only trustworthy if they are exercised *adversarially*: a fault that only
ever happens in production is a fault the test suite proves nothing about.
This module turns ``PADDLE_TPU_FAULT_INJECT`` into a :class:`FaultPlan` the
engine polls at its three seams:

* **allocator** (``_alloc_to``) — ``alloc_fail`` makes a page grab report
  the pool dry even when pages are free, driving the overload ladder
  (evict -> preempt -> fail-one) without needing a genuinely tiny pool;
* **kernel dispatch** (``_launch``) — ``kernel_error`` raises where the
  compiled step would be dispatched, BEFORE the call, so host and device
  state are untouched and the graceful engine can retry the step;
* **sampler** — ``nan_logits`` sets a per-slot poison bit that the compiled
  step turns into a genuinely non-finite logits row IN-GRAPH, so the NaN/inf
  guard proves itself against the real failure shape, not a host-side
  simulation (requires ``PADDLE_TPU_GRACEFUL=1``: the graceful-off program
  is byte-identical to the pre-fault-tolerance engine and has no poison
  operand, so this kind is inert there);

plus two host-side seams that exercise per-request isolation:

* ``slot_error`` — raises while banking one slot's generated token (the
  consume loop), proving a host-side per-request fault cannot take down the
  batch;
* ``cache_error`` — raises inside prefix-cache block registration; the
  graceful engine degrades (the block stays private, a future request
  misses where it could have hit) without failing any request;
* ``tier_drop`` — a host-KV-tier entry vanishes between the admission's
  tier match and the ship_in restore (docs/kv_tier.md): the poll fires at
  the restore seam and force-discards the entry (pins ignored — exactly
  what a lost host buffer looks like), so the engine must fall back to
  ordinary prefill compute for the remaining blocks, never hang or
  corrupt — token streams are identical either way;

and — ISSUE 9, docs/fleet_serving.md — three REPLICA-scoped kinds the
:class:`~paddle_tpu.inference.fleet.FleetRouter` polls once per replica per
fleet step (never the engine: a replica dying is a fleet-tier event):

* ``replica_crash`` — the replica dies mid-serve: the router marks it DEAD
  and replays its journal onto survivors by teacher-forced recompute;
* ``replica_stall`` — the replica makes no progress for the fired step
  (its compiled step "hangs"); enough consecutive stalls trigger hedged
  re-dispatch with first-writer-wins dedup;
* ``replica_slow`` — the replica's step completes but its latency
  heartbeat is elevated; a streak degrades its health so the router stops
  preferring it for new work.

Replica-scoped kinds are rejected when no fleet is running
(``FaultPlan.from_env(fleet=False)``, the engine's parse): the clause would
otherwise be a silent no-op — the worst failure mode for a chaos lever — so
the parse warns once naming the fleet requirement and disables injection
entirely, exactly like a typo'd kind (utils/envflags.env_fault_spec).

Grammar (validated by ``utils/envflags.env_fault_spec``; a typo warns with a
did-you-mean and disables injection entirely)::

    PADDLE_TPU_FAULT_INJECT="alloc_fail@step=7;nan_logits@slot=2,step=11"
    PADDLE_TPU_FAULT_INJECT="replica_crash@step=9,replica=1"   # fleet only

Clause keys: ``step`` (engine step number, 1-based — for replica-scoped
clauses the FLEET step number; omitted = any step), ``slot`` / ``rid`` /
``replica`` (omitted = first match polled; ``replica`` is fleet-only),
``count`` (firings before the clause exhausts; default 1, ``-1`` =
unlimited), and ``p`` + ``seed`` for probabilistic chaos — each matching
poll fires with probability ``p`` drawn from a ``seed``-keyed private
stream, so a randomized chaos run is still exactly replayable.
"""

from __future__ import annotations

import dataclasses

import numpy as np

__all__ = ["KNOWN_KINDS", "KNOWN_KEYS", "REPLICA_KINDS", "FaultClause",
           "FaultPlan", "FaultInjected"]


class FaultInjected(RuntimeError):
    """Raised at a host-side injection seam (kernel dispatch / token
    banking / cache registration) when a fault-plan clause fires.  A
    DISTINCT type so the graceful engine's recovery paths catch exactly the
    faults the plan injected — a genuine error raised by the same code is
    never silently swallowed as chaos noise.  The raise always happens
    BEFORE the seam's real work (a compiled launch is never entered), so
    host and device state are untouched and recovery can retry or fail just
    the affected request."""

#: fault kinds the engine polls for (the env_fault_spec vocabulary)
KNOWN_KINDS = frozenset({"alloc_fail", "kernel_error", "nan_logits",
                         "slot_error", "cache_error", "tier_drop"})

#: fleet-tier fault kinds the FleetRouter polls for (ISSUE 9); rejected by
#: the engine's own parse — a replica-scoped clause with no fleet running
#: would be a silent no-op
REPLICA_KINDS = frozenset({"replica_crash", "replica_stall", "replica_slow"})

#: clause keys the grammar accepts (``replica`` is fleet-only, same contract)
KNOWN_KEYS = frozenset({"step", "slot", "rid", "count", "p", "seed"})


@dataclasses.dataclass
class FaultClause:
    """One parsed clause of a fault plan.  ``count`` is decremented per
    firing; 0 means exhausted (-1 never exhausts)."""

    kind: str
    step: int | None = None
    slot: int | None = None
    rid: int | None = None
    replica: int | None = None
    count: int = 1
    p: float = 1.0
    seed: int = 0

    def __post_init__(self):
        # private seeded stream per clause: probabilistic firing stays
        # replayable and independent of every other clause's draw order
        self._rng = np.random.RandomState(self.seed)

    def matches(self, kind: str, step, slot, rid, replica=None) -> bool:
        if self.kind != kind or self.count == 0:
            return False
        if self.step is not None and step != self.step:
            return False
        if self.slot is not None and slot != self.slot:
            return False
        if self.rid is not None and rid != self.rid:
            return False
        if self.replica is not None and replica != self.replica:
            return False
        return True


class FaultPlan:
    """The engine-facing injector: ``fire(kind, ...)`` at a seam returns True
    when a clause matches (and consumes one firing).  An empty plan is inert
    and free — the hot-loop polls short-circuit on ``self._clauses``."""

    def __init__(self, clauses=()):
        self._clauses = [c if isinstance(c, FaultClause) else FaultClause(**c)
                         for c in clauses]

    @classmethod
    def from_env(cls, fleet: bool = False) -> "FaultPlan":
        """Parse ``PADDLE_TPU_FAULT_INJECT`` (validated; malformed specs warn
        once and disable injection — utils/envflags.py).  ``fleet=True``
        (the FleetRouter's parse) admits the replica-scoped vocabulary —
        the ``replica_*`` kinds and the ``replica`` clause key; the default
        engine parse REJECTS those with a warning naming the fleet
        requirement, because a replica-scoped clause polled by nobody would
        make a chaos run's evidence silently incomplete."""
        from ..utils.envflags import env_fault_spec

        if fleet:
            return cls(env_fault_spec("PADDLE_TPU_FAULT_INJECT",
                                      KNOWN_KINDS | REPLICA_KINDS,
                                      KNOWN_KEYS | {"replica"}))
        return cls(env_fault_spec("PADDLE_TPU_FAULT_INJECT", KNOWN_KINDS,
                                  KNOWN_KEYS,
                                  fleet_only_kinds=REPLICA_KINDS,
                                  fleet_only_keys=frozenset({"replica"})))

    def __bool__(self) -> bool:
        return bool(self._clauses)

    def fire(self, kind: str, *, step: int | None = None,
             slot: int | None = None, rid: int | None = None,
             replica: int | None = None) -> bool:
        """Poll one seam: True exactly when a clause matches and fires.
        Polling order is the engine's deterministic scan order (the fleet's
        is replica-index order), so a clause with an omitted ``slot`` /
        ``replica`` fires on the first matching poll — the plan stays
        replayable without pinning every key."""
        if not self._clauses:
            return False
        for c in self._clauses:
            if not c.matches(kind, step, slot, rid, replica):
                continue
            if c.p < 1.0 and float(c._rng.random_sample()) >= c.p:
                continue
            if c.count > 0:
                c.count -= 1
            return True
        return False
