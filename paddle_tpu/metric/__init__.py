"""Metrics (reference: python/paddle/metric/ — Accuracy, Precision, Recall, Auc)."""

from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor, _unwrap

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    import jax.numpy as jnp

    logits = _unwrap(input)
    lab = _unwrap(label)
    if lab.ndim == logits.ndim:
        lab = lab.squeeze(-1)
    topk = jnp.argsort(-logits, axis=-1)[..., :k]
    hit = jnp.any(topk == lab[..., None], axis=-1)
    return Tensor(jnp.mean(hit.astype(jnp.float32)))


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        return self.__class__.__name__.lower()


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        self.topk = topk if isinstance(topk, (tuple, list)) else (topk,)
        self._name = name or "acc"
        self.reset()

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def compute(self, pred, label):
        p = np.asarray(_unwrap(pred))
        l = np.asarray(_unwrap(label))
        if l.ndim == p.ndim:
            l = l.squeeze(-1)
        maxk = max(self.topk)
        top = np.argsort(-p, axis=-1)[..., :maxk]
        correct = top == l[..., None]
        return Tensor(np.asarray(correct, np.float32))

    def update(self, correct):
        c = np.asarray(_unwrap(correct)) if isinstance(correct, Tensor) else np.asarray(correct)
        n = c.shape[0]
        for i, k in enumerate(self.topk):
            self.total[i] += float(c[..., :k].any(axis=-1).sum())
            self.count[i] += n
        return self.accumulate()

    def accumulate(self):
        res = [t / c if c else 0.0 for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        return self._name


class Precision(Metric):
    def __init__(self, name=None):
        self._name = name or "precision"
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        p = (np.asarray(_unwrap(preds)) > 0.5).astype(np.int64).reshape(-1)
        l = np.asarray(_unwrap(labels)).astype(np.int64).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fp += int(((p == 1) & (l == 0)).sum())

    def accumulate(self):
        d = self.tp + self.fp
        return self.tp / d if d else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name=None):
        self._name = name or "recall"
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        p = (np.asarray(_unwrap(preds)) > 0.5).astype(np.int64).reshape(-1)
        l = np.asarray(_unwrap(labels)).astype(np.int64).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fn += int(((p == 0) & (l == 1)).sum())

    def accumulate(self):
        d = self.tp + self.fn
        return self.tp / d if d else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name=None):
        self.num_thresholds = num_thresholds
        self._name = name or "auc"
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        p = np.asarray(_unwrap(preds))
        if p.ndim == 2:
            p = p[:, -1]
        l = np.asarray(_unwrap(labels)).reshape(-1)
        idx = (p * self.num_thresholds).astype(np.int64).clip(0, self.num_thresholds)
        for i, lab in zip(idx, l):
            if lab:
                self._stat_pos[i] += 1
            else:
                self._stat_neg[i] += 1

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        # trapezoid over thresholds descending
        tp = np.cumsum(self._stat_pos[::-1])
        fp = np.cumsum(self._stat_neg[::-1])
        tpr = tp / tot_pos
        fpr = fp / tot_neg
        return float(np.trapz(tpr, fpr))

    def name(self):
        return self._name
