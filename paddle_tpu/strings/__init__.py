"""StringTensor + the strings op family.

Reference: paddle/phi/core/string_tensor.h:33 (``StringTensor`` — a dense
tensor of ``pstring`` values), paddle/phi/ops/yaml/strings_ops.yaml (the
whole family: ``strings_empty``, ``strings_empty_like``, ``strings_lower``,
``strings_upper``), kernels in paddle/phi/kernels/strings/
(strings_lower_upper_kernel.h:30,36 with utf8 vs ascii case conversion via
case_utils.h/unicode.h).

TPU-native design: strings are HOST data — no accelerator represents
variable-length text, and the reference's GPU strings kernels just shuttle
pstrings through device memory to do byte-wise case mapping.  So the
framework keeps string tensors host-side as numpy object arrays (shape
semantics intact, values immutable Python str), and the op family runs as
plain host compute.  This mirrors what the stack is actually for: tokenizer
front-ends produce int token tensors, and only those enter XLA.
"""

from __future__ import annotations

import numpy as np

__all__ = ["StringTensor", "empty", "empty_like", "lower", "upper",
           "to_string_tensor"]


class StringTensor:
    """Dense tensor of strings (reference string_tensor.h:33): numpy object
    array of ``str`` plus the usual shape/numel surface."""

    def __init__(self, data):
        arr = np.asarray(data, dtype=object)
        vals = list(arr.reshape(-1))
        ragged = [v for v in vals if isinstance(v, (list, tuple, np.ndarray))]
        if ragged:
            # a dense tensor of strings, like the reference — ragged nests
            # would silently str()-ify into repr garbage
            raise ValueError(
                f"StringTensor requires rectangular (non-ragged) input; got "
                f"nested sequence of shape {arr.shape} holding "
                f"{type(ragged[0]).__name__} elements")
        flat = [("" if v is None else str(v)) for v in vals]
        self._data = np.array(flat, dtype=object).reshape(arr.shape)

    @property
    def shape(self):
        return tuple(self._data.shape)

    def numel(self) -> int:
        return int(self._data.size)

    def numpy(self) -> np.ndarray:
        return self._data.copy()

    def tolist(self):
        return self._data.tolist()

    def __getitem__(self, idx):
        out = self._data[idx]
        if isinstance(out, np.ndarray):
            return StringTensor(out)
        return out

    def __eq__(self, other):
        if isinstance(other, StringTensor):
            other = other._data
        return bool(np.array_equal(self._data, np.asarray(other, dtype=object)))

    # container with value equality — unhashable by design, like np.ndarray
    __hash__ = None

    def __repr__(self):
        return f"StringTensor(shape={self.shape}, {self._data.tolist()!r})"


def to_string_tensor(data) -> StringTensor:
    return data if isinstance(data, StringTensor) else StringTensor(data)


def empty(shape) -> StringTensor:
    """strings_empty (strings_ops.yaml): a shape-sized tensor of ""."""
    return StringTensor(np.full(tuple(shape), "", dtype=object))


def empty_like(x) -> StringTensor:
    """strings_empty_like (strings_ops.yaml)."""
    return empty(to_string_tensor(x).shape)


def _case_map(x, fn_utf8, fn_ascii, use_utf8_encoding):
    x = to_string_tensor(x)
    fn = fn_utf8 if use_utf8_encoding else fn_ascii
    out = np.array([fn(v) for v in x._data.reshape(-1)],
                   dtype=object).reshape(x.shape)
    return StringTensor(out)


def _ascii_lower(s: str) -> str:
    # the reference's non-utf8 path maps ASCII bytes only
    # (case_utils.h AsciiCaseConverter) — multi-byte text passes through
    return "".join(c.lower() if "A" <= c <= "Z" else c for c in s)


def _ascii_upper(s: str) -> str:
    return "".join(c.upper() if "a" <= c <= "z" else c for c in s)


def lower(x, use_utf8_encoding: bool = False) -> StringTensor:
    """strings_lower (strings_lower_upper_kernel.h:30): per-element case
    fold; ``use_utf8_encoding`` selects full unicode mapping vs ASCII-only."""
    return _case_map(x, str.lower, _ascii_lower, use_utf8_encoding)


def upper(x, use_utf8_encoding: bool = False) -> StringTensor:
    """strings_upper (strings_lower_upper_kernel.h:36)."""
    return _case_map(x, str.upper, _ascii_upper, use_utf8_encoding)
