"""Process-level flag registry.

TPU-native analog of the reference's gflags-compatible flag system
(`paddle/common/flags.h:38`, `paddle/common/flags.cc` — ~183 exported ``FLAGS_*``,
surfaced in Python via ``paddle.set_flags`` / ``paddle.get_flags``).

Flags are plain Python values registered at import time; every flag can be
overridden by an environment variable of the same name (``FLAGS_check_nan_inf=1``)
at first access, mirroring the reference's env-var override behavior.
"""

from __future__ import annotations

import os
import threading
from typing import Any

_lock = threading.Lock()
_registry: dict[str, "_Flag"] = {}


class _Flag:
    __slots__ = ("name", "value", "default", "doc", "_env_checked")

    def __init__(self, name: str, default: Any, doc: str):
        self.name = name
        self.default = default
        self.value = default
        self.doc = doc
        self._env_checked = False

    def get(self) -> Any:
        if not self._env_checked:
            self._env_checked = True
            env = os.environ.get(self.name)
            if env is not None:
                self.value = _coerce(env, self.default)
        return self.value


def _coerce(text: str, like: Any) -> Any:
    if isinstance(like, bool):
        return text.lower() in ("1", "true", "yes", "on")
    if isinstance(like, int):
        return int(text)
    if isinstance(like, float):
        return float(text)
    return text


def define_flag(name: str, default: Any, doc: str = "") -> None:
    """Register a flag (analog of PD_DEFINE_* / PHI_DEFINE_EXPORTED_*)."""
    with _lock:
        if name not in _registry:
            _registry[name] = _Flag(name, default, doc)


def get_flags(names):
    """Mirror of ``paddle.get_flags``: accepts a name or list of names."""
    single = isinstance(names, str)
    if single:
        names = [names]
    out = {}
    for n in names:
        if n not in _registry:
            raise ValueError(f"unknown flag {n!r}")
        out[n] = _registry[n].get()
    return out


def set_flags(flags: dict) -> None:
    """Mirror of ``paddle.set_flags``."""
    with _lock:
        for name, value in flags.items():
            if name not in _registry:
                raise ValueError(f"unknown flag {name!r}")
            f = _registry[name]
            f._env_checked = True
            f.value = _coerce(value, f.default) if isinstance(value, str) else value


def flag(name: str) -> Any:
    """Fast internal accessor."""
    return _registry[name].get()


# -- Core flags (subset of the reference's catalogue that is meaningful on TPU) --
define_flag("FLAGS_check_nan_inf", False, "Check outputs of every op for NaN/Inf.")
define_flag("FLAGS_check_nan_inf_level", 0, "0: error on nan/inf; >0: log only.")
define_flag("FLAGS_set_to_1d", False, "Treat 0-D tensors as 1-D in numpy conversion.")
define_flag("FLAGS_default_dtype", "float32", "Default floating point dtype.")
define_flag("FLAGS_benchmark", False, "Block-until-ready after every eager op.")
define_flag("FLAGS_eager_jit_ops", True, "Route eager op dispatch through cached jax.jit.")
define_flag("FLAGS_log_level", 0, "VLOG-style verbosity for framework internals.")
define_flag("FLAGS_use_pallas_kernels", True, "Use Pallas kernels for fused ops on TPU.")
define_flag("FLAGS_embedding_deterministic", False, "Deterministic embedding grad scatter.")
define_flag("FLAGS_cudnn_deterministic", False, "Accepted for API parity; no-op on TPU.")
define_flag("FLAGS_max_inflight_collectives", 8, "Eager collective pipelining depth.")
