"""Random number generation.

Analog of the reference's ``phi::Generator`` (`paddle/phi/core/generator.h:32`),
whose state is {device, seed, offset}: every random kernel consumes the current
(seed, offset) pair and bumps the offset.  The TPU-native realization maps that
exact state onto stateless JAX PRNG: ``key = fold_in(key(seed), offset)`` with a
monotonically increasing offset — deterministic, checkpointable, and replayable
(which is what recompute's RNG-state tracker needs, see
`fleet/recompute/recompute.py:116` in the reference).
"""

from __future__ import annotations

import threading

import jax


class Generator:
    """Counter-based RNG with reference-compatible {seed, offset} state."""

    def __init__(self, seed: int = 0):
        self._lock = threading.Lock()
        self._seed = int(seed)
        self._offset = 0

    def manual_seed(self, seed: int) -> "Generator":
        with self._lock:
            self._seed = int(seed)
            self._offset = 0
        return self

    def seed(self) -> int:
        return self._seed

    def get_state(self) -> tuple[int, int]:
        with self._lock:
            return (self._seed, self._offset)

    def set_state(self, state) -> None:
        with self._lock:
            self._seed, self._offset = int(state[0]), int(state[1])

    def next_key(self) -> jax.Array:
        """Draw the next PRNG key, bumping the offset (kernel-consume semantics)."""
        with self._lock:
            k = jax.random.fold_in(jax.random.key(self._seed), self._offset)
            self._offset += 1
            return k

    def peek_key(self, offset_delta: int = 0) -> jax.Array:
        with self._lock:
            return jax.random.fold_in(jax.random.key(self._seed), self._offset + offset_delta)


_default = Generator(0)


def default_generator() -> Generator:
    return _default


def seed(value: int) -> Generator:
    """``paddle.seed`` analog: reset the global generator."""
    return _default.manual_seed(value)


def get_rng_state():
    return _default.get_state()


def set_rng_state(state) -> None:
    _default.set_state(state)


def next_key() -> jax.Array:
    return _default.next_key()
