"""Dtype system.

Analog of the reference's ``phi::DataType`` (paddle/phi/common/data_type.h) and the
Python-level dtype aliases.  We alias straight onto numpy/jax dtypes — on TPU the
set that matters is {bfloat16, float32, int32, bool, (u)int8, fp8} and XLA owns
layout, so no DataLayout enum is needed (documented mapping, SURVEY.md §7).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .flags import flag

# Canonical dtype objects are jnp dtypes so arrays interoperate directly.
bool_ = jnp.bool_
uint8 = jnp.uint8
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
float64 = jnp.float64
complex64 = jnp.complex64
complex128 = jnp.complex128
float8_e4m3fn = jnp.float8_e4m3fn
float8_e5m2 = jnp.float8_e5m2

_STR_TO_DTYPE = {
    "bool": bool_,
    "uint8": uint8,
    "int8": int8,
    "int16": int16,
    "int32": int32,
    "int64": int64,
    "float16": float16,
    "bfloat16": bfloat16,
    "float32": float32,
    "float64": float64,
    "complex64": complex64,
    "complex128": complex128,
    "float8_e4m3fn": float8_e4m3fn,
    "float8_e5m2": float8_e5m2,
}


def convert_dtype(dtype) -> np.dtype:
    """Normalize str/np/jnp dtype specifiers to a numpy dtype object."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        if dtype not in _STR_TO_DTYPE:
            raise ValueError(f"unsupported dtype string {dtype!r}")
        return np.dtype(_STR_TO_DTYPE[dtype])
    return np.dtype(dtype)


def dtype_name(dtype) -> str:
    return np.dtype(dtype).name


def get_default_dtype():
    return convert_dtype(flag("FLAGS_default_dtype"))


def set_default_dtype(dtype) -> None:
    from .flags import set_flags

    set_flags({"FLAGS_default_dtype": dtype_name(convert_dtype(dtype))})


def is_floating(dtype) -> bool:
    return jnp.issubdtype(np.dtype(dtype), jnp.floating)


def is_complex(dtype) -> bool:
    return jnp.issubdtype(np.dtype(dtype), jnp.complexfloating)


def is_inexact(dtype) -> bool:
    return jnp.issubdtype(np.dtype(dtype), jnp.inexact)


def is_integer(dtype) -> bool:
    return jnp.issubdtype(np.dtype(dtype), jnp.integer)
