"""Tensor type + eager autograd tape.

This replaces three reference layers with one TPU-native design (SURVEY.md §3.1/§3.2):

- ``phi::DenseTensor`` (`paddle/phi/core/dense_tensor.h:37`) → a thin wrapper over a
  ``jax.Array`` (PJRT owns memory/layout/streams; no allocator to build).
- the generated eager AD functions + GradNode graph
  (`paddle/fluid/eager/grad_node_info.h:197`, `eager_gen.py:367`) → every traced op
  is dispatched through :func:`apply_op`, which uses ``jax.vjp`` to run the forward
  *and* capture the exact backward closure; nodes form a tape ordered by creation id.
- ``egr::RunBackward`` (`paddle/fluid/eager/backward.cc:106` — in-degree map + ready
  queue) → reverse-creation-order sweep over reachable nodes (a tape is already a
  topological order, so no in-degree bookkeeping is needed).

Eager mode is the debugging/UX surface; the performance path is tracing the same ops
under ``jit``/``to_static`` where this tape is bypassed entirely (grad_enabled off)
and XLA sees pure jnp code.
"""

from __future__ import annotations

import itertools
import threading
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from . import dtype as dtypes
from .device import current_device
from .flags import flag

__all__ = [
    "Tensor",
    "Parameter",
    "to_tensor",
    "apply_op",
    "no_grad",
    "enable_grad",
    "is_grad_enabled",
    "set_grad_enabled",
]

_node_counter = itertools.count()


class _GradState(threading.local):
    def __init__(self):
        self.enabled = True


_grad_state = _GradState()


def is_grad_enabled() -> bool:
    return _grad_state.enabled


def set_grad_enabled(mode: bool):
    _grad_state.enabled = bool(mode)


class _GradModeGuard:
    def __init__(self, mode: bool):
        self._mode = mode

    def __enter__(self):
        self._prev = _grad_state.enabled
        _grad_state.enabled = self._mode
        return self

    def __exit__(self, *exc):
        _grad_state.enabled = self._prev
        return False

    def __call__(self, fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with _GradModeGuard(self._mode):
                return fn(*args, **kwargs)

        return wrapper


def no_grad(fn=None):
    """Context manager / decorator disabling tape recording (``paddle.no_grad``)."""
    guard = _GradModeGuard(False)
    return guard if fn is None else guard(fn)


def enable_grad(fn=None):
    guard = _GradModeGuard(True)
    return guard if fn is None else guard(fn)


class TapeNode:
    """One recorded op: holds the vjp closure and edges to parent tensors."""

    __slots__ = ("id", "op_name", "vjp_fn", "parents", "out_avals", "n_out")

    def __init__(self, op_name, vjp_fn, parents, out_avals):
        self.id = next(_node_counter)
        self.op_name = op_name
        self.vjp_fn = vjp_fn
        self.parents = parents  # list[Tensor] — only the differentiable inputs
        self.out_avals = out_avals  # list[(shape, dtype)]
        self.n_out = len(out_avals)


def _is_tracer(x) -> bool:
    return isinstance(x, jax.core.Tracer)


class Tensor:
    """Eager tensor: value + (optional) producer node on the autograd tape."""

    __slots__ = (
        "_value",
        "_node",
        "_out_idx",
        "stop_gradient",
        "_grad",
        "_retain_grads",
        "_hooks",
        "name",
        "persistable",
        "dist_attr",  # DTensor metadata (distributed.auto_parallel)
        "partition_spec",  # mesh sharding hint set by TP layers
        "sequence_parallel",  # sequence-parallel marker (fleet mpu)
        "dp_stacked_grad",  # grad uses the stacked per-rank convention
        "__weakref__",
    )

    def __init__(self, value, stop_gradient: bool = True, name: str | None = None):
        if isinstance(value, Tensor):
            value = value._value
        self._value = value
        self._node: TapeNode | None = None
        self._out_idx = 0
        self.stop_gradient = stop_gradient
        self._grad: jax.Array | None = None
        self._retain_grads = False
        self._hooks: list[Callable] | None = None
        self.name = name
        self.persistable = False

    # -- basic properties -------------------------------------------------
    @property
    def shape(self):
        return tuple(self._value.shape)

    @property
    def dtype(self):
        return np.dtype(self._value.dtype)

    @property
    def ndim(self):
        return self._value.ndim

    @property
    def size(self):
        return int(np.prod(self._value.shape)) if self._value.shape else 1

    @property
    def place(self):
        from .device import Place

        devs = getattr(self._value, "devices", None)
        if devs is not None and not _is_tracer(self._value):
            try:
                return Place(next(iter(self._value.devices())))
            except Exception:
                pass
        return Place(current_device())

    @property
    def T(self):
        from .. import ops

        return ops.manipulation.transpose(self, list(range(self.ndim))[::-1])

    @property
    def grad(self) -> "Tensor | None":
        return None if self._grad is None else Tensor(self._grad)

    @grad.setter
    def grad(self, value):
        self._grad = None if value is None else _unwrap(value)

    @property
    def is_leaf(self) -> bool:
        return self._node is None

    def value(self):
        return self._value

    # -- conversion -------------------------------------------------------
    def numpy(self) -> np.ndarray:
        return np.asarray(self._value)

    def set(self, value, place=None):
        """In-place value replacement (the reference LoDTensor's
        ``t.set(array, place)`` idiom used with scopes/executors).  Severs
        the autograd node like set_/resize_: the old graph did not produce
        this value, so backward through it would be wrong."""
        self._value = jnp.asarray(np.asarray(value))
        self._node, self._out_idx = None, 0

    def item(self):
        return self._value.item()

    def tolist(self):
        return np.asarray(self._value).tolist()

    def astype(self, dtype) -> "Tensor":
        from .. import ops

        return ops.manipulation.cast(self, dtype)

    cast = astype

    def detach(self) -> "Tensor":
        t = Tensor(self._value, stop_gradient=True)
        return t

    def clone(self) -> "Tensor":
        return apply_op("clone", lambda x: jnp.copy(x), [self])

    def cpu(self):
        return Tensor(jax.device_get(self._value), self.stop_gradient)

    def to(self, *args, **kwargs):
        # accepts dtype or device strings for script compatibility
        t = self
        for a in list(args) + list(kwargs.values()):
            if isinstance(a, str) and a.split(":")[0] in ("cpu", "tpu", "gpu"):
                from .device import _parse

                t = Tensor(jax.device_put(t._value, _parse(a)), t.stop_gradient)
            elif a is not None:
                t = t.astype(a)
        return t

    def pin_memory(self):
        return self

    def contiguous(self):
        return self

    def is_contiguous(self):
        return True

    # -- autograd ---------------------------------------------------------
    def retain_grads(self):
        self._retain_grads = True

    def register_hook(self, hook: Callable):
        if self._hooks is None:
            self._hooks = []
        self._hooks.append(hook)

        class _Handle:
            def remove(h):
                try:
                    self._hooks.remove(hook)
                except ValueError:
                    pass

        return _Handle()

    def clear_grad(self):
        self._grad = None

    clear_gradient = clear_grad

    def zero_(self):
        self._value = jnp.zeros_like(self._value)
        return self

    def backward(self, grad_tensor=None, retain_graph: bool = False):
        run_backward(self, grad_tensor, retain_graph)

    # -- python protocol ---------------------------------------------------
    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-D tensor")
        return self.shape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __repr__(self):
        grad_note = "" if self.stop_gradient else ", stop_gradient=False"
        return (
            f"Tensor(shape={list(self.shape)}, dtype={self.dtype.name}{grad_note},\n"
            f"       {np.asarray(jax.device_get(self._value)) if not _is_tracer(self._value) else self._value})"
        )

    def __bool__(self):
        return bool(self._value)

    def __int__(self):
        return int(self._value)

    def __float__(self):
        return float(self._value)

    def __index__(self):
        return int(self._value)

    def __format__(self, spec):
        return format(self.item() if self.ndim == 0 else np.asarray(self._value), spec)

    def __hash__(self):
        return id(self)

    def __getitem__(self, idx):
        idx = _unwrap_index(idx)
        return apply_op("getitem", lambda x: x[idx], [self])

    def _snapshot(self) -> "Tensor":
        """Copy of this tensor's (value, tape position) — required before
        in-place mutation so the recorded op's parent is the *pre-mutation*
        tensor (otherwise the tape would contain a self-cycle)."""
        s = Tensor(self._value, stop_gradient=self.stop_gradient)
        s._node = self._node
        s._out_idx = self._out_idx
        return s

    def __setitem__(self, idx, value):
        idx = _unwrap_index(idx)
        inputs = [self._snapshot()]
        if isinstance(value, Tensor):
            inputs.append(value)

            def fn(x, v):
                return x.at[idx].set(v.astype(x.dtype))

        else:

            def fn(x):
                return x.at[idx].set(jnp.asarray(value, x.dtype))

        out = apply_op("setitem", fn, inputs)
        # in-place semantics: this tensor becomes the op output on the tape
        self._value = out._value
        self._node = out._node
        self._out_idx = out._out_idx
        self.stop_gradient = out.stop_gradient

    # arithmetic dunders are installed by paddle_tpu.ops at import time
    def __array__(self, dtype=None):
        a = np.asarray(jax.device_get(self._value))
        return a.astype(dtype) if dtype is not None else a

    # jax pytree protocol is registered below so Tensors flow through jit/vmap.


def _tensor_flatten(t: Tensor):
    return (t._value,), (t.stop_gradient,)


def _tensor_unflatten(aux, children):
    return Tensor(children[0], stop_gradient=aux[0])


jax.tree_util.register_pytree_node(Tensor, _tensor_flatten, _tensor_unflatten)


class Parameter(Tensor):
    """Trainable tensor (analog of ``paddle.base.framework.EagerParamBase``)."""

    # _asp_mask: optional 2:4 sparsity mask (incubate.asp) — lives on the
    # parameter so it shares the parameter's lifetime
    __slots__ = ("trainable", "optimize_attr", "regularizer", "need_clip",
                 "_asp_mask")

    def __init__(self, value, trainable: bool = True, name: str | None = None):
        super().__init__(value, stop_gradient=not trainable, name=name)
        self.trainable = trainable
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.need_clip = True
        self.persistable = True

    def set_value(self, value):
        v = _unwrap(value)
        self._value = jnp.asarray(v, self.dtype).reshape(self.shape)

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()


jax.tree_util.register_pytree_node(
    Parameter,
    lambda p: ((p._value,), (p.trainable,)),
    lambda aux, ch: Parameter(ch[0], trainable=aux[0]),
)


def _unwrap(x):
    return x._value if isinstance(x, Tensor) else x


def _unwrap_index(idx):
    if isinstance(idx, tuple):
        return tuple(_unwrap(i) for i in idx)
    if isinstance(idx, list) and any(isinstance(i, Tensor) for i in idx):
        return [_unwrap(i) for i in idx]
    return _unwrap(idx)


def to_tensor(data, dtype=None, place=None, stop_gradient: bool = True) -> Tensor:
    """``paddle.to_tensor`` analog."""
    if isinstance(data, Tensor):
        v = data._value
        if dtype is not None:
            v = v.astype(dtypes.convert_dtype(dtype))
        return Tensor(v, stop_gradient=stop_gradient)
    if isinstance(data, (jnp.ndarray, jax.Array)) and not isinstance(data, np.ndarray):
        v = data
    else:
        a = np.asarray(data)
        if dtype is None and a.dtype == np.float64:
            a = a.astype(dtypes.get_default_dtype())
        v = jnp.asarray(a)
    if dtype is not None:
        v = v.astype(dtypes.convert_dtype(dtype))
    if place is not None and not _is_tracer(v):
        from .device import _parse

        v = jax.device_put(v, _parse(place) if isinstance(place, str) else place.device)
    return Tensor(v, stop_gradient=stop_gradient)


# ---------------------------------------------------------------------------
# op dispatch
# ---------------------------------------------------------------------------

# set by paddle_tpu.amp at import time: (op_name, vals) -> vals with AMP casts
_amp_cast_hook = None

# set by paddle_tpu.static while a program_guard is active:
# (op_name, fn, inputs, static_kwargs, out_tensors) -> None.  Records every
# dispatched op into the active Program (the eager tape IS the graph; this
# mirrors the reference's program-building AppendOp path, framework.py).
_op_record_hook = None


def _check_nan_inf(name: str, vals) -> None:
    for v in vals:
        if jnp.issubdtype(v.dtype, jnp.inexact) and not _is_tracer(v):
            if bool(jnp.any(~jnp.isfinite(v))):
                msg = f"Operator {name} output contains NaN/Inf"
                if flag("FLAGS_check_nan_inf_level") > 0:
                    print("WARNING:", msg)
                else:
                    raise FloatingPointError(msg)


def apply_op(
    name: str,
    fn: Callable,
    inputs: Sequence[Any],
    n_outputs: int | None = None,
    **static_kwargs,
):
    """Dispatch one op through the eager tape.

    ``fn`` is a pure jnp function taking the unwrapped inputs positionally plus
    ``static_kwargs``.  Replaces the generated per-op AD function of the
    reference (`eager_gen.py:367`): forward runs via ``jax.vjp`` when any input
    requires grad, capturing the exact XLA backward; otherwise ``fn`` runs
    directly (and is traceable, so the same ops work under jit).  The AMP policy
    hook (registered by paddle_tpu.amp) mirrors the AMP_LOGIC_TEMPLATE stage of
    the reference's generated AD functions (`eager_gen.py:645`).
    """
    vals = [_unwrap(x) for x in inputs]
    if _amp_cast_hook is not None:
        vals = _amp_cast_hook(name, vals)
    tracing = any(_is_tracer(v) for v in vals)
    record = (
        _grad_state.enabled
        and not tracing
        and any(
            isinstance(x, Tensor)
            and not x.stop_gradient
            and dtypes.is_inexact(x.dtype)
            for x in inputs
        )
    )
    if not record:
        out = fn(*vals, **static_kwargs)
        multi = isinstance(out, (tuple, list))
        outs = list(out) if multi else [out]
        if flag("FLAGS_check_nan_inf"):
            _check_nan_inf(name, outs)
        wrapped = [Tensor(o, stop_gradient=True) for o in outs]
        if _op_record_hook is not None:
            _op_record_hook(name, fn, inputs, static_kwargs, wrapped)
        return tuple(wrapped) if multi else wrapped[0]

    diff_mask = [
        isinstance(x, Tensor) and not x.stop_gradient and dtypes.is_inexact(x.dtype)
        for x in inputs
    ]
    diff_vals = [v for v, m in zip(vals, diff_mask) if m]

    def closed(*dvals):
        it = iter(dvals)
        full = [next(it) if m else v for m, v in zip(diff_mask, vals)]
        return fn(*full, **static_kwargs)

    out, vjp_fn = jax.vjp(closed, *diff_vals)
    multi = isinstance(out, (tuple, list))
    outs = list(out) if multi else [out]
    if flag("FLAGS_check_nan_inf"):
        _check_nan_inf(name, outs)
    parents = [x for x, m in zip(inputs, diff_mask) if m]
    node = TapeNode(name, vjp_fn, parents, [(o.shape, o.dtype) for o in outs])
    wrapped = []
    for i, o in enumerate(outs):
        t = Tensor(o, stop_gradient=not dtypes.is_inexact(o.dtype))
        if not t.stop_gradient:
            t._node = node
            t._out_idx = i
        wrapped.append(t)
    if _op_record_hook is not None:
        _op_record_hook(name, fn, inputs, static_kwargs, wrapped)
    return tuple(wrapped) if multi else wrapped[0]


# ---------------------------------------------------------------------------
# backward engine
# ---------------------------------------------------------------------------

def run_backward(tensor: Tensor, grad_tensor=None, retain_graph: bool = False):
    """Reverse sweep over the tape (analog of egr::RunBackward, backward.cc:106)."""
    if tensor.stop_gradient:
        raise RuntimeError("backward() on a tensor with stop_gradient=True")
    if grad_tensor is None:
        if tensor.size != 1:
            raise RuntimeError(
                "grad can be implicitly created only for scalar outputs; "
                f"got shape {tensor.shape}"
            )
        seed = jnp.ones(tensor.shape, tensor._value.dtype)
    else:
        seed = jnp.asarray(_unwrap(grad_tensor), tensor._value.dtype)

    def _route(t: Tensor, g):
        if t._hooks:
            for h in t._hooks:
                r = h(Tensor(g))
                if r is not None:
                    g = _unwrap(r)
        if t._node is None or t._retain_grads:
            t._grad = g if t._grad is None else t._grad + g
        return g

    if tensor._node is None:
        _route(tensor, seed)
        return

    # collect reachable nodes; tape ids give topological order for free
    nodes: dict[int, TapeNode] = {}
    stack = [tensor._node]
    while stack:
        n = stack.pop()
        if n.id in nodes:
            continue
        nodes[n.id] = n
        for p in n.parents:
            if p._node is not None:
                stack.append(p._node)

    # cotangent accumulator keyed by (node_id, out_idx); seed AFTER routing so a
    # hook on the root tensor affects propagated gradients too
    seed = _route(tensor, seed)
    cots: dict[tuple[int, int], Any] = {(tensor._node.id, tensor._out_idx): seed}

    for nid in sorted(nodes, reverse=True):
        node = nodes[nid]
        if node.vjp_fn is None:
            raise RuntimeError(
                "trying to backward through the graph a second time "
                "(set retain_graph=True)"
            )
        couts = []
        any_set = False
        for i, (shape, dt) in enumerate(node.out_avals):
            g = cots.pop((nid, i), None)
            if g is None:
                g = jnp.zeros(shape, dt)
            else:
                any_set = True
                if g.dtype != dt:  # AMP boundary: cotangent must match primal dtype
                    g = g.astype(dt)
            couts.append(g)
        if not any_set:
            continue
        in_grads = node.vjp_fn(tuple(couts) if node.n_out > 1 else couts[0])
        if not retain_graph:
            node.vjp_fn = None
        for p, g in zip(node.parents, in_grads):
            if g is None:
                continue
            g = _route(p, g)
            if p._node is not None:
                key = (p._node.id, p._out_idx)
                cots[key] = g if key not in cots else cots[key] + g
