"""Device management.

Analog of the reference's DeviceManager / place system
(`paddle/phi/backends/device_manager.h:134`, ``paddle.device.set_device``).
On TPU the runtime (streams, contexts, allocators) is owned by PJRT/XLA — this
module keeps the *API surface*: device discovery, a current-device setting that
controls where eager ops place their outputs, and memory stats
(analog of `paddle/phi/core/memory/stats.h`).
"""

from __future__ import annotations

import threading

import jax
import numpy as np


class Place:
    """A device identity, e.g. ``tpu:0`` / ``cpu:0`` (analog of phi::Place)."""

    __slots__ = ("device",)

    def __init__(self, device: jax.Device):
        self.device = device

    @property
    def platform(self) -> str:
        return self.device.platform

    @property
    def index(self) -> int:
        return self.device.id

    def __repr__(self):
        return f"Place({self.device.platform}:{self.device.id})"

    def __eq__(self, other):
        return isinstance(other, Place) and self.device == other.device

    def __hash__(self):
        return hash(self.device)


_current_device: jax.Device | None = None


def _parse(device: str) -> jax.Device:
    device = device.lower()
    if ":" in device:
        platform, _, idx = device.partition(":")
        idx = int(idx)
    else:
        platform, idx = device, 0
    if platform == "gpu":  # accepted for script compatibility
        platform = "tpu"
    devs = [d for d in jax.devices() if d.platform.startswith(platform)]
    if not devs:
        devs = jax.devices()  # fall back to whatever exists (e.g. cpu-only CI)
    return devs[min(idx, len(devs) - 1)]


def set_device(device: str) -> Place:
    """``paddle.device.set_device`` analog: 'tpu', 'tpu:1', 'cpu'."""
    global _current_device
    _current_device = _parse(device)
    return Place(_current_device)


def get_device() -> str:
    d = current_device()
    return f"{d.platform}:{d.id}"


def current_device() -> jax.Device:
    return _current_device if _current_device is not None else jax.devices()[0]


def device_count() -> int:
    return jax.device_count()


def local_device_count() -> int:
    return jax.local_device_count()


def is_compiled_with_cuda() -> bool:  # API parity helper
    return False


def is_compiled_with_tpu() -> bool:
    return any(d.platform == "tpu" for d in jax.devices())


# ---- memory stats (reference: paddle/phi/core/memory/stats.h; API surface of
# paddle.device.cuda.max_memory_allocated etc., served by PJRT stats on TPU) ----

def memory_stats(device: jax.Device | None = None) -> dict:
    d = device or current_device()
    try:
        return dict(d.memory_stats() or {})
    except Exception:
        return {}


def max_memory_allocated(device=None) -> int:
    return int(memory_stats(device).get("peak_bytes_in_use", 0))


def memory_allocated(device=None) -> int:
    return int(memory_stats(device).get("bytes_in_use", 0))


def max_memory_reserved(device=None) -> int:
    s = memory_stats(device)
    return int(s.get("bytes_reserved", s.get("peak_bytes_in_use", 0)))


def memory_reserved(device=None) -> int:
    """Current reserved bytes (falls back to current bytes_in_use — PJRT
    reports no separate live reserved-pool counter)."""
    s = memory_stats(device)
    return int(s.get("bytes_reserved", s.get("bytes_in_use", 0)))


def empty_cache() -> None:
    """Best-effort allocator release (XLA owns the allocator; no-op if unsupported)."""
    try:
        jax.clear_caches()
    except Exception:
        pass


def synchronize(device=None) -> None:
    """Block until all pending work on the device is complete."""
    (jax.device_put(np.zeros((), np.int32), device or current_device())).block_until_ready()


# ---- host-side stat registry (native C++ when built: paddle_tpu/native/src/
# stats.cc — the analog of the reference's STAT_ADD/STAT_GET counter macros in
# paddle/phi/core/memory/stats.h, applied to host quantities: IPC queue depth,
# checkpoint bytes in flight, pinned batches) ----

_host_stats: dict = {}
_host_stats_lock = threading.Lock()


def _stat_lib():
    from .. import native

    return native.load()


def host_stat_update(name: str, delta: int) -> int:
    lib = _stat_lib()
    if lib is not None:
        return int(lib.pt_stat_update(name.encode(), int(delta)))
    with _host_stats_lock:
        cur, peak = _host_stats.get(name, (0, 0))
        cur += int(delta)
        _host_stats[name] = (cur, max(peak, cur))
        return cur


def host_stat_current(name: str) -> int:
    lib = _stat_lib()
    if lib is not None:
        return int(lib.pt_stat_current(name.encode()))
    return _host_stats.get(name, (0, 0))[0]


def host_stat_peak(name: str) -> int:
    lib = _stat_lib()
    if lib is not None:
        return int(lib.pt_stat_peak(name.encode()))
    return _host_stats.get(name, (0, 0))[1]
