// Host tracer: RecordEvent spans + chrome://tracing JSON export.
//
// Reference: the host tracer records RecordEvent spans into thread-local
// buffers (paddle/fluid/platform/profiler/host_tracer.cc, RecordEvent emitted
// inside the generated API at api_base.py:1340-1355) and the collected
// NodeTrees are dumped as chrome://tracing JSON
// (platform/profiler/chrometracing_logger.h:32).  The TPU device side is
// covered by jax.profiler/XPlane; this native tracer covers the host side
// with the same span API and export format, callable from Python (via
// paddle_tpu.profiler.RecordEvent) without GIL-held timestamping overhead.

#include <sys/syscall.h>
#include <unistd.h>

#include <atomic>
#include <cstdio>
#include <mutex>
#include <string>
#include <vector>

#include "common.h"

namespace {

struct Span {
  int64_t t0_us;
  int64_t t1_us;
  uint32_t name_id;
  uint32_t depth;
};

struct ThreadBuf {
  std::vector<Span> spans;
  std::vector<std::pair<uint32_t, int64_t>> stack;  // (name_id, t0)
  long tid = 0;
};

std::mutex g_mu;
std::vector<std::string> g_names;                 // name_id -> name
std::vector<ThreadBuf*> g_bufs;
std::atomic<bool> g_enabled{false};

thread_local ThreadBuf* t_buf = nullptr;

ThreadBuf* get_buf() {
  if (!t_buf) {
    t_buf = new ThreadBuf();
    t_buf->tid = syscall(SYS_gettid);
    std::lock_guard<std::mutex> lk(g_mu);
    g_bufs.push_back(t_buf);
  }
  return t_buf;
}

void json_escape(FILE* f, const std::string& s) {
  for (char c : s) {
    if (c == '"' || c == '\\')
      fputc('\\', f), fputc(c, f);
    else if (static_cast<unsigned char>(c) >= 0x20)
      fputc(c, f);
    else
      fprintf(f, "\\u%04x", c);
  }
}

}  // namespace

PT_EXPORT void pt_trace_enable() { g_enabled.store(true); }
PT_EXPORT void pt_trace_disable() { g_enabled.store(false); }
PT_EXPORT int pt_trace_enabled() { return g_enabled.load() ? 1 : 0; }

// Interns a name; safe to call once per distinct event name and cache.
PT_EXPORT uint32_t pt_trace_intern(const char* name) {
  std::lock_guard<std::mutex> lk(g_mu);
  for (uint32_t i = 0; i < g_names.size(); ++i)
    if (g_names[i] == name) return i;
  g_names.emplace_back(name);
  return static_cast<uint32_t>(g_names.size() - 1);
}

PT_EXPORT void pt_trace_begin(uint32_t name_id) {
  if (!g_enabled.load(std::memory_order_relaxed)) return;
  ThreadBuf* b = get_buf();
  b->stack.emplace_back(name_id, pt::now_us());
}

PT_EXPORT void pt_trace_end() {
  if (!t_buf || t_buf->stack.empty()) return;
  auto [name_id, t0] = t_buf->stack.back();
  t_buf->stack.pop_back();
  t_buf->spans.push_back({t0, pt::now_us(), name_id,
                          static_cast<uint32_t>(t_buf->stack.size())});
}

// One-shot complete span (begin+end timestamps supplied by the caller).
PT_EXPORT void pt_trace_span(uint32_t name_id, int64_t t0_us, int64_t t1_us) {
  if (!g_enabled.load(std::memory_order_relaxed)) return;
  ThreadBuf* b = get_buf();
  b->spans.push_back({t0_us, t1_us, name_id, 0});
}

PT_EXPORT int64_t pt_trace_now_us() { return pt::now_us(); }

PT_EXPORT int64_t pt_trace_span_count() {
  std::lock_guard<std::mutex> lk(g_mu);
  int64_t n = 0;
  for (auto* b : g_bufs) n += static_cast<int64_t>(b->spans.size());
  return n;
}

PT_EXPORT void pt_trace_clear() {
  std::lock_guard<std::mutex> lk(g_mu);
  for (auto* b : g_bufs) b->spans.clear();
}

// Dumps all collected spans as chrome://tracing "X" (complete) events.
// Returns number of spans written, or -1 on I/O error.
PT_EXPORT int64_t pt_trace_dump(const char* path, int clear) {
  FILE* f = fopen(path, "w");
  if (!f) return -1;
  std::lock_guard<std::mutex> lk(g_mu);
  fputs("{\"traceEvents\":[", f);
  int64_t n = 0;
  long pid = getpid();
  for (auto* b : g_bufs) {
    for (const Span& s : b->spans) {
      if (n) fputc(',', f);
      fprintf(f, "{\"ph\":\"X\",\"cat\":\"host\",\"name\":\"");
      json_escape(f, s.name_id < g_names.size() ? g_names[s.name_id] : "?");
      fprintf(f, "\",\"pid\":%ld,\"tid\":%ld,\"ts\":%lld,\"dur\":%lld}", pid,
              b->tid, static_cast<long long>(s.t0_us),
              static_cast<long long>(s.t1_us - s.t0_us));
      ++n;
    }
    if (clear) b->spans.clear();
  }
  fputs("]}", f);
  fclose(f);
  return n;
}
