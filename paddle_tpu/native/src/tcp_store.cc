// Native TCP key-value store for rendezvous/bootstrap.
//
// Reference: TCPStore / MasterDaemon (paddle/phi/core/distributed/store/
// tcp_store.h:121, socket.cpp) — a master process serves a KV map over TCP;
// clients set/get/add/wait keys to bootstrap process groups before any
// collective backend exists.  Same role here, next to the PJRT coordination
// service instead of NCCL.
//
// Wire protocol (shared with the pure-Python fallback in
// paddle_tpu/distributed/store.py; responses reuse the request frame layout
// with an empty key):
//   request : u32 frame_len | u8 cmd | u32 key_len | key | u32 val_len | val
//   response: u32 frame_len | u8 status(0 ok, 1 timeout, 2 error) |
//             u32 key_len=0 | u32 val_len | val
//   cmd: 0 set, 1 get(blocking-with-timeout == wait+get), 2 add(val = ascii
//   int delta -> returns ascii int), 3 delete, 4 keys(prefix -> '\n' joined),
//   5 wait(val = ascii timeout-ms), 6 get_nowait
// All integers little-endian (x86/ARM hosts).

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdio>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "common.h"

namespace {

enum Cmd : uint8_t { kSet = 0, kGet = 1, kAdd = 2, kDelete = 3, kKeys = 4,
                     kWait = 5, kGetNowait = 6 };
enum Status : uint8_t { kOk = 0, kTimeout = 1, kError = 2 };

bool send_all(int fd, const char* buf, size_t n) {
  while (n > 0) {
    ssize_t w = ::send(fd, buf, n, MSG_NOSIGNAL);
    if (w <= 0) return false;
    buf += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

bool recv_all(int fd, char* buf, size_t n) {
  while (n > 0) {
    ssize_t r = ::recv(fd, buf, n, 0);
    if (r <= 0) return false;
    buf += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

void put_u32(std::string* out, uint32_t v) {
  out->append(reinterpret_cast<const char*>(&v), 4);
}

bool send_frame(int fd, uint8_t tag, const std::string& key,
                const std::string& val) {
  std::string frame;
  frame.reserve(9 + key.size() + val.size());
  frame.push_back(static_cast<char>(tag));
  put_u32(&frame, static_cast<uint32_t>(key.size()));
  frame += key;
  put_u32(&frame, static_cast<uint32_t>(val.size()));
  frame += val;
  uint32_t len = static_cast<uint32_t>(frame.size());
  std::string hdr(reinterpret_cast<const char*>(&len), 4);
  return send_all(fd, hdr.data(), 4) && send_all(fd, frame.data(), frame.size());
}

// Parses "tag key val" out of one frame. Returns false on malformed frame.
bool parse_frame(const std::string& frame, uint8_t* tag, std::string* key,
                 std::string* val) {
  if (frame.size() < 9) return false;
  size_t off = 0;
  *tag = static_cast<uint8_t>(frame[off++]);
  uint32_t klen;
  memcpy(&klen, frame.data() + off, 4);
  off += 4;
  if (off + klen + 4 > frame.size()) return false;
  key->assign(frame.data() + off, klen);
  off += klen;
  uint32_t vlen;
  memcpy(&vlen, frame.data() + off, 4);
  off += 4;
  if (off + vlen > frame.size()) return false;
  val->assign(frame.data() + off, vlen);
  return true;
}

bool recv_frame(int fd, uint8_t* tag, std::string* key, std::string* val) {
  uint32_t len;
  if (!recv_all(fd, reinterpret_cast<char*>(&len), 4)) return false;
  if (len > (64u << 20)) return false;  // 64MB sanity cap
  std::string frame(len, '\0');
  if (!recv_all(fd, frame.data(), len)) return false;
  return parse_frame(frame, tag, key, val);
}

struct StoreServer {
  std::map<std::string, std::string> data;
  std::mutex mu;
  std::condition_variable cv;
  int listen_fd = -1;
  int port = 0;
  std::atomic<bool> stop{false};
  std::thread accept_thread;
  std::vector<std::thread> workers;
  std::mutex fd_mu;
  std::vector<int> client_fds;  // live connections, shut down on stop so
                                // worker threads blocked in recv() exit

  void handle(int fd) {
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    uint8_t cmd;
    std::string key, val;
    while (!stop.load() && recv_frame(fd, &cmd, &key, &val)) {
      uint8_t status = kOk;
      std::string out;
      {
        std::unique_lock<std::mutex> lk(mu);
        switch (cmd) {
          case kSet:
            data[key] = val;
            cv.notify_all();
            break;
          case kGetNowait: {
            auto it = data.find(key);
            if (it != data.end()) out = it->second;
            break;
          }
          case kAdd: {
            long long delta = val.empty() ? 1 : atoll(val.c_str());
            long long cur = 0;
            auto it = data.find(key);
            if (it != data.end()) cur = atoll(it->second.c_str());
            cur += delta;
            data[key] = std::to_string(cur);
            out = data[key];
            cv.notify_all();
            break;
          }
          case kDelete: {
            out = data.erase(key) ? "1" : "0";
            cv.notify_all();
            break;
          }
          case kKeys: {
            for (auto& kv : data) {
              if (kv.first.rfind(key, 0) == 0) {
                if (!out.empty()) out.push_back('\n');
                out += kv.first;
              }
            }
            break;
          }
          case kGet:
          case kWait: {
            long long timeout_ms = 300000;
            if (cmd == kWait && !val.empty()) timeout_ms = atoll(val.c_str());
            if (cmd == kGet && !val.empty()) timeout_ms = atoll(val.c_str());
            auto deadline = std::chrono::steady_clock::now() +
                            std::chrono::milliseconds(timeout_ms);
            bool found = cv.wait_until(lk, deadline, [&] {
              return stop.load() || data.count(key) > 0;
            });
            if (found && data.count(key)) {
              out = data[key];
            } else {
              status = kTimeout;
            }
            break;
          }
          default:
            status = kError;
            out = "unknown cmd";
        }
      }
      if (!send_frame(fd, status, "", out)) break;
    }
    {
      std::lock_guard<std::mutex> lk(fd_mu);
      for (auto it = client_fds.begin(); it != client_fds.end(); ++it) {
        if (*it == fd) {
          client_fds.erase(it);
          break;
        }
      }
    }
    ::close(fd);
  }

  void serve() {
    while (!stop.load()) {
      int fd = ::accept(listen_fd, nullptr, nullptr);
      if (fd < 0) {
        if (stop.load()) return;
        continue;
      }
      {
        std::lock_guard<std::mutex> lk(fd_mu);
        client_fds.push_back(fd);
      }
      workers.emplace_back([this, fd] { handle(fd); });
    }
  }
};

struct StoreClient {
  int fd = -1;
  std::mutex mu;
};

}  // namespace

PT_EXPORT void* pt_store_server_start(int port) {
  auto* s = new StoreServer();
  s->listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (s->listen_fd < 0) {
    delete s;
    return nullptr;
  }
  int one = 1;
  setsockopt(s->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = INADDR_ANY;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (bind(s->listen_fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      listen(s->listen_fd, 512) != 0) {
    ::close(s->listen_fd);
    delete s;
    return nullptr;
  }
  socklen_t alen = sizeof(addr);
  getsockname(s->listen_fd, reinterpret_cast<sockaddr*>(&addr), &alen);
  s->port = ntohs(addr.sin_port);
  s->accept_thread = std::thread([s] { s->serve(); });
  return s;
}

PT_EXPORT int pt_store_server_port(void* handle) {
  return handle ? static_cast<StoreServer*>(handle)->port : -1;
}

PT_EXPORT void pt_store_server_stop(void* handle) {
  if (!handle) return;
  auto* s = static_cast<StoreServer*>(handle);
  s->stop.store(true);
  {
    std::lock_guard<std::mutex> lk(s->mu);
    s->cv.notify_all();
  }
  ::shutdown(s->listen_fd, SHUT_RDWR);
  ::close(s->listen_fd);
  {
    std::lock_guard<std::mutex> lk(s->fd_mu);
    for (int fd : s->client_fds) ::shutdown(fd, SHUT_RDWR);
  }
  if (s->accept_thread.joinable()) s->accept_thread.join();
  for (auto& t : s->workers)
    if (t.joinable()) t.join();
  delete s;
}

PT_EXPORT void* pt_store_client_connect(const char* host, int port,
                                        int timeout_ms) {
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms);
  while (true) {
    addrinfo hints{}, *res = nullptr;
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    std::string port_s = std::to_string(port);
    if (getaddrinfo(host && host[0] ? host : "127.0.0.1", port_s.c_str(),
                    &hints, &res) == 0 && res) {
      int fd = ::socket(res->ai_family, res->ai_socktype, res->ai_protocol);
      if (fd >= 0 && ::connect(fd, res->ai_addr, res->ai_addrlen) == 0) {
        freeaddrinfo(res);
        int one = 1;
        setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
        auto* c = new StoreClient();
        c->fd = fd;
        return c;
      }
      if (fd >= 0) ::close(fd);
      freeaddrinfo(res);
    }
    if (std::chrono::steady_clock::now() > deadline) return nullptr;
    std::this_thread::sleep_for(std::chrono::milliseconds(200));
  }
}

// Round-trips one request. Returns status; *out is malloc'd (caller frees via
// pt_buf_free) when non-null.
static int client_call(StoreClient* c, uint8_t cmd, const char* key,
                       const char* val, int val_len, char** out,
                       int64_t* out_len) {
  if (out) *out = nullptr;
  if (out_len) *out_len = 0;
  std::lock_guard<std::mutex> lk(c->mu);
  std::string v(val ? val : "", val ? static_cast<size_t>(val_len) : 0);
  if (!send_frame(c->fd, cmd, key ? key : "", v)) return kError;
  uint8_t status;
  std::string rkey, rval;
  if (!recv_frame(c->fd, &status, &rkey, &rval)) return kError;
  if (out && !rval.empty()) {
    *out = static_cast<char*>(malloc(rval.size()));
    memcpy(*out, rval.data(), rval.size());
    if (out_len) *out_len = static_cast<int64_t>(rval.size());
  }
  return status;
}

PT_EXPORT int pt_store_set(void* h, const char* key, const char* val,
                           int val_len) {
  return client_call(static_cast<StoreClient*>(h), kSet, key, val, val_len,
                     nullptr, nullptr);
}

PT_EXPORT int pt_store_get(void* h, const char* key, int64_t timeout_ms,
                           char** out, int64_t* out_len) {
  std::string t = std::to_string(timeout_ms);
  return client_call(static_cast<StoreClient*>(h), kGet, key, t.c_str(),
                     static_cast<int>(t.size()), out, out_len);
}

PT_EXPORT int pt_store_get_nowait(void* h, const char* key, char** out,
                                  int64_t* out_len) {
  return client_call(static_cast<StoreClient*>(h), kGetNowait, key, nullptr, 0,
                     out, out_len);
}

PT_EXPORT int64_t pt_store_add(void* h, const char* key, int64_t delta) {
  std::string d = std::to_string(delta);
  char* out = nullptr;
  int64_t out_len = 0;
  int st = client_call(static_cast<StoreClient*>(h), kAdd, key, d.c_str(),
                       static_cast<int>(d.size()), &out, &out_len);
  int64_t v = (st == kOk && out) ? atoll(std::string(out, out_len).c_str())
                                 : INT64_MIN;
  free(out);
  return v;
}

PT_EXPORT int pt_store_wait(void* h, const char* key, int64_t timeout_ms) {
  return client_call(static_cast<StoreClient*>(h), kWait, key,
                     std::to_string(timeout_ms).c_str(),
                     static_cast<int>(std::to_string(timeout_ms).size()),
                     nullptr, nullptr);
}

PT_EXPORT int pt_store_delete(void* h, const char* key) {
  char* out = nullptr;
  int64_t n = 0;
  int st = client_call(static_cast<StoreClient*>(h), kDelete, key, nullptr, 0,
                       &out, &n);
  int existed = (st == kOk && out && n > 0 && out[0] == '1') ? 1 : 0;
  free(out);
  return existed;
}

PT_EXPORT int pt_store_keys(void* h, const char* prefix, char** out,
                            int64_t* out_len) {
  return client_call(static_cast<StoreClient*>(h), kKeys, prefix, nullptr, 0,
                     out, out_len);
}

PT_EXPORT void pt_store_client_close(void* h) {
  if (!h) return;
  auto* c = static_cast<StoreClient*>(h);
  ::close(c->fd);
  delete c;
}

PT_EXPORT void pt_buf_free(char* p) { free(p); }
