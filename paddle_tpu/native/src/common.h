// Common helpers for the paddle_tpu native runtime library.
//
// Reference mapping: the reference framework's host-side runtime is C++
// (paddle/phi/core/distributed/store/tcp_store.h, platform/profiler/,
// phi/core/memory/stats.h, fluid/framework/data_feed).  This library is the
// TPU-native equivalent: the device path is XLA/PJRT, but rendezvous, IPC,
// tracing and stats stay native for the same reasons the reference keeps
// them native (latency, no GIL, usable before Python is up).
#pragma once

#include <cstdint>
#include <cstring>
#include <string>

#define PT_EXPORT extern "C" __attribute__((visibility("default")))

namespace pt {

inline int64_t now_us() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return static_cast<int64_t>(ts.tv_sec) * 1000000 + ts.tv_nsec / 1000;
}

}  // namespace pt
