// Shared-memory SPSC ring buffer for DataLoader worker→consumer transfer.
//
// Reference: the reference DataLoader moves batches from multiprocess workers
// to the main process through shared memory with signal-based cleanup
// (python/paddle/io/dataloader/worker.py, `use_shared_memory=True`;
// `paddle/fluid/memory/allocation/mmap_allocator.*` provides the shm blocks).
// Here the same role is a fixed-capacity ring in a POSIX shm segment: one
// producer (worker process) pushes length-prefixed pickled batches, one
// consumer (main process) pops them — no per-batch file descriptors, no
// serialization through a Python multiprocessing.Queue pipe.
//
// Layout: [Header | data bytes]; head/tail are free-running byte offsets
// (mod capacity). A record is u32 len + payload; len==kWrapMarker means
// "skip to start of ring".

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <string>
#include <thread>

#include "common.h"

namespace {

constexpr uint32_t kWrapMarker = 0xFFFFFFFFu;
constexpr uint64_t kMagic = 0x70745F73686D7131ULL;  // "pt_shmq1"

struct alignas(64) Header {
  uint64_t magic;
  uint64_t capacity;  // data bytes
  alignas(64) std::atomic<uint64_t> head;  // producer cursor (bytes written)
  alignas(64) std::atomic<uint64_t> tail;  // consumer cursor (bytes read)
  alignas(64) std::atomic<uint32_t> closed;
};

struct Queue {
  Header* hdr = nullptr;
  char* data = nullptr;
  size_t map_size = 0;
  std::string name;
  bool owner = false;
};

bool sleep_until_deadline(const std::chrono::steady_clock::time_point& dl) {
  if (std::chrono::steady_clock::now() >= dl) return false;
  std::this_thread::sleep_for(std::chrono::microseconds(50));
  return true;
}

}  // namespace

PT_EXPORT void* pt_shmq_create(const char* name, uint64_t capacity) {
  shm_unlink(name);
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  size_t total = sizeof(Header) + capacity;
  if (ftruncate(fd, static_cast<off_t>(total)) != 0) {
    ::close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* mem = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  ::close(fd);
  if (mem == MAP_FAILED) {
    shm_unlink(name);
    return nullptr;
  }
  auto* q = new Queue();
  q->hdr = new (mem) Header();
  q->hdr->magic = kMagic;
  q->hdr->capacity = capacity;
  q->hdr->head.store(0);
  q->hdr->tail.store(0);
  q->hdr->closed.store(0);
  q->data = static_cast<char*>(mem) + sizeof(Header);
  q->map_size = total;
  q->name = name;
  q->owner = true;
  return q;
}

PT_EXPORT void* pt_shmq_open(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size < static_cast<off_t>(sizeof(Header))) {
    ::close(fd);
    return nullptr;
  }
  void* mem = mmap(nullptr, static_cast<size_t>(st.st_size),
                   PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  ::close(fd);
  if (mem == MAP_FAILED) return nullptr;
  auto* hdr = static_cast<Header*>(mem);
  if (hdr->magic != kMagic) {
    munmap(mem, static_cast<size_t>(st.st_size));
    return nullptr;
  }
  auto* q = new Queue();
  q->hdr = hdr;
  q->data = static_cast<char*>(mem) + sizeof(Header);
  q->map_size = static_cast<size_t>(st.st_size);
  q->name = name;
  q->owner = false;
  return q;
}

// Returns 0 on success, 1 on timeout, 2 on closed/error, 3 message too large.
PT_EXPORT int pt_shmq_push(void* handle, const char* buf, uint64_t len,
                           int64_t timeout_ms) {
  auto* q = static_cast<Queue*>(handle);
  Header* h = q->hdr;
  const uint64_t cap = h->capacity;
  uint64_t need = 4 + len;
  if (need + 4 > cap) return 3;  // +4: room for a wrap marker
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms);
  while (true) {
    if (h->closed.load(std::memory_order_acquire)) return 2;
    uint64_t head = h->head.load(std::memory_order_relaxed);
    uint64_t tail = h->tail.load(std::memory_order_acquire);
    uint64_t pos = head % cap;
    uint64_t contig = cap - pos;
    uint64_t effective = (contig >= need) ? need : contig + need;
    if (cap - (head - tail) >= effective) {
      if (contig < need) {
        // not enough contiguous room: wrap marker (if it fits), skip to start
        if (contig >= 4) {
          uint32_t marker = kWrapMarker;
          memcpy(q->data + pos, &marker, 4);
        }
        head += contig;
        pos = 0;
      }
      uint32_t len32 = static_cast<uint32_t>(len);
      memcpy(q->data + pos, &len32, 4);
      memcpy(q->data + pos + 4, buf, len);
      h->head.store(head + need, std::memory_order_release);
      return 0;
    }
    if (!sleep_until_deadline(deadline)) return 1;
  }
}

// Returns 0 on success (*out malloc'd, caller frees with pt_buf_free),
// 1 on timeout, 2 on closed-and-drained.
PT_EXPORT int pt_shmq_pop(void* handle, char** out, uint64_t* out_len,
                          int64_t timeout_ms) {
  auto* q = static_cast<Queue*>(handle);
  Header* h = q->hdr;
  const uint64_t cap = h->capacity;
  *out = nullptr;
  *out_len = 0;
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms);
  while (true) {
    uint64_t tail = h->tail.load(std::memory_order_relaxed);
    uint64_t head = h->head.load(std::memory_order_acquire);
    if (head == tail) {
      if (h->closed.load(std::memory_order_acquire)) return 2;
      if (!sleep_until_deadline(deadline)) return 1;
      continue;
    }
    uint64_t pos = tail % cap;
    uint64_t contig = cap - pos;
    if (contig < 4) {  // implicit wrap: marker didn't fit
      h->tail.store(tail + contig, std::memory_order_release);
      continue;
    }
    uint32_t len32;
    memcpy(&len32, q->data + pos, 4);
    if (len32 == kWrapMarker) {
      h->tail.store(tail + contig, std::memory_order_release);
      continue;
    }
    *out = static_cast<char*>(malloc(len32));
    memcpy(*out, q->data + pos + 4, len32);
    *out_len = len32;
    h->tail.store(tail + 4 + len32, std::memory_order_release);
    return 0;
  }
}

PT_EXPORT uint64_t pt_shmq_size_bytes(void* handle) {
  auto* q = static_cast<Queue*>(handle);
  return q->hdr->head.load() - q->hdr->tail.load();
}

PT_EXPORT void pt_shmq_close(void* handle) {
  if (handle)
    static_cast<Queue*>(handle)->hdr->closed.store(1, std::memory_order_release);
}

PT_EXPORT void pt_shmq_destroy(void* handle) {
  if (!handle) return;
  auto* q = static_cast<Queue*>(handle);
  bool unlink = q->owner;
  std::string name = q->name;
  munmap(q->hdr, q->map_size);
  if (unlink) shm_unlink(name.c_str());
  delete q;
}
