// Runtime counter/stat registry with peak tracking.
//
// Reference: phi/core/memory/stats.h — per-device current/peak memory
// counters (STAT_ADD/STAT_GET macros, `paddle.device.cuda.max_memory_allocated`
// reads them).  On TPU the device allocator lives inside PJRT, so the
// native registry tracks host-side quantities (pinned batches in flight,
// checkpoint bytes, IPC queue depths) and mirrors device stats pushed down
// from Python (jax memory_stats snapshots) so tooling has one place to read.

#include <cstring>
#include <map>
#include <mutex>
#include <string>

#include "common.h"

namespace {

struct Stat {
  int64_t current = 0;
  int64_t peak = 0;
};

std::mutex g_mu;
std::map<std::string, Stat> g_stats;

}  // namespace

PT_EXPORT int64_t pt_stat_update(const char* name, int64_t delta) {
  std::lock_guard<std::mutex> lk(g_mu);
  Stat& s = g_stats[name];
  s.current += delta;
  if (s.current > s.peak) s.peak = s.current;
  return s.current;
}

PT_EXPORT void pt_stat_set(const char* name, int64_t value) {
  std::lock_guard<std::mutex> lk(g_mu);
  Stat& s = g_stats[name];
  s.current = value;
  if (value > s.peak) s.peak = value;
}

PT_EXPORT int64_t pt_stat_current(const char* name) {
  std::lock_guard<std::mutex> lk(g_mu);
  auto it = g_stats.find(name);
  return it == g_stats.end() ? 0 : it->second.current;
}

PT_EXPORT int64_t pt_stat_peak(const char* name) {
  std::lock_guard<std::mutex> lk(g_mu);
  auto it = g_stats.find(name);
  return it == g_stats.end() ? 0 : it->second.peak;
}

PT_EXPORT void pt_stat_reset_peak(const char* name) {
  std::lock_guard<std::mutex> lk(g_mu);
  auto it = g_stats.find(name);
  if (it != g_stats.end()) it->second.peak = it->second.current;
}

PT_EXPORT void pt_stat_clear() {
  std::lock_guard<std::mutex> lk(g_mu);
  g_stats.clear();
}

// Writes "name current peak\n" lines into out (malloc'd, caller frees via
// pt_buf_free); returns byte length.
PT_EXPORT int64_t pt_stat_report(char** out) {
  std::lock_guard<std::mutex> lk(g_mu);
  std::string rep;
  for (auto& kv : g_stats) {
    rep += kv.first;
    rep += ' ';
    rep += std::to_string(kv.second.current);
    rep += ' ';
    rep += std::to_string(kv.second.peak);
    rep += '\n';
  }
  *out = static_cast<char*>(malloc(rep.size()));
  memcpy(*out, rep.data(), rep.size());
  return static_cast<int64_t>(rep.size());
}
