"""Native (C++) runtime components, loaded via ctypes.

The reference framework's host runtime is C++ (TCPStore rendezvous —
paddle/phi/core/distributed/store/tcp_store.h:121; shared-memory dataloader
IPC — python/paddle/io/dataloader/worker.py + mmap_allocator; host tracer —
paddle/fluid/platform/profiler/host_tracer.cc; memory stats —
paddle/phi/core/memory/stats.h).  This package is the TPU-native equivalent:
the device path belongs to XLA/PJRT, the host-side runtime is this C++
library.

The library is compiled on first use with g++ (source ships in src/); if the
toolchain or the build fails, ``load()`` returns None and pure-Python
fallbacks (paddle_tpu.distributed.store, threading DataLoader, Python tracer)
take over.  Set PADDLE_TPU_NATIVE=0 to force the fallbacks.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libpaddle_tpu_native.so")

_lock = threading.Lock()
_lib = None
_load_attempted = False


def _declare(lib):
    c = ctypes
    lib.pt_store_server_start.restype = c.c_void_p
    lib.pt_store_server_start.argtypes = [c.c_int]
    lib.pt_store_server_port.restype = c.c_int
    lib.pt_store_server_port.argtypes = [c.c_void_p]
    lib.pt_store_server_stop.argtypes = [c.c_void_p]
    lib.pt_store_client_connect.restype = c.c_void_p
    lib.pt_store_client_connect.argtypes = [c.c_char_p, c.c_int, c.c_int]
    lib.pt_store_set.restype = c.c_int
    lib.pt_store_set.argtypes = [c.c_void_p, c.c_char_p, c.c_char_p, c.c_int]
    lib.pt_store_get.restype = c.c_int
    lib.pt_store_get.argtypes = [c.c_void_p, c.c_char_p, c.c_int64,
                                 c.POINTER(c.c_void_p), c.POINTER(c.c_int64)]
    lib.pt_store_get_nowait.restype = c.c_int
    lib.pt_store_get_nowait.argtypes = [c.c_void_p, c.c_char_p,
                                        c.POINTER(c.c_void_p), c.POINTER(c.c_int64)]
    lib.pt_store_add.restype = c.c_int64
    lib.pt_store_add.argtypes = [c.c_void_p, c.c_char_p, c.c_int64]
    lib.pt_store_wait.restype = c.c_int
    lib.pt_store_wait.argtypes = [c.c_void_p, c.c_char_p, c.c_int64]
    lib.pt_store_delete.restype = c.c_int
    lib.pt_store_delete.argtypes = [c.c_void_p, c.c_char_p]
    lib.pt_store_keys.restype = c.c_int
    lib.pt_store_keys.argtypes = [c.c_void_p, c.c_char_p,
                                  c.POINTER(c.c_void_p), c.POINTER(c.c_int64)]
    lib.pt_store_client_close.argtypes = [c.c_void_p]
    lib.pt_buf_free.argtypes = [c.c_void_p]

    lib.pt_shmq_create.restype = c.c_void_p
    lib.pt_shmq_create.argtypes = [c.c_char_p, c.c_uint64]
    lib.pt_shmq_open.restype = c.c_void_p
    lib.pt_shmq_open.argtypes = [c.c_char_p]
    lib.pt_shmq_push.restype = c.c_int
    lib.pt_shmq_push.argtypes = [c.c_void_p, c.c_char_p, c.c_uint64, c.c_int64]
    lib.pt_shmq_pop.restype = c.c_int
    lib.pt_shmq_pop.argtypes = [c.c_void_p, c.POINTER(c.c_void_p),
                                c.POINTER(c.c_uint64), c.c_int64]
    lib.pt_shmq_size_bytes.restype = c.c_uint64
    lib.pt_shmq_size_bytes.argtypes = [c.c_void_p]
    lib.pt_shmq_close.argtypes = [c.c_void_p]
    lib.pt_shmq_destroy.argtypes = [c.c_void_p]

    lib.pt_trace_intern.restype = c.c_uint32
    lib.pt_trace_intern.argtypes = [c.c_char_p]
    lib.pt_trace_begin.argtypes = [c.c_uint32]
    lib.pt_trace_span.argtypes = [c.c_uint32, c.c_int64, c.c_int64]
    lib.pt_trace_now_us.restype = c.c_int64
    lib.pt_trace_span_count.restype = c.c_int64
    lib.pt_trace_dump.restype = c.c_int64
    lib.pt_trace_dump.argtypes = [c.c_char_p, c.c_int]
    lib.pt_trace_enabled.restype = c.c_int

    lib.pt_stat_update.restype = c.c_int64
    lib.pt_stat_update.argtypes = [c.c_char_p, c.c_int64]
    lib.pt_stat_set.argtypes = [c.c_char_p, c.c_int64]
    lib.pt_stat_current.restype = c.c_int64
    lib.pt_stat_current.argtypes = [c.c_char_p]
    lib.pt_stat_peak.restype = c.c_int64
    lib.pt_stat_peak.argtypes = [c.c_char_p]
    lib.pt_stat_reset_peak.argtypes = [c.c_char_p]
    lib.pt_stat_report.restype = c.c_int64
    lib.pt_stat_report.argtypes = [c.POINTER(c.c_void_p)]
    return lib


def _build() -> bool:
    try:
        r = subprocess.run(
            ["make", "-C", _DIR, "-s"],
            capture_output=True, text=True, timeout=120,
        )
        return r.returncode == 0 and os.path.exists(_SO)
    except (OSError, subprocess.TimeoutExpired):
        return False


def load():
    """Returns the ctypes library, building it if needed; None on failure."""
    global _lib, _load_attempted
    if _lib is not None:
        return _lib
    if _load_attempted:
        return None
    with _lock:
        if _lib is not None or _load_attempted:
            return _lib
        _load_attempted = True
        if os.environ.get("PADDLE_TPU_NATIVE", "1") == "0":
            return None
        if not os.path.exists(_SO) and not _build():
            return None
        try:
            _lib = _declare(ctypes.CDLL(_SO))
        except OSError:
            _lib = None
        return _lib


def available() -> bool:
    return load() is not None


def take_buf(lib, ptr, length) -> bytes:
    """Copies a malloc'd native buffer into bytes and frees it."""
    if not ptr or length <= 0:
        if ptr:
            lib.pt_buf_free(ptr)
        return b""
    out = ctypes.string_at(ptr, length)
    lib.pt_buf_free(ptr)
    return out


class ShmQueue:
    """SPSC shared-memory ring buffer (producer or consumer endpoint).

    Reference analog: the shared-memory batch transport in the reference
    DataLoader (io/dataloader/worker.py, use_shared_memory=True).
    """

    def __init__(self, name: str, capacity: int = 64 << 20, create: bool = True):
        self._lib = load()
        if self._lib is None:
            raise RuntimeError("native library unavailable")
        self.name = name
        if create:
            self._h = self._lib.pt_shmq_create(name.encode(), capacity)
        else:
            self._h = self._lib.pt_shmq_open(name.encode())
        if not self._h:
            raise OSError(f"shm queue {name!r} {'create' if create else 'open'} failed")
        self._owner = create

    def push(self, data: bytes, timeout: float = 300.0) -> None:
        rc = self._lib.pt_shmq_push(self._h, data, len(data), int(timeout * 1000))
        if rc == 1:
            raise TimeoutError(f"shm push timed out ({len(data)} bytes)")
        if rc == 3:
            raise ValueError(f"message of {len(data)} bytes exceeds ring capacity")
        if rc != 0:
            raise BrokenPipeError("shm queue closed")

    def pop(self, timeout: float = 300.0) -> bytes | None:
        """Returns the next message, or None when closed and drained."""
        ptr = ctypes.c_void_p()
        length = ctypes.c_uint64()
        rc = self._lib.pt_shmq_pop(self._h, ctypes.byref(ptr),
                                   ctypes.byref(length), int(timeout * 1000))
        if rc == 1:
            raise TimeoutError("shm pop timed out")
        if rc == 2:
            return None
        return take_buf(self._lib, ptr.value, length.value)

    def qsize_bytes(self) -> int:
        return int(self._lib.pt_shmq_size_bytes(self._h))

    def close(self) -> None:
        if self._h:
            self._lib.pt_shmq_close(self._h)

    def destroy(self) -> None:
        if self._h:
            self._lib.pt_shmq_destroy(self._h)
            self._h = None


__all__ = ["load", "available", "take_buf", "ShmQueue"]
