"""jit: dynamic-to-static bridge (reference: python/paddle/jit/ — @to_static via
SOT bytecode tracing, jit/sot/translate.py:37).

TPU-native design: Python tracing is native to JAX, so the reference's 18.6k-LoC
bytecode simulator is unnecessary (SURVEY.md §7 mapping).  ``to_static`` wraps a
function or Layer into a cached ``jax.jit`` executable whose implicit state
(parameters/buffers) is passed as pytree arguments — so parameter updates are
picked up without retracing, and the same wrapper serves inference and the
jitted train step (paddle_tpu.jit.TrainStep)."""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, _unwrap, no_grad
from ..nn.layer_base import Layer

__all__ = [
    "to_static",
    "not_to_static",
    "functional_state",
    "functional_call",
    "TrainStep",
    "save",
    "load",
]


def functional_state(layer: Layer):
    """Extract (params, buffers) as flat name→array dicts (the pytree state)."""
    params = {name: _unwrap(p) for name, p in layer.named_parameters()}
    buffers = {name: _unwrap(b) for name, b in layer.named_buffers()}
    return params, buffers


# Stack of active _SwapState instances: in-place buffer updates (e.g.
# BatchNorm running stats) may assign tracer values ONLY to tensors that are
# part of an active swap — those are captured functionally before the swap
# exits; any other tensor would be permanently corrupted by a leaked tracer.
_active_swaps: list = []


def in_functional_swap(tensor=None) -> bool:
    if tensor is None:
        return bool(_active_swaps)
    return any(id(tensor) in s._saved for s in _active_swaps)


class _SwapState:
    """Temporarily substitute layer parameters/buffers with given arrays
    (typically tracers) — the functional bridge for eager Layers."""

    def __init__(self, layer: Layer, params: dict, buffers: dict):
        self.layer = layer
        self.params = params
        self.buffers = buffers
        self._saved = {}

    def __enter__(self):
        named_p = dict(self.layer.named_parameters())
        named_b = dict(self.layer.named_buffers())
        for name, val in self.params.items():
            t = named_p[name]
            self._saved[id(t)] = (t, t._value)
            t._value = val
        for name, val in self.buffers.items():
            t = named_b[name]
            if id(t) not in self._saved:
                self._saved[id(t)] = (t, t._value)
            t._value = val
        _active_swaps.append(self)
        return self

    def current_buffers(self) -> dict:
        """Buffer values as of now — includes in-place updates made during the
        swapped call (the BN running-stat path)."""
        return {name: _unwrap(b) for name, b in self.layer.named_buffers()}

    def __exit__(self, *exc):
        _active_swaps.remove(self)
        for t, v in self._saved.values():
            t._value = v
        return False


def functional_call(layer: Layer, params: dict, buffers: dict, *args,
                    return_new_buffers: bool = False, **kwargs):
    """Run ``layer(*args)`` as a pure function of (params, buffers, args).

    With ``return_new_buffers=True`` also returns the post-call buffer values,
    capturing in-place updates (BatchNorm running stats) functionally —
    otherwise those updates are discarded when the swap exits."""
    wrapped = jax.tree_util.tree_map(
        lambda a: Tensor(a) if isinstance(a, (jax.Array, jnp.ndarray)) else a, args
    )
    with no_grad(), _SwapState(layer, params, buffers) as swap:
        out = layer(*wrapped, **kwargs)
        new_buffers = swap.current_buffers() if return_new_buffers else None
    out = jax.tree_util.tree_map(
        lambda o: _unwrap(o) if isinstance(o, Tensor) else o, out,
        is_leaf=lambda o: isinstance(o, Tensor),
    )
    return (out, new_buffers) if return_new_buffers else out


class StaticFunction:
    """Result of @to_static: a compiled callable with paddle-like surface."""

    def __init__(self, function: Callable, layer: Layer | None = None, input_spec=None, **jit_kwargs):
        self._function = function
        self._layer = layer
        self._input_spec = input_spec
        self._jit_kwargs = jit_kwargs
        self._jitted = None
        functools.update_wrapper(self, function)

    def _build(self):
        layer = self._layer

        if layer is None:
            fn = self._function

            @jax.jit
            def pure(arg_vals, kwarg_vals):
                args = jax.tree_util.tree_map(
                    lambda a: Tensor(a) if isinstance(a, (jax.Array, jnp.ndarray)) else a, arg_vals
                )
                kwargs = jax.tree_util.tree_map(
                    lambda a: Tensor(a) if isinstance(a, (jax.Array, jnp.ndarray)) else a, kwarg_vals
                )
                with no_grad():
                    out = fn(*args, **kwargs)
                return jax.tree_util.tree_map(
                    lambda o: _unwrap(o) if isinstance(o, Tensor) else o, out,
                    is_leaf=lambda o: isinstance(o, Tensor),
                )

            self._jitted = pure
        else:
            fn = self._function

            @jax.jit
            def pure(params, buffers, arg_vals, kwarg_vals):
                args = jax.tree_util.tree_map(
                    lambda a: Tensor(a) if isinstance(a, (jax.Array, jnp.ndarray)) else a, arg_vals
                )
                kwargs = jax.tree_util.tree_map(
                    lambda a: Tensor(a) if isinstance(a, (jax.Array, jnp.ndarray)) else a, kwarg_vals
                )
                with no_grad(), _SwapState(layer, params, buffers):
                    out = fn(*args, **kwargs)
                return jax.tree_util.tree_map(
                    lambda o: _unwrap(o) if isinstance(o, Tensor) else o, out,
                    is_leaf=lambda o: isinstance(o, Tensor),
                )

            self._jitted = pure

    def __call__(self, *args, **kwargs):
        if self._jitted is None:
            self._build()
        arg_vals = jax.tree_util.tree_map(
            lambda a: _unwrap(a) if isinstance(a, Tensor) else a, args,
            is_leaf=lambda a: isinstance(a, Tensor),
        )
        kwarg_vals = jax.tree_util.tree_map(
            lambda a: _unwrap(a) if isinstance(a, Tensor) else a, kwargs,
            is_leaf=lambda a: isinstance(a, Tensor),
        )
        if self._layer is None:
            out = self._jitted(arg_vals, kwarg_vals)
        else:
            params, buffers = functional_state(self._layer)
            out = self._jitted(params, buffers, arg_vals, kwarg_vals)
        return jax.tree_util.tree_map(
            lambda o: Tensor(o) if isinstance(o, (jax.Array, jnp.ndarray)) else o, out
        )

    @property
    def code(self):
        return "<jax.jit compiled>"

    def concrete_program(self):
        return None


def to_static(function=None, input_spec=None, build_strategy=None, backend=None, full_graph=True, **kwargs):
    """``paddle.jit.to_static`` analog: decorate a function or Layer."""

    def decorate(obj):
        if isinstance(obj, Layer):
            sf = StaticFunction(obj.forward, layer=obj, input_spec=input_spec)
            obj.forward = sf
            return obj
        # plain function or unbound method
        return StaticFunction(obj, input_spec=input_spec)

    if function is not None:
        return decorate(function)
    return decorate


def not_to_static(fn):
    fn._not_to_static = True
    return fn


class TrainStep:
    """Fully-jitted train step: loss + grads + optimizer update in one XLA program
    (the performance path; the eager tape is the debugging path).

    Example::

        step = TrainStep(model, loss_fn, opt)
        for batch in loader:
            loss = step(x, y)      # params updated in place (device-side)
    """

    def __init__(self, model: Layer, loss_fn: Callable, optimizer, donate: bool = True):
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        params, buffers = functional_state(model)
        # copy: the donated step must never invalidate the eager model's arrays
        self._params = {k: jnp.copy(v) for k, v in params.items()} if donate else params
        self._buffers = buffers
        self._opt_state = optimizer.init_state_pytree(params)
        self._named = dict(model.named_parameters())

        def compute_loss(params, buffers, args):
            wrapped = [Tensor(a) if isinstance(a, (jax.Array, jnp.ndarray)) else a for a in args]
            with no_grad(), _SwapState(model, params, buffers) as swap:
                out = loss_fn(*wrapped)
                new_buffers = swap.current_buffers()
            loss = out[0] if isinstance(out, (tuple, list)) else out
            return _unwrap(loss) if isinstance(loss, Tensor) else loss, new_buffers

        opt = optimizer

        @functools.partial(jax.jit, donate_argnums=(0, 2) if donate else ())
        def step(params, buffers, opt_state, lr, args):
            (loss, new_buffers), grads = jax.value_and_grad(compute_loss, has_aux=True)(
                params, buffers, args
            )
            new_params, new_opt_state = opt.apply_gradients_pytree(params, grads, opt_state, lr)
            return loss, new_params, new_opt_state, new_buffers

        self._step = step

    def __call__(self, *args):
        arg_vals = [(_unwrap(a) if isinstance(a, Tensor) else a) for a in args]
        lr = self.optimizer.get_lr()
        loss, self._params, self._opt_state, self._buffers = self._step(
            self._params, self._buffers, self._opt_state, lr, tuple(arg_vals)
        )
        return Tensor(loss)

    def sync_to_model(self):
        """Write the device-side params/buffers back into the eager model."""
        named_b = dict(self.model.named_buffers())
        for name, val in self._params.items():
            # copy: the next donated step deletes self._params' buffers
            self._named[name]._value = jnp.copy(val)
        for name, val in self._buffers.items():
            if name in named_b:
                named_b[name]._value = val

    @property
    def params(self):
        return self._params


# ---- jit.save / jit.load (reference: paddle.jit.save TranslatedLayer) ----

def save(layer, path, input_spec=None, **config):
    """Serialize a Layer's state + class info (weights-level save; the compiled
    executable is rebuilt by jit on load — XLA compile cache makes this cheap)."""
    import pickle

    state = {}
    if isinstance(layer, Layer):
        import numpy as np

        state = {k: np.asarray(_unwrap(v)) for k, v in layer.state_dict().items()}
    with open(path + ".pdparams", "wb") as f:
        pickle.dump(state, f)


def load(path, **config):
    import pickle

    with open(path + ".pdparams", "rb") as f:
        return pickle.load(f)
