"""jit: dynamic-to-static bridge (reference: python/paddle/jit/ — @to_static via
SOT bytecode tracing, jit/sot/translate.py:37).

TPU-native design: Python tracing is native to JAX, so the reference's 18.6k-LoC
bytecode simulator is unnecessary (SURVEY.md §7 mapping).  ``to_static`` wraps a
function or Layer into a cached ``jax.jit`` executable whose implicit state
(parameters/buffers) is passed as pytree arguments — so parameter updates are
picked up without retracing, and the same wrapper serves inference and the
jitted train step (paddle_tpu.jit.TrainStep)."""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, _unwrap, no_grad
from ..nn.layer_base import Layer

__all__ = [
    "to_static",
    "not_to_static",
    "enable_to_static",
    "ignore_module",
    "set_code_level",
    "set_verbosity",
    "functional_state",
    "functional_call",
    "TranslatedLayer",
    "TrainStep",
    "save",
    "load",
]

# dy2static global switch (reference: python/paddle/jit/api.py
# enable_to_static) — when off, to_static returns the callable un-jitted
_to_static_enabled = True
# modules the reference's AST transpiler skips (jit/utils.py ignore_module);
# tracing-native to_static has no transpiler, but the registry is honored by
# returning functions from these modules unwrapped
_ignored_modules: list = []
# dy2static logging knobs (jit/dy2static/logging_utils.py)
_verbosity = 0
_code_level = 0


def enable_to_static(enable_to_static_bool: bool) -> None:
    global _to_static_enabled
    _to_static_enabled = bool(enable_to_static_bool)


def ignore_module(modules) -> None:
    _ignored_modules.extend(modules if isinstance(modules, (list, tuple))
                            else [modules])


def set_verbosity(level: int = 0, also_to_stdout: bool = False) -> None:
    global _verbosity
    _verbosity = int(level)


def set_code_level(level: int = 100, also_to_stdout: bool = False) -> None:
    global _code_level
    _code_level = int(level)


def functional_state(layer: Layer):
    """Extract (params, buffers) as flat name→array dicts (the pytree state)."""
    params = {name: _unwrap(p) for name, p in layer.named_parameters()}
    buffers = {name: _unwrap(b) for name, b in layer.named_buffers()}
    return params, buffers


# Stack of active _SwapState instances: in-place buffer updates (e.g.
# BatchNorm running stats) may assign tracer values ONLY to tensors that are
# part of an active swap — those are captured functionally before the swap
# exits; any other tensor would be permanently corrupted by a leaked tracer.
_active_swaps: list = []


def in_functional_swap(tensor=None) -> bool:
    if tensor is None:
        return bool(_active_swaps)
    return any(id(tensor) in s._saved for s in _active_swaps)


class _SwapState:
    """Temporarily substitute layer parameters/buffers with given arrays
    (typically tracers) — the functional bridge for eager Layers."""

    def __init__(self, layer: Layer, params: dict, buffers: dict):
        self.layer = layer
        self.params = params
        self.buffers = buffers
        self._saved = {}

    def __enter__(self):
        named_p = dict(self.layer.named_parameters())
        named_b = dict(self.layer.named_buffers())
        for name, val in self.params.items():
            t = named_p[name]
            self._saved[id(t)] = (t, t._value)
            t._value = val
        for name, val in self.buffers.items():
            t = named_b[name]
            if id(t) not in self._saved:
                self._saved[id(t)] = (t, t._value)
            t._value = val
        _active_swaps.append(self)
        return self

    def current_buffers(self) -> dict:
        """Buffer values as of now — includes in-place updates made during the
        swapped call (the BN running-stat path)."""
        return {name: _unwrap(b) for name, b in self.layer.named_buffers()}

    def __exit__(self, *exc):
        _active_swaps.remove(self)
        for t, v in self._saved.values():
            t._value = v
        return False


def functional_call(layer: Layer, params: dict, buffers: dict, *args,
                    return_new_buffers: bool = False, **kwargs):
    """Run ``layer(*args)`` as a pure function of (params, buffers, args).

    With ``return_new_buffers=True`` also returns the post-call buffer values,
    capturing in-place updates (BatchNorm running stats) functionally —
    otherwise those updates are discarded when the swap exits."""
    wrapped = jax.tree_util.tree_map(
        lambda a: Tensor(a) if isinstance(a, (jax.Array, jnp.ndarray)) else a, args
    )
    with no_grad(), _SwapState(layer, params, buffers) as swap:
        out = layer(*wrapped, **kwargs)
        new_buffers = swap.current_buffers() if return_new_buffers else None
    out = jax.tree_util.tree_map(
        lambda o: _unwrap(o) if isinstance(o, Tensor) else o, out,
        is_leaf=lambda o: isinstance(o, Tensor),
    )
    return (out, new_buffers) if return_new_buffers else out


class StaticFunction:
    """Result of @to_static: a compiled callable with paddle-like surface."""

    def __init__(self, function: Callable, layer: Layer | None = None, input_spec=None, **jit_kwargs):
        self._function = function
        self._layer = layer
        self._input_spec = input_spec
        self._jit_kwargs = jit_kwargs
        self._jitted = None
        functools.update_wrapper(self, function)

    def _build(self):
        layer = self._layer

        if layer is None:
            fn = self._function

            @jax.jit
            def pure(arg_vals, kwarg_vals):
                args = jax.tree_util.tree_map(
                    lambda a: Tensor(a) if isinstance(a, (jax.Array, jnp.ndarray)) else a, arg_vals
                )
                kwargs = jax.tree_util.tree_map(
                    lambda a: Tensor(a) if isinstance(a, (jax.Array, jnp.ndarray)) else a, kwarg_vals
                )
                with no_grad():
                    out = fn(*args, **kwargs)
                return jax.tree_util.tree_map(
                    lambda o: _unwrap(o) if isinstance(o, Tensor) else o, out,
                    is_leaf=lambda o: isinstance(o, Tensor),
                )

            self._jitted = pure
        else:
            fn = self._function

            @jax.jit
            def pure(params, buffers, arg_vals, kwarg_vals):
                args = jax.tree_util.tree_map(
                    lambda a: Tensor(a) if isinstance(a, (jax.Array, jnp.ndarray)) else a, arg_vals
                )
                kwargs = jax.tree_util.tree_map(
                    lambda a: Tensor(a) if isinstance(a, (jax.Array, jnp.ndarray)) else a, kwarg_vals
                )
                with no_grad(), _SwapState(layer, params, buffers):
                    out = fn(*args, **kwargs)
                return jax.tree_util.tree_map(
                    lambda o: _unwrap(o) if isinstance(o, Tensor) else o, out,
                    is_leaf=lambda o: isinstance(o, Tensor),
                )

            self._jitted = pure

    def __call__(self, *args, **kwargs):
        if not _to_static_enabled:  # consulted per call, like the
            # reference's ProgramTranslator switch — disabling after
            # decoration must still fall back to eager
            return self._function(*args, **kwargs)
        if self._jitted is None:
            self._build()
        arg_vals = jax.tree_util.tree_map(
            lambda a: _unwrap(a) if isinstance(a, Tensor) else a, args,
            is_leaf=lambda a: isinstance(a, Tensor),
        )
        kwarg_vals = jax.tree_util.tree_map(
            lambda a: _unwrap(a) if isinstance(a, Tensor) else a, kwargs,
            is_leaf=lambda a: isinstance(a, Tensor),
        )
        if self._layer is None:
            out = self._jitted(arg_vals, kwarg_vals)
        else:
            params, buffers = functional_state(self._layer)
            out = self._jitted(params, buffers, arg_vals, kwarg_vals)
        return jax.tree_util.tree_map(
            lambda o: Tensor(o) if isinstance(o, (jax.Array, jnp.ndarray)) else o, out
        )

    @property
    def code(self):
        return "<jax.jit compiled>"

    def concrete_program(self):
        return None


def to_static(function=None, input_spec=None, build_strategy=None, backend=None, full_graph=True, **kwargs):
    """``paddle.jit.to_static`` analog: decorate a function or Layer."""

    def decorate(obj):
        if not _to_static_enabled:
            return obj
        mod = getattr(obj, "__module__", None)
        if mod is not None and any(
                getattr(m, "__name__", m) == mod for m in _ignored_modules):
            return obj
        if isinstance(obj, Layer):
            sf = StaticFunction(obj.forward, layer=obj, input_spec=input_spec)
            obj.forward = sf
            return obj
        # plain function or unbound method
        return StaticFunction(obj, input_spec=input_spec)

    if function is not None:
        return decorate(function)
    return decorate


def not_to_static(fn):
    fn._not_to_static = True
    return fn


class TrainStep:
    """Fully-jitted train step: loss + grads + optimizer update in one XLA program
    (the performance path; the eager tape is the debugging path).

    Example::

        step = TrainStep(model, loss_fn, opt)
        for batch in loader:
            loss = step(x, y)      # params updated in place (device-side)
    """

    def __init__(self, model: Layer, loss_fn: Callable, optimizer, donate: bool = True):
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        params, buffers = functional_state(model)
        # copy: the donated step must never invalidate the eager model's arrays
        self._params = {k: jnp.copy(v) for k, v in params.items()} if donate else params
        self._buffers = buffers
        self._opt_state = optimizer.init_state_pytree(params)
        self._named = dict(model.named_parameters())

        def compute_loss(params, buffers, args):
            wrapped = [Tensor(a) if isinstance(a, (jax.Array, jnp.ndarray)) else a for a in args]
            with no_grad(), _SwapState(model, params, buffers) as swap:
                out = loss_fn(*wrapped)
                new_buffers = swap.current_buffers()
            loss = out[0] if isinstance(out, (tuple, list)) else out
            return _unwrap(loss) if isinstance(loss, Tensor) else loss, new_buffers

        opt = optimizer

        @functools.partial(jax.jit, donate_argnums=(0, 2) if donate else ())
        def step(params, buffers, opt_state, lr, args):
            (loss, new_buffers), grads = jax.value_and_grad(compute_loss, has_aux=True)(
                params, buffers, args
            )
            new_params, new_opt_state = opt.apply_gradients_pytree(params, grads, opt_state, lr)
            return loss, new_params, new_opt_state, new_buffers

        self._step = step

    def __call__(self, *args):
        arg_vals = [(_unwrap(a) if isinstance(a, Tensor) else a) for a in args]
        lr = self.optimizer.get_lr()
        loss, self._params, self._opt_state, self._buffers = self._step(
            self._params, self._buffers, self._opt_state, lr, tuple(arg_vals)
        )
        return Tensor(loss)

    def sync_to_model(self):
        """Write the device-side params/buffers back into the eager model."""
        named_b = dict(self.model.named_buffers())
        for name, val in self._params.items():
            # copy: the next donated step deletes self._params' buffers
            self._named[name]._value = jnp.copy(val)
        for name, val in self._buffers.items():
            if name in named_b:
                named_b[name]._value = val

    @property
    def params(self):
        return self._params


# ---- jit.save / jit.load (reference: paddle.jit.save TranslatedLayer) ----

def save(layer, path, input_spec=None, **config):
    """Serialize a Layer: always writes ``<path>.pdparams`` (numpy weights);
    when ``input_spec`` is given, additionally writes the jax.export StableHLO
    program (``<path>.pdmodel`` + ``.pdiparams``) so ``load`` can return a
    runnable TranslatedLayer without the defining Python code (reference:
    python/paddle/jit/api.py save/load contract)."""
    import pickle

    import numpy as np

    state = {}
    if isinstance(layer, Layer):
        state = {k: np.asarray(_unwrap(v)) for k, v in layer.state_dict().items()}
    with open(path + ".pdparams", "wb") as f:
        pickle.dump(state, f)

    if input_spec is not None and isinstance(layer, Layer):
        import warnings

        from ..inference import save_inference_model

        examples = []
        for spec in input_spec:
            shape = tuple(1 if (s is None or int(s) < 0) else int(s)
                          for s in spec.shape)
            if shape != tuple(spec.shape):
                warnings.warn(
                    "jit.save: dynamic dims in InputSpec are pinned to 1 — "
                    "the exported program is fixed-shape (AOT StableHLO)")
            examples.append(jnp.zeros(shape, spec.dtype))
        params, buffers = functional_state(layer)

        def fwd(state, *inputs):
            p, b = state
            return functional_call(layer, p, b, *inputs)

        save_inference_model(path, fwd, examples, params=(params, buffers))


class TranslatedLayer(Layer):
    """Layer reconstructed from a saved program (reference:
    python/paddle/jit/translated_layer.py) — executes the deserialized
    StableHLO export; no Python model code needed."""

    def __init__(self, exported, params, state=None):
        super().__init__()
        self._exported = exported
        self._exec_params = params
        self._state = state or {}

    def state_dict(self, *a, **kw):
        return dict(self._state)

    def forward(self, *inputs):
        vals = [_unwrap(x) for x in inputs]
        out = self._exported.call(self._exec_params, *vals)
        return jax.tree_util.tree_map(
            lambda o: Tensor(o) if isinstance(o, (jax.Array, jnp.ndarray)) else o,
            out)

    def program(self):
        return self._exported.mlir_module()


def load(path, **config):
    """Returns a TranslatedLayer when ``save`` exported a program for this
    path, else the raw pickled state dict (weights-only save)."""
    import os
    import pickle

    state = {}
    has_params = os.path.exists(path + ".pdparams")
    if not has_params and not os.path.exists(path + ".pdmodel"):
        raise FileNotFoundError(
            f"jit.load: neither {path}.pdparams nor {path}.pdmodel exists")
    if has_params:
        with open(path + ".pdparams", "rb") as f:
            state = pickle.load(f)
    if os.path.exists(path + ".pdmodel"):
        from ..inference import load_inference_model

        exported, params = load_inference_model(path)
        return TranslatedLayer(exported, params, state)
    return state
