"""Weight-decay regularizers (reference: python/paddle/regularizer.py).

The reference applies these inside the C++ optimizer ops via append_regularization_ops;
here they are declarative records that the jitted optimizer step reads
(`optimizer/__init__.py:_parse_wd` consumes ``_coeff``), so the decay fuses
into the same XLA program as the update.
"""

from __future__ import annotations

__all__ = ["L1Decay", "L2Decay"]


class WeightDecayRegularizer:
    def __init__(self, coeff: float = 0.0):
        self._coeff = float(coeff)

    @property
    def coeff(self) -> float:
        return self._coeff

    def __repr__(self):
        return f"{type(self).__name__}(coeff={self._coeff})"


class L1Decay(WeightDecayRegularizer):
    """loss += coeff * sum(|w|) — applied as a gradient term sign(w)*coeff."""


class L2Decay(WeightDecayRegularizer):
    """loss += 0.5 * coeff * sum(w^2) — the decoupled/fused wd path."""
