"""paddle_tpu: a TPU-native deep-learning framework with PaddlePaddle's capabilities.

Top-level namespace mirrors ``paddle.*``: tensor ops at the root, ``nn``,
``optimizer``, ``amp``, ``io``, ``autograd``, ``jit``, ``static``, ``distributed``,
``vision``, ``incubate`` as subpackages.  Compute is JAX/XLA (+Pallas kernels);
see SURVEY.md for the design mapping to the reference.
"""

from __future__ import annotations

# core
from . import device  # the full paddle.device namespace (device/__init__.py)
from .core.device import (
    get_device,
    set_device,
)
from .core.dtype import (
    bfloat16,
    bool_ as bool,  # noqa: A001 - paddle exposes paddle.bool
    complex64,
    complex128,
    float16,
    float32,
    float64,
    float8_e4m3fn,
    float8_e5m2,
    get_default_dtype,
    int8,
    int16,
    int32,
    int64,
    set_default_dtype,
    uint8,
)
from .core.flags import get_flags, set_flags
from .core.rng import get_rng_state, seed, set_rng_state
from .core.tensor import (
    Parameter,
    Tensor,
    enable_grad,
    is_grad_enabled,
    no_grad,
    set_grad_enabled,
    to_tensor,
)

# ops: populate the root namespace like paddle.*
from . import ops as _ops_pkg
from .ops.creation import *  # noqa: F401,F403
from .ops.linalg import *  # noqa: F401,F403
from .ops.manipulation import *  # noqa: F401,F403
from .ops.math import *  # noqa: F401,F403
from .ops.extras import *  # noqa: F401,F403

# re-export every registered op by name (covers the _unary/_binary generated ones)
from .ops.registry import OPS as _OPS

for _name, _od in list(_OPS.items()):
    if _name not in globals():
        globals()[_name] = _od.fn
del _name, _od

# subpackages (imported after root ops so they can use them)
from . import amp  # noqa: E402
from . import autograd  # noqa: E402
from . import distributed  # noqa: E402
from . import framework  # noqa: E402
from . import incubate  # noqa: E402
from . import io  # noqa: E402
from . import jit  # noqa: E402
from . import metric  # noqa: E402
from . import nn  # noqa: E402
from . import optimizer  # noqa: E402
from . import profiler  # noqa: E402
from . import static  # noqa: E402
from . import vision  # noqa: E402
from . import fft  # noqa: E402
from . import signal  # noqa: E402
from . import distribution  # noqa: E402
from . import sparse  # noqa: E402
from . import strings  # noqa: E402
from . import quantization  # noqa: E402
from . import geometric  # noqa: E402
from . import inference  # noqa: E402
from . import onnx  # noqa: E402
from . import callbacks  # noqa: E402
from . import hub  # noqa: E402
from . import linalg  # noqa: E402
from . import reader  # noqa: E402
from . import regularizer  # noqa: E402
from . import sysconfig  # noqa: E402
from . import tensor  # noqa: E402
from . import utils  # noqa: E402
from . import version  # noqa: E402
from .batch import batch  # noqa: E402
from . import audio  # noqa: E402
from . import text  # noqa: E402
from . import hapi  # noqa: E402
from .framework.io_utils import load, save  # noqa: E402
from .hapi import Model, summary  # noqa: E402
from .jit import to_static  # noqa: E402

disable_static = lambda *a, **k: None  # eager is the default and only "dygraph" mode
enable_static = lambda *a, **k: None  # static = jit tracing; kept for API parity
in_dynamic_mode = lambda: True

grad = autograd.grad

__version__ = "0.1.0"

# top-level namespace tail: constants, places, in-place variants, long-tail
# functions (reference python/paddle/__init__.py __all__ parity)
import sys as _sys  # noqa: E402

from . import _compat_tail as _ct  # noqa: E402

_ct._install(_sys.modules[__name__])
