"""MoE transformer LM (BASELINE config #5: DeepSeekMoE / Qwen2-MoE class).

Reference surface: the reference's MoE stack is `MoELayer` + gates
(python/paddle/incubate/distributed/models/moe/moe_layer.py, moe/gate/) with
dispatch/combine over `global_scatter`/`global_gather` NCCL alltoall, plus the
semi-auto `moe_global_mesh_tensor` APIs (auto_parallel/api.py:495).

TPU-first design: experts are a stacked weight tensor [E, ...] sharded over the
"mp" mesh axis (expert parallelism); routing uses the dense GShard/Switch
formulation — one_hot dispatch/combine einsums with a static capacity — which
XLA lowers to an all-to-all over the expert axis on ICI (SURVEY.md §7 row
"EP").  DeepSeekMoE structure: `n_shared` always-on shared experts + `E`
routed experts with top-k token-choice gating, load-balance auxiliary loss
(Switch-style) and router z-loss.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.pallas import flash_attention as fa
from ..ops.pallas import rms_norm as rms
from ..ops.pallas import rope as rope_mod
from ..ops.pallas import swiglu as swiglu_mod


@dataclasses.dataclass
class MoEConfig:
    vocab_size: int = 32000
    hidden_size: int = 2048
    intermediate_size: int = 4096       # shared-expert (dense) ffn width
    moe_intermediate_size: int = 1024   # per-routed-expert ffn width
    num_hidden_layers: int = 12
    num_attention_heads: int = 16
    num_key_value_heads: int = 4
    num_experts: int = 8
    num_shared_experts: int = 1
    top_k: int = 2
    capacity_factor: float = 1.25
    aux_loss_weight: float = 0.01
    z_loss_weight: float = 1e-3
    # "dense" = GShard one-hot einsum routing (O(tokens*E*C) FLOPs; compiles
    # to clean all-to-alls under EP sharding), "sort" = stable-argsort
    # scatter/gather routing (O(tokens*K) data movement — the winner at
    # DeepSeek-scale E), "ragged" = DROPLESS lax.ragged_dot grouped matmuls
    # (no capacity, no padding; opt-in — changes drop semantics),
    # "auto" = sort above _SORT_DISPATCH_MIN_EXPERTS
    dispatch: str = "auto"
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    dtype: Any = jnp.bfloat16

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads

    @staticmethod
    def deepseek_moe_16b():
        """DeepSeekMoE-16B structure (BASELINE ladder row #5): 64 routed +
        2 shared experts (shared width = intermediate_size * num_shared =
        2816), top-6 token-choice gating.  At E=64 the 'auto' dispatch
        resolves to the sort engine."""
        return MoEConfig(
            vocab_size=102400, hidden_size=2048, intermediate_size=1408,
            moe_intermediate_size=1408, num_hidden_layers=28,
            num_attention_heads=16, num_key_value_heads=16,
            num_experts=64, num_shared_experts=2, top_k=6,
        )

    @staticmethod
    def qwen2_moe_a14b():
        """Qwen2-57B-A14B structure: 64 routed + shared block of width
        8 * 2560 = 20480, top-8."""
        return MoEConfig(
            vocab_size=151936, hidden_size=3584, intermediate_size=2560,
            moe_intermediate_size=2560, num_hidden_layers=28,
            num_attention_heads=28, num_key_value_heads=4,
            num_experts=64, num_shared_experts=8, top_k=8,
        )

    @staticmethod
    def tiny(vocab=256, hidden=64, layers=2, heads=4, kv_heads=2,
             experts=4, top_k=2, inter=128, moe_inter=64):
        return MoEConfig(
            vocab_size=vocab, hidden_size=hidden, intermediate_size=inter,
            moe_intermediate_size=moe_inter, num_hidden_layers=layers,
            num_attention_heads=heads, num_key_value_heads=kv_heads,
            num_experts=experts, top_k=top_k, max_position_embeddings=256,
        )


def init_params(cfg: MoEConfig, key=None) -> dict:
    key = key if key is not None else jax.random.key(0)
    k = iter(jax.random.split(key, 24))
    h, i, mi, v = (cfg.hidden_size, cfg.intermediate_size,
                   cfg.moe_intermediate_size, cfg.vocab_size)
    nh, nkv, hd = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim
    L, E = cfg.num_hidden_layers, cfg.num_experts
    std = 0.02

    def init(kk, shape):
        return (jax.random.normal(kk, shape, jnp.float32) * std).astype(cfg.dtype)

    return {
        "embed": init(next(k), (v, h)),
        "final_norm": jnp.ones((h,), cfg.dtype),
        "lm_head": init(next(k), (h, v)),
        "layers": {
            "input_norm": jnp.ones((L, h), cfg.dtype),
            "post_norm": jnp.ones((L, h), cfg.dtype),
            "wq": init(next(k), (L, h, nh * hd)),
            "wk": init(next(k), (L, h, nkv * hd)),
            "wv": init(next(k), (L, h, nkv * hd)),
            "wo": init(next(k), (L, nh * hd, h)),
            # shared (dense) experts: swiglu ffn, width i * n_shared
            "s_gate": init(next(k), (L, h, i * cfg.num_shared_experts)),
            "s_up": init(next(k), (L, h, i * cfg.num_shared_experts)),
            "s_down": init(next(k), (L, i * cfg.num_shared_experts, h)),
            # router + routed experts (stacked on E)
            "router": init(next(k), (L, h, E)).astype(jnp.float32),
            "e_gate": init(next(k), (L, E, h, mi)),
            "e_up": init(next(k), (L, E, h, mi)),
            "e_down": init(next(k), (L, E, mi, h)),
        },
    }


def param_specs(cfg: MoEConfig, mp: int = 1) -> dict:
    """Experts shard over 'mp' (expert parallelism); attention is Megatron-TP
    over the same axis; ZeRO over 'sharding' like models/llama.py.  K/V
    projections replicate over 'mp' when it exceeds num_key_value_heads
    (sub-head splits trigger involuntary remat — see llama.param_specs)."""
    kv_col = None if cfg.num_key_value_heads % mp != 0 else "mp"
    return {
        "embed": P("mp", "sharding"),
        "final_norm": P(None),
        "lm_head": P("sharding", "mp"),
        "layers": {
            "input_norm": P(None, None),
            "post_norm": P(None, None),
            "wq": P(None, "sharding", "mp"),
            "wk": P(None, "sharding", kv_col),
            "wv": P(None, "sharding", kv_col),
            "wo": P(None, "mp", "sharding"),
            "s_gate": P(None, "sharding", "mp"),
            "s_up": P(None, "sharding", "mp"),
            "s_down": P(None, "mp", "sharding"),
            "router": P(None, None, None),
            "e_gate": P(None, "mp", "sharding", None),   # expert dim over mp
            "e_up": P(None, "mp", "sharding", None),
            "e_down": P(None, "mp", None, "sharding"),
        },
    }


def serving_param_specs(cfg: MoEConfig, axis: str = "tp") -> dict:
    """Serving-mesh TP *placement layout* for the MoE param tree: residual
    stream / embed / norms / router / lm_head replicated, attention split
    along (kv_)heads via the shared MEGATRON_SPLIT table, shared-expert ffn
    column/row-split, routed experts split on the EXPERT dim (expert
    compute shard-local, all-to-all dispatch/combine between shards).

    WEIGHT LAYOUT ONLY — no forward in this module consumes it yet: the
    continuous-batching engine's TP mode (docs/tp_serving.md) runs the
    dense llama decoder, whose shard_map bodies insert the per-layer psum
    boundaries themselves (llama.decoder_attn_residual /
    decoder_mlp_residual).  A sharded MoE serve additionally needs those
    reductions plus the expert dispatch collectives wired into
    ``_layer_forward``/``moe_ffn`` — the fleet-tier work this layout is
    staged for (ROADMAP item 2).  Sharding params with these specs and
    calling the existing single-chip forward inside a manual mesh region
    would produce unreduced partial sums."""
    from .llama import MEGATRON_SPLIT

    def mat(name):
        if MEGATRON_SPLIT[name] == "col":
            return P(None, None, axis)
        return P(None, axis, None)

    return {
        "embed": P(),
        "final_norm": P(),
        "lm_head": P(),
        "layers": {
            "input_norm": P(None, None),
            "post_norm": P(None, None),
            "wq": mat("wq"), "wk": mat("wk"), "wv": mat("wv"),
            "wo": mat("wo"),
            # shared (dense) experts: same column/row split as llama's mlp
            "s_gate": P(None, None, axis),
            "s_up": P(None, None, axis),
            "s_down": P(None, axis, None),
            "router": P(None, None, None),      # replicated: routing must
                                                # agree across shards
            "e_gate": P(None, axis, None, None),   # expert dim over tp
            "e_up": P(None, axis, None, None),
            "e_down": P(None, axis, None, None),
        },
    }


# auto dispatch switches to the sort path above this expert count: at E<=8
# the dense one-hot einsums are small and shard perfectly over EP meshes; past
# that the O(tokens*E*C) dispatch FLOPs dominate step time (round-3 verdict:
# DeepSeek-scale E=64 makes dense routing the bottleneck)
_SORT_DISPATCH_MIN_EXPERTS = 9


def moe_ffn(cfg: MoEConfig, x, lp):
    """Routed-expert FFN for x: [b, s, h] → (out, aux_loss, z_loss).

    Three dispatch engines behind one routing front-end (cfg.dispatch):

    * dense — GShard one-hot formulation: capacity-bounded dispatch tensor
      [g, E, C] → einsum into per-expert batches [E, C, h] → swiglu → combine.
      Under GSPMD with e_* sharded on 'mp' this compiles to
      all-to-all(dispatch) + expert-local matmuls + all-to-all(combine), the
      exact dataflow of the reference's global_scatter/global_gather
      (python/paddle/distributed/utils/moe_utils.py).
    * sort — stable argsort of (token, k) pairs by expert id, scatter into a
      static [E*C, h] buffer, gather back after expert compute.  O(g*K*h)
      data movement instead of O(g*E*C*h) einsum FLOPs; identical numerics
      (same within-expert ordering, same capacity drops) — the scalable path
      for DeepSeek-class expert counts (reference moe_layer.py routes through
      variable-size global_scatter for the same reason).
    * ragged — DROPLESS ``lax.ragged_dot`` grouped matmuls (no capacity,
      no padding, keeps tokens GShard would drop).  Opt-in only: drop
      semantics differ from dense/sort, and GSPMD cannot usefully shard the
      ragged group dimension, so under an expert-parallel mesh the expert
      weights are gathered to each device — prefer sort/dense for EP
      meshes, ragged for single-device or pure-dp serving/training.
    """
    b, s, h = x.shape
    E, K = cfg.num_experts, cfg.top_k
    g = b * s
    xf = x.reshape(g, h)

    logits = (xf.astype(jnp.float32) @ lp["router"])           # [g, E]
    probs = jax.nn.softmax(logits, axis=-1)
    # z-loss: keeps router logits small (numerics at scale)
    z_loss = jnp.mean(jax.nn.logsumexp(logits, axis=-1) ** 2)

    topk_p, topk_i = jax.lax.top_k(probs, K)                   # [g, K]
    topk_p = topk_p / jnp.maximum(topk_p.sum(-1, keepdims=True), 1e-9)

    cap = int(np.ceil(cfg.capacity_factor * K * g / E))
    cap = max(cap, 1)

    # aux load-balance loss (Switch: E * sum_e f_e * P_e)
    frac_tokens = jnp.mean(jax.nn.one_hot(topk_i[:, 0], E, dtype=jnp.float32), axis=0)
    frac_probs = jnp.mean(probs, axis=0)
    aux = E * jnp.sum(frac_tokens * frac_probs)

    mode = resolved_dispatch(cfg)
    route = {"sort": _dispatch_sort, "ragged": _dispatch_ragged,
             "dense": _dispatch_dense}[mode]
    out = route(cfg, xf, lp, topk_p, topk_i, cap)
    return out.reshape(b, s, h), aux, z_loss


def resolved_dispatch(cfg: MoEConfig) -> str:
    """The dispatch engine a config actually runs: 'dense'|'sort'|'ragged'."""
    mode = cfg.dispatch
    if mode == "auto":
        mode = ("sort" if cfg.num_experts >= _SORT_DISPATCH_MIN_EXPERTS
                else "dense")
    if mode not in ("dense", "sort", "ragged"):
        raise ValueError(
            f"MoEConfig.dispatch must be 'auto'|'dense'|'sort'|'ragged', "
            f"got {cfg.dispatch!r}")
    return mode


def _expert_compute(lp, expert_in):
    """Per-expert swiglu FFN on stacked batches [E, C, h] → [E, C, h]."""
    gate = jnp.einsum("ech,ehm->ecm", expert_in, lp["e_gate"])
    up = jnp.einsum("ech,ehm->ecm", expert_in, lp["e_up"])
    act = swiglu_mod.swiglu(gate, up)
    return jnp.einsum("ecm,emh->ech", act, lp["e_down"])


def _dispatch_dense(cfg, xf, lp, topk_p, topk_i, cap):
    g, h = xf.shape
    E, K = cfg.num_experts, cfg.top_k

    # position of each (token, k) within its expert queue, counted in
    # flattened (token, k) row-major order
    onehot = jax.nn.one_hot(topk_i, E, dtype=jnp.int32)        # [g, K, E]
    flat = onehot.reshape(g * K, E)
    pos = jnp.cumsum(flat, axis=0) - flat                      # slots before me
    pos = (pos * flat).sum(-1).reshape(g, K)                   # [g, K]
    keep = pos < cap                                           # drop overflow

    # dispatch/combine tensors from one-hot einsums
    oh_e = jax.nn.one_hot(topk_i, E, dtype=xf.dtype)           # [g, K, E]
    oh_c = jax.nn.one_hot(pos, cap, dtype=xf.dtype) * keep[..., None]  # [g, K, C]
    combine = jnp.einsum("gke,gkc,gk->gec", oh_e, oh_c, topk_p.astype(xf.dtype))
    dispatch = jnp.einsum("gke,gkc->gec", oh_e, oh_c)

    expert_in = jnp.einsum("gec,gh->ech", dispatch, xf)        # [E, C, h]
    expert_out = _expert_compute(lp, expert_in)
    return jnp.einsum("gec,ech->gh", combine, expert_out)


def _dispatch_ragged(cfg, xf, lp, topk_p, topk_i, cap):
    """DROPLESS dispatch over ``lax.ragged_dot`` (the TPU-native grouped
    matmul; MegaBlocks-style): (token, k) pairs stable-sorted by expert form
    contiguous groups, and the three expert matmuls run as ragged dots with
    per-expert group sizes — no capacity, no padding FLOPs, no dropped
    tokens.  ``cap`` is ignored; numerics match dense/sort exactly when no
    capacity drops occur (cap_factor >= E), and otherwise keep the tokens
    GShard would drop — a quality/perf point, not a parity point, so it is
    opt-in (cfg.dispatch='ragged'), never chosen by 'auto'."""
    g, h = xf.shape
    E, K = cfg.num_experts, cfg.top_k
    N = g * K

    flat_e = topk_i.reshape(N)
    order = jnp.argsort(flat_e, stable=True)
    tok = order // K
    xs = xf[tok]                                   # [N, h] grouped by expert
    counts = jnp.bincount(flat_e, length=E).astype(jnp.int32)
    gate = jax.lax.ragged_dot(xs, lp["e_gate"], counts)
    up = jax.lax.ragged_dot(xs, lp["e_up"], counts)
    act = swiglu_mod.swiglu(gate, up)
    out_s = jax.lax.ragged_dot(act, lp["e_down"], counts)   # [N, h]
    w = topk_p.reshape(N)[order].astype(xf.dtype)
    y = jnp.zeros((g, h), xf.dtype)
    return y.at[tok].add(out_s * w[:, None])


def _dispatch_sort(cfg, xf, lp, topk_p, topk_i, cap):
    g, h = xf.shape
    E, K = cfg.num_experts, cfg.top_k
    N = g * K

    flat_e = topk_i.reshape(N)                                 # expert per (t,k)
    # stable sort groups (token, k) pairs by expert while preserving the
    # row-major (token, k) order within each expert — the same order the
    # dense path's cumsum assigns, so capacity drops are bit-identical
    order = jnp.argsort(flat_e, stable=True)                   # [N]
    sorted_e = flat_e[order]
    counts = jnp.bincount(flat_e, length=E)                    # [E]
    starts = jnp.cumsum(counts) - counts
    pos_sorted = jnp.arange(N, dtype=jnp.int32) - starts[sorted_e]
    keep = pos_sorted < cap
    slot = sorted_e * cap + pos_sorted                         # [N] in [0, E*cap)
    tok = order // K                                           # source token

    # scatter tokens to their expert slots (overflow routed out-of-bounds and
    # dropped); slots are unique so set() has no collision ambiguity
    buf = jnp.zeros((E * cap, h), xf.dtype)
    buf = buf.at[jnp.where(keep, slot, E * cap)].set(xf[tok], mode="drop")
    expert_out = _expert_compute(lp, buf.reshape(E, cap, h))

    out_flat = expert_out.reshape(E * cap, h)
    gathered = out_flat[jnp.where(keep, slot, 0)] * keep[:, None].astype(xf.dtype)
    w = topk_p.reshape(N)[order].astype(xf.dtype)              # [N]
    y = jnp.zeros((g, h), xf.dtype)
    return y.at[tok].add(gathered * w[:, None])


def _layer_forward(cfg: MoEConfig, x, lp, cos, sin, use_flash=True):
    b, s, h = x.shape
    nh, nkv, hd = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim

    xn = rms.rms_norm(x, lp["input_norm"], cfg.rms_norm_eps)
    q = (xn @ lp["wq"]).reshape(b, s, nh, hd)
    kk = (xn @ lp["wk"]).reshape(b, s, nkv, hd)
    vv = (xn @ lp["wv"]).reshape(b, s, nkv, hd)
    q, kk = rope_mod.apply_rotary_pos_emb(q, kk, cos, sin)
    if use_flash:
        attn = fa.flash_attention_bshd(q, kk, vv, causal=True)
    else:
        import math

        attn = fa._composed_attention(q, kk, vv, None, True, 1.0 / math.sqrt(hd))
    # shared sharded decoder half (models/llama.py): the attention output
    # projection + residual — and, under tensor parallelism, TP boundary 1 —
    # have one home for the dense and MoE decoders alike.  The stage-2
    # fused layer tail (llama.decoder_layer_tail's mlp_fn hook, docs/
    # paged_attention.md "Megastep stage 2") is dense-decoder-only: the
    # MoE MLP half is shared-expert + routed experts, not the single
    # swiglu block the fused MLP kernel streams, so MoE keeps the
    # explicit two-half composition until MoE serving (ROADMAP item 4)
    # grows its own fused tail
    from .llama import decoder_attn_residual

    x = decoder_attn_residual(x, attn.reshape(b, s, nh * hd), lp)

    xn = rms.rms_norm(x, lp["post_norm"], cfg.rms_norm_eps)
    shared = swiglu_mod.swiglu(xn @ lp["s_gate"], xn @ lp["s_up"]) @ lp["s_down"]
    routed, aux, z = moe_ffn(cfg, xn, lp)
    return x + shared + routed, aux, z


def forward(cfg: MoEConfig, params, input_ids, use_flash=True, remat=True,
            return_aux=False):
    x = jnp.take(params["embed"], input_ids, axis=0).astype(cfg.dtype)
    b, s, _ = x.shape
    cos, sin = rope_mod.rope_cos_sin(s, cfg.head_dim, base=cfg.rope_theta,
                                     dtype=cfg.dtype)

    def body(carry, lp):
        x, aux, z = carry
        x2, a, zz = _layer_forward(cfg, x, lp, cos, sin, use_flash)
        return (x2, aux + a, z + zz), None

    scan_body = jax.checkpoint(body) if remat else body
    (x, aux, z), _ = jax.lax.scan(
        scan_body, (x, jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)),
        params["layers"])
    x = rms.rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    logits = x @ params["lm_head"]
    if return_aux:
        return logits, aux / cfg.num_hidden_layers, z / cfg.num_hidden_layers
    return logits


def loss_fn(cfg: MoEConfig, params, input_ids, labels):
    logits, aux, z = forward(cfg, params, input_ids, return_aux=True)
    logits = logits.astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    picked = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    ce = -jnp.mean(picked)
    return ce + cfg.aux_loss_weight * aux + cfg.z_loss_weight * z


def make_mesh(dp=1, mp=1, sharding=1, sep=1, pp=1, devices=None):
    from . import llama

    return llama.make_mesh(dp=dp, mp=mp, sharding=sharding, sep=sep, pp=pp,
                           devices=devices)


def build_train_step(cfg: MoEConfig, mesh: Mesh, lr=3e-4, weight_decay=0.1,
                     beta1=0.9, beta2=0.95, grad_clip=1.0):
    """Same optimizer/sharding scaffold as models/llama.build_train_step, with
    the MoE loss (ce + aux + z)."""
    specs = param_specs(cfg, mp=dict(mesh.shape).get("mp", 1))
    data_spec = P(("dp", "sharding"), "sep")

    def to_named(tree_specs):
        return jax.tree_util.tree_map(
            lambda sp: NamedSharding(mesh, sp), tree_specs,
            is_leaf=lambda sp: isinstance(sp, P))

    param_shardings = to_named(specs)

    def opt_init(params):
        z = lambda p: jnp.zeros(p.shape, jnp.float32)
        return {
            "step": jnp.zeros((), jnp.int32),
            "m": jax.tree_util.tree_map(z, params),
            "v": jax.tree_util.tree_map(z, params),
            "master": jax.tree_util.tree_map(lambda p: p.astype(jnp.float32), params),
            # pre-clip grad global-norm (multichip dryrun fingerprint;
            # mirrors models/llama.build_train_step)
            "gnorm": jnp.zeros((), jnp.float32),
        }

    def train_step(params, opt_state, input_ids, labels):
        loss, grads = jax.value_and_grad(
            lambda p: loss_fn(cfg, p, input_ids, labels))(params)
        g32 = jax.tree_util.tree_map(lambda g: g.astype(jnp.float32), grads)
        leaves = jax.tree_util.tree_leaves(g32)
        gnorm = jnp.sqrt(sum(jnp.sum(g * g) for g in leaves))
        scale_f = jnp.minimum(1.0, grad_clip / jnp.maximum(gnorm, 1e-6))
        step = opt_state["step"] + 1
        b1c = 1 - beta1 ** step.astype(jnp.float32)
        b2c = 1 - beta2 ** step.astype(jnp.float32)

        def upd(g, m, v, master):
            g = g * scale_f
            m2 = beta1 * m + (1 - beta1) * g
            v2 = beta2 * v + (1 - beta2) * g * g
            update = (m2 / b1c) / (jnp.sqrt(v2 / b2c) + 1e-8)
            master2 = master * (1 - lr * weight_decay) - lr * update
            return m2, v2, master2

        updated = jax.tree_util.tree_map(
            upd, g32, opt_state["m"], opt_state["v"], opt_state["master"])
        # tree_map over 4 trees returns a (m2, v2, w2) tuple per leaf; split
        flat, treedef = jax.tree_util.tree_flatten(
            updated, is_leaf=lambda x: isinstance(x, tuple))
        new_m = jax.tree_util.tree_unflatten(treedef, [t[0] for t in flat])
        new_v = jax.tree_util.tree_unflatten(treedef, [t[1] for t in flat])
        new_w = jax.tree_util.tree_unflatten(treedef, [t[2] for t in flat])
        new_params = jax.tree_util.tree_map(
            lambda w, p: w.astype(p.dtype), new_w, params)
        new_opt = {"step": step, "m": new_m, "v": new_v, "master": new_w,
                   "gnorm": gnorm}
        return loss, new_params, new_opt

    opt_shardings = {
        "step": NamedSharding(mesh, P()),
        "m": param_shardings,
        "v": param_shardings,
        "master": param_shardings,
        "gnorm": NamedSharding(mesh, P()),
    }
    data_sharding = NamedSharding(mesh, data_spec)
    jitted = jax.jit(
        train_step,
        in_shardings=(param_shardings, opt_shardings, data_sharding, data_sharding),
        out_shardings=(NamedSharding(mesh, P()), param_shardings, opt_shardings),
        donate_argnums=(0, 1),
    )
    # fresh zeros in opt state don't inherit param shardings — pin them
    opt_init = jax.jit(opt_init, out_shardings=opt_shardings)
    return jitted, opt_init, param_shardings, data_sharding


def count_params(params) -> int:
    return sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(params))


def active_params_per_token(cfg: MoEConfig) -> int:
    """Active (per-token) parameter count — the MoE MFU denominator."""
    h, i, mi = cfg.hidden_size, cfg.intermediate_size, cfg.moe_intermediate_size
    nh, nkv, hd = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim
    per_layer = (h * nh * hd + 2 * h * nkv * hd + nh * hd * h
                 + 3 * h * i * cfg.num_shared_experts
                 + 3 * h * mi * cfg.top_k + h * cfg.num_experts)
    return cfg.num_hidden_layers * per_layer + 2 * cfg.vocab_size * h
